"""IncrementalPCA — streaming PCA over row batches.

Reference: ``dask_ml/decomposition/incremental_pca.py`` — sklearn's
incremental rank-update walked sequentially over dask blocks (SURVEY.md §2
#10).  TPU design: the model state (components, singular values, running
mean/var) lives on device; the host streams batches into one jitted update
step — the reference's "model hops between workers" chain becomes
device-resident state with data streaming in (SURVEY.md §3.5 note).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..base import ComponentsOutMixin, TPUEstimator, TransformerMixin
from ..core.sharded import ShardedRows, unshard
from ..preprocessing.data import _like_input, _masked_or_plain
from ..utils import check_array, svd_flip
from .. import sanitize as _san


def _update_fn(components, singular_values, mean, var, n_seen, batch, *, k):
    """One incremental rank-update (Ross et al. 2008, as in sklearn).

    ``n_seen`` is a DEVICE scalar and every derived reporting attribute
    (explained variance, ratio, noise variance) is computed in-program:
    the streaming hot loop performs zero host↔device scalar crossings
    per block — the graftsan transfer sanitizer holds ``partial_fit``
    to that under ``jax.transfer_guard("disallow")``, and the seed's
    per-block ``int(n_samples_seen_)`` round-trip was exactly the
    host-sync-loop class it existed to catch.
    """
    n_batch = batch.shape[0]
    d = batch.shape[1]
    # the carry stays int32 (exact to 2^31 rows — an f32 count would
    # silently stop increasing past 2^24); the weighting arithmetic runs
    # in the batch dtype, where 1e-7 relative error on a WEIGHT is noise
    n_total = n_seen + n_batch
    ns = n_seen.astype(batch.dtype)
    nb = jnp.asarray(float(n_batch), batch.dtype)
    nt = ns + nb
    batch_mean = jnp.mean(batch, axis=0)
    batch_var = jnp.var(batch, axis=0)

    new_mean = (ns * mean + nb * batch_mean) / nt
    new_var = (
        ns * var
        + nb * batch_var
        + (ns * nb / nt) * (mean - batch_mean) ** 2
    ) / nt

    centered = batch - batch_mean
    correction = jnp.sqrt((ns * nb) / nt) * (mean - batch_mean)
    stacked = jnp.vstack(
        [
            singular_values[:, None] * components,
            centered,
            correction[None, :],
        ]
    )
    u, s, vt = jnp.linalg.svd(stacked, full_matrices=False)
    u, vt = svd_flip(u, vt, u_based_decision=False)
    sv = s[:k]
    explained = sv**2 / (nt - 1.0)
    total_var = jnp.sum(new_var) * nt / (nt - 1.0)
    ratio = explained / total_var
    # sklearn's noise floor: mean of the discarded eigenvalues; 0 when
    # every component is kept (k >= min(n, d))
    min_nd = jnp.minimum(nt, float(d))
    noise = jnp.where(
        k < min_nd,
        (total_var - jnp.sum(explained))
        / jnp.maximum(min_nd - k, 1.0),
        0.0,
    ).astype(batch.dtype)
    return vt[:k], sv, new_mean, new_var, n_total, explained, ratio, noise


# the rank-update through the central program cache (design.md §12):
# `_pf_stage` pre-compiles a ragged tail batch's program on the blessed
# compile-ahead thread while the previous batch's SVD executes.  IPCA
# batches are deliberately NOT bucket-padded — `_update` has no row
# mask, so padding rows would enter the moments; only the compile
# overlap applies here.
from .. import programs as _programs  # noqa: E402

# The whole state chain is donated: partial_fit overwrites every one of
# the five state operands with the program's outputs (components (k,d)
# → vt[:k], singular values (k,), mean/var (d,), the int32 count), so
# the rank-update happens in place in HBM instead of holding two copies
# of the model state per block.  ``batch`` is NOT donated — its (n, d)
# buffer has no same-shaped output (n > k on every legal call).
_update = _programs.cached_program(
    _update_fn, name="ipca.update", static_argnames=("k",),
    donate_argnames=("components", "singular_values", "mean", "var",
                     "n_seen"),
)


class IncrementalPCA(ComponentsOutMixin, TransformerMixin, TPUEstimator):
    #: the loop state a FitCheckpoint snapshot carries (everything
    #: partial_fit reads; the derived attrs are recomputed by the next
    #: update, but snapshotting them keeps a resumed-but-never-stepped
    #: model usable for transform as well)
    _FIT_STATE_ATTRS = (
        "components_", "singular_values_", "_mean_sh_", "var_",
        "n_samples_seen_", "_anchor_", "n_components_", "n_features_in_",
        "mean_", "explained_variance_", "explained_variance_ratio_",
        "noise_variance_",
    )

    def __init__(self, n_components=None, whiten=False, copy=True,
                 batch_size=None, fit_checkpoint=None):
        self.n_components = n_components
        self.whiten = whiten
        self.copy = copy
        self.batch_size = batch_size
        self.fit_checkpoint = fit_checkpoint

    def _init_state(self, d, k, dtype):
        self.components_ = jnp.zeros((k, d), dtype=dtype)
        self.singular_values_ = jnp.zeros((k,), dtype=dtype)
        self._mean_sh_ = jnp.zeros((d,), dtype=dtype)
        self.var_ = jnp.zeros((d,), dtype=dtype)
        self.n_samples_seen_ = 0

    # The running sample count lives ON DEVICE (`_n_seen_`): the update
    # program consumes and produces it without a host round-trip per
    # block.  `n_samples_seen_` stays the sklearn-exact Python int — the
    # fetch happens when someone READS it, not once per streamed block
    # (graftsan's steady-phase transfer guard holds partial_fit to
    # zero implicit crossings).
    @property
    def n_samples_seen_(self):
        ns = getattr(self, "_n_seen_", None)
        return 0 if ns is None else int(ns)

    @n_samples_seen_.setter
    def n_samples_seen_(self, value):
        # accepts ints (init, legacy checkpoints) and device scalars;
        # int32 keeps the count exact (an f32 carry saturates at 2^24).
        # jnp.array (a copy): _update donates n_seen, and asarray of an
        # already-int32 device scalar would alias the caller's array
        # into the donation
        self._n_seen_ = jnp.array(value, dtype=jnp.int32)

    # -- staged streaming protocol (pipeline.stream_partial_fit) -----------
    def _pf_stage(self, X, y=None, check_input=True, **kwargs):
        """Host validate/cast + device upload of one batch, run ahead on
        the prefetch worker while the previous batch's rank-update SVD
        executes.  Declines device-resident input (ShardedRows or
        jax.Array): staging those would mean a device fetch — or a
        device cast program — off the consumer thread."""
        if kwargs or isinstance(X, (ShardedRows, jnp.ndarray)):
            return None
        if check_input:
            X = check_array(X)
        xh = np.asarray(X)
        if not np.issubdtype(xh.dtype, np.inexact):
            # cast on HOST: a device astype is a program, which the
            # worker thread must never dispatch
            xh = xh.astype(np.float32)
        self._warm_update(xh.shape, xh.dtype)
        return jnp.asarray(xh)

    def _warm_update(self, xshape, dtype) -> bool:
        """Compile-ahead hook: pre-build the rank-update for a batch of
        ``xshape`` on the blessed compile thread (host-only work here —
        shape structs + a queue put).  Only possible once the state
        shapes exist, i.e. after the first consumed batch — which is
        exactly when a ragged TAIL batch's fresh program would
        otherwise stall the consumer."""
        from .. import programs

        if not programs.compile_ahead_enabled():
            return False
        # n_components_ is assigned AFTER _init_state in _pf_consume; the
        # prefetch worker can stage the next block between the two, so
        # gate on the attribute the shapes actually need (a declined
        # warm just means this block compiles on demand — warmup class)
        k = getattr(self, "n_components_", None)
        if k is None or not hasattr(self, "components_") \
                or len(xshape) != 2:
            return False
        k = int(k)
        d = int(xshape[1])
        # the device dtype the staged jnp.asarray will produce (host
        # f64 lands as f32 unless x64 is enabled) — pure metadata math
        dtype = jax.dtypes.canonicalize_dtype(dtype)
        sds = jax.ShapeDtypeStruct
        return _update.warm(
            (sds((k, d), dtype), sds((k,), dtype), sds((d,), dtype),
             sds((d,), dtype), sds((), jnp.int32),
             sds((int(xshape[0]), d), dtype)),
            k=k,
        )

    def partial_fit(self, X, y=None, check_input=True):
        # composed from the staged hooks so serial and prefetched paths
        # cannot drift; device-resident input takes the consumer-thread
        # ingest _pf_stage declines (jnp cast is a program — legal here)
        x = self._pf_stage(X, check_input=check_input)
        if x is None:
            if check_input:
                X = check_array(X)
            x = jnp.asarray(unshard(X) if isinstance(X, ShardedRows) else X)
            if not jnp.issubdtype(x.dtype, jnp.inexact):
                x = x.astype(jnp.float32)
        return self._pf_consume(x)

    def _pf_consume(self, x):
        """One incremental rank-update on a device-staged batch (the
        ``partial_fit`` body below the ingest; consumer thread only)."""
        from ..resilience.testing import maybe_fault

        maybe_fault("step")
        d = x.shape[1]
        k = self.n_components or min(x.shape[0], d)
        if not hasattr(self, "components_"):
            self._init_state(d, k, x.dtype)
            self.n_components_ = k
            # Anchor-shift (the core.sharded._masked_anchor idiom): all
            # moment/SVD arithmetic runs on x − anchor, at the data's
            # SPREAD scale instead of its offset scale.  At offset 1e6
            # the raw-scale update loses ~0.3% of var_ and ~0.1° of
            # component subspace to f32 mean cancellation (adversarial
            # property find, round 5); anchored, both drop to the
            # centered-data floor.  The first row is a valid data value
            # per feature, so the subtraction is exact for values within
            # 2× of it (Sterbenz) — exactly the offset-dominated regime.
            self._anchor_ = x[0]
        if x.shape[0] < self.n_components_:
            raise ValueError(
                f"batch of {x.shape[0]} rows < n_components={self.n_components_}"
            )
        if getattr(self, "_anchor_", None) is None:
            # state restored from a pre-anchor checkpoint: continue at
            # raw scale (anchor 0) so the shifted state is well-defined.
            # jnp.array (a copy): _update donates mean — asarray of an
            # already-device mean_ would alias it into the donation
            self._anchor_ = jnp.zeros((d,), dtype=x.dtype)
            self._mean_sh_ = jnp.array(self.mean_)
        # ONE program, all-device operands (the running count included),
        # derived reporting attrs computed in-program: the steady-state
        # streaming step crosses the host boundary zero times, verified
        # by graftsan's transfer guard when a sanitizer is active
        with _san.region("ipca.partial_fit"), _san.step_guard():
            (
                self.components_,
                self.singular_values_,
                self._mean_sh_,
                self.var_,
                self._n_seen_,
                self.explained_variance_,
                self.explained_variance_ratio_,
                self.noise_variance_,
            ) = _update(
                self.components_,
                self.singular_values_,
                self._mean_sh_,
                self.var_,
                self._n_seen_,
                x - self._anchor_,
                k=self.n_components_,
            )
            # the reported attribute is the TRUE mean (sklearn parity);
            # one final add costs only the f32 representation round-off
            self.mean_ = self._anchor_ + self._mean_sh_
        self.n_features_in_ = d
        return self

    def fit(self, X, y=None):
        """Stream X through partial_fit in row batches (reference walks dask
        blocks in sequence).  With a ``fit_checkpoint``, the batch walk
        snapshots the rank-update state at the checkpoint cadence and a
        killed fit resumes at the first unprocessed batch — the update is
        deterministic, so the resumed sweep matches an uninterrupted one.
        """
        from ..resilience.preemption import check_preemption

        ckpt = self.fit_checkpoint
        done_batches = 0
        snap = ckpt.load_if_matches(self) if ckpt is not None else None
        if snap is not None:
            done_batches, state = snap
            for attr, val in state.items():
                setattr(self, attr, val)
        elif hasattr(self, "components_"):
            del self.components_  # refit from scratch, sklearn semantics
        x = unshard(X) if isinstance(X, ShardedRows) else np.asarray(X)
        n, d = x.shape
        batch = self.batch_size or 5 * d
        # resolved rank: explicit, else inferred from the first batch as
        # partial_fit will (sklearn drops tails < rank via gen_batches)
        k = self.n_components or min(batch, n, d)
        spans = []
        for start in range(0, n, batch):
            stop = min(start + batch, n)
            if stop - start < k:
                break
            spans.append((start, stop))

        from ..resilience.preemption import active_watcher

        def _boundary(j, _model):
            # consumer-thread hook between device steps: the snapshot
            # reflects exactly the first ``i`` batches; prefetched
            # in-flight batches never touched the state, so a resume
            # re-slices and replays them identically.  Built ONLY when
            # someone is listening (the _sgd boundary pattern): the
            # state dict reads n_samples_seen_, whose getter is a
            # device fetch since the count moved on-device — paying
            # that per block on an uninstrumented fit would serialize
            # the prefetch overlap this loop exists to provide
            if ckpt is None and active_watcher() is None:
                return
            i = done_batches + j
            state = self._fit_state()
            if ckpt is not None and ckpt.due(i):
                ckpt.save(self, state, i)
            check_preemption(ckpt, self, state, i)

        from ..pipeline import stream_partial_fit

        # batches after the resume point stream through the prefetch
        # pipeline: batch i+1's slice + upload overlaps batch i's SVD
        stream_partial_fit(
            self,
            ((x[s:e], None) for s, e in spans[done_batches:]),
            fit_kwargs={"check_input": False},
            on_block=_boundary,
            label="incremental_pca_fit",
        )
        if ckpt is not None:
            ckpt.complete()
        return self

    def _fit_state(self) -> dict:
        return {a: getattr(self, a) for a in self._FIT_STATE_ATTRS
                if hasattr(self, a)}

    def transform(self, X):
        x, _ = _masked_or_plain(X)
        if getattr(self, "_anchor_", None) is not None:
            # (x − anchor) is exact in the offset regime; the spread-
            # scale mean then subtracts without cancellation
            out = ((x - self._anchor_) - self._mean_sh_) @ self.components_.T
        else:  # state restored from a pre-anchor checkpoint
            out = (x - self.mean_) @ self.components_.T
        if self.whiten:
            out = out / jnp.sqrt(self.explained_variance_)
        return _like_input(X, out)

    def inverse_transform(self, X):
        x, _ = _masked_or_plain(X)
        if self.whiten:
            x = x * jnp.sqrt(self.explained_variance_)
        if getattr(self, "_anchor_", None) is not None:
            return _like_input(
                X, (x @ self.components_ + self._mean_sh_) + self._anchor_
            )
        return _like_input(X, x @ self.components_ + self.mean_)

    def get_covariance(self):
        from .pca import PCA

        return PCA.get_covariance(self)

    get_covariance.__doc__ = (
        "Probabilistic-PCA model covariance — same fitted-attribute "
        "formula as :meth:`PCA.get_covariance` (sklearn "
        "``IncrementalPCA`` inherits it from the same base).  Note a "
        "deliberate deviation: this class's ``noise_variance_`` is the "
        "PCA-consistent residual estimator (total running variance "
        "minus retained, over the discarded dimensions), which tracks "
        "full-PCA ground truth; sklearn's IncrementalPCA reports the "
        "mean of the LAST rank-update's discarded spectrum, which "
        "under-estimates it (measured 0.186 vs true 1.019 on the "
        "test fixture) — so covariance/precision here agree with "
        "``PCA`` on the same data, not with sklearn's IPCA quirk."
    )

    def get_precision(self):
        from .pca import PCA

        return PCA.get_precision(self)

    get_precision.__doc__ = (
        "Inverse model covariance via the matrix-inversion lemma — "
        "shares :meth:`PCA.get_precision`."
    )

"""TruncatedSVD — PCA without mean-centering.

Reference: ``dask_ml/decomposition/truncated_svd.py :: TruncatedSVD``
(``algorithm='tsqr'`` exact / ``'randomized'``; fitted attrs
``components_``, ``explained_variance_(ratio_)``, ``singular_values_``).
"""

from __future__ import annotations

import jax.numpy as jnp

from ..base import ComponentsOutMixin, TPUEstimator, TransformerMixin
from ..core.sharded import ShardedRows, masked_mean, masked_var
from ..linalg import randomized_svd, tsqr_svd
from ..preprocessing.data import _ingest_float, _like_input, _masked_or_plain
from ..utils import svd_flip


class TruncatedSVD(ComponentsOutMixin, TransformerMixin, TPUEstimator):
    def __init__(self, n_components=2, algorithm="tsqr", n_iter=5,
                 random_state=None, tol=0.0, compute=True):
        self.n_components = n_components
        self.algorithm = algorithm
        self.n_iter = n_iter
        self.random_state = random_state
        self.tol = tol
        self.compute = compute

    def fit(self, X, y=None):
        self.fit_transform(X)
        return self

    def fit_transform(self, X, y=None):
        X_in = X
        X = _ingest_float(self, X)
        d = X.data.shape[1]
        k = self.n_components
        if not 0 < k < d:
            raise ValueError(
                f"n_components must be in (0, n_features={d}); got {k}"
            )
        # Zero the padded rows: unlike PCA there is no centering step to do
        # it, and sharded inputs from upstream transforms (e.g. a scaler)
        # carry nonzero pad rows.
        data = X.data * X.mask[:, None]
        if self.algorithm in ("tsqr", "full"):
            u, s, vt = tsqr_svd(data)
            u, s, vt = u[:, :k], s[:k], vt[:k]
        elif self.algorithm == "randomized":
            u, s, vt = randomized_svd(
                data, k, n_iter=self.n_iter, random_state=self.random_state
            )
        else:
            raise ValueError(f"Unknown algorithm: {self.algorithm!r}")
        u, vt = svd_flip(u, vt, u_based_decision=False)

        transformed = u * s
        n = X.n_samples
        self.components_ = vt
        exp_var = masked_var(transformed, X.mask)
        full_var = jnp.sum(masked_var(X.data, X.mask))
        self.explained_variance_ = exp_var
        self.explained_variance_ratio_ = exp_var / full_var
        self.singular_values_ = s
        self.n_features_in_ = d
        if isinstance(X_in, ShardedRows):
            return ShardedRows(data=transformed, mask=X.mask, n_samples=n)
        return transformed[:n]

    def transform(self, X):
        import scipy.sparse

        if scipy.sparse.issparse(X):
            # sparse projection on host: n×d stays sparse, only the n×k
            # result densifies (the reference consumes sparse natively in
            # ``dask_ml/decomposition/truncated_svd.py``)
            import numpy as np

            return np.asarray(X @ np.asarray(self.components_).T)
        x, _ = _masked_or_plain(X)
        return _like_input(X, x @ self.components_.T)

    def inverse_transform(self, X):
        x, _ = _masked_or_plain(X)
        return _like_input(X, x @ self.components_)

    def fit_streamed(self, blocks, n_features=None):
        """Fit from a RE-ITERABLE stream of sparse/dense row blocks without
        ever materializing the dense corpus (VERDICT r2 next #9).

        ``blocks`` is a zero-argument callable returning a fresh iterator
        of row blocks (scipy.sparse or ndarray, each ``(b, n_features)``)
        — e.g. ``lambda: vectorizer.stream_transform(corpus)``.  The
        randomized range finder runs ``n_iter`` passes of ``A^T A`` over
        the stream (each block contributes ``B^T (B Q)``; blocks stay
        sparse, so peak dense memory is ``O(n_features x sketch)``, never
        ``O(n_rows x n_features)``), then one final pass accumulates the
        small ``(AQ)^T AQ`` Gram whose eigendecomposition yields the
        components, singular values, and explained variance — no pass
        stores anything n_rows-sized.

        Reference: ``dask_ml/decomposition/truncated_svd.py`` fits lazy
        sparse dask arrays; this is the streaming twin for corpora that
        never exist as one array.
        """
        import numpy as np
        import scipy.sparse

        k = self.n_components
        oversample = 10
        first_iter = None
        if n_features is None:
            # peek one block for the width; the partially-consumed
            # iterator (first block re-chained) serves as pass 0's source
            # so the peeked block's work is not thrown away
            import itertools

            it = iter(blocks())
            first = next(it, None)
            if first is None:
                raise ValueError("empty block stream")
            n_features = first.shape[1]
            first_iter = itertools.chain([first], it)
        d = int(n_features)
        if not 0 < k < d:
            raise ValueError(
                f"n_components must be in (0, n_features={d}); got {k}"
            )
        ell = min(k + oversample, d)
        from ..utils import check_random_state

        rng = check_random_state(self.random_state)
        Q = rng.normal(size=(d, ell)).astype(np.float32)

        def _mm(B, C):
            out = B @ C  # scipy sparse @ dense -> dense; ndarray works too
            return np.asarray(out, dtype=np.float64)

        def _dense64(a):
            """Densify one HOST accumulator term to float64.

            This whole range-finder pass is a host-only path: ``B``
            blocks are numpy/scipy matrices from the caller's iterator
            and the densifications here never touch a device value —
            formerly four per-call host-sync-loop suppressions, now a
            named host tail the rule can see past, with the hostness
            runtime-verified by the sanitizer (tests/test_sanitize.py
            streams this fit under an armed transfer guard: zero
            device crossings, zero device dispatches)."""
            return np.asarray(a, dtype=np.float64)

        n_rows = 0
        col_sum = np.zeros(d, np.float64)
        col_sumsq = np.zeros(d, np.float64)
        passes = max(int(self.n_iter), 1)
        for p in range(passes):
            H = np.zeros((d, ell), np.float64)
            src = first_iter if (p == 0 and first_iter is not None) \
                else blocks()
            first_iter = None
            for B in src:
                Y = _mm(B, Q)
                H += _dense64(B.T @ Y)
                if p == 0:
                    n_rows += B.shape[0]
                    if scipy.sparse.issparse(B):
                        col_sum += _dense64(B.sum(axis=0)).ravel()
                        col_sumsq += _dense64(
                            B.multiply(B).sum(axis=0)
                        ).ravel()
                    else:
                        Bd = _dense64(B)
                        col_sum += Bd.sum(axis=0)
                        col_sumsq += (Bd * Bd).sum(axis=0)
            # re-orthonormalize between passes (the stability trick behind
            # power_iteration_normalizer='QR')
            Q, _ = np.linalg.qr(H)
            Q = Q.astype(np.float32)
        if n_rows < 1:
            raise ValueError("empty block stream")

        # final pass: the l x l Gram of AQ plus its column means
        M = np.zeros((ell, ell), np.float64)
        w_sum = np.zeros(ell, np.float64)
        for B in blocks():
            W = _mm(B, Q)
            M += W.T @ W
            w_sum += W.sum(axis=0)
        evals, G = np.linalg.eigh(M)  # ascending
        order = np.argsort(evals)[::-1][:k]
        s = np.sqrt(np.maximum(evals[order], 0.0))
        V = (Q @ G[:, order]).T  # (k, d) right singular vectors
        # deterministic signs, same convention as the dense path
        # (svd_flip u_based_decision=False: sign of each row's max-|.|)
        max_abs = np.argmax(np.abs(V), axis=1)
        signs = np.sign(V[np.arange(V.shape[0]), max_abs])
        signs[signs == 0] = 1.0
        V = V * signs[:, None]

        mean_t = (G[:, order].T @ (w_sum / n_rows)) * signs
        exp_var = np.maximum(s**2 / n_rows - mean_t**2, 0.0)
        full_var = float(
            np.sum(col_sumsq / n_rows - (col_sum / n_rows) ** 2)
        )
        self.components_ = jnp.asarray(V.astype(np.float32))
        self.singular_values_ = jnp.asarray(s.astype(np.float32))
        self.explained_variance_ = jnp.asarray(exp_var.astype(np.float32))
        self.explained_variance_ratio_ = jnp.asarray(
            (exp_var / max(full_var, 1e-30)).astype(np.float32)
        )
        self.n_features_in_ = d
        return self

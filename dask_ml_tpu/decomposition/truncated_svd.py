"""TruncatedSVD — PCA without mean-centering.

Reference: ``dask_ml/decomposition/truncated_svd.py :: TruncatedSVD``
(``algorithm='tsqr'`` exact / ``'randomized'``; fitted attrs
``components_``, ``explained_variance_(ratio_)``, ``singular_values_``).
"""

from __future__ import annotations

import jax.numpy as jnp

from ..base import TPUEstimator, TransformerMixin
from ..core.sharded import ShardedRows, masked_mean, masked_var
from ..linalg import randomized_svd, tsqr_svd
from ..preprocessing.data import _ingest_float, _like_input, _masked_or_plain
from ..utils import svd_flip


class TruncatedSVD(TransformerMixin, TPUEstimator):
    def __init__(self, n_components=2, algorithm="tsqr", n_iter=5,
                 random_state=None, tol=0.0, compute=True):
        self.n_components = n_components
        self.algorithm = algorithm
        self.n_iter = n_iter
        self.random_state = random_state
        self.tol = tol
        self.compute = compute

    def fit(self, X, y=None):
        self.fit_transform(X)
        return self

    def fit_transform(self, X, y=None):
        X_in = X
        X = _ingest_float(self, X)
        d = X.data.shape[1]
        k = self.n_components
        if not 0 < k < d:
            raise ValueError(
                f"n_components must be in (0, n_features={d}); got {k}"
            )
        # Zero the padded rows: unlike PCA there is no centering step to do
        # it, and sharded inputs from upstream transforms (e.g. a scaler)
        # carry nonzero pad rows.
        data = X.data * X.mask[:, None]
        if self.algorithm in ("tsqr", "full"):
            u, s, vt = tsqr_svd(data)
            u, s, vt = u[:, :k], s[:k], vt[:k]
        elif self.algorithm == "randomized":
            u, s, vt = randomized_svd(
                data, k, n_iter=self.n_iter, random_state=self.random_state
            )
        else:
            raise ValueError(f"Unknown algorithm: {self.algorithm!r}")
        u, vt = svd_flip(u, vt, u_based_decision=False)

        transformed = u * s
        n = X.n_samples
        self.components_ = vt
        exp_var = masked_var(transformed, X.mask)
        full_var = jnp.sum(masked_var(X.data, X.mask))
        self.explained_variance_ = exp_var
        self.explained_variance_ratio_ = exp_var / full_var
        self.singular_values_ = s
        self.n_features_in_ = d
        if isinstance(X_in, ShardedRows):
            return ShardedRows(data=transformed, mask=X.mask, n_samples=n)
        return transformed[:n]

    def transform(self, X):
        x, _ = _masked_or_plain(X)
        return _like_input(X, x @ self.components_.T)

    def inverse_transform(self, X):
        x, _ = _masked_or_plain(X)
        return _like_input(X, x @ self.components_)

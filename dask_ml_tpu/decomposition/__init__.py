"""Decomposition — twin of ``dask_ml/decomposition/`` (SURVEY.md §2 #8–#10)."""

from .pca import PCA  # noqa: F401
from .truncated_svd import TruncatedSVD  # noqa: F401
from .incremental_pca import IncrementalPCA  # noqa: F401

__all__ = ["PCA", "TruncatedSVD", "IncrementalPCA"]

"""PCA for tall-skinny row-sharded matrices.

Reference: ``dask_ml/decomposition/pca.py :: PCA`` — requires a single
column block (tall-skinny), ``svd_solver ∈ {auto, full, tsqr, randomized}``,
fitted attrs ``components_``, ``explained_variance_(ratio_)``,
``singular_values_``, ``mean_``, ``noise_variance_`` (SURVEY.md §3.4).

TPU design: masked mean-centering zeroes the padded rows, then TSQR (exact)
or Halko (randomized) runs as one shard_map program; every fitted statistic
comes out of the same compiled computation.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..base import ComponentsOutMixin, TPUEstimator, TransformerMixin
from ..core.sharded import ShardedRows, masked_mean
from ..linalg import randomized_svd, tsqr_svd
from ..preprocessing.data import _ingest_float, _like_input, _masked_or_plain
from ..utils import svd_flip


class PCA(ComponentsOutMixin, TransformerMixin, TPUEstimator):
    def __init__(self, n_components=None, copy=True, whiten=False,
                 svd_solver="auto", tol=0.0, iterated_power=4, random_state=None):
        self.n_components = n_components
        self.copy = copy
        self.whiten = whiten
        self.svd_solver = svd_solver
        self.tol = tol
        self.iterated_power = iterated_power
        self.random_state = random_state

    # -- solver selection (mirrors reference `_fit` policy) ------------
    def _resolve(self, n_samples, n_features):
        n_components = self.n_components
        if n_components is None:
            n_components = min(n_samples, n_features)
        solver = self.svd_solver
        if solver == "auto":
            if isinstance(n_components, float):
                solver = "full"
            elif n_components < 0.8 * min(n_samples, n_features) and n_features > 50:
                solver = "randomized"
            else:
                solver = "full"
        if solver == "tsqr":
            solver = "full"
        return n_components, solver

    def _center(self, X: ShardedRows):
        mean = masked_mean(X.data, X.mask)
        centered = (X.data - mean) * X.mask[:, None]
        return centered, mean

    def fit(self, X, y=None):
        self._fit(X)
        return self

    def _fit(self, X):
        X = _ingest_float(self, X)
        n, d = X.n_samples, X.data.shape[1]
        if n < d:
            raise ValueError(
                f"n_samples ({n}) must be >= n_features ({d}) for tall-skinny PCA"
            )
        n_components, solver = self._resolve(n, d)
        if isinstance(n_components, float):
            if not 0 < n_components <= 1.0:
                raise ValueError(f"Invalid n_components: {n_components}")
            k_request = d
        else:
            if n_components > d:
                raise ValueError(
                    f"n_components={n_components} must be <= n_features={d}"
                )
            k_request = n_components

        centered, mean = self._center(X)
        if solver == "randomized":
            u, s, vt = randomized_svd(
                centered, k_request, n_iter=self.iterated_power,
                random_state=self.random_state,
            )
        else:
            u, s, vt = tsqr_svd(centered)
        # sklearn >= 1.5 flips on V (deterministic regardless of row order /
        # padding); match it so components_ agree elementwise.
        u, vt = svd_flip(u, vt, u_based_decision=False)

        # Full spectrum statistics (s has k_request entries; total variance
        # needs all d — with full solver s covers everything, with randomized
        # we fall back to the masked total variance).
        explained = (s ** 2) / (n - 1)
        if solver == "randomized":
            from ..core.sharded import masked_var

            total_var = jnp.sum(masked_var(X.data, X.mask, ddof=1))
        else:
            total_var = jnp.sum(explained)
        ratio = explained / total_var

        if isinstance(n_components, float):
            cum = jnp.cumsum(ratio)
            k = min(int(jnp.searchsorted(cum, n_components, side="left")) + 1, len(s))
        else:
            k = n_components

        self.n_components_ = k
        self.components_ = vt[:k]
        self.explained_variance_ = explained[:k]
        self.explained_variance_ratio_ = ratio[:k]
        self.singular_values_ = s[:k]
        self.mean_ = mean
        self.n_samples_ = n
        self.n_features_in_ = d
        if k < min(n, d):
            self.noise_variance_ = (total_var - jnp.sum(explained[:k])) / (
                min(n, d) - k
            )
        else:
            self.noise_variance_ = jnp.asarray(0.0, dtype=s.dtype)
        return u, s, vt

    def transform(self, X):
        x, _ = _masked_or_plain(X)
        out = (x - self.mean_) @ self.components_.T
        if self.whiten:
            out = out / jnp.sqrt(self.explained_variance_)
        return _like_input(X, out)

    def fit_transform(self, X, y=None):
        u, s, vt = self._fit(X)
        out = u[:, : self.n_components_] * s[: self.n_components_]
        if self.whiten:
            import math

            out = out * math.sqrt(self.n_samples_ - 1) / s[: self.n_components_]
        if isinstance(X, ShardedRows):
            return ShardedRows(data=out, mask=X.mask, n_samples=X.n_samples)
        return out[: self.n_samples_]

    def inverse_transform(self, X):
        x, _ = _masked_or_plain(X)
        if self.whiten:
            x = x * jnp.sqrt(self.explained_variance_)
        return _like_input(X, x @ self.components_ + self.mean_)

    def get_covariance(self):
        """Model covariance (probabilistic-PCA form) — one small (d, d)
        device gemm, replicating sklearn's formula EXACTLY, including
        its whiten=True behavior (components rescaled by √λ before the
        (λ−σ²) weighting — sklearn's own convention, matched so scores
        agree elementwise in both modes)."""
        c = self.components_
        ev = self.explained_variance_
        if self.whiten:
            c = c * jnp.sqrt(ev)[:, None]
        diff = jnp.maximum(ev - self.noise_variance_, 0.0)
        cov = (c.T * diff) @ c
        d = c.shape[1]
        return cov + self.noise_variance_ * jnp.eye(d, dtype=cov.dtype)

    def get_precision(self):
        """Inverse of :meth:`get_covariance` via the matrix-inversion
        lemma (sklearn ``PCA.get_precision``): O(d·k²) instead of a
        d×d inverse when k < d, exact fallback otherwise."""
        d = self.components_.shape[1]
        ev = self.explained_variance_
        nv = self.noise_variance_
        if float(nv) == 0.0 or self.n_components_ >= d:
            cov = self.get_covariance()
            prec = jnp.linalg.inv(cov)
            if bool(jnp.all(jnp.isfinite(prec))):
                return prec  # plain inverse is well-posed: report it exactly
            # singular / near-singular covariance only: regularize with a
            # trace-scaled jitter so callers get a finite precision instead
            # of inf/nan (sklearn raises LinAlgError here; a loud-but-
            # finite answer serves score_samples better)
            jitter = 1e-12 * jnp.trace(cov) / d
            return jnp.linalg.inv(cov + jitter * jnp.eye(d, dtype=cov.dtype))
        c = self.components_
        if self.whiten:
            c = c * jnp.sqrt(ev)[:, None]
        diff = jnp.maximum(ev - nv, 0.0)
        # a component whose variance is entirely noise (diff == 0) adds
        # nothing to the model covariance, so it must add nothing to the
        # precision: zero its row (exact) instead of letting 1/diff blow
        # up — the masked diagonal lane then decouples in the inverse
        c = c * (diff > 0)[:, None]
        inner = jnp.diag(1.0 / jnp.where(diff > 0, diff, 1.0)) + (c @ c.T) / nv
        middle = jnp.linalg.inv(inner)
        eye = jnp.eye(d, dtype=c.dtype)
        return (eye - (c.T @ middle @ c) / nv) / nv

    def score_samples(self, X):
        """Per-sample average log-likelihood under the probabilistic PCA
        model (sklearn ``PCA.score_samples``; Tipping & Bishop 1999).
        Computed on device: one centering, one (d, d) solve."""
        x, _ = _masked_or_plain(X)
        xc = x - self.mean_
        cov = self.get_covariance()
        d = cov.shape[0]
        # clamp for invertibility when noise_variance_ == 0 (k == d):
        # the model covariance is then exactly the sample covariance and
        # a tiny jitter keeps the Cholesky well-posed
        jitter = 1e-12 * jnp.trace(cov) / d
        cov = cov + jitter * jnp.eye(d, dtype=cov.dtype)
        chol = jnp.linalg.cholesky(cov)
        sol = jax.scipy.linalg.cho_solve((chol, True), xc.T)  # (d, n)
        mahal = jnp.sum(xc.T * sol, axis=0)
        logdet = 2.0 * jnp.sum(jnp.log(jnp.diagonal(chol)))
        ll = -0.5 * (d * jnp.log(2.0 * jnp.pi) + logdet + mahal)
        if isinstance(X, ShardedRows):
            return ll[: X.n_samples]
        return ll

    def score(self, X, y=None):
        """Mean of ``score_samples`` over the real rows (score_samples
        already slices sharded inputs to their true row count)."""
        return float(jnp.mean(self.score_samples(X)))

"""graftpilot: live knob registry + verdict-driven closed-loop control.

Two halves (docs/design.md §21):

* :mod:`.knobs` — every documented performance lever
  (``PREFETCH_DEPTH``, ``DATA_READERS``, ``DATA_QUEUE``,
  ``SERVE_WINDOW_MS``, ``SERVE_MAX_BATCH``, ``SEARCH_INFLIGHT``) as a
  bounded, strictly-parsed :class:`~.knobs.Knob` with a runtime setter
  and change counter; the owning planes re-read overrides at their
  natural boundaries (block / drain cycle / scheduler turn) through
  lock-free :func:`~.knobs.override_or` loads.
* :mod:`.pilot` — the supervised host-only controller thread
  (``dask-ml-tpu-pilot``) that polls graftpath's live critical-path
  verdict on a cadence and applies the policy table with hysteresis
  (confidence gate, cooldown, step limits, revert-on-regression) and a
  hard ``saturation_pinned`` freeze.

``python -m dask_ml_tpu.control --self-test`` runs the seeded
false-verdict liveness check wired into ``tools/lint.sh``.
"""

from . import knobs  # noqa: F401
from .knobs import (  # noqa: F401
    KNOBS,
    Knob,
    clear_override,
    clear_overrides,
    effective,
    knob,
    observe,
    override,
    override_or,
    set_knob,
)
from . import pilot  # noqa: F401
from .pilot import (  # noqa: F401
    AUTOPILOT_ENV,
    CADENCE_ENV,
    INJECT_ENV,
    PILOT_THREAD_NAME,
    Autopilot,
    autopilot,
    current_pilot,
    maybe_autostart,
    self_test,
    stop_pilot,
)

__all__ = [
    # knobs
    "Knob", "KNOBS", "knob", "set_knob", "override", "override_or",
    "observe", "effective", "clear_override", "clear_overrides",
    # pilot
    "AUTOPILOT_ENV", "CADENCE_ENV", "INJECT_ENV", "PILOT_THREAD_NAME",
    "Autopilot", "autopilot", "maybe_autostart", "current_pilot",
    "stop_pilot", "self_test",
    "report",
]


def report() -> dict:
    """The diagnostics view: live knob table + the active pilot's books
    (None when no pilot is running)."""
    p = current_pilot()
    return {
        "knobs": knobs.report(),
        "pilot": p.report() if p is not None else None,
    }

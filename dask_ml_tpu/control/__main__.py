"""CLI: the graftpilot seeded-fault liveness self-test.

``python -m dask_ml_tpu.control --self-test`` (the default) seeds
``DASK_ML_TPU_PILOT_INJECT=false-verdict`` and asserts the controller
both MOVES the readers knob under the injected verdict and stays FROZEN
under synthetic saturation.  Exit 0 = live; exit 1 = blind, broken, or
explicitly disabled via ``DASK_ML_TPU_AUTOPILOT=off`` — so a disabled
controller verifiably fails the gate (``tools/lint.sh`` runs this on
its default path, next to graftlock's seeded-fault self-test).
"""

from __future__ import annotations

import argparse
import sys

from .pilot import self_test


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m dask_ml_tpu.control",
        description="graftpilot seeded-fault liveness self-test")
    ap.add_argument("--self-test", action="store_true", default=True,
                    help="run the false-verdict move + saturation-freeze "
                         "check (default; exit 0 = controller live)")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="suppress progress output")
    args = ap.parse_args(argv)
    return self_test(verbose=not args.quiet)


if __name__ == "__main__":
    sys.exit(main())

"""graftpilot knob registry: every tuned parameter as a live, bounded value.

Every performance lever in the runtime is a documented env knob
(``DASK_ML_TPU_PREFETCH_DEPTH``, ``DATA_READERS``, ``DATA_QUEUE``,
``SERVE_WINDOW_MS``, ``SERVE_MAX_BATCH``, ``SEARCH_INFLIGHT`` — docs/api.md
§env) — but until this module they were constants frozen at construction:
every recorded win (the 1.45x 4-vs-1 readers under remote-store emulation,
the 1.27-1.55x relay-emulated concurrent search) required a human to read
the graftpath verdict and re-run.  This registry makes each of those
parameters a :class:`Knob`: bounded, strictly parsed, with a runtime
setter (:func:`set_knob`) and a change counter, so the controller loop
(:mod:`.pilot`) — or an operator over a debug console — can move them
mid-run and the owning planes pick the new value up at their natural
re-read points (block boundary / drain cycle / scheduler turn).

Resolution order, everywhere a plane sizes itself::

    explicit ctor arg  >  live override  >  env (strict parse)  >  default

The explicit arg pins the plane (a test that asks for ``readers=2`` gets
2 and the pilot leaves it alone — planes consult the override only when
the caller passed ``None``); the env path keeps its existing strict
parse-and-raise semantics in each plane's own resolver so a typo'd
deployment still fails loudly at construction.  :func:`set_knob` by
contrast CLAMPS to the knob's ``[lo, hi]`` — a controller step can never
push a plane out of its safe envelope, and a clamped move is still a
counted move.

Concurrency contract (graftlock-clean by construction): hot paths read
overrides through :func:`override_or` — one attribute load, no lock, no
``os.environ`` — so the serve drain loop / prefetch worker / reader
threads stay exactly as lock-free as before this module existed.  Only
:func:`set_knob` / :func:`clear_overrides` take the ``control.knobs``
lock, and they acquire nothing else while holding it: zero new
lock-order edges vs ``tools/lock_baseline.json``.  Planes additionally
:func:`observe` the value they are actually running with (also a bare
attribute store) so the pilot steps from the live base — not from the
env default — when a bench detunes a plane with an explicit arg.

Pure host stdlib + the obs metrics registry: importable from any thread,
including the stage-purity-constrained prefetch worker.
"""

from __future__ import annotations

import os

from .._locks import make_lock
from ..obs.metrics import registry as _registry

__all__ = [
    "Knob",
    "KNOBS",
    "knob",
    "set_knob",
    "override",
    "override_or",
    "observe",
    "effective",
    "clear_override",
    "clear_overrides",
    "report",
]

#: one lock guards every override WRITE; reads are bare attribute loads
#: (CPython attribute stores are atomic — a reader sees the old value or
#: the new one, never a torn value).  Nothing else is ever acquired while
#: this is held, and it is never acquired while holding another lock on
#: the setter paths: no new lock-order edges.
_SET_LOCK = make_lock("control.knobs")


class Knob:
    """One live-tunable parameter: bounds, strict parse, change counter.

    ``_override`` is the runtime-set value (None = untouched: planes fall
    through to their env/default resolution).  ``_observed`` is the value
    the owning plane most recently sized itself with — the pilot's
    stepping base when no override exists yet.
    """

    __slots__ = ("name", "env", "kind", "default", "lo", "hi", "unit",
                 "doc", "changes", "_override", "_observed")

    def __init__(self, name: str, env: str, kind: type, default,
                 lo, hi, unit: str, doc: str):
        self.name = name
        self.env = env
        self.kind = kind          # int or float
        self.default = default    # None = dynamic (data_queue: 2x readers)
        self.lo = lo
        self.hi = hi
        self.unit = unit
        self.doc = doc
        self.changes = 0
        self._override = None
        self._observed = None

    # -- strict parse + clamp -------------------------------------------
    def parse(self, value):
        """Strictly parse ``value`` to this knob's kind; raise on junk.

        Accepts the kind itself, a string spelling of it, and (for float
        knobs) ints.  Booleans and floats-for-int-knobs are rejected —
        ``set_knob("data_readers", 2.5)`` is a bug, not a request.
        """
        if isinstance(value, bool):
            raise ValueError(
                f"knob {self.name!r} takes {self.kind.__name__}, "
                f"got bool {value!r}")
        if isinstance(value, str):
            try:
                value = self.kind(value)
            except ValueError:
                raise ValueError(
                    f"knob {self.name!r} must be {self.kind.__name__}, "
                    f"got {value!r}") from None
        elif self.kind is float and isinstance(value, int):
            value = float(value)
        elif not isinstance(value, self.kind):
            raise ValueError(
                f"knob {self.name!r} must be {self.kind.__name__}, "
                f"got {value!r}")
        return value

    def clamp(self, value):
        return min(max(value, self.lo), self.hi)

    # -- resolution helpers ---------------------------------------------
    def env_value(self):
        """Strict env resolution (no override, no observation): the
        knob's env var parsed with parse-or-raise semantics, else its
        static default (None for dynamic defaults)."""
        raw = os.environ.get(self.env)
        if raw is None:
            return self.default
        try:
            return self.kind(raw)
        except ValueError:
            raise ValueError(
                f"{self.env} must be {self.kind.__name__}, "
                f"got {raw!r}") from None

    def effective(self):
        """The value the system is (best-knowledge) running with:
        override > plane-observed > env > static default."""
        if self._override is not None:
            return self._override
        if self._observed is not None:
            return self._observed
        return self.env_value()

    def __repr__(self):
        return (f"Knob({self.name!r}, override={self._override!r}, "
                f"observed={self._observed!r}, changes={self.changes})")


#: the six live knobs — one per documented performance lever.  ``hi`` is
#: a thrash guard, not a promise of benefit (effective reader parallelism
#: still caps at the epoch's shard count; serve max-batch is additionally
#: ceilinged at the server's construction value so a live raise can never
#: force a steady-state compile past the warmed bucket rungs).
KNOBS: dict[str, Knob] = {k.name: k for k in (
    Knob("prefetch_depth", "DASK_ML_TPU_PREFETCH_DEPTH", int, 2, 0, 64,
         "blocks", "staged-block queue capacity between the prefetch "
         "worker and the consumer (pipeline/core.py)"),
    Knob("data_readers", "DASK_ML_TPU_DATA_READERS", int, 4, 1, 64,
         "threads", "parallel shard readers per dataset stream "
         "(data/readers.py)"),
    Knob("data_queue", "DASK_ML_TPU_DATA_QUEUE", int, None, 1, 256,
         "blocks", "reorder-window blocks readers may run ahead of the "
         "consumer (default 2x readers)"),
    Knob("serve_window_ms", "DASK_ML_TPU_SERVE_WINDOW_MS", float, 2.0,
         0.0, 1000.0, "ms", "micro-batch coalescing window ceiling "
         "(serve/batcher.py)"),
    Knob("serve_max_batch", "DASK_ML_TPU_SERVE_MAX_BATCH", int, 1024, 1,
         1 << 20, "rows", "micro-batch row cap (live moves clamp to the "
         "server's construction value: the compile ceiling)"),
    Knob("search_inflight", "DASK_ML_TPU_SEARCH_INFLIGHT", int, 8, 1,
         256, "programs", "device-queue cap per scheduler turn "
         "(model_selection/_orchestrator.py)"),
)}


def knob(name: str) -> Knob:
    """The named :class:`Knob`; unknown names raise (strict registry)."""
    try:
        return KNOBS[name]
    except KeyError:
        raise KeyError(
            f"unknown knob {name!r} (have: {', '.join(sorted(KNOBS))})"
        ) from None


def set_knob(name: str, value, source: str = "api") -> object:
    """Set a live override: strict-parse, CLAMP to bounds, count the
    change, publish the ``control.knob_value{name}`` gauge.  Returns the
    clamped value actually installed."""
    k = knob(name)
    v = k.clamp(k.parse(value))
    with _SET_LOCK:
        k._override = v
        k.changes += 1
    # instruments outside the knob lock: the registry has its own plain
    # (unmonitored) locks and must not nest under control.knobs
    _registry().gauge("control.knob_value", name).set(float(v))
    _registry().counter("control.knob_set", source).inc()
    return v


def override(name: str):
    """The live override (or None) — lock-free."""
    return knob(name)._override


def override_or(name: str, base):
    """Hot-path read: the live override if one is set, else ``base``.
    One attribute load, no lock, never touches ``os.environ`` — legal
    per drain cycle / scheduler turn / block boundary."""
    ov = KNOBS[name]._override
    return base if ov is None else ov


def observe(name: str, value) -> None:
    """Plane-side: record the value this plane is actually running with
    (bare attribute store).  Gives the pilot a stepping base when the
    plane was sized by an explicit arg or env rather than an override."""
    KNOBS[name]._observed = value


def effective(name: str):
    return knob(name).effective()


def clear_override(name: str) -> None:
    k = knob(name)
    with _SET_LOCK:
        k._override = None


def clear_overrides() -> None:
    """Drop every override and observation (test/bench isolation; change
    counters are cumulative and survive, like every other counter)."""
    with _SET_LOCK:
        for k in KNOBS.values():
            k._override = None
            k._observed = None


def report() -> dict:
    """``{name: {override, observed, effective, changes, lo, hi, env}}``
    — the diagnostics view of the live knob table."""
    out = {}
    for name, k in sorted(KNOBS.items()):
        try:
            eff = k.effective()
        except ValueError:
            eff = None  # junk env var: construction would raise loudly
        out[name] = {
            "override": k._override,
            "observed": k._observed,
            "effective": eff,
            "changes": k.changes,
            "lo": k.lo,
            "hi": k.hi,
            "env": k.env,
            "unit": k.unit,
        }
    return out

"""graftpilot: the verdict-driven closed-loop knob controller.

PR 15 (graftpath, design.md §19) turned "why is my fit slow" into a
machine-readable bottleneck VERDICT; :mod:`.knobs` turned every
performance lever into a live, bounded setter.  This module closes the
loop: a host-only supervised unit (literal thread name
``dask-ml-tpu-pilot``, declared in ``_spmd.HOST_ONLY_THREAD_NAMES`` so
graftlint accepts it statically and graftsan runtime-verifies it) polls
the live critical-path attribution on a cadence and applies the policy
table (design.md §21)::

    plane   verdict class      knob            direction
    ------  -----------------  --------------  ---------
    fit     parse-bound        data_readers    up    (then prefetch_depth)
    fit     fetch-bound        data_readers    up    (readers parallelize
                                                     the fetch RTT — the
                                                     recorded 1.45x lever;
                                                     then prefetch_depth)
    fit     stage-bound        prefetch_depth  up
    fit     queue-bound        data_queue      up
    search  dispatcher-bound   search_inflight up
    search  queue-bound        search_inflight up    (the scheduler's own
                                                     throttle IS the queue)
    search  stage-bound        search_inflight up    (cross-unit overlap)
    serve   queue-bound        serve_window_ms up    (then serve_max_batch)
    serve   dispatcher-bound   serve_window_ms down  (window dominates the
                                                     request: stop waiting)
    *       device-bound       —               (goal state: freeze)

Hysteresis, because a controller that thrashes is worse than no
controller:

* **confidence threshold** — only CONFIDENT verdicts (graftpath's
  dominance gate) move anything; low confidence freezes the cycle;
* **cooldown** — after a move, ``cooldown`` cycles must pass before the
  next move, so the effect lands in the books first;
* **step limits** — multiplicative steps (x2 / ÷2), each knob capped at
  ``max_moves`` moves per pilot lifetime plus the registry's hard
  ``[lo, hi]`` clamp;
* **revert-on-regression** — each move's before/after progress rate
  (blocks + serve requests per second) is compared after the cooldown:
  a regression reverts the knob to its prior value and burns that
  (knob, direction); a measurably-flat result (below the noise floor,
  above the revert line) keeps the value but burns the direction so the
  pilot cannot ratchet a dead knob forever.

And one HARD guard ahead of everything else: **saturation freeze**.
When the process is CPU-pinned (Δprocess_time/Δwall ≥ 0.9 over the
cycle — the same ``cpu_over_wall`` definition bench.py uses for its
``saturation_pinned`` label), more host threads cannot help and every
move would thrash the GIL, so the pilot freezes
(``control.freeze{saturation_pinned}``) — the 1-core gate box can never
be thrashed, and the seeded false-verdict liveness test asserts this
guard wins even over an injected verdict.

Seeded-fault liveness (the gate-of-the-gate, same posture as graftlock's
``--inject-*``): ``DASK_ML_TPU_PILOT_INJECT=false-verdict`` forces a
synthetic CONFIDENT parse-bound fit verdict each cycle; the self-test
(``python -m dask_ml_tpu.control --self-test``, wired into
``tools/lint.sh``) asserts the controller both MOVES the readers knob
under the injected verdict and still FREEZES under synthetic
saturation — a blind or disabled controller exits nonzero and can never
gate.
"""

from __future__ import annotations

import os
import threading
import time

from contextlib import contextmanager

from .._locks import make_lock
from ..obs import event as _obs_event
from ..obs import spans as _spans
from ..obs.critical import critical_path as _critical_path
from ..obs.metrics import registry as _registry
from ..resilience import supervisor as _supervisor
from . import knobs as _knobs

__all__ = [
    "AUTOPILOT_ENV",
    "CADENCE_ENV",
    "INJECT_ENV",
    "PILOT_THREAD_NAME",
    "Autopilot",
    "autopilot",
    "active_holds",
    "hold",
    "maybe_autostart",
    "current_pilot",
    "stop_pilot",
    "self_test",
]

AUTOPILOT_ENV = "DASK_ML_TPU_AUTOPILOT"
CADENCE_ENV = "DASK_ML_TPU_PILOT_CADENCE_MS"
INJECT_ENV = "DASK_ML_TPU_PILOT_INJECT"

#: the literal supervised host-only thread name — declared in
#: analysis/rules/_spmd.HOST_ONLY_THREAD_NAMES (graftlint static roster)
#: and runtime-verified by graftsan's thread sweep.
PILOT_THREAD_NAME = "dask-ml-tpu-pilot"

_DEFAULT_CADENCE_MS = 100.0
#: bench.py's saturation_pinned definition: cpu_over_wall >= 0.9
_SATURATION_FRAC = 0.9
#: minimum progress events in a settle window before the before/after
#: rate comparison is trusted (see :meth:`Autopilot._settle_pending`)
_SETTLE_MIN_ITEMS = 8

#: (plane, verdict class) -> ordered (knob, direction) escalation chain.
#: The first un-burned, un-capped knob in the chain moves; classes with
#: no entry (device-bound, unknown) freeze — device-bound IS the goal.
POLICY: dict[tuple, tuple] = {
    ("fit", "parse-bound"): (("data_readers", "up"),
                             ("prefetch_depth", "up")),
    ("fit", "fetch-bound"): (("data_readers", "up"),
                             ("prefetch_depth", "up")),
    ("fit", "stage-bound"): (("prefetch_depth", "up"),),
    ("fit", "queue-bound"): (("data_queue", "up"),),
    ("search", "dispatcher-bound"): (("search_inflight", "up"),),
    ("search", "queue-bound"): (("search_inflight", "up"),),
    ("search", "stage-bound"): (("search_inflight", "up"),),
    ("search", "parse-bound"): (("data_readers", "up"),),
    ("search", "fetch-bound"): (("data_readers", "up"),
                                ("prefetch_depth", "up")),
    ("serve", "queue-bound"): (("serve_window_ms", "up"),
                               ("serve_max_batch", "up")),
    ("serve", "dispatcher-bound"): (("serve_window_ms", "down"),),
}

#: histograms whose exact counts proxy end-to-end progress (blocks
#: consumed + requests served) for revert-on-regression rates.
_PROGRESS_FAMILIES = ("pipeline.block_s", "serve.request_s")

#: external hold latches: while any is set the pilot freezes every
#: cycle (counted under ``control.freeze{<reason>}``) instead of
#: reading books a drain barrier is actively disturbing — the fleet's
#: rolling deploy holds ``fleet_drain`` across each replica's drain
#: window, so half-drained latency never trains a knob move.
_HOLDS: dict = {}
_HOLDS_LOCK = make_lock("control.holds")


def active_holds() -> tuple:
    """The currently-held freeze reasons (sorted; empty = none)."""
    with _HOLDS_LOCK:
        return tuple(sorted(k for k, n in _HOLDS.items() if n > 0))


@contextmanager
def hold(reason: str):
    """Freeze the pilot for the duration of the block (re-entrant:
    nested holds of one reason count)."""
    reason = str(reason)
    with _HOLDS_LOCK:
        _HOLDS[reason] = _HOLDS.get(reason, 0) + 1
    try:
        yield
    finally:
        with _HOLDS_LOCK:
            n = _HOLDS.get(reason, 1) - 1
            if n <= 0:
                _HOLDS.pop(reason, None)
            else:
                _HOLDS[reason] = n


def _env_on(env: str, default: bool = False) -> bool:
    raw = os.environ.get(env)
    if raw is None or raw.strip() == "":
        return default
    v = raw.strip().lower()
    if v in ("1", "on", "true", "yes"):
        return True
    if v in ("0", "off", "false", "no"):
        return False
    raise ValueError(f"{env} must be on/off (1/0/true/false), got {raw!r}")


def resolve_cadence_ms(cadence_ms: float | None = None) -> float:
    """Pilot cycle cadence in ms: explicit arg > env > 100.0 (strict
    parse, >= 1 ms — a sub-ms controller would be pure overhead)."""
    if cadence_ms is None:
        raw = os.environ.get(CADENCE_ENV)
        if raw is None:
            return _DEFAULT_CADENCE_MS
        try:
            cadence_ms = float(raw)
        except ValueError:
            raise ValueError(
                f"{CADENCE_ENV} must be a float, got {raw!r}") from None
    cadence_ms = float(cadence_ms)
    if cadence_ms < 1.0:
        raise ValueError(
            f"pilot cadence must be >= 1 ms, got {cadence_ms}")
    return cadence_ms


def resolve_inject() -> str | None:
    """The seeded-fault mode (``false-verdict``) or None; junk raises."""
    raw = os.environ.get(INJECT_ENV)
    if raw is None or raw.strip() == "":
        return None
    v = raw.strip()
    if v != "false-verdict":
        raise ValueError(
            f"{INJECT_ENV} must be 'false-verdict' (or unset), got {raw!r}")
    return v


def _progress_count() -> int:
    """Exact end-to-end progress: blocks consumed + requests served."""
    total = 0
    for name, _tag, inst in _registry().export_items():
        if name in _PROGRESS_FAMILIES:
            total += inst.count
    return total


class _Window:
    """A synthetic root span over ``[t0, t1]`` — graftpath only reads
    ``t0/t1/name/span_id``, so a live window needs no completed root."""

    __slots__ = ("name", "t0", "t1", "span_id")

    def __init__(self, t0: float, t1: float, plane: str):
        # _plane_of() keys off the root-name prefix
        self.name = f"{'search' if plane == 'search' else 'fit'}.window"
        self.t0 = t0
        self.t1 = t1
        self.span_id = None


class Autopilot:
    """The controller loop.  ``start()`` spawns the supervised host-only
    thread; tests and the self-test drive ``_cycle()`` synchronously."""

    def __init__(self, *, cadence_ms: float | None = None,
                 confidence_min: float | None = None,
                 cooldown: int = 3, max_moves: int = 8,
                 _test_cpu_frac: float | None = None):
        self.cadence_s = resolve_cadence_ms(cadence_ms) / 1e3
        #: verdicts must be CONFIDENT (graftpath dominance) AND at least
        #: this sure before anything moves
        self.confidence_min = (0.35 if confidence_min is None
                               else float(confidence_min))
        self.cooldown = max(1, int(cooldown))
        self.max_moves = max(1, int(max_moves))
        self._test_cpu_frac = _test_cpu_frac
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._hb = None
        # cycle state
        self.cycles = 0
        self.moves: list[dict] = []
        self.reverts: list[dict] = []
        self.freezes: dict[str, int] = {}
        self._last_t: float | None = None
        self._last_cpu: float | None = None
        self._samples: list[tuple] = []   # (t, progress) per cycle
        self._burned: set = set()         # (knob, direction)
        self._moves_per_knob: dict[str, int] = {}
        self._pending: dict | None = None  # move awaiting its verdict
        self._cycles_since_move = 10 ** 9
        self._serve_prev: dict | None = None
        self.errors = 0

    # -- lifecycle ------------------------------------------------------
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def start(self) -> "Autopilot":
        if self.running():
            return self
        # the verdict engine reads span records; arm tracing if the host
        # has not (same posture as obs.perf.run_workload — the spine's
        # overhead ratchet bounds the cost at <=3% of traced wall)
        if not _spans.enabled():
            _spans.enable()
        self._stop.clear()
        self._hb = _supervisor.register(
            "control:pilot", "control",
            interval_s=max(self.cadence_s * 20.0, 2.0))
        # host-only controller by contract (_spmd.HOST_ONLY_THREAD_NAMES,
        # runtime-held by graftsan): it reads span/metric books and
        # writes knob overrides — never compiles, never dispatches; the
        # unprovable calls are obs.spans.event() stdlib bookkeeping
        # graftlint: disable=thread-dispatch -- host-only pilot: verdict reads + knob writes + stdlib span events, never device program dispatch (runtime-verified via HOST_ONLY_THREAD_NAMES)
        t = threading.Thread(target=self._run, name=PILOT_THREAD_NAME,
                             daemon=True)
        self._thread = t
        self._hb._thread = t
        t.start()
        _obs_event("control.pilot_start", cadence_ms=self.cadence_s * 1e3)
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
        if self._hb is not None:
            self._hb.retire()

    def _run(self) -> None:
        while not self._stop.wait(self.cadence_s):
            try:
                self._cycle()
            except Exception as exc:  # the pilot must never take the
                self.errors += 1      # process down: count and carry on
                _registry().counter("control.error", "pilot").inc()
                _obs_event("control.pilot_error", error=repr(exc))

    # -- one control cycle ----------------------------------------------
    def _beat(self) -> None:
        if self._hb is None:
            return
        if _supervisor.lookup(self._hb.name) is not self._hb:
            # diagnostics.reset() dropped the registry entry mid-run;
            # re-register so /healthz keeps covering the pilot
            self._hb = _supervisor.register(
                self._hb.name, self._hb.domain, thread=self._thread,
                interval_s=self._hb.interval_s)
        self._hb.beat()

    # NOTE on the single-owner suppressions below: every attribute the
    # cycle path writes (freezes/moves/reverts/_samples/_burned/
    # _moves_per_knob) is owned by whichever ONE thread drives
    # ``_cycle()`` — the pilot thread once ``start()`` ran, or the
    # main-thread self-test/tests on a pilot that never starts.  No
    # instance is ever driven from two threads (start() refuses while
    # running; the self-test pilots have no thread), so there is no
    # interleaving to guard; a lock here would be pure overhead held
    # every 100 ms on a host-only thread.

    def _freeze(self, reason: str) -> None:
        # graftlint: disable=unguarded-shared-state -- single-owner cycle state (see NOTE above _freeze)
        self.freezes[reason] = self.freezes.get(reason, 0) + 1
        _registry().counter("control.freeze", reason).inc()

    def _cycle(self) -> None:
        now = time.monotonic()
        cpu = time.process_time()
        self._beat()
        self.cycles += 1
        self._cycles_since_move += 1
        # graftlint: disable=unguarded-shared-state -- single-owner cycle state (see NOTE above _freeze)
        self._samples.append((now, _progress_count()))
        if len(self._samples) > 4 * self.cooldown + 8:
            del self._samples[:-(4 * self.cooldown + 8)]
        last_t, last_cpu = self._last_t, self._last_cpu
        self._last_t, self._last_cpu = now, cpu
        if last_t is None or now - last_t <= 0.0:
            return  # first cycle primes the cpu/progress baselines

        # external hold latch (fleet drain barriers): the books are
        # being deliberately disturbed — freeze, don't learn from them
        held = active_holds()
        if held:
            self._freeze(held[0])
            return

        # settle any pending move before considering a new one; while
        # the settle window is still growing, no stacked moves
        self._settle_pending()
        if self._pending is not None:
            return

        # HARD guard: a CPU-pinned process cannot benefit from more host
        # threads; every policy below would thrash.  Wins over inject.
        if self._test_cpu_frac is not None:
            cpu_frac = float(self._test_cpu_frac)
        else:
            cpu_frac = (cpu - last_cpu) / (now - last_t)
        if cpu_frac >= _SATURATION_FRAC:
            self._freeze("saturation_pinned")
            return

        inject = resolve_inject()
        if inject == "false-verdict":
            plane, verdict = "fit", {"class": "parse-bound",
                                     "confidence": 1.0,
                                     "confident": True,
                                     "injected": True}
        else:
            got = self._live_verdict(last_t, now)
            if got is None:
                return  # nothing ran this window: hold, not a freeze
            plane, verdict = got

        if self._cycles_since_move < self.cooldown:
            return  # cooldown: let the last move land in the books
        self._apply(plane, verdict)

    # -- verdict acquisition --------------------------------------------
    def _live_verdict(self, lo: float, hi: float):
        """(plane, verdict) for the just-elapsed window, or None when
        nothing ran.  fit/search comes from graftpath over a synthetic
        window root; serve from the per-leg request split deltas."""
        records = [r for r in _spans.span_records()
                   if getattr(r, "kind", "span") == "span"
                   and r.t1 > lo and r.t0 < hi]
        fit_like = None
        if records:
            plane = ("search" if any(r.name.startswith("search.")
                                     for r in records) else "fit")
            res = _critical_path(root=_Window(lo, hi, plane),
                                 records=records, publish=False)
            v = res.get("verdict") or {}
            if v.get("class") not in (None, "unknown"):
                fit_like = (res.get("plane") or plane, v)
        serve = self._serve_window_verdict()
        if fit_like is not None and serve is not None:
            # one move per cycle: follow the more confident story
            return (fit_like if fit_like[1].get("confidence", 0.0)
                    >= serve[1].get("confidence", 0.0) else serve)
        return fit_like if fit_like is not None else serve

    def _serve_window_verdict(self):
        """Windowed serve verdict from per-leg sum deltas (the
        cumulative histograms behind :func:`~..obs.critical.serve_critical`,
        differenced per cycle so the pilot sees the CURRENT regime, not
        the whole process history)."""
        sums = {seg: 0.0 for seg in ("queue", "window", "device",
                                     "fetch")}
        count = 0
        for name, _tag, inst in _registry().export_items():
            for seg in sums:
                if name == f"serve.req_{seg}_s":
                    sums[seg] += inst.sum
                    if seg == "queue":
                        count += inst.count
        prev, self._serve_prev = self._serve_prev, {"sums": sums,
                                                    "count": count}
        if prev is None or count <= prev["count"]:
            return None  # no (new) serve traffic this window
        delta = {seg: max(sums[seg] - prev["sums"][seg], 0.0)
                 for seg in sums}
        total = sum(delta.values())
        if total <= 0.0:
            return None
        shares = {seg: v / total for seg, v in delta.items()}
        top = max(shares, key=shares.get)
        cls = {"queue": "queue-bound", "window": "dispatcher-bound",
               "device": "device-bound", "fetch": "fetch-bound"}[top]
        return ("serve", {"class": cls, "confidence": shares[top],
                          "confident": shares[top] >= self.confidence_min})

    # -- the move engine -------------------------------------------------
    def _rate(self, n_cycles: int) -> float | None:
        """Progress rate (items/s) over the last ``n_cycles`` samples."""
        if len(self._samples) < n_cycles + 1:
            return None
        t1, p1 = self._samples[-1]
        t0, p0 = self._samples[-1 - n_cycles]
        if t1 <= t0:
            return None
        return (p1 - p0) / (t1 - t0)

    def _settle_pending(self) -> None:
        """After a move's cooldown: regression reverts + burns, a flat
        result keeps the value but burns the direction (no ratcheting a
        dead knob), an improvement keeps the chain alive.

        The judgment window GROWS until it holds at least
        ``_SETTLE_MIN_ITEMS`` progress events (up to ``4 * cooldown``
        cycles): a cooldown-sized window on a slow plane sees two or
        three blocks, and judging on that much quantization reverts
        good moves.  A window with ZERO progress is an idle gap between
        fits — a sizing knob cannot halt a plane — so the move is kept
        unjudged rather than read as a collapse."""
        p = self._pending
        if p is None or self._cycles_since_move < self.cooldown:
            return
        n = min(self._cycles_since_move, 4 * self.cooldown)
        before = p["rate_before"]
        if (before is None or before <= 0.0
                or len(self._samples) < n + 1):
            self._pending = None
            return  # progress meter blind around the move: keep it
        t1, p1 = self._samples[-1]
        t0, p0 = self._samples[-1 - n]
        items = p1 - p0
        if items <= 0 or t1 <= t0:
            self._pending = None
            return  # idle gap (nothing ran in the window): keep it
        if items < _SETTLE_MIN_ITEMS:
            if n < 4 * self.cooldown:
                return  # window too thin to judge yet: let it grow
            self._pending = None
            return  # capped and still thin: too quantized to judge
        self._pending = None
        after = items / (t1 - t0)
        if after < 0.95 * before:
            _knobs.set_knob(p["knob"], p["prev"], source="pilot-revert")
            # graftlint: disable=unguarded-shared-state -- single-owner cycle state (see NOTE above _freeze)
            self._burned.add((p["knob"], p["direction"]))
            rec = dict(p, rate_after=after, action="revert")
            # graftlint: disable=unguarded-shared-state -- single-owner cycle state (see NOTE above _freeze)
            self.reverts.append(rec)
            _registry().counter("control.revert", p["knob"]).inc()
            _obs_event("control.knob_revert", knob=p["knob"],
                       to=p["prev"], rate_before=round(before, 3),
                       rate_after=round(after, 3))
        elif after < 0.98 * before:
            # measurably-not-helping (below the noise floor but above
            # the revert line): keep the value, burn the direction so
            # the chain moves on.  An ambiguous settle (~1.0x) keeps
            # the chain ALIVE — cooldown-sized rate windows on a loaded
            # box flap several percent, and max_moves still bounds a
            # genuinely dead knob.
            self._burned.add((p["knob"], p["direction"]))

    def _step(self, k: "_knobs.Knob", cur, direction: str):
        if k.kind is int:
            new = cur * 2 if direction == "up" else cur // 2
            if direction == "up":
                new = max(new, cur + 1)
        else:
            if direction == "up":
                new = cur * 2.0 if cur > 0.0 else 1.0
            else:
                new = cur / 2.0 if cur > 0.5 else 0.0
        return k.clamp(new)

    def _apply(self, plane: str, verdict: dict) -> None:
        cls = verdict.get("class", "unknown")
        chain = POLICY.get((plane, cls))
        if chain is None:
            self._freeze("no_policy")  # device-bound / unknown: the
            return                     # goal state, nothing to fix
        if (not verdict.get("confident")
                or verdict.get("confidence", 0.0) < self.confidence_min):
            self._freeze("low_confidence")
            return
        for name, direction in chain:
            if (name, direction) in self._burned:
                continue
            if self._moves_per_knob.get(name, 0) >= self.max_moves:
                continue
            k = _knobs.knob(name)
            cur = k.effective()
            if cur is None:
                continue  # dynamic default, never observed: no base
            new = self._step(k, cur, direction)
            if new == cur:
                self._burned.add((name, direction))  # at a hard bound
                continue
            _knobs.set_knob(name, new, source="pilot")
            # graftlint: disable=unguarded-shared-state -- single-owner cycle state (see NOTE above _freeze)
            self._moves_per_knob[name] = (
                self._moves_per_knob.get(name, 0) + 1)
            # pre-move rate over the widest window that is still all
            # post-previous-move: short windows are integer-quantized
            # (a 50 ms window sees a handful of blocks) and a biased
            # ``before`` mis-judges the settle either way
            n_before = min(self._cycles_since_move, 4 * self.cooldown)
            rate_before = self._rate(n_before)
            self._cycles_since_move = 0
            move = {"knob": name, "direction": direction, "prev": cur,
                    "to": new, "plane": plane, "class": cls,
                    "confidence": round(
                        float(verdict.get("confidence", 0.0)), 4),
                    "injected": bool(verdict.get("injected", False)),
                    "cycle": self.cycles}
            # graftlint: disable=unguarded-shared-state -- single-owner cycle state (see NOTE above _freeze)
            self.moves.append(move)
            if not move["injected"]:
                # injected verdicts have no real throughput to judge
                self._pending = dict(move, rate_before=rate_before)
            _registry().counter("control.knob_move",
                                f"{name}:{direction}").inc()
            _obs_event("control.knob_move", knob=name,
                       direction=direction, prev=cur, to=new,
                       plane=plane, verdict=cls)
            return
        self._freeze("policy_exhausted")

    # -- reporting -------------------------------------------------------
    def converged(self, quiet_cycles: int | None = None) -> bool:
        """True once the pilot has gone ``quiet_cycles`` (default: one
        cooldown) cycles without a move — the bench/perf convergence
        criterion."""
        q = self.cooldown if quiet_cycles is None else int(quiet_cycles)
        return self._cycles_since_move >= q and self._pending is None

    def report(self) -> dict:
        return {
            "running": self.running(),
            "cadence_ms": self.cadence_s * 1e3,
            "cycles": self.cycles,
            "moves": list(self.moves),
            "reverts": list(self.reverts),
            "freezes": dict(self.freezes),
            "burned": sorted(f"{k}:{d}" for k, d in self._burned),
            "converged": self.converged(),
            "errors": self.errors,
            "knobs": _knobs.report(),
        }


# -- process-global pilot (env-armed) ------------------------------------

_PILOT_LOCK = make_lock("control.pilot")
_PILOT: Autopilot | None = None


def current_pilot() -> Autopilot | None:
    return _PILOT


def maybe_autostart() -> Autopilot | None:
    """Arm the process-global pilot iff ``DASK_ML_TPU_AUTOPILOT`` is on.
    Called from the planes' entry points (stream construction, server
    construction, search run) — idempotent and cheap when off."""
    if not _env_on(AUTOPILOT_ENV):
        return None
    global _PILOT
    with _PILOT_LOCK:
        p = _PILOT
        if p is None or not p.running():
            p = _PILOT = Autopilot()
    if not p.running():
        p.start()
    return p


def stop_pilot() -> None:
    """Stop (and forget) the process-global pilot, if any."""
    global _PILOT
    with _PILOT_LOCK:
        p, _PILOT = _PILOT, None
    if p is not None:
        p.stop()


@contextmanager
def autopilot(**kwargs):
    """Scoped pilot for benches/tests: start, yield, always stop and
    clear the overrides it installed."""
    p = Autopilot(**kwargs)
    p.start()
    try:
        yield p
    finally:
        p.stop()
        _knobs.clear_overrides()


# -- seeded-fault liveness (the gate-of-the-gate) -------------------------

def self_test(verbose: bool = True) -> int:
    """Exit-code semantics for ``python -m dask_ml_tpu.control
    --self-test``: 0 = the controller is LIVE (the injected false
    verdict moved the readers knob AND synthetic saturation froze a
    second pilot); nonzero = blind, broken, or explicitly disabled —
    and a blind controller must never gate."""
    def say(msg):
        if verbose:
            print(f"graftpilot self-test: {msg}")

    try:
        if not _env_on(AUTOPILOT_ENV, default=True):
            say(f"controller DISABLED via {AUTOPILOT_ENV} — failing the "
                "gate (a disabled controller cannot vouch for itself)")
            return 1
    except ValueError as exc:
        say(f"bad {AUTOPILOT_ENV}: {exc}")
        return 1
    prior_inject = os.environ.get(INJECT_ENV)
    os.environ.setdefault(INJECT_ENV, "false-verdict")
    if resolve_inject() != "false-verdict":
        say(f"unexpected {INJECT_ENV}={os.environ.get(INJECT_ENV)!r}")
        return 1
    reg = _registry()
    rc = 0
    _knobs.clear_overrides()
    try:
        # half 1: the injected parse-bound verdict must move readers UP
        p = Autopilot(cadence_ms=5.0, cooldown=1, _test_cpu_frac=0.0)
        base = _knobs.knob("data_readers").effective()
        for _ in range(3):
            p._cycle()
        moved = [m for m in p.moves if m["knob"] == "data_readers"
                 and m["direction"] == "up"]
        booked = reg.family("control.knob_move").get(
            "data_readers:up", 0)
        if not moved or _knobs.override("data_readers") is None:
            say("FAIL: injected false verdict did not move data_readers")
            rc = 1
        elif _knobs.override("data_readers") <= base or not booked:
            say("FAIL: data_readers move not upward / not booked")
            rc = 1
        else:
            say(f"move ok: data_readers {base} -> "
                f"{_knobs.override('data_readers')} "
                f"({len(moved)} move(s), injected verdict)")
        # half 2: saturation_pinned must freeze even an injected verdict
        _knobs.clear_overrides()
        frozen = Autopilot(cadence_ms=5.0, cooldown=1,
                           _test_cpu_frac=1.0)
        for _ in range(3):
            frozen._cycle()
        if frozen.moves or not frozen.freezes.get("saturation_pinned"):
            say("FAIL: saturation_pinned did not freeze the controller "
                f"(moves={frozen.moves}, freezes={frozen.freezes})")
            rc = 1
        else:
            say(f"freeze ok: {frozen.freezes['saturation_pinned']} "
                "saturation_pinned cycle(s), zero moves")
    finally:
        _knobs.clear_overrides()
        if prior_inject is None:
            os.environ.pop(INJECT_ENV, None)
        else:
            os.environ[INJECT_ENV] = prior_inject
    if rc == 0:
        say("PASS (move + freeze)")
    return rc

"""Deprecated shim — reference parity for ``dask_ml/xgboost.py``.

The reference module was a historical re-export of the external
``dask-xgboost`` integration and was deprecated upstream in favor of
``xgboost.dask``; it carries no capability of its own (SURVEY.md §2.1
component 27).  This twin preserves the import surface and the
deprecation behavior: importing it works, touching any attribute raises
with a pointer to the supported path.

There is no TPU XGBoost: gradient-boosted trees are hostile to the MXU
(data-dependent splits, scalar control flow).  Users wanting boosted
trees should train with the upstream ``xgboost`` package on host and wrap
the fitted model in :class:`dask_ml_tpu.wrappers.ParallelPostFit` for
sharded inference — that combination is tested and supported.
"""

from __future__ import annotations

import warnings

warnings.warn(
    "dask_ml_tpu.xgboost is a deprecation shim (the reference's "
    "dask_ml.xgboost re-export was itself deprecated). Train with the "
    "upstream xgboost package and wrap the fitted model in "
    "dask_ml_tpu.wrappers.ParallelPostFit for sharded inference.",
    FutureWarning,
    stacklevel=2,
)

_MSG = (
    "dask_ml_tpu.xgboost.{name} is not provided: the reference module was "
    "a deprecated re-export of dask-xgboost. Use the upstream xgboost "
    "package for training and dask_ml_tpu.wrappers.ParallelPostFit for "
    "sharded inference."
)


def __getattr__(name):
    raise AttributeError(_MSG.format(name=name))

"""SpectralClustering via Nyström approximation.

Reference: ``dask_ml/cluster/spectral.py :: SpectralClustering`` — sample
``n_components`` rows, exact affinity on the sample (A) + cross affinity
(B), approximate the top eigenvectors of the full normalized affinity,
embed every row, cluster the embedding with KMeans (SURVEY.md §2 #7).

TPU formulation: with sample S (m rows, replicated) and E = k(X, S)
(n×m, row-sharded), the Nyström-approximated normalized affinity is
D^{-1/2} E A⁻¹ Eᵀ D^{-1/2}.  Its top eigenvectors come from the m×m
matrix M = A^{-1/2} (CᵀC) A^{-1/2} with C = D^{-1/2} E — CᵀC is a
psum-reduced gemm, so nothing bigger than m×m ever leaves the device mesh
and no arbitrary-index gathers are needed (the reference's
``_slice_mostly_sorted`` shuffle disappears).
"""

from __future__ import annotations

import logging
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

from ..base import TPUEstimator
from ..core.prng import as_key
from ..linalg.tsqr import tsqr_strategy as _tsqr_strategy
from ..core.sharded import ShardedRows
from ..metrics.pairwise import PAIRWISE_KERNEL_FUNCTIONS
from ..preprocessing.data import _ingest_float
from .k_means import KMeans
from .. import sanitize as _san

#: runtime-verified twin of the chunk-boundary host-sync-loop
#: suppression in the exact-eigensolver loop (see sanitize/sites.py)
_RITZ_SYNC = _san.AllowSite(
    "spectral-ritz-sync", rule="host-sync-loop",
    cites="348729a2df9b2736",
    note="one (kp,) Ritz-value fetch per fused n_power_iters-deep "
         "subspace chunk, <= 10 per fit",
)

logger = logging.getLogger(__name__)

# Exact path materializes an O(n²/P) affinity per device; refuse beyond
# this many rows rather than OOM a pod mid-fit.
_EXACT_MAX_ROWS = 200_000


# The exact eigensolve is three fused programs driven by a tiny host loop:
# normalize once, advance the subspace in chunks, and check Ritz-value
# convergence between chunks (sparse kNN graphs have near-degenerate
# spectra — a fixed iteration count either wastes work on easy graphs or
# under-converges hard ones).  The iteration runs on C + I (a spectrum
# SHIFT): orthogonal iteration converges to the largest-|λ| subspace, and
# normalized affinities can have dominant NEGATIVE eigenvalues
# (near-bipartite graphs) that would crowd the wanted top positive
# eigenvectors out of the k+p subspace; λ+1 ∈ [0, 2] makes signed order
# equal magnitude order, and Rayleigh–Ritz on the ORIGINAL C recovers the
# true eigenvalues.


@jax.jit
def _normalized_affinity(W, mask):
    W = W * mask[:, None] * mask[None, :]
    deg = jnp.sum(W, axis=1)
    dinv = jnp.where((deg > 1e-12) & (mask > 0), 1.0 / jnp.sqrt(deg), 0.0)
    return dinv[:, None] * W * dinv[None, :]


@partial(jax.jit, static_argnames=("mesh_holder", "iters", "qr_strategy"))
def _subspace_chunk(C, V, *, mesh_holder, iters, qr_strategy="householder"):
    from ..linalg.tsqr import _tsqr_impl

    def body(_, v):
        return _tsqr_impl(
            C @ v + v, mesh_holder=mesh_holder, strategy=qr_strategy
        )[0]  # (C+I)v

    return jax.lax.fori_loop(0, iters, body, V)


@jax.jit
def _ritz_values(C, V):
    return jnp.linalg.eigvalsh(V.T @ (C @ V))


@partial(jax.jit, static_argnames=("k",))
def _ritz_embedding(C, V, *, k):
    M = V.T @ (C @ V)  # (kp, kp) replicated Rayleigh-Ritz on the TRUE C
    w, u = jnp.linalg.eigh(M)
    top = u[:, -k:][:, ::-1]
    lam = w[-k:][::-1]
    emb = V @ top
    norms = jnp.linalg.norm(emb, axis=1, keepdims=True)
    return emb / jnp.where(norms > 1e-12, norms, 1.0), lam


@partial(jax.jit, static_argnames=("k_nn",))
def _knn_graph(d2, mask, *, k_nn):
    """Symmetric binary kNN graph from a (padded_n, padded_n) distance
    matrix, fused: self/pad exclusion, EXACTLY-k neighbor scatter (a
    `d2 <= kth` threshold would admit every tie — duplicate-heavy data
    then blows degrees past k), union-symmetrize, mask."""
    pn = d2.shape[0]
    inf = jnp.asarray(jnp.inf, d2.dtype)
    ridx = jnp.arange(pn)
    d2 = jnp.where(ridx[:, None] == ridx[None, :], inf, d2)  # no self
    d2 = jnp.where(mask[None, :] > 0, d2, inf)  # no pad cols
    _, nbr = jax.lax.top_k(-d2, k_nn)  # (pn, k) nearest indices
    W = jnp.zeros((pn, pn), d2.dtype).at[ridx[:, None], nbr].set(1.0)
    W = jnp.maximum(W, W.T)
    return W * mask[:, None] * mask[None, :]


def _inv_sqrt_psd(a, eps=1e-8):
    w, v = jnp.linalg.eigh(a)
    w = jnp.maximum(w, eps)
    return (v * (1.0 / jnp.sqrt(w))) @ v.T


class SpectralClustering(TPUEstimator):
    def __init__(self, n_clusters=8, eigen_solver=None, random_state=None,
                 n_init=10, gamma=None, affinity="rbf", n_neighbors=10,
                 eigen_tol=0.0, assign_labels="kmeans", degree=3, coef0=1,
                 kernel_params=None, n_jobs=1, n_components=100,
                 persist_embedding=False, kmeans_params=None):
        self.n_clusters = n_clusters
        self.eigen_solver = eigen_solver
        self.random_state = random_state
        self.n_init = n_init
        self.gamma = gamma
        self.affinity = affinity
        self.n_neighbors = n_neighbors
        self.eigen_tol = eigen_tol
        self.assign_labels = assign_labels
        self.degree = degree
        self.coef0 = coef0
        self.kernel_params = kernel_params
        self.n_jobs = n_jobs
        self.n_components = n_components
        self.persist_embedding = persist_embedding
        self.kmeans_params = kmeans_params

    def _kernel(self, X, S):
        if callable(self.affinity):
            return self.affinity(X, S)
        params = dict(self.kernel_params or {})
        if self.affinity == "rbf":
            params.setdefault("gamma", self.gamma)
            return PAIRWISE_KERNEL_FUNCTIONS["rbf"](X, S, **params)
        if self.affinity == "polynomial":
            params.setdefault("gamma", self.gamma)
            params.setdefault("degree", self.degree)
            params.setdefault("coef0", self.coef0)
            return PAIRWISE_KERNEL_FUNCTIONS["polynomial"](X, S, **params)
        raise ValueError(
            f"Unsupported affinity: {self.affinity!r} "
            "(rbf, polynomial, nearest_neighbors, precomputed, or callable)"
        )

    def _sample_affinities(self, X, idx):
        """(E, A): cross affinity (padded_n, m) sharded and sample affinity
        (m, m) replicated, per the configured affinity."""
        if self.affinity == "precomputed":
            # X IS the affinity matrix: columns/rows at the sampled indices
            # (reference SpectralClustering(affinity='precomputed'))
            E = jnp.take(X.data, idx, axis=1)
            A = jnp.take(E, idx, axis=0)
            return E * X.mask[:, None], A
        # feature affinities need the sampled ROWS; precomputed above works
        # on columns only, so the gather lives here where it's used
        sample = jnp.take(X.data, idx, axis=0)
        E = self._kernel(X.data, sample)
        return E * X.mask[:, None], self._kernel(sample, sample)

    def fit(self, X, y=None):
        X = _ingest_float(self, X)
        n = X.n_samples
        if self.affinity == "precomputed" and X.data.shape[1] != n:
            raise ValueError(
                "affinity='precomputed' expects the (n_samples, n_samples) "
                f"affinity matrix itself; got shape ({n}, {X.data.shape[1]})"
            )
        if self.n_components is None or self.affinity == "nearest_neighbors":
            if self.affinity == "nearest_neighbors" and self.n_components is not None:
                # nearest_neighbors needs the FULL kNN graph (a binary kNN
                # connectivity restricted to sample columns is not a valid
                # Nyström decomposition), so n_components cannot apply
                logger.warning(
                    "affinity='nearest_neighbors' ignores n_components=%s: "
                    "the full kNN graph is built and solved exactly "
                    "(O(n^2/P) memory per device)", self.n_components,
                )
            if n > _EXACT_MAX_ROWS:
                raise ValueError(
                    f"exact spectral path materializes an n x n affinity and "
                    f"n={n} exceeds the {_EXACT_MAX_ROWS} guard; use the "
                    "Nyström path (set n_components, with affinity "
                    "'rbf'/'polynomial'/'precomputed'/callable)"
                )
            return self._fit_exact(X)
        m = min(self.n_components, n)
        key = as_key(self.random_state)

        # sample m real row INDICES — the gather of sampled rows (feature
        # affinities only) stays on device; indices are < n_samples, so no
        # pad rows are selectable
        idx = jax.random.choice(key, n, (m,), replace=False)

        E, A = self._sample_affinities(X, idx)

        A_inv = jnp.linalg.pinv(A, hermitian=True)
        # approximate degrees: d = E A^{-1} (E^T 1)
        col_sums = jnp.sum(E, axis=0)  # (m,) — psum over shards
        d = E @ (A_inv @ col_sums)
        d = jnp.where((d > 1e-12) & (X.mask > 0), d, 1.0)
        C = E / jnp.sqrt(d)[:, None]  # D^{-1/2} E, sharded

        A_is = _inv_sqrt_psd(A)
        G = C @ A_is  # (n, m) sharded
        M = G.T @ G  # (m, m) — psum-reduced gemm
        w, u = jnp.linalg.eigh(M)  # ascending
        k = self.n_clusters
        top = u[:, -k:][:, ::-1]
        lam = jnp.maximum(w[-k:][::-1], 1e-12)
        V = G @ (top / jnp.sqrt(lam)[None, :])  # (n, k) sharded embedding
        # row-normalize the embedding (standard for normalized-cuts kmeans)
        norms = jnp.linalg.norm(V, axis=1, keepdims=True)
        V = V / jnp.where(norms > 1e-12, norms, 1.0)

        return self._finalize(V, lam, X)

    def _finalize(self, emb_data, lam, X):
        """Cluster the row-normalized embedding and set the fitted attrs
        (shared by the Nyström and exact paths)."""
        emb = ShardedRows(data=emb_data, mask=X.mask, n_samples=X.n_samples)
        km_params = {"n_clusters": self.n_clusters, "random_state": self.random_state}
        km_params.update(self.kmeans_params or {})
        km = KMeans(**km_params)
        km.fit(emb)
        self.assign_labels_ = km
        self.labels_ = km.labels_
        self.eigenvalues_ = lam
        self.n_features_in_ = X.data.shape[1]
        if self.persist_embedding:
            self.embedding_ = emb
        return self

    # -- exact (non-Nyström) path --------------------------------------
    def _full_affinity(self, X):
        """(padded_n, padded_n) row-sharded affinity with masked rows/cols.
        Feature affinities flow through the ppermute ring — the Y-blocks
        circulate ICI while each device computes its tile (ring attention's
        outer loop; SURVEY.md §5)."""
        from ..core.mesh import MeshHolder, get_mesh
        from ..metrics import pairwise as pw

        if self.affinity == "precomputed":
            W = X.data
            pad = X.padded - W.shape[1]
            if pad:
                W = jnp.pad(W, ((0, 0), (0, pad)))
        elif self.affinity == "nearest_neighbors":
            # symmetric binary kNN connectivity over ALL rows (sklearn's
            # kneighbors_graph semantics: self excluded, union-symmetrized),
            # graph construction fused in _knn_graph
            d2 = pw._ring_impl(
                X.data, X.data, mesh_holder=MeshHolder(get_mesh()),
                fn=pw._sq_euclidean_hi,
            )
            k_nn = min(self.n_neighbors, max(X.n_samples - 1, 1))
            W = _knn_graph(d2, X.mask, k_nn=k_nn)
        else:
            if callable(self.affinity):
                tile = self.affinity
            elif self.affinity == "rbf":
                g = self.gamma if self.gamma is not None else 1.0 / X.data.shape[1]
                # X-vs-X self ring: _SelfTile pins the exact diagonal so
                # the cancellation guard never fires on self-pairs
                tile = pw._SelfTile("rbf", gamma=float(g))
            elif self.affinity == "polynomial":
                g = self.gamma if self.gamma is not None else 1.0 / X.data.shape[1]
                tile = pw._BoundTile(
                    pw._poly_tile, gamma=float(g), coef0=float(self.coef0),
                    degree=int(self.degree),
                )
            else:
                raise ValueError(
                    f"affinity {self.affinity!r} not supported on the exact "
                    "path (rbf, polynomial, precomputed, or callable)"
                )
            W = pw._ring_impl(
                X.data, X.data, mesh_holder=MeshHolder(get_mesh()), fn=tile
            )
        # NOTE: returned W is unmasked (except the fused kNN graph);
        # _exact_embed applies the row+col mask inside its fused program so
        # no extra n² temporary is materialized here.
        return W

    def _fit_exact(self, X, n_power_iters: int = 40, oversample: int = 8):
        """Exact normalized-cuts embedding (``n_components=None``): full
        affinity via the ring, top eigenvectors of D^{-1/2} W D^{-1/2} by
        orthogonal iteration with TSQR re-orthogonalization — the whole
        subspace stays row-sharded; only (k+p)² Rayleigh–Ritz matrices are
        replicated.  The entire eigensolve compiles to ONE XLA program
        (eager matmuls on sharded operands would issue cross-module
        collectives per op).  O(n²/P) affinity memory per device: exact is
        for moderate n, the Nyström default for the rest."""
        n = X.n_samples
        k = self.n_clusters
        W = self._full_affinity(X)
        key = as_key(self.random_state)
        kp = min(k + oversample, n)
        from ..core.mesh import MeshHolder, get_mesh
        from ..core.sharded import row_sharding

        mesh = get_mesh()
        mh = MeshHolder(mesh)
        C = _normalized_affinity(W, X.mask)
        V = jax.device_put(
            jax.random.normal(key, (X.padded, kp), dtype=X.data.dtype),
            row_sharding(mesh, 2),
        )
        tol = max(float(self.eigen_tol or 0.0), 1e-6)
        prev = None
        for chunk in range(10):  # ≤ 10 * n_power_iters iterations
            V = _subspace_chunk(
                C, V, mesh_holder=mh, iters=int(n_power_iters),
                qr_strategy=_tsqr_strategy(),
            )
            with _RITZ_SYNC.allow():
                # graftlint: disable=host-sync-loop -- chunk-boundary Ritz convergence check: one (kp,) fetch per n_power_iters-deep fused chunk (<= 10 total)
                lam_now = np.asarray(_ritz_values(C, V))[-k:]
            if prev is not None and np.max(np.abs(lam_now - prev)) < tol:
                break
            prev = lam_now
        logger.debug("exact spectral: %d subspace chunks", chunk + 1)
        emb, lam = _ritz_embedding(C, V, k=int(k))
        return self._finalize(emb, lam, X)

    def fit_predict(self, X, y=None):
        return self.fit(X).labels_

"""SpectralClustering via Nyström approximation.

Reference: ``dask_ml/cluster/spectral.py :: SpectralClustering`` — sample
``n_components`` rows, exact affinity on the sample (A) + cross affinity
(B), approximate the top eigenvectors of the full normalized affinity,
embed every row, cluster the embedding with KMeans (SURVEY.md §2 #7).

TPU formulation: with sample S (m rows, replicated) and E = k(X, S)
(n×m, row-sharded), the Nyström-approximated normalized affinity is
D^{-1/2} E A⁻¹ Eᵀ D^{-1/2}.  Its top eigenvectors come from the m×m
matrix M = A^{-1/2} (CᵀC) A^{-1/2} with C = D^{-1/2} E — CᵀC is a
psum-reduced gemm, so nothing bigger than m×m ever leaves the device mesh
and no arbitrary-index gathers are needed (the reference's
``_slice_mostly_sorted`` shuffle disappears).
"""

from __future__ import annotations

import logging

import numpy as np

import jax
import jax.numpy as jnp

from ..base import TPUEstimator
from ..core.prng import as_key
from ..core.sharded import ShardedRows
from ..metrics.pairwise import PAIRWISE_KERNEL_FUNCTIONS
from ..preprocessing.data import _ingest_float
from .k_means import KMeans

logger = logging.getLogger(__name__)


def _inv_sqrt_psd(a, eps=1e-8):
    w, v = jnp.linalg.eigh(a)
    w = jnp.maximum(w, eps)
    return (v * (1.0 / jnp.sqrt(w))) @ v.T


class SpectralClustering(TPUEstimator):
    def __init__(self, n_clusters=8, eigen_solver=None, random_state=None,
                 n_init=10, gamma=None, affinity="rbf", n_neighbors=10,
                 eigen_tol=0.0, assign_labels="kmeans", degree=3, coef0=1,
                 kernel_params=None, n_jobs=1, n_components=100,
                 persist_embedding=False, kmeans_params=None):
        self.n_clusters = n_clusters
        self.eigen_solver = eigen_solver
        self.random_state = random_state
        self.n_init = n_init
        self.gamma = gamma
        self.affinity = affinity
        self.n_neighbors = n_neighbors
        self.eigen_tol = eigen_tol
        self.assign_labels = assign_labels
        self.degree = degree
        self.coef0 = coef0
        self.kernel_params = kernel_params
        self.n_jobs = n_jobs
        self.n_components = n_components
        self.persist_embedding = persist_embedding
        self.kmeans_params = kmeans_params

    def _kernel(self, X, S):
        if callable(self.affinity):
            return self.affinity(X, S)
        params = dict(self.kernel_params or {})
        if self.affinity == "rbf":
            params.setdefault("gamma", self.gamma)
            return PAIRWISE_KERNEL_FUNCTIONS["rbf"](X, S, **params)
        if self.affinity == "polynomial":
            params.setdefault("gamma", self.gamma)
            params.setdefault("degree", self.degree)
            params.setdefault("coef0", self.coef0)
            return PAIRWISE_KERNEL_FUNCTIONS["polynomial"](X, S, **params)
        raise ValueError(
            f"Unsupported affinity: {self.affinity!r} (rbf, polynomial, or callable)"
        )

    def fit(self, X, y=None):
        X = _ingest_float(self, X)
        n = X.n_samples
        m = min(self.n_components, n)
        key = as_key(self.random_state)

        # sample m real rows — index draw + gather stay on device (indices
        # are < n_samples, so no pad rows are selectable)
        idx = jax.random.choice(key, n, (m,), replace=False)
        sample = jnp.take(X.data, idx, axis=0)

        # E: (padded_n, m) sharded; zero padded rows via mask
        E = self._kernel(X.data, sample)
        E = E * X.mask[:, None]
        A = self._kernel(sample, sample)  # (m, m) replicated

        A_inv = jnp.linalg.pinv(A, hermitian=True)
        # approximate degrees: d = E A^{-1} (E^T 1)
        col_sums = jnp.sum(E, axis=0)  # (m,) — psum over shards
        d = E @ (A_inv @ col_sums)
        d = jnp.where((d > 1e-12) & (X.mask > 0), d, 1.0)
        C = E / jnp.sqrt(d)[:, None]  # D^{-1/2} E, sharded

        A_is = _inv_sqrt_psd(A)
        G = C @ A_is  # (n, m) sharded
        M = G.T @ G  # (m, m) — psum-reduced gemm
        w, u = jnp.linalg.eigh(M)  # ascending
        k = self.n_clusters
        top = u[:, -k:][:, ::-1]
        lam = jnp.maximum(w[-k:][::-1], 1e-12)
        V = G @ (top / jnp.sqrt(lam)[None, :])  # (n, k) sharded embedding
        # row-normalize the embedding (standard for normalized-cuts kmeans)
        norms = jnp.linalg.norm(V, axis=1, keepdims=True)
        V = V / jnp.where(norms > 1e-12, norms, 1.0)

        emb = ShardedRows(data=V, mask=X.mask, n_samples=n)
        km_params = {"n_clusters": self.n_clusters, "random_state": self.random_state}
        km_params.update(self.kmeans_params or {})
        km = KMeans(**km_params)
        km.fit(emb)
        self.assign_labels_ = km
        self.labels_ = km.labels_
        self.eigenvalues_ = lam
        self.n_features_in_ = X.data.shape[1]
        if self.persist_embedding:
            self.embedding_ = emb
        return self

    def fit_predict(self, X, y=None):
        return self.fit(X).labels_

"""Clustering — twin of ``dask_ml/cluster/`` (SURVEY.md §2 #6, #7)."""

from .k_means import KMeans  # noqa: F401
from .spectral import SpectralClustering  # noqa: F401

__all__ = ["KMeans", "SpectralClustering"]

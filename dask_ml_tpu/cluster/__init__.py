"""Clustering — twin of ``dask_ml/cluster/`` (SURVEY.md §2 #6, #7), plus
a device-native ``MiniBatchKMeans`` for the streaming/Incremental plane
(the reference streams sklearn's MiniBatchKMeans through ``_partial.py``)."""

from .k_means import KMeans  # noqa: F401
from .minibatch_kmeans import MiniBatchKMeans  # noqa: F401
from .spectral import SpectralClustering  # noqa: F401

__all__ = ["KMeans", "MiniBatchKMeans", "SpectralClustering"]

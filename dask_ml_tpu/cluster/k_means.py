"""Scalable KMeans: k-means‖ initialization + Lloyd iterations.

Reference: ``dask_ml/cluster/k_means.py :: KMeans`` — k-means‖ init
(Bahmani et al. 2012, ``init_scalable``) and blockwise Lloyd rounds with
tree-reduced center updates (``_kmeans_single_lloyd``); SURVEY.md §3.2.

TPU design: one jitted SPMD step per Lloyd round — the pairwise-distance
gemm rides the MXU, per-cluster sums are a one-hot matmul (another gemm),
and the k×d/k reductions are psums over ICI inserted by XLA.  The k-means‖
rounds reuse the same distance kernel with a per-shard PRNG for candidate
sampling; only the (tiny) candidate set ever reaches the host, where the
final weighted k-means++ runs exactly as the reference does it.
"""

from __future__ import annotations

import logging
from functools import partial as _fpartial

import numpy as np

import jax
import jax.numpy as jnp

from ..base import TPUEstimator, TransformerMixin
from ..core.prng import as_key
from ..core.sharded import ShardedRows, unshard
from ..preprocessing.data import _ingest_float as _ingest_float_any
from ..utils import _timer, safe_denominator
from .. import sanitize as _san

logger = logging.getLogger(__name__)

#: runtime-verified twin of the segment-boundary host-sync-loop
#: suppression in fit's checkpointed Lloyd loop (two findings on the one
#: convergence line: float(shift) and float(tol)) — see sanitize/sites.py
_SEG_SYNC = _san.AllowSite(
    "kmeans-segment-sync", rule="host-sync-loop",
    cites=("648c6eac595ea7e4", "dfd1ac1a1b0ae4ba"),
    note="one shift/tol scalar pair per fused 32-iteration Lloyd "
         "segment, not per iteration",
)


def _ingest_float(est, X):
    """KMeans ingests half-precision input as float32: the Lloyd/init
    kernels accumulate distances and counts, and float16 accumulators both
    overflow early and break the fused loop's mixed-dtype carry (sklearn
    likewise computes k-means in wider precision than half)."""
    X = _ingest_float_any(est, X)
    if X.data.dtype in (jnp.float16, jnp.bfloat16):
        X = ShardedRows(data=X.data.astype(jnp.float32), mask=X.mask,
                        n_samples=X.n_samples)
    return X


# the one squared-distance kernel, shared with metrics.pairwise
from ..metrics.pairwise import _sq_euclidean  # noqa: E402
from ..metrics.pairwise import _sq_euclidean_hi as _sq_dists  # noqa: E402


def _kmeans_mode() -> str:
    """Precision mode for the Lloyd round, ``DASK_ML_TPU_KMEANS_PRECISION``:

    - ``highest`` (default): HIGHEST-precision gemms — assignment and
      sums bit-comparable to the fp32 reference.
    - ``fast``: cross term at ``Precision.HIGH`` (3 bf16 passes, error
      ~2⁻²² vs fp32's 2⁻²⁴) and the per-cluster reduce as a 3-pass
      bf16-split gemm (both operands split: the one-hot side carries the
      sample-weight mask).  6 MXU passes per round instead of 12; on
      MXU-bound shapes (k ≥ ~32) this can halve round time at
      k-means-irrelevant precision cost.  Chip-adjudicated: 1.36–1.44×
      faster at 1M×64 k=64 in 3 of 4 sessions (docs/design.md, round-5
      chip table); the default stays ``highest`` as a deliberate
      precision-contract exception.
    """
    import os

    v = os.environ.get("DASK_ML_TPU_KMEANS_PRECISION", "highest").lower()
    if v not in ("highest", "fast"):
        raise ValueError(
            f"DASK_ML_TPU_KMEANS_PRECISION must be 'highest' or 'fast', "
            f"got {v!r}"
        )
    return v


def _lloyd_step_fn(x, mask, centers, *, mode="highest", scatter="segsum"):
    """One Lloyd round: assign, reduce per-cluster sums/counts, update.

    Returns (new_centers, inertia, shift).  Everything is gemm-shaped; with
    sharded x the per-cluster reductions become ICI psums.  ``mode`` is
    static (see ``_kmeans_mode``).
    """
    if mode == "fast":
        d2 = _sq_euclidean(x, centers, precision=jax.lax.Precision.HIGH)
    else:
        d2 = _sq_dists(x, centers)
    labels = jnp.argmin(d2, axis=1)
    # jnp.min selects the SAME element as d2[argmin] but lowers to a fused
    # reduce; a take_along_axis gather here costs ~14 ms/round on a v5e
    # (11x the whole rest of the step) because XLA:TPU lowers dynamic
    # row-gathers serially
    min_d2 = jnp.min(d2, axis=1)
    inertia = jnp.sum(min_d2 * mask)
    # per-cluster reduce through the shared scatter policy (ops.scatter):
    # one-hot gemm on the MXU or segment_sum, whichever the platform
    # measurement favors.  Precision on the gemm path: HIGH in fast mode
    # (3-pass bf16 split — Mosaic's kernel writes the same split by
    # hand), HIGHEST otherwise (centers feed the next round's argmin).
    # The weight mask pre-multiplies x so both strategies accumulate the
    # same weighted rows; counts use HIGHEST so fractional sample
    # weights are never bf16-quantized in the denominator.
    from ..ops.scatter import bucket_sum

    k_ = centers.shape[0]
    prec = (jax.lax.Precision.HIGH if mode == "fast"
            else jax.lax.Precision.HIGHEST)
    sums = bucket_sum(x * mask[:, None], labels, k_, precision=prec,
                      strategy=scatter)
    counts = bucket_sum(mask, labels, k_,
                        precision=jax.lax.Precision.HIGHEST,
                        strategy=scatter)  # (k,)
    safe = safe_denominator(counts)[:, None]
    new_centers = jnp.where(counts[:, None] > 0, sums / safe, centers)
    shift = jnp.sum((new_centers - centers) ** 2)
    return new_centers, inertia, shift


# The Lloyd hot programs route through the central program cache
# (design.md §12): compile books + compile-ahead for the step, and —
# now that the cache captures XLA cost_analysis per signature — the
# roofline attribution that turned "Lloyd at 2% of bandwidth" from a
# bench hand-estimate into device_report()'s measured per-program
# fraction.  ``centers`` is donated in both: the (k, d) output centers
# alias the dead input buffer in HBM.  ``x``/``mask`` are deliberately
# NOT donated — fit reuses them across segments (and _assign reads x
# after the loop), so that donation would delete live buffers.
from .. import programs as _programs  # noqa: E402

_lloyd_step = _programs.cached_program(
    _lloyd_step_fn, name="kmeans.lloyd_step",
    static_argnames=("mode", "scatter"), donate_argnames=("centers",),
)


# A fused Pallas Lloyd kernel (ops/lloyd.py) lived here through rounds
# 2-5 and was DELETED after its win-or-delete chip adjudication: on a
# TPU v5e the XLA lowering of ``_lloyd_step`` beat every kernel variant
# — 0.089-0.176x at 2Mx50 k=8 and 0.198x (fast) at 1Mx64 k=64, where
# lane padding vanishes and the kernel was predicted to win.  XLA's
# fusion already keeps the round at ~2 HBM passes, so the kernel had no
# traffic to remove and its Mosaic gemms lost to XLA's MXU scheduling.
# Full numbers: docs/design.md "Pallas negative result"; resurrection is
# one git revert away.


def _lloyd_loop_fn(x, mask, centers, tol, max_iter, *,
                   mode="highest", scatter="segsum"):
    """The ENTIRE Lloyd iteration as one XLA program.

    The reference re-enters the scheduler every round (SURVEY.md §3.2); a
    per-round jitted step would likewise pay one dispatch + one host sync
    (the ``shift <= tol`` check) per round.  Fusing the loop into
    ``lax.while_loop`` keeps convergence control on device: one dispatch
    per fit, no host round-trips.  ``tol``/``max_iter`` are device scalars
    so different settings don't recompile.

    Returns ``(centers, inertia, n_iter, shift)`` — the final center
    shift rides along so a SEGMENTED run (``FitCheckpoint`` chunking)
    can detect convergence that lands exactly on a segment boundary.
    """

    def step(x_, m_, c_):
        # tracer operands: the cached step bypasses to its jitted twin,
        # which inlines here (its donation is ignored under the outer
        # trace — the loop program's own centers donation is the one
        # that aliases)
        return _lloyd_step(x_, m_, c_, mode=mode, scatter=scatter)

    def cond(state):
        i, _, _, shift = state
        return (i < max_iter) & (shift > tol)

    def body(state):
        i, centers, _, _ = state
        new_centers, inertia, shift = step(x, mask, centers)
        return i + 1, new_centers, inertia, shift

    init = (
        jnp.int32(0),
        centers,
        jnp.asarray(jnp.inf, x.dtype),
        jnp.asarray(jnp.inf, x.dtype),
    )
    i, centers, inertia, shift = jax.lax.while_loop(cond, body, init)
    return centers, inertia, i, shift


# Roofline honesty note (design.md §16): cost_analysis counts this
# fused while program's body ONCE — the trip count is data-dependent —
# so the loop's attributed flops/bytes (hence roofline_frac) are a
# floor over the whole dispatch, not a per-round measurement.  The
# per-round number lives in bench.py's lloyd section, which pins the
# round count.
_lloyd_loop = _programs.cached_program(
    _lloyd_loop_fn, name="kmeans.lloyd_loop",
    static_argnames=("mode", "scatter"), donate_argnames=("centers",),
)


def _assign_fn(x, mask, centers):
    d2 = _sq_dists(x, centers)
    labels = jnp.argmin(d2, axis=1)
    min_d2 = jnp.min(d2, axis=1)  # same element as d2[argmin], fused lowering
    return labels, jnp.sum(min_d2 * mask)


# no donation: the outputs ((n,) int labels + a scalar) are smaller
# than every input and x/centers stay live in the caller — the
# gemm-output-smaller class design.md §8 records
# graftlint: disable=donation-miss -- outputs (labels + scalar) smaller than every input; x/centers stay live in fit/predict
_assign = _programs.cached_program(_assign_fn, name="kmeans.assign")


def _valid_d2(x, centers, cvalid):
    """Distances with INVALID candidate slots pushed out of every min/argmin.
    The sentinel is +inf selected via ``where`` — never ADDED or multiplied
    (an additive 1e30 overflows to inf in float16 and 0*inf = NaN would
    poison every distance; a finite dtype-max sentinel can be beaten by
    legitimate large distances).  Slot 0 is always valid, so min/argmin
    always land on a real candidate."""
    d2 = _sq_dists(x, centers)
    return jnp.where(cvalid[None, :] > 0, d2, jnp.asarray(jnp.inf, x.dtype))


@jax.jit
def _phi_and_mind2(x, mask, centers, cvalid):
    """φ and per-row min distance against only the VALID candidate rows
    (fixed-capacity compaction pads the candidate set)."""
    min_d2 = jnp.min(_valid_d2(x, centers, cvalid), axis=1) * mask
    return jnp.sum(min_d2), min_d2


@_fpartial(jax.jit, static_argnames=("cap",))
def _sample_candidates(x, mask, u, p, *, cap):
    """Fixed-size device-side compaction of the Bernoulli draw: the rows
    with u < p rank first under ``score = selected·(1+u)``; top_k pulls at
    most ``cap`` of them into a static-shape block with a validity mask.
    Nothing of O(n) leaves the device (VERDICT round-1 weak #8: the old
    path shipped a length-n boolean vector to host every round)."""
    sel = ((u < p) & (mask > 0)).astype(x.dtype)
    score = sel * (1.0 + u)
    vals, idx = jax.lax.top_k(score, cap)
    valid = (vals > 0.0).astype(x.dtype)
    rows = jnp.take(x, idx, axis=0)
    return rows, valid


def init_scalable(X: ShardedRows, n_clusters: int, key, oversampling_factor=2,
                  init_max_iter=None):
    """k-means‖ (Bahmani et al. 2012) — reference ``k_means.py :: init_scalable``.

    Device side: distance/φ reductions, per-row Bernoulli sampling AND the
    candidate compaction (fixed-capacity top-k per round, so shapes stay
    static and only O(1) scalars sync per round).  Host side: only the
    final O(k·log n) candidate set and the weighted k-means++ on it
    (exactly the reference's division of labor, minus the scheduler
    round-trips).  The per-round capacity is 4·ℓ — the Bernoulli round
    draws ℓ candidates in expectation, so overflow (dropped candidates) is
    vanishingly rare and harmless to the sampling guarantee.
    """
    x, mask = X.data, X.mask
    n = X.n_samples
    ell = oversampling_factor * n_clusters
    cap = int(min(max(4 * ell, 8), x.shape[0]))

    # 1. one uniformly-random real point
    key, sub = jax.random.split(key)
    idx = jax.random.choice(sub, x.shape[0], p=mask / jnp.sum(mask))
    centers = x[idx][None, :]
    cvalid = jnp.ones((1,), dtype=x.dtype)

    phi, _ = _phi_and_mind2(x, mask, centers, cvalid)
    n_rounds = int(np.ceil(np.log(max(float(phi), 2.0))))
    if init_max_iter is not None:
        n_rounds = min(n_rounds, int(init_max_iter))
    n_rounds = max(n_rounds, 1)

    for r in range(n_rounds):
        phi, min_d2 = _phi_and_mind2(x, mask, centers, cvalid)
        if float(phi) == 0.0:  # O(1) scalar sync — loop control only
            break
        key, sub = jax.random.split(key)
        u = jax.random.uniform(sub, (x.shape[0],), dtype=x.dtype)
        p = jnp.minimum(ell * min_d2 / phi, 1.0)
        rows, valid = _sample_candidates(x, mask, u, p, cap=cap)
        centers = jnp.concatenate([centers, rows], axis=0)
        cvalid = jnp.concatenate([cvalid, valid])
        logger.debug("k-means|| round %d: %d candidate slots", r, centers.shape[0])

    # weight candidates by how many points they are closest to (invalid
    # slots excluded by the same distance sentinel)
    closest = jnp.argmin(_valid_d2(x, centers, cvalid), axis=1)
    weights_dev = jnp.sum(
        jax.nn.one_hot(closest, centers.shape[0], dtype=x.dtype) * mask[:, None], axis=0
    )
    # ONE host pull of the O(k·log n) candidate set at the very end
    keep = np.asarray(cvalid) > 0.0
    cand = np.asarray(centers, dtype=np.float64)[keep]
    weights = np.asarray(weights_dev)[keep]

    if cand.shape[0] <= n_clusters:
        # degenerate: fewer candidates than clusters — pad with random real
        # rows gathered device-side
        key, sub = jax.random.split(key)
        n_extra = n_clusters - cand.shape[0] + 1
        extra_idx = jax.random.choice(sub, n, (n_extra,), replace=n_extra > n)
        extra = np.asarray(jnp.take(x, extra_idx, axis=0), dtype=np.float64)
        cand = np.vstack([cand, extra])
        weights = np.concatenate([weights, np.ones(n_extra)])

    # final: weighted k-means++ + a few Lloyd steps on the candidate set
    # (host-local, candidate set is ~k·oversampling·rounds points)
    from sklearn.cluster import KMeans as SKKMeans

    local = SKKMeans(n_clusters=n_clusters, init="k-means++", n_init=1,
                     max_iter=10, random_state=0)
    local.fit(cand, sample_weight=np.maximum(weights[: cand.shape[0]], 1e-12))
    return jnp.asarray(local.cluster_centers_, dtype=x.dtype)


class KMeans(TransformerMixin, TPUEstimator):
    """Parameters mirror the reference (``n_clusters``, ``init='k-means||'``,
    ``oversampling_factor``, ``max_iter``, ``tol``, ``init_max_iter``,
    ``random_state``, ``n_jobs`` accepted-inert).

    ``fit_checkpoint`` (a :class:`~dask_ml_tpu.resilience.FitCheckpoint`)
    makes the fit preemption-safe: the fused Lloyd ``while_loop`` runs as
    SEGMENTS of ``every_n_iters`` iterations (same compiled step program,
    one extra dispatch + scalar sync per boundary), snapshotting the
    centers atomically at each boundary so a killed fit resumes from the
    last snapshot with the identical trajectory.  Preemption (SIGTERM via
    :class:`~dask_ml_tpu.resilience.PreemptionWatcher`) is honored at the
    same boundaries.
    """

    def __init__(self, n_clusters=8, init="k-means||", oversampling_factor=2,
                 max_iter=300, tol=1e-4, precompute_distances="auto",
                 random_state=None, copy_x=True, n_jobs=1, algorithm="full",
                 init_max_iter=None, fit_checkpoint=None):
        self.n_clusters = n_clusters
        self.init = init
        self.oversampling_factor = oversampling_factor
        self.max_iter = max_iter
        self.tol = tol
        self.precompute_distances = precompute_distances
        self.random_state = random_state
        self.copy_x = copy_x
        self.n_jobs = n_jobs
        self.algorithm = algorithm
        self.init_max_iter = init_max_iter
        self.fit_checkpoint = fit_checkpoint

    def _init_centers(self, X: ShardedRows, key):
        init = self.init
        if isinstance(init, (np.ndarray, jnp.ndarray)):
            # a COPY, never a view of the user's array: the Lloyd loop
            # donates its centers operand, and jnp.asarray of an
            # already-right-dtype device array would alias the user's
            # buffer into the donation
            centers = jnp.array(init, dtype=X.data.dtype)
            if centers.shape != (self.n_clusters, X.data.shape[1]):
                raise ValueError(
                    f"init array must be ({self.n_clusters}, {X.data.shape[1]}), "
                    f"got {centers.shape}"
                )
            return centers
        if init == "k-means||":
            with _timer("k-means|| initialization", logger, logging.DEBUG):
                return init_scalable(
                    X, self.n_clusters, key, self.oversampling_factor,
                    self.init_max_iter,
                )
        if init == "random":
            p = X.mask / jnp.sum(X.mask)
            idx = jax.random.choice(
                key, X.data.shape[0], (self.n_clusters,), replace=False, p=p
            )
            return X.data[idx]
        if init == "k-means++":
            # host-side k-means++ on a small device-gathered sample, like the
            # reference's fallback path
            from sklearn.cluster import kmeans_plusplus

            from ..utils import draw_seed

            n_sample = min(X.n_samples, max(1000, 50 * self.n_clusters))
            key, sub = jax.random.split(key)
            # VALIDITY-uniform subsample + the true weights inside
            # sklearn's k-means++.  Subsampling proportionally to the
            # weights would weight twice (seed probability ~ w^2 d^2 vs
            # sklearn's w d^2); a 0/1 validity draw keeps zero-weight
            # rows out while kmeans_plusplus applies w exactly once.
            p = (X.mask[: X.n_samples] > 0).astype(jnp.float32)
            p = p / jnp.sum(p)
            # replace=False always: n_sample = min(n_samples, ...), so a
            # no-replacement draw is always valid; zero-probability rows
            # that must fill the draw are neutralized by w_sample=0 in
            # kmeans_plusplus
            idx = jax.random.choice(
                sub, X.n_samples, (n_sample,), replace=False, p=p,
            )
            sample = np.asarray(jnp.take(X.data, idx, axis=0), dtype=np.float64)
            w_sample = np.asarray(
                jnp.take(X.mask[: X.n_samples], idx), dtype=np.float64
            )
            seed = int(draw_seed(int(jax.random.randint(key, (), 0, 2**31 - 1))))
            centers, _ = kmeans_plusplus(
                sample, self.n_clusters, sample_weight=w_sample,
                random_state=seed,
            )
            return jnp.asarray(centers, dtype=X.data.dtype)
        raise ValueError(f"Unknown init: {init!r}")

    def fit(self, X, y=None, sample_weight=None):
        if self.n_clusters <= 0:
            raise ValueError("n_clusters must be positive")
        X = _ingest_float(self, X)
        if X.n_samples < self.n_clusters:
            raise ValueError(
                f"n_samples={X.n_samples} < n_clusters={self.n_clusters}"
            )
        valid_mask = X.mask  # pre-weighting validity, for the tol scale
        if sample_weight is not None:
            # the mask is the per-row weight everywhere downstream: the
            # k-means|| sampling probabilities, the Lloyd center sums and
            # counts, and the inertia all become their weighted (sklearn)
            # forms by scaling it
            from ..utils import reweight_rows

            X = reweight_rows(X, sample_weight=sample_weight)
        key = as_key(self.random_state)
        ckpt = self.fit_checkpoint
        it0 = 0
        snap = ckpt.load_if_matches(self) if ckpt is not None else None
        if snap is not None:
            # resume mid-fit: the snapshot's centers REPLACE the (seed-
            # deterministic) init, and the Lloyd budget continues from the
            # recorded iteration count
            it0, state = snap
            # copy: the loop donates centers; the snapshot's array must
            # stay valid for a retried resume
            centers = jnp.array(state["centers"], dtype=X.data.dtype)
        else:
            centers = self._init_centers(X, key)

        x, mask = X.data, X.mask
        # sklearn-style tol scaling: mean of per-feature variances, masked so
        # pad rows don't inflate the threshold
        from ..core.sharded import masked_var

        # tol from UNWEIGHTED variances: sklearn's _tolerance ignores
        # sample_weight, so weighting must not move the stopping threshold
        tol = self.tol * jnp.mean(masked_var(x, valid_mask))  # on device
        from ..resilience.preemption import active_watcher, check_preemption

        with _timer("Lloyd loop", logger, logging.DEBUG), \
                _san.region("kmeans.fit.lloyd"):
            from ..ops.scatter import scatter_strategy

            # policy knobs resolve OUTSIDE the jit so they participate in
            # the jit cache key (static args); resolving inside would bake
            # the first call's env values in for the process lifetime
            mode = _kmeans_mode()
            scatter = scatter_strategy(self.n_clusters)
            if ckpt is None and active_watcher() is None:
                # the uninstrumented fast path: ONE fused dispatch
                centers, _, n_iter_dev, _ = _lloyd_loop(
                    x, mask, centers, tol.astype(x.dtype),
                    jnp.int32(self.max_iter), mode=mode, scatter=scatter,
                )
                n_iter = int(n_iter_dev)
            else:
                # segmented: the SAME compiled step program in chunks of
                # the checkpoint cadence, one host boundary per chunk
                # (snapshot + preemption check + fault-injection point)
                from ..resilience.testing import maybe_fault

                chunk = (ckpt.chunk_iters(32) if ckpt is not None
                         else min(32, int(self.max_iter)))
                n_iter = it0
                while n_iter < self.max_iter:
                    maybe_fault("step")
                    seg = min(chunk, self.max_iter - n_iter)
                    centers, _, seg_n_dev, shift = _lloyd_loop(
                        x, mask, centers, tol.astype(x.dtype),
                        jnp.int32(seg), mode=mode, scatter=scatter,
                    )
                    seg_n = int(seg_n_dev)
                    n_iter += seg_n
                    if ckpt is not None and ckpt.due(n_iter):
                        ckpt.save(self, {"centers": centers}, n_iter)
                    check_preemption(ckpt, self, {"centers": centers}, n_iter)
                    # converged: the segment stopped early, or the final
                    # shift cleared tol exactly at the boundary (the fused
                    # loop's cond — boundaries must not add iterations)
                    with _SEG_SYNC.allow():
                        # graftlint: disable=host-sync-loop -- segment-boundary sync: one scalar fetch per fused 32-iteration segment, not per Lloyd iteration
                        if seg_n < seg or float(shift) <= float(tol):
                            break
                if ckpt is not None:
                    ckpt.complete()
        labels, inertia = _assign(x, mask, centers)

        self.cluster_centers_ = centers
        self.labels_ = labels[: X.n_samples]
        self.inertia_ = float(inertia)
        self.n_iter_ = n_iter
        self.n_features_in_ = x.shape[1]
        return self

    def predict(self, X):
        X = _ingest_float(self, X)
        labels, _ = _assign(X.data, X.mask, self.cluster_centers_)
        return labels[: X.n_samples]

    def fit_predict(self, X, y=None, sample_weight=None):
        return self.fit(X, sample_weight=sample_weight).labels_

    def transform(self, X):
        """Distances to each center (reference semantic)."""
        X = _ingest_float(self, X)
        d = jnp.sqrt(_sq_dists(X.data, self.cluster_centers_))
        return d[: X.n_samples]

    def score(self, X, y=None, sample_weight=None):
        X = _ingest_float(self, X)
        if sample_weight is not None:
            from ..utils import reweight_rows

            X = reweight_rows(X, sample_weight=sample_weight)
        _, inertia = _assign(X.data, X.mask, self.cluster_centers_)
        return -float(inertia)

    def get_feature_names_out(self, input_features=None):
        """sklearn contract for cluster-transformers: ``transform``
        outputs one distance column per center, named
        ``<classname_lower><i>``."""
        import numpy as np

        k = self.cluster_centers_.shape[0]
        prefix = type(self).__name__.lower()
        return np.asarray([f"{prefix}{i}" for i in range(k)], dtype=object)

"""Device-native MiniBatchKMeans (Sculley 2010) with the partial_fit contract.

Reference capability: the reference's flagship streaming pattern is
``Incremental(sklearn.cluster.MiniBatchKMeans)`` — sklearn's minibatch
k-means driven block-by-block through the sequential partial_fit chain
(``dask_ml/_partial.py :: fit``, SURVEY.md §3.5).  There the model hops
between workers and every update runs sklearn's Cython on a host CPU.
Here the model state (centers + per-center counts) is device-resident and
``partial_fit`` IS one fused XLA program — assignment gemm on the MXU,
per-center sums via the one-hot gemm, and Sculley's per-center
learning-rate update — so ``Incremental``/``wrappers`` stream blocks into
the TPU exactly the way the SGD family does (linear_model/_sgd.py).

``fit`` runs epochs of contiguous mini-batches over the (possibly
sharded) array as ONE ``lax.scan`` program per epoch: batches are
``dynamic_slice`` windows (row GATHERS are ~10x slower on XLA:TPU — see
cluster/k_means.py), randomness enters through a per-epoch offset, and
only the epoch-mean inertia is fetched for the stopping rule (one scalar
sync per epoch).
"""

from __future__ import annotations

import logging

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..base import TPUEstimator, TransformerMixin
from ..core.prng import as_key
from ..core.sharded import ShardedRows
from ..utils import check_max_iter
from .k_means import _assign, _ingest_float, _sq_dists
from .. import sanitize as _san

logger = logging.getLogger(__name__)

#: runtime-verified twin of the epoch-boundary host-sync-loop
#: suppression below (fit's convergence check): under an active
#: sanitizer the steady-phase transfer guard is lifted for exactly this
#: one scalar fetch per epoch, and the pass is counted + ratcheted in
#: tools/sanitize_baseline.json
_EPOCH_SYNC = _san.AllowSite(
    "mbk-epoch-sync", rule="host-sync-loop",
    cites="9a3175d3693a54a3",
    note="one mean-inertia scalar per epoch: sklearn's "
         "max_no_improvement contract needs the host value",
)


def _mbk_step_fn(centers, counts, xb, mask):
    """One Sculley update on one batch: returns (centers, counts, inertia).

    Per-center learning rate 1/n_c (cumulative weight mass), applied as
    ``c += (batch_sum - batch_mass * c) / n_c_new`` — the closed form of
    sklearn's per-sample ``c += w (x - c)/n_c`` stream over the batch.

    ``mask`` doubles as the per-row weight (``reweight_rows`` folds
    ``sample_weight`` in), so ``counts`` accumulates WEIGHT MASS, not row
    counts.  It is a ``(2, k)`` float32 Kahan pair (hi, lo): a plain f32
    accumulator silently stops incrementing once a center's mass passes
    2^24 (freezing the 1/n_c decay on long partial_fit streams — the same
    saturation this file used int32 counts against when it was
    unweighted), while compensated summation stays accurate to ~2^48 and
    admits fractional weights.
    """
    d2 = _sq_dists(xb, centers)
    labels = jnp.argmin(d2, axis=1)
    min_d2 = jnp.min(d2, axis=1)
    inertia = jnp.sum(min_d2 * mask)
    # weights applied to the one-hot in f32: with bf16 data an
    # xb.dtype one-hot would round the weighted rows to bf16 (256-step
    # resolution) before the mass sum
    oh32 = (
        jax.nn.one_hot(labels, centers.shape[0], dtype=jnp.float32)
        * mask.astype(jnp.float32)[:, None]
    )
    bmass = jnp.sum(oh32, axis=0)  # f32 batch weight mass per center
    bsum = jnp.dot(
        oh32.astype(xb.dtype).T, xb, precision=lax.Precision.HIGHEST
    )
    # Kahan add: counts = (hi, lo) += bmass
    hi, lo = counts[0], counts[1]
    y = bmass + lo
    t = hi + y
    lo = y - (t - hi)
    hi = t
    mass = hi + lo
    # clamp at the smallest NORMAL f32, not an arbitrary epsilon: any
    # larger floor silently shrinks the weighted mean for tiny (but
    # legitimate) weight scales, while 1/subnormal would overflow to inf
    inv32 = jnp.where(
        mass > 0, 1.0 / jnp.maximum(mass, jnp.finfo(jnp.float32).tiny), 0.0
    )
    inv = inv32.astype(xb.dtype)
    bmass_d = bmass.astype(xb.dtype)
    new_centers = centers + (bsum - bmass_d[:, None] * centers) * inv[:, None]
    return new_centers, jnp.stack([hi, lo]), inertia


# Streamed-step entry through the central program cache (design.md §12):
# ragged stream blocks bucket to warm executables and `_pf_stage` can
# compile the next bucket ahead.  Inside `_mbk_epoch`'s scan body the
# tracer operands route through the cache's jitted twin (inlined), so
# the fused epoch program is unchanged.
from .. import programs as _programs  # noqa: E402

# centers and the Kahan mass pair are donated: partial_fit's state
# chain is strictly linear (the attrs are overwritten by the outputs on
# every call), so the (k,d)/(2,k) updates alias in place in HBM instead
# of doubling the resident state per step.  xb/mask are NOT donated —
# the gemm outputs are smaller and fit's epoch windows re-slice x.
# Inside `_mbk_epoch`'s scan body the tracer operands bypass to the
# jitted twin (inlined; its donation is ignored under the outer trace).
_mbk_step = _programs.cached_program(
    _mbk_step_fn, name="minibatch_kmeans.step",
    donate_argnames=("centers", "counts"),
)


def _mbk_epoch_fn(centers, counts, x, mask, start, *, batch_size,
                  n_batches):
    """One epoch = lax.scan over contiguous batch windows (one dispatch).

    ``start`` (traced) rotates the window origin per epoch so successive
    epochs see different batch boundaries without any gather/shuffle.
    """
    n = x.shape[0]

    def body(carry, i):
        c, cnt = carry
        # valid window starts are 0..n-batch_size INCLUSIVE (hence +1):
        # mod (n - bs) would leave the last row out of every batch
        off = jnp.mod(start + i * batch_size, jnp.maximum(n - batch_size + 1, 1))
        xb = lax.dynamic_slice_in_dim(x, off, batch_size)
        mb = lax.dynamic_slice_in_dim(mask, off, batch_size)
        c, cnt, inertia = _mbk_step(c, cnt, xb, mb)
        return (c, cnt), inertia

    (centers, counts), inertias = lax.scan(
        body, (centers, counts), jnp.arange(n_batches)
    )
    return centers, counts, jnp.mean(inertias)


# the whole-array fit's hot loop, through the cache like the streamed
# step — with the same linear state chain, so centers/counts donate
# (fit reassigns both from the outputs every epoch); x/mask persist
# across epochs and must not
_mbk_epoch = _programs.cached_program(
    _mbk_epoch_fn, name="minibatch_kmeans.epoch",
    static_argnames=("batch_size", "n_batches"),
    donate_argnames=("centers", "counts"),
)


@jax.jit
def _reassign_starved(centers, counts, x, mask, key, ratio):
    """Re-seed centers whose cumulative mass fell below
    ``ratio * max(mass)`` with weight-biased random rows, resetting their
    mass so the next batch fully replaces them (sklearn's
    ``reassignment_ratio`` semantics, applied at epoch granularity).

    The weighted sample-without-replacement is O(n log n); it runs under a
    ``lax.cond`` so the steady state (no starving centers — the common
    case once clustering stabilizes) pays only the cheap mass check.
    """
    hi, lo = counts[0], counts[1]
    mass = hi + lo
    starving = mass < ratio * jnp.max(mass)

    def reseed(_):
        p = mask / jnp.maximum(jnp.sum(mask), 1e-12)
        idx = jax.random.choice(
            key, x.shape[0], (centers.shape[0],), replace=False, p=p
        )
        seeds = jnp.take(x, idx, axis=0)
        new_centers = jnp.where(starving[:, None], seeds, centers)
        zero = jnp.zeros_like(hi)
        new_counts = jnp.stack([
            jnp.where(starving, zero, hi), jnp.where(starving, zero, lo)
        ])
        return new_centers, new_counts

    return jax.lax.cond(
        jnp.any(starving), reseed, lambda _: (centers, counts), None
    )


class MiniBatchKMeans(TransformerMixin, TPUEstimator):
    """Sklearn-contract minibatch k-means, state resident on device.

    Parameters mirror sklearn's.  ``reassignment_ratio`` re-seeds starving
    centers (mass below ``ratio * max(mass)``) from weight-biased random
    rows at EPOCH granularity in ``fit`` — sklearn checks per minibatch;
    epoch granularity keeps the scanned epoch a single fused program and
    is enough to rescue empty clusters.  ``partial_fit`` streams never
    reassign (each call sees one block; the caller owns the schedule).
    ``partial_fit`` consumes one block per call — the unit of budget for
    ``Incremental`` and the adaptive searches.
    """

    _checkpoint_private_attrs = ("_counts",)

    def __init__(self, n_clusters=8, init="k-means++", max_iter=100,
                 batch_size=1024, tol=0.0, max_no_improvement=10,
                 random_state=None, reassignment_ratio=0.01,
                 oversampling_factor=2, fit_checkpoint=None):
        self.n_clusters = n_clusters
        self.init = init
        self.max_iter = max_iter
        self.batch_size = batch_size
        self.tol = tol
        self.max_no_improvement = max_no_improvement
        self.random_state = random_state
        self.reassignment_ratio = reassignment_ratio
        self.oversampling_factor = oversampling_factor
        self.fit_checkpoint = fit_checkpoint

    # -- init --------------------------------------------------------------
    def _init_from_block(self, X: ShardedRows, key):
        """First-seen-block initialization (sklearn seeds from the first
        minibatch).  k-means++ runs on a small host-pulled sample — an
        O(k) fetch, never O(n)."""
        if isinstance(self.init, (np.ndarray, jnp.ndarray)):
            # a COPY, never a view: the step/epoch programs donate
            # centers — asarray of a right-dtype device array would
            # alias the user's init buffer into the donation
            c = jnp.array(self.init, dtype=X.data.dtype)
            if c.shape != (self.n_clusters, X.data.shape[1]):
                raise ValueError(
                    f"init array must be ({self.n_clusters}, "
                    f"{X.data.shape[1]}), got {c.shape}"
                )
            return c
        if self.init == "random":
            p = X.mask / jnp.sum(X.mask)
            # _ensure_state already rejected n_samples < n_clusters
            idx = jax.random.choice(
                key, X.data.shape[0], (self.n_clusters,), replace=False, p=p,
            )
            return jnp.take(X.data, idx, axis=0)
        if self.init in ("k-means++", "k-means||"):
            from sklearn.cluster import kmeans_plusplus

            from ..utils import draw_seed

            n_sample = int(min(X.n_samples, max(1000, 50 * self.n_clusters)))
            key, sub = jax.random.split(key)
            p = X.mask / jnp.sum(X.mask)
            # n_sample = min(n_samples, ...) so sampling w/o replacement
            # is always valid
            idx = jax.random.choice(
                sub, X.data.shape[0], (n_sample,), replace=False, p=p,
            )
            sample = np.asarray(jnp.take(X.data, idx, axis=0), np.float64)
            seed = int(draw_seed(int(jax.random.randint(key, (), 0, 2**31 - 1))))
            c, _ = kmeans_plusplus(sample, self.n_clusters, random_state=seed)
            return jnp.asarray(c, dtype=X.data.dtype)
        raise ValueError(f"Unknown init: {self.init!r}")

    def _ensure_state(self, X: ShardedRows):
        if hasattr(self, "_counts") and self._counts.ndim == 1:
            # legacy checkpoint layout ((k,) int32 row counts, from before
            # weight-mass accumulation): migrate to the Kahan pair — the
            # step would otherwise silently misread counts[0]/counts[1]
            # as the global (hi, lo) scalars
            self._counts = jnp.stack([
                self._counts.astype(jnp.float32),
                jnp.zeros_like(self._counts, jnp.float32),
            ])
        if not hasattr(self, "cluster_centers_"):
            if X.n_samples < self.n_clusters:
                raise ValueError(
                    f"n_samples={X.n_samples} < n_clusters={self.n_clusters}"
                )
            key = as_key(self.random_state)
            self.cluster_centers_ = self._init_from_block(X, key)
            # (hi, lo) Kahan pair of cumulative weight mass per center
            self._counts = jnp.zeros((2, self.n_clusters), jnp.float32)
            self.n_features_in_ = X.data.shape[1]
            self.n_steps_ = 0

    # -- staged streaming protocol (pipeline.stream_partial_fit) -----------
    def _pf_stage(self, X, y=None, sample_weight=None, **kwargs):
        """Host bucket-pad + device upload of one stream block (the
        ``partial_fit`` host branch, run ahead on the prefetch worker).
        Declines device-resident input (staging it would dispatch
        programs off-thread) and per-block weighting (``reweight_rows``
        is a device program); ``y`` is accepted and ignored, matching
        ``partial_fit``."""
        if (kwargs or sample_weight is not None
                or isinstance(X, (ShardedRows, jnp.ndarray))):
            return None
        from ..linear_model._sgd import _bucket_pad

        Xh = np.asarray(X, dtype=np.float32)
        n = Xh.shape[0]
        Xh, _, mask = _bucket_pad(Xh)
        self._warm_step(Xh.shape)
        return ShardedRows(
            data=jnp.asarray(Xh), mask=jnp.asarray(mask), n_samples=n
        )

    def _warm_step(self, xshape) -> bool:
        """Compile-ahead hook (programs.ahead): pre-build the Sculley
        step for a bucketed block of ``xshape`` on the blessed compile
        thread.  Host-only (shape structs + a queue put) — safe from
        the prefetch worker."""
        if not _programs.compile_ahead_enabled():
            return False
        b, d = int(xshape[0]), int(xshape[1])
        k = int(self.n_clusters)
        # per-block memo, same rationale as _BaseSGD._warm_step
        key = (b, d, k)
        if getattr(self, "_warm_memo", None) == key:
            return False
        self._warm_memo = key
        f32 = jnp.float32
        sds = jax.ShapeDtypeStruct
        return _mbk_step.warm(
            (sds((k, d), f32), sds((2, k), f32), sds((b, d), f32),
             sds((b,), f32)))

    def _pf_warm(self, shape, classes=None) -> bool:
        """Shape-based warm twin (the adaptive search calls this before
        a unit's partial_fit burst)."""
        if len(shape) != 2:
            return False
        from ..programs import bucket_rows

        return self._warm_step((bucket_rows(int(shape[0])), int(shape[1])))

    def _pf_consume(self, staged):
        """One fused Sculley update on a pre-staged block (consumer
        thread: the only thread dispatching device programs)."""
        from ..resilience.testing import maybe_fault

        maybe_fault("step")
        X = _ingest_float(self, staged)
        self._ensure_state(X)
        # graftsan: steady-state streamed step — all-device operands,
        # zero implicit host crossings (transfer guard verified)
        with _san.region("minibatch_kmeans.partial_fit"), _san.step_guard():
            self.cluster_centers_, self._counts, inertia = _mbk_step(
                self.cluster_centers_, self._counts, X.data, X.mask
            )
        self.n_steps_ += 1
        self._inertia_last = inertia  # device scalar; fetch only on demand
        return self

    # -- streaming contract ------------------------------------------------
    def partial_fit(self, X, y=None, sample_weight=None, **kwargs):
        """One fused device update on this block (the budget unit).

        Host blocks are padded to the SGD family's bucket sizes
        (``linear_model._sgd._BUCKETS``) before ingest, so a stream of
        ragged chunk sizes compiles a handful of programs, not one per
        distinct length.  ``sample_weight`` folds into the mask (sklearn
        semantics: weighted center means, weighted 1/n_c decay).

        Composed from the staged-protocol hooks — ``_pf_stage`` (host
        pad + upload) then ``_pf_consume`` (ingest cast + device step)
        — so the serial path and the prefetch pipeline can never drift
        apart.  The weighted path reweights between the two:
        ``reweight_rows`` only rebuilds the MASK, so it commutes with
        ``_pf_consume``'s dtype ingest of the data."""
        if not isinstance(X, ShardedRows):
            staged = self._pf_stage(X)
            if staged is None:
                # device-born jax.Array block: the D2H fetch is legal
                # HERE (consumer thread), then the same host pad path
                staged = self._pf_stage(np.asarray(X))
            X = staged
        if sample_weight is not None:
            from ..utils import reweight_rows

            X = reweight_rows(X, sample_weight=sample_weight)
        return self._pf_consume(X)

    # -- whole-array fit ---------------------------------------------------
    def fit(self, X, y=None, sample_weight=None):
        check_max_iter(self.max_iter)
        X = _ingest_float(self, X)
        if sample_weight is not None:
            # fold weights into the mask: epoch windows then carry the
            # per-row weight, so batch sums, the 1/n_c decay, the epoch
            # inertia AND the init sampling are all their weighted forms
            from ..utils import reweight_rows

            X = reweight_rows(X, sample_weight=sample_weight)
        for attr in ("cluster_centers_", "_counts"):
            if hasattr(self, attr):
                delattr(self, attr)

        from ..resilience.preemption import check_preemption
        from ..resilience.testing import maybe_fault

        ckpt = self.fit_checkpoint
        best = np.inf
        bad = 0
        epoch0 = 0
        snap = ckpt.load_if_matches(self) if ckpt is not None else None
        if snap is not None:
            # resume: install the snapshot BEFORE _ensure_state so the
            # (discarded-anyway) k-means++ init is skipped entirely
            epoch0, state = snap
            # copies: the epoch program donates centers/counts; the
            # snapshot's arrays must stay valid for a retried resume
            self.cluster_centers_ = jnp.array(state["centers"],
                                              dtype=X.data.dtype)
            self._counts = jnp.array(state["counts"], dtype=jnp.float32)
            best, bad = float(state["best"]), int(state["bad"])
            self.n_features_in_ = X.data.shape[1]
        self._ensure_state(X)
        n = X.data.shape[0]
        bs = int(min(self.batch_size, n))
        n_batches = max(n // bs, 1)
        key = as_key(self.random_state)
        # the per-epoch key schedule is a pure function of the epoch index:
        # fast-forward the splits for already-completed epochs so a resumed
        # fit draws the SAME reassignment/offset keys the killed fit would
        for e in range(epoch0):
            if e > 0 and self.reassignment_ratio:
                key, _ = jax.random.split(key)
            key, _ = jax.random.split(key)
        centers, counts = self.cluster_centers_, self._counts
        # device scalar hoisted out of the loop: re-materializing it per
        # epoch is an implicit transfer the sanitizer's guard would flag
        ratio32 = (jnp.float32(self.reassignment_ratio)
                   if self.reassignment_ratio else None)
        epoch = max(epoch0 - 1, 0)
        for epoch in range(epoch0, self.max_iter):
            maybe_fault("step")
            with _san.region("minibatch_kmeans.fit.epochs"):
                if epoch > 0 and self.reassignment_ratio:
                    # BEFORE the epoch (sklearn reassigns before the batch
                    # update): a reseeded center is always refined by the
                    # epoch that follows, so raw random seeds can never flow
                    # into the returned cluster_centers_/labels_
                    key, sub = jax.random.split(key)
                    centers, counts = _reassign_starved(
                        centers, counts, X.data, X.mask, sub, ratio32,
                    )
                key, sub = jax.random.split(key)
                start = jax.random.randint(sub, (), 0, max(n - bs + 1, 1))
                centers, counts, mean_inertia = _mbk_epoch(
                    centers, counts, X.data, X.mask, start,
                    batch_size=bs, n_batches=n_batches,
                )
            with _EPOCH_SYNC.allow():
                # graftlint: disable=host-sync-loop -- epoch-boundary convergence check: one scalar sync per epoch (n_batches fused steps), sklearn's max_no_improvement contract needs the host value
                cur = float(mean_inertia)
            stop = False
            if self.max_no_improvement is not None:
                if cur > best - self.tol * max(abs(best), 1.0):
                    bad += 1
                    if bad >= self.max_no_improvement:
                        stop = True
                else:
                    bad = 0
            best = min(best, cur)
            # keep the public attrs pointing at LIVE buffers at every
            # boundary: the epoch program DONATED the previous ones, and
            # a mid-loop exit (TrainingPreempted at check_preemption
            # below, a chaos fault) must leave a readable estimator,
            # not deleted arrays
            self.cluster_centers_, self._counts = centers, counts
            state = {"centers": centers, "counts": counts,
                     "best": best, "bad": bad}
            if ckpt is not None and not stop and ckpt.due(epoch + 1):
                ckpt.save(self, state, epoch + 1)
            check_preemption(ckpt, self, state, epoch + 1)
            if stop:
                break
        if ckpt is not None:
            ckpt.complete()
        self.cluster_centers_, self._counts = centers, counts
        self.n_iter_ = epoch + 1
        self.n_steps_ = (epoch + 1) * n_batches
        labels, inertia = _assign(X.data, X.mask, self.cluster_centers_)
        self.labels_ = labels[: X.n_samples]
        self.inertia_ = float(inertia)
        return self

    # -- inference ---------------------------------------------------------
    def predict(self, X):
        X = _ingest_float(self, X)
        labels, _ = _assign(X.data, X.mask, self.cluster_centers_)
        return labels[: X.n_samples]

    def fit_predict(self, X, y=None):
        return self.fit(X).labels_

    def transform(self, X):
        X = _ingest_float(self, X)
        d = jnp.sqrt(jnp.maximum(_sq_dists(X.data, self.cluster_centers_), 0.0))
        return d[: X.n_samples]

    def score(self, X, y=None, sample_weight=None):
        X = _ingest_float(self, X)
        if sample_weight is not None:
            from ..utils import reweight_rows

            X = reweight_rows(X, sample_weight=sample_weight)
        _, inertia = _assign(X.data, X.mask, self.cluster_centers_)
        return -float(inertia)

"""graftsan: the runtime SPMD sanitizer (compile / transfer / dispatch).

The dynamic half of graftlint (``dask_ml_tpu/analysis/``): the static
pass proves what the AST can see, this package observes real fits —

* the **compile sanitizer** counts every XLA backend compile and
  attributes it to a named :func:`region`, asserting steady-state fit
  loops compile zero new programs after warmup;
* the **transfer sanitizer** arms ``jax.transfer_guard`` around
  steady-phase hot loops, with :class:`AllowSite` escapes that cite —
  and runtime-verify — the graftlint ``host-sync-loop`` suppressions;
* the **dispatch sanitizer** records the thread of every device
  dispatch and fails fast on a second dispatching thread (the PR-1
  deadlock class, caught at the violating enqueue).

Results surface in ``diagnostics.sanitize_report()``; the committed
``tools/sanitize_baseline.json`` ratchets per-workload counts in tier-1
exactly like the lint baseline (``tools/lint.sh --sanitize`` /
``--rebaseline``).  See :mod:`.core` for the detectors, :mod:`.smoke`
for the gated workloads, :mod:`.baseline` for the ratchet semantics.

CLI::

    python -m dask_ml_tpu.sanitize --baseline tools/sanitize_baseline.json
    python -m dask_ml_tpu.sanitize --write-baseline tools/sanitize_baseline.json
"""

from . import baseline  # noqa: F401
from .core import (  # noqa: F401
    BASELINE_ENV,
    SANITIZE_ENV,
    CompileViolation,
    DispatchViolation,
    Sanitizer,
    active_sanitizer,
    ambient,
    enabled_by_env,
    last_report,
    record_d2h,
    region,
    sanitize,
    step_guard,
)
from .sites import AllowSite, registered_sites  # noqa: F401
from . import locks  # noqa: F401

__all__ = [
    "locks",
    "AllowSite",
    "BASELINE_ENV",
    "SANITIZE_ENV",
    "CompileViolation",
    "DispatchViolation",
    "Sanitizer",
    "active_sanitizer",
    "ambient",
    "baseline",
    "enabled_by_env",
    "last_report",
    "record_d2h",
    "region",
    "registered_sites",
    "sanitize",
    "step_guard",
]

# graftlock env arming (DASK_ML_TPU_LOCK_MONITOR=on): a long-lived
# process records lock contention histograms from import — strict knob
# parse, same posture as DASK_ML_TPU_TRACE / DASK_ML_TPU_METRICS_PORT
locks.arm_from_env()

"""graftlock runtime half: the instrumented-lock monitor, the lock
smoke suite, and the fifth committed ratchet.

The static rules (``analysis/rules/locks.py``) prove lock-order and
ownership properties about every path the AST can see; this module
verifies the paths that actually RUN.  Arming :class:`LockMonitor` via
:func:`instrumented_locks` hooks the package's named-lock factory
(:mod:`dask_ml_tpu._locks`): every acquisition records (lock name,
thread, wait seconds) into a per-thread held stack and a global
name-level order graph, every release books held seconds, and two
violation classes are detected live —

* **order-inversion** — thread X acquires B while holding A after some
  thread acquired A while holding B: the runtime twin of the static
  ``lock-order-cycle`` rule, caught on the first inverted acquisition
  rather than the first deadlock;
* **cross-thread-class** — a package thread (``dask-ml-tpu-*``) outside
  a lock's declared roster (``_spmd.LOCK_THREAD_CONTRACTS``) acquires
  it, or a host thread acquires a lock whose roster excludes ``host``:
  the runtime twin of ``unguarded-shared-state`` for the states those
  locks guard.

Contention is booked for free while armed: ``lock.wait_s{name}`` and
``lock.held_s{name}`` histograms land in the PR-7 metrics registry, so
``/metrics`` and ``run_report()`` expose per-lock contention — the
[autopilot] controller's input signal.

The suite (:data:`LOCK_WORKLOADS`) is the graftsan smoke suite plus
``triple_plane`` (concurrent serve + search + ingest in one process,
under an armed graftsan scope).  ``tools/lock_baseline.json`` commits
the observed order-graph edge union and per-workload violation zeros;
``tools/lint.sh --locks`` re-runs and ratchets:

* a workload in the run but not the snapshot is **new** → fail; a
  snapshot workload absent from the run is **stale** → fail;
* an observed edge absent from the snapshot is a **new edge** → fail
  (a new nesting must be consciously baselined — it is a new way to
  deadlock); a snapshot edge unobserved in a warm run passes, same
  ceiling asymmetry as the sanitize baseline (a warm jit cache skips
  compile-path acquisitions the cold ``--write-baseline`` run saw);
* violations are a **hard zero**, run and snapshot both — a baseline
  can never grandfather an inversion in.

``--inject-inversion`` / ``--inject-cross-write`` run seeded faults
through the same entry the gate uses, proving the detector fires
(exit 1) before anyone trusts its silence (exit 0).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

from .._locks import NamedLock, make_lock
from .._locks import monitor as _current_monitor
from .._locks import set_monitor

__all__ = [
    "BASELINE_ENV",
    "MONITOR_ENV",
    "LOCK_WORKLOADS",
    "LockMonitor",
    "arm_from_env",
    "compare",
    "default_path",
    "emit",
    "inject_cross_write",
    "inject_inversion",
    "instrumented_locks",
    "is_clean",
    "load",
    "main",
    "run_lock_smoke",
    "run_lock_workload",
    "triple_plane",
    "write",
]

#: baseline path override (fifth committed baseline)
BASELINE_ENV = "DASK_ML_TPU_LOCK_BASELINE"
#: "on"/"1" arms a process-wide monitor at package import: a long-lived
#: serve process then exports lock.wait_s/held_s contention for free
MONITOR_ENV = "DASK_ML_TPU_LOCK_MONITOR"
#: "inversion"/"cross-write" injects that seeded fault into a gate run
#: (``tools/lint.sh --locks`` must exit 1 under it — the detector is
#: proven live through the very entry the gate trusts)
INJECT_ENV = "DASK_ML_TPU_LOCK_INJECT"

_VERSION = 1
_PKG_THREAD_PREFIX = "dask-ml-tpu-"


def _registry():
    from ..obs.metrics import registry

    return registry()


def _contracts() -> dict:
    from ..analysis.rules._spmd import LOCK_THREAD_CONTRACTS

    return LOCK_THREAD_CONTRACTS


class LockMonitor:
    """Process-wide lockset sanitizer (the _locks monitor hook).

    Held stacks are thread-local; the order graph, violation log, and
    counters live behind ONE raw ``threading.Lock`` — raw deliberately:
    the monitor's own bookkeeping must never re-enter the monitor, and
    it is a leaf by construction (nothing is acquired under it)."""

    def __init__(self, *, book_metrics: bool = True):
        self._tls = threading.local()
        self._lock = threading.Lock()
        self.book_metrics = book_metrics
        #: (held_name, acquired_name) -> {"count", "thread"}
        self.edges: dict = {}
        self.violations: list = []
        self.acquisitions = 0
        self._flagged: set = set()
        self._contracts = _contracts()

    # -- the _locks hook surface -----------------------------------------
    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def on_acquire(self, lock: NamedLock, wait_s: float) -> None:
        name = lock.name
        thread = threading.current_thread().name
        st = self._stack()
        held = [n for n, _t in st]
        first = name not in held  # reentrant re-acquisition adds no edge
        with self._lock:
            self.acquisitions += 1
            roster = self._contracts.get(name)
            if roster is not None:
                pkg = thread.startswith(_PKG_THREAD_PREFIX)
                ok = (thread in roster) if pkg else ("host" in roster)
                if not ok:
                    self.violations.append({
                        "kind": "cross-thread-class", "lock": name,
                        "thread": thread,
                        "detail": f"thread {thread!r} acquired {name!r} "
                                  f"(roster: {sorted(roster)}) — the "
                                  f"state this lock guards is owned by "
                                  f"other thread classes",
                    })
            if first:
                for h in held:
                    if h == name:
                        continue  # pragma: no cover - first implies absent
                    e = self.edges.get((h, name))
                    if e is None:
                        self.edges[(h, name)] = {"count": 1,
                                                 "thread": thread}
                        rev = self.edges.get((name, h))
                        pair = (name, h) if name < h else (h, name)
                        if rev is not None and pair not in self._flagged:
                            self._flagged.add(pair)
                            self.violations.append({
                                "kind": "order-inversion",
                                "lock": name, "thread": thread,
                                "detail":
                                    f"thread {thread!r} acquired "
                                    f"{name!r} while holding {h!r}, but "
                                    f"{rev['thread']!r} acquired them "
                                    f"in the reverse order — the "
                                    f"interleaving that runs both at "
                                    f"once deadlocks",
                            })
                    else:
                        e["count"] += 1
        st.append((name, time.perf_counter()))
        if self.book_metrics:
            _registry().histogram("lock.wait_s", name).record(wait_s)

    def on_release(self, lock: NamedLock) -> None:
        name = lock.name
        st = self._stack()
        held_s = None
        for i in range(len(st) - 1, -1, -1):
            if st[i][0] == name:
                held_s = time.perf_counter() - st[i][1]
                del st[i]
                break
        if held_s is not None and self.book_metrics:
            _registry().histogram("lock.held_s", name).record(held_s)

    # -- results ---------------------------------------------------------
    def edge_names(self) -> list:
        with self._lock:
            return sorted(f"{a} -> {b}" for a, b in self.edges)

    def report(self) -> dict:
        with self._lock:
            return {
                "acquisitions": self.acquisitions,
                "edges": sorted(f"{a} -> {b}" for a, b in self.edges),
                "violations": list(self.violations),
            }


class instrumented_locks:
    """``with instrumented_locks() as mon:`` — arm a fresh
    :class:`LockMonitor` for the block.  Non-nestable: attribution
    (which workload produced which edge) requires one monitor."""

    def __init__(self, *, book_metrics: bool = True):
        self._mon = LockMonitor(book_metrics=book_metrics)

    def __enter__(self) -> LockMonitor:
        if _current_monitor() is not None:
            raise RuntimeError(
                "a lock monitor is already armed: instrumented_locks() "
                "scopes must not nest")
        set_monitor(self._mon)
        return self._mon

    def __exit__(self, *exc) -> None:
        set_monitor(None)


def arm_from_env() -> LockMonitor | None:
    """Import-time arming (strict knob parse, same posture as the other
    env knobs: a typo'd value raises rather than silently disarming)."""
    raw = os.environ.get(MONITOR_ENV, "").strip().lower()
    if raw in ("", "0", "off", "false"):
        return None
    if raw not in ("1", "on", "true"):
        raise ValueError(
            f"{MONITOR_ENV}={raw!r}: expected on/off (or 1/0)")
    if _current_monitor() is not None:  # pragma: no cover - double import
        return _current_monitor()
    mon = LockMonitor()
    set_monitor(mon)
    return mon


# -- seeded faults --------------------------------------------------------

def inject_inversion() -> None:
    """A→B then B→A on one thread, sequentially: no deadlock can occur
    in the run itself, but the ORDER GRAPH carries the cycle — exactly
    the window the detector exists to catch before an unlucky
    interleaving does."""
    a = make_lock("selftest.alpha")
    b = make_lock("selftest.beta")
    with a:
        with b:
            pass
    with b:
        with a:
            pass


def inject_cross_write() -> None:
    """A rogue package-prefixed thread acquires a roster-contracted
    lock (``serve.server`` admits the serve loop and host threads
    only): the runtime shape of an unguarded cross-thread write to the
    state that lock guards."""
    guarded = make_lock("serve.server")

    def _rogue():
        with guarded:
            pass

    # graftlint: disable=contract-roster-drift -- deliberately off-roster: this workload EXISTS to prove the runtime roster check catches an unreviewed package-prefixed thread; rostering it would blind the drill
    t = threading.Thread(target=_rogue, name="dask-ml-tpu-rogue-writer")
    t.start()
    t.join()


# -- workloads ------------------------------------------------------------

def triple_plane():
    """Concurrent serve + search + ingest in ONE process, under an
    armed graftsan scope.  A live :class:`~dask_ml_tpu.serve.runtime.
    ModelServer` handles a host-thread client pump for the whole span
    while the main thread runs a Hyperband search and then a sharded-
    dataset streamed fit (device dispatch stays on the primary/blessed
    threads — the concurrency under test is the LOCK plane: the serve
    loop, search dispatcher, compile-ahead builder, data readers,
    supervisor beats, and obs instruments all interleave their
    acquisitions).  Gate: zero graftsan violations AND zero lock
    violations, simultaneously."""
    import shutil
    import tempfile

    import numpy as np

    from .. import data as _data
    from .. import programs
    from ..linear_model import SGDClassifier
    from ..model_selection import HyperbandSearchCV
    from ..pipeline import stream_partial_fit
    from ..serve import ModelServer
    from .core import sanitize

    rng = np.random.RandomState(7)
    Xs = rng.normal(size=(128, 4)).astype(np.float32)
    ys = (Xs[:, 0] > 0).astype(np.int32)
    clf = SGDClassifier(random_state=0)
    clf.partial_fit(Xs, ys, classes=np.array([0, 1]))

    X = rng.normal(size=(1024, 4)).astype(np.float32)
    y = (X[:, 0] + 0.1 * rng.normal(size=1024) > 0).astype(np.int32)

    stop = threading.Event()
    pump_errors: list = []

    def _pump(srv):
        # host-class client: submit + wait until told to stop — enqueue
        # and event-wait only, statically provable host-only (the serve
        # loop owns the dispatch; serve.server's roster admits hosts)
        while not stop.is_set():
            try:
                srv.submit("m", Xs[:16]).result(30.0)
            except Exception as e:  # surfaced after join
                pump_errors.append(e)
                return

    d = tempfile.mkdtemp(prefix="graftlock-ds-")
    try:
        with sanitize(label="triple_plane") as s:
            with ModelServer(label="triple_plane", window_s=0.0) as srv:
                srv.load("m", clf)
                pump = threading.Thread(target=_pump, args=(srv,),
                                        name="triple-plane-client")
                pump.start()
                try:
                    # search plane (spawns dask-ml-tpu-search)
                    HyperbandSearchCV(
                        SGDClassifier(random_state=0),
                        {"alpha": [1e-4, 1e-3]},
                        max_iter=2, random_state=0, test_size=0.25,
                        chunk_size=64,
                    ).fit(X, y, classes=np.array([0, 1]))
                    programs.drain_ahead()
                    # ingest plane (spawns dask-ml-tpu-data-reader x2
                    # and the dask-ml-tpu-prefetch worker)
                    _data.write_dataset(d, X, y, shards=2,
                                        block_rows=256)
                    model = SGDClassifier(random_state=0)
                    ds = _data.ShardedDataset(d, key=7, readers=2,
                                              label="triple_plane")
                    stream_partial_fit(
                        model, ds.iter_blocks(epoch=0), depth=2,
                        fit_kwargs={"classes": np.array([0, 1])},
                        label="triple_plane")
                    programs.drain_ahead()
                finally:
                    stop.set()
                    pump.join(timeout=30.0)
    finally:
        shutil.rmtree(d, ignore_errors=True)
    if pump_errors:
        raise pump_errors[0]
    return s


def _lock_workloads() -> dict:
    from .smoke import WORKLOADS

    out = dict(WORKLOADS)
    out["triple_plane"] = triple_plane
    return out


#: name -> callable; resolved lazily so importing this module never
#: imports jax (the CLI/tests resolve at run time)
LOCK_WORKLOADS = _lock_workloads


def run_lock_workload(name: str, fn=None) -> dict:
    """One workload under an armed monitor → its lock metrics.  A
    workload crash is an ``error`` metric (hard failure in the
    ratchet), never a crash of the suite."""
    if fn is None:
        fn = _lock_workloads()[name]
    err = None
    with instrumented_locks() as mon:
        try:
            fn()
        except Exception as e:
            err = f"{type(e).__name__}: {e}"
    rep = mon.report()
    out = {
        "acquisitions": rep["acquisitions"],
        "edges": rep["edges"],
        "violations": len(rep["violations"]),
        "violation_details": [v["detail"] for v in rep["violations"]],
    }
    if err:
        out["error"] = err
    return out


def run_lock_smoke(names=None) -> dict:
    fns = _lock_workloads()
    names = list(fns) if names is None else list(names)
    unknown = [n for n in names if n not in fns]
    if unknown:
        raise KeyError(f"unknown workload(s): {', '.join(unknown)}")
    return {name: run_lock_workload(name, fns[name]) for name in names}


# -- baseline (fifth ratchet) ---------------------------------------------

def default_path() -> str | None:
    env = os.environ.get(BASELINE_ENV, "").strip()
    if env:
        return env
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cand = os.path.join(os.path.dirname(pkg), "tools",
                        "lock_baseline.json")
    return cand if os.path.isfile(cand) else None


def emit(results: dict) -> dict:
    """Snapshot payload: the order-graph edge UNION across the suite
    (edges are name-level facts about the process, not per-workload
    ones — workload attribution rides the per-workload edge lists the
    gate re-derives) plus per-workload violation/acquisition books."""
    union: set = set()
    for m in results.values():
        union |= set(m["edges"])
    return {
        "version": _VERSION,
        "tool": "graftlock",
        "edges": sorted(union),
        "workloads": {
            name: {"acquisitions": m["acquisitions"],
                   "edge_count": len(m["edges"]),
                   "violations": m["violations"],
                   **({"error": m["error"]} if m.get("error") else {})}
            for name, m in sorted(results.items())
        },
    }


def write(path: str, payload: dict) -> None:
    from ..analysis.cache import atomic_write_json

    atomic_write_json(path, payload, indent=2, sort_keys=True)


def load(path: str) -> dict:
    with open(path, encoding="utf-8") as fh:
        payload = json.load(fh)
    if payload.get("version", 0) > _VERSION:
        raise ValueError(
            f"lock baseline {path} has version {payload['version']}, "
            f"newer than this sanitizer understands ({_VERSION})")
    if not isinstance(payload.get("workloads"), dict) or \
            not isinstance(payload.get("edges"), list):
        raise ValueError(
            f"lock baseline {path} is malformed: no workloads/edges")
    return payload


def compare(snapshot: dict, results: dict, *, partial: bool = False) -> dict:
    """The ratchet delta (same shape as the sanitize baseline's):
    ``{"new", "stale", "regressions", "violations"}``.

    ``partial=True`` (an explicit ``--workloads`` subset, or a warm
    in-process run) checks the hard invariants only: stale is
    meaningless for a subset, and the edge union is calibrated against
    the cold full suite (a warm jit cache legitimately skips
    compile-path acquisitions), so edge comparisons would false-fail."""
    snap_wl = snapshot["workloads"]
    snap_edges = set(snapshot.get("edges", ()))
    new = [] if partial else sorted(set(results) - set(snap_wl))
    stale = [] if partial else sorted(set(snap_wl) - set(results))
    regressions: list = []
    violations: list = []

    for name, m in sorted(results.items()):
        if m.get("error"):
            violations.append(f"{name}: workload errored: {m['error']}")
        if m.get("violations", 0):
            details = "; ".join(m.get("violation_details", ())) or "?"
            violations.append(
                f"{name}: {m['violations']} lock violation(s) "
                f"(must be 0): {details}")
        if partial:
            continue
        for edge in m.get("edges", ()):
            if edge not in snap_edges:
                regressions.append(
                    f"{name}: NEW lock-order edge {edge!r} — a new "
                    f"nesting is a new way to deadlock; prove the "
                    f"order and rebaseline deliberately "
                    f"(tools/lint.sh --rebaseline)")

    for name, m in sorted(snap_wl.items()):
        if m.get("violations", 0) or m.get("error"):
            violations.append(
                f"baseline entry {name} carries violations: a snapshot "
                f"cannot grandfather an inversion — fix and rebaseline")

    return {"new": new, "stale": stale,
            "regressions": sorted(set(regressions)),
            "violations": violations}


def is_clean(delta: dict) -> bool:
    return not any(delta[k] for k in ("new", "stale", "regressions",
                                      "violations"))


# -- CLI ------------------------------------------------------------------

def _parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m dask_ml_tpu.sanitize.locks",
        description="graftlock runtime lockset sanitizer + ratchet",
    )
    p.add_argument("--workloads", default=None,
                   help="comma-separated subset (default: all; implies "
                        "hard-invariant-only checking)")
    p.add_argument("--baseline", metavar="PATH", default=None,
                   help=f"ratchet against this snapshot (default: "
                        f"{BASELINE_ENV}, else tools/lock_baseline.json)")
    p.add_argument("--write-baseline", metavar="PATH", default=None)
    p.add_argument("--inject-inversion", action="store_true",
                   help="seeded-fault self-test: run an A→B/B→A "
                        "inversion under the monitor (must exit 1)")
    p.add_argument("--inject-cross-write", action="store_true",
                   help="seeded-fault self-test: a rogue package "
                        "thread acquires a contracted lock (must "
                        "exit 1)")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--list-workloads", action="store_true")
    return p


def main(argv=None) -> int:
    try:
        args = _parser().parse_args(argv)
    except SystemExit as e:  # argparse's bad-args path
        return 0 if (e.code in (0, None)) else 2

    if args.list_workloads:
        for name in sorted(_lock_workloads()):
            print(name)
        return 0

    injections = []
    if args.inject_inversion:
        injections.append(("inject_inversion", inject_inversion))
    if args.inject_cross_write:
        injections.append(("inject_cross_write", inject_cross_write))
    if injections:
        # the self-test path: the seeded fault REPLACES the suite (it
        # must be cheap enough for tier-1), and detection is the pass
        # condition of the DETECTOR but the fail condition of the gate
        results = {name: run_lock_workload(name, fn)
                   for name, fn in injections}
        failed = [n for n, m in results.items() if m["violations"]]
        if args.format == "json":
            print(json.dumps({"workloads": results,
                              "detected": sorted(failed)},
                             indent=2, sort_keys=True))
        else:
            for name, m in sorted(results.items()):
                for detail in m["violation_details"]:
                    print(f"VIOLATION: {name}: {detail}")
            print(f"locks: {len(failed)}/{len(results)} seeded "
                  f"fault(s) detected")
        missed = [n for n, m in results.items() if not m["violations"]]
        if missed:
            print(f"locks: seeded fault(s) NOT detected: "
                  f"{', '.join(sorted(missed))} — the detector is "
                  f"blind", file=sys.stderr)
            return 2
        return 1

    names = None
    if args.workloads:
        names = [w.strip() for w in args.workloads.split(",") if w.strip()]
    if args.write_baseline and names is not None:
        print("error: --write-baseline requires the full suite "
              "(drop --workloads)", file=sys.stderr)
        return 2
    try:
        results = run_lock_smoke(names)
    except KeyError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    # DASK_ML_TPU_LOCK_INJECT seeds a fault INTO a gate run (vs the
    # --inject-* flags, which replace the suite): the violation rides
    # the normal ratchet path, so `tools/lint.sh --locks` is proven to
    # exit 1 through the very invocation CI trusts
    fault = os.environ.get(INJECT_ENV, "").strip().lower()
    if fault:
        seeded = {
            "inversion": ("injected_inversion", inject_inversion),
            "cross-write": ("injected_cross_write", inject_cross_write),
            "cross_write": ("injected_cross_write", inject_cross_write),
        }.get(fault)
        if seeded is None:
            print(f"error: {INJECT_ENV}={fault!r} (want 'inversion' "
                  f"or 'cross-write')", file=sys.stderr)
            return 2
        results[seeded[0]] = run_lock_workload(seeded[0], seeded[1])

    snap_path = args.write_baseline or args.baseline
    if args.write_baseline:
        probe = compare(emit(results), results, partial=True)
        if probe["violations"]:
            for line in probe["violations"]:
                print(f"VIOLATION: {line}", file=sys.stderr)
            print(f"locks: refusing to write a violating baseline to "
                  f"{args.write_baseline} (file untouched)",
                  file=sys.stderr)
            return 1
        write(args.write_baseline, emit(results))
    if snap_path is None:
        snap_path = default_path()

    if snap_path is not None:
        try:
            snap = load(snap_path)
        except (OSError, ValueError) as e:
            print(f"error: cannot load baseline {snap_path}: {e}",
                  file=sys.stderr)
            return 2
        delta = compare(snap, results, partial=names is not None)
    else:
        delta = compare(emit(results), results, partial=names is not None)

    clean = is_clean(delta)
    if args.format == "json":
        print(json.dumps({"workloads": results, "delta": delta,
                          "baseline": snap_path, "clean": clean},
                         indent=2, sort_keys=True))
    else:
        for name, m in sorted(results.items()):
            print(f"{name}: acquisitions={m['acquisitions']} "
                  f"edges={len(m['edges'])} "
                  f"violations={m['violations']}"
                  + (f" ERROR={m['error']}" if m.get("error") else ""))
        for key in ("violations", "regressions", "new", "stale"):
            for line in delta[key]:
                print(f"{key.upper()}: {line}")
        print("locks: "
              + ("clean" if clean else "FAILED")
              + (f" (vs {snap_path})" if snap_path else " (no baseline)"))
    return 0 if clean else 1


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())

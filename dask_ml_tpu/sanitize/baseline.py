"""Per-workload sanitizer baseline: the runtime twin of graftlint's
ratchet (``analysis/baseline.py``), with the same CI semantics —

* a workload in the run but not in the snapshot is **new** → fail
  (every workload must be consciously baselined);
* a snapshot entry not in the run is **stale** → fail, so the committed
  ``tools/sanitize_baseline.json`` always matches the suite
  (refresh with ``tools/lint.sh --rebaseline``);
* measured compile / d2h-sync / allow-site counts above the snapshot are
  **new compiles / new transfers** → fail (the ratchet);
* the hard invariants are not ratcheted at all: steady-state compiles,
  sanitizer violations, and transfer-guard errors must be **zero** in
  both the snapshot and the run — a baseline cannot grandfather a
  contract violation in.

One deliberate asymmetry vs the lint ratchet: counts *below* the
snapshot pass without being stale.  Compile counts are ceilings, not
identities — inside a warm pytest process the jit cache already holds
programs a cold ``python -m dask_ml_tpu.sanitize`` run would compile,
so only the cold run (which is what ``--write-baseline`` uses) observes
the full count.  Tighten the ceiling by rebaselining from a cold run."""

from __future__ import annotations

import json
import os

__all__ = [
    "compare",
    "default_path",
    "emit",
    "load",
    "write",
    "HARD_INVARIANTS",
    "RATCHETED_COUNTS",
]

_VERSION = 1

#: per-workload metrics that must be exactly zero, snapshot and run both
HARD_INVARIANTS = ("steady_compiles", "violations", "transfer_errors")

#: per-workload metrics ratcheted as ceilings (run > snapshot fails).
#: The blessed compile-ahead thread's compiles are deliberately HERE and
#: not in the hard invariants: a steady-phase compile on that thread is
#: its job (hiding the next bucket's build behind the current block),
#: but the count is still a committed ceiling — attributed, not
#: suppressed.
RATCHETED_COUNTS = ("warmup_compiles", "steady_d2h_syncs",
                    "ahead_compiles", "steady_ahead_compiles")


def default_path() -> str | None:
    """The committed snapshot: the ``DASK_ML_TPU_SANITIZE_BASELINE``
    knob, else ``tools/sanitize_baseline.json`` next to a repo checkout
    of this package, else None."""
    from .core import BASELINE_ENV

    env = os.environ.get(BASELINE_ENV, "").strip()
    if env:
        return env
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cand = os.path.join(os.path.dirname(pkg), "tools",
                        "sanitize_baseline.json")
    return cand if os.path.isfile(cand) else None


def emit(results: dict) -> dict:
    """Snapshot payload for a full smoke run: ``results`` maps workload
    name -> metrics dict (see :func:`.smoke.run_workload`)."""
    import jax

    return {
        "version": _VERSION,
        "tool": "graftsan",
        # recorded for the human diffing a rebaseline, NOT compared: a
        # jax upgrade legitimately shifts compile counts and the ratchet
        # (not a version gate) is what must catch that
        "jax": jax.__version__,
        "workloads": {
            name: {k: metrics[k] for k in sorted(metrics)}
            for name, metrics in sorted(results.items())
        },
    }


def write(path: str, payload: dict) -> None:
    from ..analysis.cache import atomic_write_json

    atomic_write_json(path, payload, indent=2, sort_keys=True)


def load(path: str) -> dict:
    with open(path, encoding="utf-8") as fh:
        payload = json.load(fh)
    if payload.get("version", 0) > _VERSION:
        raise ValueError(
            f"sanitize baseline {path} has version {payload['version']}, "
            f"newer than this sanitizer understands ({_VERSION})")
    if not isinstance(payload.get("workloads"), dict):
        raise ValueError(
            f"sanitize baseline {path} is malformed: no workloads table")
    return payload


def compare(snapshot: dict, results: dict, *, partial: bool = False) -> dict:
    """The ratchet delta::

        {"new":        [workload names in the run, absent from snapshot],
         "stale":      [snapshot names absent from the run],
         "regressions":[human-readable count-regression strings],
         "violations": [hard-invariant failures, run AND snapshot]}

    ``partial=True`` (an explicit ``--workloads`` subset) checks the
    hard invariants ONLY: the stale check is meaningless for a subset,
    and the compile ceilings are calibrated against the full suite's
    execution order (a depth-2 stream workload legitimately compiles
    nothing when its depth-0 twin ran first), so count comparisons on a
    subset would false-fail.  The gate always runs the full suite."""
    snap = snapshot["workloads"]
    new = [] if partial else sorted(set(results) - set(snap))
    stale = [] if partial else sorted(set(snap) - set(results))
    regressions: list[str] = []
    violations: list[str] = []

    for name, m in sorted(results.items()):
        err = m.get("error")
        if err:
            violations.append(f"{name}: workload errored: {err}")
        for k in HARD_INVARIANTS:
            if m.get(k, 0):
                violations.append(
                    f"{name}: hard invariant {k} = {m[k]} (must be 0)")
        base = snap.get(name)
        if base is None or partial:
            continue
        for k in RATCHETED_COUNTS:
            if m.get(k, 0) > base.get(k, 0):
                regressions.append(
                    f"{name}: {k} {m.get(k, 0)} > baseline "
                    f"{base.get(k, 0)} — a NEW "
                    f"{'compile' if 'compile' in k else 'transfer'} "
                    f"reached the steady path; fix it or rebaseline "
                    f"deliberately (tools/lint.sh --rebaseline)")
        run_sites = m.get("allow_sites", {})
        base_sites = base.get("allow_sites", {})
        for site, count in sorted(run_sites.items()):
            if site not in base_sites:
                regressions.append(
                    f"{name}: allow-site {site!r} is not in the "
                    f"baseline — a new boundary-sync escape must be "
                    f"baselined deliberately")
            elif count > base_sites[site]:
                regressions.append(
                    f"{name}: allow-site {site!r} passed {count}x > "
                    f"baseline {base_sites[site]}x — more boundary "
                    f"syncs per fit than the committed contract")

    for name, m in sorted(snap.items()):
        for k in HARD_INVARIANTS:
            if m.get(k, 0):
                violations.append(
                    f"baseline entry {name} carries {k} = {m[k]}: a "
                    f"snapshot cannot grandfather a contract violation "
                    f"— fix the workload and rebaseline")

    return {"new": new, "stale": stale, "regressions": regressions,
            "violations": violations}


def is_clean(delta: dict) -> bool:
    return not any(delta[k] for k in ("new", "stale", "regressions",
                                      "violations"))

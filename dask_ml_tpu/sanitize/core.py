"""graftsan core: the runtime SPMD sanitizer (compile / transfer / dispatch).

graftlint (``dask_ml_tpu/analysis/``) proves the concurrency contract the
AST can see; this module observes the half it cannot — the three runtime
costs docs/design.md §7/§8 legislate against but only measurement can
verify:

* **compile** — every XLA backend compile is counted and attributed to
  the innermost active :func:`region` on the compiling thread (via
  ``jax.monitoring``'s ``/jax/core/compile/backend_compile_duration``
  event, which jax 0.4.x emits once per backend compile and never on a
  cache hit).  A steady-state fit loop must compile **zero** new
  programs after warmup: recompilation is the hidden tax SURVEY §7
  hard part (c) names, and the `[compile]` program-cache lane needs
  measured, gated counts, not guesses.
* **transfer** — ``jax.transfer_guard("disallow")`` is armed around
  steady-phase hot loops (:meth:`Sanitizer.steady` /
  :func:`step_guard`): any *implicit* host↔device transfer — a Python
  scalar leaking into an eager op, a numpy array crossing at a jit
  boundary — raises at the violating call.  The documented boundary
  syncs (the graftlint ``host-sync-loop`` suppressions) become
  runtime-verified :class:`~.sites.AllowSite` escapes that nest an
  explicit ``allow`` and count each pass.  Explicit staging puts
  (``jnp.asarray`` of host numpy, ``device_put``) stay legal — that is
  precisely the §8 staging contract.  Scalar device→host syncs are
  additionally counted via an ``ArrayImpl._value`` hook (the
  ``float()``/``.item()`` class host-sync-loop flags statically;
  CPU's zero-copy D2H never trips the XLA guard, so the sanitizer
  carries its own counter).
* **dispatch** — every compiled-program execution
  (``pxla.ExecuteReplicated.__call__``) records its thread.  A second
  dispatching thread is the PR-1 deadlock class (design.md §7 rule 1);
  the sanitizer raises :class:`DispatchViolation` *at the violating
  dispatch* — in the offending thread, before the enqueue interleave
  can deadlock — unless the thread's name is in the blessed set
  (``analysis.rules._spmd.BLESSED_COMPILE_THREADS``, shared with the
  static stage-purity rule so the runtime and static allowlists cannot
  drift).

The hooks are installed lazily on the first :func:`sanitize` entry and
stay installed as pass-throughs (a ``None`` active-sanitizer check per
event); nothing is patched until a sanitizer is first used, and an
inactive process pays nothing.

Typical shape (the smoke suite in :mod:`.smoke` and the conftest
``sanitizer`` fixture both follow it)::

    from dask_ml_tpu import sanitize
    with sanitize.sanitize(label="sgd_stream") as s:
        fit_some_blocks(model)          # warmup: compiles counted
        with s.steady():                # guard armed, phase = steady
            fit_more_blocks(model)      # zero new compiles allowed
    s.last_report()["totals"]["steady_compiles"]  # -> 0 or the gate fails
"""

from __future__ import annotations

import contextlib
import os
import threading

from .._locks import make_lock, make_rlock
import time
from collections import defaultdict

import jax

from ..obs import event as _obs_event
from ..obs import scope as _scope
from ..obs.metrics import registry as _metrics_registry

__all__ = [
    "SANITIZE_ENV",
    "BASELINE_ENV",
    "CompileViolation",
    "DispatchViolation",
    "Sanitizer",
    "active_sanitizer",
    "enabled_by_env",
    "last_report",
    "region",
    "sanitize",
    "step_guard",
]

#: policy knob: a truthy value arms an ambient (fail-soft) sanitizer
#: around every ``pipeline.stream_partial_fit`` call, so any streamed
#: fit in the process records compile/transfer/dispatch counters into
#: ``diagnostics.sanitize_report()`` without code changes.
SANITIZE_ENV = "DASK_ML_TPU_SANITIZE"

#: policy knob: path of the committed per-workload sanitizer baseline
#: (default ``tools/sanitize_baseline.json`` next to a repo checkout).
BASELINE_ENV = "DASK_ML_TPU_SANITIZE_BASELINE"

#: the jax.monitoring event emitted once per XLA backend compile (and
#: never on a compile-cache hit) — the compile detector's signal.
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

#: region label charged for events on a thread with no open region.
UNATTRIBUTED = "<unattributed>"


class DispatchViolation(RuntimeError):
    """A device program was dispatched from a non-primary, non-blessed
    thread while a sanitizer was active — the PR-1 deadlock class,
    surfaced at the violating dispatch instead of as a post-hoc hang."""


class CompileViolation(RuntimeError):
    """Steady-state compile contract broken: a region compiled a new XLA
    program after :meth:`Sanitizer.steady` marked warmup complete."""


def enabled_by_env() -> bool:
    """Strict parse of the ``DASK_ML_TPU_SANITIZE`` knob — an
    unrecognized value is rejected loudly (the repo's env_choice
    posture), never silently read as 'on': the ambient sanitizer
    suppresses the pjit C++ fastpath, which no one should pay for a
    typo'd ``false``."""
    val = os.environ.get(SANITIZE_ENV, "").strip().lower()
    if val in ("", "0", "off", "false", "no"):
        return False
    if val in ("1", "on", "true", "yes"):
        return True
    raise ValueError(
        f"{SANITIZE_ENV} must be 0/off/false or 1/on/true; got {val!r}")


# -- active-sanitizer state ----------------------------------------------
_LOCK = make_rlock("sanitize.active")
_ACTIVE: "Sanitizer | None" = None
_LAST_REPORT: dict | None = None
_TLS = threading.local()  # per-thread region stack


def active_sanitizer() -> "Sanitizer | None":
    return _ACTIVE


def last_report() -> dict | None:
    """The report of the most recently exited sanitizer (None when no
    sanitizer has run in this process)."""
    return _LAST_REPORT


def _region_stack() -> list:
    st = getattr(_TLS, "stack", None)
    if st is None:
        st = _TLS.stack = []
    return st


def current_region() -> str:
    st = getattr(_TLS, "stack", None)
    if st:
        return st[-1]
    name = threading.current_thread().name
    if name != "MainThread":
        return f"<thread:{name}>"
    return UNATTRIBUTED


class _Region:
    """Cheap named-region context: pushes onto the calling thread's
    stack only while a sanitizer is active (estimator fit loops carry
    these annotations permanently; an un-sanitized fit pays one
    attribute check)."""

    __slots__ = ("name", "_pushed")

    def __init__(self, name: str):
        self.name = name
        self._pushed = False

    def __enter__(self):
        if _ACTIVE is not None:
            _region_stack().append(self.name)
            self._pushed = True
        return self

    def __exit__(self, *exc):
        if self._pushed:
            self._pushed = False
            st = _region_stack()
            if st and st[-1] == self.name:
                st.pop()
        return False


def region(name: str) -> _Region:
    """Attribute enclosed compile/transfer/dispatch events to ``name``.

    Reentrant and nestable (innermost wins); a fresh context object per
    call, so concurrent threads and recursive fits cannot share state.
    """
    return _Region(name)


def step_guard():
    """``jax.transfer_guard("disallow")`` when the active sanitizer is in
    its steady phase WITH the guard armed (the effective per-``steady()``
    choice, so ``steady(guard=False)`` really does disarm the per-step
    guards too), else a no-op — the per-dispatch arming estimators wrap
    around their jitted steps, so a steady-state step with a host
    operand fails at the exact call that leaked it."""
    s = _ACTIVE
    if s is not None and s.phase == "steady" and s._steady_guard:
        return jax.transfer_guard("disallow")
    return contextlib.nullcontext()


# -- lazily-installed process hooks --------------------------------------
_HOOKS_INSTALLED = False


def _install_hooks() -> None:
    """Install the three detectors' process hooks exactly once.  All of
    them are pass-throughs when no sanitizer is active."""
    global _HOOKS_INSTALLED
    with _LOCK:
        if _HOOKS_INSTALLED:
            return

        # grafttrace: the UNGATED compile counters (registry
        # compile.count / compile.duration_s) ride the obs.jaxhooks
        # listener — installed here too so any sanitized process trends
        # compiles even if tracing was never enabled.  That listener is
        # the single registry publisher; the one below only does
        # per-region sanitizer attribution, so there is no double count.
        from ..obs import jaxhooks as _jaxhooks

        _jaxhooks.install()

        # 1. compile: jax.monitoring duration listener (fires on the
        # compiling thread, once per backend compile, never on cache hit)
        import jax.monitoring as _mon

        def _on_event_duration(event: str, duration: float, **_kw) -> None:
            s = _ACTIVE
            if s is not None and event == _COMPILE_EVENT:
                s._record_compile(duration)

        _mon.register_event_duration_secs_listener(_on_event_duration)

        # 2. dispatch: wrap the compiled-program execution choke point.
        # Every jitted (and eager-op) execution funnels through
        # ExecuteReplicated.__call__ on the dispatching thread — but
        # only on the PYTHON dispatch path: jax's C++ pjit fastpath
        # executes warm programs without re-entering Python at all.  So
        # while a sanitizer is active the fastpath is suppressed (no new
        # fastpath entries are minted) and its caches are cleared at
        # scope entry (pre-warmed entries are evicted), which routes
        # every dispatch — warm or cold — through this hook.  The
        # executable cache is untouched, so suppression costs Python
        # dispatch overhead only, never a recompile; after the scope
        # exits, fastpath entries re-mint organically on the next call.
        from jax._src.interpreters import pxla as _pxla

        orig_call = _pxla.ExecuteReplicated.__call__

        def _dispatch_hook(er_self, *args):
            s = _ACTIVE
            if s is not None:
                s._record_dispatch(getattr(er_self, "name", "<program>"))
            # graftscope device-time accounting (obs/scope.py): this is
            # the second choke point — programs that do NOT route
            # through the central cache (whole-array fits under a
            # sanitizer, eager ops) still get an in-flight interval.
            # absorbed() = the program cache is already tracking this
            # very execution under its registry name.
            if _scope.absorbed():
                return orig_call(er_self, *args)
            t0 = time.perf_counter()
            out = orig_call(er_self, *args)
            _scope.track(getattr(er_self, "name", "<program>"), t0,
                         jax.tree_util.tree_leaves(out))
            return out

        _pxla.ExecuteReplicated.__call__ = _dispatch_hook

        from jax._src import pjit as _pjit

        orig_fastpath = _pjit._get_fastpath_data

        def _fastpath_hook(*args, **kwargs):
            if _ACTIVE is not None:
                return None
            return orig_fastpath(*args, **kwargs)

        _pjit._get_fastpath_data = _fastpath_hook

        # 3. d2h scalar syncs: ArrayImpl._value is the host
        # materialization funnel behind float()/int()/.item()/__bool__
        # (CPU's zero-copy D2H never trips the XLA transfer guard, so
        # the sanitizer counts these itself).  numpy's buffer-protocol
        # fast path can bypass it for bulk np.asarray — the counter is
        # therefore a *scalar-sync* counter, which is exactly the
        # host-sync-loop hazard class, not a byte meter.
        try:
            from jax._src import array as _jarray

            orig_value = _jarray.ArrayImpl._value

            def _value_hook(arr_self):
                s = _ACTIVE
                if s is not None:
                    s._record_d2h()
                return orig_value.fget(arr_self)

            _jarray.ArrayImpl._value = property(_value_hook)
        except (ImportError, AttributeError):  # pragma: no cover
            pass  # detector degrades to guard-only transfer checking

        # (the repo's own API-boundary fetch, core.sharded.unshard, is
        # instrumented at its definition via record_d2h() — a patch here
        # would miss every call site that bound the name at import time)

        _HOOKS_INSTALLED = True


def record_d2h() -> None:
    """Count one device→host fetch against the active sanitizer (no-op
    when none is active) — the hook point for this repo's own fetch
    boundaries (``core.sharded.unshard``), whose bulk ``device_get``
    rides numpy's buffer protocol and is invisible to the
    ``ArrayImpl._value`` scalar hook."""
    s = _ACTIVE
    if s is not None:
        s._record_d2h()


def _new_counter() -> dict:
    return {
        "compiles": 0,
        "steady_compiles": 0,
        "compile_s": 0.0,
        # compiles ATTRIBUTED to the blessed compile-ahead thread
        # (programs/ahead.py): allowed even in the steady phase — that
        # thread's whole job is hiding the next bucket's compile behind
        # the current block — but counted and ratcheted separately in
        # tools/sanitize_baseline.json, never folded into "compiles" or
        # silently suppressed
        "ahead_compiles": 0,
        "steady_ahead_compiles": 0,
        "ahead_compile_s": 0.0,
        "dispatches": 0,
        "steady_dispatches": 0,
        "d2h_syncs": 0,
        "steady_d2h_syncs": 0,
    }


class Sanitizer:
    """One sanitization scope: counters, phase, and violation log.

    Use via :func:`sanitize`; at most one sanitizer is active per
    process at a time (nested entry raises — scoping must stay
    unambiguous for attribution to mean anything).
    """

    def __init__(self, label: str = "sanitize", *, fail_fast: bool = True,
                 guard_steady: bool = True, blessed_threads=None):
        from ..analysis.rules._spmd import (BLESSED_COMPILE_THREADS,
                                            BLESSED_DISPATCH_THREADS)

        self.label = label
        self.fail_fast = fail_fast
        self.guard_steady = guard_steady
        self.blessed_threads = frozenset(
            BLESSED_COMPILE_THREADS if blessed_threads is None
            else blessed_threads)
        # dispatch-blessed threads (the serve loop): dispatching is
        # their JOB — never an off-thread-dispatch violation — but they
        # are NOT compile-blessed: a steady-phase compile attributed to
        # one is the micro-batcher failing its warm-program contract
        # and stays a hard violation (_record_compile below).
        self.dispatch_blessed = frozenset(BLESSED_DISPATCH_THREADS)
        self.phase = "warmup"
        #: the EFFECTIVE guard choice of the innermost steady() block —
        #: step_guard() consults this, so a steady(guard=False) caller
        #: is not re-armed by estimator-internal step guards
        self._steady_guard = False
        self.regions: dict = defaultdict(_new_counter)
        self.violations: list[dict] = []
        self.allow_counts: dict = defaultdict(int)
        self.dispatch_threads: set = set()
        self._primary_ident: int | None = None
        self._lock = make_lock("sanitize.state")

    # -- lifecycle -------------------------------------------------------
    def __enter__(self):
        global _ACTIVE
        _install_hooks()
        with _LOCK:
            if _ACTIVE is not None:
                raise RuntimeError(
                    f"a sanitizer ({_ACTIVE.label!r}) is already active: "
                    f"sanitize() scopes must not nest — use region() for "
                    f"finer attribution inside one scope"
                )
            self._primary_ident = threading.get_ident()
            _ACTIVE = self
        # evict pre-warmed C++ pjit fastpath entries so every dispatch in
        # this scope re-enters Python where the dispatch hook can see it
        # (the compiled-executable caches are separate and untouched — no
        # recompiles are induced; see _install_hooks)
        try:
            from jax._src import pjit as _pjit

            _pjit._cpp_pjit_cache_fun_only.clear()
            _pjit._cpp_pjit_cache_explicit_attributes.clear()
        except (ImportError, AttributeError):  # pragma: no cover
            pass  # dispatch detector degrades to cold-dispatch-only
        return self

    def __exit__(self, *exc):
        global _ACTIVE, _LAST_REPORT
        with _LOCK:
            if _ACTIVE is self:
                _ACTIVE = None
        _LAST_REPORT = self.report()
        return False

    @contextlib.contextmanager
    def steady(self, guard: bool | None = None):
        """Mark warmup complete for the enclosed block: compiles become
        violations, and (``guard`` True, the default from the
        constructor's ``guard_steady``) an implicit-transfer
        ``jax.transfer_guard("disallow")`` is armed on this thread —
        the :class:`~.sites.AllowSite` escapes re-allow the documented
        boundary syncs."""
        if guard is None:
            guard = self.guard_steady
        prev, prev_guard = self.phase, self._steady_guard
        self.phase = "steady"
        self._steady_guard = bool(guard)
        try:
            if guard:
                with jax.transfer_guard("disallow"):
                    yield self
            else:
                yield self
        finally:
            self.phase, self._steady_guard = prev, prev_guard

    # -- recording (hook callbacks; any thread) --------------------------
    def _record_compile(self, duration: float) -> None:
        reg = current_region()
        thread = threading.current_thread()
        steady = self.phase == "steady"
        if (threading.get_ident() != self._primary_ident
                and thread.name in self.blessed_threads):
            # the blessed compile-ahead thread: its compiles are its JOB
            # (pre-building the next bucket's program while the current
            # block computes) — attributed to their own ratcheted
            # counters, allowed in the steady phase, never a violation.
            # Any OTHER thread's steady compile below stays a hard zero.
            with self._lock:
                c = self.regions[reg]
                c["ahead_compiles"] += 1
                c["ahead_compile_s"] += float(duration)
                if steady:
                    c["steady_ahead_compiles"] += 1
            return
        with self._lock:
            c = self.regions[reg]
            c["compiles"] += 1
            c["compile_s"] += float(duration)
            if steady:
                c["steady_compiles"] += 1
        if (threading.get_ident() != self._primary_ident
                and thread.name in self.dispatch_blessed):
            # the serve loop: its load-time warmup compiles are legal
            # (the cold path's home is that thread), but a STEADY
            # compile means a request shape escaped the bucket ladder —
            # recorded as the same hard-zero violation a primary-thread
            # steady compile is, without the off-thread fail-fast raise
            # (the violation must reach the report, not kill the batch).
            if steady:
                self._violation(
                    "steady-state-compile", reg, thread.name,
                    f"XLA backend compile in region {reg!r} on the "
                    f"dispatch-blessed thread {thread.name!r} "
                    f"(phase=steady): the serve loop must only dispatch "
                    f"warm programs after load-time warmup")
            return
        off_thread = threading.get_ident() != self._primary_ident
        if off_thread or steady:
            kind = ("off-thread-compile" if off_thread
                    else "steady-state-compile")
            rec = self._violation(kind, reg, thread.name,
                                  f"XLA backend compile in region {reg!r} "
                                  f"on thread {thread.name!r} "
                                  f"(phase={self.phase})")
            if self.fail_fast and off_thread:
                # raise in the offending thread: a prefetch/stage worker
                # must never compile (design.md §8) — the pipeline
                # propagates this to the consumer at the block position
                raise CompileViolation(rec["detail"])

    def _record_dispatch(self, program: str) -> None:
        reg = current_region()
        thread = threading.current_thread()
        steady = self.phase == "steady"
        with self._lock:
            c = self.regions[reg]
            c["dispatches"] += 1
            if steady:
                c["steady_dispatches"] += 1
            self.dispatch_threads.add(thread.name)
        _metrics_registry().counter("dispatch.count").inc()
        if (threading.get_ident() != self._primary_ident
                and thread.name not in self.blessed_threads
                and thread.name not in self.dispatch_blessed):
            rec = self._violation(
                "off-thread-dispatch", reg, thread.name,
                f"device program {program!r} dispatched from second "
                f"thread {thread.name!r} (region {reg!r}): two threads "
                f"interleaving multi-device enqueues can deadlock the "
                f"runtime (design.md §7 rule 1)")
            if self.fail_fast:
                raise DispatchViolation(rec["detail"])

    def _record_d2h(self) -> None:
        reg = current_region()
        with self._lock:
            c = self.regions[reg]
            c["d2h_syncs"] += 1
            if self.phase == "steady":
                c["steady_d2h_syncs"] += 1
        _metrics_registry().counter("d2h.count").inc()

    def _record_allow(self, site_id: str) -> None:
        with self._lock:
            self.allow_counts[site_id] += 1

    def _violation(self, kind: str, reg: str, thread: str,
                   detail: str) -> dict:
        # returns the record so fail-fast raisers report THEIR
        # violation: re-reading violations[-1] after the append races a
        # concurrent thread's violation landing in between
        rec = {
            "kind": kind, "region": reg, "thread": thread,
            "detail": detail,
        }
        with self._lock:
            self.violations.append(rec)
        # span-tree + flight-recorder breadcrumb: a violation shows up
        # in the post-mortem ordered against the blocks/retries around
        # it, not just in the end-of-scope report
        _metrics_registry().counter("sanitize.violation", kind).inc()
        _obs_event("sanitize.violation", kind=kind, region=reg,
                   thread=thread)
        return rec

    # -- results ---------------------------------------------------------
    def report(self) -> dict:
        """Per-region counters + totals + violations, the
        ``diagnostics.sanitize_report()`` payload."""
        with self._lock:
            regions = {k: dict(v) for k, v in sorted(self.regions.items())}
            violations = list(self.violations)
            allow = dict(sorted(self.allow_counts.items()))
            threads = sorted(self.dispatch_threads)
        totals = _new_counter()
        for c in regions.values():
            for k in totals:
                totals[k] += c[k]
        return {
            "label": self.label,
            "phase": self.phase,
            "regions": regions,
            "totals": totals,
            "violations": violations,
            "allow_sites": allow,
            "dispatch_threads": threads,
        }

    def last_report(self) -> dict:
        return self.report()

    def assert_clean(self) -> None:
        """Raise with full attribution if any contract was violated:
        a steady-state compile, an off-thread compile or dispatch."""
        rep = self.report()
        if rep["violations"]:
            lines = [v["detail"] for v in rep["violations"]]
            raise CompileViolation(
                f"{len(lines)} sanitizer violation(s) in "
                f"{self.label!r}:\n  " + "\n  ".join(lines))


def sanitize(label: str = "sanitize", *, fail_fast: bool = True,
             guard_steady: bool = True, blessed_threads=None) -> Sanitizer:
    """Context manager: observe every compile, transfer, and dispatch in
    the enclosed block.  See the module docstring for the canonical
    warmup/steady shape."""
    return Sanitizer(label, fail_fast=fail_fast, guard_steady=guard_steady,
                     blessed_threads=blessed_threads)


@contextlib.contextmanager
def ambient(label: str):
    """Best-effort observe-only scope for the ``DASK_ML_TPU_SANITIZE=1``
    ambient mode: yields an entered fail-soft Sanitizer, or ``None``
    when another sanitizer is (or becomes) active — entry is
    atomic-or-skip, so two concurrent streams racing for the ambient
    slot both proceed and the loser simply goes unobserved, instead of
    one of them crashing on the no-nesting rule mid-fit."""
    s = Sanitizer(label, fail_fast=False)
    try:
        s.__enter__()
    except RuntimeError:  # lost the race / explicitly-scoped sanitizer
        yield None
        return
    try:
        yield s
    finally:
        s.__exit__(None, None, None)

"""graftsan CLI: run the smoke suite, ratchet against the committed
baseline.  Exit contract mirrors graftlint's (a crash can never read as
a verdict):

* 0 — suite ran, every invariant held, ratchet clean
* 1 — violations / new compiles / new transfers / stale baseline
* 2 — the sanitizer itself failed (bad args, unreadable baseline)

Usage::

    python -m dask_ml_tpu.sanitize                      # run + report
    python -m dask_ml_tpu.sanitize --baseline tools/sanitize_baseline.json
    python -m dask_ml_tpu.sanitize --write-baseline tools/sanitize_baseline.json
    python -m dask_ml_tpu.sanitize --workloads sgd_stream_d0,sgd_stream_d2
"""

from __future__ import annotations

import argparse
import json
import sys

from . import baseline as _baseline


def _parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m dask_ml_tpu.sanitize",
        description="runtime SPMD sanitizer smoke suite + ratchet",
    )
    p.add_argument("--workloads", default=None,
                   help="comma-separated subset (default: all)")
    p.add_argument("--baseline", metavar="PATH", default=None,
                   help="ratchet against this committed snapshot "
                        "(default: DASK_ML_TPU_SANITIZE_BASELINE, else "
                        "tools/sanitize_baseline.json when present)")
    p.add_argument("--write-baseline", metavar="PATH", default=None,
                   help="snapshot this run's metrics (then ratchet "
                        "against the fresh snapshot: bootstrap is clean "
                        "by construction)")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--list-workloads", action="store_true")
    return p


def main(argv=None) -> int:
    try:
        args = _parser().parse_args(argv)
    except SystemExit as e:  # argparse's bad-args path
        return 0 if (e.code in (0, None)) else 2

    from .smoke import WORKLOADS, run_smoke

    if args.list_workloads:
        for name in sorted(WORKLOADS):
            print(name)
        return 0

    names = None
    if args.workloads:
        names = [w.strip() for w in args.workloads.split(",") if w.strip()]
    if args.write_baseline and names is not None:
        # a subset snapshot would silently shadow the full-suite
        # baseline (every unselected workload would read as new on the
        # next gate, and surviving ceilings are calibrated against the
        # full suite's execution order) — refuse as a usage error
        print("error: --write-baseline requires the full suite "
              "(drop --workloads): a partial snapshot cannot be "
              "ratcheted against", file=sys.stderr)
        return 2
    try:
        results = run_smoke(names)
    except KeyError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    snap_path = args.write_baseline or args.baseline
    if args.write_baseline:
        # gate BEFORE writing: a snapshot may never carry a hard
        # invariant violation, so a violating run must leave the
        # committed file untouched (exit 1, nothing written) instead of
        # replacing it and only then failing
        probe = _baseline.compare({"workloads": dict(results)}, results)
        if probe["violations"]:
            for line in probe["violations"]:
                print(f"VIOLATION: {line}", file=sys.stderr)
            print("sanitize: refusing to write a violating baseline "
                  f"to {args.write_baseline} (file untouched)",
                  file=sys.stderr)
            return 1
        _baseline.write(args.write_baseline, _baseline.emit(results))
    if snap_path is None:
        snap_path = _baseline.default_path()

    delta = None
    if snap_path is not None:
        try:
            snap = _baseline.load(snap_path)
        except (OSError, ValueError) as e:
            print(f"error: cannot load baseline {snap_path}: {e}",
                  file=sys.stderr)
            return 2
        delta = _baseline.compare(snap, results, partial=names is not None)
    else:
        # no snapshot anywhere: hard invariants still gate
        delta = _baseline.compare({"workloads": dict(results)}, results,
                                  partial=names is not None)

    clean = _baseline.is_clean(delta)
    if args.format == "json":
        print(json.dumps({"workloads": results, "delta": delta,
                          "baseline": snap_path, "clean": clean},
                         indent=2, sort_keys=True))
    else:
        for name, m in sorted(results.items()):
            sites = ", ".join(f"{k}x{v}"
                              for k, v in sorted(m["allow_sites"].items()))
            print(f"{name}: warmup_compiles={m['warmup_compiles']} "
                  f"steady_compiles={m['steady_compiles']} "
                  f"steady_d2h={m['steady_d2h_syncs']} "
                  f"violations={m['violations']} "
                  f"threads={','.join(m['dispatch_threads'])}"
                  + (f" allow=[{sites}]" if sites else "")
                  + (f" ERROR={m['error']}" if m.get("error") else ""))
        for key in ("violations", "regressions", "new", "stale"):
            for line in delta[key]:
                print(f"{key.upper()}: {line}")
        print("sanitize: "
              + ("clean" if clean else "FAILED")
              + (f" (vs {snap_path})" if snap_path else " (no baseline)"))
    return 0 if clean else 1


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())

"""Sanitizer smoke workloads: the measured, gated compile/transfer
contract of every streamed-fit hot path.

Each workload is ONE sanitization scope with the canonical
warmup→steady split: the warmup round pays state init + XLA compiles,
the steady round streams the *same shapes into the same model* and must
therefore compile zero new programs, dispatch from one thread, and
perform no implicit transfers (the steady phase runs under
``jax.transfer_guard("disallow")`` for the staged-protocol estimators;
whole-array fits re-initialize state per ``fit`` call, which is
legitimate warmup-class work, so they run ``guard=False`` and are held
to the compile/dispatch contract only).

The suite exists to be *committed*: ``tools/sanitize_baseline.json``
snapshots each workload's metrics, ``tools/lint.sh --sanitize`` (and
tests/test_sanitize.py in tier-1) re-runs the suite and ratchets
against the snapshot — see :mod:`.baseline` for the failure semantics.
Data shapes are deliberately tiny (the contract is about *counts*, not
throughput) and fixed-seed (the compile set must be deterministic)."""

from __future__ import annotations

import os

import numpy as np

from .core import sanitize

__all__ = ["WORKLOADS", "run_workload", "run_smoke", "metrics_from"]

_SEED = 7
_BLOCKS = 4  # per round (warmup round, then steady round)


def _class_blocks(n=32, d=4, blocks=_BLOCKS, offset=0):
    rng = np.random.RandomState(_SEED + offset)
    out = []
    for _ in range(blocks):
        X = rng.normal(size=(n, d)).astype(np.float32)
        y = (X[:, 0] + 0.1 * rng.normal(size=n) > 0).astype(np.int32)
        out.append((X, y))
    return out


def _row_blocks(n=16, d=4, blocks=_BLOCKS, offset=0):
    rng = np.random.RandomState(_SEED + offset)
    return [rng.normal(size=(n, d)).astype(np.float32)
            for _ in range(blocks)]


def metrics_from(s, error: str | None = None,
                 transfer_error: bool = False) -> dict:
    """Reduce a Sanitizer report to the committed per-workload metrics."""
    rep = s.report()
    t = rep["totals"]
    return {
        "warmup_compiles": t["compiles"] - t["steady_compiles"],
        "steady_compiles": t["steady_compiles"],
        # blessed compile-ahead thread compiles: attributed + ratcheted
        # separately (a steady one is ALLOWED — that thread's job —
        # but the count is still a ceiling, not a free pass)
        "ahead_compiles": t["ahead_compiles"],
        "steady_ahead_compiles": t["steady_ahead_compiles"],
        "steady_d2h_syncs": t["steady_d2h_syncs"],
        "violations": len(rep["violations"]),
        "transfer_errors": 1 if transfer_error else 0,
        "allow_sites": dict(rep["allow_sites"]),
        "dispatch_threads": rep["dispatch_threads"],
        **({"error": error} if error else {}),
    }


def _run_streamed(label, make_model, blocks_fn, depth, *, fit_kwargs=None,
                  paired=True):
    """warmup round then guarded steady round of ``stream_partial_fit``
    over fresh same-shaped blocks into the SAME model.

    The compile-ahead queue is DRAINED at both phase boundaries: the
    sanitizer's monitoring listener attributes a compile to whichever
    scope is active when the blessed thread finishes it, so an
    un-waited warm build (e.g. one whose signature no consumer ever
    dispatched) completing late would land its ahead_compiles count in
    the NEXT workload's books and trip that workload's committed
    ceiling on a loaded box."""
    from .. import programs
    from ..pipeline import stream_partial_fit

    model = make_model()
    with sanitize(label=label) as s:
        stream_partial_fit(
            model,
            blocks_fn(offset=0) if paired
            else [(b, None) for b in blocks_fn(offset=0)],
            depth=depth, fit_kwargs=fit_kwargs, label=label,
        )
        programs.drain_ahead()
        with s.steady():
            stream_partial_fit(
                model,
                blocks_fn(offset=1) if paired
                else [(b, None) for b in blocks_fn(offset=1)],
                depth=depth, fit_kwargs=fit_kwargs, label=label,
            )
            programs.drain_ahead()
    return s


def _wl_sgd_stream(depth):
    from ..linear_model import SGDClassifier

    return _run_streamed(
        f"sgd_stream_d{depth}",
        lambda: SGDClassifier(random_state=0),
        _class_blocks, depth,
        fit_kwargs={"classes": np.array([0, 1])},
    )


def _wl_mbk_stream(depth):
    from ..cluster import MiniBatchKMeans

    return _run_streamed(
        f"mbk_stream_d{depth}",
        lambda: MiniBatchKMeans(n_clusters=3, random_state=0),
        _row_blocks, depth, paired=False,
    )


def _wl_ipca_stream(depth):
    from ..decomposition import IncrementalPCA

    return _run_streamed(
        f"ipca_stream_d{depth}",
        lambda: IncrementalPCA(n_components=2),
        _row_blocks, depth, paired=False,
    )


def _wl_sgd_bucket_ahead():
    """Bucket-crossing stream with the compile-ahead worker ON: the
    steady round's blocks land in a NEW bucket (300 rows → 1024) whose
    step program the ``_pf_stage`` warm hook pre-builds on the blessed
    ``dask-ml-tpu-compile-ahead`` thread — ``steady_compiles`` stays a
    hard zero while ``steady_ahead_compiles`` ratchets NONZERO in the
    committed baseline: the compile is attributed, not suppressed.
    (Inside a warm pytest process the 1024-bucket program may already
    be cached, in which case the ahead counts read 0 — below the
    ceiling, which passes; the cold ``python -m dask_ml_tpu.sanitize``
    run that writes the baseline observes the full count.)"""
    from ..linear_model import SGDClassifier
    from ..pipeline import stream_partial_fit
    from .. import programs

    overrides = {"DASK_ML_TPU_BUCKET": "auto",
                 "DASK_ML_TPU_COMPILE_AHEAD": "on"}
    saved = {k: os.environ.get(k) for k in overrides}
    os.environ.update(overrides)
    try:
        model = SGDClassifier(random_state=0)
        with sanitize(label="sgd_bucket_ahead") as s:
            stream_partial_fit(
                model, _class_blocks(n=32, offset=0), depth=2,
                fit_kwargs={"classes": np.array([0, 1])},
                label="sgd_bucket_ahead",
            )
            programs.drain_ahead()
            with s.steady():
                stream_partial_fit(
                    model, _class_blocks(n=300, offset=1), depth=2,
                    fit_kwargs={"classes": np.array([0, 1])},
                    label="sgd_bucket_ahead",
                )
                programs.drain_ahead()
        return s
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _wl_kmeans_fit():
    from ..cluster import KMeans

    rng = np.random.RandomState(_SEED)
    X = rng.normal(size=(64, 4)).astype(np.float32)
    with sanitize(label="kmeans_fit") as s:
        KMeans(n_clusters=3, max_iter=5, random_state=0).fit(X)
        # whole-array fit: each fit() re-inits device state (warmup-class
        # work), so the steady contract here is compile/dispatch only
        with s.steady(guard=False):
            KMeans(n_clusters=3, max_iter=5, random_state=0).fit(X)
    return s


def _wl_kmeans_fit_ckpt():
    """The SEGMENTED Lloyd path (fit_checkpoint set): every segment
    boundary passes through the ``kmeans-segment-sync`` AllowSite, so
    the committed baseline ratchets a NONZERO boundary-sync count — a
    regression that syncs per iteration instead of per segment fails
    the allow-site ceiling, not just a docstring."""
    import shutil
    import tempfile

    from ..cluster import KMeans
    from ..resilience import FitCheckpoint

    rng = np.random.RandomState(_SEED)
    X = rng.normal(size=(96, 4)).astype(np.float32)
    d = tempfile.mkdtemp(prefix="graftsan-ckpt-")
    try:
        def _fit():
            KMeans(
                n_clusters=3, max_iter=64, tol=0.0, random_state=0,
                fit_checkpoint=FitCheckpoint(
                    os.path.join(d, "ck"), every_n_iters=32),
            ).fit(X)

        with sanitize(label="kmeans_fit_ckpt") as s:
            _fit()
            with s.steady(guard=False):
                _fit()
        return s
    finally:
        shutil.rmtree(d, ignore_errors=True)


def _wl_mbk_fit():
    """MiniBatchKMeans whole-array fit: the epoch loop passes the
    ``mbk-epoch-sync`` AllowSite once per epoch — ratcheted nonzero in
    the baseline for the same reason as the kmeans ckpt workload."""
    from ..cluster import MiniBatchKMeans

    rng = np.random.RandomState(_SEED)
    X = rng.normal(size=(96, 4)).astype(np.float32)
    with sanitize(label="mbk_fit") as s:
        MiniBatchKMeans(n_clusters=3, max_iter=4, random_state=0).fit(X)
        with s.steady(guard=False):
            MiniBatchKMeans(n_clusters=3, max_iter=4, random_state=0).fit(X)
    return s


def _wl_search_concurrent():
    """The concurrent search control plane under an armed sanitizer
    (design.md §17): a small multi-bracket Hyperband fit whose brackets
    interleave as coroutines on the blessed ``dask-ml-tpu-search``
    dispatch thread, units streaming through per-unit staged feeds and
    homogeneous survivors re-packing into vmapped cohorts.  The warmup
    round (the first fit) pays every program — the packed step per
    cohort size, packed accuracy, the single-model step + score — and
    the steady round re-runs the IDENTICAL search (same seeds, same
    shapes, same bracket schedule): zero new compiles, and every
    dispatch attributed to a blessed thread (the orchestrator loop) or
    MainThread — a rogue-thread dispatch is a hard violation.  The
    steady phase runs ``guard=False`` like the whole-fit workloads:
    each fit re-creates its models (state init + H2D staging are
    warmup-class work); the compile/dispatch contract is the gate."""
    from ..linear_model import SGDClassifier
    from ..model_selection import HyperbandSearchCV

    rng = np.random.RandomState(_SEED)
    X = rng.normal(size=(256, 4)).astype(np.float32)
    y = (X[:, 0] + 0.1 * rng.normal(size=256) > 0).astype(np.int32)

    def _fit():
        from .. import programs

        HyperbandSearchCV(
            SGDClassifier(random_state=0),
            {"alpha": [1e-4, 3e-4, 1e-3, 3e-3]},
            max_iter=4, random_state=0, test_size=0.25, chunk_size=64,
        ).fit(X, y, classes=np.array([0, 1]))
        programs.drain_ahead()

    with sanitize(label="search_concurrent") as s:
        _fit()
        with s.steady(guard=False):
            _fit()
    return s


def _wl_ingest_parallel():
    """A steady fit fed by a 4-reader sharded dataset (design.md §18):
    the merged multi-reader stream re-serializes into the ONE prefetch
    worker, so the device plane must look exactly like the classic
    depth-2 stream — and the reader threads are HOST-ONLY by declared
    contract (``dask-ml-tpu-data-reader`` ∈
    ``_spmd.HOST_ONLY_THREAD_NAMES``): a compile, program dispatch, or
    transfer attributed to a reader is a hard violation, runtime-
    verified here, not taken on faith.  Warmup epoch pays the compiles;
    the steady epoch streams a DIFFERENT key-derived permutation of the
    same bucket-aligned 256-row blocks (shuffle changes order, never
    shape) — zero new programs, zero pad copies
    (``bucket.padded_blocks`` stays 0: the format's chunks are ladder
    rungs)."""
    import shutil
    import tempfile

    from .. import data as _data
    from .. import programs
    from ..linear_model import SGDClassifier
    from ..pipeline import stream_partial_fit

    rng = np.random.RandomState(_SEED)
    X = rng.normal(size=(2048, 4)).astype(np.float32)
    y = (X[:, 0] + 0.1 * rng.normal(size=2048) > 0).astype(np.int32)
    d = tempfile.mkdtemp(prefix="graftsan-ds-")
    try:
        _data.write_dataset(d, X, y, shards=4, block_rows=256)
        model = SGDClassifier(random_state=0)

        def _round(epoch):
            ds = _data.ShardedDataset(d, key=_SEED, readers=4,
                                      label="ingest_parallel")
            stream_partial_fit(
                model, ds.iter_blocks(epoch=epoch), depth=2,
                fit_kwargs={"classes": np.array([0, 1])},
                label="ingest_parallel")

        with sanitize(label="ingest_parallel") as s:
            _round(0)
            programs.drain_ahead()
            with s.steady():
                _round(1)
                programs.drain_ahead()
        return s
    finally:
        shutil.rmtree(d, ignore_errors=True)


def _wl_glm_fit():
    from ..linear_model import LogisticRegression

    rng = np.random.RandomState(_SEED)
    X = rng.normal(size=(64, 4)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.int32)
    with sanitize(label="glm_fit") as s:
        LogisticRegression(max_iter=8).fit(X, y)
        with s.steady(guard=False):
            LogisticRegression(max_iter=8).fit(X, y)
    return s


WORKLOADS = {
    "sgd_bucket_ahead": _wl_sgd_bucket_ahead,
    "sgd_stream_d0": lambda: _wl_sgd_stream(0),
    "sgd_stream_d2": lambda: _wl_sgd_stream(2),
    "mbk_stream_d0": lambda: _wl_mbk_stream(0),
    "mbk_stream_d2": lambda: _wl_mbk_stream(2),
    "ipca_stream_d0": lambda: _wl_ipca_stream(0),
    "ipca_stream_d2": lambda: _wl_ipca_stream(2),
    "kmeans_fit": _wl_kmeans_fit,
    "kmeans_fit_ckpt": _wl_kmeans_fit_ckpt,
    "mbk_fit": _wl_mbk_fit,
    "glm_fit": _wl_glm_fit,
    "search_concurrent": _wl_search_concurrent,
    "ingest_parallel": _wl_ingest_parallel,
}


def run_workload(name: str) -> dict:
    """Run one workload; a sanitizer/guard raise becomes an ``error``
    metric (and a hard failure in the ratchet), never a crash of the
    suite."""
    from .core import CompileViolation, DispatchViolation

    fn = WORKLOADS[name]
    try:
        s = fn()
    except (CompileViolation, DispatchViolation) as e:
        return {"warmup_compiles": 0, "steady_compiles": 0,
                "ahead_compiles": 0, "steady_ahead_compiles": 0,
                "steady_d2h_syncs": 0, "violations": 1,
                "transfer_errors": 0, "allow_sites": {},
                "dispatch_threads": [], "error": f"{type(e).__name__}: {e}"}
    except Exception as e:  # transfer-guard XlaRuntimeError et al.
        transfer = "Disallowed" in str(e) and "transfer" in str(e)
        return {"warmup_compiles": 0, "steady_compiles": 0,
                "ahead_compiles": 0, "steady_ahead_compiles": 0,
                "steady_d2h_syncs": 0, "violations": 0 if transfer else 1,
                "transfer_errors": 1 if transfer else 0, "allow_sites": {},
                "dispatch_threads": [], "error": f"{type(e).__name__}: {e}"}
    return metrics_from(s)


def run_smoke(names=None) -> dict:
    """Run the (selected) workloads and return {name: metrics}."""
    names = list(WORKLOADS) if names is None else list(names)
    unknown = [n for n in names if n not in WORKLOADS]
    if unknown:
        raise KeyError(f"unknown workload(s): {', '.join(unknown)}")
    return {name: run_workload(name) for name in names}

"""Runtime-verified transfer allow-sites.

Every ``host-sync-loop`` suppression in the package is a *claim*: "this
sync is an intentional boundary, not a leak".  graftlint checks the
claim's justification exists; the sanitizer checks the claim itself at
runtime.  An :class:`AllowSite` is the bridge:

* it is declared module-level next to the code it covers, **citing the
  graftlint suppression fingerprint** it runtime-verifies (the 16-hex
  id from ``tools/sanitize_baseline.json``'s sibling,
  ``tools/graftlint_baseline.json``) — tests/test_sanitize.py fails if
  a citation does not resolve to a suppressed finding in the committed
  baseline, so a dead suppression cannot keep a live runtime escape;
* entering :meth:`AllowSite.allow` under an active sanitizer nests an
  explicit ``jax.transfer_guard("allow")`` (the ONLY sanctioned escape
  from the steady-phase ``disallow``) and counts the pass, so the
  per-workload baseline ratchets boundary-sync *counts*, not just their
  existence;
* with no sanitizer active the context is a no-op — production fits pay
  one attribute check.

The ``thread-dispatch`` suppressions have no allow-site: their
runtime verification is the dispatch detector itself (the suppressed
threads must simply never appear in ``dispatch_threads``)."""

from __future__ import annotations

import contextlib

import jax

from . import core as _core

__all__ = ["AllowSite", "registered_sites"]

_REGISTRY: dict = {}


class AllowSite:
    """One documented boundary-sync escape.

    Args:
      site_id: stable short name, unique per process
        (``"kmeans-segment-sync"``).
      rule: the graftlint rule the cited suppression belongs to
        (``"host-sync-loop"``).
      cites: the 16-hex baseline fingerprint(s) of that suppression
        (``tools/graftlint_baseline.json`` ``findings[].fingerprint``) —
        a string or tuple of strings when one statement carries several
        findings.
      note: one line of why the sync is a legitimate boundary.
    """

    __slots__ = ("site_id", "rule", "cites", "note")

    def __init__(self, site_id: str, *, rule: str, cites, note: str):
        self.site_id = site_id
        self.rule = rule
        self.cites = (cites,) if isinstance(cites, str) else tuple(cites)
        self.note = note
        if site_id in _REGISTRY and _REGISTRY[site_id] is not self:
            raise ValueError(f"duplicate AllowSite id {site_id!r}")
        _REGISTRY[site_id] = self

    @contextlib.contextmanager
    def allow(self):
        """Explicitly-allowed transfer window: counts the pass and lifts
        the steady-phase guard for exactly the enclosed statements."""
        s = _core.active_sanitizer()
        if s is None:
            yield
            return
        s._record_allow(self.site_id)
        with jax.transfer_guard("allow"):
            yield

    def __repr__(self):  # pragma: no cover - debugging aid
        return (f"AllowSite({self.site_id!r}, rule={self.rule!r}, "
                f"cites={self.cites!r})")


def registered_sites() -> dict:
    """All AllowSites constructed in this process, by id.  Estimator
    modules declare their sites at import time, so importing the package
    surface (``import dask_ml_tpu``) materializes the full registry."""
    return dict(_REGISTRY)

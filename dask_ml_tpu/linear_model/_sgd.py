"""Device-native incremental estimators: ``SGDClassifier`` / ``SGDRegressor``.

The reference has no in-repo SGD — ``Incremental`` and the adaptive searches
(``dask_ml/_partial.py :: fit``, ``model_selection/_incremental.py ::
_partial_fit``) wrap *sklearn's* Cython ``SGDClassifier`` and train it on the
host, one data block per call.  On TPU that design leaves the accelerator
idle during the framework's flagship adaptive-search story, so these
estimators are the TPU-native workhorse instead:

* model state (``coef``, ``intercept``, step counter) lives on device as a
  pytree; ``partial_fit`` is ONE fused XLA program over the whole block —
  a gemm for the margins (MXU), a masked-mean gradient, and the parameter
  update, with the state buffers **donated** so the update is in-place in
  HBM;
* the update step is a *pure function* of (state, batch, hyperparams) with
  hyperparameters as traced scalars — so ``jax.vmap`` over a stacked model
  axis trains many configurations in one program (multi-model packing,
  SURVEY.md §2.2 "model-parallel search") with zero recompilation across
  configs;
* blocks are padded to a small set of bucket sizes so streaming variable-
  length chunks does not recompile per shape;
* multiclass is one-vs-all in a single ``[d, n_classes]`` coefficient
  matrix — one gemm instead of n_classes separate binary problems (the
  sklearn semantics, the MXU layout).

Unlike sklearn's per-sample updates, each ``partial_fit`` applies ONE
minibatch gradient step per block (the natural unit on a vector machine),
and ``fit``'s default is one FULL-batch step per epoch — i.e. gradient
descent with the SGD learning-rate schedule.  ``n_iter_`` counts epochs and
``tol`` compares whole-data epoch losses, so both diverge from sklearn's
per-sample accounting by design; pass ``batch_size=B`` to ``fit`` via the
constructor for scanned minibatch epochs (``n_pad/B`` device-side steps per
epoch) that track sklearn's trajectory more closely.  Convergence parity
with sklearn is asserted at the accuracy level in tests, matching the
reference's loose-rtol pattern for iterative solvers.
"""

from __future__ import annotations

import numbers
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

from ..base import ClassifierMixin, RegressorMixin, TPUEstimator
from ..core.sharded import ShardedRows
from ..utils import safe_denominator
from .. import programs as _programs
from .. import sanitize as _san

__all__ = ["SGDClassifier", "SGDRegressor"]

# Streamed blocks are padded up to one of these row counts (then to the next
# multiple of the largest) so a stream of ragged chunk sizes compiles at most
# len(_BUCKETS)+ programs per (d, k) shape.  The policy now lives in
# dask_ml_tpu/programs/bucket.py behind the DASK_ML_TPU_BUCKET knob
# (off / pow2 / explicit ladders); these names stay as the historical
# entry points every caller and test binds.
_BUCKETS = _programs.DEFAULT_BUCKETS
_bucket_rows = _programs.bucket_rows
_bucket_pad = _programs.pad_block

#: Default streaming block size: a bucket entry, so default-chunk streams
#: pad zero extra rows per partial_fit (wrappers.Incremental, _partial.fit)
DEFAULT_STREAM_CHUNK = _BUCKETS[3]

_CLS_LOSSES = ("log_loss", "hinge", "squared_hinge", "modified_huber")
_REG_LOSSES = ("squared_error", "huber")
_PENALTIES = ("l2", "l1", "elasticnet", None)
_SCHEDULES = ("constant", "optimal", "invscaling", "adaptive")

#: the traced-scalar hyperparameter keys every step signature carries
#: (order-free: dicts key the program-cache signature sorted)
_HYPER_KEYS = ("alpha", "eta0", "power_t", "t0", "l1_ratio", "epsilon",
               "eta_scale")


def _margin_losses(loss: str, margins, ysigned):
    """Per-row, per-class loss and dLoss/dMargin for ±1 targets.

    ``margins``/``ysigned``: [B, K].  Returns (loss [B,K], grad [B,K]).
    """
    z = ysigned * margins
    if loss == "log_loss":
        ell = jnp.logaddexp(0.0, -z)
        dz = -jax.nn.sigmoid(-z)
    elif loss == "hinge":
        ell = jnp.maximum(0.0, 1.0 - z)
        dz = jnp.where(z < 1.0, -1.0, 0.0)
    elif loss == "squared_hinge":
        h = jnp.maximum(0.0, 1.0 - z)
        ell = h * h
        dz = -2.0 * h
    elif loss == "modified_huber":
        h = jnp.maximum(0.0, 1.0 - z)
        ell = jnp.where(z >= -1.0, h * h, -4.0 * z)
        dz = jnp.where(z >= -1.0, -2.0 * h, -4.0)
    else:  # pragma: no cover
        raise ValueError(f"unknown classifier loss {loss!r}")
    return ell, dz * ysigned


def _regression_losses(loss: str, pred, y, epsilon):
    r = pred - y
    if loss == "squared_error":
        return 0.5 * r * r, r
    if loss == "huber":
        a = jnp.abs(r)
        ell = jnp.where(a <= epsilon, 0.5 * r * r, epsilon * (a - 0.5 * epsilon))
        grad = jnp.where(a <= epsilon, r, epsilon * jnp.sign(r))
        return ell, grad
    raise ValueError(f"unknown regressor loss {loss!r}")  # pragma: no cover


def _learning_rate(schedule: str, t, hyper):
    if schedule == "constant":
        return hyper["eta0"]
    if schedule == "adaptive":
        # sklearn semantics: eta stays at eta0 until the epoch loop sees
        # a plateau, then divides by 5 — the division arrives as a traced
        # eta_scale in hyper, so no recompile per adjustment
        return hyper["eta0"] * hyper["eta_scale"]
    if schedule == "optimal":
        # sklearn's heuristic: eta = 1 / (alpha * (t0 + t)) with
        # t0 = 1 / (alpha * eta0-like init); we fold t0 into hyper.
        return 1.0 / (hyper["alpha"] * (hyper["t0"] + t))
    if schedule == "invscaling":
        return hyper["eta0"] / jnp.power(t + 1.0, hyper["power_t"])
    raise ValueError(f"unknown learning_rate {schedule!r}")  # pragma: no cover


def sgd_init(n_features: int, n_outputs: int, dtype=jnp.float32):
    """Fresh device state pytree.  ``n_outputs``: n_classes for OvA
    classification (1 for binary would lose the ±class symmetry — binary
    uses K=1 column with ±1 targets), or 1 for regression."""
    return {
        "coef": jnp.zeros((n_features, n_outputs), dtype=dtype),
        "intercept": jnp.zeros((n_outputs,), dtype=dtype),
        "t": jnp.zeros((), dtype=dtype),
    }


def sgd_step(state, xb, yb, mask, hyper, *, loss, penalty, schedule,
             fit_intercept=True):
    """One minibatch SGD step; pure, jit/vmap-safe.

    Args:
      state: pytree from :func:`sgd_init`.
      xb: [B, d] batch rows (padding rows allowed).
      yb: classifier: [B, K] ±1 one-vs-all targets; regressor: [B, 1].
      mask: [B] 1.0 for real rows.
      hyper: dict of traced scalars — alpha, eta0, power_t, t0, l1_ratio,
        epsilon.
      loss/penalty/schedule: static strings selecting the compiled branches.
    Returns (new_state, mean_loss).
    """
    coef, intercept, t = state["coef"], state["intercept"], state["t"]
    margins = xb @ coef + intercept  # [B, K]
    if loss in _CLS_LOSSES:
        ell, dmarg = _margin_losses(loss, margins, yb)
    else:
        ell, dmarg = _regression_losses(loss, margins, yb, hyper["epsilon"])
    m = mask[:, None].astype(margins.dtype)
    count = safe_denominator(jnp.sum(mask))
    mean_loss = jnp.sum(ell * m) / count
    dmarg = dmarg * m / count
    gcoef = xb.T @ dmarg  # [d, K] — the other MXU gemm
    gint = jnp.sum(dmarg, axis=0)

    alpha = hyper["alpha"]
    if penalty == "l2":
        gcoef = gcoef + alpha * coef
    elif penalty == "l1":
        gcoef = gcoef + alpha * jnp.sign(coef)
    elif penalty == "elasticnet":
        l1r = hyper["l1_ratio"]
        gcoef = gcoef + alpha * (l1r * jnp.sign(coef) + (1.0 - l1r) * coef)

    eta = _learning_rate(schedule, t, hyper)
    new = {
        "coef": coef - eta * gcoef,
        "intercept": intercept - eta * gint if fit_intercept else intercept,
        "t": t + 1.0,
    }
    return new, mean_loss


# One compiled program per (loss, penalty, schedule, fit_intercept, shapes);
# state donated so the update happens in place in HBM.  Routed through the
# central program cache (design.md §12): shape-bucketed streams resolve to
# already-compiled executables and the compile-ahead worker can pre-build
# the next bucket's program while the current block computes.
_jitted_step = _programs.cached_program(
    sgd_step, name="sgd.step",
    static_argnames=("loss", "penalty", "schedule", "fit_intercept"),
    donate_argnames=("state",),
)


def sgd_epoch(state, xs, ys, ms, hyper, *, loss, penalty, schedule,
              fit_intercept=True):
    """One epoch = ``lax.scan`` of :func:`sgd_step` over the minibatch axis.

    ``xs``/``ys``/``ms`` carry shape ``(B, n_mb, ...)``: minibatch ``i`` is
    the stride-``n_mb`` row interleave ``rows[i::n_mb]`` (see
    :func:`_minibatch_views`), indexed out with ``dynamic_index_in_dim`` on
    the UNSHARDED axis 1 so a row-sharded stack needs no data movement and
    each step's gradient still spans every shard (GSPMD inserts the psum
    exactly as in the full-batch step).  Returns (state, mean epoch loss).
    """

    def body(st, i):
        xb = jax.lax.dynamic_index_in_dim(xs, i, axis=1, keepdims=False)
        yb = jax.lax.dynamic_index_in_dim(ys, i, axis=1, keepdims=False)
        mb = jax.lax.dynamic_index_in_dim(ms, i, axis=1, keepdims=False)
        st, step_loss = sgd_step(
            st, xb, yb, mb, hyper, loss=loss, penalty=penalty,
            schedule=schedule, fit_intercept=fit_intercept,
        )
        return st, (step_loss, jnp.sum(mb))

    n_mb = xs.shape[1]
    state, (losses, counts) = jax.lax.scan(body, state, jnp.arange(n_mb))
    # row-count-weighted mean: bucket padding makes minibatches carry
    # unequal numbers of real rows, and an unweighted mean would deflate
    # the epoch loss the tol stopper compares
    total = safe_denominator(jnp.sum(counts))
    return state, jnp.sum(losses * counts) / total


_jitted_epoch = _programs.cached_program(
    sgd_epoch, name="sgd.epoch",
    static_argnames=("loss", "penalty", "schedule", "fit_intercept"),
    donate_argnames=("state",),
)


def _eval_loss_fn(state, xb, yb, mask, hyper, *, loss):
    """Masked mean loss of the CURRENT state over ``mask`` rows — the
    per-epoch validation metric for ``early_stopping``.  This is an EXTRA
    forward pass over all rows each epoch (~+50% epoch FLOPs on the
    full-batch path); accepted so ``sgd_step``'s signature stays shared
    with the packing/ensemble planes, and only paid when early_stopping
    is on."""
    margins = xb @ state["coef"] + state["intercept"]
    if loss in _CLS_LOSSES:
        ell, _ = _margin_losses(loss, margins, yb)
    else:
        ell, _ = _regression_losses(loss, margins, yb, hyper["epsilon"])
    m = mask[:, None].astype(margins.dtype)
    return jnp.sum(ell * m) / safe_denominator(jnp.sum(mask))


# graftlint: disable=donation-miss -- output is one scalar; state/xb/yb stay live in the caller (the epoch step reads state right after)
_eval_loss = _programs.cached_program(
    _eval_loss_fn, name="sgd.eval_loss", static_argnames=("loss",),
)


def _row_shard_count(arr) -> int:
    """Device count along the row axis of ``arr``'s sharding (1 when the
    array is unsharded / on one device)."""
    try:
        spec = arr.sharding.spec  # NamedSharding
        axis = spec[0] if len(spec) else None
        if axis is None:
            return 1
        mesh = arr.sharding.mesh
        if isinstance(axis, (tuple, list)):
            out = 1
            for a in axis:
                out *= mesh.shape[a]
            return out
        return mesh.shape[axis]
    except AttributeError:
        return 1


def _minibatch_views(est, xb, yb, mask, n_real=None):
    """(xs, ys, ms) minibatch stacks for ``fit``, or None for the
    full-batch path.

    ``batch_size`` padded rows per step (global, across shards; the real
    rows per step average ``n_real/n_mb`` since the bucket-pad tail is
    interleaved too).  The padded row count splits as (B, n_mb) — a FREE
    row-major reshape, no copy — so minibatch ``i`` is the
    stride-``n_mb`` interleave ``rows[i::n_mb]``: every shard contributes
    ``local/n_mb`` rows to every minibatch, which keeps the row sharding
    intact (``n_mb`` is clamped to a divisor of the per-shard row count)
    and doubles as a deterministic mixing of the input order.  Pad rows
    carry mask 0 and spread across the minibatches; ``n_mb`` is further
    capped at ``n_real`` so every minibatch holds at least one real row
    (row ``i < n_real`` is always in minibatch ``i``) — no pure
    weight-decay steps on padding-only batches.
    """
    bs = getattr(est, "batch_size", None)
    n_pad = int(xb.shape[0])
    if bs is None:
        return None
    bs = int(bs)
    # full-batch cutoff on the REAL row count: a batch_size >= n_samples
    # means one step per epoch regardless of how far the bucket padding
    # stretched n_pad
    if bs >= (int(n_real) if n_real is not None else n_pad):
        return None
    local = n_pad // max(_row_shard_count(xb), 1)
    n_mb = max(n_pad // bs, 1)
    if n_real is not None:
        n_mb = min(n_mb, int(n_real))
    while n_mb > 1 and local % n_mb:
        n_mb -= 1
    if n_mb <= 1:
        return None
    B = n_pad // n_mb
    xs = xb.reshape(B, n_mb, *xb.shape[1:])
    ys = yb.reshape(B, n_mb, *yb.shape[1:])
    ms = mask.reshape(B, n_mb)
    return xs, ys, ms


class EpochStopper:
    """sklearn's stopping rule, shared by every epoch loop (fit,
    blockwise-ensemble packed fits): stop only after ``n_iter_no_change``
    CONSECUTIVE epochs fail to improve the best loss by ``tol`` — a single
    oscillating epoch (constant LR, large eta0) must not halt training.
    ``update`` returns True when training should stop; with ``tol=None``
    it never syncs the loss (callers should skip the host pull)."""

    def __init__(self, tol, patience: int = 5):
        self.tol = tol
        self.patience = patience
        self.best = np.inf
        self.bad = 0

    @property
    def active(self) -> bool:
        return self.tol is not None

    def update(self, cur: float) -> bool:
        if not self.active:
            return False
        if cur > self.best - self.tol:
            self.bad += 1
            if self.bad >= self.patience:
                return True
        else:
            self.bad = 0
        self.best = min(self.best, cur)
        return False

    def reset_patience(self) -> None:
        """Clear the no-improvement counter but KEEP the best loss —
        sklearn's adaptive-eta rule: after an eta/5 reduction the new
        regime must still beat the pre-reduction best, or the next
        plateau fires against it."""
        self.bad = 0


def _run_epochs(est, xb, yb, mask, n_real=None) -> int:
    """Epoch loop for ``fit``.

    Default (``batch_size=None``): one fused FULL-batch gradient step per
    epoch — i.e. plain gradient descent with the SGD learning-rate
    schedule, NOT sklearn's per-sample updates; ``n_iter_``/``tol`` count
    these whole-data epochs.  With ``batch_size=B`` each epoch is one
    scanned XLA program of ``n_pad/B`` minibatch steps over stride
    interleaves of the (shard-resident) rows — closer to sklearn's
    semantics and usually faster to converge per epoch on large n.  The
    scalar epoch loss syncs to host only when a tol check is active.

    ``early_stopping=True`` carves ``validation_fraction`` of the rows
    out by MASK (a per-row Bernoulli split on device — zero data
    movement, sharded-input safe): training runs on the remaining rows
    and the stopping rule watches the held-out masked mean LOSS (not
    sklearn's score — a documented divergence that serves both tasks
    with one fused forward pass).  ``learning_rate='adaptive'`` follows
    sklearn: on each plateau eta divides by 5 (a traced eta_scale — no
    recompile) until it falls below 1e-6.
    """
    from ..utils import check_max_iter

    check_max_iter(est.max_iter)
    hyper = est._hyper()
    adaptive = est.learning_rate == "adaptive"
    early = bool(getattr(est, "early_stopping", False))
    train_mask, val_mask = mask, None
    if early:
        from ..core.prng import as_key

        frac = float(getattr(est, "validation_fraction", 0.1))
        sel = (
            jax.random.uniform(
                as_key(getattr(est, "random_state", None)), (xb.shape[0],)
            )
            < frac
        ).astype(mask.dtype)
        val_mask = mask * sel
        train_mask = mask * (1.0 - sel)
        if float(jnp.sum(val_mask)) == 0.0:  # degenerate tiny input
            early, train_mask, val_mask = False, mask, None
    stop = EpochStopper(est.tol, getattr(est, "n_iter_no_change", 5))

    from ..resilience.preemption import active_watcher, check_preemption
    from ..resilience.testing import maybe_fault

    ckpt = getattr(est, "fit_checkpoint", None)
    epoch0 = 0
    snap = ckpt.load_if_matches(est) if ckpt is not None else None
    if snap is not None:
        # resume mid-fit: the snapshot replaces the fresh state installed
        # by _ensure_state, and the stopping rule + adaptive-eta scale
        # continue exactly where the killed fit left off (the validation
        # mask above is a pure function of random_state, so the resumed
        # trajectory is identical to the uninterrupted one)
        epoch0, st = snap
        est._state = jax.tree.map(jnp.asarray, st["state"])
        stop.best, stop.bad = float(st["best"]), int(st["bad"])
        hyper = {**hyper, "eta_scale": jnp.float32(st["eta_scale"])}

    def _snapshot_state():
        return {"state": est._state, "best": stop.best, "bad": stop.bad,
                "eta_scale": float(hyper["eta_scale"])}

    views = _minibatch_views(est, xb, yb, train_mask, n_real)
    n_iter = est.max_iter
    for epoch in range(epoch0, est.max_iter):
        maybe_fault("step")
        with _san.region("sgd.fit.epochs"), _san.step_guard():
            if views is not None:
                xs, ys, ms = views
                est._state, loss = _jitted_epoch(
                    est._state, xs, ys, ms, hyper, loss=est.loss,
                    penalty=est.penalty, schedule=est.learning_rate,
                    fit_intercept=est.fit_intercept,
                )
            else:
                loss = est._step_block(xb, yb, train_mask, hyper)
        done = False
        if stop.active:
            monitor = (
                _eval_loss(est._state, xb, yb, val_mask, hyper,
                           loss=est.loss)
                if early else loss
            )
            if stop.update(float(monitor)):
                if not adaptive:
                    n_iter, done = epoch + 1, True
                else:
                    # sklearn's adaptive rule: divide eta by 5 and keep
                    # going; stop once eta underflows 1e-6.  The stopper's
                    # best loss persists across reductions — only the
                    # patience counter resets
                    new_scale = hyper["eta_scale"] / 5.0
                    if float(new_scale) * float(hyper["eta0"]) < 1e-6:
                        n_iter, done = epoch + 1, True
                    else:
                        hyper = {**hyper, "eta_scale": new_scale}
                        stop.reset_patience()
        # boundary instrumentation only when someone is listening: the
        # snapshot dict costs a device->host sync (float(eta_scale)), and
        # the uninstrumented fit keeps its one-dispatch-per-epoch shape
        if ckpt is not None or active_watcher() is not None:
            if ckpt is not None and not done and ckpt.due(epoch + 1):
                ckpt.save(est, _snapshot_state(), epoch + 1)
            check_preemption(ckpt, est, _snapshot_state(), epoch + 1)
        if done:
            break
    if ckpt is not None:
        ckpt.complete()
    return n_iter


class _BaseSGD(TPUEstimator):
    """Shared plumbing: ingest/pad blocks, drive the jitted step."""

    def __init__(self):  # pragma: no cover - abstract
        raise NotImplementedError

    # -- hyperparams as traced scalars (vmap/packing-compatible) ----------
    def _hyper(self):
        eta0 = float(self.eta0)
        alpha = float(self.alpha)
        if self.learning_rate == "optimal" and eta0 <= 0:
            # sklearn's init: typw = sqrt(1/sqrt(alpha)); eta0 such that the
            # first step size is reasonable.  We just need a stable t0.
            eta0 = 1.0
        t0 = 1.0 / (alpha * eta0) if alpha > 0 and eta0 > 0 else 1.0
        values = (
            alpha, float(self.eta0), float(getattr(self, "power_t", 0.25)),
            t0, float(getattr(self, "l1_ratio", 0.15)),
            float(getattr(self, "epsilon", 0.1)),
        )
        # cache the DEVICE scalars keyed on the host values: streamed
        # partial_fit calls _hyper once per block, and re-materializing
        # seven scalar uploads per step is both wasted puts and an
        # implicit-transfer finding under graftsan's steady-phase
        # transfer guard (a set_params between calls changes the key and
        # rebuilds; nothing donates hyper, so sharing across steps is
        # safe)
        cached = getattr(self, "_hyper_cache", None)
        if cached is not None and cached[0] == values:
            return cached[1]
        hyper = {
            "alpha": jnp.float32(values[0]),
            "eta0": jnp.float32(values[1]),
            "power_t": jnp.float32(values[2]),
            "t0": jnp.float32(values[3]),
            "l1_ratio": jnp.float32(values[4]),
            "epsilon": jnp.float32(values[5]),
            "eta_scale": jnp.float32(1.0),
        }
        self._hyper_cache = (values, hyper)
        return hyper

    def _validate(self):
        bs = getattr(self, "batch_size", None)
        if bs is not None and (
            not isinstance(bs, numbers.Integral) or int(bs) < 1
        ):
            raise ValueError(
                f"batch_size must be a positive int or None; got {bs!r}"
            )
        if getattr(self, "early_stopping", False):
            vf = float(getattr(self, "validation_fraction", 0.1))
            if not 0.0 < vf < 1.0:
                raise ValueError(
                    f"validation_fraction must be in (0, 1); got {vf}"
                )
            if self.tol is None:
                raise ValueError(
                    "early_stopping requires a tol (the stopping rule "
                    "compares held-out losses against it)"
                )
        if self.penalty not in _PENALTIES:
            raise ValueError(f"penalty must be one of {_PENALTIES}")
        if self.learning_rate not in _SCHEDULES:
            raise ValueError(f"learning_rate must be one of {_SCHEDULES}")
        if self.learning_rate == "optimal" and not float(self.alpha) > 0:
            raise ValueError(
                "alpha must be > 0 with learning_rate='optimal' "
                "(the schedule is eta = 1/(alpha*(t0+t)))"
            )

    def _prep_block(self, X, targets):
        """Block → (xb, yb, mask) on device.

        ShardedRows X: rows stay sharded with their own mask; the host
        ``targets`` matrix is sharded the same way (zero-padded rows are
        masked out), and XLA inserts the gradient psum from the
        NamedSharding.  Host array X: padded up to a bucket size so ragged
        streamed chunks don't recompile per shape.
        """
        if isinstance(X, ShardedRows):
            # keep floating X as-is: bf16 rows halve HBM traffic and the
            # step's gemms promote to f32 internally; an eager astype here
            # would materialize an f32 copy on device EVERY call
            xd = X.data
            if not jnp.issubdtype(xd.dtype, jnp.floating):
                xd = xd.astype(jnp.float32)
            if isinstance(targets, jnp.ndarray):
                # device-encoded targets (see _encode_targets_device):
                # already row-aligned with X.data, nothing crosses to host
                return xd, targets, X.mask
            # host-encoded targets must match xd's row count EXACTLY —
            # X may be a relaxed _to_blocks slice whose length is NOT a
            # data-axis multiple, so re-sharding targets (which pads to
            # that multiple) would diverge from xd on multi-device meshes
            t = np.asarray(targets, np.float32)
            if t.shape[0] != xd.shape[0]:
                t = np.concatenate([
                    t,
                    np.zeros((xd.shape[0] - t.shape[0], t.shape[1]),
                             np.float32),
                ])
            return xd, jnp.asarray(t), X.mask
        return self._prep_block_host(X, targets)

    def _prep_block_host(self, X, targets):
        """Host-block tail of :meth:`_prep_block`: bucket-pad + H2D puts
        only.  The ONLY prep entry the staged protocol may use — the
        prefetch worker thread runs it, so it must never compile,
        dispatch, or fetch (graftlint's ``stage-purity`` rule holds the
        whole reachable set to that)."""
        X, targets, mask = _bucket_pad(
            np.asarray(X, dtype=np.float32),
            np.asarray(targets, dtype=np.float32),
        )
        return jnp.asarray(X), jnp.asarray(targets), jnp.asarray(mask)

    def _step_block(self, xb, yb, mask, hyper=None):
        self._state, loss = _jitted_step(
            self._state, xb, yb, mask,
            self._hyper() if hyper is None else hyper,
            loss=self.loss, penalty=self.penalty,
            schedule=self.learning_rate, fit_intercept=self.fit_intercept,
        )
        return loss

    # -- staged streaming protocol (pipeline.stream_partial_fit) ----------
    def _pf_consume(self, staged):
        """Device step on a block pre-staged by :meth:`_pf_stage` —
        ``partial_fit`` minus the host encode/pad/upload, which the
        pipeline's worker thread already ran for this block while the
        previous one computed.  Runs on the consumer thread (program
        dispatch stays single-threaded, design.md §7)."""
        from ..resilience.testing import maybe_fault

        maybe_fault("step")
        xb, yb, mask = staged
        self._ensure_state(xb.shape[1])
        # graftsan: the steady-state streamed step is all-device operands
        # (state donated, hyper cached) — the transfer guard holds it to
        # zero implicit host crossings per block
        with _san.region("sgd.partial_fit"), _san.step_guard():
            self._loss_ = self._step_block(xb, yb, mask)
        return self

    def _pf_stage_ok(self, X, y, sample_weight, kwargs) -> bool:
        """Eligibility gate shared by the staged-protocol probes: host
        blocks only — staging a device-resident block (ShardedRows OR a
        bare jax.Array) would fetch/cast/dispatch on the worker thread,
        the thread-dispatch hazard — and no per-block weighting
        (``effective_mask`` is itself a device program; those calls
        keep the serial path)."""
        return not (
            kwargs
            or sample_weight is not None
            or y is None
            or isinstance(X, (ShardedRows, jnp.ndarray))
            or isinstance(y, (ShardedRows, jnp.ndarray))
        )

    # -- compile-ahead (programs.ahead; design.md §12) --------------------
    def _warm_step(self, xshape, k) -> bool:
        """Enqueue an ahead-of-time compile of the streamed step program
        for a staged block of shape ``xshape`` (already bucketed) and
        ``k`` output columns, on the blessed compile-ahead thread.  Pure
        host work (shape structs + a queue put) — safe from the prefetch
        worker, where ``_pf_stage`` calls it per block (a known
        signature short-circuits in microseconds)."""
        if not _programs.compile_ahead_enabled():
            return False
        b, d = int(xshape[0]), int(xshape[1])
        k = int(k)
        # steady streams hit the same (b, d, k, statics) every block:
        # one tuple compare instead of rebuilding the shape structs and
        # re-walking the cache's signature table per staged block
        key = (b, d, k, self.loss, self.penalty, self.learning_rate,
               self.fit_intercept)
        if getattr(self, "_warm_memo", None) == key:
            return False
        self._warm_memo = key
        f32 = jnp.float32
        sds = jax.ShapeDtypeStruct
        state = {"coef": sds((d, k), f32), "intercept": sds((k,), f32),
                 "t": sds((), f32)}
        hyper = {name: sds((), f32) for name in _HYPER_KEYS}
        return _jitted_step.warm(
            (state, sds((b, d), f32), sds((b, k), f32), sds((b,), f32),
             hyper),
            loss=self.loss, penalty=self.penalty,
            schedule=self.learning_rate, fit_intercept=self.fit_intercept,
        )

    def _pf_warm(self, shape, classes=None) -> bool:
        """Shape-based twin of the ``_pf_stage`` warm hook for callers
        that know an upcoming block's (n, d) before staging it (the
        adaptive search warms each unit's program before its burst).
        Returns False when the output width cannot be derived yet."""
        if len(shape) != 2:
            return False
        k = self._warm_k(classes)
        if k is None:
            return False
        return self._warm_step(
            (_bucket_rows(int(shape[0])), int(shape[1])), k)

    # device state lives in a non-underscore-suffixed private attr; tell
    # checkpoint.save_estimator to persist it with the fitted attrs
    _checkpoint_private_attrs = ("_state",)

    # -- sklearn surface ---------------------------------------------------
    @property
    def t_(self):
        return float(self._state["t"]) if hasattr(self, "_state") else 0.0


class SGDClassifier(ClassifierMixin, _BaseSGD):
    """Linear classifier trained by minibatch SGD, state resident on device.

    One-vs-all over ``classes_`` in a single coefficient matrix; binary
    keeps one column (±1 targets).  Reference counterpart: sklearn's
    ``SGDClassifier`` as driven by ``dask_ml/_partial.py :: fit`` — here
    ``partial_fit`` IS the XLA program, so ``Incremental`` and the adaptive
    searches train on the TPU.
    """

    def __init__(self, loss="log_loss", penalty="l2", alpha=1e-4,
                 l1_ratio=0.15, fit_intercept=True, max_iter=1000, tol=1e-3,
                 learning_rate="optimal", eta0=0.01, power_t=0.25,
                 n_iter_no_change=5, random_state=None, warm_start=False,
                 class_weight=None, batch_size=None, early_stopping=False,
                 validation_fraction=0.1, fit_checkpoint=None):
        self.class_weight = class_weight
        self.batch_size = batch_size
        self.early_stopping = early_stopping
        self.validation_fraction = validation_fraction
        self.fit_checkpoint = fit_checkpoint
        self.loss = loss
        self.penalty = penalty
        self.alpha = alpha
        self.l1_ratio = l1_ratio
        self.fit_intercept = fit_intercept
        self.max_iter = max_iter
        self.tol = tol
        self.learning_rate = learning_rate
        self.eta0 = eta0
        self.power_t = power_t
        self.n_iter_no_change = n_iter_no_change
        self.random_state = random_state
        self.warm_start = warm_start

    def _validate(self):
        super()._validate()
        if self.loss not in _CLS_LOSSES:
            raise ValueError(f"loss must be one of {_CLS_LOSSES}")

    def _set_classes(self, classes):
        """Validate + assign the class set (shared by fit / partial_fit /
        the packed Cohort plane, so all three reject the same configs)."""
        classes = np.sort(np.asarray(classes))
        if len(classes) < 2:
            raise ValueError(
                "classifier needs samples of at least 2 classes; got "
                f"{classes.tolist()}"
            )
        self.classes_ = classes

    def _encode_targets(self, y):
        """y labels → ±1 one-vs-all float matrix [n, K] (K=1 binary)."""
        y = np.asarray(y).ravel()
        idx = np.searchsorted(self.classes_, y)
        if (idx >= len(self.classes_)).any() or (
            self.classes_[idx] != y
        ).any():
            raise ValueError("y contains labels not in `classes`")
        if len(self.classes_) == 2:
            return np.where(idx == 1, 1.0, -1.0).astype(np.float32)[:, None]
        out = -np.ones((y.shape[0], len(self.classes_)), dtype=np.float32)
        out[np.arange(y.shape[0]), idx] = 1.0
        return out

    def _encode_targets_device(self, ydata, mask):
        """Device twin of :meth:`_encode_targets`: labels → ±1 one-vs-all
        WITHOUT pulling the label block to host (an O(block) fetch per
        partial_fit call on the streaming path).  Pad rows (mask 0) are
        exempt from the label-validity check; one scalar crosses to host.
        """
        classes = jnp.asarray(self.classes_, ydata.dtype)
        idx = jnp.clip(
            jnp.searchsorted(classes, ydata), 0, len(self.classes_) - 1
        )
        bad = jnp.sum(
            (jnp.take(classes, idx) != ydata).astype(jnp.float32)
            * (mask > 0)
        )
        if float(bad) > 0:  # scalar fetch, mirrors the host path's check
            raise ValueError("y contains labels not in `classes`")
        if len(self.classes_) == 2:
            return jnp.where(idx == 1, 1.0, -1.0)[:, None].astype(jnp.float32)
        k = len(self.classes_)
        return (2.0 * jax.nn.one_hot(idx, k) - 1.0).astype(jnp.float32)

    def _ensure_state(self, n_features: int):
        if not hasattr(self, "_state"):
            k = 1 if len(self.classes_) == 2 else len(self.classes_)
            self._state = sgd_init(n_features, k)
            self.n_features_in_ = int(n_features)

    def _apply_weights(self, yb, mask, sample_weight, n_real,
                      allow_balanced=True):
        """Fold sample/class weights into the block mask (the mask is the
        per-row weight in every masked reduction — sklearn's weighted
        loss for free).  The true class index is recovered from the ±1
        OvA target matrix, so no separate padded label array is needed."""
        cwd = getattr(self, "class_weight", None)
        if sample_weight is None and cwd is None:
            return mask
        from ..utils import effective_mask

        idx = None
        classes = None
        if cwd is not None:
            if cwd == "balanced" and not allow_balanced:
                # sklearn parity: balanced needs the full label
                # distribution, which a stream of blocks cannot give
                raise ValueError(
                    "class_weight 'balanced' is not supported for "
                    "partial_fit"
                )
            if isinstance(cwd, dict):
                from ..utils import _check_class_weight_keys

                _check_class_weight_keys(cwd, self.classes_)
                # keys are original labels; effective_mask works on the
                # recovered class INDICES, so re-key by position
                cwd = {
                    i: float(cwd.get(c, 1.0))
                    for i, c in enumerate(self.classes_.tolist())
                }
            if yb.shape[1] == 1:
                idx = (yb[:, 0] > 0).astype(jnp.float32)
            else:
                idx = jnp.argmax(yb, axis=1).astype(jnp.float32)
            classes = np.arange(len(self.classes_))
        # ONE call: effective_mask builds class indicators from the
        # ORIGINAL mask, so balanced counts stay unweighted and sample
        # weights apply exactly once (chaining two calls would square
        # them — the indicator would be built from the weighted mask)
        return effective_mask(
            mask, idx, sample_weight=sample_weight, class_weight=cwd,
            classes=classes, n_samples=n_real,
        )

    def _pf_stage(self, X, y, classes=None, sample_weight=None, **kwargs):
        """Host parse → ±1 OvA encode → bucket-pad → device upload for
        ONE stream block; returns the staged ``(xb, yb, mask)`` payload
        for :meth:`_BaseSGD._pf_consume`, or None to decline THAT block
        (the pipeline then routes it through serial ``partial_fit``).
        Safe on the prefetch worker thread: pure host work plus H2D
        puts, no device program dispatched.  ``classes_`` thread
        contract: the first writer wins-and-matches — stage k+1 happens
        strictly after stage k on the one worker (queue order), the
        consumer only consumes blocks whose stage already finished, and
        both the staged and the serial-fallback first call derive
        ``classes_`` from the SAME constant ``classes`` kwarg, so every
        writer writes the identical value and later calls only read."""
        if not self._pf_stage_ok(X, y, sample_weight, kwargs):
            return None
        if getattr(self, "class_weight", None) is not None:
            return None  # effective_mask is a device program: serial path
        self._validate()
        if not hasattr(self, "classes_"):
            if classes is None:
                raise ValueError(
                    "classes must be passed on the first partial_fit call"
                )
            self._set_classes(classes)
        # host tail directly: _pf_stage_ok declined device-resident X, so
        # _prep_block's ShardedRows branch (a device cast program) must
        # stay structurally unreachable from the worker thread
        staged = self._prep_block_host(X, self._encode_targets(np.asarray(y)))
        # compile-ahead: if this block's bucket is a new shape, its step
        # program builds on the blessed compile thread while the PREVIOUS
        # block's device step runs — the consumer lands on a warm program
        self._warm_step(staged[0].shape, staged[1].shape[1])
        return staged

    def _warm_k(self, classes=None):
        classes = self.classes_ if classes is None and \
            hasattr(self, "classes_") else classes
        if classes is None:
            return None
        return 1 if len(classes) == 2 else len(classes)

    def partial_fit(self, X, y, classes=None, sample_weight=None, **kwargs):
        self._validate()
        if not hasattr(self, "classes_"):
            if classes is None:
                raise ValueError(
                    "classes must be passed on the first partial_fit call"
                )
            self._set_classes(classes)
        if isinstance(y, ShardedRows):
            if isinstance(X, ShardedRows):
                # all-device block: encode labels in place, zero host I/O
                targets = self._encode_targets_device(y.data, y.mask)
            else:
                from ..core.sharded import unshard

                targets = self._encode_targets(np.asarray(unshard(y)))
        else:
            targets = self._encode_targets(np.asarray(y))
        xb, yb, mask = self._prep_block(X, targets)
        n_real = X.n_samples if isinstance(X, ShardedRows) else int(
            np.asarray(X).shape[0])
        mask = self._apply_weights(
            yb, mask, sample_weight, n_real, allow_balanced=False
        )
        # the device step is the shared _pf_consume tail, so the serial
        # path and the prefetch pipeline can never drift apart
        return self._pf_consume((xb, yb, mask))

    def fit(self, X, y, sample_weight=None, **kwargs):
        self._validate()
        if isinstance(y, ShardedRows):
            from ..core.sharded import unshard

            y = unshard(y)
        y = np.asarray(y)
        if self.warm_start and hasattr(self, "classes_"):
            # Keep the fitted class set (the coef matrix's K columns);
            # refitting on labels outside it cannot be reconciled with the
            # kept state, so reject instead of training wrong columns.
            extra = np.setdiff1d(np.unique(y), self.classes_)
            if extra.size:
                raise ValueError(
                    f"warm_start refit saw labels {extra.tolist()} not in "
                    f"the fitted classes_ {self.classes_.tolist()}"
                )
        else:
            for attr in ("_state", "classes_"):
                if hasattr(self, attr):
                    delattr(self, attr)
            self._set_classes(np.unique(y))
        # Encode/pad/transfer ONCE; every epoch is then just the fused step.
        xb, yb, mask = self._prep_block(X, self._encode_targets(y))
        mask = self._apply_weights(yb, mask, sample_weight, len(y))
        self._ensure_state(xb.shape[1])
        self.n_iter_ = _run_epochs(self, xb, yb, mask, n_real=len(y))
        return self

    # -- inference (device; sliced back at the boundary) ------------------
    def _margins(self, X):
        if isinstance(X, ShardedRows):
            m = X.data.astype(jnp.float32) @ self._state["coef"] + self._state["intercept"]
            return m[: X.n_samples]
        return jnp.asarray(np.asarray(X, np.float32)) @ self._state["coef"] + self._state["intercept"]

    def decision_function(self, X):
        m = self._margins(X)
        return m[:, 0] if m.shape[1] == 1 else m

    def predict(self, X):
        m = self._margins(X)
        if m.shape[1] == 1:
            idx = (m[:, 0] > 0).astype(jnp.int32)
        else:
            idx = jnp.argmax(m, axis=1)
        return self.classes_[np.asarray(idx)]

    def predict_proba(self, X):
        if self.loss not in ("log_loss", "modified_huber"):
            raise AttributeError(
                f"probability estimates are not available for loss={self.loss!r}"
            )
        m = self._margins(X)
        if self.loss == "modified_huber":
            # sklearn's formula: linear clip of the margin to [-1, 1].
            p = (jnp.clip(m, -1.0, 1.0) + 1.0) / 2.0
        else:
            p = jax.nn.sigmoid(m)
        if m.shape[1] == 1:
            return jnp.stack([1.0 - p[:, 0], p[:, 0]], axis=1)
        if self.loss == "modified_huber":
            # all-zero rows (every class clipped to -1) → uniform
            z = jnp.sum(p, axis=1, keepdims=True)
            return jnp.where(z > 0, p / z, 1.0 / p.shape[1])
        return p / jnp.sum(p, axis=1, keepdims=True)

    def predict_log_proba(self, X):
        return jnp.log(self.predict_proba(X))

    @property
    def coef_(self):
        return np.asarray(self._state["coef"]).T  # sklearn: (K, d) / (1, d)

    @property
    def intercept_(self):
        return np.asarray(self._state["intercept"])

    def score(self, X, y, sample_weight=None):
        """Mean accuracy.  All-device inputs score as ONE replicated
        scalar fetch — the only legal form when the arrays span processes
        (a multi-host global array cannot be pulled to host row-wise, and
        even single-host it avoids the O(n) transfer)."""
        from ..core.sharded import ShardedRows as _SR

        from ..utils import classes_f32_exact, masked_device_accuracy

        if sample_weight is not None:
            if isinstance(y, _SR):
                # device labels stay on device — no O(n) pull
                from ..metrics import accuracy_score

                return float(accuracy_score(
                    y, self.predict(X), sample_weight=sample_weight
                ))
            # host labels may be strings/objects: compare on host
            yv = np.asarray(y)
            hits = np.asarray(self.predict(X)) == yv
            return float(np.average(hits, weights=np.asarray(sample_weight)))
        if (isinstance(X, _SR) and isinstance(y, _SR)
                and classes_f32_exact(self.classes_)):
            md = (X.data.astype(jnp.float32) @ self._state["coef"]
                  + self._state["intercept"])
            if md.shape[1] == 1:
                idx = (md[:, 0] > 0).astype(jnp.int32)
            else:
                idx = jnp.argmax(md, axis=1).astype(jnp.int32)
            return masked_device_accuracy(idx, y.data, X.mask, self.classes_)
        from ..metrics import accuracy_score

        return accuracy_score(y, self.predict(X))


class SGDRegressor(RegressorMixin, _BaseSGD):
    """Linear regressor trained by minibatch SGD on device."""

    def __init__(self, loss="squared_error", penalty="l2", alpha=1e-4,
                 l1_ratio=0.15, fit_intercept=True, max_iter=1000, tol=1e-3,
                 learning_rate="invscaling", eta0=0.01, power_t=0.25,
                 epsilon=0.1, n_iter_no_change=5, random_state=None,
                 warm_start=False, batch_size=None, early_stopping=False,
                 validation_fraction=0.1, fit_checkpoint=None):
        self.batch_size = batch_size
        self.early_stopping = early_stopping
        self.validation_fraction = validation_fraction
        self.fit_checkpoint = fit_checkpoint
        self.loss = loss
        self.penalty = penalty
        self.alpha = alpha
        self.l1_ratio = l1_ratio
        self.fit_intercept = fit_intercept
        self.max_iter = max_iter
        self.tol = tol
        self.learning_rate = learning_rate
        self.eta0 = eta0
        self.power_t = power_t
        self.epsilon = epsilon
        self.n_iter_no_change = n_iter_no_change
        self.random_state = random_state
        self.warm_start = warm_start

    def _validate(self):
        super()._validate()
        if self.loss not in _REG_LOSSES:
            raise ValueError(f"loss must be one of {_REG_LOSSES}")

    def _targets(self, y, X=None):
        if isinstance(y, ShardedRows):
            if isinstance(X, ShardedRows):
                # all-device block: targets stay on device, row-aligned
                # with X.data (pad rows masked out in sgd_step)
                return y.data.astype(jnp.float32).reshape(-1, 1)
            # mixed host-X + device-y: the host bucketing path needs
            # exactly n unpadded rows
            from ..core.sharded import unshard

            y = unshard(y)
        return self._targets_host(y)

    @staticmethod
    def _targets_host(y):
        """Host-only tail of :meth:`_targets` — the staged protocol's
        entry (worker thread: no device cast, no unshard fetch;
        ``_pf_stage_ok`` already declined device-resident ``y``)."""
        return np.asarray(y, dtype=np.float32).reshape(-1, 1)

    def _ensure_state(self, n_features: int):
        if not hasattr(self, "_state"):
            self._state = sgd_init(n_features, 1)
            self.n_features_in_ = int(n_features)

    @staticmethod
    def _weighted_mask(X, mask, sample_weight):
        if sample_weight is None:
            return mask
        from ..utils import effective_mask

        n_real = X.n_samples if isinstance(X, ShardedRows) else int(
            np.asarray(X).shape[0])
        return effective_mask(
            mask, sample_weight=sample_weight, n_samples=n_real
        )

    def _pf_stage(self, X, y, sample_weight=None, **kwargs):
        """Regressor twin of :meth:`SGDClassifier._pf_stage`: host
        reshape + bucket-pad + upload, no device program dispatch —
        host-only tails directly (``_pf_stage_ok`` declined device
        input, so ``_targets``/``_prep_block``'s device branches must
        stay structurally unreachable from the worker thread)."""
        if not self._pf_stage_ok(X, y, sample_weight, kwargs):
            return None
        self._validate()
        staged = self._prep_block_host(X, self._targets_host(y))
        self._warm_step(staged[0].shape, 1)
        return staged

    def _warm_k(self, classes=None):
        return 1

    def partial_fit(self, X, y, sample_weight=None, **kwargs):
        self._validate()
        xb, yb, mask = self._prep_block(X, self._targets(y, X))
        mask = self._weighted_mask(X, mask, sample_weight)
        return self._pf_consume((xb, yb, mask))

    def fit(self, X, y, sample_weight=None, **kwargs):
        self._validate()
        if not self.warm_start and hasattr(self, "_state"):
            delattr(self, "_state")
        xb, yb, mask = self._prep_block(X, self._targets(y, X))
        mask = self._weighted_mask(X, mask, sample_weight)
        self._ensure_state(xb.shape[1])
        n_real = X.n_samples if isinstance(X, ShardedRows) else int(
            np.asarray(X).shape[0])
        self.n_iter_ = _run_epochs(self, xb, yb, mask, n_real=n_real)
        return self

    def predict(self, X):
        if isinstance(X, ShardedRows):
            p = X.data.astype(jnp.float32) @ self._state["coef"] + self._state["intercept"]
            return p[: X.n_samples, 0]
        X = jnp.asarray(np.asarray(X, np.float32))
        return (X @ self._state["coef"] + self._state["intercept"])[:, 0]

    @property
    def coef_(self):
        return np.asarray(self._state["coef"])[:, 0]

    @property
    def intercept_(self):
        return np.asarray(self._state["intercept"])

    def score(self, X, y, sample_weight=None):
        from ..metrics import r2_score

        return r2_score(y, self.predict(X), sample_weight=sample_weight)

"""GLM estimators — twin of ``dask_ml/linear_model/glm.py``
(``LogisticRegression``, ``LinearRegression``, ``PoissonRegression``, base
``_GLM``): an sklearn facade that maps ``C``/``penalty``/``solver`` onto the
solver library (``lamduh = 1/C``, reference convention), adds the intercept
column, and exposes ``coef_``/``intercept_``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..base import ClassifierMixin, RegressorMixin, TPUEstimator
from ..core.sharded import ShardedRows
from ..preprocessing.data import _ingest_float
from .. import sanitize as _san
from ..solvers import (
    Logistic,
    Normal,
    Poisson,
    admm,
    get_regularizer,
    gradient_descent,
    lbfgs,
    newton,
    proximal_grad,
)
from .utils import add_intercept, binary_indicator

_SOLVERS = {
    "admm": admm,
    "lbfgs": lbfgs,
    "newton": newton,
    "gradient_descent": gradient_descent,
    "proximal_grad": proximal_grad,
}


class _GLM(TPUEstimator):
    family: type = None

    def __init__(self, penalty="l2", dual=False, tol=1e-4, C=1.0,
                 fit_intercept=True, intercept_scaling=1.0, class_weight=None,
                 random_state=None, solver="admm", max_iter=100,
                 multi_class="ovr", verbose=0, warm_start=False, n_jobs=1,
                 solver_kwargs=None, fit_checkpoint=None):
        self.penalty = penalty
        self.dual = dual
        self.tol = tol
        self.C = C
        self.fit_intercept = fit_intercept
        self.intercept_scaling = intercept_scaling
        self.class_weight = class_weight
        self.random_state = random_state
        self.solver = solver
        self.max_iter = max_iter
        self.multi_class = multi_class
        self.verbose = verbose
        self.warm_start = warm_start
        self.n_jobs = n_jobs
        self.solver_kwargs = solver_kwargs
        self.fit_checkpoint = fit_checkpoint

    def _solver_call_kwargs(self):
        """Solver kwargs shared by the single and packed dispatch paths —
        one place for the tol-vs-abstol mapping and solver validation."""
        if self.solver not in _SOLVERS:
            raise ValueError(
                f"Unknown solver {self.solver!r}; valid: {sorted(_SOLVERS)}"
            )
        kwargs = dict(
            regularizer=get_regularizer(self.penalty),
            lamduh=1.0 / self.C,
            max_iter=self.max_iter,
            **(self.solver_kwargs or {}),
        )
        if self.solver == "admm":
            kwargs["abstol"] = self.tol
        else:
            kwargs["tol"] = self.tol
        return kwargs

    def _solve(self, X: ShardedRows, y, family=None, beta0=None):
        kwargs = self._solver_call_kwargs()  # validates self.solver
        # graftsan region: every GLM solver dispatch path funnels through
        # here (plain, chunked, and the OvR/multinomial branches that
        # call _solve per class), so compile attribution names the lane
        with _san.region("glm.fit.solve"):
            if getattr(self, "fit_checkpoint", None) is not None:
                return self._solve_chunked(
                    X, y, family or self.family, beta0, kwargs,
                    self.fit_checkpoint,
                )
            return _SOLVERS[self.solver](
                X, y, return_n_iter=True, family=family or self.family,
                beta0=beta0, **kwargs
            )

    def _solve_chunked(self, X, y, family, beta0, kwargs, ckpt):
        """Preemption-safe solve: the fused device solver runs in SEGMENTS
        of the checkpoint cadence, warm-started from the previous
        segment's beta, with an atomic snapshot at every boundary.

        Restarting a solver segment resets its internal machinery (LBFGS
        curvature history, ADMM duals/rho, line-search step sizes), so the
        CHUNKED trajectory differs from the single-dispatch solve — but it
        is deterministic: a fit killed at any boundary and resumed from
        its snapshot replays the identical remaining segments, and the
        converged optimum is the same within ``tol``.  Pick a cadence of
        tens of iterations so the restart overhead amortizes (see
        :class:`~dask_ml_tpu.resilience.FitCheckpoint`).  The packed
        one-vs-rest plane ignores the checkpoint (one vmapped program for
        ALL classes — there is no per-class boundary to snapshot).
        """
        from ..resilience.preemption import check_preemption
        from ..resilience.testing import maybe_fault

        max_iter = int(kwargs.get("max_iter", 100))
        chunk = ckpt.chunk_iters(max(1, min(20, max_iter)))
        it = 0
        snap = ckpt.load_if_matches(self)
        if snap is not None:
            it, state = snap
            beta0 = np.asarray(state["beta"])
        solver = _SOLVERS[self.solver]
        beta = beta0
        while it < max_iter:
            maybe_fault("step")
            seg = min(chunk, max_iter - it)
            kw = dict(kwargs, max_iter=seg)
            beta, n_it = solver(
                X, y, return_n_iter=True, family=family, beta0=beta, **kw
            )
            n = int(n_it)
            it += n
            if ckpt.due(it):
                ckpt.save(self, {"beta": beta}, it)
            check_preemption(ckpt, self, {"beta": beta}, it)
            if n < seg:
                break  # the segment's own tol stop fired: converged
        ckpt.complete()
        return beta, it

    @staticmethod
    def _warm_ok(prev, shape, *, was_multinomial=False,
                 want_multinomial=False, classes_match=True):
        """THE warm-start geometry gate (one implementation for the
        regression, binary, OvR, and multinomial paths): previous betas
        are reusable only for the SAME problem geometry — matching
        classes, matching parameter shape, and the same
        multinomial-ness.  A mismatch means a different problem, so the
        solve cold-starts silently (sklearn errors only on changed
        classes; shape is the device-native analogue)."""
        if prev is None or not classes_match:
            return None
        if was_multinomial != want_multinomial:
            return None
        if tuple(np.asarray(prev).shape) != shape:
            return None
        return prev

    def _sweep_fit_values(self, X, y, Cs):
        """``len(Cs)`` REGRESSION fits differing only in ``C`` as one
        vmapped program (``solvers.lambda_sweep``); the grid-search fast
        path calls this for identity-link families.  Eligibility (no
        sample weights) is the caller's job.  Returns betas (K, p)."""
        from ..solvers import lambda_sweep

        X = _ingest_float(self, X)
        Xi = add_intercept(X) if self.fit_intercept else X
        kwargs = self._solver_call_kwargs()
        kwargs.pop("lamduh")
        betas, _ = lambda_sweep(
            self.solver, Xi, y, [1.0 / float(c) for c in Cs],
            family=self.family, **kwargs,
        )
        return betas

    def fit(self, X, y=None, sample_weight=None):
        X = _ingest_float(self, X)
        self.n_features_in_ = X.data.shape[1]
        Xi = add_intercept(X) if self.fit_intercept else X
        if sample_weight is not None:
            from ..utils import reweight_rows

            Xi = reweight_rows(Xi, sample_weight=sample_weight)
        warm = None
        if self.warm_start:
            warm = self._warm_ok(
                getattr(self, "betas_", None), (1, Xi.data.shape[1]),
                was_multinomial=getattr(self, "_multinomial", False),
            )
        beta, n_it = self._solve(
            Xi, y, beta0=None if warm is None else warm[0])
        # sklearn contract: iteration count(s) of the solver run(s);
        # converted only now, after the solve is dispatched
        self.n_iter_ = np.asarray([n_it], dtype=np.int32)
        if self.fit_intercept:
            self.coef_ = beta[:-1]
            self.intercept_ = float(beta[-1])
        else:
            self.coef_ = beta
            self.intercept_ = 0.0
        self._coef = beta
        self.betas_ = beta[None, :]
        return self

    def _eta(self, X):
        X = _ingest_float(self, X)
        eta = X.data @ self.coef_ + self.intercept_
        return X, eta

    def predict(self, X):
        raise NotImplementedError

    def score(self, X, y):
        raise NotImplementedError


class LogisticRegression(ClassifierMixin, _GLM):
    """Binary and multiclass logistic regression over the solver library.

    Multiclass is one-vs-rest (``multi_class='ovr'``): ALL K class solves
    run as ONE vmapped XLA program (``solvers.packed_solve``), or a true
    softmax fit with ``multi_class='multinomial'``.  ``classes_`` is
    fitted and ``predict`` returns original labels.  ``class_weight``
    (dict or ``'balanced'``) and ``fit(..., sample_weight=)`` scale the
    row mask — the solvers' masked reductions become sklearn's weighted
    loss.  ``warm_start=True`` seeds every solver with the previous
    fit's coefficients when the problem geometry (classes + parameter
    shape) is unchanged — an improvement over the reference (dask_glm
    ignores it): a warm refit on similar data converges in a fraction
    of the iterations (binary, packed OvR, and multinomial paths all
    warm-start; ADMM re-seeds consensus z and the per-shard betas).
    """

    family = Logistic

    def _sweep_fit_binary(self, X, y, Cs):
        """Fit ``len(Cs)`` variants differing ONLY in ``C`` as ONE
        vmapped program (``solvers.lambda_sweep`` — the lanes share X
        and y; the regularization strength is a traced scalar).  The
        grid-search fast path calls this; eligibility (binary labels,
        no sample/class weights, plain ovr) is the CALLER's job.

        Returns (betas (K, p), classes (2,)).
        """
        from ..core.sharded import ShardedRows as _SR
        from ..core.sharded import as_sharded
        from ..solvers import lambda_sweep

        y = as_sharded(y)
        if isinstance(y, _SR):
            yd = jnp.where(y.mask > 0, y.data, y.data[0])
            classes = np.asarray(jnp.unique(yd))
        else:
            classes = np.unique(np.asarray(y))
        if len(classes) != 2:
            raise ValueError(
                f"_sweep_fit_binary needs exactly 2 classes, got "
                f"{classes.tolist()}"
            )
        X = _ingest_float(self, X)
        Xi = add_intercept(X) if self.fit_intercept else X
        y01 = binary_indicator(y, classes[1])
        kwargs = self._solver_call_kwargs()
        kwargs.pop("lamduh")
        betas, _ = lambda_sweep(
            self.solver, Xi, y01, [1.0 / float(c) for c in Cs],
            family=self.family, **kwargs,
        )
        return betas, classes

    def fit(self, X, y=None, sample_weight=None):
        # warm start (an improvement over the reference: dask_glm ignores
        # it): capture the PREVIOUS fit's parameters before this fit
        # overwrites them; they seed the solver when the problem geometry
        # (classes + parameter shape) is unchanged
        prev_betas = (
            np.asarray(self.betas_)
            if self.warm_start and hasattr(self, "betas_") else None
        )
        prev_classes = (
            self.classes_
            if self.warm_start and hasattr(self, "classes_") else None
        )
        prev_multinomial = getattr(self, "_multinomial", False)
        if self.multi_class not in ("ovr", "auto", "multinomial"):
            raise ValueError(
                f"multi_class must be 'ovr', 'auto' or 'multinomial'; got "
                f"{self.multi_class!r}"
            )
        from ..core.sharded import ShardedRows as _SR
        from ..core.sharded import as_sharded

        # raw device label vectors ride the ShardedRows no-fetch paths
        y = as_sharded(y)
        if isinstance(y, _SR):
            # device-side class discovery: only the unique label VALUES
            # cross to host (a handful of scalars), never the n-row label
            # vector — a full unshard of device-resident labels is an
            # O(n) device->host transfer (minutes at HIGGS scale on the
            # axon relay, and large fetches can wedge the tunnel).  Pad
            # rows are remapped to the first (real) label so padding
            # cannot mint a phantom class.
            yd = jnp.where(y.mask > 0, y.data, y.data[0])
            self.classes_ = np.asarray(jnp.unique(yd))
            yv = None
        else:
            yv = np.asarray(y)
            self.classes_ = np.unique(yv)
        if len(self.classes_) < 2:
            raise ValueError(
                "LogisticRegression needs samples of at least 2 classes; "
                f"got {self.classes_.tolist()}"
            )
        X = _ingest_float(self, X)
        self.n_features_in_ = X.data.shape[1]
        Xi = add_intercept(X) if self.fit_intercept else X

        if sample_weight is not None or self.class_weight is not None:
            # weights scale the mask: every masked reduction in the
            # solvers becomes the sklearn weighted loss (VERDICT r2
            # missing #6 — the mask machinery IS the per-row weight)
            from ..utils import host_class_weight_rows, reweight_rows

            if self.class_weight is not None and yv is not None:
                # host labels can be strings or big ints that a device
                # cast would corrupt: resolve the per-row class weight on
                # host and fold it into sample_weight
                row_w = host_class_weight_rows(
                    self.class_weight, self.classes_, yv
                )
                if sample_weight is not None:
                    row_w = row_w * np.asarray(sample_weight, np.float32)
                Xi = reweight_rows(Xi, sample_weight=row_w)
            elif self.class_weight is not None:
                # device labels are numeric by construction: count and
                # weight classes on device, no label round-trip
                Xi = reweight_rows(
                    Xi, sample_weight=sample_weight,
                    class_weight=self.class_weight, classes=self.classes_,
                    y_padded=y.data,
                )
            else:
                Xi = reweight_rows(Xi, sample_weight=sample_weight)

        def _indicator(cls):
            """One-vs-rest target via the SHARED encoding helper
            (linear_model.utils.binary_indicator)."""
            return binary_indicator(yv if yv is not None else y, cls)

        K = len(self.classes_)

        def _warm(shape, want_multinomial=False):
            """Previous betas when classes and parameter shape match
            (delegates to the shared ``_warm_ok`` geometry gate)."""
            return self._warm_ok(
                prev_betas, shape,
                was_multinomial=prev_multinomial,
                want_multinomial=want_multinomial,
                classes_match=(
                    prev_classes is not None
                    and len(prev_classes) == K
                    and np.array_equal(np.asarray(prev_classes),
                                       np.asarray(self.classes_))
                ),
            )

        self._multinomial = False
        if K == 2 and not (
            self.multi_class == "multinomial" and self.penalty != "l2"
        ):
            # binary: one sigmoid solve.  'multinomial' with 2 classes is
            # the SAME loss reparameterized (w = w1 - w0); for L2 the
            # softmax penalty ||w0||² + ||w1||² equals ||w||²/2 at the
            # symmetric optimum — i.e. the sigmoid fit at HALF the
            # penalty.  That scalar equivalence is L2-ONLY (L1 of the
            # split pair is |w|, elasticnet has no single scale), so
            # non-L2 multinomial falls through to the true 2-class
            # softmax solve below.
            y01 = _indicator(self.classes_[1])
            wb = _warm((1, Xi.data.shape[1]))
            w0 = None if wb is None else wb[0]
            if self.multi_class == "multinomial":
                kwargs = self._solver_call_kwargs()
                kwargs["lamduh"] = kwargs["lamduh"] / 2.0
                beta, n_it = _SOLVERS[self.solver](
                    Xi, y01, return_n_iter=True, family=self.family,
                    beta0=w0, **kwargs,
                )
            else:
                beta, n_it = self._solve(Xi, y01, beta0=w0)
            self.betas_ = beta[None, :]
            n_iter_runs = [n_it]
        elif self.multi_class == "multinomial":
            # true softmax: ONE solve over a flat (features*K) parameter
            # vector (solvers/families.py :: multinomial); closes the
            # reference's binary-only dask_glm gap
            from ..solvers import multinomial as _mn

            fam = _mn(K)
            if yv is None:
                yd2 = jnp.where(y.mask > 0, y.data, y.data[0])
                y_idx = _SR(
                    data=jnp.searchsorted(
                        jnp.asarray(self.classes_, yd2.dtype), yd2
                    ).astype(jnp.float32),
                    mask=y.mask, n_samples=y.n_samples,
                )
            else:
                y_idx = np.searchsorted(self.classes_, yv).astype(np.float32)
            # warm start: betas_ stores W (K, p); the flat vector the
            # softmax family consumes is its (p, K) transpose raveled
            wm = _warm((K, Xi.data.shape[1]), want_multinomial=True)
            beta_flat, n_it = self._solve(
                Xi, y_idx, family=fam,
                beta0=None if wm is None else wm.T.ravel())
            W = beta_flat.reshape(Xi.data.shape[1], K).T  # (K, p)
            if K == 2:
                # non-L2 binary softmax (the L2 case took the sigmoid
                # shortcut above): collapse to the sigmoid form — the
                # decision function w = w1 - w0 gives the EXACT softmax
                # posterior, and the binary coef_/predict contract holds
                self.betas_ = (W[1] - W[0])[None, :]
            else:
                self.betas_ = W
                self._multinomial = True
            # sklearn multinomial reports ONE solver run replicated per
            # class in n_iter_; keep a single honest count instead
            n_iter_runs = [n_it]
        else:
            # packed one-vs-rest: the K independent solves run as ONE
            # vmapped XLA program (solvers.packed_solve) — the reference
            # dispatches a task graph per class; a K-long Python loop of
            # device solves was the round-2 shape (VERDICT r2 missing #4)
            from ..solvers import packed_solve

            n_pad = Xi.data.shape[0]
            if yv is None:
                Y = (
                    y.data[None, :]
                    == jnp.asarray(self.classes_, y.data.dtype)[:, None]
                ).astype(jnp.float32)
            else:
                Yh = (yv[None, :] == self.classes_[:, None]).astype(
                    np.float32
                )
                Y = jnp.asarray(
                    np.pad(Yh, ((0, 0), (0, n_pad - Yh.shape[1])))
                )
            betas, n_its = packed_solve(
                self.solver, Xi, Y, family=self.family,
                Beta0=_warm((K, Xi.data.shape[1])),
                **self._solver_call_kwargs(),
            )
            self.betas_ = betas  # (K, p)
            n_iter_runs = n_its
        # sklearn contract: one count per OvR solve — device scalars are
        # converted only here, after every class's solve has dispatched
        self.n_iter_ = np.asarray(n_iter_runs, dtype=np.int32)
        if self.fit_intercept:
            self.coef_ = (
                self.betas_[0, :-1] if len(self.classes_) == 2
                else self.betas_[:, :-1]
            )
            self.intercept_ = (
                float(self.betas_[0, -1]) if len(self.classes_) == 2
                else np.asarray(self.betas_[:, -1])
            )
        else:
            self.coef_ = (
                self.betas_[0] if len(self.classes_) == 2 else self.betas_
            )
            self.intercept_ = (
                0.0 if len(self.classes_) == 2
                else np.zeros(len(self.classes_))
            )
        self._coef = self.betas_[0] if len(self.classes_) == 2 else self.betas_
        return self

    def _etas(self, X):
        """(X, per-class raw margins [n, K_or_1])."""
        X = _ingest_float(self, X)
        if self.fit_intercept:
            eta = X.data @ self.betas_[:, :-1].T + self.betas_[:, -1]
        else:
            eta = X.data @ self.betas_.T
        return X, eta

    def predict(self, X):
        X, eta = self._etas(X)
        eta = eta[: X.n_samples]
        if len(self.classes_) == 2:
            idx = (eta[:, 0] > 0).astype(jnp.int32)
        else:
            idx = jnp.argmax(eta, axis=1)
        return self.classes_[np.asarray(idx)]

    def predict_proba(self, X):
        import jax

        X, eta = self._etas(X)
        eta = eta[: X.n_samples]
        if len(self.classes_) == 2:
            p1 = Logistic.predict(eta[:, 0])
            return jnp.stack([1.0 - p1, p1], axis=1)
        if getattr(self, "_multinomial", False):
            return jax.nn.softmax(eta, axis=1)  # true joint posterior
        p = Logistic.predict(eta)  # per-class sigmoid, OvR-normalized
        return p / jnp.sum(p, axis=1, keepdims=True)

    def predict_log_proba(self, X):
        """Log class probabilities, in numerically stable forms: binary
        uses ``log_sigmoid(±eta)``, multinomial ``log_softmax``; the OvR
        path logs its normalized sigmoids."""
        X, eta = self._etas(X)
        eta = eta[: X.n_samples]
        if len(self.classes_) == 2:
            return jnp.stack([
                jax.nn.log_sigmoid(-eta[:, 0]), jax.nn.log_sigmoid(eta[:, 0])
            ], axis=1)
        if getattr(self, "_multinomial", False):
            return jax.nn.log_softmax(eta, axis=1)
        p = Logistic.predict(eta)
        return jnp.log(p / jnp.sum(p, axis=1, keepdims=True))

    def decision_function(self, X):
        X, eta = self._etas(X)
        eta = eta[: X.n_samples]
        return eta[:, 0] if len(self.classes_) == 2 else eta

    def score(self, X, y, sample_weight=None):
        """Mean accuracy (reference forwards to dask accuracy_score);
        accepts plain or ShardedRows y.  All-device inputs score as ONE
        replicated scalar fetch — no O(n) label transfer (the form the
        device-resident CV search relies on, and the only legal one for
        multi-host global arrays)."""
        from ..core.sharded import ShardedRows as _SR
        from ..core.sharded import as_sharded, unshard

        from ..utils import classes_f32_exact, masked_device_accuracy

        X, y = as_sharded(X), as_sharded(y)
        if sample_weight is not None:
            if isinstance(y, _SR):
                # device labels stay on device: accuracy_score consumes
                # ShardedRows natively — no O(n) pull (multi-host safe)
                from ..metrics import accuracy_score

                return float(accuracy_score(
                    y, self.predict(X), sample_weight=sample_weight
                ))
            # host labels may be strings/objects: compare on host
            yv = np.asarray(y)
            hits = np.asarray(self.predict(X)) == yv
            return float(np.average(hits, weights=np.asarray(sample_weight)))
        if (isinstance(X, _SR) and isinstance(y, _SR)
                and classes_f32_exact(self.classes_)):
            Xi, eta = self._etas(X)
            if len(self.classes_) == 2:
                idx = (eta[:, 0] > 0).astype(jnp.int32)
            else:
                idx = jnp.argmax(eta, axis=1).astype(jnp.int32)
            return masked_device_accuracy(
                idx, y.data, Xi.mask, self.classes_
            )
        yv = unshard(y) if isinstance(y, _SR) else np.asarray(y)
        return float((self.predict(X) == yv).mean())


class LinearRegression(RegressorMixin, _GLM):
    family = Normal

    def predict(self, X):
        X, eta = self._eta(X)
        return eta[: X.n_samples]

    def score(self, X, y, sample_weight=None):
        from ..metrics import r2_score

        return r2_score(y, self.predict(X), sample_weight=sample_weight)


class PoissonRegression(RegressorMixin, _GLM):
    family = Poisson

    def predict(self, X):
        X, eta = self._eta(X)
        return jnp.exp(eta)[: X.n_samples]

    def get_deviance(self, X, y, sample_weight=None):
        from ..core.sharded import unshard

        mu = np.asarray(self.predict(X))
        yv = unshard(y) if isinstance(y, ShardedRows) else np.asarray(y)
        with np.errstate(divide="ignore", invalid="ignore"):
            term = np.where(yv > 0, yv * np.log(yv / mu), 0.0)
        dev = term - (yv - mu)
        if sample_weight is not None:
            dev = dev * np.asarray(sample_weight)
        return 2 * np.sum(dev)

    def score(self, X, y, sample_weight=None):
        return -self.get_deviance(X, y, sample_weight=sample_weight)

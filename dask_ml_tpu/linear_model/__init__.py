"""Linear models — twin of ``dask_ml/linear_model/`` (SURVEY.md §2 #11)."""

from ._sgd import SGDClassifier, SGDRegressor  # noqa: F401
from .glm import LinearRegression, LogisticRegression, PoissonRegression  # noqa: F401

__all__ = [
    "LogisticRegression",
    "LinearRegression",
    "PoissonRegression",
    "SGDClassifier",
    "SGDRegressor",
]

"""Reference: ``dask_ml/linear_model/utils.py :: add_intercept``."""

from __future__ import annotations

import jax.numpy as jnp

from ..core.sharded import ShardedRows


def binary_indicator(y, positive_class):
    """0/1 target for ``y == positive_class``, built where y lives
    (device labels never round-trip; the mask keeps pad rows inert).
    The ONE encoding shared by ``LogisticRegression.fit``'s OvR
    indicator, the packed C-sweep, and the sweep scorer — they must
    agree bit-for-bit or the packed grid path would score against a
    different encoding than it fit."""
    import numpy as np

    if isinstance(y, ShardedRows):
        return ShardedRows(
            data=(y.data == jnp.asarray(
                positive_class, y.data.dtype)).astype(jnp.float32),
            mask=y.mask, n_samples=y.n_samples,
        )
    return (np.asarray(y) == positive_class).astype(np.float32)


def add_intercept(X: ShardedRows) -> ShardedRows:
    """Append a ones column (zeroed on padded rows so solvers stay exact)."""
    ones = X.mask[:, None].astype(X.data.dtype)
    return ShardedRows(
        data=jnp.concatenate([X.data, ones], axis=1),
        mask=X.mask,
        n_samples=X.n_samples,
    )

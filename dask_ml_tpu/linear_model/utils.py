"""Reference: ``dask_ml/linear_model/utils.py :: add_intercept``."""

from __future__ import annotations

import jax.numpy as jnp

from ..core.sharded import ShardedRows


def add_intercept(X: ShardedRows) -> ShardedRows:
    """Append a ones column (zeroed on padded rows so solvers stay exact)."""
    ones = X.mask[:, None].astype(X.data.dtype)
    return ShardedRows(
        data=jnp.concatenate([X.data, ones], axis=1),
        mask=X.mask,
        n_samples=X.n_samples,
    )

"""GaussianNB — twin of ``dask_ml/naive_bayes.py`` (SURVEY.md §2 #18):
per-class blockwise moments, here one jitted masked reduction over the
sharded sample axis (the per-class sums are a one-hot gemm like KMeans').
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from . import programs as _programs
from .base import ClassifierMixin, TPUEstimator
from .core.sharded import ShardedRows, unshard
from .preprocessing.data import _ingest_float, _masked_or_plain


def _class_moments_fn(x, mask, onehot):
    w = onehot * mask[:, None]  # (n, k); mask may carry sample WEIGHTS
    counts = jnp.sum(w, axis=0)  # (k,) weight mass per class
    from .utils import safe_denominator

    safe = safe_denominator(counts)
    sums = w.T @ x  # (k, d)
    means = sums / safe[:, None]
    # two-pass variance: deviations from the per-class mean (E[x²]−E[x]²
    # catastrophically cancels in fp32 for data with large means).  The
    # per-row class mean comes from the BINARY onehot — selecting through
    # the weighted ``w`` would scale the mean by the row's weight and
    # corrupt every weighted deviation
    dev = x - onehot @ means
    var = (w.T @ (dev ** 2)) / safe[:, None]
    return counts, means, var


# streamed per-block moments through the central program cache
# (design.md §12): GaussianNB rides Incremental/partial_fit streams, so
# its step program gets the hit/miss books like the SGD family's
# graftlint: disable=donation-miss -- no same-shape pair: the (k,·) block moments are strictly smaller than the (n,·) operands, and the Chan merge consumes them on host-free device state elsewhere
_class_moments = _programs.cached_program(
    _class_moments_fn, name="naive_bayes.class_moments",
)


class GaussianNB(ClassifierMixin, TPUEstimator):
    # stream moments a mid-stream checkpoint must carry (the exposed
    # theta_/var_/class_count_ are trailing-underscore, saved anyway)
    _checkpoint_private_attrs = ("_m2", "_max_var")

    def __init__(self, priors=None, var_smoothing=1e-9):
        self.priors = priors
        self.var_smoothing = var_smoothing

    def fit(self, X, y=None, sample_weight=None):
        for a in ("classes_", "class_count_", "theta_", "_m2", "_max_var"):
            if hasattr(self, a):
                delattr(self, a)
        yv = unshard(y) if isinstance(y, ShardedRows) else np.asarray(y)
        return self.partial_fit(
            X, yv, classes=np.unique(yv), sample_weight=sample_weight
        )

    def partial_fit(self, X, y=None, classes=None, sample_weight=None):
        """Incremental fit over a stream of row blocks (sklearn contract):
        per-class Chan et al. merge of (weight, mean, M2) moments, so
        ``fit`` on one array and a ``partial_fit`` stream over its blocks
        produce identical statistics.  ``sample_weight`` folds into the
        mask (weighted class counts / moments, sklearn semantics)."""
        X = _ingest_float(self, X)
        yv = unshard(y) if isinstance(y, ShardedRows) else np.asarray(y)
        if yv.shape[0] != X.n_samples:
            raise ValueError("X and y have different lengths")
        if not hasattr(self, "classes_"):
            if classes is None:
                raise ValueError(
                    "classes must be passed on the first partial_fit call"
                )
            self.classes_ = np.unique(np.asarray(classes))
            k, d = len(self.classes_), X.data.shape[1]
            self.class_count_ = jnp.zeros((k,), jnp.float32)
            self.theta_ = jnp.zeros((k, d), X.data.dtype)
            self._m2 = jnp.zeros((k, d), X.data.dtype)
            self._max_var = 0.0
        elif classes is not None and not np.array_equal(
            np.unique(np.asarray(classes)), self.classes_
        ):
            raise ValueError(
                f"classes={np.asarray(classes).tolist()} is not the same "
                f"as on the first call to partial_fit "
                f"({self.classes_.tolist()})"
            )
        idx = np.searchsorted(self.classes_, yv)
        bad = (idx >= len(self.classes_)) | (self.classes_[
            np.minimum(idx, len(self.classes_) - 1)] != yv)
        if bad.any():
            raise ValueError(
                f"y contains labels not in classes_: "
                f"{np.unique(yv[bad]).tolist()}"
            )
        idx_padded = np.zeros(X.padded, dtype=np.int64)
        idx_padded[: X.n_samples] = idx
        onehot = jax.nn.one_hot(
            jnp.asarray(idx_padded), len(self.classes_), dtype=X.data.dtype
        )
        mask = X.mask
        if sample_weight is not None:
            from .utils import reweight_rows

            mask = reweight_rows(X, sample_weight=sample_weight).mask
        nb, means_b, var_b = _class_moments(X.data, mask, onehot)

        from .utils import chan_merge

        n2, self.theta_, self._m2 = chan_merge(
            self.class_count_[:, None], self.theta_, self._m2,
            nb[:, None], means_b, var_b,
        )
        n = n2[:, 0]
        self.class_count_ = n

        from .core.sharded import masked_var

        # sklearn keys var_smoothing to the largest feature variance seen
        self._max_var = max(
            self._max_var, float(jnp.max(masked_var(X.data, X.mask)))
        )
        eps = self.var_smoothing * self._max_var
        from .utils import safe_denominator as _sd

        self.var_ = self._m2 / _sd(n)[:, None] + eps
        if self.priors is not None:
            self.class_prior_ = jnp.asarray(self.priors)
        else:
            self.class_prior_ = n / _sd(jnp.sum(n))
        self.n_features_in_ = X.data.shape[1]
        return self

    def _joint_log_likelihood(self, x):
        # (n, k): log P(c) + sum_d log N(x_d | theta, var)
        log_prior = jnp.log(self.class_prior_)[None, :]
        xc = x[:, None, :] - self.theta_[None, :, :]  # (n, k, d)
        ll = -0.5 * jnp.sum(
            jnp.log(2 * jnp.pi * self.var_)[None, :, :] + xc ** 2 / self.var_[None, :, :],
            axis=2,
        )
        return log_prior + ll

    def predict(self, X):
        x, _ = _masked_or_plain(X)
        jll = self._joint_log_likelihood(x)
        idx = jnp.argmax(jll, axis=1)
        n = X.n_samples if isinstance(X, ShardedRows) else x.shape[0]
        return jnp.asarray(self.classes_)[idx][:n]

    def predict_proba(self, X):
        x, _ = _masked_or_plain(X)
        jll = self._joint_log_likelihood(x)
        n = X.n_samples if isinstance(X, ShardedRows) else x.shape[0]
        return jax.nn.softmax(jll, axis=1)[:n]

    def predict_log_proba(self, X):
        return jnp.log(self.predict_proba(X))

    def score(self, X, y, sample_weight=None):
        from .metrics import accuracy_score

        pred = jnp.asarray(self.predict(X)).astype(jnp.float32)
        return accuracy_score(y, pred, sample_weight=sample_weight)

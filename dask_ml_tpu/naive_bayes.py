"""GaussianNB — twin of ``dask_ml/naive_bayes.py`` (SURVEY.md §2 #18):
per-class blockwise moments, here one jitted masked reduction over the
sharded sample axis (the per-class sums are a one-hot gemm like KMeans').
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .base import ClassifierMixin, TPUEstimator
from .core.sharded import ShardedRows, unshard
from .preprocessing.data import _ingest_float, _masked_or_plain


@jax.jit
def _class_moments(x, mask, onehot):
    w = onehot * mask[:, None]  # (n, k)
    counts = jnp.sum(w, axis=0)  # (k,)
    sums = w.T @ x  # (k, d)
    means = sums / counts[:, None]
    # two-pass variance: deviations from the per-class mean (E[x²]−E[x]²
    # catastrophically cancels in fp32 for data with large means)
    dev = x - w @ means  # rows of the wrong class contribute 0 via w below
    var = (w.T @ (dev ** 2)) / counts[:, None]
    return counts, means, var


class GaussianNB(ClassifierMixin, TPUEstimator):
    def __init__(self, priors=None, var_smoothing=1e-9):
        self.priors = priors
        self.var_smoothing = var_smoothing

    def fit(self, X, y=None):
        X = _ingest_float(self, X)
        yv = unshard(y) if isinstance(y, ShardedRows) else np.asarray(y)
        if yv.shape[0] != X.n_samples:
            raise ValueError("X and y have different lengths")
        classes = np.unique(yv)
        idx = np.searchsorted(classes, yv)
        idx_padded = np.zeros(X.padded, dtype=np.int64)
        idx_padded[: X.n_samples] = idx
        onehot = jax.nn.one_hot(jnp.asarray(idx_padded), len(classes), dtype=X.data.dtype)

        counts, means, var = _class_moments(X.data, X.mask, onehot)
        from .core.sharded import masked_var

        eps = self.var_smoothing * float(jnp.max(masked_var(X.data, X.mask)))
        self.classes_ = classes
        self.class_count_ = counts
        self.theta_ = means
        self.var_ = var + eps
        if self.priors is not None:
            self.class_prior_ = jnp.asarray(self.priors)
        else:
            self.class_prior_ = counts / jnp.sum(counts)
        self.n_features_in_ = X.data.shape[1]
        return self

    def _joint_log_likelihood(self, x):
        # (n, k): log P(c) + sum_d log N(x_d | theta, var)
        log_prior = jnp.log(self.class_prior_)[None, :]
        xc = x[:, None, :] - self.theta_[None, :, :]  # (n, k, d)
        ll = -0.5 * jnp.sum(
            jnp.log(2 * jnp.pi * self.var_)[None, :, :] + xc ** 2 / self.var_[None, :, :],
            axis=2,
        )
        return log_prior + ll

    def predict(self, X):
        x, _ = _masked_or_plain(X)
        jll = self._joint_log_likelihood(x)
        idx = jnp.argmax(jll, axis=1)
        n = X.n_samples if isinstance(X, ShardedRows) else x.shape[0]
        return jnp.asarray(self.classes_)[idx][:n]

    def predict_proba(self, X):
        x, _ = _masked_or_plain(X)
        jll = self._joint_log_likelihood(x)
        n = X.n_samples if isinstance(X, ShardedRows) else x.shape[0]
        return jax.nn.softmax(jll, axis=1)[:n]

    def predict_log_proba(self, X):
        return jnp.log(self.predict_proba(X))

    def score(self, X, y, sample_weight=None):
        from .metrics import accuracy_score

        pred = jnp.asarray(self.predict(X)).astype(jnp.float32)
        return accuracy_score(y, pred, sample_weight=sample_weight)

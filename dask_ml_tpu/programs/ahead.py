"""The blessed compile-ahead worker thread.

One background thread — named exactly ``dask-ml-tpu-compile-ahead``,
the single name graftlint's stage-purity/thread-dispatch rules and
graftsan's runtime detectors bless
(``analysis.rules._spmd.BLESSED_COMPILE_THREADS``) — drains a queue of
ahead-of-time compile requests from :class:`~.cache.CachedProgram`:
while block *k* computes on the consumer thread, block *k+1*'s (or the
next bucket's) program lowers and compiles here, so a bucket crossing
in a steady stream never stalls the device behind XLA.

Contract (design.md §12): this thread may COMPILE — trace + lower +
backend-compile, which under omnistaging never executes a device
program — and nothing else.  It never dispatches an estimator surface,
never fetches device values, never joins a collective; graftsan
attributes its compiles separately (``ahead_compiles`` in the
sanitizer baseline) instead of suppressing them, and any other thread
compiling in a steady phase remains a hard-zero violation.  It is
DISTINCT from the input pipeline's ``dask-ml-tpu-prefetch`` staging
worker, which stays fully compile-forbidden.

``DASK_ML_TPU_COMPILE_AHEAD`` (default ``on``) turns the worker off
entirely; with it off every ``warm()`` is a no-op and all compiles
happen on the calling thread, exactly as before this module existed.
"""

from __future__ import annotations

import logging
import os
import queue
import threading
import time

__all__ = [
    "AHEAD_ENV",
    "AHEAD_THREAD_NAME",
    "enabled",
    "submit",
    "drain",
]

logger = logging.getLogger(__name__)

#: policy knob: arm/disarm the compile-ahead worker (strict parse; an
#: unrecognized value raises — the repo's env_choice posture).
AHEAD_ENV = "DASK_ML_TPU_COMPILE_AHEAD"

#: the ONE blessed compile thread name; must stay equal to the entry in
#: ``analysis.rules._spmd.BLESSED_COMPILE_THREADS`` (asserted in
#: tests/test_programs.py) so the static and runtime allowlists cannot
#: drift.
AHEAD_THREAD_NAME = "dask-ml-tpu-compile-ahead"

_LOCK = threading.Lock()
_QUEUE: queue.Queue | None = None
_THREAD: threading.Thread | None = None


def enabled() -> bool:
    """Strict parse of ``DASK_ML_TPU_COMPILE_AHEAD`` (default on)."""
    val = os.environ.get(AHEAD_ENV, "").strip().lower()
    if val in ("", "1", "on", "true", "yes"):
        return True
    if val in ("0", "off", "false", "no"):
        return False
    raise ValueError(
        f"{AHEAD_ENV} must be 0/off/false or 1/on/true; got {val!r}")


def _loop(q: queue.Queue) -> None:
    while True:
        prog, sig, args, static = q.get()
        try:
            prog._compile_entry(sig, args, static, source="ahead")
        except BaseException:  # the worker must outlive any one build
            logger.exception("compile-ahead task for %r failed",
                             getattr(prog, "name", prog))
        finally:
            q.task_done()


def _ensure_worker() -> queue.Queue:
    global _QUEUE, _THREAD
    with _LOCK:
        if _THREAD is None or not _THREAD.is_alive():
            _QUEUE = queue.Queue(maxsize=256)
            # the ONE thread allowed to compile off the main thread: the
            # literal name is what blesses it for graftlint's
            # stage-purity/thread-dispatch rules AND graftsan's runtime
            # compile/dispatch attribution (shared source:
            # analysis.rules._spmd.BLESSED_COMPILE_THREADS)
            _THREAD = threading.Thread(
                target=_loop, args=(_QUEUE,), daemon=True,
                name="dask-ml-tpu-compile-ahead",
            )
            _THREAD.start()
        return _QUEUE


def submit(prog, sig, args, static) -> bool:
    """Enqueue one ahead compile; False when the worker is off or the
    queue is full (the caller then keeps its in-flight marker clear and
    the consumer compiles on demand, exactly the pre-ahead behavior)."""
    if not enabled():
        return False
    try:
        _ensure_worker().put_nowait((prog, sig, args, static))
    except queue.Full:
        return False
    return True


def drain(timeout: float = 30.0) -> bool:
    """Wait until every submitted compile has finished (tests/bench
    determinism).  Returns False on timeout."""
    q = _QUEUE
    if q is None:
        return True
    deadline = time.monotonic() + timeout
    while q.unfinished_tasks:
        if time.monotonic() > deadline:
            return False
        time.sleep(0.005)
    return True

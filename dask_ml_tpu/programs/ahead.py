"""The blessed compile-ahead worker thread.

One background thread — named exactly ``dask-ml-tpu-compile-ahead``,
the single name graftlint's stage-purity/thread-dispatch rules and
graftsan's runtime detectors bless
(``analysis.rules._spmd.BLESSED_COMPILE_THREADS``) — drains a queue of
ahead-of-time compile requests from :class:`~.cache.CachedProgram`:
while block *k* computes on the consumer thread, block *k+1*'s (or the
next bucket's) program lowers and compiles here, so a bucket crossing
in a steady stream never stalls the device behind XLA.

Contract (design.md §12): this thread may COMPILE — trace + lower +
backend-compile, which under omnistaging never executes a device
program — and nothing else.  It never dispatches an estimator surface,
never fetches device values, never joins a collective; graftsan
attributes its compiles separately (``ahead_compiles`` in the
sanitizer baseline) instead of suppressing them, and any other thread
compiling in a steady phase remains a hard-zero violation.  It is
DISTINCT from the input pipeline's ``dask-ml-tpu-prefetch`` staging
worker, which stays fully compile-forbidden.

Fault domain (design.md §13): the worker registers a supervisor
heartbeat and its death can NEVER strand a consumer — every exit path
(a build raising, an injected :class:`~dask_ml_tpu.resilience.
ThreadCrash`, interpreter teardown) fails the in-flight markers of
every task it held, with the error attached, so a consumer waiting on
an in-flight event falls through to the synchronous compile path
immediately instead of sleeping out the 120 s safety valve.  The next
``submit`` restarts the worker (counted as a supervisor restart), up
to :data:`_MAX_RESTARTS` deaths per process — past that the module
degrades LOUDLY to synchronous compiles (one warning; ``warm()``
returns False), exactly the pre-ahead behavior.

``DASK_ML_TPU_COMPILE_AHEAD`` (default ``on``) turns the worker off
entirely; with it off every ``warm()`` is a no-op and all compiles
happen on the calling thread, exactly as before this module existed.
"""

from __future__ import annotations

import logging
import os
import queue
import threading

from .._locks import make_lock
import time

__all__ = [
    "AHEAD_ENV",
    "AHEAD_THREAD_NAME",
    "enabled",
    "submit",
    "drain",
    "worker_alive",
]

logger = logging.getLogger(__name__)

#: policy knob: arm/disarm the compile-ahead worker (strict parse; an
#: unrecognized value raises — the repo's env_choice posture).
AHEAD_ENV = "DASK_ML_TPU_COMPILE_AHEAD"

#: the ONE blessed compile thread name; must stay equal to the entry in
#: ``analysis.rules._spmd.BLESSED_COMPILE_THREADS`` (asserted in
#: tests/test_programs.py) so the static and runtime allowlists cannot
#: drift.
AHEAD_THREAD_NAME = "dask-ml-tpu-compile-ahead"

#: how many worker deaths this process tolerates before degrading to
#: synchronous compiles for good (a crash-looping builder must not spin)
_MAX_RESTARTS = 3

_LOCK = make_lock("programs.ahead")
_QUEUE: queue.Queue | None = None
_THREAD: threading.Thread | None = None
_DEATHS = 0
_DEGRADED_WARNED = False


def enabled() -> bool:
    """Strict parse of ``DASK_ML_TPU_COMPILE_AHEAD`` (default on)."""
    val = os.environ.get(AHEAD_ENV, "").strip().lower()
    if val in ("", "1", "on", "true", "yes"):
        return True
    if val in ("0", "off", "false", "no"):
        return False
    raise ValueError(
        f"{AHEAD_ENV} must be 0/off/false or 1/on/true; got {val!r}")


def worker_alive() -> bool:
    """Is the blessed thread currently running?  (Consumers waiting on
    an in-flight ahead build poll this: a dead builder means no one
    will ever set their event — fall through to a demand compile.)"""
    t = _THREAD
    return t is not None and t.is_alive()


def _fail_task(task, exc: BaseException) -> None:
    """Fail one queued build's in-flight marker (error attached) so any
    consumer waiting on it falls through to the synchronous path."""
    prog, sig = task[0], task[1]
    try:
        prog._ahead_failed(sig, exc)
    except Exception:  # pragma: no cover - forensic path must not throw
        logger.exception("failing ahead task for %r raised",
                         getattr(prog, "name", prog))


def _drain_failed(q: queue.Queue | None, exc: BaseException) -> None:
    """Fail every task still queued (the worker is dead: no one will
    build them)."""
    if q is None:
        return
    while True:
        try:
            task = q.get_nowait()
        except queue.Empty:
            return
        _fail_task(task, exc)
        q.task_done()


def _loop(q: queue.Queue) -> None:
    from ..resilience import supervisor as _supervisor
    from ..resilience.testing import ThreadCrash as _ThreadCrash
    from ..resilience.testing import maybe_fault as _maybe_fault

    hb = _supervisor.register(AHEAD_THREAD_NAME, "compile",
                              thread=threading.current_thread())
    try:
        while True:
            task = q.get()
            hb.beat()
            prog, sig, args, static = task
            try:
                # drill point: a ThreadCrash here simulates the builder
                # dying mid-build — the set-on-failure contract below is
                # what keeps its consumer from hanging
                _maybe_fault("compile-ahead")
                prog._compile_entry(sig, args, static, source="ahead")
            except _ThreadCrash as exc:
                _fail_task(task, exc)
                q.task_done()
                raise  # hard death: the finally fails the rest
            except BaseException as exc:
                # the worker must outlive any one build; _compile_entry
                # handles Exception itself (source="ahead" swallows), so
                # only escapes land here — fail the marker with the
                # error attached and keep draining
                logger.exception("compile-ahead task for %r failed",
                                 getattr(prog, "name", prog))
                _fail_task(task, exc)
                q.task_done()
            else:
                q.task_done()
    except BaseException as exc:
        # the worker is dying (injected crash, interpreter teardown, a
        # queue failure): no queued build may strand its waiter
        _supervisor.note_death("compile", AHEAD_THREAD_NAME,
                               error=f"{type(exc).__name__}: {exc}")
        _drain_failed(q, exc)
        if not isinstance(exc, _ThreadCrash):
            raise


def _ensure_worker() -> queue.Queue | None:
    """The live worker's queue, (re)starting the thread as needed;
    ``None`` once the restart budget is spent (degraded: synchronous
    compiles only)."""
    global _QUEUE, _THREAD, _DEATHS, _DEGRADED_WARNED
    from ..resilience import supervisor as _supervisor

    with _LOCK:
        if _THREAD is not None and _THREAD.is_alive():
            return _QUEUE
        if _THREAD is not None:
            # a previous worker died; its dying drain already failed its
            # tasks, but a submit racing the death can strand one — fail
            # leftovers before dropping the queue
            _DEATHS += 1
            _drain_failed(
                _QUEUE, RuntimeError("compile-ahead worker died"))
            if _DEATHS > _MAX_RESTARTS:
                if not _DEGRADED_WARNED:
                    _DEGRADED_WARNED = True
                    logger.warning(
                        "compile-ahead worker died %d times; degrading "
                        "to synchronous compiles for the rest of this "
                        "process", _DEATHS)
                return None
            _supervisor.note_restart("compile", AHEAD_THREAD_NAME)
        _QUEUE = queue.Queue(maxsize=256)
        # the ONE thread allowed to compile off the main thread: the
        # literal name is what blesses it for graftlint's
        # stage-purity/thread-dispatch rules AND graftsan's runtime
        # compile/dispatch attribution (shared source:
        # analysis.rules._spmd.BLESSED_COMPILE_THREADS)
        # graftlint: disable=thread-dispatch -- blessed compile-ahead worker: compiles + host-only supervisor/flight bookkeeping, never dispatches (runtime-verified by graftsan's dispatch detector and the ahead-crash drill)
        _THREAD = threading.Thread(
            target=_loop, args=(_QUEUE,), daemon=True,
            name="dask-ml-tpu-compile-ahead",
        )
        _THREAD.start()
        return _QUEUE


def submit(prog, sig, args, static) -> bool:
    """Enqueue one ahead compile; False when the worker is off, dead
    past its restart budget, or the queue is full (the caller then
    keeps its in-flight marker clear and the consumer compiles on
    demand, exactly the pre-ahead behavior)."""
    if not enabled():
        return False
    q = _ensure_worker()
    if q is None:
        return False
    try:
        q.put_nowait((prog, sig, args, static))
    except queue.Full:
        return False
    return True


def drain(timeout: float = 30.0) -> bool:
    """Wait until every submitted compile has finished (tests/bench
    determinism).  Returns False on timeout; a dead worker's leftover
    tasks are failed (set-on-failure) rather than waited out."""
    q = _QUEUE
    if q is None:
        return True
    deadline = time.monotonic() + timeout
    while q.unfinished_tasks:
        if not worker_alive():
            _drain_failed(q, RuntimeError("compile-ahead worker died"))
            return q.unfinished_tasks == 0
        if time.monotonic() > deadline:
            return False
        time.sleep(0.005)
    return True


def _reset_restarts_for_tests() -> None:
    """Re-arm the restart budget (drills/tests inject deliberate worker
    deaths and must not consume the process's real budget)."""
    global _DEATHS, _DEGRADED_WARNED
    with _LOCK:
        _DEATHS = 0
        _DEGRADED_WARNED = False

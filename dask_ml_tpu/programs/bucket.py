"""Shape-bucketing policy: pad ragged row blocks to a small shape set.

Every streamed block that reaches a jitted step with a fresh row count
mints a fresh XLA program — the recompile tax SURVEY §7 hard part (c)
names: ragged CSV tails, heterogeneous search configs, and variable
serving request shapes all retrigger compiles.  The fix this repo has
always used (``linear_model._sgd._BUCKETS``) is to pad the batch axis up
to one of a few bucket sizes and let the row-validity mask carry
correctness (padding rows weigh 0.0 in every masked reduction, and
adding exact zeros never changes an IEEE sum).

This module is that discipline centralized behind ONE policy knob,
``DASK_ML_TPU_BUCKET``:

* ``auto`` (default, and the empty string): the committed
  :data:`DEFAULT_BUCKETS` ladder — blocks pad to the next rung, blocks
  beyond the top rung round up to a multiple of it.  Identical to the
  historical ``_sgd`` behavior.
* ``off``: no bucketing — every distinct block length is its own
  program shape (the A/B control arm of the ``recompile_tax`` bench).
* ``pow2``: pad to the next power of two (unbounded ladder; useful when
  block sizes vary over orders of magnitude).
* ``"256,4096,65536"``: an explicit ascending ladder (same semantics
  as ``auto`` with those rungs).

The knob is read at *call* time (the repo's policy-knob contract), and
an unparseable value raises loudly — a typo'd policy must never
silently disable bucketing.

:func:`pad_block` is the shared pad+mask entry every staged estimator
path uses (SGD ``_prep_block_host``, MiniBatchKMeans ``_pf_stage``);
it runs on the prefetch worker thread, so it is pure numpy + metric
counters — no jax.  The counters (``bucket.blocks`` /
``bucket.padded_blocks`` / ``bucket.pad_rows``) surface through
``diagnostics.pipeline_report()``'s cumulative block and
``diagnostics.program_report()``: a reader that already emits
bucket-sized chunks must show ``padded_blocks == 0`` (the pad is a
no-op fast path, asserted in tests/test_programs.py).
"""

from __future__ import annotations

import os

import numpy as np

from ..obs.metrics import registry as _registry

__all__ = [
    "BUCKET_ENV",
    "DEFAULT_BUCKETS",
    "BucketPolicy",
    "resolve_policy",
    "bucket_rows",
    "counters_snapshot",
    "pad_block",
]


def counters_snapshot() -> dict:
    """The pad split as both reports surface it
    (``pipeline_report().cumulative.bucket`` and
    ``program_report().bucket``) — one reader next to the one writer in
    :func:`pad_block`, so the counter names cannot drift between them."""
    reg = _registry()
    return {
        "blocks": reg.family("bucket.blocks").get("", 0),
        "padded_blocks": reg.family("bucket.padded_blocks").get("", 0),
        "pad_rows": reg.family("bucket.pad_rows").get("", 0),
    }

#: policy knob: how streamed block row counts map to compiled shapes.
BUCKET_ENV = "DASK_ML_TPU_BUCKET"

#: the committed default ladder (the historical ``_sgd._BUCKETS``): a
#: stream of ragged chunk sizes compiles at most len()+tail programs
#: per (d, k) shape.
DEFAULT_BUCKETS = (256, 1024, 4096, 16384, 65536)


class BucketPolicy:
    """One resolved bucketing policy: ``kind`` ∈ off / pow2 / sizes."""

    __slots__ = ("kind", "sizes")

    def __init__(self, kind: str, sizes: tuple | None = None):
        self.kind = kind
        self.sizes = sizes

    def bucket(self, n: int) -> int:
        """The padded row count for a block of ``n`` real rows."""
        n = int(n)
        # empty blocks stay empty under EVERY policy: padding 0 real
        # rows up to a nonempty shape would run a pure-padding device
        # step for nothing
        if n <= 0:
            return 0
        if self.kind == "off":
            return n
        if self.kind == "pow2":
            return 1 << (n - 1).bit_length()
        for b in self.sizes:
            if n <= b:
                return b
        top = self.sizes[-1]
        return ((n + top - 1) // top) * top

    def rungs(self, max_rows: int) -> tuple:
        """Every padded row count this policy can produce for blocks of
        1..``max_rows`` real rows, ascending — the serve plane's
        load-time warm set (serve/residency.py pre-compiles one predict
        program per rung so the micro-batch loop never compiles in
        steady state).  ``off`` returns ``()``: every length is its own
        shape and pre-warming is meaningless."""
        max_rows = int(max_rows)
        if max_rows <= 0 or self.kind == "off":
            return ()
        if self.kind == "pow2":
            out, b = [], 1
            while b < max_rows:
                out.append(b)
                b <<= 1
            out.append(b)
            return tuple(out)
        top = self.bucket(max_rows)
        out = [b for b in self.sizes if b < top]
        # beyond the ladder's last rung, bucket() rounds to multiples of
        # it — enumerate those too so the warm set covers every shape a
        # coalesced batch of <= max_rows rows can pad to
        step = self.sizes[-1]
        b = out[-1] + step if out and out[-1] >= step else step
        while b < top:
            out.append(b)
            b += step
        out.append(top)
        return tuple(out)

    def __eq__(self, other):
        return (isinstance(other, BucketPolicy)
                and self.kind == other.kind and self.sizes == other.sizes)

    def __repr__(self):
        if self.kind == "sizes":
            return f"BucketPolicy(sizes={self.sizes})"
        return f"BucketPolicy({self.kind!r})"


_AUTO = BucketPolicy("sizes", DEFAULT_BUCKETS)
_OFF = BucketPolicy("off")
_POW2 = BucketPolicy("pow2")


def resolve_policy(policy: str | BucketPolicy | None = None) -> BucketPolicy:
    """Resolve a bucketing policy: explicit argument, else the
    ``DASK_ML_TPU_BUCKET`` env knob, else ``auto`` (the default ladder).

    Accepts ``off`` / ``pow2`` / ``auto`` / a comma-separated ascending
    list of positive ints; anything else raises (the repo's strict
    env-parse posture — a typo must not silently change the compile
    set)."""
    if isinstance(policy, BucketPolicy):
        return policy
    raw = policy if policy is not None else os.environ.get(BUCKET_ENV, "")
    raw = raw.strip().lower()
    if raw in ("", "auto", "default"):
        return _AUTO
    if raw == "off":
        return _OFF
    if raw == "pow2":
        return _POW2
    try:
        sizes = tuple(int(s) for s in raw.split(",") if s.strip())
    except ValueError:
        sizes = ()
    if not sizes or any(b <= 0 for b in sizes) or \
            list(sizes) != sorted(set(sizes)):
        raise ValueError(
            f"{BUCKET_ENV} must be 'off', 'pow2', 'auto', or a "
            f"strictly-ascending comma-separated list of positive ints; "
            f"got {raw!r}")
    return BucketPolicy("sizes", sizes)


def bucket_rows(n: int, policy: str | BucketPolicy | None = None) -> int:
    """The bucketed row count for ``n`` real rows under ``policy``
    (default: the ``DASK_ML_TPU_BUCKET`` knob)."""
    return resolve_policy(policy).bucket(n)


def pad_block(X: np.ndarray, targets: np.ndarray | None = None,
              policy: str | BucketPolicy | None = None):
    """Zero-pad host block rows to the policy's bucket, with a validity
    mask.  Returns ``(X_padded, targets_padded_or_None, mask)``.

    The ONE pad entry the staged estimator paths share (SGD,
    MiniBatchKMeans), so the bucketing discipline cannot drift between
    them.  Safe on the prefetch worker thread: numpy + counters only.
    A block that already arrives bucket-sized takes the no-op fast
    path — no copy, no concatenate, just a ones mask — and counts as
    unpadded in the ``bucket.*`` metrics (how the pipeline report and
    tests assert the reader/bucket agreement)."""
    n = X.shape[0]
    b = resolve_policy(policy).bucket(n)
    reg = _registry()
    reg.counter("bucket.blocks").inc()
    if b == n:
        # no-op fast path: the reader already emits bucket-sized chunks
        return X, targets, np.ones(n, dtype=np.float32)
    reg.counter("bucket.padded_blocks").inc()
    reg.counter("bucket.pad_rows").inc(b - n)
    mask = np.zeros(b, dtype=np.float32)
    mask[:n] = 1.0
    X = np.concatenate([X, np.zeros((b - n,) + X.shape[1:], X.dtype)])
    if targets is not None:
        targets = np.concatenate(
            [targets, np.zeros((b - n,) + targets.shape[1:], targets.dtype)]
        )
    return X, targets, mask

"""Central compiled-program cache, shape bucketing, and compile-ahead.

The ROADMAP ``[compile]`` lane (design.md §12): recompilation is the
hidden tax on every other lane — ragged streamed tails, heterogeneous
search configs, and variable serving shapes all retrigger XLA compiles.
This package is the one place program shapes are decided and compiled
programs live:

* :mod:`.bucket` — the ``DASK_ML_TPU_BUCKET`` shape-bucketing policy
  (off / pow2 / explicit ladders) behind the shared
  :func:`pad_block` every staged estimator path uses;
* :mod:`.cache` — :class:`CachedProgram`, the cache every step-program
  dispatch goes through instead of a bare ``jax.jit`` (the
  ``jit-outside-cache`` lint rule holds new code to that), with
  hit/miss/ahead-hit books and the ``DASK_ML_TPU_COMPILE_CACHE``
  persistent XLA cache knob;
* :mod:`.ahead` — the blessed ``dask-ml-tpu-compile-ahead`` worker
  thread that pre-compiles the next bucket's program while the current
  block computes (``DASK_ML_TPU_COMPILE_AHEAD``).

``diagnostics.program_report()`` is the user-facing view of
:func:`report`.
"""

from .ahead import (  # noqa: F401
    AHEAD_ENV,
    AHEAD_THREAD_NAME,
    drain as drain_ahead,
    enabled as compile_ahead_enabled,
    submit,
    worker_alive as ahead_worker_alive,
)
from .bucket import (  # noqa: F401
    BUCKET_ENV,
    DEFAULT_BUCKETS,
    BucketPolicy,
    bucket_rows,
    pad_block,
    resolve_policy,
)
from .cache import (  # noqa: F401
    CACHE_DIR_ENV,
    CachedProgram,
    cached_program,
    enable_persistent_cache,
    report,
    reset_counters,
)

__all__ = [
    "AHEAD_ENV",
    "AHEAD_THREAD_NAME",
    "ahead_worker_alive",
    "BUCKET_ENV",
    "CACHE_DIR_ENV",
    "DEFAULT_BUCKETS",
    "BucketPolicy",
    "CachedProgram",
    "bucket_rows",
    "cached_program",
    "compile_ahead_enabled",
    "drain_ahead",
    "enable_persistent_cache",
    "pad_block",
    "report",
    "reset_counters",
    "resolve_policy",
    "submit",
]

"""The central compiled-program cache every dispatch path goes through.

``jax.jit``'s own executable cache is per-wrapped-function and
invisible: nothing can ask it what is warm, pre-compile the next shape
on another thread, or report how much compile time a stream paid.  A
:class:`CachedProgram` replaces the bare ``partial(jax.jit, ...)``
idiom at the repo's step-program definitions with a cache this code
owns:

* every distinct *signature* — pytree structure + per-leaf
  (shape, dtype, weak_type, sharding) + static argument values — maps
  to ONE ahead-of-time compiled executable
  (``jitted.lower(...).compile()``), dispatched directly on later
  calls (measured: warm AOT dispatch costs the same ~18µs as the jit
  fastpath on this image);
* a *miss* compiles on the calling thread (warmup-class work, exactly
  what ``jax.jit`` would have done);
* :meth:`CachedProgram.warm` registers the signature as in-flight and
  hands the compile to the dedicated ``dask-ml-tpu-compile-ahead``
  thread (:mod:`.ahead`) — a consumer that arrives before the compile
  finishes WAITS on it (one compile, attributed to the blessed
  thread) instead of racing a duplicate;
* anything the cache cannot prove it handles — tracer arguments (the
  program is being inlined into an outer jit), unexpected keyword
  arrays, an executable that rejects the concrete operands
  (sharding/layout drift) — falls back to the plain jitted path, which
  is bit-identical by construction (same function, same jit options).

Hit / miss / ahead-hit / fallback counters and compile seconds land in
the obs metrics registry (``program.*``, tagged per program name) and
in :func:`report` — surfaced as ``diagnostics.program_report()`` and
ratcheted by the ``recompile_tax`` bench workload.

The persistent XLA compilation cache (cold-start killer across bench
rounds and multihost workers) arms behind ``DASK_ML_TPU_COMPILE_CACHE``
the first time any program compiles; see
:func:`enable_persistent_cache`.
"""

from __future__ import annotations

import logging
import os
import threading

from .._locks import make_lock
import time

import numpy as np

import jax

from ..obs import roofline as _roofline
from ..obs import scope as _scope
from ..obs.metrics import registry as _registry

__all__ = [
    "CACHE_DIR_ENV",
    "CachedProgram",
    "cached_program",
    "enable_persistent_cache",
    "report",
    "reset_counters",
]

logger = logging.getLogger(__name__)

#: policy knob: directory for jax's persistent XLA compilation cache
#: ('' = off, the default).  Shared across processes: bench rounds and
#: multihost workers stop paying cold compiles for programs any prior
#: process already built.
CACHE_DIR_ENV = "DASK_ML_TPU_COMPILE_CACHE"

#: how long a consumer waits on an in-flight compile-ahead build before
#: giving up and compiling on its own thread (a safety valve, not a
#: steady-state path — ahead compiles are small step programs).
_AHEAD_WAIT_S = 120.0

_REG_LOCK = make_lock("programs.registry")
_BY_NAME: dict[str, "CachedProgram"] = {}

_PERSISTENT = {"armed": False, "dir": None, "error": None}
_PERSISTENT_LOCK = make_lock("programs.persistent")


def enable_persistent_cache(path: str | None = None) -> str | None:
    """Arm jax's persistent XLA compilation cache at ``path`` (default:
    the ``DASK_ML_TPU_COMPILE_CACHE`` knob; ``''`` leaves it off).

    Returns the armed directory or None.  Called lazily before the
    first compile in this module, and idempotent — the thresholds are
    opened up (min size/time → 0) so even the small step programs this
    repo streams get cached.  Fail-soft: an unwritable directory or an
    unsupported backend logs one warning and leaves the in-process
    behavior untouched (the persistent cache is an accelerator, never
    a correctness dependency)."""
    with _PERSISTENT_LOCK:
        if _PERSISTENT["armed"]:
            return _PERSISTENT["dir"]
        if path is None:
            path = os.environ.get(CACHE_DIR_ENV, "").strip()
        if not path:
            return None
        try:
            os.makedirs(path, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", path)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                              -1)
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              0.0)
        except Exception as e:  # pragma: no cover - backend-dependent
            _PERSISTENT["armed"], _PERSISTENT["error"] = True, str(e)
            logger.warning(
                "persistent compilation cache at %r could not be armed "
                "(%s); continuing without it", path, e)
            return None
        _PERSISTENT["armed"], _PERSISTENT["dir"] = True, path
        return path


# -- signatures ----------------------------------------------------------

def _structure(tree, leaves: list):
    """Deterministic hashable structure token; appends leaves in order.
    Hand-rolled (tuple/list/dict/None only) so the signature path stays
    provably host-only for the stage-purity reachability analysis —
    ``_pf_stage`` implementations call :meth:`CachedProgram.warm` from
    the prefetch worker thread."""
    if tree is None:
        return "-"
    if isinstance(tree, (tuple, list)):
        return ("T", tuple(_structure(x, leaves) for x in tree))
    if isinstance(tree, dict):
        return ("D", tuple((k, _structure(tree[k], leaves))
                           for k in sorted(tree)))
    leaves.append(tree)
    return "*"


def _leaf_key(x):
    """(shape, dtype, weak_type, sharding-token) for one leaf, or None
    for a leaf the cache must not reason about (tracers, opaque
    objects).  A ShapeDtypeStruct keys identically to the concrete
    array it stands for, so a warm() built from shapes matches the
    consumer's real operands."""
    if isinstance(x, jax.core.Tracer):
        return None
    if isinstance(x, jax.ShapeDtypeStruct):
        sh = getattr(x, "sharding", None)
        return (tuple(x.shape), str(np.dtype(x.dtype)), False,
                None if sh is None else repr(sh))
    if isinstance(x, jax.Array):
        aval = x.aval
        return (tuple(aval.shape), str(aval.dtype),
                bool(getattr(aval, "weak_type", False)), _sharding_token(x))
    if isinstance(x, np.ndarray):
        return (tuple(x.shape), str(x.dtype), False, "host")
    if isinstance(x, (bool, int, float, complex, np.generic)):
        return ("py", type(x).__name__)
    return None


def _sharding_token(x) -> str | None:
    """None for plain default-device placement (what an unsharded
    lowering binds to), a repr for anything committed elsewhere —
    NamedSharding'd ShardedRows data keys distinctly from host-upload
    blocks, so one program never sees both layouts."""
    try:
        sh = x.sharding
        from jax.sharding import SingleDeviceSharding

        if isinstance(sh, SingleDeviceSharding):
            (dev,) = sh.device_set
            return None if dev == jax.devices()[0] else repr(sh)
        return repr(sh)
    except Exception:  # pragma: no cover - exotic array types
        return "unknown"


class _Entry:
    __slots__ = ("compiled", "source", "compile_s", "consumer_hits", "bad",
                 "cost")

    def __init__(self, compiled, source: str, compile_s: float):
        self.compiled = compiled
        self.source = source          # "demand" | "ahead"
        self.compile_s = compile_s
        self.consumer_hits = 0
        self.bad = False
        # XLA's static flop/byte estimate for THIS signature's
        # executable (obs/roofline.py; None when the backend cannot
        # say) — joined with the dispatch's device interval so
        # device_report() can attribute achieved FLOP/s per program
        self.cost = _roofline.capture_cost(compiled)


def _new_counters() -> dict:
    return {
        "hits": 0, "misses": 0, "ahead_hits": 0, "ahead_submitted": 0,
        "ahead_errors": 0, "bypass": 0, "fallback": 0,
        "compile_s": 0.0, "ahead_compile_s": 0.0, "saved_s": 0.0,
        "wait_s": 0.0,
    }


class CachedProgram:
    """One jit-wrapped step function behind the central cache.

    Drop-in for the ``partial(jax.jit, static_argnames=...,
    donate_argnames=...)(fn)`` idiom: call it exactly like the jitted
    function (statics as keywords).  Unknown keyword arrays, tracer
    operands, and executable/operand mismatches all route through the
    plain jitted twin — the cache can only ever change WHERE a compile
    happens, never what runs.
    """

    def __init__(self, fn, *, name: str, static_argnames=(),
                 donate_argnames=(), **jit_kwargs):
        self.name = name
        self.fn = fn
        self._static = tuple(static_argnames)
        # the one sanctioned direct jit wrap: every CachedProgram's
        # fallback/lowering twin is built here
        # graftlint: disable=jit-outside-cache -- the cache's own internal jit wrap; all call sites route through CachedProgram
        self._jitted = jax.jit(
            fn, static_argnames=tuple(static_argnames) or None,
            donate_argnames=tuple(donate_argnames) or None, **jit_kwargs)
        self._lock = make_lock("programs.cache")
        self._entries: dict = {}
        self._inflight: dict = {}
        self.counters = _new_counters()
        with _REG_LOCK:
            _BY_NAME[name] = self

    # expose the jitted twin's surface (lower/trace/etc.) for callers
    # that need the raw AOT API
    def __getattr__(self, item):
        jitted = self.__dict__.get("_jitted")
        if jitted is None:  # mid-__init__ / unpickle: no twin yet
            raise AttributeError(item)
        return getattr(jitted, item)

    # -- signature -------------------------------------------------------
    def signature(self, args, static: dict):
        leaves: list = []
        tok = _structure(args, leaves)
        keys = []
        for leaf in leaves:
            k = _leaf_key(leaf)
            if k is None:
                return None
            keys.append(k)
        try:
            stat = tuple(sorted(static.items()))
            hash(stat)
        except TypeError:
            return None
        return (tok, tuple(keys), stat)

    # -- dispatch --------------------------------------------------------
    def _run_tracked(self, fn, args, kwargs=None, cost=None):
        """Dispatch through ``fn`` with graftscope device-time tracking:
        the in-flight interval opens at the enqueue and closes when the
        outputs report ready (obs/scope.py).  ``absorb()`` keeps the
        graftsan ``ExecuteReplicated`` hook — which this same call
        funnels through while a sanitizer is active — from opening a
        duplicate interval; the cache end owns the attribution (it
        knows the program's registry name).  ``cost`` is the entry's
        captured cost_analysis on the AOT path (None on the jitted-twin
        fallback — an unattributed dispatch reports time but no work,
        honest either way)."""
        t0 = time.perf_counter()
        with _scope.absorb():
            out = fn(*args, **kwargs) if kwargs else fn(*args)
        _scope.track(self.name, t0, jax.tree_util.tree_leaves(out),
                     cost=cost)
        return out

    def __call__(self, *args, **kwargs):
        static = {k: v for k, v in kwargs.items() if k in self._static}
        if len(static) != len(kwargs):
            # non-static keyword operands: shapes the cache does not
            # model — the jitted twin handles them identically
            self._count("bypass")
            return self._run_tracked(self._jitted, args, kwargs)
        sig = self.signature(args, static)
        if sig is None:
            self._count("bypass")
            return self._run_tracked(self._jitted, args, kwargs)
        entry, how = self._lookup_or_compile(sig, args, static)
        if entry is None or entry.bad:
            self._count("fallback")
            return self._run_tracked(self._jitted, args, kwargs)
        try:
            out = self._run_tracked(entry.compiled, args, cost=entry.cost)
        except (TypeError, ValueError) as e:
            # operand/executable mismatch (these raise BEFORE execution,
            # so donated buffers are intact): permanently route this
            # signature through the jitted twin
            entry.bad = True
            self._count("fallback")
            logger.debug("program %s: compiled-call mismatch (%s); "
                         "falling back to jit", self.name, e)
            return self._run_tracked(self._jitted, args, kwargs)
        # first-consumer accounting under the lock: two threads
        # dispatching the same warm entry concurrently must not both
        # read consumer_hits == 0 and double-book the ahead hit
        with self._lock:
            first = entry.consumer_hits == 0
            entry.consumer_hits += 1
            if first and entry.source == "ahead":
                self.counters["saved_s"] += entry.compile_s
        if how == "hit":
            self._count("hits")
        if first and entry.source == "ahead":
            self._count("ahead_hits")
            _registry().counter("program.ahead_hit", self.name).inc()
        return out

    def _lookup_or_compile(self, sig, args, static):
        # single-flight per signature: whoever registers the in-flight
        # marker under the lock is THE builder; everyone else waits on
        # its event (an ahead build, or a concurrent demand miss from a
        # search-pool thread) instead of racing a duplicate backend
        # compile of the identical program
        while True:
            with self._lock:
                e = self._entries.get(sig)
                if e is not None:
                    return e, "hit"
                ev = self._inflight.get(sig)
                if ev is None:
                    self._inflight[sig] = threading.Event()
                    break  # we are the builder
            t0 = time.perf_counter()
            done = self._wait_inflight(ev)
            with self._lock:
                self.counters["wait_s"] += time.perf_counter() - t0
                e = self._entries.get(sig)
            if e is not None:
                return e, "hit"
            if not done:
                # builder wedged past the deadline — or the blessed
                # ahead thread died with this build still queued (the
                # liveness poll in _wait_inflight): safety-valve compile
                # on this thread (its eventual finish pops the marker
                # benignly; _lookup's own marker registration below is
                # what makes the duplicate at worst one extra compile)
                with self._lock:
                    if self._inflight.get(sig) is ev:
                        self._inflight.pop(sig, None)
                break
            # builder finished with no entry (its build failed — the
            # event carries the error when the ahead worker died): loop
            # — the marker is gone, so we register and build ourselves,
            # surfacing the real error on this thread
        self._count("misses")
        return self._compile_entry(sig, args, static, source="demand"), \
            "miss"

    @staticmethod
    def _wait_inflight(ev) -> bool:
        """Wait on another builder's in-flight event, with a liveness
        poll when the builder is the blessed ahead thread: a dead
        builder will never set its event (its dying drain fails queued
        markers, but a submit racing the death can strand one), so a
        dead-thread verdict converts the 120 s safety valve into an
        immediate fall-through to the synchronous compile path."""
        from . import ahead as _ahead

        if not getattr(ev, "ahead", False):
            return ev.wait(_AHEAD_WAIT_S)
        deadline = time.perf_counter() + _AHEAD_WAIT_S
        while True:
            if ev.wait(0.2):
                return True
            if not _ahead.worker_alive():
                return ev.wait(0.05)  # one last look: it may have just set
            if time.perf_counter() >= deadline:
                return False

    def _ahead_failed(self, sig, exc: BaseException) -> None:
        """The blessed compile-ahead worker could not build ``sig`` (the
        build raised past its own net, or the worker died with the task
        queued/in hand): pop the in-flight marker and SET the event with
        the error attached, so a consumer waiting on it falls through to
        the synchronous compile path immediately — a dead builder must
        never read as an in-flight one (design.md §13)."""
        with self._lock:
            ev = self._inflight.pop(sig, None)
        self._count("ahead_errors")
        if ev is not None:
            ev.error = exc
            ev.set()
        logger.warning(
            "compile-ahead build of %s failed (%s: %s); consumers fall "
            "back to the synchronous compile path",
            self.name, type(exc).__name__, exc)

    # -- compilation (consumer thread on miss; blessed thread on warm) ---
    def _compile_entry(self, sig, args, static, source: str):
        enable_persistent_cache()
        t0 = time.perf_counter()
        entry = None
        try:
            compiled = self._jitted.lower(*args, **static).compile()
            entry = _Entry(compiled, source, time.perf_counter() - t0)
            try:
                # tell the roofline layer what platform cost estimates
                # belong to (roofline itself never imports jax, so the
                # host-only sampler/scrape threads can read it freely)
                _roofline.note_platform(jax.default_backend())
            except Exception:  # pragma: no cover - backend query failure
                pass
        except Exception as e:
            if source == "ahead":
                # the consumer's own demand path still works; record and
                # move on (warm() must never be able to break a fit)
                self._count("ahead_errors")
                logger.warning("compile-ahead of %s failed: %s",
                               self.name, e)
            else:
                with self._lock:
                    ev = self._inflight.pop(sig, None)
                if ev is not None:
                    ev.set()
                raise
        finally:
            if entry is not None:
                key = ("ahead_compile_s" if source == "ahead"
                       else "compile_s")
                with self._lock:
                    self._entries[sig] = entry
                    self.counters[key] += entry.compile_s
                    ev = self._inflight.pop(sig, None)
                if ev is not None:
                    ev.set()
                _registry().histogram(f"program.{key}").record(
                    entry.compile_s)
            elif source == "ahead":
                with self._lock:
                    ev = self._inflight.pop(sig, None)
                if ev is not None:
                    ev.set()
        return entry

    # -- compile-ahead ---------------------------------------------------
    def warm(self, args, **static) -> bool:
        """Request an ahead-of-time compile of the program for ``args``
        (a pytree of ``jax.ShapeDtypeStruct`` — or concrete arrays —
        matching a future call's operands) on the dedicated
        ``dask-ml-tpu-compile-ahead`` thread.

        Returns True when a compile was enqueued; False when the
        signature is already built/in-flight, compile-ahead is off, or
        the worker could not take it.  Registers the in-flight marker
        SYNCHRONOUSLY, so a consumer that calls before the build
        finishes waits on it instead of compiling a duplicate.  Safe on
        the prefetch worker thread: signature math and a queue put,
        nothing device-touching."""
        from . import ahead

        if not ahead.enabled():
            return False
        sig = self.signature(args, static)
        if sig is None:
            return False
        ev = threading.Event()
        ev.ahead = True  # waiters poll the blessed thread's liveness
        with self._lock:
            if sig in self._entries or sig in self._inflight:
                return False
            self._inflight[sig] = ev
        if not ahead.submit(self, sig, args, static):
            with self._lock:
                self._inflight.pop(sig, None)
            ev.set()
            return False
        self._count("ahead_submitted")
        return True

    # -- books -----------------------------------------------------------
    def _count(self, key: str) -> None:
        with self._lock:
            self.counters[key] += 1
        name = {"hits": "program.hit", "misses": "program.miss",
                "bypass": "program.bypass", "fallback": "program.fallback",
                "ahead_submitted": "program.ahead_submit",
                "ahead_errors": "program.ahead_error"}.get(key)
        if name is not None:
            _registry().counter(name, self.name).inc()

    def report(self) -> dict:
        with self._lock:
            out = dict(self.counters)
            out["programs"] = len(self._entries)
            out["inflight"] = len(self._inflight)
            out["cost_known"] = sum(1 for e in self._entries.values()
                                    if e.cost is not None)
        for k in ("compile_s", "ahead_compile_s", "saved_s", "wait_s"):
            out[k] = round(out[k], 6)
        return out

    def reset_counters(self) -> None:
        with self._lock:
            self.counters = _new_counters()
            for e in self._entries.values():
                e.consumer_hits = 0

    def clear(self) -> None:
        """Drop every compiled executable (test isolation; the next call
        per signature recompiles)."""
        with self._lock:
            self._entries.clear()
            self.counters = _new_counters()


def cached_program(fn, *, name: str, static_argnames=(),
                   donate_argnames=(), **jit_kwargs) -> CachedProgram:
    """Factory for the module-level ``_jitted_* = cached_program(...)``
    idiom (mirrors ``partial(jax.jit, ...)(fn)``)."""
    return CachedProgram(fn, name=name, static_argnames=static_argnames,
                         donate_argnames=donate_argnames, **jit_kwargs)


def report() -> dict:
    """Per-program cache books + totals — the
    ``diagnostics.program_report()`` payload."""
    with _REG_LOCK:
        progs = dict(_BY_NAME)
    per = {name: p.report() for name, p in sorted(progs.items())}
    totals = _new_counters()
    totals["programs"] = 0
    for r in per.values():
        for k in totals:
            totals[k] += r.get(k, 0)
    for k in ("compile_s", "ahead_compile_s", "saved_s", "wait_s"):
        totals[k] = round(totals[k], 6)
    from .bucket import counters_snapshot

    return {
        "programs": per,
        "totals": totals,
        "bucket": counters_snapshot(),
        "persistent_cache": _PERSISTENT["dir"],
    }


def reset_counters() -> None:
    """Zero every program's books and the ``bucket.*`` /`` program.*``
    registry families (bench / test isolation; compiled executables are
    kept — recompiling warm programs would change what a later section
    measures)."""
    with _REG_LOCK:
        progs = list(_BY_NAME.values())
    for p in progs:
        p.reset_counters()
    _registry().reset(prefix="program.")
    _registry().reset(prefix="bucket.")

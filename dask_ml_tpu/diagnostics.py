"""Tracing / profiling utilities.

Reference posture (SURVEY.md §5): dask-ml keeps only a ``_timer`` phase
logger in-repo and delegates real profiling to the external dask dashboard
and ``dask.diagnostics``.  The TPU equivalents are XProf device traces
(``jax.profiler``) and a ``block_until_ready`` timing harness — thin, also
in-repo, so every estimator keeps the reference's pattern of named, timed
phases with zero heavyweight machinery.
"""

from __future__ import annotations

import contextlib
import time

import numpy as np

import jax

from .utils import _timer  # noqa: F401  (re-export: phase logging)

# fault observability (re-export): the process-global retry/fault
# counters live in resilience.retry; surfacing them here keeps one
# diagnostics namespace for "what happened during that fit" — timings,
# traces, AND absorbed/propagated faults (resilience faults must be
# observable, never silent)
from .resilience.retry import (  # noqa: F401
    FaultStats,
    fault_stats,
    reset_fault_stats,
)

# input-pipeline observability (re-export): the parse/transfer/compute
# stage split every streamed fit records (pipeline.stats) — the round-5
# verdict's "measure the disk->device bottleneck instead of asserting
# it", kept in the same "what happened during that fit" namespace
from .pipeline import (  # noqa: F401
    pipeline_report,
    reset_pipeline_stats,
)

# grafttrace (re-export): the unified span/metrics/flight spine the
# reporters above publish through (dask_ml_tpu/obs/, design.md §11) —
# run_report() below is its merged per-fit view
from . import obs  # noqa: F401
from .obs import (  # noqa: F401
    event,
    export_perfetto,
    flight_dump,
    metrics_snapshot,
    span,
)

__all__ = [
    "trace", "benchmark_step", "benchmark_slope", "_timer",
    "FaultStats", "fault_stats", "reset_fault_stats", "fault_report",
    "pipeline_report", "reset_pipeline_stats",
    "lint_report", "sanitize_report", "program_report", "serve_report",
    "obs", "span", "event", "metrics_snapshot", "export_perfetto",
    "flight_dump", "run_report", "reset",
]


def fault_report() -> dict:
    """The elastic fault-domain runtime's books (design.md §13), next to
    :func:`fault_stats`'s raw counters::

        {"faults":    {faults, retries, failures}   # fault_stats view
         "budgets":   {name: {spent, denied, remaining}},
         "backoff_s": {tag: total_sleep_seconds},
         "degraded_skips": {stream_label: n},
         "supervisor": {domain: {units, late, dead, beats,
                                 deaths, restarts}}}

    Everything is registry-backed (``resilience.budget_*``,
    ``resilience.backoff_s``, ``resilience.degraded_skip``,
    ``supervisor.*``) so the same numbers appear in
    :func:`run_report`'s metrics snapshot and survive the owning
    objects — a finished fit's budget consumption stays reportable.
    """
    from .resilience import supervisor as _supervisor
    from .resilience.elastic import budget_report

    reg = obs.registry()
    snap = reg.snapshot()
    backoff = {}
    for key, h in snap.get("histograms", {}).items():
        if key.startswith("resilience.backoff_s"):
            tag = key[len("resilience.backoff_s"):].strip("{}")
            backoff[tag or ""] = h.get("sum", 0.0)
    return {
        "faults": fault_stats().snapshot(),
        "budgets": budget_report(),
        "backoff_s": backoff,
        "degraded_skips": reg.family("resilience.degraded_skip"),
        "supervisor": _supervisor.report(),
    }


def program_report() -> dict:
    """The central compiled-program cache's books, next to
    :func:`pipeline_report` (design.md §12)::

        {"programs": {name: {hits, misses, ahead_hits, ahead_submitted,
                             bypass, fallback, compile_s,
                             ahead_compile_s, saved_s, wait_s,
                             programs, inflight}},
         "totals": {...same keys summed...},
         "bucket": {blocks, padded_blocks, pad_rows},
         "persistent_cache": dir_or_None}

    ``saved_s`` is the compile wall time the blessed compile-ahead
    thread hid from consumers (ahead-compiled programs that were
    subsequently hit); ``bucket`` is the shape-bucketing pad split
    (``padded_blocks == 0`` means every reader emitted bucket-sized
    chunks — the no-op fast path).  Reset with
    :func:`dask_ml_tpu.programs.reset_counters` (compiled executables
    are kept)."""
    from . import programs

    return programs.report()


def serve_report() -> dict:
    """The online inference plane's books (design.md §15)::

        {"servers": [{label, alive, queued, budget, residency, ...}],
         "metrics": {"serve.request_s{model}": {p50, p95, p99, ...},
                     "serve.rejected{reason}": n, ...}}

    Per-model request latency quantiles (queue wait included — the
    client's number), queue-wait and batch-occupancy histograms,
    rejections by reason, and each live server's residency/budget
    state.  The same ``serve.*`` registry families export through the
    live ``/metrics`` endpoint and ratchet through the committed
    ``serve_latency`` perf workload."""
    from . import serve

    return serve.report()


def run_report() -> dict:
    """The merged "what happened, in order, during THAT fit" view.

    One dict over the whole observability spine:

    * ``span_tree`` — the most recent ROOT span (the last whole
      fit/stream/search) assembled as a nested tree: pipeline stage
      children (parse/stage/compute, prefetch-worker spans stitched
      in), search rounds/units, with retry/checkpoint/violation events
      attached to the spans they occurred under.  ``None`` when tracing
      is disabled or nothing has completed.
    * ``critical_path`` — graftpath's causal join of that root with the
      graftscope device timeline and the queue-wait signals
      (design.md §19): parse/stage/queue-wait/dispatch/device/fetch/
      idle category seconds summing to the wall within
      ``DASK_ML_TPU_CRITICAL_TOL``, overlap efficiency, and the
      bottleneck verdict with its evidence chain.  Falls back to the
      serve window's per-request queue/window/device/fetch split when
      no root span exists.
    * ``metrics`` — the registry snapshot: counters, gauges, and
      histograms with p50/p95/p99 (``pipeline.block_s``,
      ``compile.duration_s``, ...).
    * ``device`` — graftscope's occupancy view (design.md §14):
      per-program dispatches + busy seconds, utilization over the
      device window, idle seconds, and the top-3 idle gaps — the
      device-side half of the host stage split next to it.  The read
      settles briefly (≤1 s) so a just-finished fit's last in-flight
      program closes its interval.
    * ``pipeline`` / ``faults`` / ``sanitize`` / ``serve`` — the
      per-plane reporters, unchanged shapes (views over the same
      registry).

    Call :func:`reset` first to scope the report to one fit; export the
    same fit with :func:`export_perfetto` to render its host lanes AND
    its measured device lane in one trace.
    """
    resilience = fault_report()
    # graftpath AFTER the settled device read below would re-settle;
    # compute it first on its own settle so the last in-flight program
    # closes before the window is attributed
    obs.scope.settle(1.0)
    return {
        "schema": obs.SCHEMA_VERSION,
        "span_tree": obs.span_tree(),
        # the causal critical path of the most recent root (fit/search),
        # falling back to the serve window when no root exists —
        # categories sum to wall within the documented tolerance and
        # the bottleneck verdict carries its evidence (design.md §19)
        "critical_path": obs.critical_path(),
        "metrics": obs.metrics_snapshot(),
        "device": obs.scope.device_report(settle_s=1.0),
        "pipeline": pipeline_report(),
        # the legacy top-level key IS the resilience view's snapshot —
        # one read, so the two can never disagree mid-call
        "faults": resilience["faults"],
        "resilience": resilience,
        "sanitize": sanitize_report(),
        "serve": serve_report(),
    }


def reset() -> None:
    """One-call observability reset: fault stats, pipeline stats, the
    metrics registry, the span rings, the flight recorder, and the
    graftscope device timeline — the test/bench isolation idiom
    (replaces hand-chained ``reset_fault_stats()`` +
    ``reset_pipeline_stats()`` calls).  The live metrics endpoint and
    the graftscope sampler survive a reset: their books zero, and
    their supervisor heartbeats re-register immediately below (the
    unit-table wipe must not orphan a unit that is still serving)."""
    obs.reset_all()
    # the legacy reporters' registry families are already gone; these
    # clear their residual module state (the last-stream slot; private
    # books if the global stats object was ever swapped out; the
    # supervisor's registered-unit table)
    reset_fault_stats()
    reset_pipeline_stats()
    from .resilience import supervisor as _supervisor

    _supervisor.reset()
    obs.serve.rearm()
    obs.scope.rearm()


def sanitize_report() -> dict | None:
    """The graftsan runtime-sanitizer counters, next to
    :func:`pipeline_report`'s stage split: per-region compile / dispatch
    / d2h-sync counters, violations, allow-site passes, and the
    dispatching thread set.

    Returns the ACTIVE sanitizer's live report when one is open (inside
    a ``sanitize.sanitize()`` scope or a ``DASK_ML_TPU_SANITIZE=1``
    ambient stream), else the report of the most recently completed
    scope, else None (no sanitizer has run in this process).  See
    :mod:`dask_ml_tpu.sanitize` for the detector semantics and
    ``tools/sanitize_baseline.json`` for the committed per-workload
    contract these counters are ratcheted against.
    """
    from . import sanitize as _san

    s = _san.active_sanitizer()
    if s is not None:
        return s.report()
    return _san.last_report()


def lint_report(paths=None, baseline="auto") -> dict:
    """Per-rule graftlint finding counts for benches and CI trending.

    Runs the repo's static analyzer (:mod:`dask_ml_tpu.analysis`) over
    ``paths`` (default: this installed package) and returns::

        {"counts": {rule_id: {"active": n, "suppressed": m}},
         "active": total_active, "suppressed": total_suppressed,
         "errors": [parse errors],
         "baseline": {"path": ..., "new": n, "fixed": m,
                      "per_rule": {rule_id: {"new": x, "fixed": y}}}}

    ``active`` must trend to (and stay at) zero — tier-1 gates on it via
    tests/test_graftlint.py; ``suppressed`` is the debt metric to trend
    down release over release.  The ``baseline`` block is the per-PR
    delta vs the committed ratchet snapshot — what CHANGES/bench tooling
    trends ("this PR removed two suppressions, added none").
    ``baseline="auto"`` finds the committed snapshot next to a repo
    checkout (``tools/graftlint_baseline.json``); pass a path to pin it
    or ``None`` to skip; the block is ``None`` when no snapshot exists.
    """
    import os

    from . import analysis

    pkg_dir = os.path.dirname(os.path.abspath(__file__))
    if paths is None:
        paths = [pkg_dir]
    # cache=True: trending callers re-lint an unchanged tree constantly;
    # the digest-keyed cache makes that free and can never serve stale
    # results (any source edit changes the digest)
    findings, errors = analysis.lint_paths(paths, cache=True)
    counts = analysis.per_rule_counts(findings)
    if baseline == "auto":
        cand = os.path.join(os.path.dirname(pkg_dir), "tools",
                            "graftlint_baseline.json")
        baseline = cand if os.path.isfile(cand) else None
    delta_block = None
    if baseline is not None:
        try:
            snap = analysis.baseline.load(baseline)
        except (OSError, ValueError):
            snap = None
        if snap is not None:
            root = analysis.baseline.baseline_root(paths)
            try:
                delta = analysis.baseline.compare(snap, findings, root)
            except ValueError:
                # scope mismatch (an auto-discovered baseline vs
                # explicit non-package paths): no comparable snapshot,
                # report no delta rather than crash a trending call
                snap = None
        if snap is not None:
            per_rule: dict = {}
            for f in delta["new"]:
                per_rule.setdefault(f.rule, {"new": 0, "fixed": 0})
                per_rule[f.rule]["new"] += 1
            for e in delta["fixed"]:
                per_rule.setdefault(e["rule"], {"new": 0, "fixed": 0})
                per_rule[e["rule"]]["fixed"] += 1
            delta_block = {
                "path": baseline,
                "new": len(delta["new"]),
                "fixed": len(delta["fixed"]),
                "per_rule": dict(sorted(per_rule.items())),
            }
    return {
        "counts": counts,
        "active": sum(c["active"] for c in counts.values()),
        "suppressed": sum(c["suppressed"] for c in counts.values()),
        "errors": list(errors),
        "baseline": delta_block,
    }


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture an XProf/TensorBoard device trace of the enclosed block.

    The TPU analogue of watching the distributed dashboard's task stream:
    ``with diagnostics.trace('/tmp/prof'): est.fit(X)`` then point
    TensorBoard (or xprof) at the directory.

    Exception-safe: ``start_trace`` itself can raise (unwritable
    directory, a trace already active) — the stop only runs if the
    start succeeded, so the REAL error propagates instead of being
    masked by ``stop_trace`` complaining about a never-started trace.
    """
    started = False
    try:
        jax.profiler.start_trace(log_dir)
        started = True
        yield
    finally:
        if started:
            jax.profiler.stop_trace()


def _sync(out):
    """Force completion by FETCHING a result, not block_until_ready.

    On relayed/remote backends (the axon TPU tunnel in this image)
    ``block_until_ready`` returns before remote execution finishes and
    identical executions can appear cached — timings built on it are
    fiction (see BENCH_LOCAL.md).  Materializing one scalar-ish leaf is
    the only sync that holds everywhere.
    """
    fetched = False
    for leaf in jax.tree.leaves(out):
        if hasattr(leaf, "ravel"):
            # one element from EVERY array leaf: separate dispatches (or
            # mixed host/device leaves) must all complete, not just the
            # first leaf in tree order
            np.asarray(jax.numpy.ravel(leaf)[:1])
            fetched = True
    if not fetched:
        jax.block_until_ready(out)  # no array leaves: best effort


def benchmark_step(fn, *args, warmup: int = 1, iters: int = 10, **kwargs):
    """Time a jitted step function honestly (async dispatch flushed).

    Returns ``{"mean_s", "std_s", "min_s", "iters"}``.  The first
    ``warmup`` calls (compilation) are excluded; every timed call fetches
    an output element so neither XLA's async dispatch nor a remote
    relay's early ``block_until_ready`` can hide device time.  NOTE: on
    a relayed backend every fetch carries the tunnel round-trip — for
    per-iteration numbers free of that constant, time a CHAINED loop at
    two iteration counts and divide the difference (the slope method
    bench.py uses).
    """
    for _ in range(warmup):
        _sync(fn(*args, **kwargs))
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        _sync(fn(*args, **kwargs))
        times.append(time.perf_counter() - t0)
    arr = np.asarray(times)
    return {
        "mean_s": float(arr.mean()),
        "std_s": float(arr.std()),
        "min_s": float(arr.min()),
        "iters": iters,
    }


def benchmark_slope(run, counts=(4, 24), reps: int = 3):
    """Per-iteration time via the slope method (RTT/dispatch cancel).

    ``run(n)`` must execute n chained iterations (a traced-bound
    ``lax.fori_loop``/``scan``/``while_loop`` program) and FETCH a result
    before returning.  Returns ``{"per_iter_s", "counts", "raw_s"}``.
    """
    lo, hi = counts
    run(hi)  # compile
    run(lo)  # a static-bound run(n) compiles per count: warm BOTH
    raw = {}
    for n in (lo, hi):
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            run(n)
            best = min(best, time.perf_counter() - t0)
        raw[n] = best
    per = (raw[hi] - raw[lo]) / (hi - lo)
    if per <= 0:
        # a non-positive slope means the measurement is broken (noise
        # larger than the signal, or per-count recompilation): surface it
        # as NaN — a silent 0.0 reads as "infinitely fast"
        per = float("nan")
    return {
        "per_iter_s": per,
        "counts": (lo, hi),
        "raw_s": raw,
    }

"""Tracing / profiling utilities.

Reference posture (SURVEY.md §5): dask-ml keeps only a ``_timer`` phase
logger in-repo and delegates real profiling to the external dask dashboard
and ``dask.diagnostics``.  The TPU equivalents are XProf device traces
(``jax.profiler``) and a ``block_until_ready`` timing harness — thin, also
in-repo, so every estimator keeps the reference's pattern of named, timed
phases with zero heavyweight machinery.
"""

from __future__ import annotations

import contextlib
import time

import numpy as np

import jax

from .utils import _timer  # noqa: F401  (re-export: phase logging)

__all__ = ["trace", "benchmark_step", "_timer"]


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture an XProf/TensorBoard device trace of the enclosed block.

    The TPU analogue of watching the distributed dashboard's task stream:
    ``with diagnostics.trace('/tmp/prof'): est.fit(X)`` then point
    TensorBoard (or xprof) at the directory.
    """
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def benchmark_step(fn, *args, warmup: int = 1, iters: int = 10, **kwargs):
    """Time a jitted step function honestly (async dispatch flushed).

    Returns ``{"mean_s", "std_s", "min_s", "iters"}``.  The first
    ``warmup`` calls (compilation) are excluded; every timed call blocks on
    its outputs so XLA's async dispatch cannot hide device time.
    """
    for _ in range(warmup):
        jax.block_until_ready(fn(*args, **kwargs))
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args, **kwargs))
        times.append(time.perf_counter() - t0)
    arr = np.asarray(times)
    return {
        "mean_s": float(arr.mean()),
        "std_s": float(arr.std()),
        "min_s": float(arr.min()),
        "iters": iters,
    }

"""Model residency: keep many fitted models device-resident, lane-pack
homogeneous ones, evict LRU under an HBM budget.

The registry is OWNED by the serve loop thread: every mutating entry
point (:meth:`ModelRegistry.admit`, :meth:`ensure_resident`,
:meth:`ensure_pack`) runs there — uploads, warm compiles, and stack
builds are device work, and the serving plane keeps ALL device work on
its one blessed dispatch thread (``analysis/rules/_spmd.py``
``BLESSED_DISPATCH_THREADS``).  Read-only views (:meth:`report`,
:meth:`names`) are safe anywhere.

Three residence classes:

* **sgd** (``SGDClassifier`` / ``SGDRegressor``): the fitted ``coef`` /
  ``intercept`` device arrays are extracted once at admit; requests
  dispatch the fused ``serve.margins`` program and decode on host.
  Models sharing a :func:`serve_pack_key` additionally join a
  :class:`LanePack` whose stacked ``[M, d, k]`` state serves requests
  for DIFFERENT models in one vmapped dispatch.
* **generic** (anything else with ``predict``): served through the
  estimator's own predict surface on the serve thread; device-native
  states (``_state`` pytrees) are budget-counted, host models cost 0.
* **parked**: an LRU-evicted sgd model's state lives as host numpy;
  its next request re-uploads (a *residency fault*, counted per model
  in ``serve.residency_fault``) and may evict someone else.

Load-time warmup: admitting a model pre-compiles its predict program
for EVERY bucket rung a coalesced batch can pad to
(:meth:`~dask_ml_tpu.programs.BucketPolicy.rungs`), so the steady-state
serve loop only ever dispatches warm cached programs — the zero-steady-
compile contract the armed-sanitizer test pins.
"""

from __future__ import annotations

import logging

import numpy as np

from ..obs.metrics import registry as _registry
from . import programs as _sprog

logger = logging.getLogger(__name__)

__all__ = ["ResidentModel", "LanePack", "ModelRegistry", "serve_pack_key"]


def serve_pack_key(model):
    """Hashable serving-compatibility key, or None when the model can't
    lane-pack.  Unlike training's :func:`~dask_ml_tpu.model_selection.
    _packing.pack_key`, INFERENCE only needs the state SHAPES to agree —
    the margins gemm has no loss/penalty/schedule branches — so models
    from entirely different training configs pack together as long as
    their coefficient matrices are congruent."""
    from ..linear_model._sgd import _BaseSGD

    if not isinstance(model, _BaseSGD) or not hasattr(model, "_state"):
        return None
    coef = model._state["coef"]
    return (type(model).__name__, tuple(coef.shape), str(coef.dtype))


def _leaf_nbytes(tree) -> int:
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        size = getattr(leaf, "size", None)
        dtype = getattr(leaf, "dtype", None)
        if size is not None and dtype is not None:
            total += int(size) * np.dtype(dtype).itemsize
    return total


class ResidentModel:
    """One registered model's residency record."""

    __slots__ = ("name", "model", "kind", "classes", "coef", "intercept",
                 "host_coef", "host_intercept", "state_bytes", "last_used",
                 "pack_key", "proba_loss", "device_native")

    def __init__(self, name: str, model):
        from ..base import TPUEstimator
        from ..linear_model._sgd import _BaseSGD, SGDClassifier

        self.name = str(name)
        self.model = model
        self.classes = None
        self.coef = self.intercept = None
        self.host_coef = self.host_intercept = None
        self.last_used = 0
        self.proba_loss = None
        self.pack_key = serve_pack_key(model)
        # device-native generic estimators (TPUEstimator predicts are
        # jitted programs) take the bucket-padded dispatch path and get
        # load-time predict warmup; host sklearn models see raw rows
        self.device_native = isinstance(model, TPUEstimator)
        if isinstance(model, _BaseSGD):
            if not hasattr(model, "_state"):
                raise ValueError(
                    f"model {name!r} is not fitted (no _state); serve "
                    f"residency holds fitted estimators only")
            self.kind = ("sgd_classifier" if isinstance(model, SGDClassifier)
                         else "sgd_regressor")
            if self.kind == "sgd_classifier":
                self.classes = np.asarray(model.classes_)
                if model.loss in ("log_loss", "modified_huber"):
                    self.proba_loss = model.loss
            self.coef = model._state["coef"]
            self.intercept = model._state["intercept"]
            self.state_bytes = _leaf_nbytes((self.coef, self.intercept))
        else:
            if not callable(getattr(model, "predict", None)):
                raise TypeError(
                    f"model {name!r} ({type(model).__name__}) has no "
                    f"predict surface to serve")
            self.kind = "generic"
            self.state_bytes = _leaf_nbytes(getattr(model, "_state", None))

    @property
    def resident(self) -> bool:
        return self.kind == "generic" or self.coef is not None

    @property
    def n_features(self) -> int:
        ref = self.coef if self.coef is not None else self.host_coef
        return int(ref.shape[0]) if ref is not None else -1

    def park(self) -> int:
        """Drop device state to host copies; returns the bytes freed.
        Generic models never park (their state lives inside the
        estimator — evicting it would mutate the user's object)."""
        if self.kind == "generic" or self.coef is None:
            return 0
        self.host_coef = np.asarray(self.coef)
        self.host_intercept = np.asarray(self.intercept)
        self.coef = self.intercept = None
        return self.state_bytes

    def unpark(self) -> int:
        """Re-upload parked host state; returns the bytes now resident.
        Serve-thread only (host→device puts)."""
        import jax.numpy as jnp

        if self.coef is None:
            self.coef = jnp.asarray(self.host_coef)
            self.intercept = jnp.asarray(self.host_intercept)
            self.host_coef = self.host_intercept = None
            _registry().counter("serve.residency_fault", self.name).inc()
        return self.state_bytes

    def decode(self, margins: np.ndarray):
        """Host decode of fetched ``(b, k)`` margins into predictions."""
        if self.kind == "sgd_regressor":
            return margins[:, 0]
        if margins.shape[1] == 1:
            idx = (margins[:, 0] > 0).astype(np.intp)
        else:
            idx = np.argmax(margins, axis=1)
        return self.classes[idx]

    def decode_proba(self, probs: np.ndarray) -> np.ndarray:
        """Host tail of the device proba transform: the binary case's
        single positive-class column becomes the sklearn-shaped
        ``(b, 2)`` pair."""
        if probs.shape[1] == 1:
            return np.stack([1.0 - probs[:, 0], probs[:, 0]], axis=1)
        return probs


class LanePack:
    """The stacked ``[M, d, k]`` serving state of one pack key's
    members, rebuilt lazily when membership changes (admit/evict)."""

    __slots__ = ("key", "members", "coefs", "intercepts", "stack_bytes",
                 "dirty")

    def __init__(self, key):
        self.key = key
        self.members: list[ResidentModel] = []
        self.coefs = self.intercepts = None
        self.stack_bytes = 0
        self.dirty = True

    def lanes(self) -> dict:
        return {rm.name: i for i, rm in enumerate(self.members)}

    def drop_stack(self) -> int:
        freed, self.stack_bytes = self.stack_bytes, 0
        self.coefs = self.intercepts = None
        self.dirty = True
        return freed


class ModelRegistry:
    """Name → :class:`ResidentModel` with LRU eviction under the HBM
    budget and per-pack lane stacks.  See the module docstring for the
    threading contract (mutations on the serve loop only)."""

    def __init__(self, *, budget_bytes: int, policy, max_batch: int):
        self._by_name: dict[str, ResidentModel] = {}
        self._packs: dict[tuple, LanePack] = {}
        self.budget_bytes = int(budget_bytes)
        self.policy = policy
        self.max_batch = int(max_batch)
        self._clock = 0
        self._warmed: set = set()

    # -- views -----------------------------------------------------------
    def names(self) -> list:
        return sorted(self._by_name)

    def get(self, name: str) -> ResidentModel | None:
        return self._by_name.get(name)

    def resident_bytes(self) -> int:
        total = sum(rm.state_bytes for rm in self._by_name.values()
                    if rm.resident and rm.kind != "generic")
        total += sum(p.stack_bytes for p in self._packs.values())
        total += sum(rm.state_bytes for rm in self._by_name.values()
                     if rm.kind == "generic")
        return total

    def report(self) -> dict:
        return {
            "models": {
                rm.name: {
                    "kind": rm.kind,
                    "resident": rm.resident,
                    "state_bytes": rm.state_bytes,
                    "pack": rm.pack_key is not None,
                }
                for rm in self._by_name.values()
            },
            "packs": {
                " ".join(map(str, key)): [rm.name for rm in p.members]
                for key, p in self._packs.items()
            },
            "resident_bytes": self.resident_bytes(),
            "budget_bytes": self.budget_bytes,
        }

    # -- admission (serve thread) ----------------------------------------
    def admit(self, name: str, model) -> ResidentModel:
        """Register (or replace) a model, join its lane pack, make room
        under the budget, and warm its predict programs.  Re-loading a
        name whose pack stack is live takes the HOT-SWAP path: one
        donated ``serve.lane_refresh`` writes the new state into the
        resident stack in place — the online deploy primitive — instead
        of dropping and re-stacking all M lanes."""
        import jax.numpy as jnp

        from . import programs as _sprog

        rm = ResidentModel(name, model)
        old = self._by_name.get(name)
        pack = self._packs.get(rm.pack_key) if rm.pack_key is not None \
            else None
        if (old is not None and pack is not None
                and old.pack_key == rm.pack_key
                and not pack.dirty and old in pack.members):
            lane = pack.members.index(old)
            pack.members[lane] = rm
            self._by_name[name] = rm
            self.touch(rm)
            self.ensure_resident(rm)
            pack.coefs, pack.intercepts = _sprog.lane_refresh(
                pack.coefs, pack.intercepts, rm.coef, rm.intercept,
                jnp.int32(lane))
            _registry().counter("serve.lane_refresh").inc()
            self._warm(rm)
            self._publish()
            return rm
        if old is not None:
            self._remove_from_pack(old)
        self._by_name[name] = rm
        self.touch(rm)
        if rm.pack_key is not None:
            pack = self._packs.setdefault(rm.pack_key, LanePack(rm.pack_key))
            pack.members.append(rm)
            pack.drop_stack()
        self._make_room(exclude=rm)
        self._warm(rm)
        if rm.pack_key is not None and \
                len(self._packs[rm.pack_key].members) >= 2:
            # build the lane stack (and warm its vmapped program) NOW,
            # on the admitting serve thread: load time is the warmup
            # phase — a lazy first-dispatch build would compile in the
            # steady phase, exactly what the sanitizer test forbids.
            # Singleton packs skip it (single-model dispatch never
            # touches the stack; the stack builds when a sibling loads)
            self.ensure_pack(self._packs[rm.pack_key])
        self._publish()
        return rm

    def evict(self, name: str) -> bool:
        """Drop a model from the registry entirely."""
        rm = self._by_name.pop(name, None)
        if rm is None:
            return False
        self._remove_from_pack(rm)
        rm.park()
        self._publish()
        return True

    def touch(self, rm: ResidentModel) -> None:
        self._clock += 1
        rm.last_used = self._clock

    def _remove_from_pack(self, rm: ResidentModel) -> None:
        pack = self._packs.get(rm.pack_key)
        if pack is None:
            return
        pack.members = [m for m in pack.members if m is not rm]
        pack.drop_stack()
        if not pack.members:
            del self._packs[rm.pack_key]

    def _make_room(self, exclude=()) -> None:
        """LRU-park sgd models (dropping their pack stacks) until the
        resident total fits the budget.  ``exclude`` (a ResidentModel or
        an iterable of them) protects the working set being served RIGHT
        NOW: a working set larger than the budget parks everyone else
        and runs anyway — the budget bounds RETAINED state, it cannot
        shrink a live batch."""
        if isinstance(exclude, ResidentModel):
            exclude = (exclude,)
        keep = {id(rm) for rm in exclude}
        candidates = sorted(
            (rm for rm in self._by_name.values()
             if id(rm) not in keep and rm.resident
             and rm.kind != "generic"),
            key=lambda rm: rm.last_used)
        for rm in candidates:
            if self.resident_bytes() <= self.budget_bytes:
                return
            pack = self._packs.get(rm.pack_key)
            if pack is not None:
                pack.drop_stack()
            rm.park()
            _registry().counter("serve.evictions").inc()
            logger.info("serve residency: parked %r (LRU, budget %d MiB)",
                        rm.name, self.budget_bytes >> 20)

    # -- residence (serve thread) ----------------------------------------
    def ensure_resident(self, rm: ResidentModel) -> None:
        if rm.kind != "generic" and rm.coef is None:
            rm.unpark()
            self._make_room(exclude=rm)

    def ensure_pack(self, pack: LanePack):
        """The pack's stacked state, rebuilding if membership changed.
        Members must be resident first (the stack reads their device
        refs)."""
        import jax.numpy as jnp

        if pack.dirty:
            for rm in pack.members:
                self.ensure_resident(rm)
            pack.coefs = jnp.stack([rm.coef for rm in pack.members])
            pack.intercepts = jnp.stack(
                [rm.intercept for rm in pack.members])
            pack.stack_bytes = _leaf_nbytes((pack.coefs, pack.intercepts))
            pack.dirty = False
            self._warm_pack(pack)
            self._make_room(exclude=pack.members)
            self._publish()
        return pack.coefs, pack.intercepts

    # -- warmup (serve thread; compiles are load-time work) --------------
    _WARM_CAP = 16

    def _rungs(self) -> tuple:
        rungs = self.policy.rungs(self.max_batch)
        if len(rungs) > self._WARM_CAP:
            # no silent caps: a pathological ladder would warm dozens of
            # programs — keep the SMALLEST rungs (the shapes single-row
            # and small-batch traffic actually pads to; large coalesced
            # batches are the rare case) and say so loudly, because any
            # dropped rung's first steady request compiles on the serve
            # thread, which the armed sanitizer counts as a hard
            # steady-compile violation.  The default policies never hit
            # this (auto/1024 = 2 rungs, pow2/1024 = 11).
            logger.warning(
                "serve warmup: bucket policy yields %d rungs <= "
                "max_batch %d; pre-compiling the smallest %d only — a "
                "request coalescing past rung %d will compile at first "
                "use (a steady-compile violation under an armed "
                "sanitizer); lower DASK_ML_TPU_SERVE_MAX_BATCH or use "
                "a coarser DASK_ML_TPU_BUCKET ladder",
                len(rungs), self.max_batch, self._WARM_CAP,
                rungs[self._WARM_CAP - 1])
            rungs = rungs[:self._WARM_CAP]
        return rungs

    def _warm(self, rm: ResidentModel) -> None:
        """Pre-compile (and pre-dispatch once) the single-model predict
        programs — margins, and the donated proba transform when the
        loss supports it — for every bucket rung this model can see."""
        import jax.numpy as jnp

        if rm.kind == "generic":
            self._warm_generic(rm)
            return
        self.ensure_resident(rm)
        d, k = rm.n_features, int(rm.coef.shape[1])
        sig = ("single", d, k, rm.proba_loss)
        if sig in self._warmed:
            return
        self._warmed.add(sig)
        for b in self._rungs():
            xb = jnp.zeros((b, d), jnp.float32)
            m = _sprog.margins(rm.coef, rm.intercept, xb)
            if rm.proba_loss is not None:
                _sprog.proba(m, loss=rm.proba_loss)  # donates m: fine,
                # the warm margins buffer is throwaway by construction

    def _warm_generic(self, rm: ResidentModel) -> None:
        """Load-time predict warmup for device-native GENERIC estimators
        — the serving twin of the training plane's ``_pf_warm`` hook:
        the request path for these models is their own (jitted) predict
        surface over bucket-padded rows (runtime._dispatch_single), so
        driving predict once per reachable rung HERE, on the admitting
        serve thread, moves every per-shape compile into the load phase
        and the steady request path never compiles (the armed-sanitizer
        contract the SGD family already meets).  Host sklearn models
        skip: they see raw rows and never compile.  A model that does
        not expose its feature width cannot be warmed — logged loudly,
        because its first per-shape request WILL compile."""
        if not rm.device_native:
            return
        d = getattr(rm.model, "n_features_in_", None)
        if d is None:
            logger.warning(
                "serve warmup: generic model %r exposes no "
                "n_features_in_; its predict programs cannot pre-compile "
                "and the first request of each batch shape will compile "
                "on the serve loop (a steady-compile violation under an "
                "armed sanitizer)", rm.name)
            return
        # NO cross-model dedup here, unlike the SGD path: a generic
        # predict's compiled signature depends on the model's fitted
        # state shapes (e.g. a (k, d) centers operand — two same-class
        # models with different k compile different programs), which
        # this registry cannot enumerate generically.  Re-warming an
        # already-warm signature costs a few fast dispatches at load —
        # load is the expensive moment by design; a skipped warm would
        # be a steady-phase compile, the hard violation.
        for b in self._rungs():
            rm.model.predict(np.zeros((b, int(d)), np.float32))

    def _warm_pack(self, pack: LanePack) -> None:
        import jax.numpy as jnp

        m, d, k = pack.coefs.shape
        sig = ("pack", m, d, k)
        if sig in self._warmed:
            return
        self._warmed.add(sig)
        for b in self._rungs():
            xs = jnp.zeros((m, b, d), jnp.float32)
            _sprog.lane_margins(pack.coefs, pack.intercepts, xs)
        # the hot-swap program too: a re-load under traffic must hit a
        # warm lane_refresh (zeros stand in for the donated stacks)
        _sprog.lane_refresh(
            jnp.zeros((m, d, k), jnp.float32),
            jnp.zeros((m, k), jnp.float32),
            jnp.zeros((d, k), jnp.float32),
            jnp.zeros((k,), jnp.float32), jnp.int32(0))

    def _publish(self) -> None:
        reg = _registry()
        reg.gauge("serve.resident_bytes").set(float(self.resident_bytes()))
        reg.gauge("serve.resident_models").set(float(len(self._by_name)))

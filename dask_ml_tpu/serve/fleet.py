"""graftfleet: a fault-domain serving fabric over replicated
ModelServers (design.md §22, ROADMAP [serve-fleet]).

One :class:`~.runtime.ModelServer` is industrial but a single fault
domain: one blessed dispatch thread, one restart budget — a crash past
the budget takes the plane down, and every deploy is stop-the-world.
:class:`ServeFleet` runs N replicas, each a FULL fault domain (its own
``dask-ml-tpu-serve`` loop under the dispatcher-lock discipline, its
own registry under its own ``SERVE_HBM_MB`` budget, its own
:class:`~dask_ml_tpu.resilience.elastic.FaultBudget`), behind the
host-level routing policy of :mod:`.router`:

* **placement** — hot models replicate everywhere; cold models
  partition by rendezvous hash across per-replica budgets;
* **readiness-gated routing** — candidates must pass the replica's
  ``ready()`` probe (the ``/readyz`` contract: alive, not draining,
  residency warmup complete) — cold traffic never routes;
* **retry with full-jitter backoff** — a retryable rejection
  (``queue_full`` / ``draining`` / ``serve_down`` / a mid-deploy
  ``unknown_model``) re-routes to another replica; every re-route
  draws on the FLEET-level FaultBudget and counts
  (``fleet.retry{reason}``) — a retry storm is budgeted, never free;
* **hedged tails** — a caller parked past the hedge delay launches a
  duplicate predict on a second ready replica; first response wins
  (``fleet.hedge{won}``); the loser cannot be cancelled mid-dispatch,
  so its duplicate device spend is COUNTED (``fleet.hedge{wasted}``),
  never hidden — predict is stateless, so hedging is always exact;
* **graceful degradation** — a terminally-dead replica (its own budget
  exhausted) is respawned within the fleet budget with its placed
  models re-warmed (``submit_load``: the router keeps traffic on
  survivors while the new loop compiles; readiness re-admits it), and
  every request that was in flight on the corpse replays EXACTLY
  (router-level: the fleet still holds the submitted rows — predict is
  stateless).  Fleet budget exhausted ⇒ **brownout**, not blackout:
  priority classes shed lowest-first (``fleet.rejected{brownout}``),
  the highest class keeps serving on the survivors;
* **rolling deploys** — ``rolling_refresh`` walks replicas one at a
  time behind a drain barrier: stop routing (state ``draining``; the
  replica itself rejects ``draining``), flush in-flight, refresh via
  the registry's hot-swap/``serve.lane_refresh`` path, re-admit on
  readiness.  The graftpilot controller is HELD (frozen, counted under
  ``control.freeze{fleet_drain}``) for the duration — half-drained
  books must never train a knob move.

Everything lands in the one metrics registry, so the existing
``/metrics`` endpoint scrapes the whole fleet with no extra wiring;
``report()``/``scrape()`` aggregate the per-replica books the way an
external router would aggregate per-process scrapes.

Honesty note (gate box): replicas here are in-process ModelServer
instances, not OS processes — each is a genuine independent fault
domain (own dispatch thread, own registry, own budget, own supervised
unit) but they share one Python heap and one GIL, the same posture as
the repo's 8-virtual-device mesh.  The router/placement/drain/hedge
logic is transport-independent; a multi-process deployment changes the
submit edge, not the policy.  Chip-round numbers own the real
multiplier.

Self-test (wired into ``tools/lint.sh``, graftlock convention)::

    python -m dask_ml_tpu.serve.fleet --self-test           # exit 0
    DASK_ML_TPU_FLEET_INJECT=replica-kill \\
        python -m dask_ml_tpu.serve.fleet --self-test       # exit 1

Both runs seed the SAME replica kill mid-traffic; the knob makes the
router BLIND (no readiness gate, no failover, no respawn), and the
gate must exit 1 — a zero-lost-requests assertion that cannot fail
can never be trusted to gate.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from .._locks import make_lock
from .. import obs
from ..control import pilot as _pilot
from ..obs.metrics import registry as _registry
from ..resilience.elastic import FaultBudget
from ..resilience.testing import FaultInjected as _FaultInjected
from ..resilience.testing import ThreadCrash as _ThreadCrash
from ..resilience.testing import maybe_fault as _maybe_fault
from .batcher import RequestRejected
from .config import (
    resolve_drain_timeout_s,
    resolve_fleet_inject,
    resolve_fleet_priorities,
    resolve_fleet_replicas,
    resolve_fleet_retries,
    resolve_hbm_budget_bytes,
    resolve_hedge_s,
)
from .router import Router, full_jitter_backoff
from .runtime import ModelServer

__all__ = ["Replica", "FleetFuture", "ServeFleet", "self_test", "main"]

#: submit-side rejection reasons the router may re-route
_RETRYABLE = ("queue_full", "draining", "serve_down", "shutdown",
              "unknown_model")

#: rejection reasons the router must surface UNCHANGED — the client's
#: error (bad_input/oversize), its SLO (deadline), or a deliberate
#: load-shed verdict (brownout): re-routing any of these burns healthy
#: replicas on a request that fails everywhere by design.  _RETRYABLE
#: and _NON_RETRYABLE together are CLOSED over every produced
#: RequestRejected reason; graftlint's contract-orphan-producer rule
#: flags a new reason string that lands in neither roster, so retry
#: semantics stay a reviewed decision instead of a silent default.
_NON_RETRYABLE = ("bad_input", "oversize", "deadline", "brownout")

_STATE_CODES = {"ready": 0, "warming": 1, "draining": 2, "dead": 3}


def _model_nbytes(model) -> int:
    """Cheap placement-time size estimate (fitted linear state; generic
    models estimate 0 and rely on the replica's own LRU budget)."""
    total = 0
    for attr in ("coef_", "intercept_", "cluster_centers_", "components_"):
        v = getattr(model, attr, None)
        if v is not None:
            total += int(np.asarray(v).nbytes)
    return total


class Replica:
    """One fleet slot: an index plus the CURRENT ModelServer occupying
    it (respawn replaces the server, never the slot)."""

    def __init__(self, index: int, server: ModelServer):
        self.index = int(index)
        self.server = server
        self.draining = False
        self._respawn_lock = threading.Lock()

    def ready(self) -> bool:
        return not self.draining and self.server.ready()

    def qsize(self) -> int:
        return self.server._batcher.qsize()

    def state(self) -> str:
        srv = self.server
        if srv._closed or srv._failed is not None or \
                srv._thread is None or not srv._thread.is_alive():
            return "dead"
        if self.draining or srv.draining():
            return "draining"
        return "ready" if self.ready() else "warming"


class FleetFuture:
    """One fleet request's handle: wraps the live replica attempt(s)
    and owns the retry/hedge driver — ``result()`` is where re-routes,
    hedges, and counted rejections happen (the caller's wait IS the
    recovery trigger, the same consumer-side-liveness posture as
    ``ServeFuture``)."""

    def __init__(self, fleet: "ServeFleet", name: str, x, *,
                 deadline_s, proba: bool, replica, fut):
        self._fleet = fleet
        self.model = name
        self._x = x
        self._deadline_s = deadline_s
        self._proba = proba
        self._t0 = time.monotonic()
        self._attempts = [(replica, fut)]
        self._tried = {replica.index}
        self._retries = 0
        self._hedged = False
        self._value = None
        self._exc = None
        self._settled = False

    def done(self) -> bool:
        return self._settled or any(f.done() for _, f in self._attempts)

    def result(self, timeout: float | None = 30.0):
        if self._settled:
            if self._exc is not None:
                raise self._exc
            return self._value
        deadline = None if timeout is None else \
            time.monotonic() + float(timeout)
        while True:
            for i, (rep, fut) in enumerate(list(self._attempts)):
                # the consumer-side liveness poll: a dead loop is
                # detected (and its budgeted restart triggered) here
                fut._server._ensure_alive()
                if fut.done():
                    status, value = self._settle(i, rep, fut)
                    if status:
                        return value
                    break  # attempts changed: rescan from the top
            else:
                self._maybe_hedge()
            if deadline is not None and time.monotonic() >= deadline:
                raise TimeoutError(
                    f"fleet request for {self.model!r} timed out")
            time.sleep(0.002)

    # -- internals -------------------------------------------------------
    def _finish(self, value=None, exc=None):
        self._settled = True
        self._value, self._exc = value, exc
        if exc is not None:
            raise exc
        return True, value

    def _settle(self, i: int, rep, fut):
        reg = _registry()
        try:
            value = fut.result(timeout=0.001)
        except RequestRejected as e:
            self._attempts.pop(i)
            self._fleet._note_trouble(rep, e.reason)
            if self._fleet.blind or e.reason not in _RETRYABLE:
                return self._finish(exc=e)
            if self._attempts:
                return False, None  # a hedge is still racing: wait on it
            return self._replay(e)
        except BaseException as e:  # dispatch fault: a driver bug, not
            return self._finish(exc=e)  # a routing problem — surface it
        # success
        if self._hedged:
            reg.counter("fleet.hedge", "won" if i > 0 else "lost").inc()
            for _ in self._attempts[:i] + self._attempts[i + 1:]:
                # the loser's dispatch cannot be recalled — count its
                # duplicate device spend instead of pretending it away
                reg.counter("fleet.hedge", "wasted").inc()
        self._fleet._note_latency(self.model, time.monotonic() - self._t0)
        return self._finish(value=value)

    def _replay(self, e: RequestRejected):
        """Exact in-flight replay: re-route the SAME rows (predict is
        stateless) within the retry ceiling and the fleet budget; past
        either, the rejection is counted and raised — never a hang."""
        fleet = self._fleet
        while self._retries < fleet.retries:
            self._retries += 1
            if not fleet._budget.acquire("fleet-retry"):
                fleet._enter_brownout()
                break
            _registry().counter("fleet.retry", e.reason).inc()
            time.sleep(full_jitter_backoff(self._retries - 1))
            try:
                rep, fut = fleet._route(
                    self.model, self._x, deadline_s=self._deadline_s,
                    proba=self._proba, exclude=frozenset(self._tried))
            except RequestRejected as e2:
                if e2.reason not in _RETRYABLE:
                    return self._finish(exc=e2)
                # every placed replica already tried and failed this
                # request: widen the net (a respawned replica may be
                # back) before the next attempt
                self._tried.clear()
                e = e2
                continue
            self._tried.add(rep.index)
            self._attempts.append((rep, fut))
            return False, None
        fleet._count_reject(e.reason, self.model)
        return self._finish(exc=e)

    def _maybe_hedge(self) -> None:
        fleet = self._fleet
        if (self._hedged or fleet.blind or fleet.hedge_s <= 0
                or len(self._attempts) != 1
                or time.monotonic() - self._t0 < fleet.hedge_s):
            return
        self._hedged = True  # one hedge per request, launched or not
        live = {rep.index for rep, _ in self._attempts}
        try:
            rep, fut = fleet._route(
                self.model, self._x, deadline_s=self._deadline_s,
                proba=self._proba, exclude=frozenset(live | self._tried),
                chaos=False)
        except RequestRejected:
            return  # nowhere to hedge to — the primary still owns it
        _registry().counter("fleet.hedge", "launched").inc()
        self._tried.add(rep.index)
        self._attempts.append((rep, fut))


class ServeFleet:
    """N ModelServer replicas behind a health-aware router."""

    def __init__(self, *, replicas: int | None = None,
                 label: str = "fleet", hedge_ms: float | None = None,
                 drain_timeout_s: float | None = None,
                 retries: int | None = None,
                 priorities=None,
                 budget: FaultBudget | None = None,
                 replica_fault_attempts: int | None = None,
                 hbm_budget_mb: float | None = None,
                 blind: bool = False,
                 **server_kwargs):
        self.label = str(label)
        self.n = resolve_fleet_replicas(replicas)
        self.hedge_s = resolve_hedge_s(hedge_ms)
        self.drain_timeout_s = resolve_drain_timeout_s(drain_timeout_s)
        self.retries = resolve_fleet_retries(retries)
        self.priorities = resolve_fleet_priorities(priorities)
        self.blind = bool(blind)
        self._budget = budget if budget is not None else \
            FaultBudget.from_env(name=f"fleet:{self.label}")
        self._replica_attempts = replica_fault_attempts
        self._hbm_budget_mb = hbm_budget_mb
        self._server_kwargs = dict(server_kwargs)
        self._lock = make_lock("serve.fleet")
        self._models: dict = {}   # name -> (model, hot, slo_s)
        self._closed = False
        self._shed_level = 0
        self._rr = 0  # blind round-robin cursor
        self._replicas = [
            Replica(i, self._spawn_server(i)) for i in range(self.n)]
        self._router = Router(
            self._replicas,
            budget_bytes=resolve_hbm_budget_bytes(hbm_budget_mb),
            blind=self.blind)
        self._publish_states()

    # -- lifecycle -------------------------------------------------------
    def _spawn_server(self, index: int) -> ModelServer:
        budget = None
        if self._replica_attempts is not None:
            budget = FaultBudget(
                self._replica_attempts, 600.0,
                name=f"fleet:{self.label}/r{index}")
        return ModelServer(
            label=f"{self.label}/r{index}", metrics_tag=f"r{index}",
            hbm_budget_mb=self._hbm_budget_mb, budget=budget,
            **self._server_kwargs)

    def close(self, timeout: float = 5.0) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
        for rep in self._replicas:
            rep.server.close(timeout=timeout)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- placement / admission -------------------------------------------
    def load(self, name: str, model, *, hot: bool = False,
             slo_ms: float | None = None, timeout: float = 60.0) -> tuple:
        """Place and admit ``model`` under ``name``: every replica for a
        hot model, one rendezvous-chosen replica (within the per-replica
        budget) for a cold one.  Blocks until every placed replica is
        warm.  ``slo_ms`` arms the per-model SLO budget
        (``fleet.slo_miss{model}``, brownout shed evidence)."""
        placed = self._router.place(
            name, nbytes=_model_nbytes(model), hot=hot)
        with self._lock:
            self._models[name] = (
                model, bool(hot),
                None if slo_ms is None else float(slo_ms) / 1e3)
        for rep in self._replicas:
            if rep.index in placed:
                rep.server.load(name, model, timeout=timeout)
        self._publish_states()
        return placed

    def unload(self, name: str, timeout: float = 30.0) -> bool:
        placed = self._router.placement(name)
        out = False
        for rep in self._replicas:
            if rep.index in placed:
                out = rep.server.unload(name, timeout=timeout) or out
        self._router.forget(name)
        with self._lock:
            self._models.pop(name, None)
        return out

    def warm_from(self, dataset_dir: str, *, rows: int = 64,
                  timeout: float = 30.0) -> dict:
        """Readiness warmup from a written sharded dataset: replica
        ``i`` pulls the first block(s) of ITS ``for_host(i, n)`` shard
        slice and drives the rows through every model placed on it —
        the per-host sharding rule reused as warmup traffic, so first
        real requests hit request paths (not just compiled programs)
        that have already run end to end."""
        import os as _os

        from ..data import MANIFEST_NAME, DatasetManifest

        manifest = DatasetManifest.load(
            _os.path.join(dataset_dir, MANIFEST_NAME))
        warmed: dict = {}
        for rep in self._replicas:
            mine = manifest.for_host(rep.index, self.n)
            if not mine.shards:
                continue
            with mine.open_shard(0) as reader:
                cols = reader.read_block(0)
            X = np.asarray(cols[0][:rows], dtype=np.float32)
            names = [n for n in rep.server.registry.names()]
            for name in names:
                rm = rep.server.registry.get(name)
                if rm is not None and 0 <= rm.n_features != X.shape[1]:
                    continue  # width-mismatched dataset: skip honestly
                rep.server.predict(name, X, timeout=timeout)
                warmed[f"r{rep.index}/{name}"] = int(X.shape[0])
        return warmed

    # -- request path ----------------------------------------------------
    def _count_reject(self, reason: str, model: str = "") -> None:
        _registry().counter("fleet.rejected", reason).inc()
        retry = ("retryable" if reason in _RETRYABLE else
                 "terminal" if reason in _NON_RETRYABLE else
                 "unclassified")
        obs.event("fleet.reject", model=model, reason=reason,
                  retry=retry)

    def _fleet_reject(self, reason: str, detail: str, model: str = ""):
        self._count_reject(reason, model)
        raise RequestRejected(reason, detail)

    def _chaos(self, rep) -> bool:
        """Drill injection points, fired once per candidate considered.
        The injected exception is TRANSLATED into the domain event the
        point names: a ThreadCrash at ``replica-kill`` hard-kills the
        candidate's serve loop (the request still routes to the dying
        replica — that in-flight window is the drill's subject); a
        fault at ``replica-slow`` arms a dispatch delay (the hedge
        path's subject); a fault at ``router-partition`` quarantines
        the candidate from the router's view and SKIPS it (returns
        True)."""
        try:
            _maybe_fault("replica-kill")
        except _ThreadCrash:
            rep.server.kill()
        try:
            _maybe_fault("replica-slow")
        except _FaultInjected:
            rep.server._test_dispatch_delay_s = 0.25
        try:
            _maybe_fault("router-partition")
        except _FaultInjected:
            self._router.partition(rep.index, 0.35)
            return True
        return False

    def _route(self, name: str, x, *, deadline_s, proba: bool,
               exclude=frozenset(), chaos: bool = True):
        """One routing attempt: pick a candidate, fire the chaos
        points, submit.  Sighted routing fails over across candidates
        within this pass; BLIND routing ships to its round-robin pick
        and propagates whatever happens (the self-test's broken
        router)."""
        if not self.blind:
            # a dead slot never heals by itself (its own budget is
            # spent — that is what made it dead): every routing pass
            # sweeps for corpses so the fleet converges back to N
            # replicas while survivors carry the traffic
            self._respawn_dead()
        cands = self._router.candidates(name, exclude=exclude)
        if not cands:
            raise RequestRejected(
                "serve_down" if self._router.placement(name)
                else "unknown_model",
                f"no routable replica for {name!r}")
        if self.blind:
            rep = cands[self._rr % len(cands)]
            self._rr += 1
            if chaos:
                self._chaos(rep)
            return rep, rep.server.submit(
                name, x, deadline_s=deadline_s, proba=proba)
        last: RequestRejected | None = None
        for rep in cands:
            if chaos and self._chaos(rep):
                continue  # partitioned out from under the router
            try:
                return rep, rep.server.submit(
                    name, x, deadline_s=deadline_s, proba=proba)
            except RequestRejected as e:
                if e.reason not in _RETRYABLE:
                    raise
                self._note_trouble(rep, e.reason)
                last = e
        raise last if last is not None else RequestRejected(
            "serve_down", f"no routable replica for {name!r}")

    def submit(self, name: str, X, *, priority: str = "normal",
               deadline_s: float | None = None,
               proba: bool = False) -> FleetFuture:
        """Route one predict into the fleet; returns its
        :class:`FleetFuture`.  Every ACCEPTED request resolves with a
        result or a counted rejection — never a silent drop, never a
        hang (the chaos drills' zero-lost invariant)."""
        if self._closed:
            self._fleet_reject("shutdown", "fleet closed", name)
        if priority not in self.priorities:
            raise ValueError(
                f"unknown priority {priority!r} "
                f"(classes, lowest first: {self.priorities})")
        _registry().counter("fleet.requests", priority).inc()
        with self._lock:
            shed = self._shed_level
        if shed:
            if all(r.ready() for r in self._replicas):
                # every replica healthy again: brownout is over
                with self._lock:
                    self._shed_level = 0
                obs.event("fleet.brownout_clear", label=self.label)
            elif self.priorities.index(priority) < shed:
                self._fleet_reject(
                    "brownout",
                    f"fleet budget exhausted: shedding classes below "
                    f"{self.priorities[shed]!r}", name)
        attempt = 0
        while True:
            try:
                rep, fut = self._route(
                    name, X, deadline_s=deadline_s, proba=proba)
                break
            except RequestRejected as e:
                if self.blind or e.reason not in _RETRYABLE:
                    raise
                attempt += 1
                if attempt > self.retries or \
                        not self._budget.acquire("fleet-route"):
                    if attempt <= self.retries:
                        self._enter_brownout()
                    self._fleet_reject(e.reason, str(e), name)
                _registry().counter("fleet.retry", e.reason).inc()
                time.sleep(full_jitter_backoff(attempt - 1))
        return FleetFuture(self, name, X, deadline_s=deadline_s,
                           proba=proba, replica=rep, fut=fut)

    def predict(self, name: str, X, *, timeout: float | None = 30.0,
                priority: str = "normal", deadline_s: float | None = None):
        return self.submit(name, X, priority=priority,
                           deadline_s=deadline_s).result(timeout)

    def predict_proba(self, name: str, X, *,
                      timeout: float | None = 30.0,
                      priority: str = "normal",
                      deadline_s: float | None = None):
        return self.submit(name, X, priority=priority,
                           deadline_s=deadline_s,
                           proba=True).result(timeout)

    # -- degradation / recovery ------------------------------------------
    def _note_latency(self, name: str, latency_s: float) -> None:
        reg = _registry()
        reg.histogram("fleet.request_s", name).record(latency_s)
        with self._lock:
            slo = (self._models.get(name) or (None, None, None))[2]
        if slo is not None and latency_s > slo:
            reg.counter("fleet.slo_miss", name).inc()
            # feed the adaptive window: an SLO-missing model is latency
            # evidence the gather window is too patient — record the
            # sighting for the pilot's serve policy to weigh
            from ..control import knobs as _knobs

            _knobs.observe("serve_window_ms", max(slo * 1e3 / 4, 0.1))

    def _note_trouble(self, rep, reason: str) -> None:
        if self.blind:
            return
        if reason in ("serve_down", "shutdown") and \
                rep.server._failed is not None:
            self._respawn(rep)
        self._publish_states()

    def _respawn_dead(self) -> None:
        for rep in self._replicas:
            if rep.state() == "dead":
                self._respawn(rep)

    def _respawn(self, rep) -> bool:
        """Budgeted replica respawn: a NEW ModelServer takes the slot,
        its placed models re-warm asynchronously (readiness keeps the
        router off it until they resolve), the corpse is closed (its
        sweep already rejected its stragglers loudly).  Past the fleet
        budget: brownout, the slot stays dead."""
        if not rep._respawn_lock.acquire(blocking=False):
            return False  # another caller is already respawning it
        try:
            srv = rep.server
            if not (srv._closed or srv._failed is not None):
                # thread-dead but not terminally failed: the server's
                # OWN budgeted restart (with exact in-flight replay)
                # comes first — the routing pass stands in for the
                # consumer-side liveness poll a readiness-skipped
                # replica would otherwise never receive
                t = srv._thread
                if t is not None and not t.is_alive():
                    srv._ensure_alive()
                if srv._failed is None:
                    return False  # alive again (restarted in place)
            if not self._budget.acquire("replica-respawn"):
                self._enter_brownout()
                return False
            fresh = self._spawn_server(rep.index)
            with self._lock:
                models = [
                    (name, m) for name, (m, _h, _s) in self._models.items()
                    if rep.index in self._router.placement(name)]
            for name, model in models:
                fresh.submit_load(name, model)
            rep.server = fresh
            srv.close(timeout=1.0)
            _registry().counter("fleet.respawn").inc()
            obs.event("fleet.respawn", label=self.label,
                      replica=rep.index)
            self._publish_states()
            return True
        finally:
            rep._respawn_lock.release()

    def _enter_brownout(self) -> None:
        with self._lock:
            if self._shed_level >= len(self.priorities) - 1:
                return
            self._shed_level += 1
            level = self._shed_level
        _registry().counter("fleet.brownout").inc()
        obs.event("fleet.brownout", label=self.label, level=level,
                  shedding=list(self.priorities[:level]))

    # -- rolling deploy ---------------------------------------------------
    def rolling_refresh(self, name: str, model, *,
                        timeout: float = 60.0) -> dict:
        """Replica-by-replica model refresh behind a drain barrier:
        stop routing → flush in-flight → refresh (the registry's
        hot-swap path, ``serve.lane_refresh`` for packed lanes) →
        re-admit on readiness.  The pilot is held (frozen) throughout;
        rejections during the window are confined to ``draining`` (the
        drill-ratcheted deploy invariant).  Returns per-replica drain
        verdicts."""
        placed = self._router.placement(name)
        if not placed:
            raise KeyError(f"model {name!r} is not placed on this fleet")
        with self._lock:
            _old, hot, slo = self._models[name]
            # respawns during the walk must load the NEW model
            self._models[name] = (model, hot, slo)
        out: dict = {}
        with _pilot.hold("fleet_drain"):
            for rep in self._replicas:
                if rep.index not in placed:
                    continue
                try:
                    _maybe_fault("fleet-deploy")
                except _ThreadCrash:
                    # drill: the replica dies at the drain barrier —
                    # the refresh must still complete (budgeted restart
                    # or respawn, then the load proceeds)
                    rep.server.kill()
                rep.draining = True
                self._publish_states()
                try:
                    drained = rep.server.drain(self.drain_timeout_s)
                    try:
                        rep.server.load(name, model, timeout=timeout)
                    except RequestRejected:
                        # the replica died terminally mid-deploy:
                        # respawn takes the slot and loads the new
                        # model via the placed-models replay
                        if not self._respawn(rep):
                            raise
                    out[f"r{rep.index}"] = {"drained": bool(drained)}
                finally:
                    rep.draining = False
                    rep.server.resume()
                    self._publish_states()
                deadline = time.monotonic() + timeout
                while not rep.ready() and time.monotonic() < deadline:
                    time.sleep(0.005)
                out[f"r{rep.index}"]["ready"] = rep.ready()
        _registry().counter("fleet.deploy").inc()
        obs.event("fleet.deploy", label=self.label, model=name,
                  replicas=sorted(out))
        return out

    # -- books ------------------------------------------------------------
    def _publish_states(self) -> None:
        reg = _registry()
        for rep in self._replicas:
            reg.gauge("fleet.replica_state", f"r{rep.index}").set(
                float(_STATE_CODES[rep.state()]))

    def report(self) -> dict:
        """The router's aggregated view: per-replica books (the scrape
        an external router would pull from each process's /metrics)
        plus fleet counters and placement."""
        self._publish_states()
        reg = _registry()
        metrics: dict = {}
        for mname, tag, inst in reg.export_items():
            if not mname.startswith("fleet."):
                continue
            key = f"{mname}{{{tag}}}" if tag else mname
            snap = getattr(inst, "snapshot", None)
            metrics[key] = snap() if callable(snap) else inst.value
        with self._lock:
            shed = self._shed_level
        return {
            "label": self.label,
            "replicas": {f"r{rep.index}": dict(rep.server.report(),
                                               state=rep.state())
                         for rep in self._replicas},
            "router": self._router.report(),
            "budget": self._budget.snapshot(),
            "shed_level": shed,
            "priorities": list(self.priorities),
            "metrics": dict(sorted(metrics.items())),
        }

    scrape = report  # the aggregated-scrape alias


# -- seeded-fault self-test (tools/lint.sh) -------------------------------

class _ToyModel:
    """Host-only generic model (no device programs — the self-test must
    stay under a second after imports)."""

    def predict(self, X):
        X = np.asarray(X, dtype=np.float32)
        return (X.sum(axis=1) > 0.0).astype(np.int64)


def self_test(verbose: bool = True) -> int:
    """Seed a replica kill mid-traffic and require the fleet to lose
    ZERO accepted requests.  ``DASK_ML_TPU_FLEET_INJECT=replica-kill``
    runs the same fault through a BLIND router (no readiness gate, no
    failover, no respawn) — the gate must then exit 1, proving the
    loss detector can actually fire (graftlock posture: a gate that
    cannot fail can never be trusted)."""
    from ..resilience.testing import FaultPlan, fault_plan

    def say(msg):
        if verbose:
            print(f"fleet self-test: {msg}")

    blind = resolve_fleet_inject() == "replica-kill"
    model = _ToyModel()
    rng = np.random.RandomState(7)
    X = rng.normal(size=(24, 4)).astype(np.float32)
    want = model.predict(X)
    plan = FaultPlan().inject(
        "replica-kill", at_call=4, times=1,
        exc=_ThreadCrash("self-test: replica kill"))
    lost = []
    respawns0 = _registry().counter("fleet.respawn").value
    fleet = ServeFleet(
        replicas=3, label="selftest", window_s=0.0, hedge_ms=0.0,
        retries=2, replica_fault_attempts=0,
        budget=FaultBudget(16, 60.0, name="fleet:selftest"),
        blind=blind)
    try:
        fleet.load("toy", model, hot=True)
        with fault_plan(plan):
            for i in range(24):
                try:
                    got = fleet.predict("toy", X[i:i + 1], timeout=5.0)
                    if int(np.asarray(got)[0]) != int(want[i]):
                        lost.append((i, "wrong answer"))
                except (RequestRejected, TimeoutError) as e:
                    lost.append((i, f"{type(e).__name__}: {e}"))
    finally:
        fleet.close()
    respawned = _registry().counter("fleet.respawn").value - respawns0
    say(f"blind={blind} faults={sum(plan.fired.values())} "
        f"lost={len(lost)} respawns={respawned:g}")
    if blind:
        ok = len(lost) > 0
        say("blind router LOST requests (the gate can fail): exit 1"
            if ok else
            "blind router lost nothing — the loss detector is broken")
        return 1 if ok else 2
    ok = (not lost and sum(plan.fired.values()) == 1 and respawned >= 1)
    if ok:
        say("replica killed mid-traffic, zero lost, respawned: exit 0")
        return 0
    for i, why in lost[:5]:
        say(f"request {i} lost: {why}")
    say("FAILED: the fleet lost accepted requests (or never respawned)")
    return 1


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m dask_ml_tpu.serve.fleet",
        description="graftfleet seeded-fault self-test",
    )
    p.add_argument("--self-test", action="store_true")
    p.add_argument("--quiet", action="store_true")
    try:
        args = p.parse_args(argv)
    except SystemExit as e:
        return 0 if (e.code in (0, None)) else 2
    if not args.self_test:
        p.print_help()
        return 2
    return self_test(verbose=not args.quiet)


if __name__ == "__main__":  # pragma: no cover
    import sys

    sys.exit(main())

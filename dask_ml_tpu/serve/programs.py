"""The serve plane's device programs, behind the central program cache.

Four programs cover every resident linear model:

* :data:`margins` — one model, one coalesced batch: ``xb @ coef +
  intercept``.  The label decision (argmax / sign) happens on the HOST
  over the fetched ``(b, k)`` margins: micro-batches are small by
  definition, and keeping the device program class-count-agnostic means
  one compiled shape per (bucket, d, k) instead of one per decode rule.
* :data:`lane_margins` — the vmap of the same gemm over a stacked model
  axis: requests for DIFFERENT homogeneous models that land in the same
  micro-batch window dispatch as ONE program over the residency
  registry's lane-packed state (the K=4–64 lane-packing measured
  1.6–7.6× on chip, ROUND5_NOTES) instead of M separate launches.
* :data:`proba` — the probability transform of a margins buffer, with
  the **margins donated**: the output has the margins' exact shape
  (sigmoid / clip per class column, normalized along the class axis),
  so XLA aliases the donated buffer and the transform is in-place in
  HBM — the probabilities overwrite the margins instead of doubling the
  batch's live footprint.  Shape-agnostic over leading axes, so the
  same program body serves ``(b, k)`` single-model and ``(M, b, k)``
  lane-packed margins; the donation follows every per-signature AOT
  executable the cache mints, including the fresh one when a coalesced
  batch crosses a bucket rung (regression-pinned in
  tests/test_serve.py).
* :data:`lane_refresh` — hot-swap of ONE lane of a pack's resident
  stack (a model re-loaded under an existing name — the online plane's
  deploy primitive), with the **stacks donated**: ``dynamic_update_
  slice`` writes the new coefficients into the resident ``[M, d, k]``
  buffer in place rather than re-uploading and re-stacking M models.
  The lane index is a traced scalar, so every lane shares one program.

The batch buffers (``xb`` / ``xs``) are deliberately NOT donated: the
gemm's output is ``(…, k)`` — smaller than the ``(…, d)`` input — so
there is no same-shaped output to alias into and the donation would be
a no-op (the same reasoning design.md §8 records for training block
buffers).  Donation lives where it aliases.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .. import programs as _programs

__all__ = ["margins", "lane_margins", "proba", "lane_refresh"]


def _margins_fn(coef, intercept, xb):
    """``(d,k),(k,),(b,d) -> (b,k)`` — the whole single-model serve
    predict: one gemm on the MXU, bias add fused."""
    return xb @ coef + intercept


# graftlint: disable=donation-miss -- gemm-output-smaller: the (b,k) margins cannot alias (b,d)/(d,k) inputs, and coef/intercept are the resident model state (module docstring)
margins = _programs.cached_program(_margins_fn, name="serve.margins")


def _lane_margins_fn(coefs, intercepts, xs):
    """``(M,d,k),(M,k),(M,b,d) -> (M,b,k)`` — per-lane batches against
    per-lane models, one program for the whole pack."""
    return jax.vmap(_margins_fn)(coefs, intercepts, xs)


# graftlint: disable=donation-miss -- gemm-output-smaller, and the stacked coefs/intercepts are the residency registry's LIVE lane state (donating them would delete the pack)
lane_margins = _programs.cached_program(
    _lane_margins_fn, name="serve.lane_margins")


def _proba_fn(m, *, loss):
    """Margins → per-class probabilities, same shape (``k`` is the last
    axis; ``k == 1`` yields the positive-class column, the host decode
    assembles the binary pair).  Mirrors ``SGDClassifier.
    predict_proba``'s formulas on device."""
    if loss == "modified_huber":
        p = (jnp.clip(m, -1.0, 1.0) + 1.0) / 2.0
    elif loss == "log_loss":
        p = jax.nn.sigmoid(m)
    else:
        raise ValueError(
            f"probability estimates are not available for loss={loss!r}")
    if m.shape[-1] == 1:
        return p
    if loss == "modified_huber":
        z = jnp.sum(p, axis=-1, keepdims=True)
        return jnp.where(z > 0, p / z, 1.0 / m.shape[-1])
    return p / jnp.sum(p, axis=-1, keepdims=True)


proba = _programs.cached_program(
    _proba_fn, name="serve.proba", static_argnames=("loss",),
    donate_argnames=("m",),
)


def _lane_refresh_fn(coefs, intercepts, coef, intercept, lane):
    """Write one model's fresh state into lane ``lane`` of the resident
    stacks, in place (both stacks donated; ``lane`` traced)."""
    zero = jnp.int32(0)
    return (
        jax.lax.dynamic_update_slice(coefs, coef[None], (lane, zero, zero)),
        jax.lax.dynamic_update_slice(intercepts, intercept[None],
                                     (lane, zero)),
    )


lane_refresh = _programs.cached_program(
    _lane_refresh_fn, name="serve.lane_refresh",
    donate_argnames=("coefs", "intercepts"),
)

"""The online inference runtime: one supervised micro-batch serve loop.

:class:`ModelServer` is the serving plane's front door (design.md §15):

* callers ``load()`` fitted models and ``submit()`` / ``predict()``
  single rows or small row batches; every device interaction — model
  admission, warm compiles, lane-stack builds, batch staging, program
  dispatch, result fetch — happens on ONE dedicated thread (the
  dispatch-blessed ``dask-ml-tpu-serve``), so the serve plane can never
  interleave multi-device enqueues with itself;
* queued requests coalesce through the :class:`~.batcher.MicroBatcher`
  into bucket-ladder shapes, dispatch through the warm cached programs
  (:mod:`.programs`), and decode/slice back per request on the host;
* the loop is a supervised unit (domain ``"serve"``, one heartbeat per
  drained batch): a dead loop flips ``/healthz``, and the next submit —
  or a caller already blocked on a future — restarts it within the
  server's :class:`~dask_ml_tpu.resilience.FaultBudget`, REPLAYING the
  in-flight batch (predict is stateless, so replay is exact); past the
  budget every pending request is rejected loudly with
  ``serve_down``, never left hanging;
* per-model request latency (``serve.request_s``), queue wait, batch
  occupancy, and rejection counters land in the obs metrics registry —
  the live ``/metrics`` endpoint (obs/serve.py) exports them with no
  extra wiring, and the committed perf ratchet pins the latency SLO.

Honesty contract (mirrors graftscope's): request latency INCLUDES queue
wait and the adaptive gather window — the number a client experiences —
while ``serve.batch_window_s`` and ``serve.queue_wait_s`` split out how
much of it was the batcher's own choice.  Since graftpath (design.md
§19) every fulfilled request additionally records its EXACT four-leg
split — ``serve.req_{queue,window,device,fetch}_s``, contiguous stamps
on one clock so they sum to ``serve.request_s`` — keyed by the
request's trace id from submit through coalesce, dispatch, and fetch;
the slowest request seen leaves a flight-recorder exemplar carrying
that id and its split.
"""

from __future__ import annotations

import logging
import threading

from .._locks import make_lock
import time

import numpy as np

from .. import obs
from ..control import knobs as _knobs
from ..control.pilot import maybe_autostart as _maybe_autostart
from ..obs.metrics import registry as _registry
from ..resilience import supervisor as _supervisor
from ..resilience.elastic import FaultBudget
from ..resilience.testing import ThreadCrash as _ThreadCrash
from ..resilience.testing import maybe_fault as _maybe_fault
from .batcher import MicroBatcher, Request, RequestRejected, ServeFuture, \
    reject
from .config import (
    resolve_deadline_s,
    resolve_hbm_budget_bytes,
    resolve_max_batch,
    resolve_queue_depth,
    resolve_window_s,
)
from .residency import ModelRegistry

logger = logging.getLogger(__name__)

__all__ = ["SERVE_THREAD_NAME", "ModelServer", "report"]

#: the serve loop's literal thread name — the identity both halves of
#: the dispatch contract key on: graftlint's thread-dispatch rule
#: accepts it statically (_spmd.BLESSED_DISPATCH_THREADS) and graftsan
#: permits its dispatches at runtime while still hard-failing a steady
#: compile attributed to it.
SERVE_THREAD_NAME = "dask-ml-tpu-serve"

#: live servers, for the module-level :func:`report`
_SERVERS: list = []
_SERVERS_LOCK = make_lock("serve.servers")

#: constructions per label, to uniquify supervisor unit names — two
#: servers sharing a label must NOT share a heartbeat entry, or a dead
#: loop hides behind its twin's live thread and /healthz never flips
_LABEL_SEQ: dict = {}


def _unit_name(label: str) -> str:
    with _SERVERS_LOCK:
        n = _LABEL_SEQ.get(label, 0) + 1
        _LABEL_SEQ[label] = n
    return f"serve:{label}" if n == 1 else f"serve:{label}#{n}"


class _Control:
    """A queued control operation (load/unload) — handled on the serve
    loop like a request, so registry mutations and their warm compiles
    stay on the one dispatch thread."""

    __slots__ = ("op", "name", "model", "future")

    def __init__(self, op: str, name: str, model=None, future=None):
        self.op = op
        self.name = name
        self.model = model
        self.future = future


class ModelServer:
    """Online inference over a registry of resident fitted models."""

    def __init__(self, *, label: str = "serve", max_batch: int | None = None,
                 window_s: float | None = None, queue_depth: int | None = None,
                 deadline_s: float | None = None,
                 hbm_budget_mb: float | None = None,
                 budget: FaultBudget | None = None,
                 metrics_tag: str | None = None):
        from .. import programs as _programs

        self.label = str(label)
        self._unit = _unit_name(self.label)
        self.max_batch = resolve_max_batch(max_batch)
        #: the construction max-batch is the COMPILE CEILING: warmup
        #: covers bucket rungs up to it, so a live knob raise past it
        #: would force a steady-state compile on the serve thread (a
        #: hard graftsan violation) — _refresh_knobs clamps to this.
        self._max_batch_ceiling = self.max_batch
        self.window_s = resolve_window_s(window_s)
        # explicit ctor args PIN (graftpilot doctrine: a test asking for
        # window_s=0 gets exactly that); env/default sizing stays live
        self._max_batch_pinned = max_batch is not None
        self._window_pinned = window_s is not None
        if not self._max_batch_pinned:
            _knobs.observe("serve_max_batch", self.max_batch)
        if not self._window_pinned:
            _knobs.observe("serve_window_ms", self.window_s * 1e3)
        _maybe_autostart()  # DASK_ML_TPU_AUTOPILOT=1 arms the controller
        self.default_deadline_s = resolve_deadline_s(deadline_s)
        self.registry = ModelRegistry(
            budget_bytes=resolve_hbm_budget_bytes(hbm_budget_mb),
            policy=_programs.resolve_policy(),
            max_batch=self.max_batch,
        )
        self._batcher = MicroBatcher(
            depth=resolve_queue_depth(queue_depth),
            max_batch=self.max_batch, window_s=self.window_s)
        self._budget = budget if budget is not None else \
            FaultBudget.from_env(name=f"serve:{self.label}")
        self._stop = threading.Event()
        self._lock = make_lock("serve.server")
        self._inflight: list = []
        self._replay: list = []
        self._failed: BaseException | None = None
        self._closed = False
        self._draining = False
        #: control futures (loads/unloads) not yet resolved — the
        #: readiness signal: a replica with residency warmup still in
        #: flight must not be routed cold traffic (/readyz is 503)
        self._pending_controls: list = []
        #: per-replica latency attribution (fleets): when set, the
        #: request-latency histogram families record under this tag
        #: instead of the model name, so per-replica graftpath verdicts
        #: stay separable while the global sums are unchanged
        self._metrics_tag = metrics_tag
        #: chaos hook (drills/self-test): armed by :meth:`kill`, raises
        #: ThreadCrash at the top of the loop's next cycle — same
        #: test-only posture as ``_test_dispatch_delay_s`` below
        self._crash_armed = False
        self._hb = None
        self._thread: threading.Thread | None = None
        #: perf-harness hook: an injected per-dispatch sleep the
        #: committed latency ratchet must fail on (obs/perf.py)
        self._test_dispatch_delay_s = 0.0
        #: slowest request seen (monotone): the flight-recorder
        #: exemplar threshold — serve-loop-only state, no lock needed
        self._slowest_s = 0.0
        #: perf-harness hook: an injected per-control sleep so tests can
        #: pin the /readyz warmup window deterministically
        self._test_control_delay_s = 0.0
        self._start_loop()
        with _SERVERS_LOCK:
            _SERVERS.append(self)
        from ..obs.serve import register_readiness

        register_readiness(self._unit, self.ready)

    # -- lifecycle -------------------------------------------------------
    def _start_loop(self) -> None:
        # the ONE sanctioned off-main dispatch thread: the literal name
        # is the contract (see SERVE_THREAD_NAME); all device work for
        # serving is serialized inside this loop
        thread = threading.Thread(
            target=self._loop, daemon=True, name="dask-ml-tpu-serve",
        )
        self._thread = thread
        self._hb = _supervisor.register(
            self._unit, "serve", thread=thread)
        thread.start()

    def close(self, timeout: float = 5.0) -> None:
        """Stop the loop, reject everything still queued (reason
        ``shutdown``), and retire the supervised unit."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout)
        for item in self._batcher.drain_pending() + self._drain_inflight():
            if isinstance(item, Request):
                reject(item, "shutdown", "server closed")
            elif isinstance(item, _Control) and item.future is not None:
                item.future.set_exception(
                    RequestRejected("shutdown", "server closed"))
        if self._hb is not None:
            self._hb.retire()
        from ..obs.serve import unregister_readiness

        unregister_readiness(self._unit)
        with _SERVERS_LOCK:
            if self in _SERVERS:
                _SERVERS.remove(self)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    @staticmethod
    def _unresolved(item) -> bool:
        fut = getattr(item, "future", None)
        return fut is not None and not fut.done()

    def _drain_inflight(self) -> list:
        with self._lock:
            out, self._inflight = self._inflight, []
            out += self._replay
            self._replay = []
        return [r for r in out if self._unresolved(r)]

    # -- public request API (caller threads) -----------------------------
    def _offer_control(self, item: _Control) -> ServeFuture:
        self._check_open()
        with self._lock:
            self._pending_controls.append(item.future)
        self._batcher.offer_control(item)
        self._ensure_alive()
        return item.future

    def submit_load(self, name: str, model) -> ServeFuture:
        """Queue a model admission WITHOUT blocking (the fleet respawn /
        rolling-deploy path: warmup runs on the serve thread while the
        caller keeps routing traffic elsewhere; :meth:`ready` — and the
        ``/readyz`` probe — stay false until every queued control has
        resolved)."""
        return self._offer_control(_Control("load", name, model,
                                            ServeFuture(self)))

    def load(self, name: str, model, timeout: float = 60.0):
        """Admit a fitted model under ``name`` (replacing any previous
        holder).  Blocks until the model is resident and its predict
        programs are warm — load is the expensive moment, so the steady
        request path never compiles."""
        return self.submit_load(name, model).result(timeout)

    def unload(self, name: str, timeout: float = 30.0) -> bool:
        fut = self._offer_control(
            _Control("unload", name, future=ServeFuture(self)))
        return fut.result(timeout)

    # -- drain / readiness / chaos (caller threads) ----------------------
    def drain(self, timeout_s: float = 5.0) -> bool:
        """The rolling-deploy drain barrier: stop admitting requests
        (``submit()`` rejects with reason ``draining`` immediately —
        never queued into a loop about to be refreshed) and wait for
        the queue plus the in-flight batch to flush.  Control items
        (loads/unloads) stay admissible: the refresh itself rides the
        drained loop.  Returns True when quiesced within the timeout."""
        with self._lock:
            self._draining = True
        _registry().counter("serve.drain").inc()
        obs.event("serve.drain", label=self.label)
        deadline = time.monotonic() + float(timeout_s)
        while True:
            if self._quiesced():
                return True
            if time.monotonic() >= deadline:
                return self._quiesced()
            # a dead loop can never flush: the liveness poll restarts
            # it (or sweeps, past the budget) so drain cannot hang
            self._ensure_alive()
            time.sleep(0.005)

    def resume(self) -> None:
        """Re-admit traffic after a drain (the deploy's re-admission
        edge; the router additionally gates on :meth:`ready`)."""
        with self._lock:
            self._draining = False

    def _quiesced(self) -> bool:
        """No queued requests and no unresolved in-flight work.  The
        gather loop holds a popped batch for a moment before publishing
        it as in-flight — a microsecond window the drain poll may race;
        the deploy path is still safe because the refresh is a queued
        control, ordered after any such batch on the same loop."""
        if self._batcher.qsize() > 0:
            return False
        with self._lock:
            pending = any(self._unresolved(r)
                          for r in self._inflight + self._replay)
        return not pending

    def draining(self) -> bool:
        with self._lock:
            return self._draining

    def ready(self) -> bool:
        """The READINESS half of the health split (satellite of
        design.md §22): alive AND not draining AND residency warmup
        complete (no queued control still unresolved).  ``/healthz``
        keeps answering liveness (503 only on a DEAD unit); ``/readyz``
        is 503 until this is true — the router must not route cold
        traffic to a replica still compiling its rungs."""
        t = self._thread
        if (self._closed or self._failed is not None
                or t is None or not t.is_alive()):
            return False
        with self._lock:
            if self._draining:
                return False
            self._pending_controls = [
                f for f in self._pending_controls if not f.done()]
            return not self._pending_controls

    def kill(self) -> None:
        """Chaos hook (drills / fleet self-test): arm a simulated hard
        death — the serve loop raises ThreadCrash at the top of its
        next cycle, exactly as if the runtime killed the thread, with
        whatever was queued left behind for the supervised-restart /
        fleet-respawn paths to recover."""
        self._crash_armed = True

    @staticmethod
    def _reject_submit(reason: str, detail: str, model: str = ""):
        """The submit-time shed path: counted + flight-recorded like
        every other rejection (the every-rejection-is-a-record
        contract), then raised to the caller."""
        _registry().counter("serve.rejected", reason).inc()
        obs.event("serve.reject", model=model, reason=reason)
        raise RequestRejected(reason, detail)

    def submit(self, name: str, X, *, deadline_s: float | None = None,
               proba: bool = False) -> ServeFuture:
        """Queue one predict request; returns its future.  Admission
        control happens HERE: a full queue, an unknown model, an
        oversize batch, or a proba request the model's loss cannot
        honor raises :class:`RequestRejected` immediately."""
        self._check_open()
        with self._lock:
            draining = self._draining
        if draining:
            # reject NOW, loudly — queueing into a loop behind a drain
            # barrier would strand the request in a dying generation
            self._reject_submit(
                "draining",
                f"server {self.label!r} is draining for a refresh",
                name)
        _registry().counter("serve.requests").inc()
        xa = np.asarray(X, dtype=np.float32)
        if xa.ndim == 1:
            xa = xa[None, :]
        if xa.ndim != 2:
            self._reject_submit(
                "bad_input",
                f"expected 1-D or 2-D rows, got ndim={xa.ndim}", name)
        rm = self.registry.get(name)
        if rm is None:
            self._reject_submit(
                "unknown_model",
                f"no model {name!r} loaded (have {self.registry.names()})",
                name)
        if proba and rm.proba_loss is None:
            self._reject_submit(
                "bad_input",
                f"model {name!r} cannot serve probabilities "
                f"(kind={rm.kind}, loss without a probability transform)",
                name)
        if rm.n_features >= 0 and xa.shape[1] != rm.n_features:
            self._reject_submit(
                "bad_input",
                f"model {name!r} expects {rm.n_features} features, "
                f"got {xa.shape[1]}", name)
        if xa.shape[0] > self.max_batch:
            self._reject_submit(
                "oversize",
                f"{xa.shape[0]} rows > max_batch {self.max_batch}; bulk "
                f"scoring belongs to _partial.predict", name)
        fut = ServeFuture(self)
        if xa.shape[0] == 0:
            if proba:
                fut.set_result(np.empty((0, max(len(rm.classes), 2)),
                                        np.float32))
            else:
                dtype = (rm.classes.dtype if rm.classes is not None
                         else np.float32)
                fut.set_result(np.empty((0,), dtype=dtype))
            return fut
        dl = self.default_deadline_s if deadline_s is None \
            else float(deadline_s)
        req = Request(name, xa, fut, dl,
                      mode="proba" if proba else "label")
        self._ensure_alive()
        self._batcher.offer(req)  # raises queue_full here, not later
        return fut

    def predict(self, name: str, X, *, timeout: float | None = 30.0,
                deadline_s: float | None = None):
        """Synchronous predict: ``submit`` + ``result``."""
        return self.submit(name, X, deadline_s=deadline_s).result(timeout)

    def predict_proba(self, name: str, X, *, timeout: float | None = 30.0,
                      deadline_s: float | None = None):
        """Synchronous per-class probabilities (classifiers with a
        probability loss): the margins transform runs on device with
        the margins buffer DONATED — probabilities overwrite margins in
        place in HBM."""
        return self.submit(name, X, deadline_s=deadline_s,
                           proba=True).result(timeout)

    def _check_open(self) -> None:
        if self._closed:
            self._reject_submit("shutdown", "server closed")
        if self._failed is not None:
            self._reject_submit(
                "serve_down",
                f"serve loop failed terminally: {self._failed}")

    # -- liveness / recovery (caller threads) ----------------------------
    def _ensure_alive(self) -> None:
        """The consumer-side liveness poll: a dead serve loop is
        detected at the next submit or future wait, restarted within
        the fault budget with its in-flight batch replayed.  A closed
        or terminally-failed server SWEEPS instead: a request that
        raced past ``_check_open`` into the queue after the shutdown
        drain would otherwise be orphaned — every waiter's poll runs
        this, so such a straggler resolves within one poll interval."""
        if self._closed or self._failed is not None:
            reason = "shutdown" if self._closed else "serve_down"
            for item in self._batcher.drain_pending():
                if isinstance(item, Request):
                    reject(item, reason, "server is down; late arrival "
                           "swept at the liveness poll")
                elif getattr(item, "future", None) is not None:
                    item.future.set_exception(RequestRejected(
                        reason, "server is down"))
            return
        t = self._thread
        if t is None or t.is_alive():
            return
        with self._lock:
            t = self._thread
            if t is None or t.is_alive() or self._closed or self._failed:
                return
            _supervisor.note_death(
                "serve", self._hb.name,
                error="serve loop died without reporting")
            if not self._budget.acquire("serve-restart"):
                self._failed = RuntimeError(
                    f"serve loop for {self.label!r} is dead and the "
                    f"fault budget is exhausted "
                    f"({self._budget.snapshot()})")
                pending = [r for r in self._inflight + self._replay
                           if self._unresolved(r)]
                self._inflight, self._replay = [], []
            else:
                pending = None
                # replay the batch the dead loop had drained — control
                # items (loads/unloads) included, so no future is ever
                # left hanging: predict mutates nothing and admit
                # replaces-by-name, so re-running either is exact;
                # expired requests get their deadline rejection at
                # dispatch
                self._replay = [r for r in self._inflight + self._replay
                                if self._unresolved(r)]
                self._inflight = []
                # restart INSIDE the lock: a concurrent caller's
                # liveness check must see the fresh thread, not race a
                # second restart (and a second budget spend)
                self._start_loop()
        if pending is not None:
            for r in pending:
                if isinstance(r, Request):
                    reject(r, "serve_down",
                           "serve loop dead, budget spent")
                elif r.future is not None:
                    r.future.set_exception(RequestRejected(
                        "serve_down", "serve loop dead, budget spent"))
            for item in self._batcher.drain_pending():
                if isinstance(item, Request):
                    reject(item, "serve_down",
                           "serve loop dead, budget spent")
                elif isinstance(item, _Control) and item.future is not None:
                    item.future.set_exception(
                        RequestRejected("serve_down",
                                        "serve loop dead, budget spent"))
            return
        _supervisor.note_restart("serve", self._hb.name)
        obs.event("serve.restart", label=self.label)

    def _beat(self) -> None:
        # a diagnostics.reset() wiped the supervisor table: re-register
        # so the unit stays supervised (same posture as the metrics
        # endpoint's _beat)
        if _supervisor.lookup(self._hb.name) is not self._hb:
            self._hb = _supervisor.register(
                self._hb.name, "serve", thread=self._thread)
        self._hb.beat()

    def _refresh_knobs(self) -> None:
        """Per-DRAIN-CYCLE knob refresh (graftpilot): pick up live
        window / max-batch overrides before each gather.  Lock-free
        attribute reads, never ``os.environ`` — the config-module
        posture holds.  Max-batch clamps to the construction value (the
        compile ceiling): a live raise must never force a steady-state
        compile on this thread."""
        w_ms = (None if self._window_pinned
                else _knobs.override_or("serve_window_ms", None))
        if w_ms is not None:
            w_s = max(float(w_ms), 0.0) / 1e3
            if w_s != self.window_s:
                self.window_s = w_s
                self._batcher.window_s = w_s
        mb = (None if self._max_batch_pinned
              else _knobs.override_or("serve_max_batch", None))
        if mb is not None:
            mb = min(max(int(mb), 1), self._max_batch_ceiling)
            if mb != self.max_batch:
                self.max_batch = mb
                self._batcher.max_batch = mb

    # -- the loop (serve thread) -----------------------------------------
    def _loop(self) -> None:
        try:
            while not self._stop.is_set():
                if self._crash_armed:
                    self._crash_armed = False
                    raise _ThreadCrash("injected replica kill")
                self._refresh_knobs()
                with self._lock:
                    replay, self._replay = self._replay, []
                batch = replay or self._batcher.gather(self._stop)
                if not batch:
                    continue
                requests = [b for b in batch if isinstance(b, Request)]
                controls = [b for b in batch if isinstance(b, _Control)]
                # the WHOLE drained batch — controls included — is
                # in-flight until fully processed: a crash mid-batch
                # must replay queued loads too, not leave their
                # futures hanging
                with self._lock:
                    self._inflight = list(batch)
                if requests:
                    # drill point: a ThreadCrash here simulates the loop
                    # dying WITH a drained batch in hand — the replay
                    # path's exact case.  Fired once per drained batch
                    # OF REQUESTS, so drill call numbers are
                    # deterministic.
                    _maybe_fault("serve-loop")
                    self._beat()
                    self._dispatch(requests)
                for c in controls:
                    self._handle_control(c)
                with self._lock:
                    self._inflight = []
        except _ThreadCrash:
            return  # simulated hard death: vanish without reporting
        except BaseException as exc:  # driver bug: fail loud, then die
            obs.event("serve.fault", label=self.label,
                      error=obs.fmt_exc(exc))
            logger.exception("serve loop %r died", self.label)
            for r in self._drain_inflight():
                r.future.set_exception(exc)
            return

    def _handle_control(self, c: _Control) -> None:
        try:
            if self._test_control_delay_s:
                time.sleep(self._test_control_delay_s)
            if c.op == "load":
                self.registry.admit(c.name, c.model)
                out = True
            elif c.op == "unload":
                out = self.registry.evict(c.name)
            else:  # pragma: no cover - future ops
                raise ValueError(f"unknown control op {c.op!r}")
            if c.future is not None:
                c.future.set_result(out)
        except BaseException as exc:
            if c.future is not None:
                c.future.set_exception(exc)
            else:  # pragma: no cover - loads always carry futures
                logger.exception("serve control %s(%r) failed", c.op,
                                 c.name)

    def _tag(self, model: str) -> str:
        """Latency-family tag: the per-replica label when fleet-owned
        (per-replica verdicts stay separable), else the model name."""
        return self._metrics_tag if self._metrics_tag else model

    # -- dispatch (serve thread) -----------------------------------------
    def _dispatch(self, requests: list) -> None:
        now = time.monotonic()
        reg = _registry()
        live: dict[str, list] = {}
        for r in requests:
            reg.histogram("serve.queue_wait_s", self._tag(r.model)).record(
                now - r.t_enqueue)
            if r.expired(now):
                # stale before any device work: the deadline's whole
                # point — drop with an explicit record, spend nothing
                reject(r, "deadline",
                       f"request {r.id} expired in queue "
                       f"({now - r.t_enqueue:.3f}s > deadline)")
            else:
                live.setdefault(r.model, []).append(r)
        if not live:
            return
        if self._test_dispatch_delay_s:
            time.sleep(self._test_dispatch_delay_s)
        # group same-pack models dispatched THIS batch into one lane
        # program; everything else goes single-model
        by_pack: dict = {}
        singles: list = []
        for name, reqs in live.items():
            rm = self.registry.get(name)
            if rm is None:
                for r in reqs:
                    reject(r, "unknown_model",
                           f"model {name!r} unloaded while queued")
                continue
            # re-validate against the CURRENT model: a hot-swap/reload
            # between submit and dispatch can change the feature width
            # or drop proba capability — shed exactly the now-invalid
            # requests (recorded, per the contract) instead of letting
            # a raw shape error poison the whole coalesced group
            ok = []
            for r in reqs:
                if rm.n_features >= 0 and r.x.shape[1] != rm.n_features:
                    reject(r, "bad_input",
                           f"model {name!r} was replaced while queued "
                           f"(now expects {rm.n_features} features, "
                           f"request has {r.x.shape[1]})")
                elif r.mode == "proba" and rm.proba_loss is None:
                    reject(r, "bad_input",
                           f"model {name!r} was replaced while queued "
                           f"and no longer serves probabilities")
                else:
                    ok.append(r)
            if not ok:
                continue
            reqs = ok
            if rm.pack_key is not None and \
                    all(r.mode == "label" for r in reqs):
                # proba requests stay single-model: the probability
                # transform is static per model loss, which may differ
                # across a pack's lanes
                by_pack.setdefault(rm.pack_key, []).append((rm, reqs))
            else:
                singles.append((rm, reqs))
        for key, groups in by_pack.items():
            if len(groups) >= 2:
                self._run_group(lambda g=groups, k=key:
                                self._dispatch_pack(k, g),
                                [r for _, reqs in groups for r in reqs])
            else:
                singles.extend(groups)
        for rm, reqs in singles:
            self._run_group(lambda rm=rm, reqs=reqs:
                            self._dispatch_single(rm, reqs), reqs)

    def _run_group(self, fn, reqs: list) -> None:
        """One dispatch group: a failure poisons ONLY its requests'
        futures — the loop (and the other groups in the batch) keep
        serving."""
        try:
            fn()
        except BaseException as exc:
            if isinstance(exc, _ThreadCrash):
                # simulated hard death (drills): vanish WITHOUT
                # resolving the futures — they are in-flight state the
                # restart path must replay, exactly like a real crash
                raise
            obs.event("serve.dispatch_fault", label=self.label,
                      error=obs.fmt_exc(exc))
            for r in reqs:
                if not r.future.done():
                    r.future.set_exception(exc)

    def _fulfill(self, reqs: list, preds_by_req: list,
                 t_dispatch0: float | None = None,
                 t_dispatched: float | None = None) -> None:
        """Resolve the group's futures and record each request's exact
        latency split (design.md §19).  Four CONTIGUOUS legs per
        request — stamped on one clock, so they sum to ``request_s``
        exactly:

        * ``queue``  — submit → popped off the admission queue;
        * ``window`` — popped → this group's dispatch began (the gather
          window's coalescing wait plus batch grouping);
        * ``device`` — dispatch began → the device program call
          returned (staging put + program enqueue; on an inline/sync
          backend the execution itself — on an async one the residual
          device time surfaces in the fetch leg, same honesty note as
          ``diagnostics._sync``);
        * ``fetch``  — program call returned → future resolved (result
          fetch, host decode, per-request slice-back).
        """
        reg = _registry()
        done = time.monotonic()
        for r, p in zip(reqs, preds_by_req):
            r.future.set_result(p)
            lat = done - r.t_enqueue
            reg.histogram("serve.request_s", self._tag(r.model)).record(lat)
            if t_dispatch0 is None or t_dispatched is None or \
                    r.t_dequeue is None:
                continue  # a path without stamps records only the total
            split = {
                "queue": max(r.t_dequeue - r.t_enqueue, 0.0),
                "window": max(t_dispatch0 - r.t_dequeue, 0.0),
                "device": max(t_dispatched - t_dispatch0, 0.0),
                "fetch": max(done - t_dispatched, 0.0),
            }
            for leg, dt in split.items():
                reg.histogram(f"serve.req_{leg}_s",
                              self._tag(r.model)).record(dt)
            # slowest-request exemplar: a monotone-max record in the
            # flight recorder, so a post-mortem shows WHERE the worst
            # request's time went (trace id + split), not just that a
            # p99 existed
            if lat > self._slowest_s:
                self._slowest_s = lat
                obs.event(
                    "serve.slow_request", request=r.id, model=r.model,
                    request_ms=round(lat * 1e3, 3),
                    queue_ms=round(split["queue"] * 1e3, 3),
                    window_ms=round(split["window"] * 1e3, 3),
                    device_ms=round(split["device"] * 1e3, 3),
                    fetch_ms=round(split["fetch"] * 1e3, 3))

    @staticmethod
    def _concat_rows(reqs: list) -> np.ndarray:
        return (reqs[0].x if len(reqs) == 1
                else np.concatenate([r.x for r in reqs]))

    def _dispatch_single(self, rm, reqs: list) -> None:
        import jax.numpy as jnp

        from .._partial import stage_predict_block
        from . import programs as _sprog

        reg = _registry()
        t_dispatch0 = time.monotonic()  # the group's device leg begins
        X = self._concat_rows(reqs)
        n_real = X.shape[0]
        self.registry.touch(rm)
        probs = None
        if rm.kind == "generic":
            if rm.device_native:
                # device-native generics dispatch their own jitted
                # predict over BUCKET-PADDED rows (the same shared
                # stage_predict_block discipline, same slice-back
                # contract) so every request shape resolves to a rung
                # the load-time warmup already compiled — the steady
                # request path never compiles for ANY admitted model
                padded, n = stage_predict_block(X, self.registry.policy)
                m = rm.model.predict(padded)
                t_dispatched = time.monotonic()
                preds = np.asarray(m)
                if n is not None:
                    preds = preds[:n]
            else:
                # host estimators see RAW rows — the same device-native
                # gate _partial.predict applies: padding a host model's
                # input wastes its whole-batch compute and is only
                # exact for strictly row-wise predicts
                preds = rm.model.predict(X)
                t_dispatched = time.monotonic()
                preds = np.asarray(preds)
        else:
            # the ONE predict-staging entry the offline plane also
            # uses, so the pad discipline cannot drift between planes
            padded, _ = stage_predict_block(X, self.registry.policy)
            self.registry.ensure_resident(rm)
            xb = jnp.asarray(padded)
            m = _sprog.margins(rm.coef, rm.intercept, xb)
            t_dispatched = time.monotonic()  # program enqueued
            mnp = np.asarray(m)  # fetched BEFORE the transform below
            if any(r.mode == "proba" for r in reqs):
                # in-place on device: proba donates (and overwrites)
                # the margins buffer — the host copy above serves the
                # label decodes in the same coalesced batch
                p = _sprog.proba(m, loss=rm.proba_loss)
                probs = rm.decode_proba(np.asarray(p))
            preds = rm.decode(mnp)
        reg.counter("serve.dispatches", rm.name).inc()
        reg.histogram("serve.batch_rows").record(float(n_real))
        reg.histogram("serve.batch_requests").record(float(len(reqs)))
        out, lo = [], 0
        for r in reqs:
            src = probs if r.mode == "proba" else preds
            out.append(src[lo:lo + r.n])
            lo += r.n
        self._fulfill(reqs, out, t_dispatch0, t_dispatched)

    def _dispatch_pack(self, key, groups: list) -> None:
        """Requests for >= 2 homogeneous models in one window: ONE
        vmapped program over the residency registry's lane stack.  Each
        requested lane carries its own bucket-padded rows; lanes with no
        requests this window ride along as zeros (the lane win is
        amortized dispatch, measured 1.6–7.6x at K=4–64)."""
        import jax.numpy as jnp

        from . import programs as _sprog

        reg = _registry()
        t_dispatch0 = time.monotonic()  # the group's device leg begins
        pack = self.registry._packs[key]
        for rm, _ in groups:
            self.registry.ensure_resident(rm)
            self.registry.touch(rm)
        coefs, intercepts = self.registry.ensure_pack(pack)
        lanes = pack.lanes()
        d = int(coefs.shape[1])
        from .. import programs as _programs

        rows = {rm.name: sum(r.n for r in reqs) for rm, reqs in groups}
        b = _programs.bucket_rows(max(rows.values()),
                                  policy=self.registry.policy)
        xs = np.zeros((len(pack.members), b, d), np.float32)
        for rm, reqs in groups:
            lo = 0
            lane = lanes[rm.name]
            for r in reqs:
                xs[lane, lo:lo + r.n] = r.x
                lo += r.n
        m = _sprog.lane_margins(coefs, intercepts, jnp.asarray(xs))
        t_dispatched = time.monotonic()  # program enqueued
        out = np.asarray(m)
        n_requests = 0
        for rm, reqs in groups:
            lane_m = out[lanes[rm.name]]
            preds = rm.decode(lane_m)
            outs, lo = [], 0
            for r in reqs:
                outs.append(preds[lo:lo + r.n])
                lo += r.n
            self._fulfill(reqs, outs, t_dispatch0, t_dispatched)
            reg.counter("serve.dispatches", rm.name).inc()
            n_requests += len(reqs)
        reg.counter("serve.lane_dispatches").inc()
        reg.histogram("serve.batch_rows").record(
            float(sum(rows.values())))
        reg.histogram("serve.batch_requests").record(float(n_requests))

    # -- books -----------------------------------------------------------
    def report(self) -> dict:
        """This server's residency + queue books (the registry metrics
        themselves are global: ``serve.*`` families in
        ``diagnostics.serve_report()``)."""
        return {
            "label": self.label,
            "alive": bool(self._thread is not None
                          and self._thread.is_alive()),
            "ready": self.ready(),
            "draining": self.draining(),
            "closed": self._closed,
            "failed": (None if self._failed is None
                       else str(self._failed)),
            "max_batch": self.max_batch,
            "window_s": self.window_s,
            "queue_depth": self._batcher.depth,
            "queued": self._batcher.qsize(),
            "budget": self._budget.snapshot(),
            "residency": self.registry.report(),
        }


def report() -> dict:
    """Module-level serving view — ``diagnostics.serve_report()``:
    every live server's books plus the registry's ``serve.*`` metric
    families (request/queue-wait latency quantiles, batch occupancy,
    rejections by reason, residency gauges)."""
    reg = _registry()
    with _SERVERS_LOCK:
        servers = list(_SERVERS)
    metrics: dict = {}
    for name, tag, inst in reg.export_items():
        if not name.startswith("serve."):
            continue
        key = f"{name}{{{tag}}}" if tag else name
        snap = getattr(inst, "snapshot", None)
        metrics[key] = snap() if callable(snap) else inst.value
    return {
        "servers": [s.report() for s in servers],
        "metrics": dict(sorted(metrics.items())),
    }

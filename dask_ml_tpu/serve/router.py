"""Fleet routing policy: placement, readiness-gated candidate
selection, partitions, and backoff (design.md §22).

This module is the DECISION half of graftfleet — pure host-side policy
with no threads and no device work, so every rule is unit-testable
without a fleet:

* **consistent placement** — hot models replicate to every replica;
  cold models partition across the per-replica ``SERVE_HBM_MB``
  budgets by rendezvous (highest-random-weight) hashing, so the same
  model lands on the same replica across routers and restarts, and the
  fleet's aggregate capacity is N x the single-process budget.  A cold
  model that fits no remaining budget still places (the replica's LRU
  registry absorbs it) but the spill is counted
  (``fleet.placement_spill``) — capacity pressure is visible, never
  silent;
* **readiness-gated candidates** — a replica is routable only when its
  ``ready()`` probe is true (alive, not draining, residency warmup
  complete — the ``/readyz`` contract) and it is not partitioned from
  the router's view; candidates order by queue depth (least-loaded
  first, rendezvous order as the tiebreak);
* **partitions** — a router-side quarantine with an expiry: the
  replica keeps serving its in-flight work, the router just stops
  routing to it until the partition heals (the ``router-partition``
  chaos drill's subject);
* **full-jitter backoff** — the retry delay schedule
  (``random.uniform(0, min(cap, base * 2^attempt))``), the classic
  thundering-herd-free shape.

A ``blind=True`` router skips the readiness and partition gates and
never reorders by load — the deliberately broken configuration the
seeded-fault self-test (``DASK_ML_TPU_FLEET_INJECT=replica-kill``)
uses to prove the zero-lost-requests gate can fail.
"""

from __future__ import annotations

import hashlib
import random
import time

from .._locks import make_lock
from ..obs.metrics import registry as _registry

__all__ = [
    "REPLICA_STATES",
    "Router",
    "full_jitter_backoff",
    "rendezvous",
]

#: replica lifecycle states (the ``fleet.replica_state`` gauge encodes
#: them by index: 0=ready 1=warming 2=draining 3=dead)
REPLICA_STATES = ("ready", "warming", "draining", "dead")


def full_jitter_backoff(attempt: int, *, base_s: float = 0.01,
                        cap_s: float = 0.25, rng=None) -> float:
    """The full-jitter delay for retry ``attempt`` (0-based):
    ``uniform(0, min(cap, base * 2^attempt))`` — every waiter draws a
    fresh delay so synchronized retries cannot stampede a recovering
    replica."""
    span = min(float(cap_s), float(base_s) * (2 ** max(int(attempt), 0)))
    return (rng or random).uniform(0.0, span)


def rendezvous(name: str, ids, k: int = 1) -> list:
    """Highest-random-weight placement: score every id by a keyed hash
    and keep the top ``k``.  Adding or removing one replica moves only
    the models that hashed to it — the consistent-placement property a
    modulo would not have."""
    def score(i):
        h = hashlib.md5(f"{name}|{i}".encode("utf-8")).hexdigest()
        return int(h[:16], 16)

    ranked = sorted(ids, key=score, reverse=True)
    return ranked[:max(int(k), 1)]


class Router:
    """Placement table + candidate selection over a fixed replica set.

    Replicas are duck-typed: the router needs ``.index``, ``.ready()``
    and ``.qsize()`` — the fleet owns their lifecycle."""

    def __init__(self, replicas, *, budget_bytes: int | None = None,
                 blind: bool = False):
        self._replicas = list(replicas)
        self._budget_bytes = budget_bytes
        self.blind = bool(blind)
        self._lock = make_lock("serve.router")
        self._placement: dict = {}      # model -> tuple of indices
        self._hot: set = set()
        self._placed_bytes: dict = {i.index: 0 for i in self._replicas}
        self._model_bytes: dict = {}
        self._partition_until: dict = {}  # index -> monotonic expiry

    # -- placement -------------------------------------------------------
    def place(self, name: str, *, nbytes: int = 0,
              hot: bool = False) -> tuple:
        """Choose (and remember) the replica indices hosting ``name``.
        Re-placing an existing model keeps its assignment (deploys
        refresh in place; placement churn is a chaos source, not a
        feature)."""
        with self._lock:
            if name in self._placement:
                if hot:
                    self._hot.add(name)
                return self._placement[name]
            ids = [r.index for r in self._replicas]
            if hot:
                chosen = tuple(ids)
                self._hot.add(name)
            else:
                ranked = rendezvous(name, ids, k=len(ids))
                pick = ranked[0]
                if self._budget_bytes:
                    fits = [i for i in ranked
                            if self._placed_bytes[i] + nbytes
                            <= self._budget_bytes]
                    if fits:
                        pick = fits[0]
                    else:
                        # nowhere fits: place on the rendezvous-first
                        # replica anyway (its LRU registry absorbs) and
                        # make the capacity pressure loud
                        _registry().counter("fleet.placement_spill").inc()
                chosen = (pick,)
            self._placement[name] = chosen
            for i in chosen:
                self._placed_bytes[i] += int(nbytes)
            self._model_bytes[name] = int(nbytes)
            return chosen

    def forget(self, name: str) -> None:
        with self._lock:
            ids = self._placement.pop(name, ())
            nb = self._model_bytes.pop(name, 0)
            self._hot.discard(name)
            for i in ids:
                self._placed_bytes[i] = max(
                    0, self._placed_bytes.get(i, 0) - nb)

    def placement(self, name: str) -> tuple:
        with self._lock:
            return self._placement.get(name, ())

    def is_hot(self, name: str) -> bool:
        with self._lock:
            return name in self._hot

    # -- partitions (router-side quarantine) -----------------------------
    def partition(self, index: int, duration_s: float) -> None:
        """Quarantine one replica from this router's view for
        ``duration_s`` — in-flight work on it proceeds; only NEW
        routing avoids it."""
        with self._lock:
            self._partition_until[index] = \
                time.monotonic() + float(duration_s)
        _registry().counter("fleet.partition").inc()

    def is_partitioned(self, index: int) -> bool:
        with self._lock:
            until = self._partition_until.get(index)
            if until is None:
                return False
            if time.monotonic() >= until:
                del self._partition_until[index]
                return False
            return True

    # -- candidate selection ---------------------------------------------
    def candidates(self, name: str, *, exclude=()) -> list:
        """The replicas to try for ``name``, best first: placed AND
        ready AND un-partitioned, least queue depth breaking toward
        rendezvous order.  Blind mode returns the raw placement — no
        gates, no reordering (the self-test's broken router)."""
        placed = self.placement(name)
        byidx = {r.index: r for r in self._replicas}
        out = [byidx[i] for i in placed if i in byidx
               and i not in exclude]
        if self.blind:
            return out
        out = [r for r in out
               if r.ready() and not self.is_partitioned(r.index)]
        out.sort(key=lambda r: r.qsize())
        return out

    def report(self) -> dict:
        with self._lock:
            return {
                "blind": self.blind,
                "placement": {m: list(v)
                              for m, v in sorted(self._placement.items())},
                "hot": sorted(self._hot),
                "placed_bytes": dict(self._placed_bytes),
                "partitioned": sorted(
                    i for i in self._partition_until
                    if time.monotonic() < self._partition_until[i]),
            }

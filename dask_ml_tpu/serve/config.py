"""Serving-plane policy knobs (``DASK_ML_TPU_SERVE_*``).

All resolvers follow the repo's env_choice posture: explicit argument
wins, else the live graftpilot override (window / max-batch only — the
two levers the controller owns), else the env knob, else the documented
default — and an unparseable value raises loudly (a typo'd knob must
never silently change admission or latency behavior).  Env is read at
server construction, not per request: the serve loop's hot path never
touches ``os.environ`` — its per-drain-cycle refresh
(``ModelServer._refresh_knobs``) reads only the lock-free override
attribute.
"""

from __future__ import annotations

import os

from ..control import knobs as _knobs

__all__ = [
    "MAX_BATCH_ENV",
    "WINDOW_ENV",
    "QUEUE_ENV",
    "DEADLINE_ENV",
    "HBM_ENV",
    "resolve_max_batch",
    "resolve_window_s",
    "resolve_queue_depth",
    "resolve_deadline_s",
    "resolve_hbm_budget_bytes",
]

#: policy knob: max coalesced REAL rows per serve dispatch (the
#: micro-batch ceiling; a single request may not exceed it either —
#: bulk scoring belongs to the offline ``_partial.predict`` plane).
MAX_BATCH_ENV = "DASK_ML_TPU_SERVE_MAX_BATCH"

#: policy knob: micro-batch gather window in milliseconds — how long
#: the serve loop may hold the first queued request while waiting for
#: more to coalesce.  Adaptive: the full window applies only while the
#: device is idle; with programs in flight the loop dispatches
#: immediately (requests already coalesce behind the running program).
#: 0 disables the wait entirely (latency-first).
WINDOW_ENV = "DASK_ML_TPU_SERVE_WINDOW_MS"

#: policy knob: admission-control bound — max REQUESTS queued ahead of
#: the serve loop.  A full queue sheds load with an explicit
#: ``RequestRejected`` (reason ``queue_full``), never silent latency.
QUEUE_ENV = "DASK_ML_TPU_SERVE_QUEUE"

#: policy knob: default per-request deadline in milliseconds (0 = none).
#: A request still queued past its deadline is dropped BEFORE dispatch
#: with an explicit rejection (reason ``deadline``) — stale work never
#: spends device time.
DEADLINE_ENV = "DASK_ML_TPU_SERVE_DEADLINE_MS"

#: policy knob: device-residency budget in MiB for the model registry.
#: Loading past the budget LRU-evicts resident state to host (an
#: evicted model's next request pays one re-upload, counted per model
#: in the ``serve.residency_fault`` registry family).
HBM_ENV = "DASK_ML_TPU_SERVE_HBM_MB"

_DEFAULT_MAX_BATCH = 1024
_DEFAULT_WINDOW_MS = 2.0
_DEFAULT_QUEUE = 256
_DEFAULT_DEADLINE_MS = 0.0
_DEFAULT_HBM_MB = 512.0


def _env_number(env: str, cast, default):
    raw = os.environ.get(env, "").strip()
    if not raw:
        return default
    try:
        return cast(raw)
    except ValueError:
        raise ValueError(
            f"{env} must be a {cast.__name__}, got {raw!r}") from None


def resolve_max_batch(value: int | None = None) -> int:
    if value is None:
        ov = _knobs.override("serve_max_batch")
        value = (ov if ov is not None
                 else _env_number(MAX_BATCH_ENV, int, _DEFAULT_MAX_BATCH))
    value = int(value)
    if value < 1:
        raise ValueError(f"serve max batch must be >= 1, got {value}")
    return value


def resolve_window_s(value: float | None = None) -> float:
    """The gather window in SECONDS (the knob is in ms)."""
    if value is None:
        ov = _knobs.override("serve_window_ms")
        ms = (float(ov) if ov is not None
              else _env_number(WINDOW_ENV, float, _DEFAULT_WINDOW_MS))
    else:
        ms = float(value) * 1e3
    if ms < 0:
        raise ValueError(f"serve window must be >= 0 ms, got {ms}")
    return ms / 1e3


def resolve_queue_depth(value: int | None = None) -> int:
    value = int(_env_number(QUEUE_ENV, int, _DEFAULT_QUEUE)
                if value is None else value)
    if value < 1:
        raise ValueError(f"serve queue depth must be >= 1, got {value}")
    return value


def resolve_deadline_s(value: float | None = None) -> float:
    """The default per-request deadline in SECONDS (0 = none; the knob
    is in ms)."""
    ms = (_env_number(DEADLINE_ENV, float, _DEFAULT_DEADLINE_MS)
          if value is None else float(value) * 1e3)
    if ms < 0:
        raise ValueError(f"serve deadline must be >= 0 ms, got {ms}")
    return ms / 1e3


def resolve_hbm_budget_bytes(value: float | None = None) -> int:
    mb = (_env_number(HBM_ENV, float, _DEFAULT_HBM_MB)
          if value is None else float(value))
    if mb <= 0:
        raise ValueError(f"serve HBM budget must be > 0 MiB, got {mb}")
    return int(mb * (1 << 20))

"""Serving-plane policy knobs (``DASK_ML_TPU_SERVE_*``).

All resolvers follow the repo's env_choice posture: explicit argument
wins, else the live graftpilot override (window / max-batch only — the
two levers the controller owns), else the env knob, else the documented
default — and an unparseable value raises loudly (a typo'd knob must
never silently change admission or latency behavior).  Env is read at
server construction, not per request: the serve loop's hot path never
touches ``os.environ`` — its per-drain-cycle refresh
(``ModelServer._refresh_knobs``) reads only the lock-free override
attribute.
"""

from __future__ import annotations

import os

from ..control import knobs as _knobs

__all__ = [
    "MAX_BATCH_ENV",
    "WINDOW_ENV",
    "QUEUE_ENV",
    "DEADLINE_ENV",
    "HBM_ENV",
    "FLEET_REPLICAS_ENV",
    "FLEET_HEDGE_ENV",
    "FLEET_DRAIN_ENV",
    "FLEET_RETRIES_ENV",
    "FLEET_PRIORITIES_ENV",
    "FLEET_INJECT_ENV",
    "resolve_max_batch",
    "resolve_window_s",
    "resolve_queue_depth",
    "resolve_deadline_s",
    "resolve_hbm_budget_bytes",
    "resolve_fleet_replicas",
    "resolve_hedge_s",
    "resolve_drain_timeout_s",
    "resolve_fleet_retries",
    "resolve_fleet_priorities",
    "resolve_fleet_inject",
]

#: policy knob: max coalesced REAL rows per serve dispatch (the
#: micro-batch ceiling; a single request may not exceed it either —
#: bulk scoring belongs to the offline ``_partial.predict`` plane).
MAX_BATCH_ENV = "DASK_ML_TPU_SERVE_MAX_BATCH"

#: policy knob: micro-batch gather window in milliseconds — how long
#: the serve loop may hold the first queued request while waiting for
#: more to coalesce.  Adaptive: the full window applies only while the
#: device is idle; with programs in flight the loop dispatches
#: immediately (requests already coalesce behind the running program).
#: 0 disables the wait entirely (latency-first).
WINDOW_ENV = "DASK_ML_TPU_SERVE_WINDOW_MS"

#: policy knob: admission-control bound — max REQUESTS queued ahead of
#: the serve loop.  A full queue sheds load with an explicit
#: ``RequestRejected`` (reason ``queue_full``), never silent latency.
QUEUE_ENV = "DASK_ML_TPU_SERVE_QUEUE"

#: policy knob: default per-request deadline in milliseconds (0 = none).
#: A request still queued past its deadline is dropped BEFORE dispatch
#: with an explicit rejection (reason ``deadline``) — stale work never
#: spends device time.
DEADLINE_ENV = "DASK_ML_TPU_SERVE_DEADLINE_MS"

#: policy knob: device-residency budget in MiB for the model registry.
#: Loading past the budget LRU-evicts resident state to host (an
#: evicted model's next request pays one re-upload, counted per model
#: in the ``serve.residency_fault`` registry family).
HBM_ENV = "DASK_ML_TPU_SERVE_HBM_MB"

#: fleet knob: replica count for :class:`~.fleet.ServeFleet` (each
#: replica is a full ModelServer fault domain: its own blessed serve
#: thread, its own registry under its own ``SERVE_HBM_MB`` budget, its
#: own restart budget).
FLEET_REPLICAS_ENV = "DASK_ML_TPU_FLEET_REPLICAS"

#: fleet knob: tail-latency hedge delay in milliseconds — how long a
#: caller waits on the primary replica before launching a duplicate
#: predict on a second ready replica (first response wins; the loser's
#: device spend is counted, never hidden).  0 disables hedging.
FLEET_HEDGE_ENV = "DASK_ML_TPU_FLEET_HEDGE_MS"

#: fleet knob: per-replica drain barrier timeout in milliseconds for
#: rolling deploys — how long ``rolling_refresh`` waits for a draining
#: replica to flush its in-flight requests before refreshing anyway.
FLEET_DRAIN_ENV = "DASK_ML_TPU_FLEET_DRAIN_TIMEOUT_MS"

#: fleet knob: max router-level re-routes per request (full-jitter
#: backoff between attempts, every attempt drawn from the fleet-level
#: FaultBudget — a retry storm is budgeted, never free).
FLEET_RETRIES_ENV = "DASK_ML_TPU_FLEET_RETRIES"

#: fleet knob: comma-separated priority classes, LOWEST first — the
#: brownout shed order (budget exhausted sheds the leftmost class
#: first, the rightmost class is shed last).
FLEET_PRIORITIES_ENV = "DASK_ML_TPU_FLEET_PRIORITIES"

#: seeded-fault self-test knob (``tools/lint.sh`` convention, same
#: posture as DASK_ML_TPU_LOCK_INJECT): ``replica-kill`` seeds a
#: replica death through the fleet self-test's BLIND router — the gate
#: must exit 1 (requests were lost), proving the zero-lost-requests
#: assertion machinery can actually fail.
FLEET_INJECT_ENV = "DASK_ML_TPU_FLEET_INJECT"

_DEFAULT_MAX_BATCH = 1024
_DEFAULT_WINDOW_MS = 2.0
_DEFAULT_QUEUE = 256
_DEFAULT_DEADLINE_MS = 0.0
_DEFAULT_HBM_MB = 512.0
_DEFAULT_FLEET_REPLICAS = 2
_DEFAULT_FLEET_HEDGE_MS = 50.0
_DEFAULT_FLEET_DRAIN_MS = 5000.0
_DEFAULT_FLEET_RETRIES = 2
_DEFAULT_FLEET_PRIORITIES = ("low", "normal", "high")


def _env_number(env: str, cast, default):
    raw = os.environ.get(env, "").strip()
    if not raw:
        return default
    try:
        return cast(raw)
    except ValueError:
        raise ValueError(
            f"{env} must be a {cast.__name__}, got {raw!r}") from None


def resolve_max_batch(value: int | None = None) -> int:
    if value is None:
        ov = _knobs.override("serve_max_batch")
        value = (ov if ov is not None
                 else _env_number(MAX_BATCH_ENV, int, _DEFAULT_MAX_BATCH))
    value = int(value)
    if value < 1:
        raise ValueError(f"serve max batch must be >= 1, got {value}")
    return value


def resolve_window_s(value: float | None = None) -> float:
    """The gather window in SECONDS (the knob is in ms)."""
    if value is None:
        ov = _knobs.override("serve_window_ms")
        ms = (float(ov) if ov is not None
              else _env_number(WINDOW_ENV, float, _DEFAULT_WINDOW_MS))
    else:
        ms = float(value) * 1e3
    if ms < 0:
        raise ValueError(f"serve window must be >= 0 ms, got {ms}")
    return ms / 1e3


def resolve_queue_depth(value: int | None = None) -> int:
    value = int(_env_number(QUEUE_ENV, int, _DEFAULT_QUEUE)
                if value is None else value)
    if value < 1:
        raise ValueError(f"serve queue depth must be >= 1, got {value}")
    return value


def resolve_deadline_s(value: float | None = None) -> float:
    """The default per-request deadline in SECONDS (0 = none; the knob
    is in ms)."""
    ms = (_env_number(DEADLINE_ENV, float, _DEFAULT_DEADLINE_MS)
          if value is None else float(value) * 1e3)
    if ms < 0:
        raise ValueError(f"serve deadline must be >= 0 ms, got {ms}")
    return ms / 1e3


def resolve_hbm_budget_bytes(value: float | None = None) -> int:
    mb = (_env_number(HBM_ENV, float, _DEFAULT_HBM_MB)
          if value is None else float(value))
    if mb <= 0:
        raise ValueError(f"serve HBM budget must be > 0 MiB, got {mb}")
    return int(mb * (1 << 20))


def resolve_fleet_replicas(value: int | None = None) -> int:
    value = int(_env_number(FLEET_REPLICAS_ENV, int, _DEFAULT_FLEET_REPLICAS)
                if value is None else value)
    if value < 1:
        raise ValueError(f"fleet replicas must be >= 1, got {value}")
    return value


def resolve_hedge_s(value: float | None = None) -> float:
    """The hedge delay in SECONDS (the knob is in ms; 0 = hedging off)."""
    ms = (_env_number(FLEET_HEDGE_ENV, float, _DEFAULT_FLEET_HEDGE_MS)
          if value is None else float(value))
    if ms < 0:
        raise ValueError(f"fleet hedge delay must be >= 0 ms, got {ms}")
    return ms / 1e3


def resolve_drain_timeout_s(value: float | None = None) -> float:
    """The rolling-deploy drain barrier timeout in SECONDS (knob in ms)."""
    ms = (_env_number(FLEET_DRAIN_ENV, float, _DEFAULT_FLEET_DRAIN_MS)
          if value is None else float(value) * 1e3)
    if ms <= 0:
        raise ValueError(f"fleet drain timeout must be > 0 ms, got {ms}")
    return ms / 1e3


def resolve_fleet_retries(value: int | None = None) -> int:
    value = int(_env_number(FLEET_RETRIES_ENV, int, _DEFAULT_FLEET_RETRIES)
                if value is None else value)
    if value < 0:
        raise ValueError(f"fleet retries must be >= 0, got {value}")
    return value


def resolve_fleet_priorities(value=None) -> tuple:
    """Priority classes, LOWEST first (the brownout shed order).  Strict
    parse: empty entries and duplicates raise."""
    if value is None:
        raw = os.environ.get(FLEET_PRIORITIES_ENV, "").strip()
        if not raw:
            return _DEFAULT_FLEET_PRIORITIES
        value = [w.strip() for w in raw.split(",")]
    classes = tuple(str(w) for w in value)
    if not classes or any(not c for c in classes) or \
            len(set(classes)) != len(classes):
        raise ValueError(
            f"{FLEET_PRIORITIES_ENV} must be distinct non-empty class "
            f"names lowest-first, got {value!r}")
    return classes


def resolve_fleet_inject() -> str | None:
    """The seeded-fault self-test knob (strict parse: only the
    documented fault names are accepted)."""
    raw = os.environ.get(FLEET_INJECT_ENV, "").strip()
    if not raw:
        return None
    if raw not in ("replica-kill",):
        raise ValueError(
            f"{FLEET_INJECT_ENV} must be 'replica-kill', got {raw!r}")
    return raw

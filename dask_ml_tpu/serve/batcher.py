"""Micro-batching: bounded admission queue + adaptive gather window.

The request path is the input pipeline's bounded-depth discipline
(``pipeline/core.py``) turned inside out: training bounds STAGED BLOCKS
ahead of one consumer; serving bounds QUEUED REQUESTS ahead of one
dispatcher, and sheds load EXPLICITLY instead of blocking the caller —

* a **full queue** rejects at submit with reason ``queue_full`` (the
  client sees backpressure in microseconds, not as unbounded latency);
* a request still queued past its **deadline** is dropped at drain
  time, BEFORE any device work, with reason ``deadline``;
* every rejection is a loud record: a ``serve.rejected{reason}``
  counter increment plus a ``serve.reject`` flight-recorder event
  carrying the request id — never a silent drop (the same posture as
  degraded-mode block skips, design.md §13).

The gather window is adaptive on the device-occupancy signal graftscope
already tracks (``obs.scope.pending_count``): while programs are in
flight the loop dispatches what it has immediately — arrivals coalesce
naturally behind the running program, and waiting would only add
latency — and only an IDLE device waits up to the configured window for
stragglers to fill the batch (``DASK_ML_TPU_SERVE_WINDOW_MS``).
"""

from __future__ import annotations

import itertools
import queue
import threading
import time

import numpy as np

from .. import obs
from ..obs.metrics import registry as _registry

__all__ = [
    "Request",
    "RequestRejected",
    "ServeFuture",
    "MicroBatcher",
]

_req_ids = itertools.count(1)


class RequestRejected(RuntimeError):
    """A request was shed with an explicit reason (``queue_full`` /
    ``deadline`` / ``oversize`` / ``unknown_model`` / ``shutdown`` /
    ``serve_down`` / ``draining`` — plus the fleet router's
    ``brownout`` and ``fleet_down``) — admission control, deadline
    drops, and drain barriers surface HERE, never as silent latency or
    lost futures."""

    def __init__(self, reason: str, detail: str):
        super().__init__(f"[{reason}] {detail}")
        self.reason = reason


class ServeFuture:
    """One request's completion handle.  ``result()`` polls its owning
    server's liveness while waiting, so a caller blocked on a future is
    itself the recovery trigger when the serve loop died with no new
    submits arriving (the pipeline's consumer-side liveness poll,
    applied to the request plane)."""

    __slots__ = ("_event", "_value", "_exc", "_server")

    def __init__(self, server=None):
        self._event = threading.Event()
        self._value = None
        self._exc = None
        self._server = server

    def done(self) -> bool:
        return self._event.is_set()

    def set_result(self, value) -> None:
        self._value = value
        self._event.set()

    def set_exception(self, exc: BaseException) -> None:
        self._exc = exc
        self._event.set()

    def result(self, timeout: float | None = None):
        deadline = None if timeout is None else \
            time.monotonic() + float(timeout)
        while not self._event.is_set():
            if self._server is not None:
                self._server._ensure_alive()
            remaining = 0.05 if deadline is None else \
                min(0.05, deadline - time.monotonic())
            if remaining <= 0:
                raise TimeoutError("serve request timed out")
            self._event.wait(remaining)
        if self._exc is not None:
            raise self._exc
        return self._value


class Request:
    """One submitted predict: host rows + bookkeeping.  ``mode`` is
    ``"label"`` (decode to classes / regression values) or ``"proba"``
    (per-class probabilities via the donated device transform).

    ``id`` is the request's TRACE ID: it rides the whole causal chain —
    submit (``t_enqueue``) → batcher coalesce (``t_dequeue``, stamped
    when the gather loop pops the request off the admission queue) →
    dispatch → fetch — so the runtime can record an exact per-request
    queue/window/device/fetch latency split (design.md §19) and the
    flight-recorder slow-request exemplars name the request they saw."""

    __slots__ = ("id", "model", "x", "n", "future", "t_enqueue",
                 "t_dequeue", "t_deadline", "mode")

    def __init__(self, model: str, x: np.ndarray, future: ServeFuture,
                 deadline_s: float, mode: str = "label"):
        self.id = next(_req_ids)
        self.model = model
        self.x = x
        self.n = int(x.shape[0])
        self.future = future
        self.mode = mode
        self.t_enqueue = time.monotonic()
        self.t_dequeue = None  # stamped by MicroBatcher.gather
        self.t_deadline = (self.t_enqueue + deadline_s
                           if deadline_s > 0 else None)

    def expired(self, now: float) -> bool:
        return self.t_deadline is not None and now > self.t_deadline


def reject(req: Request, reason: str, detail: str) -> None:
    """The ONE rejection entry: counter + flight event + failed future."""
    _registry().counter("serve.rejected", reason).inc()
    obs.event("serve.reject", request=req.id, model=req.model,
              reason=reason)
    req.future.set_exception(RequestRejected(reason, detail))


class MicroBatcher:
    """The bounded request queue and its gather logic (serve-loop side).

    ``offer`` runs on caller threads (admission only — one non-blocking
    put); ``gather`` runs on the serve loop and owns the window."""

    def __init__(self, *, depth: int, max_batch: int, window_s: float):
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self.max_batch = int(max_batch)
        self.window_s = float(window_s)
        self.depth = int(depth)
        # a request popped mid-gather that would overflow the row
        # ceiling: held for the NEXT batch (serve-loop-only state)
        self._carry: Request | None = None

    def qsize(self) -> int:
        return self._q.qsize()

    # -- caller side -----------------------------------------------------
    def offer(self, item) -> None:
        """Admit ``item`` or raise :class:`RequestRejected` NOW — the
        queue bound IS the backpressure; a blocking put would just move
        the unbounded wait into the caller."""
        try:
            self._q.put_nowait(item)
        except queue.Full:
            if isinstance(item, Request):
                _registry().counter("serve.rejected", "queue_full").inc()
                obs.event("serve.reject", request=item.id,
                          model=item.model, reason="queue_full")
            raise RequestRejected(
                "queue_full",
                f"serve queue at depth {self._q.maxsize}; shedding load"
            ) from None
        _registry().gauge("serve.queue_depth").set(float(self._q.qsize()))

    def offer_control(self, item) -> None:
        """Control items (model load/unload, shutdown) are never shed —
        they block for a slot instead (rare, caller-paced)."""
        self._q.put(item)

    # -- serve-loop side -------------------------------------------------
    @staticmethod
    def _stamp_dequeue(item) -> None:
        """End of the request's QUEUE leg: the first time the gather
        loop holds it.  A carried request keeps its original stamp —
        the carry wait is the batcher's choice, i.e. window time."""
        if isinstance(item, Request) and item.t_dequeue is None:
            item.t_dequeue = time.monotonic()

    def gather(self, stop: threading.Event, poll_s: float = 0.05):
        """One micro-batch: block for the first item (``None`` when the
        loop should re-check ``stop``), then — for plain requests —
        coalesce more until the row ceiling, an expired window, or an
        empty queue on a busy device.  Control items return alone."""
        if self._carry is not None:
            first, self._carry = self._carry, None
        else:
            try:
                first = self._q.get(timeout=poll_s)
            except queue.Empty:
                return None
        self._stamp_dequeue(first)
        if not isinstance(first, Request):
            return [first]
        batch = [first]
        rows = first.n
        t0 = time.monotonic()
        window = self.window_s
        while rows < self.max_batch and not stop.is_set():
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                from ..obs import scope as _scope

                # adaptive window: a BUSY device means arrivals already
                # coalesce behind the running program — dispatch now;
                # only an idle device waits for stragglers
                if _scope.pending_count() > 0:
                    break
                remaining = window - (time.monotonic() - t0)
                if remaining <= 0:
                    break
                time.sleep(min(remaining, 0.0005))
                continue
            self._stamp_dequeue(item)
            if not isinstance(item, Request):
                # control item mid-gather: dispatch the batch first,
                # handle control next round (order preserved)
                batch.append(item)
                break
            if rows + item.n > self.max_batch:
                self._carry = item  # heads the next batch instead
                break
            batch.append(item)
            rows += item.n
        _registry().histogram("serve.batch_window_s").record(
            time.monotonic() - t0)
        _registry().gauge("serve.queue_depth").set(float(self._q.qsize()))
        return batch

    def drain_pending(self):
        """Every queued (and carried) item, without blocking
        (shutdown/teardown)."""
        out = []
        if self._carry is not None:
            out.append(self._carry)
            self._carry = None
        while True:
            try:
                out.append(self._q.get_nowait())
            except queue.Empty:
                return out

"""The online inference plane (design.md §15) — the ROADMAP
``[serving]`` lane: training has been industrial for ten PRs; this
package is the runtime that lets the resulting models face traffic.

The reference project stops at batch prediction (``ParallelPostFit``-
style shard-wise apply, SURVEY §3.5); an online plane is a new
subsystem, built entirely on substrate earlier PRs shipped:

* **micro-batching** (:mod:`.batcher`): queued single-row / small-batch
  requests coalesce into the shared bucket ladder
  (``DASK_ML_TPU_BUCKET``), so every dispatch hits a warm cached
  program (:mod:`dask_ml_tpu.programs`) — zero steady-state compiles,
  sanitizer-verified;
* **model residency** (:mod:`.residency`): many fitted models stay
  device-resident at once under an HBM budget with LRU parking, and
  homogeneous models lane-pack into one vmapped program per window
  (the K=4–64 packing measured 1.6–7.6× on chip);
* **admission control**: a bounded request queue sheds load with an
  explicit ``queue_full`` rejection, per-request deadlines drop stale
  work before dispatch — backpressure is a fast error, never silent
  latency;
* **ops for free** (:mod:`.runtime`): the serve loop is a supervised
  unit (``/healthz`` flips when it dies, restarts ride the fault
  budget with the in-flight batch replayed), and per-model p50/p99
  request latency, batch occupancy, and rejection counters export
  through the live ``/metrics`` endpoint and the committed perf
  ratchet (``serve_latency`` in tools/perf_baseline.json).

Quick start::

    from dask_ml_tpu.serve import ModelServer

    server = ModelServer()
    server.load("churn", fitted_sgd_classifier)
    label = server.predict("churn", one_row)        # sync, micro-batched
    fut = server.submit("churn", rows, deadline_s=0.05)
    labels = fut.result()
    server.close()

Fleet quick start (replication + routing, design.md §22)::

    from dask_ml_tpu.serve import ServeFleet

    fleet = ServeFleet(replicas=4)        # DASK_ML_TPU_FLEET_REPLICAS
    fleet.load("churn", fitted_sgd_classifier, hot=True, slo_ms=20)
    labels = fleet.predict("churn", rows, priority="high")
    fleet.rolling_refresh("churn", retrained_model)  # drain barrier
    fleet.close()
"""

from .batcher import RequestRejected, ServeFuture  # noqa: F401
from .config import (  # noqa: F401
    DEADLINE_ENV,
    FLEET_DRAIN_ENV,
    FLEET_HEDGE_ENV,
    FLEET_INJECT_ENV,
    FLEET_PRIORITIES_ENV,
    FLEET_REPLICAS_ENV,
    FLEET_RETRIES_ENV,
    HBM_ENV,
    MAX_BATCH_ENV,
    QUEUE_ENV,
    WINDOW_ENV,
)
from .fleet import FleetFuture, Replica, ServeFleet  # noqa: F401
from .residency import ModelRegistry, serve_pack_key  # noqa: F401
from .router import (  # noqa: F401
    REPLICA_STATES,
    Router,
    full_jitter_backoff,
    rendezvous,
)
from .runtime import (  # noqa: F401
    SERVE_THREAD_NAME,
    ModelServer,
    report,
)

__all__ = [
    "DEADLINE_ENV",
    "FLEET_DRAIN_ENV",
    "FLEET_HEDGE_ENV",
    "FLEET_INJECT_ENV",
    "FLEET_PRIORITIES_ENV",
    "FLEET_REPLICAS_ENV",
    "FLEET_RETRIES_ENV",
    "HBM_ENV",
    "MAX_BATCH_ENV",
    "QUEUE_ENV",
    "WINDOW_ENV",
    "REPLICA_STATES",
    "SERVE_THREAD_NAME",
    "FleetFuture",
    "ModelRegistry",
    "ModelServer",
    "Replica",
    "RequestRejected",
    "Router",
    "ServeFleet",
    "ServeFuture",
    "full_jitter_backoff",
    "rendezvous",
    "report",
    "serve_pack_key",
]

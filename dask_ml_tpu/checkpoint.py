"""Checkpoint / resume subsystem.

The reference has NO checkpoint subsystem (SURVEY.md §5: persistence is
pickle-by-user-convention; incremental searches keep ``history_`` from which
training can be analyzed but not resumed).  Per the survey's build guidance,
this framework designs checkpointing in from the start — it doubles as the
fault-recovery story for long fits: the reference inherits lineage-based
recompute from dask.distributed, and a TPU pod's analogue is restart from the
last round snapshot.

Two levels:

* ``save_estimator`` / ``load_estimator`` — persist ANY fitted estimator:
  constructor params + trailing-underscore fitted attributes, with device
  arrays (``jax.Array``) pulled to host numpy and ``ShardedRows`` unsharded
  (re-ingestion re-shards on whatever mesh is active at load time, so a
  checkpoint written on one mesh shape restores onto another).
* ``SearchCheckpoint`` — round-granular snapshots of an in-flight
  incremental search (models, per-model history, policy counters), written
  atomically (tmp + rename) so a crash mid-write never corrupts the last
  good snapshot.  ``BaseIncrementalSearchCV(checkpoint=...)`` saves after
  every adaptive round and resumes from the snapshot if one exists.
"""

from __future__ import annotations

import importlib
import json
import os
import pickle
import tempfile

import numpy as np

import jax

from .core.sharded import ShardedRows, unshard
from .obs import event as _obs_event
from .obs.metrics import registry as _obs_registry

__all__ = ["save_estimator", "load_estimator", "SearchCheckpoint"]


def _note_save(kind: str, path: str, **attrs) -> None:
    """Checkpoint observability (design.md §11): one ``checkpoint.save``
    counter tagged by kind, plus a span-tree/flight event — a resumed
    post-mortem shows WHICH snapshots the dying fit managed to write."""
    _obs_registry().counter("checkpoint.save", kind).inc()
    _obs_event("checkpoint.save", kind=kind, path=path, **attrs)

_FORMAT_VERSION = 1


class _ShardedMarker:
    """Tags an attr that was a ShardedRows so load re-shards it."""

    def __init__(self, array: np.ndarray):
        self.array = array


def _to_host(value):
    """Recursively pull device state to host (pickle-safe)."""
    if isinstance(value, ShardedRows):
        return _ShardedMarker(unshard(value))
    if isinstance(value, jax.Array):
        return np.asarray(jax.device_get(value))
    if isinstance(value, dict):
        return {k: _to_host(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        out = [_to_host(v) for v in value]
        return _rebuild_sequence(value, out)
    return value


def _rebuild_sequence(original, out: list):
    """Rebuild a converted list as the original's type.  Namedtuples take
    positional fields (``cls(*out)``); other tuple subclasses that don't
    accept an iterable fall back to a plain tuple rather than corrupting
    state (e.g. an LBFGSState NamedTuple fitted attribute)."""
    if not isinstance(original, tuple):
        return out
    if hasattr(original, "_fields"):  # namedtuple / NamedTuple
        return type(original)(*out)
    try:
        return type(original)(out)
    except TypeError:
        return tuple(out)


def _from_host(value):
    if isinstance(value, _ShardedMarker):
        from .core.sharded import shard_rows

        return shard_rows(value.array)
    if isinstance(value, dict):
        return {k: _from_host(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        out = [_from_host(v) for v in value]
        return _rebuild_sequence(value, out)
    return value


def _atomic_pickle_once(obj, path: str):
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            pickle.dump(obj, f)
        # fault-injection point BETWEEN the tmp write and the atomic
        # rename: exactly the crash-mid-write window the tmp+rename
        # protocol protects against (the previous snapshot must survive)
        from .resilience.testing import maybe_fault

        maybe_fault("checkpoint-write")
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def _atomic_pickle(obj, path: str):
    """Atomic snapshot write with transient-fault recovery.

    Every checkpoint write in the repo (``FitCheckpoint`` /
    ``SearchCheckpoint`` / ``save_estimator``) funnels through here, so
    this is the one choke point for the checkpoint-write fault domain
    (design.md §13): a transient ``OSError`` (ENOSPC race, flaky
    network filesystem) is retried — each attempt rewrites the tmp file
    whole, and the rename stays atomic, so a retry can never tear a
    snapshot.  Anything else (a pickling ``TypeError``, an injected
    :class:`~dask_ml_tpu.resilience.FaultInjected` crash drill)
    propagates unretried: a crash-mid-write drill must observe exactly
    one attempt.  Counted under the ``"checkpoint-write"`` tag in
    :func:`~dask_ml_tpu.diagnostics.fault_stats`.
    """
    from .resilience.retry import retry as _retry

    _retry(_atomic_pickle_once, obj, path, retries=2, backoff=0.05,
           max_backoff=1.0, retryable=(OSError,), deadline=30.0,
           tag="checkpoint-write")


def save_estimator(estimator, path: str) -> None:
    """Persist a fitted estimator to a directory.

    Layout: ``meta.json`` (class identity + format version) and
    ``state.pkl`` (constructor params + fitted attrs, host-side).
    Persisted attrs are the trailing-underscore sklearn fitted attrs PLUS
    any names the estimator lists in ``_checkpoint_private_attrs`` — the
    opt-in for device state kept in private attrs (e.g. SGD's ``_state``
    pytree, MiniBatchKMeans' ``_counts``).
    """
    os.makedirs(path, exist_ok=True)
    cls = type(estimator)
    meta = {
        "format": _FORMAT_VERSION,
        "module": cls.__module__,
        "qualname": cls.__qualname__,
    }
    extra = tuple(getattr(estimator, "_checkpoint_private_attrs", ()))
    fitted = {
        k: _to_host(v)
        for k, v in vars(estimator).items()
        if (k.endswith("_") and not k.startswith("__")) or k in extra
    }
    state = {"params": estimator.get_params(deep=False), "fitted": fitted}
    with open(os.path.join(path, "meta.json"), "w") as f:
        json.dump(meta, f, indent=1)
    _atomic_pickle(state, os.path.join(path, "state.pkl"))
    _note_save("estimator", path, cls=cls.__qualname__)


def load_estimator(path: str):
    """Restore an estimator saved with ``save_estimator``."""
    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    if meta["format"] > _FORMAT_VERSION:  # pragma: no cover
        raise ValueError(f"checkpoint format {meta['format']} is newer than {_FORMAT_VERSION}")
    module = importlib.import_module(meta["module"])
    cls = module
    for part in meta["qualname"].split("."):
        cls = getattr(cls, part)
    with open(os.path.join(path, "state.pkl"), "rb") as f:
        state = pickle.load(f)
    est = cls(**state["params"])
    for k, v in state["fitted"].items():
        setattr(est, k, _from_host(v))
    return est


class SearchCheckpoint:
    """Round-granular snapshot store for incremental searches.

    One pickle file per search; snapshots are whole-state (models + info +
    policy counters + accumulated wall time), overwritten atomically each
    round.  A ``fingerprint`` of the search configuration is stored with
    every snapshot and checked on load: resuming a DIFFERENT search (edited
    parameter grid, changed schedule) against a stale snapshot would
    silently corrupt budgets, so a mismatch is rejected and the search
    starts fresh.  ``complete()`` removes the snapshot so a finished
    search's next ``fit`` starts fresh.
    """

    def __init__(self, path: str, fingerprint: str | None = None,
                 keep_on_complete: bool = False):
        self.path = str(path)
        self.fingerprint = fingerprint
        # bracket checkpoints inside a sequential Hyperband keep their
        # final snapshot: deleting on completion would force a crash-
        # restart to retrain every already-FINISHED bracket from scratch
        # (the resumed policy immediately returns {} so a finished
        # bracket replays in one no-op round); the parent search removes
        # the files once the WHOLE fit completes
        self.keep_on_complete = keep_on_complete

    def exists(self) -> bool:
        return os.path.exists(self.path)

    def save(self, models, info, policy_state, elapsed: float = 0.0) -> None:
        _atomic_pickle(
            {
                "format": _FORMAT_VERSION,
                "fingerprint": self.fingerprint,
                "models": models,
                "info": dict(info),
                "policy_state": policy_state,
                "elapsed": elapsed,
            },
            self.path,
        )
        _note_save("search", self.path, models=len(models))

    def load_if_matches(self):
        """One read: the snapshot tuple, or None if absent / written by a
        differently-configured search (see class docstring)."""
        if not self.exists():
            return None
        with open(self.path, "rb") as f:
            snap = pickle.load(f)
        if snap.get("fingerprint") != self.fingerprint:
            return None
        return snap["models"], snap["info"], snap["policy_state"], snap.get("elapsed", 0.0)

    def complete(self, force: bool = False) -> None:
        """Remove the snapshot of a finished search.  ``force`` overrides
        ``keep_on_complete`` — used by a parent search (Hyperband) to clear
        its brackets' kept snapshots once the WHOLE fit is done."""
        if self.keep_on_complete and not force:
            return
        if self.exists():
            os.unlink(self.path)


def _param_repr(v) -> str:
    """Full-fidelity repr of one parameter value.  numpy truncates reprs of
    arrays >1000 elements with '...', which would give two different large
    parameter grids identical fingerprints — hash shape+dtype+raw bytes for
    arrays (and recurse into containers) instead."""
    if isinstance(v, (np.ndarray, jax.Array)):
        import hashlib

        a = np.asarray(v)
        h = hashlib.sha256(np.ascontiguousarray(a).tobytes()).hexdigest()[:16]
        return f"ndarray(shape={a.shape},dtype={a.dtype},sha={h})"
    if isinstance(v, (list, tuple)):
        inner = ",".join(_param_repr(x) for x in v)
        return f"{type(v).__name__}[{inner}]"
    if isinstance(v, (set, frozenset)):
        # set iteration order is hash-randomized per process for strings —
        # sort element reprs so identical configs fingerprint identically
        # across restarts (the whole point of the fingerprint).
        inner = ",".join(sorted(_param_repr(x) for x in v))
        return f"{type(v).__name__}{{{inner}}}"
    if isinstance(v, dict):
        inner = ",".join(f"{k!r}:{_param_repr(x)}" for k, x in sorted(v.items(), key=lambda kv: repr(kv[0])))
        return f"dict{{{inner}}}"
    return repr(v)


def search_fingerprint(search) -> str:
    """Stable identity of a search's configuration (class + estimator class
    + every constructor param that shapes the schedule or model space)."""
    import hashlib

    payload = repr(
        (
            type(search).__qualname__,
            type(search.estimator).__qualname__,
            sorted((k, _param_repr(v)) for k, v in search.estimator.get_params(deep=False).items()),
            sorted(
                (k, _param_repr(v))
                for k, v in search.get_params(deep=False).items()
                if k not in ("estimator", "checkpoint", "verbose")
            ),
        )
    )
    return hashlib.sha256(payload.encode()).hexdigest()[:16]

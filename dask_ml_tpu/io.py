"""Host IO: native multithreaded CSV / raw-float32 ingest.

The reference delegates file ingest to dask.dataframe/array readers
(external; pandas C parser under the hood).  Here the loader is an in-repo
C++ shim (``native/loader.cpp``, built on first use with the system g++)
driven through ctypes — no Python-level tokenization on the ingest path —
plus generators that stream row blocks straight into ``shard_rows`` /
``wrappers.Incremental``.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

from ._locks import make_lock

import numpy as np

__all__ = [
    "read_csv",
    "read_binary",
    "stream_csv_blocks",
    "stream_binary_blocks",
    "read_csv_sharded",
    "stream_text_lines",
    "stream_dataset",
    "to_columnar",
]

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "native")
_SRC = os.path.join(_NATIVE_DIR, "loader.cpp")
_SO = os.path.join(_NATIVE_DIR, "_loader.so")

_lock = make_lock("io.registry")
_lib = None


def _build() -> None:
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
        _SRC, "-o", _SO,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
    except FileNotFoundError as e:  # pragma: no cover
        raise RuntimeError("native loader needs g++ on PATH") from e
    except subprocess.CalledProcessError as e:  # pragma: no cover
        raise RuntimeError(f"native loader build failed:\n{e.stderr}") from e


def _load():
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        if (not os.path.exists(_SO)) or (
            os.path.getmtime(_SO) < os.path.getmtime(_SRC)
        ):
            _build()
        lib = ctypes.CDLL(_SO)
        lib.dmlt_csv_dims.argtypes = [
            ctypes.c_char_p, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ]
        lib.dmlt_csv_dims.restype = ctypes.c_int
        lib.dmlt_csv_read_f32.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.POINTER(ctypes.c_float), ctypes.c_int,
        ]
        lib.dmlt_csv_read_f32.restype = ctypes.c_int
        lib.dmlt_bin_read_f32.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_float),
        ]
        lib.dmlt_bin_read_f32.restype = ctypes.c_int
        lib.dmlt_stream_open.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int64, ctypes.c_int,
            ctypes.c_int, ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int),
        ]
        lib.dmlt_stream_open.restype = ctypes.c_void_p
        lib.dmlt_stream_next.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.dmlt_stream_next.restype = ctypes.c_int
        lib.dmlt_stream_close.argtypes = [ctypes.c_void_p]
        lib.dmlt_stream_close.restype = None
        _lib = lib
        return lib


def _check(rc: int, path: str) -> None:
    if rc != 0:
        raise OSError(-rc, os.strerror(-rc) if -rc < 200 else "parse error", path)


def csv_dims(path: str, *, has_header: bool = False) -> tuple[int, int]:
    """(rows, cols) of a numeric CSV, excluding the header if present."""
    lib = _load()
    rows = ctypes.c_int64()
    cols = ctypes.c_int64()
    rc = lib.dmlt_csv_dims(path.encode(), int(has_header), ctypes.byref(rows), ctypes.byref(cols))
    _check(rc, path)
    return rows.value, cols.value


def read_csv(path: str, *, has_header: bool = False,
             n_threads: int | None = None, retries: int = 0,
             retry_backoff: float = 0.1,
             retry_deadline_s: float | None = 120.0,
             retry_budget=None) -> np.ndarray:
    """Parse a numeric CSV into a float32 (rows, cols) array, one parser
    thread per row range.

    ``retries`` re-attempts the whole parse on a transient fault
    (flaky network filesystem, contended mount) with exponential backoff
    via :func:`dask_ml_tpu.resilience.retry` — absorbed faults and
    propagated failures are both counted in the global
    :func:`~dask_ml_tpu.diagnostics.fault_stats` under the ``"ingest"``
    tag, so recovery is observable, never silent.  ``retry_deadline_s``
    wall-clock-bounds the retry loop (the re-attempt budget is caller
    input, so the bound must not depend on it — graftlint's
    ``unbounded-retry`` contract): a persistently failing mount raises
    :class:`~dask_ml_tpu.resilience.DeadlineExceeded` loudly instead of
    backing off for as long as the budget arithmetic allows.
    ``retry_budget`` optionally shares a per-fit
    :class:`~dask_ml_tpu.resilience.FaultBudget` with the other fault
    points of the calling fit (design.md §13) — cascading ingest faults
    then stop at the fit-wide ceiling, not this site's alone.
    """
    from .resilience.retry import retry as _retry
    from .resilience.testing import maybe_fault

    def _parse():
        maybe_fault("ingest")
        lib = _load()
        rows, cols = csv_dims(path, has_header=has_header)
        out = np.empty((rows, cols), dtype=np.float32)
        nt = n_threads or min(32, os.cpu_count() or 1)
        rc = lib.dmlt_csv_read_f32(
            path.encode(), int(has_header), 0, rows, cols,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), int(nt),
        )
        _check(rc, path)
        return out

    return _retry(_parse, retries=int(retries), backoff=retry_backoff,
                  deadline=retry_deadline_s, budget=retry_budget,
                  tag="ingest")


def read_binary(path: str, shape: tuple[int, ...], *,
                offset_bytes: int = 0) -> np.ndarray:
    """Read raw little-endian float32 into the given shape."""
    lib = _load()
    out = np.empty(shape, dtype=np.float32)
    rc = lib.dmlt_bin_read_f32(
        path.encode(), int(offset_bytes), int(out.size),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
    )
    _check(rc, path)
    return out


def stream_csv_blocks(path: str, block_rows: int, *, has_header: bool = False,
                      n_threads: int | None = None, prefetch: int = 2,
                      retries: int = 0, retry_backoff: float = 0.1,
                      retry_deadline_s: float | None = 120.0,
                      retry_budget=None):
    """Yield float32 row blocks of (at most) ``block_rows`` — the
    out-of-core ingest feeding ``wrappers.Incremental`` (the reference's
    sequential block streaming, SURVEY.md §2.2).

    Backed by the native WINDOWED streaming session: the file moves
    through a ~32 MB window (never fully resident — host RSS is bounded
    no matter the file size: a 2 GB stream measures ~494 MB peak
    including the jax runtime, and a 12 GB stream asserts < 1.5 GB —
    tests/test_streaming_rss.py) while a background C++ worker parses
    ``prefetch`` blocks ahead of the consumer, so parsing overlaps the
    device compute consuming the blocks.

    ``retries`` re-attempts each BLOCK fetch on a transient fault with
    exponential backoff (:func:`dask_ml_tpu.resilience.retry`, tag
    ``"ingest"``) — the native session keeps the stream position, so a
    failed attempt never skips rows.  ``retry_deadline_s`` wall-clock
    bounds each block's retry loop (see :func:`read_csv`)."""
    if block_rows < 1:
        raise ValueError(f"block_rows must be >= 1, got {block_rows}")
    from .resilience.retry import retry as _retry
    from .resilience.testing import maybe_fault

    lib = _load()
    n_threads = n_threads or min(8, os.cpu_count() or 1)
    rows = ctypes.c_int64()
    cols = ctypes.c_int64()
    err = ctypes.c_int()
    handle = lib.dmlt_stream_open(
        path.encode(), int(has_header), int(block_rows), int(n_threads),
        int(max(prefetch, 1)), ctypes.byref(rows), ctypes.byref(cols),
        ctypes.byref(err),
    )
    if not handle:
        _check(err.value, path)
    try:
        c = cols.value
        got = ctypes.c_int64()

        def _next_block():
            maybe_fault("ingest")
            # fresh buffer per block: the native memcpy fills it and the
            # trimmed view is yielded as-is — no second Python-side copy
            buf = np.empty((block_rows, max(c, 1)), dtype=np.float32)
            rc = lib.dmlt_stream_next(
                handle, buf.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                ctypes.byref(got),
            )
            _check(rc, path)
            return buf

        while True:
            buf = _retry(_next_block, retries=int(retries),
                         backoff=retry_backoff,
                         deadline=retry_deadline_s, budget=retry_budget,
                         tag="ingest")
            if got.value == 0:
                break
            yield buf[: got.value]
    finally:
        lib.dmlt_stream_close(handle)


def stream_binary_blocks(path: str, block_rows: int, n_features: int, *,
                         n_rows: int | None = None, offset_bytes: int = 0,
                         retries: int = 0, retry_backoff: float = 0.1,
                         retry_deadline_s: float | None = 120.0,
                         retry_budget=None):
    """Yield float32 row blocks of (at most) ``block_rows`` from a raw
    little-endian float32 file — the binary twin of
    :func:`stream_csv_blocks`, for out-of-core streams whose parse cost
    is pure disk read.

    ``n_rows`` defaults to every complete row after ``offset_bytes``
    (the file may carry a trailing partial row, e.g. an interrupted
    writer — it is ignored, matching the complete-blocks contract).
    Feed the generator to ``_partial.fit`` / ``wrappers.Incremental`` to
    ride the prefetch pipeline (:mod:`dask_ml_tpu.pipeline`): block
    *k+1*'s read + H2D staging then overlaps block *k*'s device step.

    ``retries`` re-attempts each BLOCK read on a transient fault
    (:func:`dask_ml_tpu.resilience.retry`, tag ``"ingest"``); reads are
    offset-addressed, so a failed attempt never skips rows.
    ``retry_deadline_s`` wall-clock bounds each block's retry loop (see
    :func:`read_csv`).
    """
    if block_rows < 1:
        raise ValueError(f"block_rows must be >= 1, got {block_rows}")
    if n_features < 1:
        raise ValueError(f"n_features must be >= 1, got {n_features}")
    if offset_bytes < 0:
        raise ValueError(f"offset_bytes must be >= 0, got {offset_bytes}")
    row_bytes = 4 * int(n_features)
    try:
        total = os.path.getsize(path)
    except OSError as e:
        raise OSError(e.errno or 2, e.strerror or "stat failed", path)
    if n_rows is None:
        n_rows = max(total - int(offset_bytes), 0) // row_bytes
    n_rows = int(n_rows)
    # up-front extent validation, EAGER (this wrapper runs at call time,
    # not first next()): a truncated file must fail HERE, not as a short
    # read in the middle of an epoch — mid-stream the model has already
    # trained on a partial pass, the worst failure shape
    need = int(offset_bytes) + n_rows * row_bytes
    if need > total:
        raise ValueError(
            f"{path}: {n_rows} rows x {n_features} float32 features at "
            f"offset {offset_bytes} needs {need} bytes, file has {total} "
            f"— truncated file or wrong shape")

    def _blocks():
        from .resilience.retry import retry as _retry
        from .resilience.testing import maybe_fault

        def _read_block(lo, rows):
            maybe_fault("ingest")
            return read_binary(
                path, (rows, int(n_features)),
                offset_bytes=int(offset_bytes) + lo * row_bytes,
            )

        for lo in range(0, n_rows, int(block_rows)):
            rows = min(int(block_rows), n_rows - lo)
            yield _retry(_read_block, lo, rows, retries=int(retries),
                         backoff=retry_backoff, deadline=retry_deadline_s,
                         budget=retry_budget, tag="ingest")

    return _blocks()


def stream_text_lines(path: str, block_lines: int = 10_000, *,
                      retries: int = 0, retry_backoff: float = 0.1,
                      retry_deadline_s: float | None = 120.0,
                      retry_budget=None):
    """Yield lists of (at most) ``block_lines`` stripped text lines —
    out-of-core text ingest feeding the streaming vectorizers
    (``feature_extraction.text.*.stream_transform``): the file is read
    incrementally, never whole.

    ``retries`` re-attempts each BLOCK read on a transient fault with
    exponential backoff (:func:`dask_ml_tpu.resilience.retry`, tag
    ``"ingest"`` — the PR-4 ingest contract the numeric streams already
    carry): reads are byte-offset-addressed, so a failed attempt
    reopens, seeks to the block's start, and re-reads exactly the same
    lines — nothing skipped, nothing repeated.  ``retry_deadline_s``
    wall-clock-bounds each block's retry loop and ``retry_budget``
    optionally shares the fit-wide
    :class:`~dask_ml_tpu.resilience.FaultBudget` (see
    :func:`read_csv`)."""
    if block_lines < 1:
        raise ValueError(f"block_lines must be >= 1, got {block_lines}")
    from .resilience.retry import retry as _retry
    from .resilience.testing import maybe_fault

    state: dict = {"pos": 0, "f": None}

    def _read_block():
        maybe_fault("ingest")
        f = state["f"]
        if f is None or f.closed:
            f = state["f"] = open(path, "r", encoding="utf-8")
        f.seek(state["pos"])
        # readline (not iteration): line iteration read-ahead makes
        # tell() illegal, and the saved offset is the retry contract
        block: list[str] = []
        while len(block) < block_lines:
            line = f.readline()
            if not line:
                break
            block.append(line.rstrip("\n"))
        state["pos"] = f.tell()
        return block

    try:
        while True:
            block = _retry(_read_block, retries=int(retries),
                           backoff=retry_backoff,
                           deadline=retry_deadline_s, budget=retry_budget,
                           tag="ingest")
            if not block:
                break
            yield block
    finally:
        if state["f"] is not None and not state["f"].closed:
            state["f"].close()


def read_csv_sharded(path: str, *, has_header: bool = False, mesh=None,
                     retries: int = 0, retry_backoff: float = 0.1):
    """Parse a CSV and place it row-sharded over the mesh (ShardedRows)."""
    from .core.sharded import shard_rows

    return shard_rows(
        read_csv(path, has_header=has_header, retries=retries,
                 retry_backoff=retry_backoff),
        mesh,
    )


#: file suffixes ``to_columnar`` treats as raw float32 (anything else
#: parses as CSV)
_BINARY_SUFFIXES = (".bin", ".raw", ".f32")


def to_columnar(path: str, out_dir: str, *, source: str = "auto",
                n_features: int | None = None, has_header: bool = False,
                label_col: int | None = None, shards: int = 4,
                block_rows: int = 4096, compression: str = "zlib"):
    """Convert a CSV or raw-float32 file into a sharded columnar
    dataset directory (:mod:`dask_ml_tpu.data`) — one streaming pass,
    bounded memory, bucket-aligned blocks.

    The columnar form is what repeated epochs should stream: parse cost
    is paid ONCE here instead of per epoch, blocks are individually
    addressable (the key-derived shuffle and reader replay need that),
    and ``block_rows`` (default 4096, an ``auto`` ladder rung) makes
    ``programs.bucket.pad_block`` a no-op on the hot path.
    ``label_col`` splits that column off as the target ``y``.
    Returns the :class:`~dask_ml_tpu.data.DatasetManifest`.
    """
    from . import data as _data

    if source == "auto":
        source = "binary" if path.lower().endswith(_BINARY_SUFFIXES) \
            else "csv"
    if source == "csv":
        return _data.convert_csv(
            out_dir=out_dir, path=path, has_header=has_header,
            label_col=label_col, shards=shards, block_rows=block_rows,
            compression=compression)
    if source == "binary":
        if n_features is None:
            raise ValueError(
                "to_columnar needs n_features for a raw binary source")
        return _data.convert_binary(
            out_dir=out_dir, path=path, n_features=int(n_features),
            label_col=label_col, shards=shards, block_rows=block_rows,
            compression=compression)
    raise ValueError(
        f"source must be 'auto', 'csv', or 'binary', got {source!r}")


def stream_dataset(path, **kwargs):
    """Open a sharded columnar dataset (a manifest path / dataset
    directory / :class:`~dask_ml_tpu.data.DatasetManifest`) as a
    :class:`~dask_ml_tpu.data.ShardedDataset` — the parallel-reader,
    key-shuffled successor of the single-stream ``stream_*_blocks``
    generators; feed it to ``_partial.fit`` / ``wrappers.Incremental``
    / ``pipeline.stream_partial_fit`` directly."""
    from .data import ShardedDataset

    return ShardedDataset(path, **kwargs)

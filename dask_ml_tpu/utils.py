"""Shared utilities — twin of ``dask_ml/utils.py`` (reference symbols:
``check_array``, ``handle_zeros_in_scale``, ``svd_flip``, ``draw_seed``,
``_timer``, ``assert_estimator_equal``), re-done for jax arrays.
"""

from __future__ import annotations

import contextlib
import logging
import numbers
import time

import numpy as np

import jax
import jax.numpy as jnp

from .core.sharded import ShardedRows, unshard

logger = logging.getLogger(__name__)


def check_array(
    array,
    *,
    accept_sharded: bool = True,
    ensure_2d: bool = True,
    allow_nd: bool = False,
    dtype="numeric",
    copy: bool = False,
):
    """Validate input like the reference's dask-aware ``check_array``.

    Accepts numpy arrays, jax arrays, and :class:`ShardedRows`.  Returns the
    input unchanged structurally (no premature host transfer), after shape /
    dtype validation.
    """
    if isinstance(array, ShardedRows):
        inner = array.data
        if ensure_2d and inner.ndim != 2:
            raise ValueError(f"Expected 2D input, got ndim={inner.ndim}")
        if array.n_samples == 0:
            raise ValueError("Found array with 0 samples")
        return array
    if hasattr(array, "to_numpy"):  # pandas
        array = array.to_numpy()
    arr = jnp.asarray(array) if isinstance(array, jax.Array) else np.asarray(array)
    if dtype == "numeric" and not np.issubdtype(arr.dtype, np.number):
        raise ValueError(f"Expected numeric dtype, got {arr.dtype}")
    if arr.ndim == 0:
        raise ValueError("Expected an array, got a scalar")
    if ensure_2d and arr.ndim != 2:
        if arr.ndim == 1 or not allow_nd:
            raise ValueError(
                f"Expected 2D array, got ndim={arr.ndim}. "
                "Reshape your data with .reshape(-1, 1) for a single feature."
            )
    if not allow_nd and arr.ndim > 2:
        raise ValueError(f"Expected <=2 dims, got ndim={arr.ndim}")
    if arr.shape[0] == 0:
        raise ValueError("Found array with 0 samples")
    if copy and isinstance(arr, np.ndarray):
        arr = arr.copy()
    return arr


def check_consistent_length(*arrays):
    lengths = set()
    for a in arrays:
        if a is None:
            continue
        if isinstance(a, ShardedRows):
            n = a.n_samples
        else:
            shape = getattr(a, "shape", None)
            n = shape[0] if shape else len(a)
        lengths.add(int(n))
    if len(lengths) > 1:
        raise ValueError(f"Inconsistent sample counts: {sorted(lengths)}")


def check_chunks(n_samples, n_features=None, chunks=None):
    """Normalize a row-block size the way the reference normalizes dask
    chunks (reference: ``dask_ml/utils.py :: check_chunks``).

    The TPU collection model has no column chunking (features live whole on
    each shard — SURVEY §2.2 data parallelism), so ``chunks`` here is the
    ROW-block granularity; ``_partial.fit`` normalizes its ``chunk_size``
    through this.  Accepts ``None`` (auto: ≤ 16 blocks), an int (rows per
    block), or a (rows, features) tuple whose feature entry must cover all
    columns.  Returns rows-per-block as an int.
    """
    n_samples = int(n_samples)
    if n_samples <= 0:
        raise ValueError("n_samples must be positive")
    if chunks is None:
        return max(1, -(-n_samples // 16))
    if isinstance(chunks, numbers.Integral):
        chunks = int(chunks)
        if chunks <= 0:
            raise ValueError(f"chunks must be positive; got {chunks}")
        return chunks
    if isinstance(chunks, (tuple, list)) and len(chunks) == 2:
        rows, cols = chunks
        if n_features is not None and int(cols) != int(n_features):
            raise ValueError(
                f"column chunking is not supported on the TPU layout; the "
                f"feature chunk must span all {n_features} columns, got {cols}"
            )
        return check_chunks(n_samples, n_features, int(rows))
    raise ValueError(f"Unrecognized chunks: {chunks!r}")


def check_matching_blocks(*arrays):
    """Raise unless all sharded inputs share one row layout (reference:
    ``dask_ml/utils.py :: check_matching_blocks`` — same-chunk check).

    For :class:`ShardedRows`, "matching blocks" means identical logical
    length, identical padded length, and identical device sharding — the
    preconditions for zipping two collections through one shard_map.
    Non-sharded array-likes only need matching logical length.
    """
    check_consistent_length(*arrays)
    sharded = [a for a in arrays if isinstance(a, ShardedRows)]
    if len(sharded) < 2:
        return
    first = sharded[0]
    for other in sharded[1:]:
        if other.data.shape[0] != first.data.shape[0]:
            raise ValueError(
                f"Mismatched padded lengths: {first.data.shape[0]} vs "
                f"{other.data.shape[0]} — reshard with shard_rows so the "
                f"pad+mask layouts agree"
            )
        if other.data.sharding != first.data.sharding:
            raise ValueError(
                "Mismatched device shardings: "
                f"{first.data.sharding} vs {other.data.sharding}"
            )


def slice_columns(X, columns):
    """Select columns from an array, dataframe or ShardedRows (reference:
    ``dask_ml/utils.py :: slice_columns``).  ``None`` returns X unchanged;
    dataframes slice by label, arrays by position."""
    if columns is None:
        return X
    if isinstance(X, ShardedRows):
        cols = np.asarray(columns)
        if cols.dtype == bool:  # mask → positions (parity with X[:, mask])
            cols = np.flatnonzero(cols)
        idx = jnp.asarray(cols.astype(np.int32))
        return ShardedRows(
            data=X.data[:, idx], mask=X.mask, n_samples=X.n_samples
        )
    if hasattr(X, "iloc"):  # pandas
        return X[list(columns)]
    return X[:, np.asarray(columns)]


def env_choice(name: str, allowed: tuple, default: str = "auto") -> str:
    """Read a strategy knob from the environment with validation — the
    shared shape behind ``DASK_ML_TPU_SCATTER`` / ``DASK_ML_TPU_PACK``
    (each policy keeps its own platform-auto logic, but the read/validate
    step lives once)."""
    import os

    v = os.environ.get(name, default).strip().lower()
    if v not in allowed:
        raise ValueError(
            f"{name} must be {'|'.join(allowed)}, got {v!r}"
        )
    return v


def safe_denominator(x):
    """0-safe divisor that PRESERVES fractional weight masses.

    ``maximum(x, 1)`` silently shrinks any mean whose total mass is in
    (0, 1) — the mask doubles as the per-row weight throughout this
    framework, so sub-unit masses are legitimate (caught by the NB
    weighted-stream and sub-unit-KMeans property tests).  The kept branch
    is never 0, so the division is always finite.
    """
    return jnp.where(x > 0, x, 1.0)


def chan_merge(na, ma, m2a, nb, mb, vb):
    """Merge two (count, mean, M2) moment summaries (Chan et al. 1979) —
    the numerically safe parallel-variance update shared by
    ``StandardScaler.partial_fit`` (scalar count, (d,) moments) and
    ``GaussianNB.partial_fit`` ((k,1) per-class counts, (k,d) moments).
    Counts must broadcast against the moment arrays; zero-count sides are
    handled (the 1-clamped denominator only engages when n == 0, where
    every product above it is 0 too).  Returns ``(n, mean, m2)``.
    """
    n = na + nb
    nsafe = safe_denominator(n)
    delta = mb - ma
    mean = ma + delta * (nb / nsafe)
    m2 = m2a + vb * nb + delta * delta * (na * nb / nsafe)
    return n, mean, m2


def handle_zeros_in_scale(scale):
    """Avoid division by ~0 when scaling (constant features scale by 1).

    Reference: ``dask_ml/utils.py :: handle_zeros_in_scale``.
    """
    scale = jnp.asarray(scale)
    if scale.ndim == 0:
        return jnp.where(scale == 0.0, 1.0, scale)
    eps = 10 * jnp.finfo(scale.dtype).eps
    return jnp.where(jnp.abs(scale) < eps, 1.0, scale)


def svd_flip(u, v, u_based_decision: bool = True):
    """Deterministic SVD sign convention (reference: ``utils.py :: svd_flip``)."""
    if u_based_decision:
        max_abs = jnp.argmax(jnp.abs(u), axis=0)
        signs = jnp.sign(u[max_abs, jnp.arange(u.shape[1])])
    else:
        max_abs = jnp.argmax(jnp.abs(v), axis=1)
        signs = jnp.sign(v[jnp.arange(v.shape[0]), max_abs])
    u = u * signs[jnp.newaxis, :]
    v = v * signs[:, jnp.newaxis]
    return u, v


def _check_class_weight_keys(class_weight, classes):
    """A dict key naming no fitted class is a typo, not a preference —
    raise like sklearn's compute_class_weight instead of silently
    training unweighted."""
    known = set(np.asarray(classes).tolist())
    unknown = [k for k in class_weight if k not in known]
    if unknown:
        raise ValueError(
            f"class_weight keys {unknown!r} are not in the fitted classes "
            f"{sorted(known)!r}"
        )


def effective_mask(mask, y_padded=None, *, sample_weight=None,
                   class_weight=None, classes=None, n_samples=None):
    """Fold per-row weights into a validity mask.

    The pad+mask discipline makes every masked reduction a weighted
    reduction for free: the mask IS a multiplicative per-row weight, so
    ``sample_weight`` and ``class_weight`` simply scale it (pad rows stay
    at 0).  sklearn semantics throughout: ``'balanced'`` uses
    ``n / (K * count_k)`` with UNWEIGHTED counts; a class-weight dict
    defaults absent classes to 1.0.

    Args:
      mask: (padded_n,) validity/weight vector (device).
      y_padded: (padded_n,) raw label values (device) — required for
        ``class_weight``.
      sample_weight: host (n_samples,) per-row weights, or None.
      class_weight: dict {label: weight} or ``'balanced'`` or None.
      classes: label inventory (required for ``class_weight``).
      n_samples: true row count (defaults to ``len(sample_weight)``).
    Returns the weighted mask (device, same shape as ``mask``).
    """
    w = mask
    if sample_weight is not None:
        sw = np.asarray(sample_weight, np.float32).ravel()
        n = int(n_samples) if n_samples is not None else sw.shape[0]
        if sw.shape[0] != n:
            raise ValueError(
                f"sample_weight has {sw.shape[0]} entries for {n} samples"
            )
        pad = int(mask.shape[0]) - sw.shape[0]
        if pad < 0:
            raise ValueError(
                f"sample_weight longer ({sw.shape[0]}) than padded rows "
                f"({mask.shape[0]})"
            )
        if pad:
            sw = np.pad(sw, (0, pad))
        w = w * jnp.asarray(sw)
    if class_weight is not None:
        if y_padded is None or classes is None:
            raise ValueError("class_weight requires labels and classes")
        cls_np = np.asarray(classes)
        cls = jnp.asarray(cls_np, y_padded.dtype)
        ind = (
            (y_padded[None, :] == cls[:, None]).astype(jnp.float32)
            * mask[None, :]
        )
        if isinstance(class_weight, str):
            if class_weight != "balanced":
                raise ValueError(
                    f"class_weight must be a dict or 'balanced'; got "
                    f"{class_weight!r}"
                )
            counts = jnp.sum(ind, axis=1)
            total = jnp.sum(mask)
            cw = total / (len(cls_np) * safe_denominator(counts))
        else:
            _check_class_weight_keys(class_weight, cls_np)
            cw = jnp.asarray(
                [float(class_weight.get(c, 1.0)) for c in cls_np.tolist()],
                jnp.float32,
            )
        w = w * jnp.sum(cw[:, None] * ind, axis=0)
    return w


def classes_f32_exact(classes) -> bool:
    """True when every class label survives a float32 round-trip — the
    precondition for device-side label comparison (int labels past 2^24
    would collide after the cast and silently score wrong)."""
    classes = np.asarray(classes)
    return bool(
        np.issubdtype(classes.dtype, np.number)
        and np.array_equal(
            classes.astype(np.float32).astype(classes.dtype), classes
        )
    )


def masked_device_accuracy(pred_idx, y_data, mask, classes) -> float:
    """Masked accuracy as ONE replicated scalar fetch.

    ``pred_idx``: (padded_n,) predicted class indices (device);
    ``y_data``: (padded_n,) raw label values (device).  Comparison is on
    VALUES — a label outside ``classes`` counts as a miss, matching the
    host accuracy path.  The single scalar fetch is the only legal form
    for multi-host global arrays (and avoids the O(n) transfer anywhere).
    Callers must gate on :func:`classes_f32_exact`.
    """
    cls = jnp.asarray(np.asarray(classes).astype(np.float32))
    hit = (
        (cls[pred_idx] == y_data.astype(jnp.float32)).astype(jnp.float32)
        * mask
    )
    return float(jnp.sum(hit) / safe_denominator(jnp.sum(mask)))


def reweight_rows(X, *, sample_weight=None, class_weight=None,
                  classes=None, y_padded=None):
    """Return ``X`` (ShardedRows) with per-row weights folded into its
    mask via :func:`effective_mask` — the one place estimators rebuild a
    weighted ShardedRows, so the weighting contract cannot drift between
    them.  No-op (same object) when no weights are given."""
    if sample_weight is None and class_weight is None:
        return X
    return ShardedRows(
        data=X.data,
        mask=effective_mask(
            X.mask, y_padded, sample_weight=sample_weight,
            class_weight=class_weight, classes=classes,
            n_samples=X.n_samples,
        ),
        n_samples=X.n_samples,
    )


def host_class_weight_rows(class_weight, classes, yv):
    """Per-row class weights resolved ON HOST — the twin of
    :func:`effective_mask`'s device class-weight branch for label arrays
    that cannot cross to device (strings, big ints).  Same sklearn
    semantics: ``'balanced'`` is ``n / (K * count_k)`` with unweighted
    counts; dict keys default to 1.0.  Keep the two branches in sync."""
    classes = np.asarray(classes)
    yv = np.asarray(yv)
    if isinstance(class_weight, str):
        if class_weight != "balanced":
            raise ValueError(
                f"class_weight must be a dict or 'balanced'; got "
                f"{class_weight!r}"
            )
        # align counts to the FULL class inventory: a class absent from
        # this yv must not shift (or overrun) the weight table
        uniq, counts_u = np.unique(yv, return_counts=True)
        counts = np.zeros(len(classes))
        counts[np.searchsorted(classes, uniq)] = counts_u
        cw = yv.shape[0] / (len(classes) * np.maximum(counts, 1.0))
    else:
        _check_class_weight_keys(class_weight, classes)
        cw = np.asarray(
            [float(class_weight.get(c, 1.0)) for c in classes.tolist()]
        )
    return cw[np.searchsorted(classes, yv)].astype(np.float32)


def check_max_iter(max_iter):
    """Reject non-positive epoch budgets up front: every epoch-loop
    estimator reads the loop variable after the loop, so ``max_iter=0``
    would otherwise surface as an unbound-variable crash mid-fit."""
    if max_iter < 1:
        raise ValueError(f"max_iter must be >= 1, got {max_iter}")


def draw_seed(random_state, low=0, high=2**31 - 1, size=None):
    """Draw integer seed(s) from a numpy RandomState-compatible source.

    Reference: ``dask_ml/utils.py :: draw_seed``.
    """
    rng = check_random_state(random_state)
    return rng.randint(low, high, size=size)


def check_random_state(random_state) -> np.random.RandomState:
    if random_state is None or isinstance(random_state, numbers.Integral):
        return np.random.RandomState(random_state)
    if isinstance(random_state, np.random.RandomState):
        return random_state
    raise ValueError(f"Cannot make RandomState from {random_state!r}")


@contextlib.contextmanager
def _timer(name: str, _logger=None, level=logging.INFO):
    """Log phase durations (reference: ``utils.py :: _timer``)."""
    _logger = _logger or logger
    start = time.perf_counter()
    _logger.log(level, "Starting %s", name)
    try:
        yield
    finally:
        _logger.log(level, "Finished %s in %.4fs", name, time.perf_counter() - start)


def copy_learned_attributes(from_estimator, to_estimator):
    """Copy fitted (trailing-underscore) attributes between estimators.

    Reference: ``dask_ml/_utils.py :: copy_learned_attributes``.
    """
    for name, value in vars(from_estimator).items():
        if name.endswith("_") and not name.startswith("_"):
            setattr(to_estimator, name, value)
    return to_estimator


def assert_estimator_equal(left, right, exclude=(), **kwargs):
    """Assert two fitted estimators carry (approximately) equal fitted attrs.

    Reference: ``dask_ml/utils.py :: assert_estimator_equal``.
    """
    left_attrs = {k for k in vars(left) if k.endswith("_") and not k.startswith("_")}
    right_attrs = {k for k in vars(right) if k.endswith("_") and not k.startswith("_")}
    if isinstance(exclude, str):
        exclude = {exclude}
    attrs = (left_attrs & right_attrs) - set(exclude)
    assert attrs, "no common fitted attributes"
    for attr in attrs:
        l, r = getattr(left, attr), getattr(right, attr)
        _assert_eq(l, r, name=attr, **kwargs)


def _assert_eq(l, r, name="", **kwargs):
    if isinstance(l, (ShardedRows, jax.Array)):
        l = unshard(l)
    if isinstance(r, (ShardedRows, jax.Array)):
        r = unshard(r)
    if isinstance(l, np.ndarray) or isinstance(r, np.ndarray):
        np.testing.assert_allclose(np.asarray(l), np.asarray(r), err_msg=name, **kwargs)
    elif isinstance(l, numbers.Number):
        np.testing.assert_allclose(l, r, err_msg=name, **kwargs)
    else:
        assert l == r, f"{name}: {l!r} != {r!r}"

"""Exporters: schema-versioned JSONL event log + Chrome/Perfetto JSON.

**JSONL** (``DASK_ML_TPU_TRACE=path`` or ``obs.enable(jsonl_path=...)``)
streams every completed span/event as one JSON line the moment it
completes, so a crashed process keeps everything up to the crash.  The
first line is a header ``{"schema": "grafttrace", "version": 1, ...}``;
:func:`read_jsonl` validates it on read-back and refuses a NEWER major
version (an older one is fine — the schema only grows).

**Perfetto** (:func:`perfetto_trace` / :func:`export_perfetto`) emits
the Chrome ``trace_event`` format (``{"traceEvents": [...]}``, complete
``"X"`` slices in microseconds, one ``tid`` lane per recorded thread
with ``"M"`` thread-name metadata) plus a dedicated **device lane**
(tid 0) built from graftscope's per-program in-flight intervals
(:mod:`.scope`).  Load it in ui.perfetto.dev or ``chrome://tracing``:
the host-side parse/stage/compute overlap renders directly against
measured device occupancy — idle gaps are the white space in the
device lane — and the whole thing still sits happily next to an XProf
device trace of the same fit.
"""

from __future__ import annotations

import json
import os
import sys
import threading

from .._locks import make_lock
import time

from . import spans as _spans

__all__ = [
    "JsonlSink",
    "read_jsonl",
    "perfetto_trace",
    "export_perfetto",
]


class JsonlSink:
    """Append-one-line-per-record writer (thread-safe: the prefetch
    worker completes spans too).  Each line is flushed so a kill -9
    loses at most the record being written."""

    def __init__(self, path: str):
        self.path = str(path)
        self._lock = make_lock("obs.export")
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)
        self._f = open(self.path, "a", encoding="utf-8")
        self._write_obj({
            "schema": "grafttrace",
            "version": _spans.SCHEMA_VERSION,
            "pid": os.getpid(),
            "unix_time": round(time.time(), 3),
            # perf_counter epoch at header time: lets a reader map the
            # records' monotonic stamps onto wall clock
            "perf_counter": round(time.perf_counter(), 9),
        })

    def _write_obj(self, obj: dict) -> None:
        # drill point (resilience.testing): an injected
        # OSError(ENOSPC) here exercises the disk-full degradation
        # below — drop the sink, keep training.  Looked up through
        # sys.modules, NOT imported: obs is imported BY resilience, and
        # a DASK_ML_TPU_TRACE sink writes its header DURING obs's own
        # import, where importing resilience back would be a cycle.  If
        # the module is absent no plan can be active (plans live in it),
        # so skipping the fire is exact, not a best-effort.
        testing = sys.modules.get("dask_ml_tpu.resilience.testing")
        if testing is not None:
            testing.maybe_fault("exporter-write")
        line = json.dumps(obj, separators=(",", ":"), default=repr)
        with self._lock:
            self._f.write(line + "\n")
            self._f.flush()

    def write(self, rec) -> None:
        try:
            self._write_obj(rec.as_dict())
        # graftlint: disable=swallowed-fault -- write-after-close during interpreter/sink shutdown: the sink was already dropped WITH its one warning (OSError branch below); a second message per straggling span would be noise, not observability
        except ValueError:  # closed file on shutdown: quiet drop
            pass
        except OSError:
            # disk full / filesystem gone read-only: the TRACED FIT
            # must not die for its trace.  Warn once, drop the sink
            # (ring + flight recording continue), keep training.
            import logging

            logging.getLogger(__name__).warning(
                "grafttrace: JSONL sink %s failed; disabling file "
                "streaming for this process", self.path, exc_info=True,
            )
            self.close()
            from . import spans as _sp

            if _sp._STATE.sink is self:
                _sp._STATE.sink = None

    def close(self) -> None:
        with self._lock:
            try:
                self._f.close()
            except Exception:  # pragma: no cover
                pass


def read_jsonl(path: str) -> tuple[dict, list[dict]]:
    """``(first_header, records)`` from a grafttrace JSONL file; raises
    ``ValueError`` on a malformed header or a newer schema version.

    The sink appends, so a file may hold SEVERAL sessions (the
    documented multi-process ``DASK_ML_TPU_TRACE=path`` usage), each
    opening with its own header line.  Every header is validated and
    excluded from ``records``; note each session's ``t0``/``t1`` stamps
    are that process's monotonic clock — map them to wall time via its
    own header's ``perf_counter``/``unix_time`` pair before comparing
    across sessions.
    """
    with open(path, encoding="utf-8") as f:
        lines = [ln for ln in (ln.strip() for ln in f) if ln]
    if not lines:
        raise ValueError(f"{path}: empty trace file")
    first = json.loads(lines[0])
    if first.get("schema") != "grafttrace":
        raise ValueError(f"{path}: not a grafttrace JSONL (header {first!r})")
    records = []
    for i, ln in enumerate(lines):
        try:
            obj = json.loads(ln)
        except json.JSONDecodeError:
            if i == len(lines) - 1:
                # a torn FINAL line is the expected kill-9/OOM artifact
                # ("a crashed process keeps everything up to the
                # crash"): drop it, keep the intact records
                break
            raise ValueError(
                f"{path}: malformed record at line {i + 1}"
            ) from None
        if obj.get("schema") == "grafttrace":  # a session header
            if int(obj.get("version", 0)) > _spans.SCHEMA_VERSION:
                raise ValueError(
                    f"{path}: schema version {obj['version']} is newer "
                    f"than this reader ({_spans.SCHEMA_VERSION})"
                )
            continue
        records.append(obj)
    return first, records


#: host span names that are device DISPATCH SITES — the source ends of
#: the graftpath flow arrows into the device lane
_FLOW_DISPATCH_NAMES = frozenset({"pipeline.compute"})


def _json_attrs(attrs: dict) -> dict:
    return {k: (v if isinstance(v, (str, int, float, bool, type(None)))
                else repr(v))
            for k, v in attrs.items()}


def perfetto_trace(records=None, device=None) -> dict:
    """Build a Chrome ``trace_event`` dict from grafttrace records
    (default: everything retained in the span rings) plus a dedicated
    **device lane** (``tid 0``, thread-name ``"device"``): one ``X``
    slice per graftscope in-flight interval (default: the retained
    :func:`~.scope.timeline`; pass ``device=[]`` to omit), so host
    parse/stage overlap and device occupancy read in ONE trace — idle
    gaps are literally the white space in that lane.

    Accepts either :class:`~.spans.SpanRecord` objects or the dict form
    (a JSONL read-back), so a trace can be re-rendered offline from the
    event log alone (the device lane is in-process state: an offline
    re-render passes its own interval dicts or ``[]``).
    """
    if records is None:
        records = _spans.span_records()
    if device is None:
        from . import scope as _scope

        device = _scope.timeline()
    dicts = [r if isinstance(r, dict) else r.as_dict() for r in records]
    if not dicts and not device:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    epoch = min([d["t0"] for d in dicts] + [iv["t0"] for iv in device])
    pid = os.getpid()
    tids: dict[str, int] = {}
    events = []
    for iv in device:
        args = {"open": True} if iv.get("open") else {}
        if "flops" in iv:
            # per-dispatch roofline attribution rides the slice: flops,
            # bytes, and — when the interval has real duration — the
            # achieved GFLOP/s a trace reader can eyeball against peaks
            args["flops"] = iv["flops"]
            args["bytes"] = iv["bytes"]
            dur_s = iv["t1"] - iv["t0"]
            if dur_s > 0:
                args["gflops_per_s"] = round(iv["flops"] / dur_s / 1e9, 3)
        events.append({
            "name": iv["program"], "pid": pid, "tid": 0,
            "ts": round((iv["t0"] - epoch) * 1e6, 3),
            "dur": round((iv["t1"] - iv["t0"]) * 1e6, 3),
            "ph": "X",
            "args": args,
        })
    for d in dicts:
        tid = tids.setdefault(d["thread"], len(tids) + 1)
        args = _json_attrs(d.get("attrs", {}))
        if d.get("error"):
            args["error"] = d["error"]
        common = {
            "name": d["name"], "pid": pid, "tid": tid,
            "ts": round((d["t0"] - epoch) * 1e6, 3), "args": args,
        }
        if d["kind"] == "event":
            events.append({**common, "ph": "i", "s": "t"})
        else:
            events.append({
                **common, "ph": "X",
                "dur": round((d["t1"] - d["t0"]) * 1e6, 3),
            })
    # graftpath flow events (design.md §19): bind each device-lane slice
    # to the host span that was driving the device when it was enqueued
    # — the dispatch-site spans (``pipeline.compute``) whose window
    # contains the interval's enqueue moment.  Perfetto renders the
    # pair as an arrow from the host lane into the device lane, so the
    # causal chain host-step → device-program is visible in the trace,
    # not just inferable from vertical alignment.  Ambiguity resolves
    # to the SMALLEST containing span (the innermost dispatch scope);
    # an interval no dispatch span contains (serve-plane dispatches, a
    # sanitizer-hook track from an unspanned thread) gets no arrow.
    dispatch_spans = sorted(
        ((d["t0"], d["t1"], tids[d["thread"]]) for d in dicts
         if d["kind"] != "event" and d["name"] in _FLOW_DISPATCH_NAMES),
        key=lambda s: s[1] - s[0])
    flow_id = 0
    flows = []
    for iv in device:
        host = next(((t0, t1, tid) for t0, t1, tid in dispatch_spans
                     if t0 <= iv["t0"] <= t1), None)
        if host is None:
            continue
        flow_id += 1
        ts = round((iv["t0"] - epoch) * 1e6, 3)
        common = {"name": "graftpath", "cat": "graftpath",
                  "pid": pid, "id": flow_id}
        flows.append({**common, "ph": "s", "tid": host[2], "ts": ts})
        flows.append({**common, "ph": "f", "bp": "e", "tid": 0,
                      "ts": ts})
    meta = [
        {"ph": "M", "pid": pid, "tid": tid, "name": "thread_name",
         "args": {"name": thread}}
        for thread, tid in tids.items()
    ]
    if device:
        meta.insert(0, {"ph": "M", "pid": pid, "tid": 0,
                        "name": "thread_name", "args": {"name": "device"}})
    return {"traceEvents": meta + events + flows,
            "displayTimeUnit": "ms"}


def export_perfetto(path: str | None = None, records=None,
                    device=None) -> dict:
    """:func:`perfetto_trace`, optionally written to ``path`` as JSON.
    Returns the trace dict either way."""
    trace = perfetto_trace(records, device=device)
    if path:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(trace, f, separators=(",", ":"))
    return trace

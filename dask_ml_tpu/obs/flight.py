"""Flight recorder: the always-on post-mortem ring.

A hung, preempted, or crashed fit used to leave a bare stack dump; this
process-global bounded ring retains the last N **significant** events —
faults, retries, checkpoint saves, preemption notices, sanitizer
violations, stream boundaries — regardless of whether tracing is
enabled, so the conftest watchdog, :func:`~dask_ml_tpu.resilience.
preemption.check_preemption`, and any unhandled-fault handler can dump
"what was happening, in order, just before this" instead of frames
alone.

Appends are one ``deque.append`` of a small tuple (thread-safe in
CPython, no lock); the dump path takes its snapshot via ``list(ring)``.
Wall-clock timestamps (``time.time``) are recorded alongside the
monotonic ones so a post-mortem correlates with external logs.
"""

from __future__ import annotations

import collections
import threading
import time

__all__ = [
    "FLIGHT_SIZE",
    "record",
    "tail",
    "clear",
    "post_mortem",
    "dump",
]

#: retained event count — sized for "the last few rounds of a fit", not
#: a full trace (that is what the span rings / JSONL sink are for)
FLIGHT_SIZE = 256

_RING: collections.deque = collections.deque(maxlen=FLIGHT_SIZE)


def record(kind: str, name: str, attrs: dict | None = None) -> None:
    """Append one event.  Cheap enough for fault paths inside retry
    loops; NOT meant for per-row hot loops."""
    _RING.append((
        time.time(), time.perf_counter(), kind, name,
        threading.current_thread().name, dict(attrs) if attrs else {},
    ))


def tail(n: int | None = None) -> list[dict]:
    """The most recent ``n`` events (default: all retained), oldest
    first, as dicts."""
    items = list(_RING)
    if n is not None:
        items = items[-n:]
    return [
        {"time": ts, "t": tp, "kind": kind, "name": name,
         "thread": thread, "attrs": attrs}
        for ts, tp, kind, name, thread, attrs in items
    ]


def clear() -> None:
    _RING.clear()


def post_mortem(reason: str = "", n: int = FLIGHT_SIZE) -> str:
    """Formatted dump text: the flight tail plus every thread's
    currently-open span path (which block/round was in flight), ready
    for stderr or a logger."""
    from . import spans as _spans

    lines = [f"=== grafttrace flight recorder"
             + (f" ({reason})" if reason else "") + " ==="]
    open_paths = _spans.open_span_paths()
    if open_paths:
        lines.append("open spans:")
        for thread, path in sorted(open_paths.items()):
            lines.append(f"  {thread}: {path}")
    else:
        lines.append("open spans: (none)")
    # OPEN graftscope intervals ride the dump too: a hang during a long
    # device program used to show only host spans — no device context —
    # so a watchdog/preemption post-mortem could not tell "wedged
    # program" from "starved host".  Read-only (no sweep, no poll);
    # guarded like the rest of the forensic path.
    try:
        from . import scope as _scope

        open_ivs = _scope.open_intervals()
    except Exception:  # pragma: no cover - forensic path must not throw
        open_ivs = []
    if open_ivs:
        lines.append("open device intervals:")
        for iv in open_ivs:
            lines.append(f"  {iv['program']}: in flight "
                         f"{iv['age_s']:.3f}s")
    else:
        lines.append("open device intervals: (none)")
    events = tail(n)
    lines.append(f"last {len(events)} events:")
    for e in events:
        stamp = time.strftime("%H:%M:%S", time.localtime(e["time"]))
        attrs = (" " + " ".join(f"{k}={v!r}" for k, v in
                                sorted(e["attrs"].items()))
                 if e["attrs"] else "")
        lines.append(
            f"  {stamp} [{e['thread']}] {e['kind']}:{e['name']}{attrs}"
        )
    if not events:
        lines.append("  (empty)")
    return "\n".join(lines)


def dump(reason: str = "", file=None, n: int = 64) -> None:
    """Print :func:`post_mortem` (default: stderr).  Never raises — this
    runs on watchdog/preemption/fault paths where a secondary failure
    must not mask the primary one."""
    import sys

    try:
        print(post_mortem(reason, n=n),
              file=file if file is not None else sys.stderr, flush=True)
    except Exception:  # pragma: no cover - forensic path must not throw
        pass

"""The metrics registry: counters, gauges, and HDR-style histograms.

One process-global registry is the numeric spine every layer reports
through (docs/design.md §11): the input pipeline publishes its stage
split per stream, the resilience layer its fault/retry/failure counts
per tag, graftsan its compile/dispatch/d2h counters, checkpoints their
save counts.  The pre-existing reporters (``pipeline_report()``,
``fault_stats()``, ``sanitize_report()``) keep their shapes as VIEWS
over (or alongside) this registry, so nothing downstream breaks while
new consumers — ``diagnostics.run_report()``, the bench per-workload
``obs`` block, the future serving plane's latency SLOs — read one
coherent store.

Instruments are cheap and thread-safe: a counter increment is one lock
plus one integer add; a histogram record is one lock, one ``math.log``
and one dict add.  Histograms are HDR-style **log-bucketed** (growth
factor 2^(1/4), ~19% relative resolution per bucket) so p50/p95/p99
over microseconds-to-minutes latencies cost O(buckets touched) memory
with no stored samples, exactly the shape a long-running serving
process needs.  Everything here is pure host stdlib — no jax, no
numpy — so instruments are legal anywhere, including the prefetch
worker thread (stage-purity/thread-dispatch provably host-only).

Naming contract (enforced by convention, documented in design.md §11):
``<layer>.<what>[_<unit>]`` — ``pipeline.stall_s``, ``resilience.retry``,
``compile.count``, ``checkpoint.save``.  Tags (one optional label per
instrument) separate books within a name: ``resilience.retry`` is
tagged by the retry site's tag, mirroring ``FaultStats``.
"""

from __future__ import annotations

import math
import threading

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "registry",
    "metrics_snapshot",
    "reset_metrics",
]

#: histogram bucket growth factor: 2^(1/4) ≈ 1.189 (~19% relative error,
#: 4 buckets per octave — 150 buckets span 1 µs .. 10 min)
_GROWTH = 2.0 ** 0.25
_LOG_GROWTH = math.log(_GROWTH)
#: smallest distinguishable value; anything at or below lands in bucket 0
_FLOOR = 1e-9


class Counter:
    """Monotone integer counter."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        return self._value


class Gauge:
    """Last-write-wins float (queue depth, ring occupancy, ...)."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    @property
    def value(self) -> float:
        return self._value


class Histogram:
    """Log-bucketed value distribution with quantile estimation.

    ``record(v)`` files ``v`` into bucket ``floor(log(v/1e-9) /
    log(2^0.25))`` (sparse dict); quantiles walk the sorted buckets and
    return each bucket's geometric midpoint, so a reported p99 is within
    ~19% of the true p99 — HDR semantics without storing samples.
    Exact ``count``/``sum``/``min``/``max`` ride alongside.
    """

    __slots__ = ("_lock", "_buckets", "count", "sum", "min", "max")

    def __init__(self):
        self._lock = threading.Lock()
        self._buckets: dict[int, int] = {}
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf

    def record(self, v: float) -> None:
        v = float(v)
        if v <= _FLOOR:
            idx = 0
        else:
            idx = int(math.log(v / _FLOOR) / _LOG_GROWTH) + 1
        with self._lock:
            self._buckets[idx] = self._buckets.get(idx, 0) + 1
            self.count += 1
            self.sum += v
            if v < self.min:
                self.min = v
            if v > self.max:
                self.max = v

    def quantile(self, q: float) -> float:
        """Estimated ``q``-quantile (0 <= q <= 1); NaN when empty."""
        with self._lock:
            return self._quantile_locked(q)

    def _quantile_locked(self, q: float) -> float:
        if not self.count:
            return math.nan
        # nearest-rank: p99 of 5 samples is the max, not the 4th —
        # the convention an SLO reader expects from small samples
        rank = q * self.count
        seen = 0
        for idx in sorted(self._buckets):
            seen += self._buckets[idx]
            if seen >= rank:
                if idx == 0:
                    return 0.0
                # geometric midpoint of the bucket, clamped to the
                # exact observed range so a 1-sample histogram
                # reports its sample, not a bucket boundary
                mid = _FLOOR * _GROWTH ** (idx - 0.5)
                return min(max(mid, self.min), self.max)
        return self.max  # pragma: no cover - rank < count always hits

    def snapshot(self) -> dict:
        # one acquisition across every field read: releasing after the
        # empty-check and reading count/sum/min/max bare let a
        # concurrent record() interleave mid-update and produce a torn
        # snapshot (count bumped, sum not yet)
        with self._lock:
            if not self.count:
                return {"count": 0}
            return {
                "count": self.count,
                "sum": round(self.sum, 9),
                "min": round(self.min, 9),
                "max": round(self.max, 9),
                "p50": round(self._quantile_locked(0.50), 9),
                "p95": round(self._quantile_locked(0.95), 9),
                "p99": round(self._quantile_locked(0.99), 9),
            }


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class MetricsRegistry:
    """Name+tag keyed instrument store.

    ``counter("resilience.retry", "ingest")`` returns the one counter
    for that (name, tag) pair, creating it on first use — callers keep
    no handles they must coordinate.  A name must keep one instrument
    kind (asking for a histogram under an existing counter name raises:
    silent kind drift would corrupt every reader).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._instruments: dict[tuple[str, str], object] = {}
        self._kinds: dict[str, str] = {}

    def _get(self, kind: str, name: str, tag: str | None):
        key = (name, tag or "")
        inst = self._instruments.get(key)
        if inst is not None:
            if type(inst) is not _KINDS[kind]:
                raise ValueError(
                    f"metric {name!r} is a {self._kinds.get(name)}, "
                    f"not a {kind}"
                )
            return inst
        with self._lock:
            inst = self._instruments.get(key)
            if inst is None:
                prev = self._kinds.get(name)
                if prev is not None and prev != kind:
                    raise ValueError(
                        f"metric {name!r} is a {prev}, not a {kind}"
                    )
                self._kinds[name] = kind
                inst = self._instruments[key] = _KINDS[kind]()
            return inst

    def counter(self, name: str, tag: str | None = None) -> Counter:
        return self._get("counter", name, tag)

    def gauge(self, name: str, tag: str | None = None) -> Gauge:
        return self._get("gauge", name, tag)

    def histogram(self, name: str, tag: str | None = None) -> Histogram:
        return self._get("histogram", name, tag)

    def family(self, name: str) -> dict:
        """All tags of one counter/gauge name → ``{tag: value}`` (the
        ``FaultStats`` per-tag view); empty dict when the name is
        unknown."""
        with self._lock:
            items = [
                (k[1], inst) for k, inst in self._instruments.items()
                if k[0] == name
            ]
        return {tag: inst.value for tag, inst in items
                if isinstance(inst, (Counter, Gauge))}

    def export_items(self) -> list:
        """``[(name, tag, instrument)]`` sorted by (name, tag) — the
        structured walk the Prometheus exporter (:mod:`.serve`) formats
        from.  Unlike :meth:`snapshot`'s ``name{tag}`` composite keys,
        tags stay separate so label values can be escaped correctly
        (a tag may itself contain braces, quotes, or newlines)."""
        with self._lock:
            return [(name, tag, inst) for (name, tag), inst
                    in sorted(self._instruments.items())]

    def snapshot(self) -> dict:
        """``{"counters": {key: n}, "gauges": {...}, "histograms":
        {key: {count, sum, min, max, p50, p95, p99}}}`` where ``key`` is
        ``name`` or ``name{tag}``."""
        with self._lock:
            items = sorted(self._instruments.items())
        out: dict = {"counters": {}, "gauges": {}, "histograms": {}}
        for (name, tag), inst in items:
            key = f"{name}{{{tag}}}" if tag else name
            if isinstance(inst, Counter):
                out["counters"][key] = inst.value
            elif isinstance(inst, Gauge):
                out["gauges"][key] = inst.value
            else:
                out["histograms"][key] = inst.snapshot()
        return out

    def reset(self, prefix: str | None = None) -> None:
        """Drop instruments (all, or those whose name starts with
        ``prefix``).  Handles cached by callers go stale by design —
        in-repo publishers re-fetch by name per observation."""
        with self._lock:
            if prefix is None:
                self._instruments.clear()
                self._kinds.clear()
                return
            for key in [k for k in self._instruments
                        if k[0].startswith(prefix)]:
                del self._instruments[key]
            for name in [n for n in self._kinds if n.startswith(prefix)]:
                del self._kinds[name]


_REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
    """The process-global registry every in-repo publisher reports to."""
    return _REGISTRY


def metrics_snapshot() -> dict:
    return _REGISTRY.snapshot()


def reset_metrics(prefix: str | None = None) -> None:
    _REGISTRY.reset(prefix)

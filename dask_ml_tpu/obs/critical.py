"""graftpath: the causal critical-path engine (docs/design.md §19).

Every plane already reports *time* — grafttrace spans (host), graftscope
in-flight intervals (device), the registry's wait histograms — but
nothing joins them causally: on a saturated gate box every A/B reads as
a meaningless wall ratio and the bottleneck is argued in prose.  This
module turns the existing substrate into a **verdict**: for one fit /
search / serve window, an exhaustive category attribution of the wall
clock plus the bottleneck class the evidence supports.

Causal model
------------
The observation window is a completed ROOT span (a ``pipeline.stream``
fit, a ``search.fit``).  Every retained record that overlaps the window
— regardless of tree membership, so rootless reader-thread records and
detached async-unit records join the same timeline — is clipped to it
and bucketed into one of seven categories by a **priority layering**:
each instant of the window is attributed to exactly ONE category, the
most causally specific signal that covers it:

1. ``device``     — graftscope in-flight intervals (enqueue→ready; the
                    device is busy-or-fed, so host work underneath is
                    *hidden*, off the critical path);
2. ``parse``      — the reader threads' ``data.parse``
                    (pread+decompress+decode, recorded per block): the
                    concurrent ground truth of who was working, so 4
                    readers on 1 core show up as parse pressure, not
                    mystery waiting — this layer claims its time
                    BEFORE the wait layer, because the worker's wait
                    below is *caused* by this work;
3. ``fetch``      — ``data.fetch`` (remote-store / emulated block
                    fetch RTT) and any future ``*.fetch`` span;
4. ``queue_wait`` — specific wait signals: the data plane's
                    reorder-merge wait (``data.queue_wait``), the
                    search scheduler's throttle park
                    (``search.queue_wait``);
5. *(parse again)* — ``pipeline.parse``: the staging worker's source
                    pull, net of the reader work and waits it wraps;
6. ``stage``      — ``pipeline.stage`` (bucket-pad + H2D put);
7. *(queue_wait again)* — ``pipeline.stall``: the consumer's staged-
                    queue starvation NOT explained by any concurrent
                    producer work above (a stall covered by a worker's
                    parse attributes to parse — the cause — and only
                    the unexplained remainder lands here);
8. ``dispatch``   — ``pipeline.compute`` net of the device time inside
                    it (the host cost of driving a step), plus every
                    other non-container host span (``search.unit``
                    bodies: scoring, cohort packing, control flow);
9. ``idle_gap``   — the unattributed remainder.

The categories therefore sum to the wall EXACTLY by construction — on
the span plane the tolerance check is an invariant guard (it can only
fire if a future change breaks the constructive partition) — while the
documented tolerance (``DASK_ML_TPU_CRITICAL_TOL``) is LIVE on the
joins that are not constructive: the serve plane's per-request
identity (queue+window+device+fetch vs ``request_s``).  A window whose
``idle_gap`` exceeds 50% of the wall refuses to name a bottleneck
(verdict ``unknown``: honesty over invention).

Verdict rules
-------------
The bottleneck class is the largest non-idle category::

    device → device-bound      parse → parse-bound
    stage  → stage-bound       queue_wait → queue-bound
    dispatch → dispatcher-bound  fetch → fetch-bound

with the winning share reported as ``confidence`` and the evidence
chain (per-category seconds, the top spans of the winning category,
device occupancy over the window) attached — the verdict is never a
bare string.  ``overlap_efficiency`` = hidden host time / host time:
the fraction of host LANE time (parse/stage/fetch, one lane per
producing thread *name* — concurrent same-named workers, e.g. the
four ``dask-ml-tpu-data-reader`` threads, merge into one lane, which
keeps the number a structural property rather than one that scales
with the worker count) that ran CONCURRENTLY with consumption work on
a *different* lane (1.0 = the pipeline hides everything it stages;
0.0 = strictly serial — a depth-0 stream measures ~0 by construction,
because its parse, stage, and compute share one lane).  Hiding is
judged against the host-side dispatch-scope spans, not the device
intervals, whose end-detection slack on a GIL-starved box would
fabricate overlap where none exists.  The perf ratchet (:mod:`.perf`, v3) floors it per
workload and pins the bottleneck class, so a pipeline that silently
stops overlapping fails the gate even when p50 stays inside its band.

Everything here is pure host stdlib (no jax, no numpy) — legal on any
thread, same posture as the rest of :mod:`dask_ml_tpu.obs`.
"""

from __future__ import annotations

import os
import threading

from .._locks import make_lock

from .metrics import registry as _registry
from . import scope as _scope
from . import spans as _spans

__all__ = [
    "CRITICAL_TOL_ENV",
    "CRITICAL_DOMINANCE_ENV",
    "BOTTLENECK_CLASSES",
    "CATEGORIES",
    "resolve_tolerance",
    "resolve_dominance",
    "critical_path",
    "serve_critical",
    "last_verdicts",
    "reset",
]

#: policy knob: sum-to-wall / serve-identity tolerance as a fraction of
#: the wall (default 0.05).  Strict parse; the verdict degrades to
#: ``unknown`` when a non-constructive join misses the tolerance.
CRITICAL_TOL_ENV = "DASK_ML_TPU_CRITICAL_TOL"

#: policy knob: the share the winning category needs for a CONFIDENT
#: verdict (default 0.35) — below it the verdict still names the
#: largest category but ``confident`` is False and the perf ratchet's
#: bottleneck pin does not bite (a 32/30/28 split is not a bottleneck).
CRITICAL_DOMINANCE_ENV = "DASK_ML_TPU_CRITICAL_DOMINANCE"

_DEFAULT_TOL = 0.05
_DEFAULT_DOMINANCE = 0.35

#: the attribution taxonomy, in report order
CATEGORIES = ("parse", "stage", "queue_wait", "dispatch", "device",
              "fetch", "idle_gap")

#: verdict classes, index == the ``critical.bottleneck`` gauge value on
#: ``/metrics`` (a Prometheus label cannot carry the class name as a
#: value, so the gauge speaks this enum; the tag names the plane)
BOTTLENECK_CLASSES = (
    "unknown",           # 0
    "device-bound",      # 1
    "parse-bound",       # 2
    "stage-bound",       # 3
    "dispatcher-bound",  # 4
    "queue-bound",       # 5
    "fetch-bound",       # 6
)

_CLASS_OF = {
    "device": "device-bound",
    "parse": "parse-bound",
    "stage": "stage-bound",
    "dispatch": "dispatcher-bound",
    "queue_wait": "queue-bound",
    "fetch": "fetch-bound",
}

#: span names that are pure CONTAINERS (they cover their children's
#: whole lifetime including idle): excluded from the dispatch catch-all
#: so control-plane scaffolding cannot masquerade as host work
_CONTAINER_NAMES = frozenset({
    "pipeline.stream", "search.fit", "search.round", "search.bracket",
})

#: name → category SOURCE for the specific (non-catch-all) layers.
#: ``data.parse`` is split from ``pipeline.parse`` because the two
#: nest causally: the worker's ``pipeline.parse`` span wraps a source
#: pull that may be a reorder-queue WAIT, while the readers'
#: ``data.parse`` spans are the concurrent ground truth of who was
#: actually working — the reader layer must claim its time before the
#: wait layer does, and the wait layer before the worker's wrapper.
_SPECIFIC = {
    "data.parse": "parse_src",
    "data.fetch": "fetch",
    "data.queue_wait": "queue_wait_src",
    "search.queue_wait": "queue_wait_src",
    "pipeline.parse": "parse",
    "pipeline.stage": "stage",
    "pipeline.stall": "stall",
}

_LOCK = make_lock("obs.critical")
_LAST: dict[str, dict] = {}  # plane -> last computed verdict block


def _resolve_fraction(env: str, default: float, what: str,
                      value=None) -> float:
    if value is None:
        raw = os.environ.get(env, "").strip()
        if not raw:
            return default
        try:
            value = float(raw)
        except ValueError:
            raise ValueError(
                f"{env} must be a number, got {raw!r}") from None
    value = float(value)
    if not 0.0 < value < 1.0:
        raise ValueError(f"{what} must be in (0, 1), got {value}")
    return value


def resolve_tolerance(tol: float | None = None) -> float:
    """Sum-to-wall tolerance fraction: explicit, else the
    ``DASK_ML_TPU_CRITICAL_TOL`` knob, else 0.05.  Strict parse."""
    return _resolve_fraction(CRITICAL_TOL_ENV, _DEFAULT_TOL,
                             "critical tolerance", tol)


def resolve_dominance(dom: float | None = None) -> float:
    """Confident-verdict share: explicit, else the
    ``DASK_ML_TPU_CRITICAL_DOMINANCE`` knob, else 0.35."""
    return _resolve_fraction(CRITICAL_DOMINANCE_ENV, _DEFAULT_DOMINANCE,
                             "dominance threshold", dom)


# -- interval algebra (disjoint sorted [a, b] lists) ---------------------

def _union(intervals):
    """Sorted disjoint union of (a, b) pairs."""
    ivs = sorted((a, b) for a, b in intervals if b > a)
    out: list[list[float]] = []
    for a, b in ivs:
        if out and a <= out[-1][1]:
            if b > out[-1][1]:
                out[-1][1] = b
        else:
            out.append([a, b])
    return [(a, b) for a, b in out]


def _length(ivs) -> float:
    return sum(b - a for a, b in ivs)


def _overlap(xs, ys) -> float:
    """Total overlap length between two disjoint sorted lists."""
    i = j = 0
    total = 0.0
    while i < len(xs) and j < len(ys):
        a = max(xs[i][0], ys[j][0])
        b = min(xs[i][1], ys[j][1])
        if b > a:
            total += b - a
        if xs[i][1] <= ys[j][1]:
            i += 1
        else:
            j += 1
    return total


def _clip(t0: float, t1: float, lo: float, hi: float):
    a, b = max(t0, lo), min(t1, hi)
    return (a, b) if b > a else None


def _hist_sum(name: str) -> float:
    """Summed histogram value across tags WITHOUT creating the
    instrument (``registry().histogram(name)`` would seed an empty
    family on a read — the family() posture, applied to histograms)."""
    return sum(getattr(inst, "sum", 0.0)
               for n, _tag, inst in _registry().export_items()
               if n == name)


# -- the per-window engine ----------------------------------------------

def _verdict_block(shares: dict, dominance: float,
                   idle_frac: float) -> dict:
    candidates = {k: v for k, v in shares.items() if k != "idle_gap"}
    top = max(candidates, key=candidates.get) if candidates else None
    if top is None or candidates[top] <= 0.0 or idle_frac > 0.5:
        return {"class": "unknown", "confidence": 0.0,
                "confident": False,
                "reason": ("idle_gap dominates the window"
                           if idle_frac > 0.5 else "no attributed time")}
    return {"class": _CLASS_OF[top],
            "confidence": round(candidates[top], 4),
            "confident": candidates[top] >= dominance}


def _publish(plane: str, result: dict) -> None:
    """Land the verdict on the scrape surface: one gauge pair per plane
    (class as the documented enum index, overlap efficiency as-is) and
    the module's last-verdict join for ``device_report()``."""
    reg = _registry()
    cls = result.get("verdict", {}).get("class", "unknown")
    reg.gauge("critical.bottleneck", plane).set(
        float(BOTTLENECK_CLASSES.index(cls)))
    oe = result.get("overlap_efficiency")
    if oe is not None:
        reg.gauge("critical.overlap_efficiency", plane).set(float(oe))
    with _LOCK:
        _LAST[plane] = {
            "verdict": cls,
            "confidence": result.get("verdict", {}).get("confidence"),
            "overlap_efficiency": oe,
        }


def last_verdicts() -> dict:
    """``{plane: {verdict, confidence, overlap_efficiency}}`` of the
    most recent :func:`critical_path` / :func:`serve_critical` calls —
    the lightweight join ``device_report()`` attaches (occupancy and
    its interpretation belong on one page)."""
    with _LOCK:
        return {k: dict(v) for k, v in _LAST.items()}


def reset() -> None:
    """Drop the last-verdict join (test/bench isolation; the gauges are
    cleared by the caller's registry reset)."""
    with _LOCK:
        _LAST.clear()


def _plane_of(root) -> str:
    name = getattr(root, "name", "") or ""
    if name.startswith("search."):
        return "search"
    if name.startswith("serve."):
        return "serve"
    return "fit"


def critical_path(root=None, *, records=None, device=None,
                  tolerance: float | None = None,
                  dominance: float | None = None,
                  publish: bool = True) -> dict:
    """Assemble the critical path of one completed root span (default:
    :func:`~.spans.last_root`) — see the module docstring for the
    causal model.  Returns::

        {"plane": "fit" | "search",
         "wall_s": w, "t0": ..., "t1": ...,
         "categories": {parse, stage, queue_wait, dispatch, device,
                        fetch, idle_gap},       # seconds, sum == wall
         "shares":     {... same keys ...},     # fractions of wall
         "coverage": attributed_fraction,       # 1 - idle share
         "tolerance": tol, "within_tolerance": bool,
         "overlap_efficiency": hidden_host/host or None,
         "host_s": ..., "hidden_host_s": ...,
         "device": {dispatches, busy_s, utilization},   # window-scoped
         "verdict": {"class", "confidence", "confident"},
         "evidence": {top spans of the winning category, wait books}}

    With no root (tracing disabled, nothing completed) the serve plane
    is tried (:func:`serve_critical`); failing that, an explicit
    ``{"plane": None, "verdict": {"class": "unknown"}}`` — the report
    never invents a story.
    """
    tol = resolve_tolerance(tolerance)
    dom = resolve_dominance(dominance)
    root = root if root is not None else _spans.last_root()
    if root is None:
        serve = serve_critical(tolerance=tol, dominance=dom,
                               publish=publish)
        if serve is not None:
            return serve
        return {"plane": None, "wall_s": 0.0, "categories": {},
                "shares": {}, "coverage": 0.0, "tolerance": tol,
                "within_tolerance": True, "overlap_efficiency": None,
                "verdict": {"class": "unknown", "confidence": 0.0,
                            "confident": False,
                            "reason": "no completed root span and no "
                                      "serve traffic"},
                "evidence": {}}
    lo, hi = float(root.t0), float(root.t1)
    wall = max(hi - lo, 1e-12)
    if records is None:
        records = _spans.span_records()
    if device is None:
        device = _scope.timeline(open_until=hi)

    # bucket clipped intervals by category source
    src: dict[str, list] = {k: [] for k in
                            ("device", "parse_src", "queue_wait_src",
                             "parse", "stage", "fetch", "stall",
                             "dispatch")}
    top_spans: dict[str, list] = {}
    for iv in device:
        c = _clip(iv["t0"], iv["t1"], lo, hi)
        if c is not None:
            src["device"].append(c)
            top_spans.setdefault("device", []).append(
                (c[1] - c[0], iv.get("program", "device"), {}))
    root_id = getattr(root, "span_id", None)
    host_by_thread: dict[str, list] = {}     # parse/stage/fetch work
    consume_by_thread: dict[str, list] = {}  # dispatch-scope spans
    for r in records:
        if getattr(r, "kind", "span") != "span":
            continue
        rid = getattr(r, "span_id", None)
        if rid is not None and rid == root_id:
            continue
        name = r.name
        cat = _SPECIFIC.get(name)
        if cat is None:
            if name in _CONTAINER_NAMES:
                continue
            cat = "dispatch"  # pipeline.compute + generic host work
        c = _clip(r.t0, r.t1, lo, hi)
        if c is None:
            continue
        thread = getattr(r, "thread", "") or ""
        if cat in ("parse_src", "parse", "stage", "fetch"):
            host_by_thread.setdefault(thread, []).append(c)
        elif cat == "dispatch":
            consume_by_thread.setdefault(thread, []).append(c)
        src[cat].append(c)
        # evidence: remember the biggest few raw spans per category
        key = ("queue_wait" if cat in ("queue_wait_src", "stall")
               else "parse" if cat == "parse_src" else cat)
        bucket = top_spans.setdefault(key, [])
        bucket.append((c[1] - c[0], name,
                       dict(getattr(r, "attrs", None) or {})))

    unions = {k: _union(v) for k, v in src.items()}

    # priority layering: most-specific-first disjoint attribution
    order = (("device", "device"),
             ("parse_src", "parse"),      # reader ground truth first
             ("fetch", "fetch"),
             ("queue_wait_src", "queue_wait"),
             ("parse", "parse"),          # worker wrapper residue
             ("stage", "stage"),
             ("stall", "queue_wait"),
             ("dispatch", "dispatch"))
    attributed: list = []
    cats = {k: 0.0 for k in CATEGORIES}
    for source, cat in order:
        u = unions[source]
        if not u:
            continue
        net = _length(u) - _overlap(u, attributed)
        cats[cat] += max(net, 0.0)
        attributed = _union(attributed + u)
    covered = _length(attributed)
    cats["idle_gap"] = max(wall - covered, 0.0)

    shares = {k: round(v / wall, 4) for k, v in cats.items()}
    # constructive partition: the only miss a tolerance can see here is
    # accumulated clipping/rounding — still checked, still reported
    total = sum(cats.values())
    within = abs(total - wall) <= tol * wall

    # overlap efficiency: host LANE time (parse/stage/fetch; one lane
    # per thread NAME — concurrent same-named workers merge, see the
    # module docstring) hidden under CONCURRENT consumption work on a
    # DIFFERENT lane (the dispatch-scope spans).  Deliberately NOT the
    # device intervals: their t1 carries detection slack (one sampler
    # period, worse on a GIL-starved 1-core box), and a slack-extended
    # interval lapping the NEXT block's parse would fabricate overlap
    # in a strictly serial depth-0 stream — the host-side concurrency
    # structure is the stable truth of whether the pipeline overlaps,
    # and it is exactly what a depth knob changes.
    host_s = 0.0
    hidden_s = 0.0
    for thread, ivs in host_by_thread.items():
        u = _union(ivs)
        host_s += _length(u)
        other = _union([iv for t, civs in consume_by_thread.items()
                        if t != thread for iv in civs])
        hidden_s += _overlap(u, other)
    overlap_eff = (round(hidden_s / host_s, 4) if host_s > 1e-9
                   else None)

    verdict = _verdict_block(shares, dom, shares["idle_gap"])
    if not within:
        verdict = {"class": "unknown", "confidence": 0.0,
                   "confident": False,
                   "reason": f"category sum {total:.6f}s misses wall "
                             f"{wall:.6f}s beyond tolerance {tol}"}

    win_cat = next((k for k, v in _CLASS_OF.items()
                    if v == verdict["class"]), None)
    evidence = {
        "wait_books": {
            "pipeline_stall_s": round(_length(unions["stall"]), 6),
            # session-cumulative registry sums (read-only scan: a
            # report must not seed instruments it only wants to read)
            "data_queue_wait_s": round(_hist_sum("data.queue_wait_s"), 6),
            "search_queue_wait_s": round(
                _hist_sum("search.queue_wait_s"), 6),
        },
        "n_records": sum(len(v) for v in src.values()),
    }
    if win_cat is not None:
        # sort on duration only: a (dur, name, attrs) tuple comparison
        # would fall through to dict.__lt__ on a tie and raise
        spans_list = sorted(top_spans.get(win_cat, []), reverse=True,
                            key=lambda t: t[0])[:3]
        evidence["top_spans"] = [
            {"name": n, "dur_s": round(d, 6), "attrs": a}
            for d, n, a in spans_list]

    dev_busy = _length(unions["device"])
    result = {
        "plane": _plane_of(root),
        "root": root.name,
        "wall_s": round(wall, 6),
        "t0": round(lo, 6),
        "t1": round(hi, 6),
        "categories": {k: round(v, 6) for k, v in cats.items()},
        "shares": shares,
        "coverage": round(covered / wall, 4),
        "tolerance": tol,
        "within_tolerance": within,
        "overlap_efficiency": overlap_eff,
        "host_s": round(host_s, 6),
        "hidden_host_s": round(hidden_s, 6),
        "device": {
            "dispatches": len(src["device"]),
            "busy_s": round(dev_busy, 6),
            "utilization": round(dev_busy / wall, 4),
        },
        "verdict": verdict,
        "evidence": evidence,
    }
    if publish:
        _publish(result["plane"], result)
    return result


# -- the serve plane -----------------------------------------------------

_SERVE_SEGMENTS = ("queue", "window", "device", "fetch")

#: serve segment → verdict class: the request path has no parse/stage,
#: so the taxonomy maps onto its four legs (window = the batcher's own
#: coalescing choice, i.e. the dispatcher's behavior)
_SERVE_CLASS = {"queue": "queue-bound", "window": "dispatcher-bound",
                "device": "device-bound", "fetch": "fetch-bound"}


def serve_critical(*, tolerance: float | None = None,
                   dominance: float | None = None,
                   publish: bool = True,
                   tag: str | None = None) -> dict | None:
    """The serve window's critical path, from the per-request split the
    runtime records (``serve.req_{queue,window,device,fetch}_s`` —
    four contiguous legs per request, stamped with the request's trace
    id through submit → coalesce → dispatch → fetch).  Aggregate form:
    total seconds per leg across the retained window, shares of total
    request time, the identity check ``queue+window+device+fetch ≈
    Σ request_s`` within the tolerance, and the verdict.  ``None`` when
    no split has been recorded (no serve traffic — the report must not
    invent an empty story).

    ``tag`` restricts the aggregation to one latency-histogram tag —
    normally a model name, or a replica tag (``r0``, ``r1``, ...) when
    the servers were built with ``metrics_tag`` (the fleet's
    per-replica bottleneck verdicts in ``bench.py``'s fleet section);
    ``None`` keeps the global all-tags sum."""
    tol = resolve_tolerance(tolerance)
    dom = resolve_dominance(dominance)
    reg = _registry()
    totals = {}
    count = 0
    for seg in _SERVE_SEGMENTS:
        s = 0.0
        for name, _tag, inst in reg.export_items():
            if name == f"serve.req_{seg}_s" and \
                    (tag is None or _tag == tag):
                s += inst.sum
                if seg == "queue":
                    count += inst.count
        totals[seg] = s
    if count == 0:
        return None
    request_s = sum(inst.sum for name, _tag, inst in reg.export_items()
                    if name == "serve.request_s"
                    and (tag is None or _tag == tag))
    total = sum(totals.values())
    denom = max(request_s, 1e-12)
    within = abs(total - request_s) <= tol * denom
    shares = {k: round(v / max(total, 1e-12), 4)
              for k, v in totals.items()}
    top = max(shares, key=shares.get)
    if within:
        verdict = {"class": _SERVE_CLASS[top],
                   "confidence": shares[top],
                   "confident": shares[top] >= dom}
    else:
        verdict = {"class": "unknown", "confidence": 0.0,
                   "confident": False,
                   "reason": f"split sum {total:.6f}s misses "
                             f"request_s {request_s:.6f}s beyond "
                             f"tolerance {tol}"}
    result = {
        "plane": "serve" if tag is None else f"serve:{tag}",
        "requests": count,
        "wall_s": round(request_s, 6),  # summed request seconds
        "categories": {k: round(v, 6) for k, v in totals.items()},
        "shares": shares,
        "coverage": round(total / denom, 4),
        "tolerance": tol,
        "within_tolerance": within,
        "overlap_efficiency": None,
        "verdict": verdict,
        "evidence": {
            "identity": f"queue+window+device+fetch = {total:.6f}s "
                        f"vs sum(request_s) = {request_s:.6f}s",
        },
    }
    if publish:
        # a tagged (per-replica / per-model) verdict publishes under
        # its own plane key so it never clobbers the global serve one
        _publish(result["plane"], result)
    return result

"""The one obs module that touches jax: compile-event publication.

``jax.monitoring`` emits ``/jax/core/compile/backend_compile_duration``
once per XLA backend compile (never on a cache hit) — the same signal
graftsan's compile detector attributes per-region.  This listener is the
UNGATED twin: it publishes ``compile.count`` / ``compile.duration_s``
into the metrics registry on every compile, sanitizer or not, so
``diagnostics.run_report()`` and the bench per-workload ``obs`` blocks
can trend compilation alongside throughput in any process.

Kept out of ``obs/__init__`` imports deliberately: the rest of the obs
package is pure stdlib (provably host-only for graftlint's
thread-dispatch/stage-purity reachability), and this module is imported
lazily by :func:`~.spans.enable` and by graftsan's hook installer.
``install()`` is idempotent and is the SINGLE registry publisher for
compile events — graftsan's own listener only does per-region
attribution, so double-installation can never double-count.
"""

from __future__ import annotations

import threading

from .._locks import make_lock

from . import metrics as _metrics

__all__ = ["install", "COMPILE_EVENT"]

#: jax.monitoring event key: one firing per XLA backend compile
COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_LOCK = make_lock("obs.jaxhooks")
_INSTALLED = False


def install() -> None:
    """Register the compile-event listener exactly once per process."""
    global _INSTALLED
    with _LOCK:
        if _INSTALLED:
            return
        import jax.monitoring as _mon

        def _on_event_duration(event: str, duration: float, **_kw) -> None:
            if event == COMPILE_EVENT:
                reg = _metrics.registry()
                reg.counter("compile.count").inc()
                reg.histogram("compile.duration_s").record(float(duration))

        _mon.register_event_duration_secs_listener(_on_event_duration)
        _INSTALLED = True

"""graftscope perf ratchet: the committed performance baseline.

The repo already ratchets *static findings* (graftlint), *runtime SPMD
counts* (graftsan), and *recovery behavior* (graftdrill).  This module
is the fourth committed baseline — **performance itself**: a small
suite of streamed-fit workloads whose per-block latency quantiles,
device utilization, and stall fraction are snapshotted into
``tools/perf_baseline.json`` and re-measured by ``tools/lint.sh
--perf`` (and tier-1 via tests/test_graftscope.py) with the same
new/stale/regression semantics as the other three:

* a workload in the run but not in the snapshot is **new** → fail;
* a snapshot entry not in the run is **stale** → fail (the committed
  file always matches the suite; refresh with ``tools/lint.sh
  --rebaseline``, which rewrites all FOUR baselines in one invocation);
* a measured metric outside its **tolerance band** of the snapshot is
  a regression → fail.  Bands, not exact times — the tier-1 box is a
  loaded 2-core sandbox and wall clocks flap; what the ratchet must
  catch is the *order-of-magnitude* class (a sleep smuggled into a
  step program, a pipeline that stopped overlapping, a device left
  idle), not scheduler jitter:

  - ``p50_block_s`` ceiling: ``base * 5 + 10 ms`` (the median is the
    robust one; an injected per-step sleep lands far above it);
  - ``p99_block_s`` ceiling: ``base * 8 + 50 ms`` (the tail IS noisy
    on a starved box — the wide band still catches real slowdowns);
  - ``utilization`` floor: ``base * 0.5`` (checked only when the
    committed value is ≥ 0.1 — a workload that never fed the device
    cannot floor anything);
  - ``stall_fraction`` ceiling: ``base * 3 + 0.20``.

* a workload that ERRORS (or whose block count drifted from the
  snapshot — the shapes are the calibration) is a hard failure.

v2 adds the ROOFLINE columns (ISSUE 12): each workload commits a
per-program table of device busy seconds, XLA-estimated flops/bytes
(captured at compile time by the program cache), and the roofline
fraction against ``obs.roofline``'s peak table — and the ratchet
floors each program's committed fraction (``ROOFLINE_FLOOR_FACTOR``),
so "utilization may not regress" becomes per-program, not just global.
A drifted program SET (a dispatch path silently changed) fails like a
block-count drift.

v3 adds the GRAFTPATH columns (ISSUE 15, :mod:`.critical`): each
workload commits its ``overlap_efficiency`` (hidden host time / host
time — the structural number a saturation-pinned wall ratio cannot
fake) and its ``bottleneck`` verdict (``{"class", "share"}``), and the
ratchet

* **floors overlap efficiency** (``OVERLAP_FLOOR_FACTOR`` × committed,
  checked when the committed value is ≥ ``OVERLAP_MIN_BASE``): a
  pipeline that silently stops overlapping fails the gate even when
  its p50 stays inside the latency band;
* **pins the bottleneck class**: a CONFIDENT flip — committed share
  and measured share both ≥ ``BOTTLENECK_PIN_SHARE`` with different
  classes — is a regression (a sleep smuggled into the step path flips
  a device-bound workload to dispatcher-bound long before any wall
  band notices on a fast box).  Unconfident wobble between near-equal
  categories deliberately does NOT pin — the gate box is loaded and a
  32/30 split is not a verdict.

Workloads are deliberately tiny-but-not-trivial: block shapes chosen
so the device step costs milliseconds (a measurable busy interval on
this image) and bucket-aligned (16384 = the ``auto`` ladder's 16k rung,
so the pad path is a no-op and the numbers measure the pipeline, not
padding).  Fixed seeds; warmup round first so the measured round is
compile-free.

CLI (exit contract mirrors graftlint/graftsan: 0 clean, 1 ratchet
failure, 2 crash/bad-args)::

    python -m dask_ml_tpu.obs.perf                      # run + ratchet
    python -m dask_ml_tpu.obs.perf --write-baseline tools/perf_baseline.json
    python -m dask_ml_tpu.obs.perf --workloads sgd_stream_d2
    python -m dask_ml_tpu.obs.perf --inject-slowdown 0.25   # must FAIL
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

__all__ = [
    "PERF_BASELINE_ENV",
    "WORKLOADS",
    "run_workload",
    "run_suite",
    "compare",
    "is_clean",
    "default_path",
    "emit",
    "load",
    "write",
    "main",
]

#: policy knob: path of the committed perf snapshot (default:
#: ``tools/perf_baseline.json`` next to a repo checkout).
PERF_BASELINE_ENV = "DASK_ML_TPU_PERF_BASELINE"

_VERSION = 3  # v3: graftpath columns (overlap_efficiency, bottleneck)
_SEED = 11
_BLOCKS = 10
_ROWS, _DIM = 16384, 32  # 16k = an `auto` bucket rung: no pad, no drift
_PARSE_S = 0.001

#: tolerance bands (factor, absolute-slack) / floors — see module
#: docstring for why each is shaped the way it is.
P50_BAND = (5.0, 0.010)
P99_BAND = (8.0, 0.050)
UTIL_FLOOR_FACTOR = 0.5
UTIL_MIN_BASE = 0.10
STALL_BAND = (3.0, 0.20)
#: per-program roofline-fraction floor: flops are an exact compile-time
#: constant, so all the flap lives in measured busy seconds — the floor
#: is wider (×0.25) than the utilization one, and only bites when the
#: committed fraction is big enough to floor at all.
ROOFLINE_FLOOR_FACTOR = 0.25
ROOFLINE_MIN_BASE = 1e-4
#: graftpath (v3) bands: overlap efficiency floors like utilization
#: (half the committed value, only when committed is real), and the
#: bottleneck class pins only on a CONFIDENT flip — both the committed
#: and the measured winning category at >= this share of the wall.
OVERLAP_FLOOR_FACTOR = 0.5
OVERLAP_MIN_BASE = 0.10
BOTTLENECK_PIN_SHARE = 0.5


def _program_roofline(dev: dict) -> dict:
    """The committed per-program roofline columns from one
    ``device_report``: busy seconds, XLA-estimated flops/bytes, and the
    roofline fraction (None when the program's dispatches carried no
    cost — e.g. a jitted-twin fallback)."""
    out = {}
    for name, p in sorted(dev.get("programs", {}).items()):
        out[name] = {
            "busy_s": p.get("busy_s", 0.0),
            "flops": p.get("flops"),
            "bytes": p.get("bytes"),
            "roofline_frac": p.get("roofline_frac"),
        }
    return out


# -- workloads -----------------------------------------------------------

def _class_blocks(offset: int, parse_s: float = _PARSE_S):
    import numpy as np

    rng = np.random.RandomState(_SEED + offset)
    X = rng.normal(size=(_ROWS, _DIM)).astype(np.float32)
    w = rng.normal(size=_DIM)
    y = (X @ w > 0).astype(np.int32)
    for _ in range(_BLOCKS):
        if parse_s:
            time.sleep(parse_s)
        yield X, y


def _row_blocks(offset: int, parse_s: float = _PARSE_S):
    import numpy as np

    rng = np.random.RandomState(_SEED + offset)
    X = rng.normal(size=(_ROWS, _DIM)).astype(np.float32)
    for _ in range(_BLOCKS):
        if parse_s:
            time.sleep(parse_s)
        yield X, None


def _inject(model, sleep_s: float):
    """Testing hook: a per-step sleep smuggled into the model's device
    step — the injected slowdown the acceptance criterion requires the
    ratchet to fail on.  Wraps BOTH dispatch surfaces (the staged
    ``_pf_consume`` and plain ``partial_fit``) so depth-0 and depth-2
    workloads slow identically."""
    if not sleep_s:
        return model
    if hasattr(model, "_pf_consume"):
        orig_consume = model._pf_consume

        def _slow_consume(staged):
            time.sleep(sleep_s)
            return orig_consume(staged)

        model._pf_consume = _slow_consume
    orig_pf = model.partial_fit

    def _slow_pf(*args, **kwargs):
        time.sleep(sleep_s)
        return orig_pf(*args, **kwargs)

    model.partial_fit = _slow_pf
    return model


def _graftpath_cols(cp: dict | None) -> dict:
    """The two committed v3 columns from one critical-path result
    (None → explicit nulls: an entry must say "no verdict", not omit
    the field and read as pre-v3)."""
    if not cp:
        return {"overlap_efficiency": None, "bottleneck": None}
    v = cp.get("verdict") or {}
    return {
        "overlap_efficiency": cp.get("overlap_efficiency"),
        "bottleneck": {"class": v.get("class", "unknown"),
                       "share": round(float(v.get("confidence") or 0.0),
                                      4)},
    }


def _run_streamed(make_model, blocks_fn, depth, *, fit_kwargs=None,
                  inject_s: float = 0.0) -> dict:
    """Warmup round (compiles) then a measured round of the SAME model
    over fresh same-shaped blocks; returns the committed metrics."""
    from .. import diagnostics
    from ..pipeline import stream_partial_fit
    from . import critical as _critical
    from . import scope as _scope
    from .metrics import registry as _registry

    model = _inject(make_model(), inject_s)
    stream_partial_fit(model, blocks_fn(offset=0), depth=depth,
                       fit_kwargs=fit_kwargs, label="perf_warmup")
    # scope the measured round: fresh pipeline/device books (the
    # suite owns its process the way the sanitize smoke suite does)
    diagnostics.reset_pipeline_stats()
    cur = _scope.cursor()
    stream_partial_fit(model, blocks_fn(offset=1), depth=depth,
                       fit_kwargs=fit_kwargs, label="perf_measured")
    hist = _registry().histogram("pipeline.block_s")
    rep = diagnostics.pipeline_report()
    dev = _scope.device_report(since=cur, settle_s=5.0)
    # graftpath verdict of the measured stream (the device report
    # above already settled, so the window's last interval is closed)
    cp = _critical.critical_path()
    wall = float(rep.get("wall_s", 0.0)) or 1e-9
    return {
        "blocks": int(rep.get("blocks", 0)),
        "p50_block_s": round(float(hist.quantile(0.50)), 6),
        "p99_block_s": round(float(hist.quantile(0.99)), 6),
        "utilization": float(dev["utilization"]),
        "stall_fraction": round(
            min(float(rep.get("stall_s", 0.0)) / wall, 1.0), 4),
        "wall_s": round(wall, 6),
        "device_busy_s": dev["busy_s"],
        "programs": _program_roofline(dev),
        **_graftpath_cols(cp),
    }


def _wl_sgd(depth, inject_s=0.0):
    import numpy as np

    from ..linear_model import SGDClassifier

    return _run_streamed(
        lambda: SGDClassifier(random_state=0), _class_blocks, depth,
        fit_kwargs={"classes": np.array([0, 1])}, inject_s=inject_s)


def _wl_mbk(depth, inject_s=0.0):
    from ..cluster import MiniBatchKMeans

    return _run_streamed(
        lambda: MiniBatchKMeans(n_clusters=8, random_state=0),
        _row_blocks, depth, inject_s=inject_s)


#: ingest_stall calibration: the sharded-dataset feed (4 readers over 4
#: zlib columnar shards) streaming the same 10 × 16384-row blocks as
#: the sgd workloads — committed stall ceiling + p50/p99 block latency
#: under the parallel feed (ISSUE 14).
_INGEST_READERS = 4
_INGEST_SHARDS = 4

_ingest_dir: list = []


def _ingest_dataset_dir() -> str:
    """Build (once per process, removed at exit) the perf dataset:
    ``_BLOCKS`` bucket-rung blocks of (16384, 32) float32 + int32
    targets, zlib-compressed, spread over 4 shards — so the measured
    round pays real pread + decompress + decode per block on the reader
    threads."""
    import atexit
    import shutil
    import tempfile

    import numpy as np

    from .. import data as _data

    if _ingest_dir:
        return _ingest_dir[0]
    d = tempfile.mkdtemp(prefix="graftperf-ds-")
    rng = np.random.RandomState(_SEED)
    w = rng.normal(size=_DIM)
    X = rng.normal(size=(_ROWS, _DIM)).astype(np.float32)
    y = (X @ w > 0).astype(np.int32)
    _data.write_dataset(
        d, np.tile(X, (_BLOCKS, 1)), np.tile(y, _BLOCKS),
        shards=_INGEST_SHARDS, block_rows=_ROWS, compression="zlib")
    _ingest_dir.append(d)
    atexit.register(shutil.rmtree, d, ignore_errors=True)
    return d


def _wl_ingest(inject_s=0.0):
    """The parallel-ingest SLO, CI-enforced: a depth-2 streamed SGD fit
    fed by the 4-reader sharded dataset.  Same committed metric shape
    as the sgd stream workloads — ``p50/p99_block_s`` per consumed
    block, ``stall_fraction`` the consumer's starve share (the number
    the parallel readers exist to hold down), ``utilization`` the
    device-busy share — so the ratchet catches a reader pool that
    stopped overlapping (stall ceiling) or a merge queue that went
    quadratic (latency bands)."""
    import numpy as np

    from .. import data as _data
    from ..linear_model import SGDClassifier

    dirp = _ingest_dataset_dir()

    def _blocks(offset):
        return _data.ShardedDataset(
            dirp, key=_SEED, readers=_INGEST_READERS,
            label="perf_ingest").iter_blocks(epoch=offset)

    return _run_streamed(
        lambda: SGDClassifier(random_state=0), _blocks, 2,
        fit_kwargs={"classes": np.array([0, 1])}, inject_s=inject_s)


#: serve_latency calibration: closed-loop request counts (the workload's
#: ``blocks`` = completed requests, so the shape-drift gate still bites)
_SERVE_1ROW = 100
_SERVE_16ROW = 20


def _wl_serve(inject_s=0.0):
    """The serving SLO, CI-enforced: closed-loop 1-row and 16-row
    requests against a fitted SGD model through a latency-first
    ``ModelServer`` (window 0).  For this workload a "block" is a
    REQUEST: ``p50/p99_block_s`` are end-to-end request latency
    quantiles (queue wait included — the client's number), and
    ``stall_fraction`` is the queue-wait share of the wall.  The
    injected slowdown rides the server's per-dispatch test hook, so
    ``--inject-slowdown`` fails this entry exactly like the streamed
    ones."""
    import numpy as np

    from ..linear_model import SGDClassifier
    from ..serve import ModelServer
    from . import scope as _scope
    from .metrics import registry as _registry

    rng = np.random.RandomState(_SEED)
    X = rng.normal(size=(1024, 16)).astype(np.float32)
    w = rng.normal(size=16)
    y = (X @ w > 0).astype(np.int32)
    model = SGDClassifier(random_state=0)
    model.partial_fit(X, y, classes=np.array([0, 1]))

    server = ModelServer(label="perf_serve", window_s=0.0)
    try:
        server.load("m", model)
        for _ in range(10):  # warmup round: programs + request path hot
            server.predict("m", X[:1])
        server._test_dispatch_delay_s = float(inject_s)
        _registry().reset(prefix="serve.request_s")
        _registry().reset(prefix="serve.queue_wait_s")
        _registry().reset(prefix="serve.req_")  # the graftpath split
        cur = _scope.cursor()
        t0 = time.perf_counter()
        for i in range(_SERVE_1ROW):
            server.predict("m", X[i % 64:i % 64 + 1])
        for i in range(_SERVE_16ROW):
            lo = (i * 16) % 512
            server.predict("m", X[lo:lo + 16])
        wall = time.perf_counter() - t0
        hist = _registry().histogram("serve.request_s", "m")
        qwait = _registry().histogram("serve.queue_wait_s", "m")
        dev = _scope.device_report(since=cur, settle_s=5.0)
        from . import critical as _critical

        # the serve plane's verdict comes from the per-request split
        # the measured window recorded (queue/window/device/fetch);
        # overlap efficiency is a pipeline number and stays null here
        sc = _critical.serve_critical()
        return {
            "blocks": _SERVE_1ROW + _SERVE_16ROW,
            "p50_block_s": round(float(hist.quantile(0.50)), 6),
            "p99_block_s": round(float(hist.quantile(0.99)), 6),
            "utilization": float(dev["utilization"]),
            "stall_fraction": round(
                min(float(qwait.sum) / max(wall, 1e-9), 1.0), 4),
            "wall_s": round(wall, 6),
            "device_busy_s": dev["busy_s"],
            "programs": _program_roofline(dev),
            **_graftpath_cols(sc),
        }
    finally:
        server.close()


#: search_util calibration: the adaptive policy's round count is exact
#: (no patience, fixed budget), so ``blocks`` = rounds and the
#: shape-drift gate still bites.
_SEARCH_MODELS = 4
_SEARCH_MAX_ITER = 12


def _wl_search(inject_s=0.0):
    """The concurrent-search utilization floor + round latency, CI-
    enforced (ISSUE 13): a small incremental search over heterogeneous
    SGD configs (distinct (loss, penalty) statics — deliberately
    NON-packable, so every round multiplexes real independent units)
    runs on the orchestrator plane, and the committed entry floors
    ``device_report`` utilization over the search window and bands the
    ``search.round_s`` p50/p99.  For this workload a "block" is a
    ROUND; ``stall_fraction`` is the scheduler's throttle share of the
    wall (``search.queue_wait_s`` — queue wait, FED per the honesty
    contract, but still the number to watch trend).  The injected
    slowdown rides the models' ``_pf_consume``, so ``--inject-slowdown``
    fails this entry exactly like the streamed ones."""
    import numpy as np

    from ..linear_model import SGDClassifier
    from ..model_selection import IncrementalSearchCV
    from . import scope as _scope
    from .metrics import registry as _registry

    class _PerfSGD(SGDClassifier):
        _inject_s = 0.0

        def _pf_consume(self, staged):
            if type(self)._inject_s:
                time.sleep(type(self)._inject_s)
            return super()._pf_consume(staged)

    _PerfSGD._inject_s = float(inject_s)
    rng = np.random.RandomState(_SEED)
    n, d = 16384, _DIM  # train split blocks pad to the 4k `auto` rung
    X = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=d)
    y = (X @ w > 0).astype(np.int32)
    grid = {
        "loss": ["log_loss", "hinge", "squared_hinge", "modified_huber"],
        "penalty": ["l2", "l1", "elasticnet", "l2"],
    }
    params = [{"loss": grid["loss"][i], "penalty": grid["penalty"][i]}
              for i in range(_SEARCH_MODELS)]

    def _search():
        return IncrementalSearchCV(
            _PerfSGD(random_state=0), {"dummy": [0]},
            n_initial_parameters=_SEARCH_MODELS,
            max_iter=_SEARCH_MAX_ITER, random_state=0, test_size=0.25,
            chunk_size=4096,
        )

    # parameter list injected directly (ParameterSampler cannot express
    # "these exact four configs"): override the sampling hook
    def _fit_once():
        s = _search()
        s._get_params = lambda: [dict(p) for p in params]
        s.fit(X, y, classes=np.array([0, 1]))
        return s

    _fit_once()  # warmup round: all four step/score programs compile
    _registry().reset(prefix="search.")
    cur = _scope.cursor()
    t0 = time.perf_counter()
    _fit_once()
    wall = time.perf_counter() - t0
    hist = _registry().histogram("search.round_s")
    qwait = _registry().histogram("search.queue_wait_s")
    dev = _scope.device_report(since=cur, settle_s=5.0)
    from . import critical as _critical

    cp = _critical.critical_path()  # root: the measured search.fit
    # pin the committed table to CACHED programs only: the search's
    # scoring path runs plain-jit ops that graftscope only sees when
    # graftsan's ExecuteReplicated hook happens to be installed (e.g.
    # after the sanitize suite ran in this process) — a program set
    # that depends on process history would flap the drift gate
    programs = {name: p for name, p in _program_roofline(dev).items()
                if not name.startswith("jit(")}
    return {
        "blocks": int(hist.count),
        "p50_block_s": round(float(hist.quantile(0.50)), 6),
        "p99_block_s": round(float(hist.quantile(0.99)), 6),
        "utilization": float(dev["utilization"]),
        "stall_fraction": round(
            min(float(qwait.sum) / max(wall, 1e-9), 1.0), 4),
        "wall_s": round(wall, 6),
        "device_busy_s": dev["busy_s"],
        "programs": programs,
        **_graftpath_cols(cp),
    }


#: controller calibration (ISSUE 18, graftpilot): the remote-store
#: regime's fetch emulation + the detuned starting point the pilot must
#: tune its way out of, and the committed hysteresis numbers.
_CTRL_FETCH_S = 0.010
#: long enough for the full escalation chain: with the settle window
#: growing to 4 x cooldown cycles per move, a ten-move trajectory
#: needs ~2 s of converge traffic
_CTRL_CONVERGE_EPOCHS = 10
_CTRL_MEASURE_EPOCHS = 3
_CTRL_CADENCE_MS = 25.0
_CTRL_COOLDOWN = 2
#: per-knob move cap for the workload's pilot: bounds the worst-case
#: trajectory (both chain knobs fully stepped) so the committed step
#: ceiling is meaningful on a noisy box.
_CTRL_MAX_MOVES = 5
#: ratchet slack: the measured run may take this many moves more than
#: the committed run before the step ceiling fails the gate (settle
#: verdicts are rate-noise-driven on a loaded 2-core box, so run-to-run
#: move counts wobble by a few), and its autopilot/tuned throughput
#: ratio may sag to this factor of the committed ratio before the
#: floor does.
CONTROLLER_MOVES_SLACK = 4
CONTROLLER_RATIO_FLOOR_FACTOR = 0.9


def _wl_controller(inject_s=0.0):
    """The graftpilot convergence ratchet, CI-enforced (ISSUE 18): from
    a DETUNED start (``DASK_ML_TPU_DATA_READERS=1``, ``PREFETCH_DEPTH=1``
    — env-detuned, not arg-pinned, so the knobs stay live) under
    remote-store emulation (10 ms fetch per block inside the readers),
    the controller must tune itself back to the hand-tuned arm's
    throughput.  Three phases:

    * **tuned arm** — env defaults (the defaults ARE the hand-tuned
      values: 4 readers, depth 2), no pilot: the reference rate;
    * **converge fit** — detuned env + a live :class:`Autopilot`
      polling the real graftpath verdict;
    * **measured fit** — same pilot still holding its overrides: the
      converged throughput the ratchet compares.

    Both measured arms run as ``_CTRL_MEASURE_EPOCHS`` independent
    single-epoch fits and report the BEST epoch rate: a max statistic
    is stable against one-off load spikes on the shared gate box where
    a mean is not, and "best sustained epoch" is the honest reading of
    a converged rate (the pilot keeps polling between the measured
    fits, so a trajectory that finishes late still counts).

    Committed gates (see :func:`compare`): ``converged`` must hold,
    ``convergence_moves`` ceilings at the committed count +
    ``CONTROLLER_MOVES_SLACK``, and ``throughput_ratio``
    (converged / tuned rate) floors at ``CONTROLLER_RATIO_FLOOR_FACTOR``
    × the committed ratio.  The generic v3 columns
    (``overlap_efficiency`` + ``bottleneck``) ratchet the measured fit's
    structure through the ordinary bands.  The latency/utilization
    columns are committed as zeros — convergence is the metric here,
    and the per-block numbers already ratchet via the sgd/ingest
    entries.  Under ``--inject-slowdown`` the workload shrinks to one
    epoch per phase (the injection must fail the SUITE fast, not stall
    it) — the block-count drift this causes is itself a failure, which
    is the contract."""
    import numpy as np

    from .. import data as _data
    from ..control import knobs as _knobs
    from ..control.pilot import Autopilot
    from ..linear_model import SGDClassifier
    from ..pipeline import stream_partial_fit
    from . import critical as _critical

    dirp = _ingest_dataset_dir()
    classes = np.array([0, 1])
    converge_epochs = 1 if inject_s else _CTRL_CONVERGE_EPOCHS
    measure_epochs = 1 if inject_s else _CTRL_MEASURE_EPOCHS
    detune = {"DASK_ML_TPU_DATA_READERS": "1",
              "DASK_ML_TPU_PREFETCH_DEPTH": "1"}

    def _fit(label, epochs):
        ds = _data.ShardedDataset(dirp, key=_SEED, epochs=epochs,
                                  fetch_latency_s=_CTRL_FETCH_S,
                                  label=label)
        model = _inject(SGDClassifier(random_state=0), inject_s)
        t0 = time.perf_counter()
        stream_partial_fit(model, ds.iter_blocks(),
                           fit_kwargs={"classes": classes}, label=label)
        wall = time.perf_counter() - t0
        return _BLOCKS * epochs / max(wall, 1e-9), wall

    def _best_rate(label, n_fits):
        # max over independent single-epoch fits (see docstring): the
        # top epoch is what both arms can sustain, minus load spikes
        rates, walls = [], []
        for i in range(n_fits):
            r, w = _fit(f"{label}_{i}", 1)
            rates.append(r)
            walls.append(w)
        return max(rates), sum(walls)

    saved = {k: os.environ.get(k) for k in detune}
    pilot = None
    _knobs.clear_overrides()
    try:
        _fit("ctrl_warmup", 1)  # compiles + reader paths hot
        tuned_rate, _ = _best_rate("ctrl_tuned", measure_epochs)
        os.environ.update(detune)
        pilot = Autopilot(cadence_ms=_CTRL_CADENCE_MS,
                          cooldown=_CTRL_COOLDOWN,
                          max_moves=_CTRL_MAX_MOVES)
        pilot.start()
        _fit("ctrl_converge", converge_epochs)
        auto_rate, auto_wall = _best_rate("ctrl_measured", measure_epochs)
        for _ in range(100):  # let a pending settle window close (the
            if pilot.converged():  # idle gap clears it within cycles)
                break
            time.sleep(0.01)
        pilot.stop()
        cp = _critical.critical_path()  # the measured fit's structure
        rep = pilot.report()
        return {
            "blocks": _BLOCKS * measure_epochs,
            "p50_block_s": 0.0,
            "p99_block_s": 0.0,
            "utilization": 0.0,
            "stall_fraction": 0.0,
            "wall_s": round(auto_wall, 6),
            "device_busy_s": 0.0,
            "programs": {},
            "convergence_moves": len(rep["moves"]),
            "converged": bool(rep["converged"]),
            "throughput_ratio": round(
                auto_rate / max(tuned_rate, 1e-9), 4),
            "knob_trajectory": [
                {"knob": m["knob"], "direction": m["direction"],
                 "to": m["to"], "class": m["class"]}
                for m in rep["moves"]],
            "freezes": dict(rep["freezes"]),
            **_graftpath_cols(cp),
        }
    finally:
        if pilot is not None and pilot.running():
            pilot.stop()
        _knobs.clear_overrides()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


WORKLOADS = {
    "sgd_stream_d0": lambda inject_s=0.0: _wl_sgd(0, inject_s),
    "sgd_stream_d2": lambda inject_s=0.0: _wl_sgd(2, inject_s),
    "mbk_stream_d2": lambda inject_s=0.0: _wl_mbk(2, inject_s),
    "serve_latency": lambda inject_s=0.0: _wl_serve(inject_s),
    "search_util": lambda inject_s=0.0: _wl_search(inject_s),
    "ingest_stall": lambda inject_s=0.0: _wl_ingest(inject_s),
    "controller": lambda inject_s=0.0: _wl_controller(inject_s),
}


def run_workload(name: str, inject_s: float = 0.0) -> dict:
    """Run one workload; an exception becomes an ``error`` metric (a
    hard ratchet failure), never a crash of the suite.  Span recording
    is armed (ring-only) around the workload if it is not already —
    the v3 graftpath columns are assembled from the span timeline, and
    a CLI run (``tools/lint.sh --perf``) has no conftest to arm it —
    and RESTORED after: an in-process caller (bench.py's roofline
    section) keeps its own tracing-off posture."""
    from . import spans as _spans

    armed_here = not _spans.enabled()
    if armed_here:
        _spans.enable()
    try:
        return WORKLOADS[name](inject_s=inject_s)
    except KeyError:
        raise
    except Exception as e:
        return {"blocks": 0, "p50_block_s": 0.0, "p99_block_s": 0.0,
                "utilization": 0.0, "stall_fraction": 0.0, "wall_s": 0.0,
                "device_busy_s": 0.0, "programs": {},
                "overlap_efficiency": None, "bottleneck": None,
                "error": f"{type(e).__name__}: {e}"}
    finally:
        if armed_here:
            _spans.disable()


def run_suite(names=None, inject_s: float = 0.0) -> dict:
    names = list(WORKLOADS) if names is None else list(names)
    unknown = [n for n in names if n not in WORKLOADS]
    if unknown:
        raise KeyError(f"unknown workload(s): {', '.join(unknown)}")
    return {name: run_workload(name, inject_s=inject_s) for name in names}


# -- baseline ------------------------------------------------------------

def default_path() -> str | None:
    env = os.environ.get(PERF_BASELINE_ENV, "").strip()
    if env:
        return env
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cand = os.path.join(os.path.dirname(pkg), "tools",
                        "perf_baseline.json")
    return cand if os.path.isfile(cand) else None


def emit(results: dict) -> dict:
    import jax

    return {
        "version": _VERSION,
        "tool": "graftscope-perf",
        # recorded for the human diffing a rebaseline, NOT compared:
        # the bands (not a version/topology gate) catch real drift
        "jax": jax.__version__,
        "platform": jax.devices()[0].platform,
        "n_devices": len(jax.devices()),
        "workloads": {
            name: {k: metrics[k] for k in sorted(metrics)}
            for name, metrics in sorted(results.items())
        },
    }


def write(path: str, payload: dict) -> None:
    from ..analysis.cache import atomic_write_json

    atomic_write_json(path, payload, indent=2, sort_keys=True)


def load(path: str) -> dict:
    with open(path, encoding="utf-8") as fh:
        payload = json.load(fh)
    if payload.get("version", 0) > _VERSION:
        raise ValueError(
            f"perf baseline {path} has version {payload['version']}, "
            f"newer than this ratchet understands ({_VERSION})")
    if not isinstance(payload.get("workloads"), dict):
        raise ValueError(
            f"perf baseline {path} is malformed: no workloads table")
    return payload


def _ceiling(base: float, band) -> float:
    return base * band[0] + band[1]


def compare(snapshot: dict, results: dict, *, partial: bool = False) -> dict:
    """The ratchet delta (same shape as the graftsan one)::

        {"new": [...], "stale": [...], "regressions": [...],
         "violations": [...]}

    ``partial=True`` (an explicit ``--workloads`` subset) checks errors
    only: stale is meaningless for a subset, and the bands are
    calibrated against the full suite's execution order (warm caches)."""
    snap = snapshot["workloads"]
    new = [] if partial else sorted(set(results) - set(snap))
    stale = [] if partial else sorted(set(snap) - set(results))
    regressions: list[str] = []
    violations: list[str] = []

    for name, m in sorted(results.items()):
        if m.get("error"):
            violations.append(f"{name}: workload errored: {m['error']}")
            continue
        base = snap.get(name)
        if base is None or partial:
            continue
        if base.get("error"):
            violations.append(
                f"baseline entry {name} carries an error — a snapshot "
                f"cannot grandfather a broken workload; fix and "
                f"rebaseline")
            continue
        if m.get("blocks") != base.get("blocks"):
            regressions.append(
                f"{name}: measured {m.get('blocks')} blocks vs baseline "
                f"{base.get('blocks')} — the workload definition "
                f"drifted; rebaseline deliberately "
                f"(tools/lint.sh --rebaseline)")
            continue
        # graftpilot convergence ratchet (the `controller` entry): the
        # committed run's move count is the step ceiling, its
        # autopilot/tuned throughput ratio the floor, and convergence
        # itself is non-negotiable — a controller that stopped
        # converging fails even if every generic band below holds
        # (those are committed as zeros for this entry)
        if "convergence_moves" in base:
            if not m.get("converged"):
                regressions.append(
                    f"{name}: controller did not converge (moves "
                    f"{m.get('convergence_moves')}, trajectory "
                    f"{m.get('knob_trajectory')}) — the pilot is still "
                    f"moving knobs at fit end where the committed run "
                    f"went quiet")
            moves_ceil = (base.get("convergence_moves", 0)
                          + CONTROLLER_MOVES_SLACK)
            if m.get("convergence_moves", 0) > moves_ceil:
                regressions.append(
                    f"{name}: convergence took "
                    f"{m.get('convergence_moves')} moves > ceiling "
                    f"{moves_ceil} (committed "
                    f"{base.get('convergence_moves')} + "
                    f"{CONTROLLER_MOVES_SLACK}) — the policy/hysteresis "
                    f"got less decisive; fix it or rebaseline "
                    f"deliberately")
            # capped at 1.0: the criterion is "within 0.9x of the
            # hand-tuned arm" — a committed run that happened to BEAT
            # the hand-tuned arm must not raise the bar past it
            b_ratio = min(float(base.get("throughput_ratio") or 0.0),
                          1.0)
            ratio_floor = b_ratio * CONTROLLER_RATIO_FLOOR_FACTOR
            if float(m.get("throughput_ratio") or 0.0) < ratio_floor:
                regressions.append(
                    f"{name}: converged throughput ratio "
                    f"{m.get('throughput_ratio')} < floor "
                    f"{ratio_floor:.3f} (committed {b_ratio} × "
                    f"{CONTROLLER_RATIO_FLOOR_FACTOR}) — the tuned-up "
                    f"arm lost ground against the hand-tuned one")
        for key, band in (("p50_block_s", P50_BAND),
                          ("p99_block_s", P99_BAND)):
            ceil = _ceiling(base.get(key, 0.0), band)
            if m.get(key, 0.0) > ceil:
                regressions.append(
                    f"{name}: {key} {m[key]:.4f}s > ceiling {ceil:.4f}s "
                    f"(baseline {base.get(key, 0.0):.4f}s × {band[0]} + "
                    f"{band[1]}s) — the step path got slower; fix it or "
                    f"rebaseline deliberately")
        b_util = base.get("utilization", 0.0)
        if b_util >= UTIL_MIN_BASE and \
                m.get("utilization", 0.0) < b_util * UTIL_FLOOR_FACTOR:
            regressions.append(
                f"{name}: utilization {m.get('utilization', 0.0):.3f} < "
                f"floor {b_util * UTIL_FLOOR_FACTOR:.3f} (baseline "
                f"{b_util:.3f} × {UTIL_FLOOR_FACTOR}) — the device is "
                f"idling where the committed run kept it fed")
        s_ceil = _ceiling(base.get("stall_fraction", 0.0), STALL_BAND)
        if m.get("stall_fraction", 0.0) > s_ceil:
            regressions.append(
                f"{name}: stall_fraction {m['stall_fraction']:.3f} > "
                f"ceiling {s_ceil:.3f} — the consumer is starving "
                f"where the committed run overlapped")
        # graftpath v3: overlap-efficiency floor + bottleneck-class pin
        # (both skipped against a pre-v3 snapshot entry, which carries
        # neither column — same posture as the programs table below)
        b_oe = base.get("overlap_efficiency")
        if b_oe is not None and b_oe >= OVERLAP_MIN_BASE:
            m_oe = m.get("overlap_efficiency") or 0.0
            floor = b_oe * OVERLAP_FLOOR_FACTOR
            if m_oe < floor:
                regressions.append(
                    f"{name}: overlap_efficiency {m_oe:.3f} < floor "
                    f"{floor:.3f} (baseline {b_oe:.3f} × "
                    f"{OVERLAP_FLOOR_FACTOR}) — the pipeline stopped "
                    f"hiding host time under device time; the wall "
                    f"bands may not notice on a fast box, the "
                    f"structure gate does")
        b_bn = base.get("bottleneck")
        if b_bn is not None and isinstance(b_bn, dict):
            m_bn = m.get("bottleneck") or {}
            b_cls = b_bn.get("class", "unknown")
            m_cls = m_bn.get("class", "unknown")
            if (b_cls not in ("unknown",)
                    and m_cls != b_cls
                    and float(b_bn.get("share") or 0.0)
                    >= BOTTLENECK_PIN_SHARE
                    and float(m_bn.get("share") or 0.0)
                    >= BOTTLENECK_PIN_SHARE):
                regressions.append(
                    f"{name}: bottleneck verdict flipped {b_cls} "
                    f"(share {b_bn.get('share')}) → {m_cls} (share "
                    f"{m_bn.get('share')}) — the workload's critical "
                    f"path moved to a different plane; fix it or "
                    f"rebaseline deliberately "
                    f"(tools/lint.sh --rebaseline)")
        # per-program roofline ratchet: the utilization floor, but per
        # cached program — a workload whose aggregate numbers hold can
        # still lose one program's roofline standing (a donation
        # dropped, a precision knob regressed, a program knocked onto
        # its fallback path).  Skipped against a pre-roofline (v1)
        # snapshot entry, which has no programs table.
        b_progs = base.get("programs")
        if b_progs is not None:
            m_progs = m.get("programs", {})
            if sorted(m_progs) != sorted(b_progs):
                regressions.append(
                    f"{name}: program set drifted (measured "
                    f"{sorted(m_progs)} vs baseline {sorted(b_progs)}) "
                    f"— a dispatch path changed; rebaseline "
                    f"deliberately (tools/lint.sh --rebaseline)")
            else:
                for pname, bp in sorted(b_progs.items()):
                    b_frac = bp.get("roofline_frac")
                    if b_frac is None or b_frac < ROOFLINE_MIN_BASE:
                        continue
                    m_frac = m_progs[pname].get("roofline_frac") or 0.0
                    floor = b_frac * ROOFLINE_FLOOR_FACTOR
                    if m_frac < floor:
                        regressions.append(
                            f"{name}/{pname}: roofline_frac "
                            f"{m_frac:.6f} < floor {floor:.6f} "
                            f"(baseline {b_frac:.6f} × "
                            f"{ROOFLINE_FLOOR_FACTOR}) — the program "
                            f"is further from the machine than the "
                            f"committed run")

    return {"new": new, "stale": stale, "regressions": regressions,
            "violations": violations}


def is_clean(delta: dict) -> bool:
    return not any(delta[k] for k in ("new", "stale", "regressions",
                                      "violations"))


# -- CLI -----------------------------------------------------------------

def _parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m dask_ml_tpu.obs.perf",
        description="graftscope perf smoke suite + committed ratchet",
    )
    p.add_argument("--workloads", default=None,
                   help="comma-separated subset (default: all)")
    p.add_argument("--baseline", metavar="PATH", default=None,
                   help="ratchet against this committed snapshot "
                        "(default: DASK_ML_TPU_PERF_BASELINE, else "
                        "tools/perf_baseline.json when present)")
    p.add_argument("--write-baseline", metavar="PATH", default=None,
                   help="snapshot this run's metrics (then ratchet "
                        "against the fresh snapshot)")
    p.add_argument("--inject-slowdown", type=float, default=0.0,
                   metavar="S",
                   help="testing: sleep S seconds inside every step "
                        "program — the ratchet MUST fail")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--list-workloads", action="store_true")
    return p


def main(argv=None) -> int:
    try:
        args = _parser().parse_args(argv)
    except SystemExit as e:  # argparse's bad-args path
        return 0 if (e.code in (0, None)) else 2

    if args.list_workloads:
        for name in sorted(WORKLOADS):
            print(name)
        return 0

    names = None
    if args.workloads:
        names = [w.strip() for w in args.workloads.split(",") if w.strip()]
    if args.write_baseline and names is not None:
        print("error: --write-baseline requires the full suite "
              "(drop --workloads): a partial snapshot cannot be "
              "ratcheted against", file=sys.stderr)
        return 2
    if args.inject_slowdown and names is not None:
        # a --workloads subset runs in errors-only (partial) mode, so
        # the injected slowdown would read as green — the exact
        # opposite of the flag's "MUST fail" contract
        print("error: --inject-slowdown requires the full suite "
              "(drop --workloads): partial runs skip the tolerance "
              "bands the injection must trip", file=sys.stderr)
        return 2
    if args.write_baseline and args.inject_slowdown:
        print("error: refusing to baseline an injected slowdown",
              file=sys.stderr)
        return 2
    try:
        results = run_suite(names, inject_s=args.inject_slowdown)
    except KeyError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    snap_path = args.write_baseline or args.baseline
    if args.write_baseline:
        errs = [f"{n}: {m['error']}" for n, m in sorted(results.items())
                if m.get("error")]
        if errs:
            for line in errs:
                print(f"ERROR: {line}", file=sys.stderr)
            print("perf: refusing to write an erroring baseline to "
                  f"{args.write_baseline} (file untouched)",
                  file=sys.stderr)
            return 1
        write(args.write_baseline, emit(results))
    if snap_path is None:
        snap_path = default_path()

    if snap_path is not None:
        try:
            snap = load(snap_path)
        except (OSError, ValueError) as e:
            print(f"error: cannot load baseline {snap_path}: {e}",
                  file=sys.stderr)
            return 2
        delta = compare(snap, results, partial=names is not None)
    else:
        delta = compare({"workloads": dict(results)}, results,
                        partial=names is not None)

    clean = is_clean(delta)
    if args.format == "json":
        print(json.dumps({"workloads": results, "delta": delta,
                          "baseline": snap_path, "clean": clean},
                         indent=2, sort_keys=True))
    else:
        for name, m in sorted(results.items()):
            # graftpath columns (v3): overlap n/a = no host stage time
            # to hide (the serve plane); the verdict share in parens
            bn = m.get("bottleneck") or {}
            oe = m.get("overlap_efficiency")
            print(f"{name}: p50={m['p50_block_s'] * 1e3:.2f}ms "
                  f"p99={m['p99_block_s'] * 1e3:.2f}ms "
                  f"util={m['utilization']:.3f} "
                  f"stall={m['stall_fraction']:.3f} "
                  f"wall={m['wall_s']:.3f}s "
                  + (f"overlap={oe:.3f} " if oe is not None
                     else "overlap=n/a ")
                  + (f"bottleneck={bn.get('class')}"
                     f"({bn.get('share', 0):.2f})" if bn
                     else "bottleneck=n/a")
                  + (f" ERROR={m['error']}" if m.get("error") else ""))
            for pname, p in sorted((m.get("programs") or {}).items()):
                frac = p.get("roofline_frac")
                flops, nbytes = p.get("flops"), p.get("bytes")
                # `is not None`, not truthiness: a costed zero-flop
                # (bandwidth-only) program must print flops=0, which is
                # a different statement from "cost capture failed"
                print(f"  {pname}: busy={p.get('busy_s', 0.0) * 1e3:.2f}ms"
                      + (f" flops={flops:.3e}" if flops is not None
                         else "")
                      + (f" bytes={nbytes:.3e}" if nbytes is not None
                         else "")
                      + (f" roofline={frac:.5f}" if frac is not None
                         else " roofline=n/a"))
        for key in ("violations", "regressions", "new", "stale"):
            for line in delta[key]:
                print(f"{key.upper()}: {line}")
        print("perf: " + ("clean" if clean else "FAILED")
              + (f" (vs {snap_path})" if snap_path else " (no baseline)"))
    return 0 if clean else 1


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())

"""Structured spans: the event spine of one fit, as a tree.

A **span** is a named, timed scope (``with obs.span("fit"): ...``); an
**event** is a point-in-time record attached to the innermost open span
(a retry, a checkpoint save, a sanitizer violation).  Completed records
land in a per-thread **ring buffer** — appends never contend across
threads (each thread owns its deque; the global registry of rings is
touched once per thread lifetime) — and, when a JSONL sink is armed
(``DASK_ML_TPU_TRACE``), stream to disk as they complete.

Parentage rules (docs/design.md §11):

1. Default: the innermost open span on the CURRENT thread's stack.
2. ``parent=``: explicit parent id — used with ``detached=True`` for
   async scopes (search rounds/brackets interleave many coroutines on
   one loop thread, so stack-parentage would cross-link them; a
   detached span never touches the thread stack).
3. ``adopt(parent_id)``: thread stitching — a worker thread (the
   prefetch worker, an executor unit) enters ``adopt`` with the owning
   fit's span id; spans it opens with an empty local stack attach there
   instead of becoming roots.  This is how the prefetch worker's
   ``pipeline.parse``/``pipeline.stage`` spans appear inside the
   consumer's ``pipeline.stream`` tree.

A span that completes with no parent by any rule is a **root**; the most
recent root is what ``diagnostics.run_report()`` assembles into the
per-fit tree.  Tracing is off by default: ``span()`` costs one global
flag read and returns a shared no-op.  ``enable()`` (or a set
``DASK_ML_TPU_TRACE``) arms recording; the conftest arms it for every
test run so a hung test's watchdog dump can show the open span path.
Events additionally feed the always-on flight recorder (:mod:`.flight`)
even while tracing is disabled — faults and checkpoints must leave a
post-mortem regardless.
"""

from __future__ import annotations

import collections
import itertools
import os
import threading

from .._locks import make_lock
import time

from . import flight as _flight

__all__ = [
    "SCHEMA_VERSION",
    "TRACE_ENV",
    "RING_ENV",
    "Span",
    "span",
    "record_span",
    "event",
    "fmt_exc",
    "adopt",
    "current_span_id",
    "enable",
    "disable",
    "enabled",
    "open_span_paths",
    "last_root",
    "span_records",
    "span_tree",
    "clear_spans",
]

#: grafttrace record-schema version, stamped into every JSONL header and
#: bumped on any field rename/removal (additions are compatible)
SCHEMA_VERSION = 1

#: policy knob: a path arms tracing at import and streams every
#: completed span/event there as schema-versioned JSONL
TRACE_ENV = "DASK_ML_TPU_TRACE"

#: policy knob: per-thread completed-span ring capacity (default 8192)
RING_ENV = "DASK_ML_TPU_TRACE_RING"

_DEFAULT_RING = 8192

_ids = itertools.count(1)  # CPython next() is atomic: lock-free span ids

_TLS = threading.local()  # .stack: open spans; .ring: completed records
_REG_LOCK = make_lock("obs.spans")
_RINGS: dict[int, tuple[str, collections.deque, list]] = {}
_LAST_ROOT: "SpanRecord | None" = None


class _State:
    __slots__ = ("enabled", "ring_size", "sink")

    def __init__(self):
        self.enabled = False
        self.ring_size = _DEFAULT_RING
        self.sink = None  # JsonlSink | None


_STATE = _State()


class SpanRecord:
    """One completed span or point event (events have ``t1 == t0``)."""

    __slots__ = ("kind", "span_id", "parent_id", "name", "t0", "t1",
                 "thread", "attrs", "error")

    def __init__(self, kind, span_id, parent_id, name, t0, t1, thread,
                 attrs, error=None):
        self.kind = kind
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.t0 = t0
        self.t1 = t1
        self.thread = thread
        self.attrs = attrs
        self.error = error

    def as_dict(self) -> dict:
        d = {
            "kind": self.kind, "span_id": self.span_id,
            "parent_id": self.parent_id, "name": self.name,
            "t0": round(self.t0, 9), "t1": round(self.t1, 9),
            "dur_s": round(self.t1 - self.t0, 9), "thread": self.thread,
        }
        if self.attrs:
            d["attrs"] = dict(self.attrs)
        if self.error:
            d["error"] = self.error
        return d


def _stack() -> list:
    st = getattr(_TLS, "stack", None)
    if st is None:
        st = _TLS.stack = []
        with _REG_LOCK:
            ident = threading.get_ident()
            ring = _RINGS.get(ident, (None, None, None))[1]
            if ring is None:
                ring = collections.deque(maxlen=_STATE.ring_size)
            _RINGS[ident] = (threading.current_thread().name, ring, st)
    return st


def _ring() -> collections.deque:
    _stack()  # ensure registration
    return _RINGS[threading.get_ident()][1]


def _emit(rec: SpanRecord) -> None:
    global _LAST_ROOT
    _ring().append(rec)
    if rec.kind == "span" and rec.parent_id is None:
        _LAST_ROOT = rec
    sink = _STATE.sink
    if sink is not None:
        sink.write(rec)


class _Noop:
    """Shared do-nothing span for the disabled path (one flag read, no
    allocation)."""

    __slots__ = ()
    span_id = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP = _Noop()


class Span:
    """An OPEN span; completes (and records) on ``__exit__``."""

    __slots__ = ("name", "attrs", "span_id", "parent_id", "_detached",
                 "_t0", "_pushed")

    def __init__(self, name: str, parent_id: int | None,
                 detached: bool, attrs: dict):
        self.name = name
        self.attrs = attrs
        self.span_id = next(_ids)
        self.parent_id = parent_id
        self._detached = detached
        self._pushed = False
        self._t0 = 0.0

    def __enter__(self):
        st = None
        if not self._detached:
            st = _stack()
            if self.parent_id is None:
                if st:
                    self.parent_id = st[-1].span_id
                else:
                    self.parent_id = getattr(_TLS, "adopt", None)
            st.append(self)
            self._pushed = True
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        t1 = time.perf_counter()
        if self._pushed:
            self._pushed = False
            st = _stack()
            if st and st[-1] is self:
                st.pop()
            else:  # pragma: no cover - misnested exit: drop, don't corrupt
                try:
                    st.remove(self)
                except ValueError:
                    pass
        # StopIteration/GeneratorExit are control flow, not failures: a
        # span around a source pull (pipeline.parse wraps next(src))
        # ends every healthy stream with one — stamping it as an error
        # would put a false failure on every successful fit's tree
        failed = exc_type is not None and not issubclass(
            exc_type, (StopIteration, GeneratorExit))
        _emit(SpanRecord(
            "span", self.span_id, self.parent_id, self.name, self._t0,
            t1, threading.current_thread().name, self.attrs,
            error=(fmt_exc(exc) if failed and exc is not None
                   else f"{exc_type.__name__}" if failed else None),
        ))
        return False


def span(name: str, *, parent: int | None = None, detached: bool = False,
         **attrs):
    """Open a named span (see module docstring for parentage rules).

    ``detached=True`` skips the thread stack: the span is parented ONLY
    by the explicit ``parent`` and never becomes an implicit parent —
    the form async scopes must use.  Returns a no-op when tracing is
    disabled.
    """
    if not _STATE.enabled:
        return _NOOP
    return Span(name, parent, detached, attrs)


def record_span(name: str, t0: float, t1: float, *,
                parent: int | None = None, **attrs) -> None:
    """Record an ALREADY-ELAPSED interval as a completed span.

    The retroactive form :mod:`.critical`'s wait signals need: a
    contiguous queue wait is only known to have been a wait once it
    ends (the consumer's ``q.get`` loop, the reorder-merge wait), so
    the producer stamps ``t0`` when the wait begins and calls this when
    it resolves.  Parentage follows :func:`event`'s rule (innermost
    open span on this thread, else the adopt target) unless ``parent``
    is given — pass an explicit parent from rootless threads (dataset
    readers), or skip the call entirely when no parent exists, so a
    retroactive record can never steal ``last_root`` from a real fit.
    No-op while tracing is disabled."""
    if not _STATE.enabled:
        return
    if parent is None:
        st = getattr(_TLS, "stack", None)
        parent = (st[-1].span_id if st
                  else getattr(_TLS, "adopt", None))
    if parent is None:
        # a retroactive record may not become a root: _emit would
        # publish it as last_root and run_report's tree would show a
        # stray wait instead of the fit — drop instead (the registry
        # histograms the producers also write keep the totals)
        return
    _emit(SpanRecord(
        "span", next(_ids), parent, name, float(t0),
        max(float(t1), float(t0)), threading.current_thread().name,
        attrs))


def event(name: str, *, parent: int | None = None, **attrs) -> None:
    """Record a point event: onto the span tree when tracing is enabled,
    and ALWAYS into the flight recorder (faults/checkpoints must leave a
    post-mortem even in an untraced process)."""
    _flight.record("event", name, attrs)
    if not _STATE.enabled:
        return
    if parent is None:
        st = getattr(_TLS, "stack", None)
        parent = (st[-1].span_id if st
                  else getattr(_TLS, "adopt", None))
    t = time.perf_counter()
    _emit(SpanRecord("event", next(_ids), parent, name, t, t,
                     threading.current_thread().name, attrs))


def fmt_exc(exc: BaseException) -> str:
    """The ONE error-string format of the event schema (design.md §11):
    ``Type: message``, capped at 200 chars — every producer (span
    errors, retry/failure events, pipeline.fault) uses this so flight
    and JSONL payloads cannot drift per site."""
    return f"{type(exc).__name__}: {exc}"[:200]


class adopt:
    """Stitch this thread's parentless spans/events under ``parent_id``
    (a span id captured on the owning thread).  Nestable; ``None``
    restores root behavior."""

    __slots__ = ("_parent", "_prev")

    def __init__(self, parent_id: int | None):
        self._parent = parent_id
        self._prev = None

    def __enter__(self):
        self._prev = getattr(_TLS, "adopt", None)
        _TLS.adopt = self._parent
        return self

    def __exit__(self, *exc):
        _TLS.adopt = self._prev
        return False


def current_span_id() -> int | None:
    """The innermost open span id on THIS thread (None outside any span
    or with tracing disabled) — what a consumer captures before handing
    work to a worker thread for :class:`adopt` stitching."""
    st = getattr(_TLS, "stack", None)
    if st:
        return st[-1].span_id
    return getattr(_TLS, "adopt", None)


# -- lifecycle -----------------------------------------------------------
def enable(jsonl_path: str | None = None,
           ring_size: int | None = None) -> None:
    """Arm span recording.  ``jsonl_path`` additionally streams every
    completed record to a schema-versioned JSONL file (the
    ``DASK_ML_TPU_TRACE`` form); ``ring_size`` resizes FUTURE threads'
    rings (``DASK_ML_TPU_TRACE_RING``)."""
    if ring_size is not None:
        ring_size = int(ring_size)
        if ring_size < 1:
            raise ValueError(f"ring size must be >= 1, got {ring_size}")
        _STATE.ring_size = ring_size
    if jsonl_path:
        from .export import JsonlSink

        # construct BEFORE swapping: a failed re-arm (unwritable path)
        # must raise without destroying a working sink
        new_sink = JsonlSink(jsonl_path)
        old, _STATE.sink = _STATE.sink, new_sink
        if old is not None:  # re-arming: release the previous file
            old.close()
    _STATE.enabled = True
    # compile counters are part of the spine: arm the (idempotent,
    # listener-only) jax.monitoring hook alongside tracing — lazily
    # imported so the obs package itself stays jax-free for the static
    # host-only proofs
    try:
        from . import jaxhooks

        jaxhooks.install()
    except Exception:  # pragma: no cover - jax-less analysis contexts
        pass


def disable() -> None:
    """Disarm recording (rings and the flight recorder keep their
    contents; the JSONL sink is closed)."""
    _STATE.enabled = False
    sink, _STATE.sink = _STATE.sink, None
    if sink is not None:
        sink.close()


def enabled() -> bool:
    return _STATE.enabled


# -- introspection / assembly -------------------------------------------
def open_span_paths() -> dict:
    """``{thread_name: "fit > pipeline.stream > ..."}`` of currently-open
    spans — read racily by the watchdog/flight dump (forensics, not
    synchronization).  Threads sharing a name (concurrent prefetch
    workers under a pool search) are disambiguated as ``name#ident`` so
    a hang dump shows EVERY in-flight worker, not one survivor."""
    with _REG_LOCK:
        items = [(ident, name, list(st))
                 for ident, (name, _, st) in _RINGS.items()]
    open_items = [(ident, name, st) for ident, name, st in items if st]
    dup_names = {name for _, name, _ in open_items
                 if sum(1 for _, n, _ in open_items if n == name) > 1}
    out = {}
    for ident, name, st in open_items:
        key = f"{name}#{ident}" if name in dup_names else name
        out[key] = " > ".join(s.name for s in st)
    return out


def span_records() -> list:
    """All retained records across every thread ring, oldest-ish first
    (per-ring order is exact; cross-ring merged by start time)."""
    with _REG_LOCK:
        rings = [ring for _, ring, _ in _RINGS.values()]
    records: list = []
    for ring in rings:
        records.extend(ring)  # deque iteration is GIL-atomic enough
    records.sort(key=lambda r: (r.t0, r.span_id))
    return records


def last_root() -> SpanRecord | None:
    """The most recently completed ROOT span (the last whole fit/stream,
    by parentage rule)."""
    return _LAST_ROOT


def span_tree(root: SpanRecord | None = None) -> dict | None:
    """Assemble the tree under ``root`` (default: :func:`last_root`)
    from the retained rings: nested ``{name, t0, t1, dur_s, thread,
    attrs, children: [...], events: [...]}``.

    Ring-bounded by design: a tree bigger than the rings loses its
    OLDEST spans (the tail of a long fit is the interesting part); a
    child whose parent was evicted attaches to the root.
    """
    root = root if root is not None else _LAST_ROOT
    if root is None:
        return None
    records = span_records()
    by_id = {r.span_id: r for r in records}
    by_id[root.span_id] = root

    # membership: walk each record's parent chain to see if it reaches
    # the root (memoized); evicted parents inside the root's window
    # count as members parented to the root
    member: dict[int, bool] = {root.span_id: True}

    def reaches(rec0) -> bool:
        rid = rec0.span_id
        chain = []
        verdict = False
        while rid is not None and rid not in member:
            chain.append(rid)
            rec = by_id.get(rid)
            if rec is None:
                # evicted ancestor: adopt into the root iff the orphan
                # started inside the root's window (docstring contract)
                verdict = rec0.t0 >= root.t0
                rid = None
                break
            rid = rec.parent_id
        if rid is not None:
            verdict = member[rid]
        for c in chain:
            member[c] = verdict
        return verdict

    nodes: dict[int, dict] = {}

    def node_for(rec) -> dict:
        n = nodes.get(rec.span_id)
        if n is None:
            n = nodes[rec.span_id] = rec.as_dict()
            n["children"] = []
            n["events"] = []
        return n

    root_node = node_for(root)
    for rec in records:
        if rec.span_id == root.span_id or not reaches(rec):
            continue
        parent = by_id.get(rec.parent_id)
        pnode = node_for(parent) if parent is not None else root_node
        if rec.kind == "event":
            pnode["events"].append(rec.as_dict())
        else:
            pnode["children"].append(node_for(rec))
    return root_node


def clear_spans() -> None:
    """Drop retained records, the last-root pointer, and DEAD threads'
    rings (open span stacks on live threads are untouched)."""
    global _LAST_ROOT
    live = {t.ident for t in threading.enumerate()}
    with _REG_LOCK:
        for ident in [i for i in _RINGS if i not in live]:
            del _RINGS[ident]
        for _, ring, _ in _RINGS.values():
            ring.clear()
    _LAST_ROOT = None


# env arming: a set DASK_ML_TPU_TRACE turns the whole process on at
# import, streaming to that path — zero code changes at call sites.
# DASK_ML_TPU_TRACE_RING alone only SIZES the rings (api.md: a
# memory/history knob, not an arming switch — a later enable() uses it).
_env_ring = os.environ.get(RING_ENV, "").strip()
if _env_ring:
    _STATE.ring_size = int(_env_ring)
    if _STATE.ring_size < 1:
        raise ValueError(f"{RING_ENV} must be >= 1, got {_env_ring!r}")
_env_path = os.environ.get(TRACE_ENV, "").strip()
if _env_path:
    try:
        enable(jsonl_path=_env_path)
    except OSError:
        # ambient env arming must not kill `import dask_ml_tpu` over
        # an unwritable trace directory — the traced job matters more
        # than its trace.  Degrade to ring+flight recording, loudly.
        # (The explicit obs.enable(jsonl_path=...) API still raises:
        # a caller who ASKED for a file gets the error.)
        import logging

        logging.getLogger(__name__).warning(
            "grafttrace: %s=%s is unwritable; tracing continues "
            "ring-only (no JSONL stream)", TRACE_ENV, _env_path,
            exc_info=True,
        )
        enable()

"""graftscope: device-time accounting — the device-side half of grafttrace.

grafttrace (design.md §11) records host wall spans; this module makes
**device occupancy** a first-class observable.  Every dispatched program
is tracked at the two choke points the repo already owns — the central
program cache's dispatch (:mod:`dask_ml_tpu.programs.cache`) and the
graftsan ``ExecuteReplicated`` hook — as an **in-flight interval**:

* ``t0`` — the moment the program was enqueued on the dispatching
  thread (jax dispatch is asynchronous on every backend this repo
  runs, measured on this image: a 270 ms program returns from its
  dispatch call in 3 ms);
* ``t1`` — the moment its outputs were observed ready.  Readiness is
  detected by duck-typed ``leaf.is_ready()`` polling (a ~0.3 µs
  host-only future check): at every subsequent tracked dispatch, and —
  so the end of a busy period is found even when the host goes quiet —
  on a dedicated **sampler thread** (:data:`SCOPE_THREAD_NAME`,
  supervised under the ``"obs"`` domain) that polls every
  :data:`_SAMPLE_S` seconds while work is in flight and parks on a
  condition variable otherwise.

Each interval may additionally carry the dispatched executable's
captured XLA cost estimate (flops / bytes accessed — the program cache
hands it to :func:`track`), which :func:`device_report` joins with
measured busy time into per-program achieved FLOP/s and a roofline
fraction against :mod:`.roofline`'s peak table.

The union of in-flight intervals is the "device busy-or-fed" timeline:
its complement inside the observation window is **device idle time** —
the budget currency the ROADMAP's [search-scale] lane names, and the
occupancy number the [serving] lane's SLOs sit next to.  Per-program
seconds land in the metrics registry (``device.busy_s{program}``
histograms, ``device.dispatches{program}`` counters — scraped by
:mod:`.serve`), closed intervals in a bounded ring consumed by
:func:`device_report` (``diagnostics.run_report()["device"]``) and
:func:`~.export.perfetto_trace`'s dedicated device lane.

Honesty contract: ``t1`` carries a detection slack of at most one
sampler period (~2 ms) — fine for the ms-scale block programs this
repo streams, and the committed perf ratchet (:mod:`.perf`) is
calibrated under the same cadence.  An interval covers enqueue→ready,
i.e. queue wait counts as *fed*, not idle — exactly the currency a
scheduler that wants to keep the device fed should budget.  On a
relayed backend (the axon TPU tunnel) readiness can report early
(BENCH_LOCAL.md); there the XProf device trace stays the authority and
this lane is a lower bound on idle.  The jitted-twin fallback path may
fold its own cold trace/compile into one interval (the AOT cache path
never does) — warm rounds, which is what the ratchet measures, are
unaffected.

Everything here is pure host stdlib — no jax import (the obs package's
host-only posture): callers hand in output leaves and this module only
ever calls ``is_ready()`` on them.  A leaf whose ``is_ready`` raises
(a buffer donated into the next step) counts as ready — the consuming
program's own interval is already open, so the lane stays continuous.
"""

from __future__ import annotations

import threading

from .._locks import make_condition, make_lock
import time

from .metrics import registry as _registry

__all__ = [
    "SCOPE_THREAD_NAME",
    "track",
    "absorb",
    "absorbed",
    "sweep",
    "settle",
    "cursor",
    "timeline",
    "device_report",
    "pending_count",
    "open_intervals",
    "rearm",
    "reset",
]

#: the sampler thread's literal name.  It is HOST-ONLY: it polls
#: ``is_ready()`` futures, beats a supervisor heartbeat, and records
#: into the metrics registry — it must never compile or dispatch
#: (``analysis.rules._spmd.HOST_ONLY_THREAD_NAMES``; the graftsan
#: dispatch detector holds it to that at runtime, same as the prefetch
#: worker).
SCOPE_THREAD_NAME = "dask-ml-tpu-scope"

#: sampler poll period while work is in flight: the end-detection slack
#: of every interval is at most this (plus scheduler jitter).
_SAMPLE_S = 0.002

#: how many closed intervals the timeline ring retains (registry totals
#: survive eviction; the ring bounds what device_report / the perfetto
#: device lane can SEE, same posture as the span rings).
_RING_CAP = 8192

#: sampler deaths tolerated before degrading to sweep-on-dispatch only
#: (detection slack grows to the inter-dispatch gap; totals stay exact).
_MAX_RESTARTS = 5

#: supervisor-beat decimation: one beat per this many sampler sweeps
#: (a 500 Hz poller must not turn the beat counter into noise).
_BEATS_EVERY = 50


class _Pending:
    __slots__ = ("program", "t0", "leaves", "seq", "cost")

    def __init__(self, program, t0, leaves, seq, cost=None):
        self.program = program
        self.t0 = t0
        self.leaves = leaves
        self.seq = seq
        self.cost = cost  # {"flops", "bytes", ...} | None (roofline.py)


_LOCK = make_lock("obs.scope")
_COND = make_condition("obs.scope", _LOCK)
_PENDING: list[_Pending] = []
_CLOSED: list[dict] = []  # ring: trimmed to _RING_CAP on append
_SEQ = 0
_SAMPLER: threading.Thread | None = None
_SAMPLER_DEATHS = 0
_TLS = threading.local()


def _leaf_ready(leaf) -> bool:
    try:
        return bool(leaf.is_ready())
    except Exception:
        # a buffer donated into the next program (or an exotic array
        # type): its producing program is chained into the consumer's
        # already-open interval — treat as ready, the lane stays whole
        return True


# -- recording (choke-point callbacks; any dispatching thread) -----------

def track(program: str, t0: float, leaves, cost=None) -> bool:
    """Open an in-flight interval for one dispatched program.

    ``leaves`` are the dispatch's output leaves; only leaves exposing
    ``is_ready()`` participate (tracer outputs — a program inlining
    into an outer trace — have none, and are deliberately not counted
    as dispatches).  ``cost`` is the dispatched executable's captured
    cost_analysis (:func:`~.roofline.capture_cost`; the program cache
    passes it on the AOT path) — it rides the interval so the closed
    timeline carries flops/bytes per dispatch.  Returns True when an
    interval was opened.  Host-only: a time read, a lock, a list
    append, a registry increment."""
    live = [x for x in leaves if hasattr(x, "is_ready")]
    if not live:
        return False
    now = time.perf_counter()
    global _SEQ
    with _COND:
        _sweep_locked(now)
        seq = _SEQ
        _SEQ += 1
        _PENDING.append(_Pending(str(program), float(t0), live, seq, cost))
        _ensure_sampler_locked()
        _COND.notify()
    _registry().counter("device.dispatches", str(program)).inc()
    return True


class absorb:
    """Suppress inner-choke-point tracking on this thread: the program
    cache wraps its dispatch call in one of these so the graftsan
    ``ExecuteReplicated`` hook (which the same call funnels through
    while a sanitizer is active) does not open a duplicate interval
    for the identical execution."""

    __slots__ = ()

    def __enter__(self):
        _TLS.absorb = getattr(_TLS, "absorb", 0) + 1
        return self

    def __exit__(self, *exc):
        _TLS.absorb -= 1
        return False


def absorbed() -> bool:
    return getattr(_TLS, "absorb", 0) > 0


# -- interval closing ----------------------------------------------------

def _close_locked(p: _Pending, t1: float) -> None:
    iv = {
        "program": p.program,
        "t0": p.t0,
        "t1": max(float(t1), p.t0),
        "seq": p.seq,
    }
    if p.cost is not None:
        iv["flops"] = p.cost.get("flops", 0.0)
        iv["bytes"] = p.cost.get("bytes", 0.0)
    _CLOSED.append(iv)
    if len(_CLOSED) > _RING_CAP:
        del _CLOSED[: len(_CLOSED) - _RING_CAP]


def _sweep_locked(now: float) -> list[tuple]:
    done = [p for p in _PENDING if all(_leaf_ready(x) for x in p.leaves)]
    if not done:
        return []
    closed = []
    for p in done:
        _PENDING.remove(p)
        _close_locked(p, now)
        closed.append((p.program, max(now - p.t0, 0.0), p.cost))
    # registry publication outside the hot predicate but still under
    # _LOCK: instrument locks nest inside, never the other way around.
    # roofline.py is pure host stdlib, so the attribution stays legal
    # on the sampler thread; the peaks lookup is loop-invariant and
    # hoisted so a busy sweep pays it once, not per interval.
    reg = _registry()
    peaks = None
    if any(cost is not None for _, _, cost in closed):
        from . import roofline as _roofline

        # fail-soft lookup: a malformed DASK_ML_TPU_PEAKS must raise on
        # the reporting surfaces, not kill the sampler or a dispatch
        peaks = _roofline.try_peaks_for(_roofline.detected_platform())
    for program, dur, cost in closed:
        reg.histogram("device.busy_s", program).record(dur)
        if cost is None:
            continue
        # roofline attribution lands with the interval: flops/bytes as
        # monotone counters (a /metrics scraper can rate() them), the
        # last closed interval's roofline fraction as a live gauge
        reg.counter("device.flops", program).inc(int(cost["flops"]))
        reg.counter("device.bytes", program).inc(int(cost["bytes"]))
        att = _roofline.attribution(cost["flops"], cost["bytes"], dur,
                                    peaks)
        if att["roofline_frac"] is not None:
            reg.gauge("device.roofline_frac", program).set(
                att["roofline_frac"])
    return closed


def sweep() -> None:
    """Close every pending interval whose outputs are ready (called by
    the sampler; safe from any thread — host-only)."""
    with _COND:
        _sweep_locked(time.perf_counter())


def settle(timeout_s: float = 5.0) -> bool:
    """Poll until no tracked dispatch is in flight (a report/bench
    boundary, never the hot path).  Returns False on timeout — a
    wedged program must not wedge its report."""
    deadline = time.monotonic() + timeout_s
    while True:
        with _COND:
            _sweep_locked(time.perf_counter())
            if not _PENDING:
                return True
        if time.monotonic() >= deadline:
            return False
        time.sleep(_SAMPLE_S)


# -- the sampler thread --------------------------------------------------

def _sampler_loop() -> None:
    from ..resilience import supervisor as _supervisor

    hb = _supervisor.register(SCOPE_THREAD_NAME, "obs",
                              thread=threading.current_thread())
    beats = 0
    while True:
        with _COND:
            while not _PENDING:
                _COND.wait()
            _sweep_locked(time.perf_counter())
        beats += 1
        if beats % _BEATS_EVERY == 0:
            # diagnostics.reset() wipes the supervisor table; a live
            # sampler re-registers itself so the endpoint's /healthz
            # keeps seeing it (rearm() covers the no-pending case)
            if _supervisor.lookup(SCOPE_THREAD_NAME) is not hb:
                hb = _supervisor.register(
                    SCOPE_THREAD_NAME, "obs",
                    thread=threading.current_thread())
            hb.beat()
        time.sleep(_SAMPLE_S)


def _ensure_sampler_locked() -> None:
    global _SAMPLER, _SAMPLER_DEATHS
    t = _SAMPLER
    if t is not None and t.is_alive():
        return
    if t is not None:
        _SAMPLER_DEATHS += 1
        if _SAMPLER_DEATHS > _MAX_RESTARTS:
            return  # degraded: sweep-on-dispatch + settle() only
        from ..resilience import supervisor as _supervisor

        _supervisor.note_death("obs", SCOPE_THREAD_NAME)
        _supervisor.note_restart("obs", SCOPE_THREAD_NAME)
    # host-only sampler: is_ready futures + heartbeat + registry — never
    # compiles, never dispatches (runtime-checked by graftsan, which
    # does NOT bless this name)
    _SAMPLER = threading.Thread(
        target=_sampler_loop, daemon=True, name=SCOPE_THREAD_NAME,
    )
    _SAMPLER.start()


def rearm() -> None:
    """Re-register a live sampler's supervisor heartbeat (called by
    ``diagnostics.reset()`` right after it wipes the unit table)."""
    from ..resilience import supervisor as _supervisor

    t = _SAMPLER
    if t is not None and t.is_alive() \
            and _supervisor.lookup(SCOPE_THREAD_NAME) is None:
        _supervisor.register(SCOPE_THREAD_NAME, "obs", thread=t)


# -- reading -------------------------------------------------------------

def cursor() -> int:
    """An opaque position in the interval sequence: pass to
    :func:`timeline` / :func:`device_report` as ``since`` to scope a
    read to dispatches tracked after this call (the bench per-workload
    delta idiom)."""
    with _LOCK:
        return _SEQ


def pending_count() -> int:
    with _LOCK:
        return len(_PENDING)


def open_intervals() -> list[dict]:
    """Every still-in-flight dispatch as ``{"program", "t0", "age_s"}``
    (oldest first) — the forensic read the flight-recorder dump uses: a
    hang DURING a long device program must show which program was in
    flight and for how long, not just the host-side open spans.  Pure
    host read; no sweep (the dump path must not poll readiness)."""
    now = time.perf_counter()
    with _LOCK:
        out = [{"program": p.program, "t0": p.t0,
                "age_s": round(max(now - p.t0, 0.0), 6)}
               for p in _PENDING]
    out.sort(key=lambda iv: iv["t0"])
    return out


def timeline(since: int | None = None, open_until: float | None = None):
    """Retained intervals (oldest first): closed ones from the ring
    plus — so a live scrape mid-fit sees the current busy period —
    every still-pending dispatch as ``[t0, open_until]`` (default: now)
    with ``"open": True``."""
    now = time.perf_counter() if open_until is None else float(open_until)
    with _COND:
        _sweep_locked(time.perf_counter())
        out = [dict(iv) for iv in _CLOSED
               if since is None or iv["seq"] >= since]
        for p in _PENDING:
            if since is None or p.seq >= since:
                out.append({"program": p.program, "t0": p.t0,
                            "t1": max(now, p.t0), "seq": p.seq,
                            "open": True})
    out.sort(key=lambda iv: (iv["t0"], iv["seq"]))
    return out


def _search_section() -> dict | None:
    """The adaptive-search registry families (``search.*`` —
    model_selection, design.md §17), rendered next to the device
    occupancy they budget against.  ``round_s`` records for EVERY
    search path (the sequential loop included); the scheduler families
    — ``dispatch_turns``, ``throttled``, ``queue_wait_s``,
    ``requeues``, the ``inflight`` gauge — appear only when the
    concurrent orchestrator actually ran (their absence next to
    ``round_s`` means the searches took the serialized path).  None
    when no search ran in this process (the section must not invent an
    empty story).  Pure registry reads — host-only, scrape-safe."""
    reg = _registry()
    counters: dict = {}
    gauges: dict = {}
    hists: dict = {}
    for name, tag, inst in reg.export_items():
        if not name.startswith("search."):
            continue
        key = f"{name[len('search.'):]}" + (f"{{{tag}}}" if tag else "")
        snap = getattr(inst, "snapshot", None)
        if callable(snap):
            s = snap()
            hists[key] = {k: s[k] for k in ("count", "sum", "p50", "p99")
                          if k in s}
        elif type(inst).__name__ == "Gauge":
            gauges[key] = inst.value
        else:
            counters[key] = inst.value
    if not (counters or gauges or hists):
        return None
    out: dict = dict(sorted(counters.items()))
    out.update(sorted(gauges.items()))
    out.update(sorted(hists.items()))
    return out


def _merge(intervals):
    """Union-merge sorted-by-t0 intervals -> (busy_s, merged, gaps)."""
    merged: list[list[float]] = []
    for iv in intervals:
        if merged and iv["t0"] <= merged[-1][1]:
            merged[-1][1] = max(merged[-1][1], iv["t1"])
        else:
            merged.append([iv["t0"], iv["t1"]])
    busy = sum(b - a for a, b in merged)
    gaps = [{"t0": merged[i][1], "t1": merged[i + 1][0],
             "dur_s": merged[i + 1][0] - merged[i][1]}
            for i in range(len(merged) - 1)
            if merged[i + 1][0] > merged[i][1]]
    return busy, merged, gaps


def device_report(since: int | None = None, *, settle_s: float = 0.0,
                  top_gaps: int = 3) -> dict:
    """Device occupancy over the retained window::

        {"dispatches": n, "busy_s": s, "window_s": w, "idle_s": w - s,
         "utilization": s / w,            # 0.0 when nothing dispatched
         "idle_gaps": [{"t0", "t1", "dur_s"} x top-3, largest first],
         "programs": {name: {"dispatches": n, "busy_s": s}},
         "pending": still-in-flight count}

    The window is ``[first interval start, last interval end]`` of the
    retained (``since``-scoped) timeline — i.e. utilization of the
    period the device was actually in use, the number the perf ratchet
    floors.  ``settle_s > 0`` first waits (bounded) for in-flight
    dispatches so a *post-fit* report closes its last interval; a live
    scrape must pass 0 (the default — never wait on the device in a
    handler thread).

    Each program whose dispatches carried captured cost_analysis
    (:mod:`.roofline`) additionally reports its accumulated ``flops`` /
    ``bytes`` and the joined ``achieved_flops_per_s`` /
    ``achieved_bytes_per_s`` / ``intensity`` / ``roofline_frac``
    against the peak table; the top-level ``roofline`` block names the
    platform and peaks (with provenance) those fractions used — absent
    when the platform is undetected, None fractions when peaks are
    unknown (honesty over invention).

    When an adaptive search has run in this process, a ``search`` block
    rides along (``search.*`` registry families — per-round latency for
    every search path, plus the orchestrator's dispatch turns, throttle
    events, requeues, in-flight gauge, and queue-wait when the
    CONCURRENT plane ran): the scheduler budgets against exactly this
    report's idle time, so its books belong next to the occupancy they
    defend (design.md §17)."""
    if settle_s > 0:
        settle(settle_s)
    ivs = timeline(since)
    programs: dict[str, dict] = {}
    work: dict[str, list] = {}  # program -> [flops, bytes, costed_busy]
    for iv in ivs:
        p = programs.setdefault(iv["program"],
                                {"dispatches": 0, "busy_s": 0.0})
        p["dispatches"] += 1
        p["busy_s"] += iv["t1"] - iv["t0"]
        if "flops" in iv and not iv.get("open"):
            w = work.setdefault(iv["program"], [0.0, 0.0, 0.0])
            w[0] += iv["flops"]
            w[1] += iv["bytes"]
            w[2] += iv["t1"] - iv["t0"]
    from . import roofline as _roofline

    platform = _roofline.detected_platform()
    peaks = _roofline.peaks_for(platform)
    for name, p in programs.items():
        p["busy_s"] = round(p["busy_s"], 6)
        w = work.get(name)
        if w is not None:
            p.update(_roofline.attribution(w[0], w[1], w[2], peaks))
    search = _search_section()
    from . import critical as _critical

    verdicts = _critical.last_verdicts()
    if not ivs:
        out = {"dispatches": 0, "busy_s": 0.0, "window_s": 0.0,
               "idle_s": 0.0, "utilization": 0.0, "idle_gaps": [],
               "programs": {}, "pending": pending_count()}
        if search is not None:
            out["search"] = search
        if verdicts:
            out["critical"] = verdicts
        return out
    busy, merged, gaps = _merge(ivs)
    window = max(iv["t1"] for iv in ivs) - ivs[0]["t0"]
    gaps.sort(key=lambda g: -g["dur_s"])
    out = {
        "dispatches": len(ivs),
        "busy_s": round(busy, 6),
        "window_s": round(window, 6),
        "idle_s": round(max(window - busy, 0.0), 6),
        "utilization": round(busy / window, 4) if window > 0 else 0.0,
        "idle_gaps": [{k: round(v, 6) for k, v in g.items()}
                      for g in gaps[:top_gaps]],
        "programs": dict(sorted(programs.items())),
        "pending": pending_count(),
    }
    if platform is not None:
        out["roofline"] = {"platform": platform, "peaks": peaks}
    if search is not None:
        out["search"] = search
    # graftpath join (design.md §19): the most recent per-plane
    # bottleneck verdicts next to the occupancy they interpret —
    # absent when no verdict has been computed (no invented story)
    if verdicts:
        out["critical"] = verdicts
    return out


def reset() -> None:
    """Drop the timeline ring and every pending interval (test/bench
    isolation; the registry's ``device.*`` families are cleared by the
    caller's registry reset — ``obs.reset_all()`` does both)."""
    with _COND:
        _PENDING.clear()
        _CLOSED.clear()

"""Live metrics endpoint: the serving lane's scrape surface.

A single stdlib ``http.server`` thread (no dependencies — same posture
as the rest of :mod:`dask_ml_tpu.obs`) exports the whole metrics
registry in Prometheus text exposition format plus a supervisor-backed
health verdict, shipped BEFORE the serving lane itself so the scrape
surface exists the day that lane lands (ROADMAP [serving]):

* ``GET /metrics`` — every counter/gauge as a sample line, every
  histogram as a Prometheus *summary* (``{quantile="0.5|0.95|0.99"}``
  + ``_sum`` + ``_count``), names mangled ``pipeline.block_s`` →
  ``pipeline_block_s``, registry tags as a ``tag="..."`` label with
  full label-value escaping (``\\`` ``"`` and newline);
* ``GET /healthz`` — JSON from :func:`dask_ml_tpu.resilience.
  supervisor.healthz`: 200 while no supervised unit is dead, 503
  otherwise — the LIVENESS probe a deployment points at this process;
* ``GET /readyz`` — the READINESS half of the split: 503 while any
  registered readiness probe (e.g. a ModelServer whose residency
  warmup is still compiling rungs, or a replica behind a drain
  barrier) reports not-ready, or while liveness itself fails.  A
  router must gate traffic on THIS, not on liveness — a live process
  can still be cold.

Lifecycle mirrors the compile-ahead worker (design.md §13): the server
thread is named :data:`METRICS_THREAD_NAME`, registered with the
supervisor under the ``"obs"`` domain (one beat per request served),
and re-registers itself after a ``diagnostics.reset()`` wipes the unit
table — the endpoint survives a books reset cleanly.  It is strictly
HOST-ONLY: it reads registry snapshots and supervisor verdicts, and
must never compile or dispatch a device program
(``analysis.rules._spmd.HOST_ONLY_THREAD_NAMES``; graftsan's dispatch
detector raises in this thread if it ever does).  A scrape never waits
on the device: handlers read books, they do not settle them.

Armed by ``DASK_ML_TPU_METRICS_PORT`` (default off; ``0`` binds an
OS-assigned ephemeral port — the test idiom) at package import, or
explicitly via :func:`start`.  Binding is fail-soft on the env path (a
taken port logs one warning and the process runs unscraped — the fit
matters more than its scrape) and loud on the explicit one.
"""

from __future__ import annotations

import json
import logging
import os
import threading

from .._locks import make_lock
from http.server import BaseHTTPRequestHandler, HTTPServer

from .metrics import Counter, Gauge, registry as _registry

__all__ = [
    "METRICS_PORT_ENV",
    "METRICS_THREAD_NAME",
    "MetricsServer",
    "prometheus_text",
    "readyz",
    "register_readiness",
    "unregister_readiness",
    "resolve_port",
    "start",
    "stop",
    "active",
    "rearm",
]

logger = logging.getLogger(__name__)

#: policy knob: TCP port for the live metrics endpoint ('' = off, the
#: default; ``0`` = an OS-assigned ephemeral port, reported by
#: :func:`active`'s ``.port``).  Strict parse — a non-integer raises.
METRICS_PORT_ENV = "DASK_ML_TPU_METRICS_PORT"

#: the endpoint thread's literal name — host-only by contract, never
#: blessed to compile or dispatch (see module docstring).
METRICS_THREAD_NAME = "dask-ml-tpu-metrics"

_QUANTILES = (("0.5", "p50"), ("0.95", "p95"), ("0.99", "p99"))


def resolve_port(port: int | None = None) -> int | None:
    """Resolve the endpoint port: explicit argument, else the
    ``DASK_ML_TPU_METRICS_PORT`` knob; ``None`` = off.  Strict parse
    (the repo's env_choice posture): a non-integer or negative value
    raises instead of silently reading as off."""
    if port is not None:
        port = int(port)
    else:
        raw = os.environ.get(METRICS_PORT_ENV, "").strip()
        if not raw:
            return None
        try:
            port = int(raw)
        except ValueError:
            raise ValueError(
                f"{METRICS_PORT_ENV} must be an integer port, got {raw!r}"
            ) from None
    if port < 0 or port > 65535:
        raise ValueError(f"metrics port must be 0..65535, got {port}")
    return port


# -- readiness (the /readyz half of the health split) --------------------

_READINESS_LOCK = make_lock("obs.readiness")
_READINESS: dict = {}  # unit name -> zero-arg bool probe


def register_readiness(name: str, probe) -> None:
    """Register a zero-arg readiness probe under ``name`` (unit names —
    ModelServer registers its supervised unit).  Re-registering a name
    replaces its probe (restart idiom)."""
    with _READINESS_LOCK:
        _READINESS[str(name)] = probe


def unregister_readiness(name: str) -> None:
    with _READINESS_LOCK:
        _READINESS.pop(str(name), None)


def readyz() -> dict:
    """The readiness verdict ``/readyz`` serves: liveness (no DEAD
    supervised unit) AND every registered probe true.  A probe that
    raises counts as not-ready — a broken probe must fail closed, or a
    router would route cold traffic on an exception."""
    from ..resilience import supervisor as _supervisor

    hz = _supervisor.healthz()
    with _READINESS_LOCK:
        probes = dict(_READINESS)
    states: dict = {}
    not_ready: list = []
    for name in sorted(probes):
        try:
            ok = bool(probes[name]())
        except Exception:
            ok = False
        states[name] = ok
        if not ok:
            not_ready.append(name)
    return {
        "ok": bool(hz["ok"]) and not not_ready,
        "live": bool(hz["ok"]),
        "dead": hz["dead"],
        "not_ready": not_ready,
        "probes": states,
    }


# -- Prometheus text exposition ------------------------------------------

def _mangle(name: str) -> str:
    """Registry name -> Prometheus metric name (``[a-zA-Z_:][a-zA-Z0-9_:]*``)."""
    out = [c if (c.isascii() and (c.isalnum() or c in "_:")) else "_"
           for c in name]
    if out and out[0].isdigit():
        out.insert(0, "_")
    return "".join(out) or "_"


def _escape_label(value: str) -> str:
    """Prometheus label-value escaping: backslash, double-quote, and
    newline (the exposition format's three escapes)."""
    return (value.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _labels(*pairs) -> str:
    items = [f'{k}="{_escape_label(str(v))}"' for k, v in pairs if v != ""]
    return "{" + ",".join(items) + "}" if items else ""


def _fmt(v: float) -> str:
    if v != v:  # NaN (an empty histogram's quantile)
        return "NaN"
    return repr(float(v))


def prometheus_text(items=None) -> str:
    """The whole registry as Prometheus text exposition format (0.0.4).

    Counters/gauges map directly; histograms map to summaries (the
    registry's HDR quantiles ARE the p50/p95/p99 an SLO scraper wants)
    with ``_sum``/``_count`` companions.  One ``# TYPE`` line per
    metric family, families sorted, tags as a ``tag`` label."""
    if items is None:
        items = _registry().export_items()
    families: dict[str, list] = {}
    kinds: dict[str, str] = {}
    for name, tag, inst in items:
        m = _mangle(name)
        families.setdefault(m, []).append((tag, inst))
        kinds[m] = ("counter" if isinstance(inst, Counter)
                    else "gauge" if isinstance(inst, Gauge)
                    else "summary")
    lines: list[str] = []
    for m in sorted(families):
        kind = kinds[m]
        lines.append(f"# TYPE {m} {kind}")
        for tag, inst in families[m]:
            if kind in ("counter", "gauge"):
                lines.append(f"{m}{_labels(('tag', tag))} "
                             f"{_fmt(inst.value)}")
                continue
            # ONE snapshot per instrument: quantiles and sum/count come
            # from the same locked read, so a concurrent writer can
            # never produce a scrape whose count mismatches its
            # quantiles (and the O(buckets) quantile pass runs once)
            snap = inst.snapshot()
            for qlabel, qkey in _QUANTILES:
                lines.append(
                    f"{m}{_labels(('tag', tag), ('quantile', qlabel))} "
                    f"{_fmt(snap.get(qkey, float('nan')))}")
            lines.append(f"{m}_sum{_labels(('tag', tag))} "
                         f"{_fmt(snap.get('sum', 0.0))}")
            lines.append(f"{m}_count{_labels(('tag', tag))} "
                         f"{snap.get('count', 0)}")
    return "\n".join(lines) + "\n"


# -- the endpoint --------------------------------------------------------

class _Handler(BaseHTTPRequestHandler):
    server_version = "graftscope"
    # one-request-per-connection, deliberately: the endpoint is ONE
    # supervised thread (no anonymous handler pool to bless), so a
    # keep-alive client parked between scrape intervals would wedge
    # every other client AND stop()'s join.  HTTP/1.0 + an explicit
    # Connection: close makes the stdlib handler close after each
    # response; the socket timeout bounds a client that connects and
    # never speaks.
    protocol_version = "HTTP/1.0"
    timeout = 2.0

    def do_GET(self):  # noqa: N802 - stdlib handler contract
        owner: MetricsServer = self.server._dmlt_owner
        owner._beat()
        if self.path == "/metrics":
            body = prometheus_text().encode("utf-8")
            code = 200
            ctype = "text/plain; version=0.0.4; charset=utf-8"
        elif self.path == "/healthz":
            from ..resilience import supervisor as _supervisor

            verdict = _supervisor.healthz()
            body = json.dumps(verdict, sort_keys=True).encode("utf-8")
            code = 200 if verdict["ok"] else 503
            ctype = "application/json"
        elif self.path == "/readyz":
            verdict = readyz()
            body = json.dumps(verdict, sort_keys=True).encode("utf-8")
            code = 200 if verdict["ok"] else 503
            ctype = "application/json"
        else:
            body = b"graftscope: /metrics, /healthz or /readyz\n"
            code = 404
            ctype = "text/plain; charset=utf-8"
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.send_header("Connection", "close")
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *_args):  # scrapes must not spam stderr
        pass


class MetricsServer:
    """One bound endpoint + its serving thread (use :func:`start`)."""

    def __init__(self, port: int, host: str = "127.0.0.1"):
        self._server = HTTPServer((host, port), _Handler)
        self._server._dmlt_owner = self
        self.host = host
        self.port = int(self._server.server_address[1])  # 0 -> assigned
        self._hb = None
        # the endpoint thread only runs the stdlib serve loop; every
        # handler body above is host-only registry/supervisor reads.
        # The LITERAL name is what declares it host-only to graftlint's
        # thread-dispatch rule (_spmd.HOST_ONLY_THREAD_NAMES — the
        # serve_forever target is unresolvable to the static index) and
        # what graftsan's dispatch detector holds to that contract at
        # runtime; tests assert it equals METRICS_THREAD_NAME.
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="dask-ml-tpu-metrics",
        )

    def _start(self) -> "MetricsServer":
        from ..resilience import supervisor as _supervisor

        self._thread.start()
        self._hb = _supervisor.register(
            METRICS_THREAD_NAME, "obs", thread=self._thread)
        logger.info("graftscope metrics endpoint on %s:%d "
                    "(/metrics, /healthz, /readyz)", self.host, self.port)
        return self

    def _beat(self) -> None:
        from ..resilience import supervisor as _supervisor

        # a diagnostics.reset() wiped the unit table: re-register so
        # the endpoint stays supervised (reset must not orphan it)
        if _supervisor.lookup(METRICS_THREAD_NAME) is not self._hb:
            self._hb = _supervisor.register(
                METRICS_THREAD_NAME, "obs", thread=self._thread)
        self._hb.beat()

    def running(self) -> bool:
        return self._thread.is_alive()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5.0)
        if self._hb is not None:
            self._hb.retire()


_LOCK = make_lock("obs.metrics_server")
_ACTIVE: MetricsServer | None = None


def active() -> MetricsServer | None:
    """The running endpoint (None when off)."""
    return _ACTIVE


def start(port: int | None = None, host: str = "127.0.0.1") -> \
        MetricsServer | None:
    """Start the endpoint on ``port`` (default: the knob; None/'' =
    stay off and return None).  Idempotent while one is running —
    restarting on a different port requires :func:`stop` first."""
    global _ACTIVE
    resolved = resolve_port(port)
    if resolved is None:
        return None
    with _LOCK:
        if _ACTIVE is not None and _ACTIVE.running():
            return _ACTIVE
        _ACTIVE = MetricsServer(resolved, host=host)._start()
        return _ACTIVE


def stop() -> None:
    """Stop the endpoint (no-op when off)."""
    global _ACTIVE
    with _LOCK:
        srv, _ACTIVE = _ACTIVE, None
    if srv is not None:
        srv.stop()


def rearm() -> None:
    """Re-register a live endpoint's supervisor heartbeat (called by
    ``diagnostics.reset()`` right after the unit table is wiped, so a
    reset leaves the endpoint supervised, not orphaned)."""
    srv = _ACTIVE
    if srv is not None and srv.running():
        from ..resilience import supervisor as _supervisor

        if _supervisor.lookup(METRICS_THREAD_NAME) is None:
            srv._hb = _supervisor.register(
                METRICS_THREAD_NAME, "obs", thread=srv._thread)


def start_from_env() -> MetricsServer | None:
    """The import-time arming path: strict knob parse (a typo'd value
    raises), fail-soft bind (a taken port warns and continues — the
    fit matters more than its scrape)."""
    port = resolve_port()
    if port is None:
        return None
    try:
        return start(port)
    except OSError as e:
        logger.warning(
            "graftscope: %s=%s could not bind (%s); continuing without "
            "a metrics endpoint", METRICS_PORT_ENV, port, e)
        return None

"""Roofline accounting: per-program FLOP/byte attribution vs peaks.

graftscope (:mod:`.scope`) measures per-program device *time*; this
module supplies the other two axes the ROADMAP ``[speed]`` lane needs —
**work** (FLOPs, bytes moved) and **capability** (the platform's peak
FLOP/s and bytes/s) — so "Lloyd runs at 2% of roofline" becomes a
measured, per-program, CI-ratchetable quantity instead of a hand
estimate next to a bench table.

Work comes from XLA itself: at compile time the program cache
(:mod:`dask_ml_tpu.programs.cache`) calls :func:`capture_cost` on each
freshly built executable — ``compiled.cost_analysis()``, XLA's own
static estimate of flops and bytes accessed — and hands the numbers to
every subsequent dispatch's in-flight interval.  The scope sampler then
accumulates ``device.flops``/``device.bytes`` per program in the
metrics registry (scraped by ``/metrics``) and
:func:`~.scope.device_report` joins work with measured busy time into
achieved FLOP/s, achieved bytes/s, arithmetic intensity, and a roofline
fraction against the peak table below.

Honesty contract (design.md §16):

* ``cost_analysis`` is XLA's **static estimate** of one dispatch: a
  fused ``while_loop`` program (the Lloyd loop) counts its body ONCE —
  the trip count is data-dependent — so attributed flops for such
  programs are a lower bound and the roofline fraction is a *floor*,
  not a measurement of the loop body.  Straight-line step programs
  (the streamed SGD/MBK/serve hot loops) have no such slack.
* The peak table is labelled by provenance: ``measured`` entries were
  timed on the image this repo gates on, ``assumed`` entries are
  datasheet numbers never verified on this backend, ``env`` entries
  came from the operator's :data:`PEAKS_ENV` knob.  An unknown platform
  yields no peaks and no roofline fraction — never a made-up one.
* On a relayed backend (the axon TPU tunnel) busy time can under-read
  (scope.py honesty note), which would OVER-state achieved rates; the
  XProf device trace stays the authority there.

Pure host stdlib — no jax import (the obs posture).  The platform is
NOTED by the program cache's compile path (:func:`note_platform`, on a
thread that is already compiling) rather than probed here: the scope
sampler and the metrics endpoint read it as a plain string, so they
stay provably host-only for the thread-dispatch analysis.
"""

from __future__ import annotations

import os
import threading

from .._locks import make_lock

__all__ = [
    "PEAKS_ENV",
    "DEFAULT_PEAKS",
    "parse_peaks",
    "peaks_for",
    "try_peaks_for",
    "note_platform",
    "detected_platform",
    "capture_cost",
    "attribution",
    "reset_cache",
]

#: policy knob: override/extend the per-platform peak table.  Format is
#: ``platform:flops=<float>,bytes=<float>[;platform:...]`` — e.g.
#: ``cpu:flops=1.4e11,bytes=2.6e10;tpu:flops=4.9e13,bytes=8.19e11``.
#: Strict parse (the repo's knob posture): a malformed value raises at
#: first use instead of silently reading as defaults.
PEAKS_ENV = "DASK_ML_TPU_PEAKS"

#: per-platform peak capability, labelled by provenance.  The ``cpu``
#: row was MEASURED on this image's 2-core gate box (best-of numpy fp32
#: gemm for flops, best-of 64 MiB memcpy read+write for bytes,
#: 2026-08-04 — the procedure is reproduced in design.md §16); the
#: ``tpu`` row is the v5e datasheet (819 GB/s HBM, 49 fp32 TFLOP/s —
#: the same numbers bench.py's MFU columns assume) and stays flagged
#: ``assumed`` until a chip round measures it.
DEFAULT_PEAKS = {
    "cpu": {"flops_per_s": 1.4e11, "bytes_per_s": 2.6e10,
            "source": "measured (gate box: numpy fp32 gemm + memcpy, "
                      "2026-08-04)"},
    "tpu": {"flops_per_s": 4.9e13, "bytes_per_s": 8.19e11,
            "source": "assumed (v5e datasheet: 49 fp32 TFLOP/s, "
                      "819 GB/s HBM; unmeasured on this image)"},
}

_LOCK = make_lock("obs.roofline")
_CACHE: dict | None = None  # parsed env + defaults, resolved once


def parse_peaks(raw: str) -> dict:
    """Parse the :data:`PEAKS_ENV` format into ``{platform: {flops_per_s,
    bytes_per_s, source}}``.  Strict: unknown keys, missing fields, and
    non-positive numbers raise ``ValueError``."""
    out: dict = {}
    for part in raw.split(";"):
        part = part.strip()
        if not part:
            continue
        plat, sep, body = part.partition(":")
        plat = plat.strip().lower()
        if not sep or not plat:
            raise ValueError(
                f"{PEAKS_ENV}: expected 'platform:flops=...,bytes=...', "
                f"got {part!r}")
        entry: dict = {}
        for item in body.split(","):
            key, sep2, val = item.partition("=")
            key = key.strip().lower()
            if not sep2 or key not in ("flops", "bytes"):
                raise ValueError(
                    f"{PEAKS_ENV}: expected flops=<v>/bytes=<v>, got "
                    f"{item.strip()!r}")
            try:
                fv = float(val)
            except ValueError:
                raise ValueError(
                    f"{PEAKS_ENV}: {key} must be a number, got {val!r}"
                ) from None
            if fv <= 0:
                raise ValueError(f"{PEAKS_ENV}: {key} must be > 0")
            entry[f"{key}_per_s"] = fv
        if set(entry) != {"flops_per_s", "bytes_per_s"}:
            raise ValueError(
                f"{PEAKS_ENV}: platform {plat!r} needs BOTH flops= and "
                f"bytes=")
        entry["source"] = "env"
        out[plat] = entry
    return out


def _table() -> dict:
    global _CACHE
    with _LOCK:
        if _CACHE is None:
            table = {k: dict(v) for k, v in DEFAULT_PEAKS.items()}
            raw = os.environ.get(PEAKS_ENV, "").strip()
            if raw:
                table.update(parse_peaks(raw))
            _CACHE = table
        return _CACHE


def try_peaks_for(platform: str | None) -> dict | None:
    """:func:`peaks_for` for the accounting hot paths (the scope
    sampler's sweep): a malformed :data:`PEAKS_ENV` returns None (one
    warning) instead of raising — the strict parse must surface on the
    loud reporting surfaces (``device_report``, the bench, the perf
    ratchet), never kill the daemon sampler or abort a fit from inside
    dispatch-time accounting."""
    try:
        return peaks_for(platform)
    except ValueError as e:
        global _WARNED
        if not _WARNED:
            _WARNED = True
            import logging

            logging.getLogger(__name__).warning(
                "roofline peaks unavailable on the accounting path "
                "(%s); roofline fractions will be absent until the "
                "knob is fixed", e)
        return None


_WARNED = False


def peaks_for(platform: str | None) -> dict | None:
    """Peak capability for ``platform`` (``{"flops_per_s", "bytes_per_s",
    "source"}``), or None for an unknown/undetected platform — the
    honest answer, never a made-up peak.  Returns a copy: the entries
    end up embedded in reports callers may mutate, and a shared cache
    dict must not be corruptible from outside."""
    if not platform:
        return None
    entry = _table().get(str(platform).lower())
    return None if entry is None else dict(entry)


_PLATFORM: str | None = None


def note_platform(platform) -> None:
    """Record the backend platform (called by the program cache right
    after a compile, on a thread that is already device-blessed — this
    module must never touch jax itself)."""
    global _PLATFORM
    if platform:
        _PLATFORM = str(platform).lower()


def detected_platform() -> str | None:
    """The platform the program cache last compiled on, or None before
    any cached compile — when nothing has compiled there is nothing to
    attribute, and an unknown platform honestly has no peaks."""
    return _PLATFORM


def reset_cache() -> None:
    """Forget the resolved peak table (test isolation: the next read
    re-applies :data:`PEAKS_ENV`; the noted platform survives — it is
    a fact about the process, not a policy)."""
    global _CACHE, _WARNED
    with _LOCK:
        _CACHE = None
        _WARNED = False


# -- compile-time cost capture -------------------------------------------

def capture_cost(compiled) -> dict | None:
    """``{"flops": f, "bytes": b, "out_bytes": o}`` from an XLA
    executable's ``cost_analysis()``, or None when the backend cannot
    say (relayed executables, exotic programs).  Fail-soft by contract:
    cost capture must never be able to break a compile."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    flops = ca.get("flops", 0.0)
    bytes_ = ca.get("bytes accessed", 0.0)
    out_b = ca.get("bytes accessedout{}", 0.0)
    try:
        flops, bytes_, out_b = float(flops), float(bytes_), float(out_b)
    except (TypeError, ValueError):
        return None
    if flops < 0 or bytes_ < 0:  # XLA's "unknown" sentinel
        return None
    return {"flops": flops, "bytes": bytes_, "out_bytes": max(out_b, 0.0)}


# -- the join ------------------------------------------------------------

def attribution(flops: float, bytes_: float, busy_s: float,
                peaks: dict | None) -> dict:
    """Achieved rates + roofline fraction for one program's accumulated
    (flops, bytes, busy seconds).

    The roofline bound at the program's arithmetic intensity ``I =
    flops/bytes`` is ``min(peak_flops, I * peak_bytes)``; the fraction
    is achieved FLOP/s over that bound — i.e. "how close to the best
    this machine could possibly do for a program of this intensity".  A
    zero-flop program (pure data movement) is scored on bandwidth
    alone.  Without peaks the rates still report; the fraction is None.
    """
    out: dict = {
        "flops": round(flops, 1),
        "bytes": round(bytes_, 1),
        "achieved_flops_per_s": (round(flops / busy_s, 1)
                                 if busy_s > 0 else 0.0),
        "achieved_bytes_per_s": (round(bytes_ / busy_s, 1)
                                 if busy_s > 0 else 0.0),
        "intensity": round(flops / bytes_, 4) if bytes_ > 0 else None,
        "roofline_frac": None,
    }
    if peaks is None or busy_s <= 0:
        return out
    pf, pb = peaks["flops_per_s"], peaks["bytes_per_s"]
    if flops > 0 and bytes_ > 0:
        bound = min(pf, (flops / bytes_) * pb)
        out["roofline_frac"] = round((flops / busy_s) / bound, 6)
    elif bytes_ > 0:
        out["roofline_frac"] = round((bytes_ / busy_s) / pb, 6)
    elif flops > 0:
        out["roofline_frac"] = round((flops / busy_s) / pf, 6)
    return out

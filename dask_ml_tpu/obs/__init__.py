"""grafttrace: unified structured tracing, metrics, and flight recorder.

The one event spine every runtime layer reports through (docs/design.md
§11).  Four pieces:

* :mod:`.spans` — the span tree (``obs.span("fit")`` → rounds → blocks
  → parse/stage/compute children) in lock-free per-thread rings, with
  worker-thread stitching (``adopt``) and async-safe detached spans;
* :mod:`.metrics` — the counters/gauges/HDR-histogram registry
  (``pipeline.stall_s``, ``resilience.retry``, ``compile.count``) that
  ``PipelineStats``, ``FaultStats``, and graftsan publish into — the
  old reporters keep their shapes as views;
* :mod:`.export` — schema-versioned JSONL streaming
  (``DASK_ML_TPU_TRACE=path``) and Chrome/Perfetto ``trace_event``
  export, so a streamed fit's host-side overlap renders next to an
  XProf device trace;
* :mod:`.flight` — the always-on last-N-events post-mortem ring dumped
  by the conftest watchdog and the preemption/fault paths.

Everything importable from here is pure-stdlib host code (no jax) —
safe in any thread including the prefetch worker; the jax compile
listener lives in :mod:`.jaxhooks` and is installed lazily by
:func:`enable` / :func:`install_jax_hooks`.
"""

from __future__ import annotations

from .metrics import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    metrics_snapshot,
    registry,
    reset_metrics,
)
from .spans import (  # noqa: F401
    RING_ENV,
    SCHEMA_VERSION,
    TRACE_ENV,
    Span,
    SpanRecord,
    adopt,
    clear_spans,
    current_span_id,
    disable,
    enable,
    enabled,
    event,
    fmt_exc,
    last_root,
    open_span_paths,
    span,
    span_records,
    span_tree,
)
from .export import (  # noqa: F401
    export_perfetto,
    perfetto_trace,
    read_jsonl,
)
from . import flight  # noqa: F401
from .flight import (  # noqa: F401
    dump as flight_dump,
    post_mortem as flight_post_mortem,
    tail as flight_tail,
)

__all__ = [
    # spans
    "SCHEMA_VERSION", "TRACE_ENV", "RING_ENV",
    "span", "event", "fmt_exc", "adopt", "current_span_id",
    "enable", "disable", "enabled",
    "open_span_paths", "last_root", "span_records", "span_tree",
    "clear_spans", "Span", "SpanRecord",
    # metrics
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "registry", "metrics_snapshot", "reset_metrics",
    # export
    "export_perfetto", "perfetto_trace", "read_jsonl",
    # flight
    "flight", "flight_dump", "flight_post_mortem", "flight_tail",
    # lifecycle
    "install_jax_hooks", "reset_all",
]


def install_jax_hooks() -> None:
    """Arm the compile-event registry listener without enabling span
    recording (bench processes that only want counters)."""
    from . import jaxhooks

    jaxhooks.install()


def reset_all() -> None:
    """Zero the whole spine: metrics registry, span rings + last root,
    and the flight recorder.  ``diagnostics.reset()`` is the public
    one-call form (it also clears the legacy reporters' residue)."""
    reset_metrics()
    clear_spans()
    flight.clear()

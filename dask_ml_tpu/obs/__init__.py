"""grafttrace: unified structured tracing, metrics, and flight recorder.

The one event spine every runtime layer reports through (docs/design.md
§11).  Four pieces:

* :mod:`.spans` — the span tree (``obs.span("fit")`` → rounds → blocks
  → parse/stage/compute children) in lock-free per-thread rings, with
  worker-thread stitching (``adopt``) and async-safe detached spans;
* :mod:`.metrics` — the counters/gauges/HDR-histogram registry
  (``pipeline.stall_s``, ``resilience.retry``, ``compile.count``) that
  ``PipelineStats``, ``FaultStats``, and graftsan publish into — the
  old reporters keep their shapes as views;
* :mod:`.export` — schema-versioned JSONL streaming
  (``DASK_ML_TPU_TRACE=path``) and Chrome/Perfetto ``trace_event``
  export, so a streamed fit's host-side overlap renders next to an
  XProf device trace;
* :mod:`.flight` — the always-on last-N-events post-mortem ring dumped
  by the conftest watchdog and the preemption/fault paths;
* :mod:`.scope` — graftscope device-time accounting: per-program
  in-flight intervals from the dispatch choke points, the
  utilization/idle-gap report (``run_report()["device"]``), and the
  Perfetto device lane;
* :mod:`.serve` — the live Prometheus ``/metrics`` + ``/healthz``
  endpoint (``DASK_ML_TPU_METRICS_PORT``), supervised like the
  compile-ahead thread.

Everything importable from here is pure-stdlib host code (no jax) —
safe in any thread including the prefetch worker; the jax compile
listener lives in :mod:`.jaxhooks` and is installed lazily by
:func:`enable` / :func:`install_jax_hooks`.
"""

from __future__ import annotations

from .metrics import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    metrics_snapshot,
    registry,
    reset_metrics,
)
from .spans import (  # noqa: F401
    RING_ENV,
    SCHEMA_VERSION,
    TRACE_ENV,
    Span,
    SpanRecord,
    adopt,
    clear_spans,
    current_span_id,
    disable,
    enable,
    enabled,
    event,
    fmt_exc,
    last_root,
    open_span_paths,
    record_span,
    span,
    span_records,
    span_tree,
)
from .export import (  # noqa: F401
    export_perfetto,
    perfetto_trace,
    read_jsonl,
)
from . import flight  # noqa: F401
from .flight import (  # noqa: F401
    dump as flight_dump,
    post_mortem as flight_post_mortem,
    tail as flight_tail,
)
from . import roofline  # noqa: F401
from . import scope  # noqa: F401
from .scope import device_report  # noqa: F401
from . import serve  # noqa: F401
from .serve import prometheus_text  # noqa: F401
from . import critical  # noqa: F401
from .critical import critical_path, serve_critical  # noqa: F401

__all__ = [
    # spans
    "SCHEMA_VERSION", "TRACE_ENV", "RING_ENV",
    "span", "record_span", "event", "fmt_exc", "adopt",
    "current_span_id",
    "enable", "disable", "enabled",
    "open_span_paths", "last_root", "span_records", "span_tree",
    "clear_spans", "Span", "SpanRecord",
    # metrics
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "registry", "metrics_snapshot", "reset_metrics",
    # export
    "export_perfetto", "perfetto_trace", "read_jsonl",
    # flight
    "flight", "flight_dump", "flight_post_mortem", "flight_tail",
    # graftscope: device-time accounting + roofline + scrape endpoint
    "scope", "roofline", "device_report", "serve", "prometheus_text",
    # graftpath: the causal critical-path engine (design.md §19)
    "critical", "critical_path", "serve_critical",
    # lifecycle
    "install_jax_hooks", "reset_all",
]


def install_jax_hooks() -> None:
    """Arm the compile-event registry listener without enabling span
    recording (bench processes that only want counters)."""
    from . import jaxhooks

    jaxhooks.install()


def reset_all() -> None:
    """Zero the whole spine: metrics registry, span rings + last root,
    the flight recorder, the graftscope device timeline, and the
    graftpath last-verdict join.  ``diagnostics.reset()`` is the public
    one-call form (it also clears the legacy reporters' residue and
    re-registers the live metrics-endpoint/sampler heartbeats)."""
    reset_metrics()
    clear_spans()
    flight.clear()
    scope.reset()
    critical.reset()


# graftscope endpoint env arming (DASK_ML_TPU_METRICS_PORT): a set port
# starts the scrape surface at import, same posture as DASK_ML_TPU_TRACE
# above — strict knob parse (a typo raises), fail-soft bind (a taken
# port warns; the fit matters more than its scrape).
serve.start_from_env()

"""Regression metrics (reference: ``dask_ml/metrics/regression.py``)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .classification import _align, _apply_weight


def mean_squared_error(y_true, y_pred, sample_weight=None, squared: bool = True, compute=True):
    t, p, mask = _align(y_true, y_pred)
    w = _apply_weight(mask, sample_weight)
    if t.ndim > 1 or p.ndim > 1:
        per = jnp.mean((t.reshape(t.shape[0], -1) - p.reshape(p.shape[0], -1)) ** 2, axis=1)
    else:
        per = (t - p) ** 2
    out = jnp.sum(per * w) / jnp.sum(w)
    if not squared:
        out = jnp.sqrt(out)
    return float(out) if compute else out


def mean_absolute_error(y_true, y_pred, sample_weight=None, compute=True):
    t, p, mask = _align(y_true, y_pred)
    w = _apply_weight(mask, sample_weight)
    if t.ndim > 1 or p.ndim > 1:
        per = jnp.mean(jnp.abs(t.reshape(t.shape[0], -1) - p.reshape(p.shape[0], -1)), axis=1)
    else:
        per = jnp.abs(t - p)
    return float(jnp.sum(per * w) / jnp.sum(w)) if compute else jnp.sum(per * w) / jnp.sum(w)


def mean_squared_log_error(y_true, y_pred, sample_weight=None, compute=True):
    t, p, mask = _align(y_true, y_pred)
    w = _apply_weight(mask, sample_weight)
    per = (jnp.log1p(t) - jnp.log1p(p)) ** 2
    return float(jnp.sum(per * w) / jnp.sum(w)) if compute else jnp.sum(per * w) / jnp.sum(w)


def r2_score(y_true, y_pred, sample_weight=None, compute=True):
    t, p, mask = _align(y_true, y_pred)
    w = _apply_weight(mask, sample_weight)
    wsum = jnp.sum(w)
    mean_t = jnp.sum(t * w) / wsum
    ss_res = jnp.sum((t - p) ** 2 * w)
    ss_tot = jnp.sum((t - mean_t) ** 2 * w)
    # Constant y_true: sklearn defines 1.0 for a perfect fit, else 0.0.
    eps = jnp.finfo(ss_tot.dtype).tiny
    out = jnp.where(
        ss_tot > eps,
        1.0 - ss_res / jnp.where(ss_tot > eps, ss_tot, 1.0),
        jnp.where(ss_res > eps, 0.0, 1.0),
    )
    return float(out) if compute else out


def _as_2d(a):
    return a.reshape(a.shape[0], -1) if a.ndim > 1 else a[:, None]


def mean_absolute_percentage_error(y_true, y_pred, sample_weight=None,
                                   compute=True):
    """|y - p| / max(|y|, eps), averaged (sklearn semantics: eps is
    FLOAT64's machine epsilon — exactly representable in f32 — so zero
    targets blow up identically to sklearn; 2D inputs take the uniform
    average over outputs like the sibling mse/mae)."""
    t, p, mask = _align(y_true, y_pred)
    w = _apply_weight(mask, sample_weight)
    eps = float(np.finfo(np.float64).eps)
    ape = jnp.abs(_as_2d(t) - _as_2d(p)) / jnp.maximum(
        jnp.abs(_as_2d(t)), eps
    )
    per = jnp.mean(ape, axis=1)
    out = jnp.sum(per * w) / jnp.sum(w)
    return float(out) if compute else out


def median_absolute_error(y_true, y_pred, sample_weight=None, compute=True):
    """Median |y - p| over REAL rows (pad rows pushed past the median via
    an inf sentinel); 2D inputs average the per-output medians (sklearn's
    uniform_average)."""
    t, p, mask = _align(y_true, y_pred)
    if sample_weight is not None:
        raise NotImplementedError(
            "median_absolute_error does not support sample_weight "
            "(sklearn computes a weighted percentile; open an issue if "
            "needed)"
        )
    err = jnp.abs(_as_2d(t) - _as_2d(p))
    err = jnp.where(mask[:, None] > 0, err, jnp.inf)  # pads sort last
    n_real = jnp.sum(mask > 0)
    s = jnp.sort(err, axis=0)
    hi_idx = n_real // 2
    lo_idx = jnp.maximum((n_real - 1) // 2, 0)
    out = jnp.mean((s[lo_idx] + s[hi_idx]) / 2.0)
    return float(out) if compute else out


def explained_variance_score(y_true, y_pred, sample_weight=None,
                             compute=True):
    """1 - Var[y - p] / Var[y] per output, uniform-averaged (sklearn
    semantics, weighted variances)."""
    t, p, mask = _align(y_true, y_pred)
    w = _apply_weight(mask, sample_weight)[:, None]
    td, pd = _as_2d(t), _as_2d(p)
    wsum = jnp.sum(w)
    resid = td - pd
    mean_r = jnp.sum(resid * w, axis=0) / wsum
    var_r = jnp.sum((resid - mean_r) ** 2 * w, axis=0) / wsum
    mean_t = jnp.sum(td * w, axis=0) / wsum
    var_t = jnp.sum((td - mean_t) ** 2 * w, axis=0) / wsum
    eps = jnp.finfo(var_t.dtype).tiny
    per_output = jnp.where(
        var_t > eps,
        1.0 - var_r / jnp.where(var_t > eps, var_t, 1.0),
        jnp.where(var_r > eps, 0.0, 1.0),
    )
    out = jnp.mean(per_output)
    return float(out) if compute else out

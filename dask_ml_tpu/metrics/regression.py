"""Regression metrics (reference: ``dask_ml/metrics/regression.py``)."""

from __future__ import annotations

import jax.numpy as jnp

from .classification import _align, _apply_weight


def mean_squared_error(y_true, y_pred, sample_weight=None, squared: bool = True, compute=True):
    t, p, mask = _align(y_true, y_pred)
    w = _apply_weight(mask, sample_weight)
    if t.ndim > 1 or p.ndim > 1:
        per = jnp.mean((t.reshape(t.shape[0], -1) - p.reshape(p.shape[0], -1)) ** 2, axis=1)
    else:
        per = (t - p) ** 2
    out = jnp.sum(per * w) / jnp.sum(w)
    if not squared:
        out = jnp.sqrt(out)
    return float(out) if compute else out


def mean_absolute_error(y_true, y_pred, sample_weight=None, compute=True):
    t, p, mask = _align(y_true, y_pred)
    w = _apply_weight(mask, sample_weight)
    if t.ndim > 1 or p.ndim > 1:
        per = jnp.mean(jnp.abs(t.reshape(t.shape[0], -1) - p.reshape(p.shape[0], -1)), axis=1)
    else:
        per = jnp.abs(t - p)
    return float(jnp.sum(per * w) / jnp.sum(w)) if compute else jnp.sum(per * w) / jnp.sum(w)


def mean_squared_log_error(y_true, y_pred, sample_weight=None, compute=True):
    t, p, mask = _align(y_true, y_pred)
    w = _apply_weight(mask, sample_weight)
    per = (jnp.log1p(t) - jnp.log1p(p)) ** 2
    return float(jnp.sum(per * w) / jnp.sum(w)) if compute else jnp.sum(per * w) / jnp.sum(w)


def r2_score(y_true, y_pred, sample_weight=None, compute=True):
    t, p, mask = _align(y_true, y_pred)
    w = _apply_weight(mask, sample_weight)
    wsum = jnp.sum(w)
    mean_t = jnp.sum(t * w) / wsum
    ss_res = jnp.sum((t - p) ** 2 * w)
    ss_tot = jnp.sum((t - mean_t) ** 2 * w)
    # Constant y_true: sklearn defines 1.0 for a perfect fit, else 0.0.
    eps = jnp.finfo(ss_tot.dtype).tiny
    out = jnp.where(
        ss_tot > eps,
        1.0 - ss_res / jnp.where(ss_tot > eps, ss_tot, 1.0),
        jnp.where(ss_res > eps, 0.0, 1.0),
    )
    return float(out) if compute else out

"""Scorer registry (reference: ``dask_ml/metrics/scorer.py`` — ``get_scorer``,
``check_scoring``, ``SCORERS``), sklearn-compatible signatures.
"""

from __future__ import annotations

from functools import partial

from .classification import accuracy_score, balanced_accuracy_score, f1_score, log_loss, precision_score, recall_score, roc_auc_score
from .regression import mean_absolute_error, mean_squared_error, r2_score


def _passthrough_scorer(estimator, X, y=None, **kwargs):
    return estimator.score(X, y, **kwargs)


def make_scorer(score_func, greater_is_better: bool = True, **kwargs):
    sign = 1.0 if greater_is_better else -1.0

    def scorer(estimator, X, y):
        y_pred = estimator.predict(X)
        return sign * score_func(y, y_pred, **kwargs)

    scorer._score_func = score_func
    scorer._sign = sign
    return scorer


def _neg_log_loss_scorer(estimator, X, y):
    proba = estimator.predict_proba(X)
    return -log_loss(y, proba)


def _roc_auc_scorer(estimator, X, y):
    if hasattr(estimator, "decision_function"):
        s = estimator.decision_function(X)
    else:
        s = estimator.predict_proba(X)[:, 1]
    return roc_auc_score(y, s)


SCORERS = {
    "accuracy": make_scorer(accuracy_score),
    "f1": make_scorer(f1_score),
    "f1_macro": make_scorer(partial(f1_score, average="macro")),
    "f1_micro": make_scorer(partial(f1_score, average="micro")),
    "f1_weighted": make_scorer(partial(f1_score, average="weighted")),
    "precision": make_scorer(precision_score),
    "precision_macro": make_scorer(partial(precision_score, average="macro")),
    "recall": make_scorer(recall_score),
    "recall_macro": make_scorer(partial(recall_score, average="macro")),
    "roc_auc": _roc_auc_scorer,
    "balanced_accuracy": make_scorer(balanced_accuracy_score),
    "neg_mean_squared_error": make_scorer(mean_squared_error, greater_is_better=False),
    "neg_root_mean_squared_error": make_scorer(
        partial(mean_squared_error, squared=False), greater_is_better=False
    ),
    "neg_mean_absolute_error": make_scorer(mean_absolute_error, greater_is_better=False),
    "r2": make_scorer(r2_score),
    "neg_log_loss": _neg_log_loss_scorer,
}


def get_scorer(scoring):
    """Resolve a scoring name or callable to a scorer(estimator, X, y)."""
    if callable(scoring):
        return scoring
    try:
        return SCORERS[scoring]
    except KeyError:
        raise ValueError(
            f"{scoring!r} is not a valid scoring value. Valid options: {sorted(SCORERS)}"
        )


def check_scoring(estimator, scoring=None):
    if scoring is None:
        if hasattr(estimator, "score"):
            return _passthrough_scorer
        raise TypeError(f"{estimator!r} has no score method; pass scoring explicitly")
    return get_scorer(scoring)

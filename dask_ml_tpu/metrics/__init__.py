"""Metrics — twin of ``dask_ml/metrics/`` (SURVEY.md §2 component #12).

Lazy dask reductions become jitted masked reductions; blockwise pairwise
distances become sharded gemms on the MXU.
"""

from .pairwise import (  # noqa: F401
    euclidean_distances,
    pairwise_distances,
    pairwise_distances_argmin_min,
    linear_kernel,
    polynomial_kernel,
    rbf_kernel,
    sigmoid_kernel,
    PAIRWISE_KERNEL_FUNCTIONS,
)
from .classification import (  # noqa: F401
    accuracy_score,
    balanced_accuracy_score,
    confusion_matrix,
    f1_score,
    log_loss,
    precision_score,
    recall_score,
    roc_auc_score,
)
from .regression import (  # noqa: F401
    explained_variance_score,
    mean_absolute_error,
    mean_absolute_percentage_error,
    mean_squared_error,
    mean_squared_log_error,
    median_absolute_error,
    r2_score,
)
from .scorer import SCORERS, check_scoring, get_scorer  # noqa: F401

__all__ = [
    "euclidean_distances",
    "pairwise_distances",
    "pairwise_distances_argmin_min",
    "linear_kernel",
    "polynomial_kernel",
    "rbf_kernel",
    "sigmoid_kernel",
    "PAIRWISE_KERNEL_FUNCTIONS",
    "accuracy_score",
    "balanced_accuracy_score",
    "confusion_matrix",
    "f1_score",
    "precision_score",
    "recall_score",
    "roc_auc_score",
    "log_loss",
    "mean_absolute_error",
    "mean_squared_error",
    "mean_squared_log_error",
    "r2_score",
    "explained_variance_score",
    "mean_absolute_percentage_error",
    "median_absolute_error",
    "SCORERS",
    "check_scoring",
    "get_scorer",
]

"""Classification metrics (reference: ``dask_ml/metrics/classification.py``).

Each metric is a single masked reduction over the sharded sample axis; with
sharded inputs XLA inserts the cross-device psum.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.sharded import ShardedRows


def _lengths(a):
    if isinstance(a, ShardedRows):
        return a.n_samples, a.padded
    n = len(a) if not hasattr(a, "shape") else a.shape[0]  # lists welcome
    return n, n


def _align(y_true, y_pred):
    """Return (true, pred, mask) as padded device arrays of equal length.

    Mixed sharded/plain inputs of the same logical length are aligned by
    zero-padding the plain side up to the sharded side's padded length (the
    padded tail is masked out anyway).
    """
    n_t, pad_t = _lengths(y_true)
    n_p, pad_p = _lengths(y_pred)
    if n_t != n_p:
        raise ValueError(
            f"y_true and y_pred have different lengths: {n_t} vs {n_p}"
        )
    padded = max(pad_t, pad_p)

    def to_padded(a):
        x = a.data if isinstance(a, ShardedRows) else jnp.asarray(a)
        if x.shape[0] < padded:
            x = jnp.pad(x, [(0, padded - x.shape[0])] + [(0, 0)] * (x.ndim - 1))
        return x

    if isinstance(y_true, ShardedRows) and pad_t == padded:
        mask = y_true.mask
    elif isinstance(y_pred, ShardedRows) and pad_p == padded:
        mask = y_pred.mask
    else:
        mask = jnp.ones(padded, dtype=jnp.float32)
    return to_padded(y_true), to_padded(y_pred), mask


def _apply_weight(mask, sample_weight):
    if sample_weight is None:
        return mask
    w = sample_weight.data if isinstance(sample_weight, ShardedRows) else jnp.asarray(sample_weight)
    if w.shape[0] < mask.shape[0]:
        # host-side weights for a padded device array: pad with zeros
        w = jnp.pad(w, (0, mask.shape[0] - w.shape[0]))
    elif w.shape[0] > mask.shape[0]:
        # sharded (padded) weights for plain arrays: padded tail is zeros
        w = w[: mask.shape[0]]
    return mask * w


def accuracy_score(y_true, y_pred, normalize: bool = True, sample_weight=None, compute=True):
    """Fraction (or count) of correct predictions."""
    t, p, mask = _align(y_true, y_pred)
    w = _apply_weight(mask, sample_weight)
    correct = (t == p).astype(jnp.float32)
    hits = jnp.sum(correct * w)
    result = hits / jnp.sum(w) if normalize else hits
    return float(result) if compute else result


def log_loss(y_true, y_pred, eps="auto", normalize: bool = True, sample_weight=None, labels=None):
    """Negative log-likelihood of a classifier's probabilistic predictions.

    ``y_pred`` may be (n, k) probabilities or (n,) positive-class probability.
    ``eps="auto"`` clips at the INPUT's machine epsilon (sklearn semantics:
    a float64 probability of 0 contributes log(2.2e-16), not log(1e-15) —
    the clip level, not the log arithmetic, is what parity depends on).
    """
    if eps == "auto":
        # read the dtype WITHOUT materializing device data on host
        # (np.asarray of a jax array transfers; of a ShardedRows it makes
        # an object scalar); f32 inputs need f32's eps or the upper clip
        # 1-eps rounds back to 1.0 and log(1-p) overflows to -inf
        in_dtype = getattr(y_pred, "dtype", None)
        if in_dtype is None:
            in_dtype = np.asarray(y_pred).dtype
        # jnp.finfo: recognizes ml_dtypes floats (bfloat16) that
        # np.issubdtype rejects — falling back to float64 eps for bf16
        # would clip above bf16 resolution and let p==1.0 reach log(0)
        if jnp.issubdtype(in_dtype, jnp.floating):
            eps = float(jnp.finfo(in_dtype).eps)
        else:
            eps = float(np.finfo(np.float64).eps)
    t, p, mask = _align(y_true, y_pred)
    w = _apply_weight(mask, sample_weight)
    p = jnp.clip(p, eps, 1.0 - eps)
    if p.ndim == 1:
        per = -(t * jnp.log(p) + (1.0 - t) * jnp.log(1.0 - p))
    else:
        n_classes = p.shape[1]
        if labels is not None:
            labels = np.sort(np.asarray(labels))
            t_host = np.asarray(t).astype(np.int64)
            unseen = np.setdiff1d(np.unique(t_host), labels)
            if unseen.size:
                raise ValueError(
                    f"y_true contains labels not in `labels`: {unseen.tolist()}"
                )
            t = jnp.asarray(np.searchsorted(labels, t_host))
        onehot = jax.nn.one_hot(t.astype(jnp.int32), n_classes, dtype=p.dtype)
        p = p / jnp.sum(p, axis=1, keepdims=True)
        per = -jnp.sum(onehot * jnp.log(p), axis=1)
    total = jnp.sum(per * w)
    return float(total / jnp.sum(w)) if normalize else float(total)


def _class_inventory(t, p, mask, labels):
    """Sorted class values for P/R/F: from ``labels`` if given, else the
    union of true+predicted REAL values discovered on device (only the
    unique values cross to host)."""
    if labels is not None:
        # CALLER's order is the output order for average=None (sklearn
        # contract) — do not sort
        return np.asarray(labels)
    fill = t[0]
    tv = jnp.where(mask > 0, t, fill)
    pv = jnp.where(mask > 0, p, fill)
    return np.union1d(np.asarray(jnp.unique(tv)), np.asarray(jnp.unique(pv)))


def _indicator_matrices(y_true, y_pred, sample_weight, labels):
    """Shared preamble of the count-based metrics: class inventory and
    the per-class one-hot indicators, plus the per-row weights."""
    t, p, mask = _align(y_true, y_pred)
    w = _apply_weight(mask, sample_weight)
    classes = _class_inventory(t, p, mask, labels)
    cd = jnp.asarray(classes, t.dtype)
    t1 = (t[:, None] == cd[None, :]).astype(jnp.float32)
    p1 = (p[:, None] == cd[None, :]).astype(jnp.float32)
    return classes, t1, p1, w


_COUNT_CHUNK = 1 << 22  # rows per f32 device partial sum: keeps every
# per-chunk count below 2^24, where f32 accumulation saturates

_AUC_BLOCK = 1 << 20  # roc_auc two-level prefix sum: within-block f32
# cumsums stay far below the 2^24 saturation point; block bases
# accumulate in float64 on host (tests shrink this to hit multi-block)


def _prf_counts(y_true, y_pred, sample_weight, labels):
    """Per-class (tp, pred_pos, true_pos) via one-hot products — no
    confusion-matrix scatter (slow on XLA:TPU).  Chunked with host
    float64 accumulation so counts stay exact past f32's 2^24 (same
    discipline as confusion_matrix)."""
    classes, t1, p1, w = _indicator_matrices(
        y_true, y_pred, sample_weight, labels
    )
    k = len(classes)
    tp = np.zeros(k, np.float64)
    pred_pos = np.zeros(k, np.float64)
    true_pos = np.zeros(k, np.float64)
    n = t1.shape[0]
    for lo in range(0, n, _COUNT_CHUNK):
        hi = min(lo + _COUNT_CHUNK, n)
        # weight each ROW once (weighting both indicators would square w
        # in the tp term)
        wc = w[lo:hi, None]
        tb, pb = t1[lo:hi], p1[lo:hi]
        tp += np.asarray(jnp.sum(tb * pb * wc, axis=0), np.float64)
        pred_pos += np.asarray(jnp.sum(pb * wc, axis=0), np.float64)
        true_pos += np.asarray(jnp.sum(tb * wc, axis=0), np.float64)
    return classes, tp, pred_pos, true_pos


def _prf(y_true, y_pred, *, average, sample_weight, labels, pos_label, beta=1.0):
    classes, tp, pp, tpos = _prf_counts(y_true, y_pred, sample_weight, labels)

    def safe(num, den):
        return np.where(den > 0, num / np.maximum(den, 1e-30), 0.0)

    prec = safe(tp, pp)
    rec = safe(tp, tpos)
    b2 = beta * beta
    f = safe((1 + b2) * prec * rec, b2 * prec + rec)
    if average == "binary":
        if len(classes) > 2:
            raise ValueError(
                "Target is multiclass but average='binary'; choose "
                "average from {'micro', 'macro', 'weighted', None} "
                f"(observed labels: {classes.tolist()})"
            )
        where = np.flatnonzero(classes == pos_label)
        if where.size == 0:
            if labels is not None:
                # the caller spelled out the label set: a pos_label not
                # in it is a coding error, not a thin CV fold — raise
                # like sklearn instead of silently scoring 0
                raise ValueError(
                    f"pos_label={pos_label!r} is not a valid label: "
                    f"{classes.tolist()}"
                )
            # sklearn semantics: an absent pos_label scores 0 with an
            # UndefinedMetricWarning, it does not abort the CV loop
            import warnings

            from sklearn.exceptions import UndefinedMetricWarning

            warnings.warn(
                f"pos_label={pos_label!r} not in observed labels "
                f"{classes.tolist()}; scores are 0.0",
                UndefinedMetricWarning, stacklevel=3,
            )
            return 0.0, 0.0, 0.0
        i = int(where[0])
        return float(prec[i]), float(rec[i]), float(f[i])
    if average == "macro":
        return float(prec.mean()), float(rec.mean()), float(f.mean())
    if average == "micro":
        P = safe(tp.sum(), pp.sum())
        R = safe(tp.sum(), tpos.sum())
        F = safe((1 + b2) * P * R, b2 * P + R)
        return float(P), float(R), float(F)
    if average == "weighted":
        wts = tpos / max(tpos.sum(), 1e-30)
        return (
            float((prec * wts).sum()),
            float((rec * wts).sum()),
            float((f * wts).sum()),
        )
    if average is None:
        return prec, rec, f
    raise ValueError(f"Unsupported average: {average!r}")


def precision_score(y_true, y_pred, *, average="binary", pos_label=1,
                    sample_weight=None, labels=None):
    """tp / (tp + fp), per sklearn semantics (binary/micro/macro/weighted
    or per-class with average=None); counts reduce on device."""
    return _prf(y_true, y_pred, average=average, sample_weight=sample_weight,
                labels=labels, pos_label=pos_label)[0]


def recall_score(y_true, y_pred, *, average="binary", pos_label=1,
                 sample_weight=None, labels=None):
    """tp / (tp + fn), per sklearn semantics."""
    return _prf(y_true, y_pred, average=average, sample_weight=sample_weight,
                labels=labels, pos_label=pos_label)[1]


def f1_score(y_true, y_pred, *, average="binary", pos_label=1,
             sample_weight=None, labels=None):
    """Harmonic mean of precision and recall, per sklearn semantics."""
    return _prf(y_true, y_pred, average=average, sample_weight=sample_weight,
                labels=labels, pos_label=pos_label)[2]


def roc_auc_score(y_true, y_score, sample_weight=None):
    """Binary ROC AUC via the rank (Mann-Whitney U) formulation.

    One device sort + two vectorized binary searches — exact under score
    ties (tied positive/negative pairs count 0.5) and sample weights, and
    pad rows drop out through their zero weight:
    ``AUC = sum over positives of w * (W_neg_below + W_neg_tied / 2)
    / (W_pos * W_neg)``.
    """
    t, s, mask = _align(y_true, y_score)
    w = _apply_weight(mask, sample_weight)
    classes = _class_inventory(t, t, mask, None)
    if len(classes) != 2:
        raise ValueError(
            "roc_auc_score needs exactly 2 classes in y_true; got "
            f"{classes.tolist()}"
        )
    pos = (t == jnp.asarray(classes[1], t.dtype)).astype(jnp.float32)
    # keep the scores' own floating dtype: a cast would create spurious
    # ties between scores that differ below the narrower resolution
    # (under default JAX config device floats are at most f32; enable
    # x64 for float64-exact tie handling)
    if not jnp.issubdtype(s.dtype, jnp.floating):
        s = s.astype(jnp.float32)
    # pad rows: weight 0 — push them to the front so real ties are intact
    s = jnp.where(mask > 0, s, -jnp.inf)
    order = jnp.argsort(s)
    s_sorted = s[order]
    wneg_sorted = (w * (1.0 - pos))[order]
    lo = jnp.searchsorted(s_sorted, s, side="left")
    hi = jnp.searchsorted(s_sorted, s, side="right")
    wpos = w * pos
    # below + tied/2 at index j is 0.5*(cum(lo_j) + cum(hi_j)) where cum
    # is the exclusive prefix sum of negative weight.  A single f32
    # cumsum loses unit precision past 2^24 accumulated weight, so the
    # prefix sum is TWO-LEVEL: within-block cumsums stay on device in
    # f32 (exact at block scale), while the O(B) block bases accumulate
    # in float64 on host — fetches are B-sized, never O(n) (large D2H
    # transfers can wedge the axon relay).
    n_tot = int(s.shape[0])
    L = _AUC_BLOCK
    while L >= 2 * max(n_tot, 1):
        L >>= 1
    B = -(-n_tot // L)
    n_pad = B * L
    wneg_p = jnp.zeros((n_pad,), jnp.float32).at[:n_tot].set(wneg_sorted)
    blocks = wneg_p.reshape(B, L)
    within_incl = jnp.cumsum(blocks, axis=1)
    block_sums = within_incl[:, -1]
    within_excl = (within_incl - blocks).reshape(-1)
    # index n_pad is reachable only when hi == n_tot == n_pad: zero
    # within-block prefix, block id B (whose base is the full W_neg)
    flat_within = jnp.concatenate(
        [within_excl, jnp.zeros((1,), jnp.float32)]
    )
    # EVERY n-length accumulation is chunked with float64 host combines —
    # a single f32 device sum saturates at 2^24 accumulated unit weight,
    # the exact regime this two-level path exists for
    ids = jnp.concatenate([lo // L, hi // L])
    wps = jnp.concatenate([wpos, wpos])
    seg64 = np.zeros(B + 1, np.float64)
    for c0 in range(0, 2 * n_tot, _COUNT_CHUNK):
        c1 = min(c0 + _COUNT_CHUNK, 2 * n_tot)
        seg64 += np.asarray(
            jax.ops.segment_sum(
                wps[c0:c1], ids[c0:c1], num_segments=B + 1
            ),
            np.float64,
        )
    num_within64 = 0.0
    W_pos = 0.0
    half_inner = wpos * 0.5 * (flat_within[lo] + flat_within[hi])
    for c0 in range(0, n_tot, _COUNT_CHUNK):
        c1 = min(c0 + _COUNT_CHUNK, n_tot)
        num_within64 += float(jnp.sum(half_inner[c0:c1]))
        W_pos += float(jnp.sum(wpos[c0:c1]))
    bases = np.concatenate(
        [[0.0], np.cumsum(np.asarray(block_sums, np.float64))]
    )
    num = num_within64 + 0.5 * float(seg64 @ bases)
    W_neg = float(bases[-1])
    denom = W_pos * W_neg
    if denom <= 0:
        raise ValueError("Only one class present after weighting")
    return num / denom


def confusion_matrix(y_true, y_pred, *, labels=None, sample_weight=None,
                     normalize=None):
    """Confusion matrix C with C[i, j] = weight of samples of true class i
    predicted as class j — ONE device gemm (true-one-hot^T @ weighted
    pred-one-hot), no scatter (slow on XLA:TPU).
    """
    classes, t1, p1, w = _indicator_matrices(
        y_true, y_pred, sample_weight, labels
    )
    # chunked accumulation: a single f32 gemm silently saturates counts
    # at 2^24; per-chunk partial matrices stay exact (chunk < 2^22 rows)
    # and are summed in float64 ON HOST — the k x k result never goes
    # back to device (jnp would downcast the f64 sums without x64)
    n_rows = t1.shape[0]
    chunk = _COUNT_CHUNK
    hi_prec = jax.lax.Precision.HIGHEST  # default MXU bf16 would
    # truncate weights to 8 mantissa bits
    cm = np.zeros((len(classes), len(classes)), np.float64)
    for lo in range(0, n_rows, chunk):
        hi = min(lo + chunk, n_rows)
        cm += np.asarray(
            jnp.dot(t1[lo:hi].T, p1[lo:hi] * w[lo:hi, None],
                    precision=hi_prec),
            dtype=np.float64,
        )
    if normalize == "true":
        denom = cm.sum(axis=1, keepdims=True)
    elif normalize == "pred":
        denom = cm.sum(axis=0, keepdims=True)
    elif normalize == "all":
        denom = np.asarray(cm.sum())
    elif normalize is None:
        denom = None
    else:
        raise ValueError(f"Unsupported normalize: {normalize!r}")
    if denom is not None:
        with np.errstate(divide="ignore", invalid="ignore"):
            cm = cm / denom
        # sklearn nan_to_nums the zero-support rows/cols (verified
        # empirically; its docs read as NaN but the code zero-fills)
        return np.nan_to_num(cm)
    if sample_weight is None:
        return cm.astype(np.int64)
    return cm


def balanced_accuracy_score(y_true, y_pred, *, sample_weight=None,
                            adjusted=False):
    """Mean per-class recall over classes PRESENT in ``y_true`` (sklearn
    drops classes with no true samples before averaging — a plain macro
    recall would count a predicted-only class as recall 0)."""
    _, tp, _, tpos = _prf_counts(y_true, y_pred, sample_weight, None)
    present = tpos > 0
    if not present.any():
        raise ValueError("y_true has no represented classes")
    rec = tp[present] / tpos[present]
    score = float(rec.mean())
    if adjusted:
        chance = 1.0 / int(present.sum())
        score = (score - chance) / (1.0 - chance)
    return score

"""Classification metrics (reference: ``dask_ml/metrics/classification.py``).

Each metric is a single masked reduction over the sharded sample axis; with
sharded inputs XLA inserts the cross-device psum.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.sharded import ShardedRows


def _lengths(a):
    return (a.n_samples, a.padded) if isinstance(a, ShardedRows) else (a.shape[0], a.shape[0])


def _align(y_true, y_pred):
    """Return (true, pred, mask) as padded device arrays of equal length.

    Mixed sharded/plain inputs of the same logical length are aligned by
    zero-padding the plain side up to the sharded side's padded length (the
    padded tail is masked out anyway).
    """
    n_t, pad_t = _lengths(y_true)
    n_p, pad_p = _lengths(y_pred)
    if n_t != n_p:
        raise ValueError(
            f"y_true and y_pred have different lengths: {n_t} vs {n_p}"
        )
    padded = max(pad_t, pad_p)

    def to_padded(a):
        x = a.data if isinstance(a, ShardedRows) else jnp.asarray(a)
        if x.shape[0] < padded:
            x = jnp.pad(x, [(0, padded - x.shape[0])] + [(0, 0)] * (x.ndim - 1))
        return x

    if isinstance(y_true, ShardedRows) and pad_t == padded:
        mask = y_true.mask
    elif isinstance(y_pred, ShardedRows) and pad_p == padded:
        mask = y_pred.mask
    else:
        mask = jnp.ones(padded, dtype=jnp.float32)
    return to_padded(y_true), to_padded(y_pred), mask


def _apply_weight(mask, sample_weight):
    if sample_weight is None:
        return mask
    w = sample_weight.data if isinstance(sample_weight, ShardedRows) else jnp.asarray(sample_weight)
    if w.shape[0] < mask.shape[0]:
        # host-side weights for a padded device array: pad with zeros
        w = jnp.pad(w, (0, mask.shape[0] - w.shape[0]))
    elif w.shape[0] > mask.shape[0]:
        # sharded (padded) weights for plain arrays: padded tail is zeros
        w = w[: mask.shape[0]]
    return mask * w


def accuracy_score(y_true, y_pred, normalize: bool = True, sample_weight=None, compute=True):
    """Fraction (or count) of correct predictions."""
    t, p, mask = _align(y_true, y_pred)
    w = _apply_weight(mask, sample_weight)
    correct = (t == p).astype(jnp.float32)
    hits = jnp.sum(correct * w)
    result = hits / jnp.sum(w) if normalize else hits
    return float(result) if compute else result


def log_loss(y_true, y_pred, eps="auto", normalize: bool = True, sample_weight=None, labels=None):
    """Negative log-likelihood of a classifier's probabilistic predictions.

    ``y_pred`` may be (n, k) probabilities or (n,) positive-class probability.
    ``eps="auto"`` clips at the INPUT's machine epsilon (sklearn semantics:
    a float64 probability of 0 contributes log(2.2e-16), not log(1e-15) —
    the clip level, not the log arithmetic, is what parity depends on).
    """
    if eps == "auto":
        # read the dtype WITHOUT materializing device data on host
        # (np.asarray of a jax array transfers; of a ShardedRows it makes
        # an object scalar); f32 inputs need f32's eps or the upper clip
        # 1-eps rounds back to 1.0 and log(1-p) overflows to -inf
        in_dtype = getattr(y_pred, "dtype", None)
        if in_dtype is None:
            in_dtype = np.asarray(y_pred).dtype
        # jnp.finfo: recognizes ml_dtypes floats (bfloat16) that
        # np.issubdtype rejects — falling back to float64 eps for bf16
        # would clip above bf16 resolution and let p==1.0 reach log(0)
        if jnp.issubdtype(in_dtype, jnp.floating):
            eps = float(jnp.finfo(in_dtype).eps)
        else:
            eps = float(np.finfo(np.float64).eps)
    t, p, mask = _align(y_true, y_pred)
    w = _apply_weight(mask, sample_weight)
    p = jnp.clip(p, eps, 1.0 - eps)
    if p.ndim == 1:
        per = -(t * jnp.log(p) + (1.0 - t) * jnp.log(1.0 - p))
    else:
        n_classes = p.shape[1]
        if labels is not None:
            labels = np.sort(np.asarray(labels))
            t_host = np.asarray(t).astype(np.int64)
            unseen = np.setdiff1d(np.unique(t_host), labels)
            if unseen.size:
                raise ValueError(
                    f"y_true contains labels not in `labels`: {unseen.tolist()}"
                )
            t = jnp.asarray(np.searchsorted(labels, t_host))
        onehot = jax.nn.one_hot(t.astype(jnp.int32), n_classes, dtype=p.dtype)
        p = p / jnp.sum(p, axis=1, keepdims=True)
        per = -jnp.sum(onehot * jnp.log(p), axis=1)
    total = jnp.sum(per * w)
    return float(total / jnp.sum(w)) if normalize else float(total)

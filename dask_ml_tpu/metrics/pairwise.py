"""Pairwise distances and kernels.

Reference: ``dask_ml/metrics/pairwise.py`` (blockwise ‖x‖²+‖y‖²−2x·yᵀ and
rbf/polynomial/sigmoid/linear kernels).  Here X may be row-sharded over the
mesh; Y (typically centers or a sample) is replicated, so each device
computes its tile with one local gemm — the distance matrix comes out
row-sharded with zero communication.  This is the MXU hot path for KMeans
and SpectralClustering.

When BOTH operands are sharded (the reference's general
``pairwise_distances(X, Y)`` over two chunked arrays), the tiles are
computed with a **ppermute ring**: each device computes its local X-block
against the Y-block it currently holds, then passes the Y-block one hop
around the data-axis ring.  After P steps every device has its full row
block of the n×m result.  Structurally this is ring attention's outer loop
(SURVEY.md §5 long-context paragraph): Y blocks flow over ICI while the
gemms overlap with the transfers; no device ever materializes more than
(n/P)·m of the output or m/P·d of the remote operand.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..core.compat import shard_map_unchecked as _shard_map
from ..core.mesh import MeshHolder, data_axes, data_axes_size, get_mesh
from ..core.sharded import ShardedRows


def _data_of(x):
    """(padded data, true row count). Padded rows are sliced off results at
    the public API boundary; internal hot loops (KMeans) call the jitted
    kernels directly with masks instead."""
    if isinstance(x, ShardedRows):
        return x.data, x.n_samples
    x = jnp.asarray(x)
    return x, x.shape[0]


def _both_sharded(X, Y):
    return isinstance(X, ShardedRows) and isinstance(Y, ShardedRows)


@partial(jax.jit, static_argnames=("mesh_holder", "fn"))
def _ring_impl(x, y, *, mesh_holder, fn):
    """n×m tile matrix with both operands row-sharded: Y circulates the
    ring while each device fills its row block column-block by
    column-block."""
    mesh = mesh_holder.mesh
    # the ring runs over EVERY data-carrying axis (('dcn','data') on a
    # hierarchical mesh — collectives accept the axis tuple with
    # flattened ring semantics, so cross-slice hops ride DCN)
    row_ax = data_axes(mesh)
    n_shards = data_axes_size(mesh)

    def local(x_l, y_l):
        i = jax.lax.axis_index(row_ax)
        m_l = y_l.shape[0]
        out0 = jnp.zeros((x_l.shape[0], n_shards * m_l), dtype=x_l.dtype)
        perm = [(j, (j + 1) % n_shards) for j in range(n_shards)]

        def body(carry, s):
            y_cur, out = carry
            col = ((i - s) % n_shards) * m_l  # block y_cur came from
            if getattr(fn, "takes_offsets", False):
                # offset-aware tiles (X-vs-X self rings) get GLOBAL row/col
                # offsets so they can pin exact self-pairs on the diagonal
                tile = fn(x_l, y_cur, i * x_l.shape[0], col)
            else:
                tile = fn(x_l, y_cur)  # (n_l, m_l) — local MXU gemm
            out = jax.lax.dynamic_update_slice(out, tile, (0, col))
            y_cur = jax.lax.ppermute(y_cur, row_ax, perm)
            return (y_cur, out), None

        (_, out), _ = jax.lax.scan(
            body, (y_l, out0), jnp.arange(n_shards)
        )
        return out

    return _shard_map(
        local, mesh,
        in_specs=(P(row_ax, None), P(row_ax, None)),
        out_specs=P(row_ax, None),
    )(x, y)


def ring_pairwise(X: ShardedRows, Y: ShardedRows, fn, mesh=None):
    """Apply a pairwise tile kernel ``fn(x_tile, y_tile) -> (nx, ny)`` with
    both operands sharded, via the ppermute ring.  Returns the (n, m)
    result row-sharded and sliced to real rows/cols (Y's padding rows are
    trailing in global order, so a column slice removes them)."""
    mesh = mesh or get_mesh()
    out = _ring_impl(
        X.data, Y.data, mesh_holder=MeshHolder(mesh), fn=fn
    )
    return out[: X.n_samples, : Y.n_samples]


@partial(jax.jit, static_argnames=("precision",))
def _sq_euclidean(x, y, precision=None):
    x_norm = jnp.sum(x * x, axis=1, keepdims=True)
    y_norm = jnp.sum(y * y, axis=1, keepdims=True).T
    d2 = x_norm + y_norm - 2.0 * jnp.dot(x, y.T, precision=precision)
    return jnp.maximum(d2, 0.0)


# Entries with d² below _SAFE_TAU·(‖x‖²+‖y‖²) are recomputed with the
# exact (x−y)² form: the ‖x‖²+‖y‖²−2x·y expansion carries absolute error
# ~c·eps32·(‖x‖²+‖y‖²) (c grows like the accumulation depth), so for
# near-duplicate rows the cancellation error dominates the true distance
# — sqrt(d²) can come out ~1e-3 when the truth is 1e-6.  τ=1e-2 keeps
# the post-sqrt relative error of UNflagged entries under ~1e-4·√d.
_SAFE_TAU = 1e-2


def _row_chunked(x, y, tile_fn):
    """Apply ``tile_fn(x_chunk, y) -> (chunk, m)`` over row chunks of x,
    bounding the (chunk, m, d) broadcast cube to ~64MB instead of
    materializing (n, m, d).  Shared by the L1 tile and the exact
    euclidean recompute."""
    m, d = y.shape[0], x.shape[1]
    n = x.shape[0]
    if n == 0 or m == 0:
        return jnp.zeros((n, m), dtype=x.dtype)
    chunk = max(int(16_000_000 / max(m * d, 1)), 1)
    chunk = min(chunk, n)
    pad = (-n) % chunk
    xp = jnp.pad(x, ((0, pad), (0, 0))) if pad else x
    # lax.map (not a Python loop) keeps the traced graph O(1) in the
    # chunk count — at (50k x 50k x 128) the chunk count is ~25,000 and
    # an unrolled loop would explode compile time/memory.
    out = jax.lax.map(
        lambda xb: tile_fn(xb, y), xp.reshape(-1, chunk, d)
    )
    return out.reshape(-1, m)[:n]


def _exact_sq_chunked(x, y, d2, flagged):
    """Replace flagged entries of d2 with the exact Σ(x−y)² form."""
    ex = _row_chunked(
        x, y,
        lambda xb, yy: jnp.sum((xb[:, None, :] - yy[None, :, :]) ** 2,
                               axis=-1),
    )
    return jnp.where(flagged, ex, d2)


@partial(jax.jit, static_argnames=("self_pairs",))
def _sq_euclidean_safe(x, y, row0=0, col0=0, self_pairs=False):
    """Cancellation-guarded squared distances for VALUE consumers
    (``euclidean_distances``, ``rbf_kernel``): gemm expansion at HIGHEST
    precision, then an exact recompute of any tile whose entries fall in
    the cancellation regime (the sklearn float32 mitigation, done the XLA
    way: ``lax.cond`` skips the exact pass entirely when no entry is
    flagged, so well-separated data keeps pure-MXU speed).  ARGMIN
    consumers keep ``_sq_euclidean_hi`` — a wrong small distance cannot
    flip an argmin between near-duplicates.

    ``self_pairs=True`` declares x and y to be row blocks of THE SAME
    matrix, with ``row0``/``col0`` their global offsets (0 for the
    replicated Y=None call; ring steps pass their block offsets): the
    global diagonal is pinned to exactly 0 and excluded from flagging,
    so self-distance calls keep the gemm fast path instead of always
    tripping the d²≈0 diagonal."""
    if x.shape[0] == 0 or y.shape[0] == 0:
        return jnp.zeros((x.shape[0], y.shape[0]), dtype=x.dtype)
    # Distances are translation-invariant: center both operands by ONE
    # shared per-feature anchor before expanding.  Data with a large mean
    # offset (norms >> spread — exactly the cancellation-prone regime)
    # would otherwise flag EVERY entry and permanently abandon the gemm
    # for the chunked O(n·m·d) recompute; after centering, norms reflect
    # spread, so the flag fires only for genuinely near-duplicate rows.
    anchor = 0.5 * (jnp.mean(x, axis=0) + jnp.mean(y, axis=0))
    x = x - anchor
    y = y - anchor
    x_norm = jnp.sum(x * x, axis=1, keepdims=True)
    y_norm = jnp.sum(y * y, axis=1, keepdims=True).T
    scale = x_norm + y_norm
    d2 = scale - 2.0 * jnp.dot(x, y.T, precision=jax.lax.Precision.HIGHEST)
    d2 = jnp.maximum(d2, 0.0)
    flagged = d2 < _SAFE_TAU * scale
    if self_pairs:
        ii = row0 + jax.lax.broadcasted_iota(jnp.int32, d2.shape, 0)
        jj = col0 + jax.lax.broadcasted_iota(jnp.int32, d2.shape, 1)
        diag = ii == jj
        d2 = jnp.where(diag, 0.0, d2)
        flagged = flagged & ~diag
    return jax.lax.cond(
        jnp.any(flagged),
        lambda: _exact_sq_chunked(x, y, d2, flagged),
        lambda: d2,
    )


def _sq_euclidean_hi(x, y):
    """HIGHEST-precision distances for ARGMIN consumers (KMeans
    assignment, kNN graphs, argmin_min): the TPU MXU's default precision
    truncates fp32 operands to bf16, flipping labels near cluster
    boundaries.  VALUE consumers (``euclidean_distances``,
    ``rbf_kernel``) route through ``_sq_euclidean_safe`` instead, which
    is also HIGHEST plus a cancellation guard; only internal hot loops
    that tolerate bf16 error (e.g. solver gemms) use the fast default."""
    return _sq_euclidean(x, y, precision=jax.lax.Precision.HIGHEST)

def _euclid_tile(x, y):
    return jnp.sqrt(_sq_euclidean_safe(x, y))


def _manhattan_tile(x, y):
    """L1 distances: |x-y| has no gemm form, so go through the bounded
    row-chunked broadcast."""
    return _row_chunked(
        x, y,
        lambda xb, yy: jnp.sum(jnp.abs(xb[:, None, :] - yy[None, :, :]),
                               axis=-1),
    )


def _cosine_tile(x, y):
    xn = x / jnp.maximum(jnp.linalg.norm(x, axis=1, keepdims=True), 1e-30)
    yn = y / jnp.maximum(jnp.linalg.norm(y, axis=1, keepdims=True), 1e-30)
    return 1.0 - xn @ yn.T


def euclidean_distances(X, Y=None, squared: bool = False):
    """Row-sharded ‖x−y‖ distances (reference ``euclidean_distances``).
    Sharded×sharded inputs route through the ppermute ring."""
    if Y is not None and _both_sharded(X, Y):
        if Y is X:  # self ring: pin the global diagonal
            tile = _SelfTile("sq" if squared else "euclid")
        else:
            tile = _sq_euclidean_safe if squared else _euclid_tile
        return ring_pairwise(X, Y, tile)
    x, n = _data_of(X)
    y, m = (x, n) if Y is None else _data_of(Y)
    d2 = _sq_euclidean_safe(x, y, self_pairs=Y is None)
    out = d2 if squared else jnp.sqrt(d2)
    return out[:n, :m]


def pairwise_distances(X, Y=None, metric: str = "euclidean", **kwargs):
    if callable(metric):
        # Callables run EAGERLY on the (global) operands — they may be
        # numpy-based or depend on global structure, neither of which
        # survives being traced per-tile inside the ring's shard_map.
        # Jit-safe tile kernels can opt into the ring via ring_pairwise.
        x, n = _data_of(X)
        y, m = (x, n) if Y is None else _data_of(Y)
        return metric(x, y, **kwargs)[:n, :m]
    if metric == "euclidean":
        return euclidean_distances(X, Y)
    if metric == "sqeuclidean":
        return euclidean_distances(X, Y, squared=True)
    if metric == "cosine":
        if Y is not None and _both_sharded(X, Y):
            return ring_pairwise(X, Y, _cosine_tile)
        x, n = _data_of(X)
        y, m = (x, n) if Y is None else _data_of(Y)
        return _cosine_tile(x, y)[:n, :m]
    if metric in ("manhattan", "cityblock", "l1"):
        if Y is not None and _both_sharded(X, Y):
            return ring_pairwise(X, Y, _manhattan_tile)
        x, n = _data_of(X)
        y, m = (x, n) if Y is None else _data_of(Y)
        return _manhattan_tile(x, y)[:n, :m]
    raise ValueError(f"Unsupported metric: {metric!r}")


@jax.jit
def _argmin_min(x, y):
    d2 = _sq_euclidean_hi(x, y)
    idx = jnp.argmin(d2, axis=1)
    # jnp.min picks the same element as d2[idx] without the dynamic
    # row-gather (take_along_axis), which XLA:TPU lowers ~10x slower
    return idx, jnp.sqrt(jnp.maximum(jnp.min(d2, axis=1), 0.0))


def pairwise_distances_argmin_min(X, Y):
    """(argmin index, min distance) per row (reference symbol of same name)."""
    x, n = _data_of(X)
    y, _ = _data_of(Y)
    idx, dist = _argmin_min(x, y)
    return idx[:n], dist[:n]


def _linear_tile(x, y):
    return x @ y.T


def linear_kernel(X, Y=None):
    if Y is not None and _both_sharded(X, Y):
        return ring_pairwise(X, Y, _linear_tile)
    x, n = _data_of(X)
    y, m = (x, n) if Y is None else _data_of(Y)
    return (x @ y.T)[:n, :m]


def _poly_tile(x, y, gamma, coef0, degree):
    return (gamma * (x @ y.T) + coef0) ** degree


def polynomial_kernel(X, Y=None, degree: int = 3, gamma=None, coef0: float = 1.0):
    if Y is not None and _both_sharded(X, Y):
        g = 1.0 / X.data.shape[1] if gamma is None else gamma
        return ring_pairwise(
            X, Y,
            _BoundTile(_poly_tile, gamma=float(g), coef0=float(coef0),
                       degree=int(degree)),
        )
    x, n = _data_of(X)
    y, m = (x, n) if Y is None else _data_of(Y)
    if gamma is None:
        gamma = 1.0 / x.shape[1]
    return ((gamma * (x @ y.T) + coef0) ** degree)[:n, :m]


class _BoundTile:
    """Hashable-by-value tile kernel with bound scalars, so passing it as a
    static jit argument caches the compiled ring per (fn, params) instead
    of recompiling per call (functools.partial hashes by identity)."""

    def __init__(self, fn, **params):
        self.fn = fn
        self.params = tuple(sorted(params.items()))

    def __call__(self, x, y):
        return self.fn(x, y, **dict(self.params))

    def __hash__(self):
        return hash((self.fn, self.params))

    def __eq__(self, other):
        return (
            type(other) is _BoundTile
            and other.fn is self.fn
            and other.params == self.params
        )


class _SelfTile:
    """Offset-aware ring tile for X-vs-X calls (``takes_offsets``
    protocol in ``_ring_impl``): routes through ``_sq_euclidean_safe``
    with global offsets so exact self-pairs on the diagonal are pinned
    to 0 and never trip the cancellation recompute.  Hashable by value
    like ``_BoundTile`` so the compiled ring caches per (post, params)."""

    takes_offsets = True

    def __init__(self, post, **params):
        self.post = post  # 'sq' | 'euclid' | 'rbf'
        self.params = tuple(sorted(params.items()))

    def __call__(self, x, y, row0, col0):
        d2 = _sq_euclidean_safe(x, y, row0, col0, self_pairs=True)
        if self.post == "sq":
            return d2
        if self.post == "euclid":
            return jnp.sqrt(d2)
        return jnp.exp(-dict(self.params)["gamma"] * d2)

    def __hash__(self):
        return hash((type(self), self.post, self.params))

    def __eq__(self, other):
        return (
            type(other) is _SelfTile
            and other.post == self.post
            and other.params == self.params
        )


def _rbf_tile(x, y, gamma):
    return jnp.exp(-gamma * _sq_euclidean_safe(x, y))


def rbf_kernel(X, Y=None, gamma=None):
    if Y is not None and _both_sharded(X, Y):
        g = 1.0 / X.data.shape[1] if gamma is None else gamma
        tile = (_SelfTile("rbf", gamma=float(g)) if Y is X
                else _BoundTile(_rbf_tile, gamma=float(g)))
        return ring_pairwise(X, Y, tile)
    x, n = _data_of(X)
    y, m = (x, n) if Y is None else _data_of(Y)
    if gamma is None:
        gamma = 1.0 / x.shape[1]
    d2 = _sq_euclidean_safe(x, y, self_pairs=Y is None)
    return jnp.exp(-gamma * d2)[:n, :m]


def sigmoid_kernel(X, Y=None, gamma=None, coef0: float = 1.0):
    x, n = _data_of(X)
    y, m = (x, n) if Y is None else _data_of(Y)
    if gamma is None:
        gamma = 1.0 / x.shape[1]
    return jnp.tanh(gamma * (x @ y.T) + coef0)[:n, :m]


PAIRWISE_KERNEL_FUNCTIONS = {
    "linear": linear_kernel,
    "polynomial": polynomial_kernel,
    "rbf": rbf_kernel,
    "sigmoid": sigmoid_kernel,
}

"""Pairwise distances and kernels.

Reference: ``dask_ml/metrics/pairwise.py`` (blockwise ‖x‖²+‖y‖²−2x·yᵀ and
rbf/polynomial/sigmoid/linear kernels).  Here X may be row-sharded over the
mesh; Y (typically centers or a sample) is replicated, so each device
computes its tile with one local gemm — the distance matrix comes out
row-sharded with zero communication.  This is the MXU hot path for KMeans
and SpectralClustering.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.sharded import ShardedRows


def _data_of(x):
    """(padded data, true row count). Padded rows are sliced off results at
    the public API boundary; internal hot loops (KMeans) call the jitted
    kernels directly with masks instead."""
    if isinstance(x, ShardedRows):
        return x.data, x.n_samples
    x = jnp.asarray(x)
    return x, x.shape[0]


@jax.jit
def _sq_euclidean(x, y):
    x_norm = jnp.sum(x * x, axis=1, keepdims=True)
    y_norm = jnp.sum(y * y, axis=1, keepdims=True).T
    d2 = x_norm + y_norm - 2.0 * (x @ y.T)
    return jnp.maximum(d2, 0.0)

def euclidean_distances(X, Y=None, squared: bool = False):
    """Row-sharded ‖x−y‖ distances (reference ``euclidean_distances``)."""
    x, n = _data_of(X)
    y, m = (x, n) if Y is None else _data_of(Y)
    d2 = _sq_euclidean(x, y)
    out = d2 if squared else jnp.sqrt(d2)
    return out[:n, :m]


def pairwise_distances(X, Y=None, metric: str = "euclidean", **kwargs):
    if callable(metric):
        x, n = _data_of(X)
        y, m = (x, n) if Y is None else _data_of(Y)
        return metric(x, y, **kwargs)[:n, :m]
    if metric == "euclidean":
        return euclidean_distances(X, Y)
    if metric == "sqeuclidean":
        return euclidean_distances(X, Y, squared=True)
    if metric == "cosine":
        x, n = _data_of(X)
        y, m = (x, n) if Y is None else _data_of(Y)
        xn = x / jnp.linalg.norm(x, axis=1, keepdims=True)
        yn = y / jnp.linalg.norm(y, axis=1, keepdims=True)
        return (1.0 - xn @ yn.T)[:n, :m]
    raise ValueError(f"Unsupported metric: {metric!r}")


@jax.jit
def _argmin_min(x, y):
    d2 = _sq_euclidean(x, y)
    idx = jnp.argmin(d2, axis=1)
    return idx, jnp.sqrt(jnp.take_along_axis(d2, idx[:, None], axis=1)[:, 0])


def pairwise_distances_argmin_min(X, Y):
    """(argmin index, min distance) per row (reference symbol of same name)."""
    x, n = _data_of(X)
    y, _ = _data_of(Y)
    idx, dist = _argmin_min(x, y)
    return idx[:n], dist[:n]


def linear_kernel(X, Y=None):
    x, n = _data_of(X)
    y, m = (x, n) if Y is None else _data_of(Y)
    return (x @ y.T)[:n, :m]


def polynomial_kernel(X, Y=None, degree: int = 3, gamma=None, coef0: float = 1.0):
    x, n = _data_of(X)
    y, m = (x, n) if Y is None else _data_of(Y)
    if gamma is None:
        gamma = 1.0 / x.shape[1]
    return ((gamma * (x @ y.T) + coef0) ** degree)[:n, :m]


def rbf_kernel(X, Y=None, gamma=None):
    x, n = _data_of(X)
    y, m = (x, n) if Y is None else _data_of(Y)
    if gamma is None:
        gamma = 1.0 / x.shape[1]
    return jnp.exp(-gamma * _sq_euclidean(x, y))[:n, :m]


def sigmoid_kernel(X, Y=None, gamma=None, coef0: float = 1.0):
    x, n = _data_of(X)
    y, m = (x, n) if Y is None else _data_of(Y)
    if gamma is None:
        gamma = 1.0 / x.shape[1]
    return jnp.tanh(gamma * (x @ y.T) + coef0)[:n, :m]


PAIRWISE_KERNEL_FUNCTIONS = {
    "linear": linear_kernel,
    "polynomial": polynomial_kernel,
    "rbf": rbf_kernel,
    "sigmoid": sigmoid_kernel,
}

"""Pairwise distances and kernels.

Reference: ``dask_ml/metrics/pairwise.py`` (blockwise ‖x‖²+‖y‖²−2x·yᵀ and
rbf/polynomial/sigmoid/linear kernels).  Here X may be row-sharded over the
mesh; Y (typically centers or a sample) is replicated, so each device
computes its tile with one local gemm — the distance matrix comes out
row-sharded with zero communication.  This is the MXU hot path for KMeans
and SpectralClustering.

When BOTH operands are sharded (the reference's general
``pairwise_distances(X, Y)`` over two chunked arrays), the tiles are
computed with a **ppermute ring**: each device computes its local X-block
against the Y-block it currently holds, then passes the Y-block one hop
around the data-axis ring.  After P steps every device has its full row
block of the n×m result.  Structurally this is ring attention's outer loop
(SURVEY.md §5 long-context paragraph): Y blocks flow over ICI while the
gemms overlap with the transfers; no device ever materializes more than
(n/P)·m of the output or m/P·d of the remote operand.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..core.compat import shard_map_unchecked as _shard_map
from ..core.mesh import DATA_AXIS, MeshHolder, get_mesh
from ..core.sharded import ShardedRows


def _data_of(x):
    """(padded data, true row count). Padded rows are sliced off results at
    the public API boundary; internal hot loops (KMeans) call the jitted
    kernels directly with masks instead."""
    if isinstance(x, ShardedRows):
        return x.data, x.n_samples
    x = jnp.asarray(x)
    return x, x.shape[0]


def _both_sharded(X, Y):
    return isinstance(X, ShardedRows) and isinstance(Y, ShardedRows)


@partial(jax.jit, static_argnames=("mesh_holder", "fn"))
def _ring_impl(x, y, *, mesh_holder, fn):
    """n×m tile matrix with both operands row-sharded: Y circulates the
    ring while each device fills its row block column-block by
    column-block."""
    mesh = mesh_holder.mesh
    n_shards = mesh.shape[DATA_AXIS]

    def local(x_l, y_l):
        i = jax.lax.axis_index(DATA_AXIS)
        m_l = y_l.shape[0]
        out0 = jnp.zeros((x_l.shape[0], n_shards * m_l), dtype=x_l.dtype)
        perm = [(j, (j + 1) % n_shards) for j in range(n_shards)]

        def body(carry, s):
            y_cur, out = carry
            tile = fn(x_l, y_cur)  # (n_l, m_l) — local MXU gemm
            col = ((i - s) % n_shards) * m_l  # block y_cur came from
            out = jax.lax.dynamic_update_slice(out, tile, (0, col))
            y_cur = jax.lax.ppermute(y_cur, DATA_AXIS, perm)
            return (y_cur, out), None

        (_, out), _ = jax.lax.scan(
            body, (y_l, out0), jnp.arange(n_shards)
        )
        return out

    return _shard_map(
        local, mesh,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS, None)),
        out_specs=P(DATA_AXIS, None),
    )(x, y)


def ring_pairwise(X: ShardedRows, Y: ShardedRows, fn, mesh=None):
    """Apply a pairwise tile kernel ``fn(x_tile, y_tile) -> (nx, ny)`` with
    both operands sharded, via the ppermute ring.  Returns the (n, m)
    result row-sharded and sliced to real rows/cols (Y's padding rows are
    trailing in global order, so a column slice removes them)."""
    mesh = mesh or get_mesh()
    out = _ring_impl(
        X.data, Y.data, mesh_holder=MeshHolder(mesh), fn=fn
    )
    return out[: X.n_samples, : Y.n_samples]


@partial(jax.jit, static_argnames=("precision",))
def _sq_euclidean(x, y, precision=None):
    x_norm = jnp.sum(x * x, axis=1, keepdims=True)
    y_norm = jnp.sum(y * y, axis=1, keepdims=True).T
    d2 = x_norm + y_norm - 2.0 * jnp.dot(x, y.T, precision=precision)
    return jnp.maximum(d2, 0.0)


def _sq_euclidean_hi(x, y):
    """HIGHEST-precision distances for ARGMIN consumers (KMeans
    assignment, kNN graphs, argmin_min): the TPU MXU's default precision
    truncates fp32 operands to bf16, flipping labels near cluster
    boundaries.  Kernel consumers (rbf/exp, sqrt outputs) keep the fast
    default — their outputs are smooth in the distance."""
    return _sq_euclidean(x, y, precision=jax.lax.Precision.HIGHEST)

def _euclid_tile(x, y):
    return jnp.sqrt(_sq_euclidean(x, y))


def _manhattan_tile(x, y):
    """L1 distances, chunked over rows of x: |x-y| has no gemm form, so
    the (tile, m, d) broadcast is bounded to ~64MB per chunk instead of
    materializing the full (n, m, d) cube."""
    m = y.shape[0]
    d = x.shape[1]
    chunk = max(int(16_000_000 / max(m * d, 1)), 1)
    chunk = min(chunk, max(x.shape[0], 1))  # never pad past the real rows

    def one(lo):
        xb = jax.lax.dynamic_slice_in_dim(x, lo, chunk)
        return jnp.sum(jnp.abs(xb[:, None, :] - y[None, :, :]), axis=-1)

    n = x.shape[0]
    pad = (-n) % chunk
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
    outs = [one(lo) for lo in range(0, x.shape[0], chunk)]
    return jnp.concatenate(outs, axis=0)[:n]


def _cosine_tile(x, y):
    xn = x / jnp.maximum(jnp.linalg.norm(x, axis=1, keepdims=True), 1e-30)
    yn = y / jnp.maximum(jnp.linalg.norm(y, axis=1, keepdims=True), 1e-30)
    return 1.0 - xn @ yn.T


def euclidean_distances(X, Y=None, squared: bool = False):
    """Row-sharded ‖x−y‖ distances (reference ``euclidean_distances``).
    Sharded×sharded inputs route through the ppermute ring."""
    if Y is not None and _both_sharded(X, Y):
        return ring_pairwise(
            X, Y, _sq_euclidean if squared else _euclid_tile
        )
    x, n = _data_of(X)
    y, m = (x, n) if Y is None else _data_of(Y)
    d2 = _sq_euclidean(x, y)
    out = d2 if squared else jnp.sqrt(d2)
    return out[:n, :m]


def pairwise_distances(X, Y=None, metric: str = "euclidean", **kwargs):
    if callable(metric):
        # Callables run EAGERLY on the (global) operands — they may be
        # numpy-based or depend on global structure, neither of which
        # survives being traced per-tile inside the ring's shard_map.
        # Jit-safe tile kernels can opt into the ring via ring_pairwise.
        x, n = _data_of(X)
        y, m = (x, n) if Y is None else _data_of(Y)
        return metric(x, y, **kwargs)[:n, :m]
    if metric == "euclidean":
        return euclidean_distances(X, Y)
    if metric == "sqeuclidean":
        return euclidean_distances(X, Y, squared=True)
    if metric == "cosine":
        if Y is not None and _both_sharded(X, Y):
            return ring_pairwise(X, Y, _cosine_tile)
        x, n = _data_of(X)
        y, m = (x, n) if Y is None else _data_of(Y)
        return _cosine_tile(x, y)[:n, :m]
    if metric in ("manhattan", "cityblock", "l1"):
        if Y is not None and _both_sharded(X, Y):
            return ring_pairwise(X, Y, _manhattan_tile)
        x, n = _data_of(X)
        y, m = (x, n) if Y is None else _data_of(Y)
        return _manhattan_tile(x, y)[:n, :m]
    raise ValueError(f"Unsupported metric: {metric!r}")


@jax.jit
def _argmin_min(x, y):
    d2 = _sq_euclidean_hi(x, y)
    idx = jnp.argmin(d2, axis=1)
    # jnp.min picks the same element as d2[idx] without the dynamic
    # row-gather (take_along_axis), which XLA:TPU lowers ~10x slower
    return idx, jnp.sqrt(jnp.maximum(jnp.min(d2, axis=1), 0.0))


def pairwise_distances_argmin_min(X, Y):
    """(argmin index, min distance) per row (reference symbol of same name)."""
    x, n = _data_of(X)
    y, _ = _data_of(Y)
    idx, dist = _argmin_min(x, y)
    return idx[:n], dist[:n]


def _linear_tile(x, y):
    return x @ y.T


def linear_kernel(X, Y=None):
    if Y is not None and _both_sharded(X, Y):
        return ring_pairwise(X, Y, _linear_tile)
    x, n = _data_of(X)
    y, m = (x, n) if Y is None else _data_of(Y)
    return (x @ y.T)[:n, :m]


def _poly_tile(x, y, gamma, coef0, degree):
    return (gamma * (x @ y.T) + coef0) ** degree


def polynomial_kernel(X, Y=None, degree: int = 3, gamma=None, coef0: float = 1.0):
    if Y is not None and _both_sharded(X, Y):
        g = 1.0 / X.data.shape[1] if gamma is None else gamma
        return ring_pairwise(
            X, Y,
            _BoundTile(_poly_tile, gamma=float(g), coef0=float(coef0),
                       degree=int(degree)),
        )
    x, n = _data_of(X)
    y, m = (x, n) if Y is None else _data_of(Y)
    if gamma is None:
        gamma = 1.0 / x.shape[1]
    return ((gamma * (x @ y.T) + coef0) ** degree)[:n, :m]


class _BoundTile:
    """Hashable-by-value tile kernel with bound scalars, so passing it as a
    static jit argument caches the compiled ring per (fn, params) instead
    of recompiling per call (functools.partial hashes by identity)."""

    def __init__(self, fn, **params):
        self.fn = fn
        self.params = tuple(sorted(params.items()))

    def __call__(self, x, y):
        return self.fn(x, y, **dict(self.params))

    def __hash__(self):
        return hash((self.fn, self.params))

    def __eq__(self, other):
        return (
            type(other) is _BoundTile
            and other.fn is self.fn
            and other.params == self.params
        )


def _rbf_tile(x, y, gamma):
    return jnp.exp(-gamma * _sq_euclidean(x, y))


def rbf_kernel(X, Y=None, gamma=None):
    if Y is not None and _both_sharded(X, Y):
        g = 1.0 / X.data.shape[1] if gamma is None else gamma
        return ring_pairwise(X, Y, _BoundTile(_rbf_tile, gamma=float(g)))
    x, n = _data_of(X)
    y, m = (x, n) if Y is None else _data_of(Y)
    if gamma is None:
        gamma = 1.0 / x.shape[1]
    return jnp.exp(-gamma * _sq_euclidean(x, y))[:n, :m]


def sigmoid_kernel(X, Y=None, gamma=None, coef0: float = 1.0):
    x, n = _data_of(X)
    y, m = (x, n) if Y is None else _data_of(Y)
    if gamma is None:
        gamma = 1.0 / x.shape[1]
    return jnp.tanh(gamma * (x @ y.T) + coef0)[:n, :m]


PAIRWISE_KERNEL_FUNCTIONS = {
    "linear": linear_kernel,
    "polynomial": polynomial_kernel,
    "rbf": rbf_kernel,
    "sigmoid": sigmoid_kernel,
}

"""Scaling out: flat fleet meshes and the hierarchical DCN mesh.

Single-host programs run UNCHANGED on a fleet: form the process group
(`core.distributed.initialize`), build `global_mesh()`, and every psum
crosses hosts automatically (ICI within a slice, DCN between).  This
example demonstrates the mesh shapes in ONE process (the real
2-process form is `__graft_entry__.dryrun_multihost`, which spawns a
Gloo group over localhost):

- a flat `('data', 'model')` mesh — the recommended setup;
- a hierarchical `('dcn', 'data', 'model')` mesh with rows sharded over
  BOTH data-carrying axes — ADMM's consensus psums, TSQR's all_gather,
  and the pairwise ring all run natively on the `('dcn', 'data')` axis
  tuple (`core.mesh.data_axes`).
"""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import os

if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:  # older jax: XLA_FLAGS (set by the harness) covers it
    pass

import numpy as np  # noqa: E402

from dask_ml_tpu.core import use_mesh  # noqa: E402
from dask_ml_tpu.core import distributed as dist  # noqa: E402
from dask_ml_tpu.core.mesh import Mesh  # noqa: E402
from dask_ml_tpu.linear_model import LogisticRegression  # noqa: E402

rng = np.random.RandomState(0)
X = rng.normal(size=(4000, 12)).astype(np.float32)
w = rng.normal(size=12)
y = (X @ w > 0).astype(np.float32)

# -- flat global mesh: what a fleet deployment uses by default
flat = dist.global_mesh()  # ('data', 'model') over all devices
with use_mesh(flat):
    Xs = dist.shard_rows_global(X, flat)
    ys = dist.shard_rows_global(y, flat)
    lr = LogisticRegression(solver="admm", max_iter=50).fit(Xs, ys)
    acc_flat = float(lr.score(Xs, ys))
print(f"flat mesh {dict(flat.shape)}: ADMM accuracy {acc_flat:.3f}")

# -- hierarchical mesh: explicit 'dcn' axis (2 slices x 4 devices here;
# on a real fleet global_mesh(hierarchical=True) derives it from the
# process group)
devs = np.array(jax.devices()).reshape(2, 4, 1)
hmesh = Mesh(devs, ("dcn", "data", "model"))
with use_mesh(hmesh):
    Xh = dist.shard_rows_global(X, hmesh)
    yh = dist.shard_rows_global(y, hmesh)
    lrh = LogisticRegression(solver="admm", max_iter=50).fit(Xh, yh)
    acc_h = float(lrh.score(Xh, yh))
print(f"dcn mesh {dict(hmesh.shape)}: ADMM accuracy {acc_h:.3f}")
assert abs(acc_flat - acc_h) < 0.02
print("flat and hierarchical meshes agree")

"""North-star #2: KMeans with k-means|| init and the fused Lloyd loop.

Each Lloyd round is one program: distance gemm on the MXU, masked
one-hot-gemm center reduce, psum across shards. Measured 0.73 ms per
2M x 50 round on a single v5e chip (BENCH_LOCAL.md).
"""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import os

if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:  # older jax: XLA_FLAGS (set by the harness) covers it
    pass

import numpy as np  # noqa: E402
from sklearn.datasets import make_blobs  # noqa: E402

from dask_ml_tpu.cluster import KMeans  # noqa: E402
from dask_ml_tpu.core import shard_rows  # noqa: E402

X, y = make_blobs(n_samples=100_000, centers=8, n_features=16,
                  random_state=0)
km = KMeans(n_clusters=8, random_state=0).fit(shard_rows(X.astype(np.float32)))
print(f"inertia: {km.inertia_:.1f}  n_iter: {km.n_iter_}")
print("center norms:", np.linalg.norm(np.asarray(km.cluster_centers_), axis=1).round(2))

"""Sparse text at scale: out-of-core CountVectorizer -> streamed SVD.

The corpus is consumed lazily (bounded-window chunks, never
materialized); TruncatedSVD.fit_streamed densifies one block at a time,
so a 100k-vocabulary pipeline fits in O(features x sketch) memory.
"""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import os

if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:  # older jax: XLA_FLAGS (set by the harness) covers it
    pass

import numpy as np  # noqa: E402

from dask_ml_tpu.decomposition import TruncatedSVD  # noqa: E402
from dask_ml_tpu.feature_extraction.text import CountVectorizer  # noqa: E402

corpus = [
    f"topic{i % 7} shares words with topic{(i + 1) % 7} but not {i % 97}"
    for i in range(5000)
]
vec = CountVectorizer().fit(corpus)  # global document frequencies
svd = TruncatedSVD(n_components=5, random_state=0)
svd.fit_streamed(lambda: vec.stream_transform(corpus))
print(f"vocabulary: {len(vec.vocabulary_)} terms")
print("explained variance ratio:",
      np.asarray(svd.explained_variance_ratio_).round(4))

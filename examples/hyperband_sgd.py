"""North-star #5: HyperbandSearchCV over the device-native SGDClassifier.

Homogeneous candidate configs pack into ONE vmapped program per training
round (DISPATCH_STATS shows the packed dispatches); schedules match the
reference's bracket math exactly (metadata == metadata_).
"""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import os

if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:  # older jax: XLA_FLAGS (set by the harness) covers it
    pass

import numpy as np  # noqa: E402
from scipy.stats import loguniform  # noqa: E402

from dask_ml_tpu.core import shard_rows  # noqa: E402
from dask_ml_tpu.linear_model import SGDClassifier  # noqa: E402
from dask_ml_tpu.model_selection import HyperbandSearchCV  # noqa: E402

rng = np.random.RandomState(0)
X = rng.normal(size=(20_000, 12)).astype(np.float32)
y = (X @ rng.normal(size=12) > 0).astype(np.float32)

search = HyperbandSearchCV(
    SGDClassifier(tol=None),
    # a continuous distribution: Hyperband samples as many configs as
    # its largest bracket asks for without exhausting a finite grid
    {"alpha": loguniform(1e-6, 1e-1), "eta0": [0.01, 0.1, 0.5]},
    max_iter=27, random_state=0, verbose=True,
)
search.fit(shard_rows(X), shard_rows(y), classes=[0.0, 1.0])
print(f"best: {search.best_params_}  score={search.best_score_:.4f}")
print(f"budget: {search.metadata_['partial_fit_calls']} partial_fit calls "
      f"across {search.metadata_['n_models']} models")

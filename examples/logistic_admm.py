"""North-star #1: LogisticRegression(solver='admm') on sharded rows.

The whole ADMM solve — per-shard L-BFGS subproblems inside shard_map,
psum consensus, residual-based stopping — compiles to ONE XLA program
(reference: dask_glm pays a scheduler round-trip per outer iteration).
"""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import os

if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:  # older jax: XLA_FLAGS (set by the harness) covers it
    pass

import numpy as np  # noqa: E402

from dask_ml_tpu.core import shard_rows  # noqa: E402
from dask_ml_tpu.linear_model import LogisticRegression  # noqa: E402

rng = np.random.RandomState(0)
n, d = 200_000, 28  # HIGGS-shaped columns
X = rng.normal(size=(n, d)).astype(np.float32)
w_true = rng.normal(size=d)
y = (X @ w_true + 0.3 * rng.normal(size=n) > 0).astype(np.float32)

sX, sy = shard_rows(X), shard_rows(y)  # rows pad+shard over the mesh
clf = LogisticRegression(solver="admm", C=1e4, max_iter=30).fit(sX, sy)
print(f"train accuracy: {clf.score(sX, sy):.4f}")
print(f"n_iter_: {clf.n_iter_}  coef | {np.asarray(clf.coef_)[:4].round(3)}")

"""Out-of-core training: a block stream through partial_fit.

The model lives ON DEVICE; blocks stream through it and are dropped —
only one block is ever resident, so the total stream can exceed device
memory (the driver-verified >HBM path in bench.py uses this exact loop
at 70 x 1M-row blocks = 17.9 GB on a 16 GB chip).
"""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import os

if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:  # older jax: XLA_FLAGS (set by the harness) covers it
    pass

import numpy as np  # noqa: E402

from dask_ml_tpu.datasets import stream_classification_blocks  # noqa: E402
from dask_ml_tpu.linear_model import SGDClassifier  # noqa: E402

clf = SGDClassifier(random_state=0)
n_blocks, rows = 20, 4096
for i, (Xb, yb) in enumerate(
    stream_classification_blocks(n_blocks, rows, 32, seed=0)
):
    clf.partial_fit(Xb, yb, classes=[0.0, 1.0])
print(f"streamed {n_blocks * rows} rows through a device-resident model")
print(f"steps taken: {clf.t_:.0f}")

# --- the same loop fed from DISK through the native C++ loader --------
# (how a real out-of-core dataset flows: file -> parser -> device; the
# parser sustains ~363 MB/s on one core, and the prefetch ring keeps
# parsing overlapped with device compute)
import tempfile  # noqa: E402

from dask_ml_tpu.io import stream_csv_blocks  # noqa: E402

rng = np.random.RandomState(0)
with tempfile.NamedTemporaryFile("w", suffix=".csv", delete=False) as f:
    for _ in range(8):
        block = rng.normal(size=(2048, 9)).astype(np.float32)
        # last column is the label
        block[:, -1] = (block[:, 0] > 0).astype(np.float32)
        f.write("\n".join(
            ",".join(f"{v:.6g}" for v in row) for row in block) + "\n")
    csv_path = f.name

clf2 = SGDClassifier(random_state=0)
n_rows = 0
for blk in stream_csv_blocks(csv_path, 4096):
    Xb, yb = blk[:, :-1], blk[:, -1]
    clf2.partial_fit(Xb, yb, classes=[0.0, 1.0])
    n_rows += blk.shape[0]
pathlib.Path(csv_path).unlink()
print(f"loader-fed: {n_rows} rows from disk, steps {clf2.t_:.0f}")

"""Out-of-core training: a block stream through partial_fit.

The model lives ON DEVICE; blocks stream through it and are dropped —
only one block is ever resident, so the total stream can exceed device
memory (the driver-verified >HBM path in bench.py uses this exact loop
at 70 x 1M-row blocks = 17.9 GB on a 16 GB chip).
"""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)

import numpy as np  # noqa: E402

from dask_ml_tpu.datasets import stream_classification_blocks  # noqa: E402
from dask_ml_tpu.linear_model import SGDClassifier  # noqa: E402

clf = SGDClassifier(random_state=0)
n_blocks, rows = 20, 4096
for i, (Xb, yb) in enumerate(
    stream_classification_blocks(n_blocks, rows, 32, seed=0)
):
    clf.partial_fit(Xb, yb, classes=[0.0, 1.0])
print(f"streamed {n_blocks * rows} rows through a device-resident model")
print(f"steps taken: {clf.t_:.0f}")

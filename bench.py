"""Benchmark harness — prints ONE JSON line, always.

Measures the two BASELINE.md north-star workloads, reporting KMeans
Lloyd throughput (rows*iters/sec) as the primary metric and ADMM
logistic fit time as context.  ``vs_baseline`` is 1.0-normalized because
the reference publishes no absolute numbers (BASELINE.json :: published
== {}).

Environment-proofing (VERDICT.md round-1 item #1): backend acquisition
is guarded — if the preset TPU plugin fails to initialize, fall back to
CPU (with a smaller workload) rather than crash; each workload fails
soft; the JSON line is emitted no matter what.

Both workloads run their ENTIRE iteration loop as one XLA program
(lax.while_loop fusion); on TPU the Lloyd round additionally uses the
fused Pallas assign+reduce kernel (ops.lloyd) when enabled.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
import traceback

# Hard cap on total bench runtime.  A watchdog THREAD (not SIGALRM: Python
# signal handlers only run between bytecodes, and the wedge we guard
# against is the main thread blocked inside a PJRT C++ wait that releases
# the GIL) prints the JSON accumulated so far and exits 0, so the driver
# never records a bare rc=124 with no JSON line.
_BUDGET_S = int(os.environ.get("DASK_ML_TPU_BENCH_BUDGET_S", "480"))
_RESULT = {
    "metric": "kmeans_lloyd_rows_per_sec",
    "value": 0.0,
    "unit": "rows*iters/s (fp32)",
    "vs_baseline": 0.0,
    "extra": {},
}


def _emit_and_exit():
    _RESULT["extra"]["timed_out"] = True
    print(json.dumps(_RESULT), flush=True)
    os._exit(0)


def _tpu_backend_usable(probe_timeout_s: float = 75.0) -> bool:
    """Probe the preset (axon/TPU) backend in a SUBPROCESS with a hard
    timeout.  jax.devices() can hang forever (not just raise) when the
    TPU tunnel is down — round-1 MULTICHIP rc=124 — so an in-process
    try/except is not enough; only a killable child is safe."""
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        return False
    try:
        proc = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices(); print('OK')"],
            timeout=probe_timeout_s,
            capture_output=True,
            text=True,
        )
        return proc.returncode == 0 and "OK" in proc.stdout
    except (subprocess.TimeoutExpired, OSError):
        return False


def _acquire_backend():
    """Initialize a jax backend, falling back to CPU if the preset TPU
    plugin is unavailable or hung.  Returns (jax, platform)."""
    if not _tpu_backend_usable():
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            pass
        return jax, jax.devices()[0].platform
    import jax

    return jax, jax.devices()[0].platform


def main():
    watchdog = threading.Timer(_BUDGET_S, _emit_and_exit)
    watchdog.daemon = True
    watchdog.start()
    result = _RESULT
    extra = result["extra"]
    try:
        jax, platform = _acquire_backend()
    except Exception:
        extra["backend_error"] = traceback.format_exc(limit=3)
        watchdog.cancel()
        print(json.dumps(result))
        return

    import numpy as np
    import jax.numpy as jnp

    extra["platform"] = platform
    extra["n_devices"] = len(jax.devices())
    on_tpu = platform not in ("cpu",)
    rng = np.random.RandomState(0)

    # --- KMeans Lloyd throughput (north-star #2 shape, scaled to chip) ---
    try:
        from dask_ml_tpu.cluster.k_means import _lloyd_loop, _pallas_ok
        from dask_ml_tpu.core import shard_rows, get_mesh
        from dask_ml_tpu.core.mesh import MeshHolder

        n, d, k = (2_000_000, 50, 8) if on_tpu else (200_000, 50, 8)
        X = rng.normal(size=(n, d)).astype(np.float32)
        s = shard_rows(X)
        centers = s.data[:k]
        use_pallas = _pallas_ok(s.data, centers)
        mh = MeshHolder(get_mesh()) if use_pallas else None
        iters = 40
        # the trailing float() pull is the only reliable sync on the axon
        # relay (block_until_ready returns early); the loop may stop short
        # of `iters` at an exact fixed point, so throughput uses the ACTUAL
        # round count
        args = (s.data, s.mask, centers, jnp.float32(0.0), jnp.int32(iters))
        float(_lloyd_loop(*args, mesh_holder=mh, use_pallas=use_pallas)[1])
        t0 = time.perf_counter()
        out = _lloyd_loop(*args, mesh_holder=mh, use_pallas=use_pallas)
        float(out[1])  # force the whole chain
        dt = time.perf_counter() - t0
        n_rounds = max(int(out[2]), 1)
        result["value"] = round(n * n_rounds / dt, 1)
        result["unit"] = f"rows*iters/s ({n}x{d}, k={k}, fp32)"
        result["vs_baseline"] = 1.0
        extra["pallas_lloyd"] = bool(use_pallas)
        extra["lloyd_wall_s"] = round(dt, 3)
        extra["lloyd_rounds"] = n_rounds
        # roofline context: bytes touched per Lloyd round ~ n*d*4 (X read)
        extra["lloyd_gb_per_s"] = round(n * d * 4 * n_rounds / dt / 1e9, 2)
    except Exception:
        extra["lloyd_error"] = traceback.format_exc(limit=3)

    # --- ADMM logistic fit (north-star #1 shape, scaled) ---
    try:
        from dask_ml_tpu.core import shard_rows
        from dask_ml_tpu.linear_model import LogisticRegression

        n2, d2 = (1_000_000, 28) if on_tpu else (100_000, 28)
        w = rng.normal(size=d2).astype(np.float32)
        X2 = rng.normal(size=(n2, d2)).astype(np.float32)
        y2 = (1 / (1 + np.exp(-(X2 @ w))) > rng.uniform(size=n2)).astype(
            np.float32
        )
        sX2, sy2 = shard_rows(X2), shard_rows(y2)
        lr = LogisticRegression(solver="admm", C=1e4, max_iter=10)
        lr.fit(sX2, sy2)  # compile
        t0 = time.perf_counter()
        lr.fit(sX2, sy2)
        admm_fit_s = time.perf_counter() - t0
        extra[f"admm_logreg_fit_{n2}x{d2}_10iter_s"] = round(admm_fit_s, 3)
    except Exception:
        extra["admm_error"] = traceback.format_exc(limit=3)

    watchdog.cancel()
    print(json.dumps(result))


if __name__ == "__main__":
    main()

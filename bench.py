"""Benchmark harness — prints ONE JSON line.

Measures the two BASELINE.md north-star workloads on the available
hardware, reporting KMeans Lloyd throughput (rows·iters/sec) as the
primary metric and ADMM logistic fit time as context.  ``vs_baseline``
is 1.0-normalized because the reference publishes no absolute numbers
(BASELINE.json :: published == {}).

Both workloads run their ENTIRE iteration loop as one XLA program
(lax.while_loop fusion); on TPU the Lloyd round additionally uses the
fused Pallas assign+reduce kernel (ops.lloyd).
"""

from __future__ import annotations

import json
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    from dask_ml_tpu.cluster.k_means import _lloyd_loop, _pallas_ok
    from dask_ml_tpu.core import shard_rows, get_mesh
    from dask_ml_tpu.core.mesh import MeshHolder
    from dask_ml_tpu.linear_model import LogisticRegression

    rng = np.random.RandomState(0)

    # --- KMeans Lloyd throughput (north-star #2 shape, scaled to chip) ---
    n, d, k = 2_000_000, 50, 8  # make_blobs 100M x 50 config, scaled
    X = rng.normal(size=(n, d)).astype(np.float32)
    s = shard_rows(X)
    centers = s.data[:k]
    use_pallas = _pallas_ok(s.data, centers)
    mh = MeshHolder(get_mesh()) if use_pallas else None
    iters = 40
    # the trailing float() pull is the only reliable sync on the axon relay
    # (block_until_ready returns early); the loop may stop short of `iters`
    # at an exact fixed point, so throughput uses the ACTUAL round count
    args = (s.data, s.mask, centers, jnp.float32(0.0), jnp.int32(iters))
    float(_lloyd_loop(*args, mesh_holder=mh, use_pallas=use_pallas)[1])  # compile
    t0 = time.perf_counter()
    out = _lloyd_loop(*args, mesh_holder=mh, use_pallas=use_pallas)
    float(out[1])  # force the whole chain
    dt = time.perf_counter() - t0
    n_rounds = int(out[2])
    lloyd_rows_per_sec = n * n_rounds / dt

    # --- ADMM logistic fit (north-star #1 shape, scaled) ---
    d2 = 28
    w = rng.normal(size=d2).astype(np.float32)
    X2 = rng.normal(size=(1_000_000, d2)).astype(np.float32)
    y2 = (1 / (1 + np.exp(-(X2 @ w))) > rng.uniform(size=X2.shape[0])).astype(np.float32)
    sX2, sy2 = shard_rows(X2), shard_rows(y2)
    lr = LogisticRegression(solver="admm", C=1e4, max_iter=10)
    lr.fit(sX2, sy2)  # compile
    t0 = time.perf_counter()
    lr.fit(sX2, sy2)
    admm_fit_s = time.perf_counter() - t0

    print(
        json.dumps(
            {
                "metric": "kmeans_lloyd_rows_per_sec",
                "value": round(lloyd_rows_per_sec, 1),
                "unit": "rows*iters/s (2M x 50, k=8, fp32)",
                "vs_baseline": 1.0,
                "extra": {
                    "platform": jax.devices()[0].platform,
                    "n_devices": len(jax.devices()),
                    "pallas_lloyd": use_pallas,
                    "admm_logreg_fit_1m_x28_10iter_s": round(admm_fit_s, 3),
                },
            }
        )
    )


if __name__ == "__main__":
    main()

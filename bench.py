"""Benchmark harness — prints ONE JSON line, always.

Measures the two BASELINE.md north-star workloads, reporting KMeans
Lloyd throughput (rows*iters/sec) as the primary metric and ADMM
logistic fit time as context.  The reference publishes no absolute
numbers (BASELINE.json :: published == {}), so the normalization is
``vs_history``: each workload's headline metric against the BEST
same-platform record committed in BENCH_r*.json — the cross-round
regression gate (>1 = at least as good as any prior round; a >1.6x
headline regression emits a warning into ``extra`` and stderr).

Environment-proofing (VERDICT.md round-1 item #1): backend acquisition
is guarded — if the preset TPU plugin fails to initialize, fall back to
CPU (with a smaller workload) rather than crash; each workload fails
soft; the JSON line is emitted no matter what.

Both workloads run their ENTIRE iteration loop as one XLA program
(lax.while_loop fusion).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
import traceback

# Hard cap on total bench runtime.  A watchdog THREAD (not SIGALRM: Python
# signal handlers only run between bytecodes, and the wedge we guard
# against is the main thread blocked inside a PJRT C++ wait that releases
# the GIL) prints the JSON accumulated so far and exits 0, so the driver
# never records a bare rc=124 with no JSON line.
_BUDGET_S = int(os.environ.get("DASK_ML_TPU_BENCH_BUDGET_S", "480"))
_START_TS = time.time()
_RESULT = {
    "metric": "kmeans_lloyd_rows_per_sec",
    "value": 0.0,
    "unit": "rows*iters/s (fp32)",
    "vs_history": 0.0,
    "extra": {},
}

# Wedge insurance (round-2 postmortem: the axon tunnel died 6 h into the
# round and the whole session's on-chip measurements were lost because
# nothing was persisted until the final emit).  Every workload entry is
# appended to this JSONL file the INSTANT it is measured, fsync'd; the
# final emit — watchdog path included — merges entries from earlier runs
# so a crashed/wedged run's numbers survive into the next run's JSON.
_KNOWN_SECTIONS = {
    "lloyd", "admm", "tsqr", "scatter", "pairwise", "streamed", "packed",
    "csv", "recompile", "serve", "fleet", "search", "roofline", "ingest",
    "controller",
}
ONLY_SECTIONS = {
    s.strip()
    for s in os.environ.get("DASK_ML_TPU_BENCH_ONLY", "").split(",")
    if s.strip()
}
if ONLY_SECTIONS - _KNOWN_SECTIONS:
    # a typo here would silently measure nothing and emit a full-looking
    # JSON from carried-forward entries — fail loudly instead
    sys.exit(
        f"DASK_ML_TPU_BENCH_ONLY: unknown section(s) "
        f"{sorted(ONLY_SECTIONS - _KNOWN_SECTIONS)}; "
        f"known: {sorted(_KNOWN_SECTIONS)}"
    )


def _want(section):
    """Section filter for manual partial runs (DASK_ML_TPU_BENCH_ONLY=
    admm,scatter ...); skipped sections' numbers are carried forward from
    bench_partial.jsonl by the merge, so a filtered run still emits a
    full JSON line.  Unset (the driver's case) = run everything."""
    return not ONLY_SECTIONS or section in ONLY_SECTIONS


class _SkipSection(Exception):
    pass


_PARTIAL_PATH = os.environ.get(
    "DASK_ML_TPU_BENCH_PARTIAL",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "bench_partial.jsonl"),
)
_RUN_ID = f"{os.getpid()}-{int(_START_TS)}"


def _load_prior_partial():
    """Entries persisted by PREVIOUS bench runs (this run's are live).

    Reads the append log plus the git-TRACKED chip-evidence snapshot
    (bench_chip_evidence.jsonl) so a cleaned workspace cannot erase chip
    numbers; entries are sorted by their recorded ``ts`` so the merge's
    newest-first pass is order-independent across files (a stale partial
    log must not shadow newer committed evidence, or vice versa)."""
    prior = []
    here = os.path.dirname(os.path.abspath(__file__))
    for path in (os.path.join(here, "bench_chip_evidence.jsonl"),
                 _PARTIAL_PATH):
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if rec.get("run_id") != _RUN_ID:
                        prior.append(rec)
        except OSError:
            pass
    prior.sort(key=lambda r: r.get("ts", 0.0))
    return prior


_PRIOR = _load_prior_partial()

# Workload names whose definition/units changed; their old records must
# not be carried forward next to the redefined entry (r4: csv parse_mb_s
# went from output-array bytes/s to file-text bytes/s with a new size).
_RETIRED_WORKLOADS = {"csv_ingest_200000x32", "csv_ingest_50000x32",
                      "csv_ingest_1040000x32",
                      # r5: the coin-flip OvR A/B measured uncontrolled
                      # work (lane truncation differed per arm per
                      # realization; ratio swung 0.74x-3.4x) — replaced
                      # by packed_ovr_fixedwork_* with learnable targets
                      # and an executed-iteration validity gate
                      "packed_ovr_lbfgs_1000000x28_K4",
                      "packed_ovr_lbfgs_100000x16_K4",
                      # ISSUE 12: the ADMM bf16 design-matrix A/B was
                      # adjudicated negative (1.008x committed, 1.000x
                      # rerun — design.md §16) and its branch deleted;
                      # the stale records must not carry forward as if
                      # still measured
                      "admm_logreg_bf16_100000x28_10outer",
                      "admm_logreg_bf16_1000000x28_10outer",
                      "admm_logreg_bf16_11000000x28_10outer"}


def _persist(rec):
    rec = dict(rec)
    rec["run_id"] = _RUN_ID
    rec["ts"] = round(time.time(), 1)
    try:
        with open(_PARTIAL_PATH, "a") as f:
            f.write(json.dumps(rec) + "\n")
            f.flush()
            os.fsync(f.fileno())
    except OSError:
        pass


def _merge_and_finalize():
    """Fold prior-run partial entries into the result: any workload not
    re-measured this run is carried over (tagged), and if this run fell
    back to CPU while a prior run measured Lloyd on a real chip, the
    headline value is taken from the chip entry — a tunnel wedge must
    not make real numbers vanish behind a CPU fallback."""
    extra = _RESULT["extra"]
    workloads = extra.setdefault("workloads", [])
    have = {w.get("workload") for w in workloads}
    # newest-first so the freshest prior record per workload name wins
    for rec in reversed(_PRIOR):
        # only chip measurements are worth carrying across runs — a CPU
        # fallback number is reproducible on demand and would only add
        # noise to a later run's output; same policy for extras
        if rec.get("platform") in (None, "cpu"):
            continue
        if "_extra" in rec:
            # keep carried extras clearly separated from this run's own
            # measurements — a carried pallas_parity_ok must not read as
            # having been verified on this run's platform (historic
            # example: the deleted Pallas kernel's parity flag)
            for k, v in rec["_extra"].items():
                if k not in extra:
                    extra.setdefault("carried_extra", {}).setdefault(k, v)
            continue
        name = rec.get("workload")
        if name in _RETIRED_WORKLOADS:
            continue
        if name and name not in have:
            carried = {k: v for k, v in rec.items() if k != "run_id"}
            carried["from_partial"] = True
            workloads.append(carried)
            have.add(name)
    # headline rescue fires when Lloyd went unmeasured (a chip run that
    # wedged mid-bench) OR when this run fell back to CPU — in both cases
    # a real chip number, however old, beats what this run produced
    if not _RESULT["value"] or extra.get("platform", "cpu") == "cpu":
        chip_lloyd = [
            w for w in workloads
            if w.get("workload", "").startswith("kmeans_lloyd")
            and w.get("platform") not in (None, "cpu")
            and "rows_per_s" in w
        ]
        if chip_lloyd:
            best = max(chip_lloyd, key=lambda w: w["rows_per_s"])
            _RESULT["value"] = best["rows_per_s"]
            _RESULT["unit"] = "rows*iters/s (fp32, carried from chip run)"
            _vh = _vs_history(best)  # carried entries carry platform
            _RESULT["vs_history"] = 1.0 if _vh is None else _vh
            extra["headline_platform"] = best.get("platform")
            # age-stamp carried evidence so a reader of the compact line
            # cannot mistake it for a fresh measurement (VERDICT r4
            # weak #3)
            if best.get("ts"):
                extra["headline_evidence_age_days"] = round(
                    (time.time() - best["ts"]) / 86400, 1)


def _compact_partial():
    """After a successful full emit, rewrite the partial file keeping only
    the freshest chip record per workload name (plus chip extras) so the
    file cannot grow without bound across rounds."""
    keep, seen = [], set()
    recs = []
    try:
        with open(_PARTIAL_PATH) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    recs.append(json.loads(line))
                except ValueError:
                    continue  # torn tail line from a killed run
    except OSError:
        return
    for rec in reversed(recs):
        # same chip-only policy for extras as for workloads: a
        # CPU-measured speedup ratio must not masquerade as chip evidence
        if rec.get("platform") in (None, "cpu"):
            continue
        if rec.get("workload") in _RETIRED_WORKLOADS:
            continue
        if "_extra" in rec:
            key = ("_extra", tuple(sorted(rec["_extra"])))
        else:
            key = ("w", rec.get("workload"))
        if key in seen:
            continue
        seen.add(key)
        keep.append(rec)
    # temp + rename: a kill or ENOSPC mid-rewrite must not destroy the
    # chip history this file exists to protect
    tmp = _PARTIAL_PATH + ".tmp"
    try:
        with open(tmp, "w") as f:
            for rec in reversed(keep):
                f.write(json.dumps(rec) + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, _PARTIAL_PATH)
    except OSError:
        pass


_FULL_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "BENCH_FULL.json"
)

# One number per workload on the compact line, first match wins.
_HEADLINE_KEYS = (
    "rows_per_s", "per_round_ms", "per_eval_ms", "per_qr_ms",
    "per_step_ms", "parse_mb_s", "packed_speedup", "sweep_speedup",
    "probe_grid_speedup", "speedup", "overlap_speedup",
)

# headline metrics where SMALLER is better (everything else: bigger)
_LOWER_BETTER = frozenset({
    "per_round_ms", "per_eval_ms", "per_qr_ms", "per_step_ms",
})

#: a workload whose headline metric falls below 1/this of its best
#: committed record is flagged as a regression (VERDICT r5 weak #3/#5)
_REGRESSION_FACTOR = 1.6


def _load_history():
    """Committed records per (workload, platform) from the
    BENCH_r*.json round files: ``{(name, platform): {"key", "values":
    [v, ...], "rounds": [r, ...]}}``.  Only same-metric-key records
    compare (a workload whose unit changed rounds ago must not gate
    today's number).

    Two hardenings from the r04→r05 Lloyd 0.546x root-cause (ISSUE 12):
    records flagged ``carried`` are SKIPPED — a carried-forward number
    is an echo of an earlier round's measurement, not an independent
    committed record, and counting it once per round laundered one
    outlier into "history" — and ALL values are kept so the comparator
    can use a robust reference instead of the single best."""
    import glob

    hist = {}
    here = os.path.dirname(os.path.abspath(__file__))
    for path in sorted(glob.glob(os.path.join(here, "BENCH_r[0-9]*.json"))):
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        parsed = doc.get("parsed") if isinstance(doc, dict) else None
        if not isinstance(parsed, dict):
            continue
        rnd = os.path.basename(path)
        for w in (parsed.get("extra") or {}).get("workloads") or []:
            name, plat = w.get("w"), w.get("p")
            key = next((k for k in _HEADLINE_KEYS if k in w), None)
            if not name or key is None or w.get("carried"):
                continue
            try:
                val = float(w[key])
            except (TypeError, ValueError):
                continue
            if val <= 0:
                continue
            cur = hist.get((name, plat))
            if cur is not None and cur["key"] != key:
                continue  # redefined metric: first-seen key wins
            if cur is None:
                cur = hist[(name, plat)] = {"key": key, "values": [],
                                            "rounds": []}
            cur["values"].append(val)
            cur["rounds"].append(rnd)
    return hist


_HISTORY_CACHE = None


def _history():
    global _HISTORY_CACHE
    if _HISTORY_CACHE is None:
        _HISTORY_CACHE = _load_history()
    return _HISTORY_CACHE


def _vs_history(entry):
    """This entry's headline metric over the committed same-platform
    reference of the same workload (normalized so > 1.0 = at least as
    good); None when there is no comparable history.

    The reference is the MEDIAN of committed records once three or more
    exist, else the best.  Rationale (the r04→r05 Lloyd root-cause,
    ISSUE 12): every headline is a two-point-slope statistic whose lo
    anchor can absorb a transient (a tunnel-RTT hiccup during the lo
    run inflated one chip session's Lloyd throughput ~1.8x over seven
    agreeing sessions), and a best-of comparator ratchets on exactly
    those outliers — the wall clocks of the hi runs were flat across
    all eight sessions while vs_history screamed 0.546x."""
    name = entry.get("workload")
    key = next((k for k in _HEADLINE_KEYS if k in entry), None)
    if not name or key is None:
        return None
    prior = _history().get((name, entry.get("platform")))
    if prior is None or prior["key"] != key or not prior["values"]:
        return None
    try:
        cur = float(entry[key])
    except (TypeError, ValueError):
        return None
    if cur <= 0:
        return None
    import statistics

    vals = sorted(prior["values"])
    if len(vals) >= 3:
        ref = statistics.median(vals)
    else:
        ref = vals[0] if key in _LOWER_BETTER else vals[-1]
    ratio = ref / cur if key in _LOWER_BETTER else cur / ref
    return round(ratio, 3)


def _compact_line(result):
    """Final stdout line guaranteed to fit the driver's 2000-char stdout
    tail (round-3 postmortem: the full JSON outgrew the tail and the
    round's official record became an unparseable truncated string —
    BENCH_r03.json :: parsed == null).  The FULL payload is written to
    BENCH_FULL.json; this line carries the headline metric plus one
    number per workload."""
    extra = result.get("extra", {})
    ws = []
    for w in extra.get("workloads", []):
        ent = {"w": w.get("workload"),
               "p": w.get("platform", extra.get("platform"))}
        for k in _HEADLINE_KEYS:
            if k in w:
                ent[k] = w[k]
                break
        if "vs_history" in w:
            ent["h"] = w["vs_history"]
        if "decision" in w:
            ent["d"] = w["decision"]
        # graftscope occupancy: the bench trajectory's device-idle
        # currency, one utilization + idle-seconds pair per workload
        w_obs = w.get("obs") or {}
        if "device_util" in w_obs:
            ent["util"] = w_obs["device_util"]
            ent["idle_s"] = w_obs["device_idle_s"]
        # graftlock contention: this workload's lock-wait delta rides
        # the compact line next to the obs totals block
        if "lock_wait_s" in w_obs:
            ent["lkw_s"] = w_obs["lock_wait_s"]
        if w.get("from_partial"):
            ent["carried"] = True
        ws.append(ent)
    compact = {
        "metric": result.get("metric"),
        "value": result.get("value"),
        "unit": result.get("unit"),
        "vs_history": result.get("vs_history"),
        "extra": {
            "platform": extra.get("platform"),
            "n_devices": extra.get("n_devices"),
            "timed_out": extra.get("timed_out", False),
            "headline_platform": extra.get("headline_platform"),
            "headline_evidence_age_days": extra.get(
                "headline_evidence_age_days"),
            "full_payload": "BENCH_FULL.json",
            "workloads": ws,
        },
    }
    if extra.get("obs_totals"):
        # grafttrace session totals (compiles / stalls / retries) — the
        # compact observability trend; per-workload deltas are in the
        # full payload's per-entry "obs" blocks
        compact["extra"]["obs"] = extra["obs_totals"]
    if extra.get("full_payload_write_failed"):
        compact["extra"]["full_payload_write_failed"] = True
    line = json.dumps(compact)
    while len(line) > 1900 and ws:
        ws.pop()
        compact["extra"]["workloads_truncated"] = True
        line = json.dumps(compact)
    return line


def _emit_final(result):
    """Write the full payload to BENCH_FULL.json (temp + rename, so a
    kill or ENOSPC mid-write cannot leave a truncated file masquerading
    as this run's record), then print the compact line — flagged if the
    full write failed, so the pointer is never silently stale."""
    tmp = _FULL_PATH + ".tmp"
    try:
        with open(tmp, "w") as f:
            json.dump(result, f, indent=1)
            f.write("\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, _FULL_PATH)
    except Exception:
        result.setdefault("extra", {})["full_payload_write_failed"] = True
    try:
        print(_compact_line(result), flush=True)
    except Exception:
        print(json.dumps({"metric": result.get("metric", "bench"),
                          "value": result.get("value", 0.0),
                          "unit": result.get("unit", ""),
                          "vs_history": result.get("vs_history", 0.0),
                          "extra": {"emit_error": True}}), flush=True)


def _emit_and_exit():
    # every step guarded: this runs in the watchdog thread while the main
    # thread may be mutating _RESULT['extra'] mid-dict-insert — an
    # unhandled "dict changed size during iteration" here would skip
    # os._exit and reproduce the rc=124-no-JSON failure this exists for
    try:
        _RESULT["extra"]["timed_out"] = True
        _merge_and_finalize()
    except Exception:
        pass
    for attempt in range(3):
        try:
            import copy

            _emit_final(copy.deepcopy(_RESULT))
            break
        except Exception:
            time.sleep(0.05)
    else:
        try:
            print(json.dumps({"metric": _RESULT["metric"], "value": 0.0,
                              "unit": _RESULT["unit"], "vs_history": 0.0,
                              "extra": {"timed_out": True,
                                        "emit_race": True}}), flush=True)
        except Exception:
            pass
    os._exit(0)


def _time_once(fn):
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


def _ab_stats(fn_a, fn_b, reps=5):
    """Interleaved A/B wall timing with dispersion, for policy
    adjudications.  The arms alternate every rep (and the starting arm
    flips each round) so drift — page-cache warmup, thermal, background
    load — lands on both arms equally; each arm reports median + IQR
    over ``reps`` samples.  A winner is declared ONLY when the arms'
    [q1, q3] intervals are disjoint; otherwise the decision is
    ``"undecided"`` (round-4 lesson: the same nominal workload's A/B
    ratio swung 0.416×–0.744× across single-shot runs, and a policy
    default was being flipped by one noisy ratio).

    Returns ``(stats_a, stats_b, decision)`` where each stats dict is
    ``{median_s, iqr_s, reps}`` and decision is ``"a" | "b" |
    "undecided"``."""
    fn_a(); fn_b()  # compile/warm both arms
    ta, tb = [], []
    for r in range(reps):
        pair = ((fn_a, ta), (fn_b, tb))
        if r % 2:
            pair = pair[::-1]
        for fn, acc in pair:
            t0 = time.perf_counter()
            fn()
            acc.append(time.perf_counter() - t0)
    sa, sb, decision = _iqr_decide(ta, tb)
    for s in (sa, sb):
        s["median_s"] = round(s["median_s"], 4)
        s["iqr_s"] = round(s["iqr_s"], 4)
    return sa, sb, decision


def _iqr_decide(ts_a, ts_b):
    """THE adjudication rule, shared by every A/B form (wall-time and
    slope): per-arm median + IQR, winner only when the [q1, q3]
    intervals are disjoint.  One implementation so the two measurement
    styles can never drift onto different decision criteria."""
    import numpy as np

    def stats(ts):
        q1, med, q3 = np.percentile(ts, [25, 50, 75])
        return (
            {"median_s": float(med), "iqr_s": float(q3 - q1),
             "reps": len(ts)},
            float(q1), float(q3),
        )

    sa, a1, a3 = stats(ts_a)
    sb, b1, b3 = stats(ts_b)
    if a3 < b1:
        decision = "a"
    elif b3 < a1:
        decision = "b"
    else:
        decision = "undecided"
    return sa, sb, decision


def _slope_ab(fn_a, fn_b, lo_i, hi_i, reps=5):
    """A/B of per-iteration SLOPES with the same interleaving/dispersion
    discipline as ``_ab_stats``: each rep measures one two-point slope
    per arm (arms alternate, starting arm flips), so the relay's
    constant RTT cancels within each slope and drift cancels across
    arms.  Returns ``(stats_a, stats_b, decision)`` with per-iteration
    medians in ``median_s``."""
    fn_a(hi_i); fn_b(hi_i)  # compile both
    sl_a, sl_b = [], []
    for r in range(reps):
        pair = ((fn_a, sl_a), (fn_b, sl_b))
        if r % 2:
            pair = pair[::-1]
        for fn, acc in pair:
            t_lo = _time_once(lambda: fn(lo_i))
            t_hi = _time_once(lambda: fn(hi_i))
            acc.append(max((t_hi - t_lo) / (hi_i - lo_i), 1e-9))
    return _iqr_decide(sl_a, sl_b)


def _two_point_slope(fn, lo_i, hi_i, reps=3):
    """Best-of-``reps`` wall time at two chained-iteration counts; the
    slope cancels the constant RTT/dispatch cost (the only honest
    per-iteration time on the axon relay — see module docstring).
    ``fn`` takes the iteration count, must sync internally (fetch a
    scalar), and must hit ONE jit executable for both counts (convert
    the count to a consistent aval inside ``fn``)."""
    fn(hi_i)  # compile
    ts = {}
    for n_i in (lo_i, hi_i):
        best_t = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            fn(n_i)
            best_t = min(best_t, time.perf_counter() - t0)
        ts[n_i] = best_t
    return max((ts[hi_i] - ts[lo_i]) / (hi_i - lo_i), 1e-9)


def _tpu_backend_usable(probe_timeout_s: float = 75.0) -> bool:
    """Probe the preset (axon/TPU) backend in a SUBPROCESS with a hard
    timeout.  jax.devices() can hang forever (not just raise) when the
    TPU tunnel is down — round-1 MULTICHIP rc=124 — so an in-process
    try/except is not enough; only a killable child is safe."""
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        return False
    try:
        proc = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices(); print('OK')"],
            timeout=probe_timeout_s,
            capture_output=True,
            text=True,
        )
        return proc.returncode == 0 and "OK" in proc.stdout
    except (subprocess.TimeoutExpired, OSError):
        return False


def _acquire_backend():
    """Initialize a jax backend, falling back to CPU if the preset TPU
    plugin is unavailable or hung.  Returns (jax, platform)."""
    if not _tpu_backend_usable():
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            pass
        return jax, jax.devices()[0].platform
    import jax

    return jax, jax.devices()[0].platform


def main():
    watchdog = threading.Timer(_BUDGET_S, _emit_and_exit)
    watchdog.daemon = True
    watchdog.start()
    result = _RESULT
    extra = result["extra"]
    try:
        jax, platform = _acquire_backend()
    except Exception:
        extra["backend_error"] = traceback.format_exc(limit=3)
        watchdog.cancel()
        try:
            _merge_and_finalize()
        except Exception:
            pass
        _emit_final(result)
        return

    import numpy as np
    import jax.numpy as jnp

    extra["platform"] = platform
    extra["n_devices"] = len(jax.devices())
    on_tpu = platform not in ("cpu",)
    rng = np.random.RandomState(0)

    # Roofline peaks for judging bw_frac / mfu: ONE source of truth now
    # — obs.roofline's per-platform table (measured cpu, assumed tpu
    # v5e, DASK_ML_TPU_PEAKS-overridable), so the bench's MFU columns
    # and device_report()'s roofline_frac can never disagree about what
    # the machine can do.  The legacy DASK_ML_TPU_PEAK_* knobs still
    # win when set (api.md bench-harness rows).
    from dask_ml_tpu.obs import roofline as _roofline

    _pk = _roofline.peaks_for(platform) or {}
    peak_gb_s = float(os.environ.get(
        "DASK_ML_TPU_PEAK_GB_S",
        _pk.get("bytes_per_s", 819e9 if on_tpu else 50e9) / 1e9))
    peak_tflops = float(os.environ.get(
        "DASK_ML_TPU_PEAK_FP32_TFLOPS",
        _pk.get("flops_per_s", 49e12 if on_tpu else 1e12) / 1e12))
    _legacy_env = any(os.environ.get(k) for k in
                      ("DASK_ML_TPU_PEAK_GB_S",
                       "DASK_ML_TPU_PEAK_FP32_TFLOPS"))
    extra["assumed_peaks"] = {
        "hbm_gb_s": peak_gb_s, "fp32_tflops": peak_tflops,
        # provenance honesty: an operator override must never carry the
        # peak table's "measured" label
        "source": ("env (legacy DASK_ML_TPU_PEAK_*)" if _legacy_env
                   else _pk.get("source", "legacy fallback")),
    }
    workloads = extra["workloads"] = []

    # grafttrace counters ride every workload record: install the
    # compile listener (counters only, no span recording — benches want
    # zero tracing overhead) and snapshot-delta the registry per record
    # so BENCH_r*.json trends compiles / pipeline stalls / retries
    # alongside throughput.
    from dask_ml_tpu import obs as _obs

    _obs.install_jax_hooks()
    # graftlock contention: arm the lock monitor for the whole bench so
    # every package NamedLock books lock.wait_s/held_s into the same
    # registry; per-workload wait deltas ride the obs blocks below and
    # the compact line (violations are not gated here — that is the
    # lint.sh --locks ratchet's job, on the smoke suite, not the bench)
    try:
        from dask_ml_tpu import _locks as _named_locks
        from dask_ml_tpu.sanitize import locks as _graftlock

        if _named_locks.monitor() is None:
            _named_locks.set_monitor(_graftlock.LockMonitor())
    except Exception:
        extra["lock_monitor_error"] = traceback.format_exc(limit=2)
    _obs_prev = {}
    _scope_cursor = {"pos": 0}

    class _spans_armed:
        """Arm span recording (ring-only) around one A/B section.

        The bench keeps tracing OFF globally (counters only, zero span
        overhead on the throughput workloads); the graftpath critical
        sections need the span timeline, so the A/B sections arm it
        for exactly their own duration — overhead is bounded at <=3%
        of traced wall by the committed obs ratchet, far inside the
        A/B dispersion gates, and BOTH arms of a pair run armed so the
        comparison stays fair."""

        def __enter__(self):
            self._was = _obs.enabled()
            if not self._was:
                _obs.enable()
            return self

        def __exit__(self, *exc):
            if not self._was:
                _obs.disable()
            return False

    def _critical_arm():
        """Compact graftpath verdict of the arm that just finished
        (the most recent root span): the bottleneck class + evidence
        numbers each A/B arm records so a saturation-pinned pair is
        LABELLED by the tool, not argued in prose."""
        try:
            cp = _obs.critical_path()
            return {
                "verdict": cp["verdict"]["class"],
                "confidence": cp["verdict"].get("confidence"),
                "overlap_efficiency": cp.get("overlap_efficiency"),
                "shares": cp.get("shares"),
            }
        except Exception:  # observability must never sink a bench
            return None

    def _pair_critical(arms: dict, cpu_over_walls) -> dict:
        """The pair-level `critical` block: each arm's verdict plus the
        machine-readable saturation label — when EVERY arm's
        cpu_over_wall is ~1 the host core(s) were the binding resource
        in both arms and the wall ratio carries no overlap information
        (the 1-CPU-core gate-box failure mode the ROADMAP names)."""
        cw = [c for c in cpu_over_walls if c is not None]
        return {
            **arms,
            "saturation_pinned": bool(cw and min(cw) >= 0.9),
        }

    def _obs_read():
        """Current registry scalars — the ONE key list both the
        per-workload deltas and the end-of-run obs_totals use."""
        reg = _obs.registry()
        # graftscope device seconds: sum over the per-program busy
        # histogram family (tags = program names)
        dev_busy = 0.0
        lock_wait = 0.0
        for name, _tag, inst in reg.export_items():
            if name == "device.busy_s":
                dev_busy += inst.sum
            elif name == "lock.wait_s":
                lock_wait += inst.sum
        return {
            # µs-scale when uncontended — keep 6 decimals so a real
            # contention delta is visible, not rounded into the floor
            "lock_wait_s": round(lock_wait, 6),
            "compiles": reg.counter("compile.count").value,
            "compile_s": round(
                reg.histogram("compile.duration_s").sum, 3),
            "pipeline_stall_s": round(
                reg.histogram("pipeline.stall_s").sum, 3),
            "pipeline_hidden_s": round(
                reg.histogram("pipeline.hidden_s").sum, 3),
            "device_busy_s": round(dev_busy, 3),
            "device_dispatches": sum(
                reg.family("device.dispatches").values()),
            "retries": sum(reg.family("resilience.retry").values()),
            "faults": sum(reg.family("resilience.fault").values()),
        }

    def _obs_delta():
        """Registry movement since the previous _record call: compact
        scalars only (counts and stage sums, no histograms)."""
        cur = _obs_read()
        delta = {}
        for k, v in cur.items():
            d = v - _obs_prev.get(k, 0)
            if d < 0:  # a reset_*() inside a section restarted the books
                d = v
            delta[k] = round(d, 6 if k == "lock_wait_s" else 3)
        _obs_prev.update(cur)
        out = {k: (int(v) if k in ("compiles", "retries", "faults",
                                   "device_dispatches")
                   else v)
               for k, v in delta.items() if v}
        # per-workload occupancy over THIS record's dispatch window
        # (graftscope cursor delta): utilization + idle seconds — the
        # device-idle budget currency, per workload, in the trajectory
        try:
            dev = _obs.scope.device_report(since=_scope_cursor["pos"],
                                           settle_s=1.0)
            _scope_cursor["pos"] = _obs.scope.cursor()
            if dev["dispatches"]:
                out["device_util"] = dev["utilization"]
                out["device_idle_s"] = dev["idle_s"]
        except Exception:  # observability must never sink a bench
            pass
        return out

    def _record(entry):
        """Append a measured workload AND persist it immediately, stamped
        with its ``vs_history`` ratio against the best committed
        same-platform round record (the cross-round regression gate —
        VERDICT r5 weak #3/#5); >1.6x regressions warn loudly."""
        entry = dict(entry)
        entry.setdefault("platform", platform)
        try:
            obs_block = _obs_delta()
            if obs_block:
                entry.setdefault("obs", obs_block)
        except Exception:  # observability must never sink a bench
            pass
        vh = _vs_history(entry)
        if vh is not None:
            entry["vs_history"] = vh
            # warnings gate on CHIP records only: CPU numbers come from
            # whatever host the driver landed on (2-core sandbox vs a
            # prior round's fat box) and cross-round CPU ratios are
            # platform noise, not regressions — same chip-only evidence
            # policy as the partial-file carry
            if (vh < 1.0 / _REGRESSION_FACTOR
                    and entry.get("platform") not in (None, "cpu")):
                msg = (
                    f"{entry.get('workload')}: {vh}x of its best committed "
                    f"record (> {_REGRESSION_FACTOR}x regression)"
                )
                extra.setdefault("regression_warnings", []).append(msg)
                print(f"bench: REGRESSION {msg}", file=sys.stderr)
        workloads.append(entry)
        _persist(entry)

    def _record_extra(key, value):
        extra[key] = value
        _persist({"_extra": {key: value}, "platform": platform})

    def _time_lloyd(s, centers, n, d, k, iters,
                    mode="highest"):
        from dask_ml_tpu.cluster.k_means import _lloyd_loop

        # Sync discipline (measured on the axon relay this session):
        # block_until_ready returns BEFORE remote execution completes, and
        # every result fetch carries a ~70 ms tunnel round-trip.  The only
        # honest per-iteration time is therefore the SLOPE between two
        # fetched runs of different iteration counts — the RTT and any
        # constant dispatch cost cancel.  tol=0 keeps the loop from
        # converging early, so the round counts are exact.
        from dask_ml_tpu.ops.scatter import scatter_strategy

        scatter = scatter_strategy(k)  # resolved OUTSIDE the jit (static)

        def run(n_it):
            # fresh (k,d) copy per call: the cached loop DONATES its
            # centers operand (ISSUE 12) — reusing one buffer across
            # timed runs would dispatch a deleted array.  The copy is
            # one tiny on-device op, invisible next to 40 fused rounds.
            out = _lloyd_loop(
                s.data, s.mask, jnp.array(centers), jnp.float32(0.0),
                jnp.int32(n_it), mode=mode, scatter=scatter,
            )
            float(out[1])  # result fetch = the one reliable sync
            return int(out[2])  # rounds ACTUALLY executed (the loop may
            # hit an exact fixed point before n_it even at tol=0)

        lo, hi = max(iters // 10, 1), iters
        run(hi)  # compile both counts (same executable: iters is traced)
        times, rounds = {}, {}
        for n_it in (lo, hi):
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                rounds[n_it] = run(n_it)
                best = min(best, time.perf_counter() - t0)
            times[n_it] = best
        per_iter = max(
            (times[hi] - times[lo]) / max(rounds[hi] - rounds[lo], 1), 1e-9
        )
        # per round: assign gemm 2ndk + onehot-reduce gemm 2ndk flops;
        # minimum HBM traffic = one X read (n*d*4B) per round
        flops = 4.0 * n * d * k
        gbytes = n * d * 4 / 1e9
        return {
            "workload": (
                f"kmeans_lloyd_{n}x{d}_k{k}_xla"
                + ("" if mode == "highest" else f"_{mode}")
            ),
            "wall_s": round(times[hi], 3),
            "rounds": rounds[hi],
            "per_iter_ms": round(per_iter * 1e3, 3),
            "rows_per_s": round(n / per_iter, 1),
            "achieved_gb_s": round(gbytes / per_iter, 2),
            "bw_frac": round(gbytes / per_iter / peak_gb_s, 4),
            "achieved_tflops": round(flops / per_iter / 1e12, 3),
            "mfu": round(flops / per_iter / 1e12 / peak_tflops, 4),
        }

    section_s = extra["section_s"] = {}
    _t_sec = time.time()

    # --- KMeans Lloyd throughput (north-star #2 shape, scaled to chip) ---
    try:
        if not _want("lloyd"):
            raise _SkipSection
        from dask_ml_tpu.core import shard_rows

        n, d, k = (2_000_000, 50, 8) if on_tpu else (200_000, 50, 8)
        X = rng.normal(size=(n, d)).astype(np.float32)
        s = shard_rows(X)
        centers = s.data[:k]
        iters = 40

        xla_stats = _time_lloyd(s, centers, n, d, k, iters)
        _record(xla_stats)
        best = xla_stats
        # (The opt-in Pallas kernel this section used to parity-check and
        # A/B was deleted after its chip adjudication: XLA won 0.089-
        # 0.176x at this shape and 0.198x at k=64 — docs/design.md
        # "Pallas negative result".)

        result["value"] = best["rows_per_s"]
        result["unit"] = f"rows*iters/s ({n}x{d}, k={k}, fp32)"
        # headline regression gate: this run's Lloyd throughput vs the
        # best committed same-platform round (1.0 when no history).
        # platform is attached explicitly: ``best`` is the raw timing
        # dict, and _record stamps platform onto its own COPY only
        vh = _vs_history({**best, "platform": platform})
        result["vs_history"] = 1.0 if vh is None else vh

        # --- k=64 fast-mode adjudication: at large k the per-round gemms
        # are MXU-bound and the 6-pass bf16-split "fast" precision can
        # beat 12-pass HIGHEST (chip-measured 1.362x, r5).  DEEP-budget
        # only on TPU: the variants' compiles would starve the driver's
        # default 480 s window; the auto-trigger/manual runs get it.
        if on_tpu and _BUDGET_S < 900:
            _record_extra("lloyd_k64_skipped",
                          f"deep-budget only (budget={_BUDGET_S}s < 900)")
            raise _SkipSection
        n64, d64, k64 = (1_000_000, 64, 64) if on_tpu else (100_000, 64, 64)
        X64 = rng.normal(size=(n64, d64)).astype(np.float32)
        s64 = shard_rows(X64)
        c64 = s64.data[:k64]
        it64 = 20
        xla_hi64 = _time_lloyd(s64, c64, n64, d64, k64, it64)
        _record(xla_hi64)
        xla_fast64 = _time_lloyd(s64, c64, n64, d64, k64, it64,
                                 mode="fast")
        _record(xla_fast64)
        _record_extra("lloyd_k64_xla_fast_vs_highest", round(
            xla_hi64["per_iter_ms"] / xla_fast64["per_iter_ms"], 3))
    except _SkipSection:
        pass
    except Exception:
        extra["lloyd_error"] = traceback.format_exc(limit=3)

    section_s["lloyd"] = round(time.time() - _t_sec, 1)
    _t_sec = time.time()

    # --- ADMM logistic fit (north-star #1, HIGGS shape scaled to chip) ---
    try:
        if not _want("admm"):
            raise _SkipSection
        from dask_ml_tpu.core import shard_rows
        from dask_ml_tpu.linear_model import LogisticRegression

        # Full HIGGS rows (11M) only on a DEEP budget (manual
        # DASK_ML_TPU_BENCH_ONLY=admm runs): measured on chip, the 11M
        # section costs ~7 min of front-loaded compiles + slope runs,
        # which overruns the driver's 480 s budget — and a watchdog
        # os._exit mid-fetch wedges the axon tunnel for every later
        # process (observed twice).  The driver's run measures 1M rows
        # fresh and carries the 11M entries from the deep run's partial
        # file; both appear in the final JSON under distinct names.
        deep = _BUDGET_S >= 900 and (
            (time.time() - _START_TS) < _BUDGET_S * 0.45
        )
        n2, d2 = (
            (11_000_000 if deep else 1_000_000, 28) if on_tpu
            else (100_000, 28)
        )
        # generate ON device: host datagen + 1.2 GB ingest over the axon
        # tunnel costs ~65 s that says nothing about the framework
        from dask_ml_tpu.core.sharded import ShardedRows
        from dask_ml_tpu.core.sharded import row_sharding
        from dask_ml_tpu.core.mesh import get_mesh as _get_mesh

        mesh2 = _get_mesh()
        n_sh = mesh2.shape["data"]
        n2 -= n2 % n_sh  # keep rows an exact shard multiple

        @jax.jit
        def _gen(key):
            kw, kx, ku = jax.random.split(key, 3)
            w = jax.random.normal(kw, (d2,), jnp.float32)
            X = jax.random.normal(kx, (n2, d2), jnp.float32)
            p = jax.nn.sigmoid(X @ w)
            y = (p > jax.random.uniform(ku, (n2,))).astype(jnp.float32)
            return X, y

        Xd, yd = _gen(jax.random.PRNGKey(0))
        ones = jnp.ones((n2,), jnp.float32)
        sh2, sh1 = row_sharding(mesh2, 2), row_sharding(mesh2, 1)
        sX2 = ShardedRows(data=jax.device_put(Xd, sh2),
                          mask=jax.device_put(ones, sh1), n_samples=n2)
        sy2 = ShardedRows(data=jax.device_put(yd, sh1),
                          mask=sX2.mask, n_samples=n2)
        admm_iters, inner = 10, 30

        # end-to-end fit once, for accuracy + the sklearn-contract path
        lr = LogisticRegression(
            solver="admm", C=1e4, max_iter=admm_iters,
            solver_kwargs={"inner_iter": inner},
        )
        lr.fit(sX2, sy2)
        # accuracy ON DEVICE, one scalar fetch: lr.score pulls the full
        # 11M-row prediction vector to host, and device->host transfers
        # of that size take minutes on the axon relay (and can wedge the
        # tunnel entirely — observed this session)
        @jax.jit
        def _device_acc(xd, yd, mask, coef, intercept):
            pred = (xd @ coef + intercept) > 0
            hit = (pred == (yd > 0.5)).astype(jnp.float32) * mask
            return jnp.sum(hit) / jnp.maximum(jnp.sum(mask), 1.0)

        acc = float(_device_acc(
            sX2.data, sy2.data, sX2.mask,
            jnp.asarray(lr.coef_), jnp.float32(lr.intercept_),
        ))

        # Per-outer-round timing drives the SOLVER entry point directly:
        # the estimator wrapper's host-side chatter costs ~2 s of tunnel
        # round-trips per fit with ±0.5 s jitter, which swamps the slope.
        # A direct admm() call is one dispatch + one result fetch.  Same
        # slope discipline as Lloyd; tolerances 0 so the outer loop runs
        # exactly max_iter rounds (the inner L-BFGS count stays adaptive —
        # hence no bw/mfu claim; see logreg_value_and_grad below).
        from dask_ml_tpu.linear_model.utils import add_intercept
        from dask_ml_tpu.solvers import admm as admm_solver
        from dask_ml_tpu.solvers.regularizers import L2

        sXi = add_intercept(sX2)
        lo_it, hi_it = 2, 20

        def solve(n_outer, design, ls="backtrack"):
            beta, n_it = admm_solver(
                design, sy2, lamduh=1e-4, max_iter=n_outer,
                regularizer=L2, inner_iter=inner,
                abstol=0.0, reltol=0.0, inner_tol=0.0,
                return_n_iter=True, line_search=ls,
            )
            np.asarray(beta)  # result fetch = the one reliable sync
            return beta, int(n_it)

        def slope_time(fn, reps=3):
            """_two_point_slope + capture of the last result (for the
            parity gate); max_iter is traced, so both counts hit one
            executable."""
            last = None

            def run(n_outer):
                nonlocal last
                last = fn(n_outer)

            per = _two_point_slope(run, lo_it, hi_it, reps=reps)
            return per, last

        # The bf16-design-matrix A/B that ran here through r5 was
        # ADJUDICATED AND DROPPED (ISSUE 12): interleaved slope A/B
        # measured 1.008x (committed r5 record) and 1.000x (2026-08-04
        # rerun, IQRs fully overlapping) — the inner L-BFGS is
        # compute/latency-bound, not X-bandwidth-bound, so halving X's
        # HBM traffic buys nothing on any measured backend.  Negative
        # result recorded in docs/design.md §16; the bf16 workload names
        # are in _RETIRED_WORKLOADS so stale records stop carrying.
        per_outer, _ = slope_time(lambda n: solve(n, sXi))
        dt2 = per_outer * admm_iters
        # NO bw/mfu claim here: the inner L-BFGS iteration count is
        # adaptive (Wolfe-failure exit), so X-pass counts are data-
        # dependent; the roofline-accountable proxy is the
        # logreg_value_and_grad workload below
        _record({
            "workload": f"admm_logreg_{n2}x{d2}_{admm_iters}outer",
            "wall_s": round(per_outer * admm_iters, 3),
            "per_outer_ms": round(per_outer * 1e3, 3),
            "rows_per_s": round(n2 * admm_iters / dt2, 1),
            "train_accuracy": round(acc, 4),
        })

        # --- admm INNER line search A/B: the one line-search config the
        # r5 lbfgs adjudication left unmeasured (the inner L-BFGS runs
        # inside shard_map, where probe_grid is legal but its grid of
        # extra objective passes hits the per-shard slice).  admm keeps
        # line_search='backtrack' as its default until this says
        # otherwise decisively on chip. ---
        try:
            last_ls = {}

            def run_bt(n_outer):
                last_ls["bt"] = solve(n_outer, sXi, "backtrack")

            def run_pg(n_outer):
                last_ls["pg"] = solve(n_outer, sXi, "probe_grid")

            s_bt_i, s_pg_i, dec_i = _slope_ab(run_bt, run_pg, lo_it, hi_it)
            beta_pg, _ = last_ls["pg"]
            acc_pg = float(_device_acc(
                sX2.data, sy2.data, sX2.mask,
                jnp.asarray(beta_pg[:-1]), beta_pg[-1].astype(jnp.float32),
            ))
            _record({
                "workload": f"admm_inner_line_search_{n2}x{d2}",
                "backtrack_per_outer_ms": round(
                    s_bt_i["median_s"] * 1e3, 3),
                "probe_grid_per_outer_ms": round(
                    s_pg_i["median_s"] * 1e3, 3),
                "probe_grid_speedup": round(
                    s_bt_i["median_s"] / max(s_pg_i["median_s"], 1e-9), 3),
                "stats": {
                    "backtrack": {k: round(v, 6) if isinstance(v, float)
                                  else v for k, v in s_bt_i.items()},
                    "probe_grid": {k: round(v, 6) if isinstance(v, float)
                                   else v for k, v in s_pg_i.items()},
                },
                "decision": {"a": "backtrack", "b": "probe_grid"}.get(
                    dec_i, "undecided"),
                "train_accuracy_probe_grid": round(acc_pg, 4),
                "parity_ok": bool(acc_pg >= acc - 0.02),
            })
        except Exception:
            extra["admm_inner_ls_error"] = traceback.format_exc(limit=2)

        # --- logistic value_and_grad: the ADMM/L-BFGS inner primitive,
        # with EXACT traffic accounting (2 X-passes per eval: forward
        # X@b, backward X^T r), slope-timed over chained evals.
        # Measured at the driver-run shape (<=1M rows) even on deep
        # runs: the 11M-row vg compile/fetch hung >17 min on the axon
        # relay (r5 capture) and the watchdog exit mid-fetch wedged the
        # tunnel for every later process; 1M x 28 (112 MB/pass) already
        # saturates HBM on one chip, so the big shape adds risk, not
        # information. ---
        from dask_ml_tpu.solvers.families import Logistic

        nv = n2
        Xv, yv, mv = sX2.data, sy2.data, sX2.mask
        if deep and n2 > 1_000_000:
            nv = 1_000_000 - (1_000_000 % n_sh)
            Xv = jax.device_put(Xv[:nv], sh2)
            yv = jax.device_put(yv[:nv], sh1)
            mv = jax.device_put(mv[:nv], sh1)

        @jax.jit
        def vg_run(Xa, ya, ma, n_evals, b0):
            # data threads through AS ARGUMENTS — a closure-captured
            # device array is a compile-time constant, and serializing
            # 112 MB of constants into the remote axon compile is the
            # same pathology that hung the tsqr chain for its full
            # watchdog (fixed there the same way).  fori_loop with a
            # TRACED bound: one compile serves both iteration counts
            # (scan would recompile per static length)
            vg = jax.value_and_grad(
                lambda b: Logistic.loss(b, Xa, ya, ma)
            )

            def one(_, carry):
                b, _v = carry
                val, g = vg(b)
                return b - jnp.float32(1e-6) * g, val

            return jax.lax.fori_loop(
                0, n_evals, one, (b0, jnp.float32(0.0))
            )

        b0 = jnp.zeros((d2,), jnp.float32)
        per_eval = _two_point_slope(
            lambda n_evals: float(
                vg_run(Xv, yv, mv, jnp.int32(n_evals), b0)[1]), 2, 20
        )
        ev_gbytes = 2 * nv * d2 * 4 / 1e9
        ev_flops = 4.0 * nv * d2
        _record({
            "workload": f"logreg_value_and_grad_{nv}x{d2}",
            "per_eval_ms": round(per_eval * 1e3, 3),
            "rows_per_s": round(nv / per_eval, 1),
            "achieved_gb_s": round(ev_gbytes / per_eval, 2),
            "bw_frac": round(ev_gbytes / per_eval / peak_gb_s, 4),
            "achieved_tflops": round(ev_flops / per_eval / 1e12, 3),
            "mfu": round(ev_flops / per_eval / 1e12 / peak_tflops, 4),
        })
    except _SkipSection:
        pass
    except Exception:
        extra["admm_error"] = traceback.format_exc(limit=3)

    section_s["admm"] = round(time.time() - _t_sec, 1)
    _t_sec = time.time()

    # --- TSQR (north-star #3: PCA/TruncatedSVD backbone).  One shard_map
    # program: local QR on the MXU, all_gather of d x d R factors,
    # replicated stage-2 QR, local Q correction.  Slope-timed over chained
    # factorizations (each iteration's input is scaled by a function of
    # the previous R so XLA cannot parallelize or hoist them). ---
    try:
        if _want("tsqr") and time.time() - _START_TS < _BUDGET_S * 0.80:
            from dask_ml_tpu.core.mesh import get_mesh as _gm
            from dask_ml_tpu.linalg.tsqr import (
                _MeshHolder, _tsqr_impl, tsqr_strategy,
            )

            nQ, dQ = (4_000_000, 64) if on_tpu else (200_000, 32)
            mhQ = _MeshHolder(_gm())
            # generate ON device inside jit and thread Xq through the
            # chain AS AN ARGUMENT: a closure-captured device array is a
            # compile-time CONSTANT, and serializing a 1 GB constant into
            # the remote axon compile hung the whole section for its full
            # 1500 s watchdog twice this round (measured: gen 9 s, tsqr
            # compile 15 s, chain-compile-with-constant >150 s and never
            # seen finishing)
            Xq = jax.jit(
                lambda key: jax.random.normal(key, (nQ, dQ), jnp.float32)
            )(jax.random.PRNGKey(1))
            Xq.block_until_ready()

            def _mk_chain(strategy):
                @jax.jit
                def tsqr_chain(x0, n_it):
                    def one(i, x):
                        q, r = _tsqr_impl(
                            x, mesh_holder=mhQ, strategy=strategy)
                        # serialize on BOTH outputs (depending only on r
                        # would let XLA dead-code-eliminate the
                        # Q-correction gemm), via a single-element update
                        # — a whole-array x*scale would add a read+write
                        # pass of the same order as the TSQR's own
                        # traffic and bias the slope
                        eps = (jnp.abs(r[0, 0]) + jnp.abs(q[0, 0])) * 1e-30
                        return jax.lax.dynamic_update_slice(
                            x, x[:1, :1] + eps, (0, 0))

                    x = jax.lax.fori_loop(0, n_it, one, x0)
                    return x[0, 0]

                return lambda n_it: float(tsqr_chain(Xq, jnp.int32(n_it)))

            chains = {s: _mk_chain(s) for s in ("householder", "cholqr2")}
            auto_strategy = tsqr_strategy()
            per_qr = _two_point_slope(chains[auto_strategy], 1, 5)
            # per-strategy cost model (R is d x d, negligible either way):
            # householder — read X + write Q (the local QR works in
            # place), ~2nd^2 local QR + 2nd^2 Q-correction flops;
            # cholqr2 — six n x d passes (Gram read, whiten read+write,
            # re-Gram read, repair whiten read+write) and four n x d x d
            # gemms
            if auto_strategy == "cholqr2":
                q_gbytes = 6 * nQ * dQ * 4 / 1e9
                q_flops = 8.0 * nQ * dQ * dQ
            else:
                q_gbytes = 2 * nQ * dQ * 4 / 1e9
                q_flops = 4.0 * nQ * dQ * dQ
            # in-program guard outcome (ADVICE r5): cholqr2's R = L2T.L1T
            # is a product of Cholesky factors, so diag(R) > 0 iff the
            # guard ACCEPTED the fast path; the Householder fallback's R
            # carries mixed diagonal signs (all-positive by chance:
            # ~2^-d).  A fallback run must not be costed with the
            # 6-pass cholqr2 roofline model above.
            guard = {}
            if auto_strategy == "cholqr2":
                _, rG = _tsqr_impl(Xq, mesh_holder=mhQ, strategy="cholqr2")
                diag_min = float(jnp.min(jnp.diagonal(rG)))
                guard_ok = diag_min > 0.0
                guard = {
                    "guard_diag_min": round(diag_min, 6),
                    "cholqr2_guard_ok": guard_ok,
                    "cost_model": (
                        "cholqr2" if guard_ok
                        else "INVALID: householder fallback detected"
                    ),
                }
            _record({
                "workload": f"tsqr_{nQ}x{dQ}",
                "strategy": auto_strategy,
                **guard,
                "per_qr_ms": round(per_qr * 1e3, 3),
                "rows_per_s": round(nQ / per_qr, 1),
                "achieved_gb_s": round(q_gbytes / per_qr, 2),
                "bw_frac": round(q_gbytes / per_qr / peak_gb_s, 4),
                "achieved_tflops": round(q_flops / per_qr / 1e12, 3),
                "mfu": round(q_flops / per_qr / 1e12 / peak_tflops, 4),
            })

            # strategy A/B: Householder local QR (a) vs CholeskyQR2 (b) —
            # the DASK_ML_TPU_TSQR policy's evidence (linalg/tsqr.py).
            # Same interleaved-slope discipline as every policy A/B.
            sa, sb, decision = _slope_ab(
                chains["householder"], chains["cholqr2"], 1, 5)
            measured = {"a": "householder", "b": "cholqr2",
                        "undecided": "undecided"}[decision]
            _record({
                "workload": f"tsqr_strategy_ab_{nQ}x{dQ}",
                "householder": sa, "cholqr2": sb,
                "cholqr2_speedup": round(
                    sa["median_s"] / max(sb["median_s"], 1e-9), 3),
                "decision": measured,
                "auto_policy": auto_strategy,
                "auto_matches_measurement": (
                    None if measured == "undecided"
                    else bool(auto_strategy == measured)),
            })
    except Exception:
        extra["tsqr_error"] = traceback.format_exc(limit=3)

    section_s["tsqr"] = round(time.time() - _t_sec, 1)
    _t_sec = time.time()

    # --- scatter-shaped ops (VERDICT r2 next #7): the histogram
    # segment_sum under QuantileTransformer/RobustScaler
    # (preprocessing/data.py::_hist_quantiles) and the one-hot-matmul
    # alternative that rides the MXU instead.  Slope-timed; the delta is
    # the go/no-go evidence for a Pallas histogram kernel. ---
    try:
        if _want("scatter") and time.time() - _START_TS < _BUDGET_S * 0.85:
            nS = 2_000_000 if on_tpu else 200_000
            nbins = 256
            vals = jnp.asarray(rng.normal(size=(nS,)).astype(np.float32))

            # every timed jit takes the values array AS AN ARGUMENT —
            # a closure-captured device array is a compile-time constant
            # serialized into the remote axon compile (the tsqr-chain
            # hang, fixed the same way)
            def make_hist_segsum(nb, scale):
                # shared body for every segment-sum bin count: the
                # anti-hoist perturbation (va + acc[0]*1e-20) forces a
                # fresh bucketing per round so XLA cannot lift the
                # scatter out of the loop
                @jax.jit
                def run(va, n_it):
                    def one(i, acc):
                        ids = jnp.clip(
                            ((va + acc[0] * 1e-20) * scale).astype(
                                jnp.int32) + nb // 2, 0, nb - 1)
                        hist = jax.ops.segment_sum(
                            jnp.ones_like(va), ids, num_segments=nb)
                        return acc + hist
                    return jax.lax.fori_loop(
                        0, n_it, one, jnp.zeros((nb,), jnp.float32))
                return run

            hist_scatter = make_hist_segsum(nbins, 42.0)

            @jax.jit
            def hist_onehot(va, n_it):
                def one(i, acc):
                    ids = jnp.clip(
                        ((va + acc[0] * 1e-20) * 42.0).astype(jnp.int32)
                        + nbins // 2, 0, nbins - 1)
                    oh = jax.nn.one_hot(ids, nbins, dtype=jnp.float32)
                    return acc + oh.sum(axis=0)
                return jax.lax.fori_loop(
                    0, n_it, one, jnp.zeros((nbins,), jnp.float32))

            @jax.jit
            def mode_scatter(va, n_it):
                k_ids = 1024

                def one(i, acc):
                    ids = jnp.clip(
                        ((va + acc[0] * 1e-20) * 100.0).astype(jnp.int32)
                        + k_ids // 2, 0, k_ids - 1)
                    return acc.at[ids].add(1.0)
                return jax.lax.fori_loop(
                    0, n_it, one, jnp.zeros((1024,), jnp.float32))

            # the quantile sketch's ACTUAL configuration (4096 bins,
            # where one-hot is memory-quadratic and segsum is forced by
            # the ops.scatter large-segment guard) — this is the number
            # that says whether the sketch's scatter is a TPU bottleneck
            # worth a Pallas histogram kernel
            hist_scatter_4096 = make_hist_segsum(4096, 680.0)

            per_by_name = {}
            for name, fn, n_out in (
                ("hist_segment_sum", hist_scatter, nbins),
                ("hist_onehot_matmul", hist_onehot, nbins),
                ("hist_segment_sum_4096", hist_scatter_4096, 4096),
                ("mode_at_add", mode_scatter, 1024),
            ):
                # jnp.int32 inside the lambda: consistent aval for the
                # warmup and timed calls → one jit executable
                per = _two_point_slope(
                    lambda n_i, f=fn: float(
                        f(vals, jnp.int32(n_i))[0]), 2, 20
                )
                per_by_name[name] = per
                _record({
                    "workload": f"scatter_{name}_{nS}x{n_out}",
                    "per_iter_ms": round(per * 1e3, 3),
                    "rows_per_s": round(nS / per, 1),
                    # minimum traffic: read vals once per round
                    "achieved_gb_s": round(nS * 4 / per / 1e9, 2),
                })
            _record_extra("hist_onehot_vs_segsum_speedup", round(
                per_by_name["hist_segment_sum"]
                / per_by_name["hist_onehot_matmul"], 3))
    except Exception:
        extra["scatter_error"] = traceback.format_exc(limit=3)

    section_s["scatter"] = round(time.time() - _t_sec, 1)
    _t_sec = time.time()

    # --- pairwise ppermute ring (VERDICT r5 missing #2): the ONE SPMD
    # program in the repo with zero recorded perf character — both
    # operands row-sharded, Y circulating the data-axis ring while each
    # device fills its row block (metrics/pairwise.py :: _ring_impl) ---
    try:
        if _want("pairwise") and time.time() - _START_TS < _BUDGET_S * 0.85:
            from dask_ml_tpu.core import shard_rows as _srp
            from dask_ml_tpu.core.mesh import MeshHolder as _MH
            from dask_ml_tpu.core.mesh import get_mesh as _gmr
            from dask_ml_tpu.metrics.pairwise import (
                _ring_impl, _sq_euclidean,
            )

            nR, mR, dR = (1 << 18, 4096, 64) if on_tpu else (8192, 1024, 32)
            mhR = _MH(_gmr())
            keyR = jax.random.PRNGKey(7)
            kx, ky = jax.random.split(keyR)
            # generate on device, then reshard to the row sharding the
            # ring's shard_map expects (same no-giant-constant rule as
            # the tsqr chain above)
            Xr = _srp(jax.jit(
                lambda k: jax.random.normal(k, (nR, dR), jnp.float32))(kx))
            Yr = _srp(jax.jit(
                lambda k: jax.random.normal(k, (mR, dR), jnp.float32))(ky))
            xr_d, yr_d = Xr.data, Yr.data

            @jax.jit
            def ring_chain(x0, y0, n_it):
                def one(i, x):
                    dmat = _ring_impl(
                        x, y0, mesh_holder=mhR, fn=_sq_euclidean
                    )
                    # serialize via a FULL reduction of the output: a
                    # single-element read would let XLA dead-code most
                    # of the tile writes; the extra n*m read pass is
                    # 1/(2d) of the gemm's flops-equivalent traffic
                    eps = jnp.max(dmat) * 1e-30
                    return jax.lax.dynamic_update_slice(
                        x, x[:1, :1] + eps, (0, 0)
                    )

                x = jax.lax.fori_loop(0, n_it, one, x0)
                return x[0, 0]

            def run_ring(n_it):
                return float(ring_chain(xr_d, yr_d, jnp.int32(n_it)))

            per_eval = _two_point_slope(run_ring, 1, 4)
            n_shards = len(jax.devices())
            r_flops = 2.0 * nR * mR * dR  # the ring gemms (norms ~0)
            # ICI bytes per device per eval: Y's full global rotation
            r_ring_gb = mR * dR * 4 / 1e9
            _record({
                "workload": f"pairwise_ring_{nR}x{mR}x{dR}",
                "n_shards": n_shards,
                "per_eval_ms": round(per_eval * 1e3, 3),
                "rows_per_s": round(nR / per_eval, 1),
                "achieved_tflops": round(r_flops / per_eval / 1e12, 3),
                "mfu": round(r_flops / per_eval / 1e12 / peak_tflops, 4),
                "ring_gb_per_dev": round(r_ring_gb, 4),
            })
    except _SkipSection:
        pass
    except Exception:
        extra["pairwise_error"] = traceback.format_exc(limit=3)

    section_s["pairwise"] = round(time.time() - _t_sec, 1)
    _t_sec = time.time()

    # --- streamed >device-memory fit (SURVEY §7 hard-part (b)): blocks
    # born on device, consumed by partial_fit, dropped — the total stream
    # exceeds HBM while only ~one block is ever live. ---
    try:
        if _want("streamed") and time.time() - _START_TS < _BUDGET_S * 0.92:
            from dask_ml_tpu.datasets import stream_classification_blocks
            from dask_ml_tpu.linear_model import SGDClassifier

            if on_tpu:
                # 70 blocks x 1M rows x 64 feat x 4B = 17.9 GB > 16 GB HBM
                block_rows, dS, n_blocks = 1 << 20, 64, 70
            else:
                block_rows, dS, n_blocks = 1 << 14, 16, 8
            clf = SGDClassifier(random_state=0)
            warm, t_steady, n_done = 2, None, 0
            # deadline INSIDE the block loop: on a slow tunnel the
            # 70-block sweep may not finish inside the watchdog — land
            # the honest blocks that DID stream (total_gb/exceeds_hbm16
            # recorded from n_done, not the configured 70) instead of
            # timing out with nothing.  SECTION-relative allowance capped
            # by the absolute 0.92 entry-gate mark: anchoring to
            # _START_TS alone would make a full run that reaches here
            # late cut the sweep immediately even on hardware that would
            # finish all 70 blocks in seconds.
            sec_deadline = min(_START_TS + _BUDGET_S * 0.92,
                               time.time() + _BUDGET_S * 0.45)
            for i, (Xb, yb) in enumerate(
                stream_classification_blocks(n_blocks, block_rows, dS)
            ):
                clf.partial_fit(Xb, yb, classes=[0.0, 1.0])
                if i + 1 == warm:
                    float(clf._loss_)  # sync; steady clock starts here
                    t_steady = time.perf_counter()
                elif i % 8 == 7:
                    # periodic scalar sync bounds the async-dispatch queue
                    # so blocks can't pile up live on device
                    float(clf._loss_)
                n_done += 1
                if (n_done > warm + 1
                        and time.time() > sec_deadline):
                    float(clf._loss_)  # sync before declaring the cut
                    break
            final_loss = float(clf._loss_)  # closing sync
            dt = time.perf_counter() - t_steady
            srows = (n_done - warm) * block_rows
            total_gb = n_done * block_rows * dS * 4 / 1e9
            # a deadline-truncated sweep gets its own workload name so
            # _compact_partial can never shadow a COMPLETE 70-block chip
            # record with a fresher truncated one; the suffix is FIXED
            # (not per-count) so successive truncated runs supersede each
            # other in the compaction instead of accumulating one record
            # per distinct cut point forever
            _cut = "_cut" if n_done < n_blocks else ""
            _record({
                "workload":
                    f"streamed_sgd_{n_blocks}x{block_rows}x{dS}{_cut}",
                "blocks_done": n_done,
                "total_gb": round(total_gb, 2),
                "exceeds_hbm16": bool(total_gb > 16.0),
                "steady_ms_per_block": round(
                    dt / max(n_done - warm, 1) * 1e3, 2),
                "rows_per_s": round(srows / max(dt, 1e-9), 1),
                "achieved_gb_s": round(
                    srows * dS * 4 / max(dt, 1e-9) / 1e9, 2),
                "train_loss": round(final_loss, 4),
            })

            # loader-fed out-of-core segment: host FILE -> native C++
            # loader -> device -> partial_fit (the reference's _partial.py
            # story end to end, not just device-born blocks).  4 distinct
            # 64MB blocks on disk cycled so the parse+transfer path runs
            # every block while disk stays 256MB.  The per-loop budget
            # bounds a SLOW tunnel (device progress is synced every
            # block); a fully WEDGED tunnel blocks inside one sync, and
            # the process-level watchdog (_emit_and_exit) is what bounds
            # that — same contract as every other section.
            import tempfile

            from dask_ml_tpu.io import read_binary

            # remaining-budget gate: after a deadline-cut sweep the
            # watchdog may be <40 s away, and entering a 90 s loader loop
            # there guarantees a watchdog exit mid-fetch (the wedge
            # mechanism) — skip the segment instead; its record carries
            # forward from the last complete run
            if time.time() - _START_TS > _BUDGET_S * 0.92 - 120.0:
                raise _SkipSection
            blk_rows, dL = (1 << 18, 64) if on_tpu else (1 << 14, 16)
            n_cycle, max_lblocks, budget_s = 4, 24, 90.0
            arrL = rng.rand(n_cycle * blk_rows, dL).astype(np.float32)
            with tempfile.NamedTemporaryFile(
                suffix=".bin", delete=False
            ) as f:
                bin_path = f.name
            try:
                arrL.tofile(bin_path)
                clfL = SGDClassifier(random_state=0)
                done, t0L = 0, None
                for i in range(max_lblocks):
                    off = (i % n_cycle) * blk_rows * dL * 4
                    xb = read_binary(bin_path, (blk_rows, dL),
                                     offset_bytes=off)
                    yb = (xb[:, 0] > 0.5).astype(np.float32)
                    clfL.partial_fit(xb, yb, classes=[0.0, 1.0])
                    if i == 0:
                        float(clfL._loss_)  # sync; steady clock from here
                        t0L = time.perf_counter()
                    else:
                        # per-block scalar sync: the budget check must
                        # measure DEVICE progress, not host dispatch —
                        # otherwise a slow tunnel lets all blocks queue
                        # live (the out-of-core story inverted) and the
                        # closing sync blocks unboundedly
                        float(clfL._loss_)
                        done += 1
                        if time.perf_counter() - t0L > budget_s:
                            break
                float(clfL._loss_)  # closing sync
                dtL = time.perf_counter() - t0L
                _record({
                    "workload": f"streamed_loader_fed_{blk_rows}x{dL}",
                    "blocks": done,
                    "ms_per_block": round(dtL / max(done, 1) * 1e3, 1),
                    "rows_per_s": round(
                        done * blk_rows / max(dtL, 1e-9), 1),
                    "host_mb_s": round(
                        done * blk_rows * dL * 4 / max(dtL, 1e-9) / 1e6,
                        1),
                })

                # overlap A/B (the tentpole's measurement): the SAME
                # file->loader->device->partial_fit stream, serial
                # (depth=0) vs prefetch-overlapped (depth=2) through
                # dask_ml_tpu.pipeline — quantifies how much of the
                # parse+transfer time the input pipeline actually hides
                # behind device compute, with the per-stage split
                # attached from diagnostics.pipeline_report()
                if time.time() - _START_TS < _BUDGET_S * 0.92 - 60.0:
                    from dask_ml_tpu import _partial as _dpartial
                    from dask_ml_tpu.diagnostics import (
                        pipeline_report, reset_pipeline_stats,
                    )
                    from dask_ml_tpu.io import stream_binary_blocks

                    def _overlap_fit(depth):
                        clfO = SGDClassifier(random_state=0)
                        blocks = (
                            (xb, (xb[:, 0] > 0.5).astype(np.float32))
                            for xb in stream_binary_blocks(
                                bin_path, blk_rows, dL)
                        )
                        _dpartial.fit(
                            clfO, blocks, prefetch_depth=depth,
                            classes=[0.0, 1.0],
                        )
                        float(clfO._loss_)  # sync the donated chain

                    sa, sb, decision = _ab_stats(
                        lambda: _overlap_fit(0), lambda: _overlap_fit(2),
                        reps=3,
                    )
                    reset_pipeline_stats()
                    _overlap_fit(2)
                    rep = pipeline_report()
                    _record({
                        "workload":
                            f"streamed_loader_overlap_{blk_rows}x{dL}",
                        "overlap_speedup": round(
                            sa["median_s"] / max(sb["median_s"], 1e-9), 3),
                        "depth0": sa, "depth2": sb,
                        "decision": {"a": "serial", "b": "overlap",
                                     "undecided": "undecided"}[decision],
                        "stage_split": {
                            k: rep.get(k) for k in (
                                "parse_s", "transfer_s", "compute_s",
                                "stall_s", "wall_s", "hidden_s", "blocks",
                                "staged",
                            )
                        },
                    })
            finally:
                try:
                    os.unlink(bin_path)
                except OSError:
                    pass
    except _SkipSection:
        pass
    except Exception:
        extra["streamed_error"] = traceback.format_exc(limit=3)

    # --- recompile_tax: heterogeneous-shape stream, bucketing off vs on
    # (the programs/ cache A/B, design.md §12).  A ragged block-length
    # sequence streams through SGD partial_fit twice: DASK_ML_TPU_BUCKET
    # =off mints one XLA program per distinct length; =auto resolves
    # every block to a few warm bucketed programs (+ compile-ahead on
    # the blessed thread).  Verdict currency: compile.count registry
    # delta and wall, with the trained coefficients REQUIRED identical
    # (mask-weighted padding is exact) — fewer compiles with a different
    # model would be a correctness bug, not a win. ---
    try:
        if _want("recompile") and time.time() - _START_TS < _BUDGET_S * 0.93:
            from dask_ml_tpu import programs as _programs
            from dask_ml_tpu.linear_model import SGDClassifier as _RTClf
            from dask_ml_tpu.pipeline import (
                stream_partial_fit as _rt_stream,
            )

            nRT, dRT = (8192, 32) if on_tpu else (1536, 12)
            # ragged, all-distinct lengths, none equal to a bucket rung
            # (so the off arm cannot accidentally pre-warm the on arm)
            sizes = sorted({
                max(3, nRT - 13), nRT // 2 + 7, nRT // 3 + 11,
                nRT // 4 + 3, nRT // 5 + 17, nRT // 6 + 5,
                nRT // 7 + 9, nRT // 8 + 1,
            })

            def _rt_blocks():
                r = np.random.RandomState(11)
                for n in sizes:
                    X = r.normal(size=(n, dRT)).astype(np.float32)
                    yield X, (X[:, 0] > 0).astype(np.float32)

            _rt_env = os.environ.get("DASK_ML_TPU_BUCKET")

            def _rt_run(policy):
                from dask_ml_tpu.obs import scope as _rt_scope

                os.environ["DASK_ML_TPU_BUCKET"] = policy
                try:
                    _programs.reset_counters()
                    reg = _obs.registry()
                    c0 = reg.counter("compile.count").value
                    s0 = reg.histogram("compile.duration_s").sum
                    cur = _rt_scope.cursor()
                    clf = _RTClf(random_state=0)
                    cp0 = time.process_time()
                    t0 = time.perf_counter()
                    _rt_stream(clf, _rt_blocks(),
                               fit_kwargs={"classes": [0.0, 1.0]},
                               label=f"recompile_tax_{policy}")
                    float(clf._loss_)  # sync the donated chain
                    _programs.drain_ahead()
                    wall = time.perf_counter() - t0
                    cpu = time.process_time() - cp0
                    dev = _rt_scope.device_report(since=cur,
                                                  settle_s=5.0)
                    tot = _programs.report()["totals"]
                    return {
                        "wall_s": round(wall, 3),
                        "compiles": reg.counter("compile.count").value - c0,
                        "compile_s": round(
                            reg.histogram("compile.duration_s").sum - s0,
                            3),
                        "warm_hit_rate": round(
                            tot["hits"]
                            / max(tot["hits"] + tot["misses"], 1), 3),
                        "ahead_hits": tot["ahead_hits"],
                        "compile_s_hidden": tot["saved_s"],
                        # saturation evidence, uniform across A/B
                        # sections (the search section's idiom)
                        "cpu_over_wall": round(
                            cpu / max(wall, 1e-9), 3),
                        "device_util": dev["utilization"],
                        "critical": _critical_arm(),
                    }, np.asarray(clf.coef_)
                finally:
                    if _rt_env is None:
                        os.environ.pop("DASK_ML_TPU_BUCKET", None)
                    else:
                        os.environ["DASK_ML_TPU_BUCKET"] = _rt_env

            with _spans_armed():
                off, coef_off = _rt_run("off")
                on, coef_on = _rt_run("auto")
            # model-equality contract: padding rows are exact zeros in
            # every masked reduction, but a different padded SHAPE can
            # re-tile XLA's reduction tree (SIMD lanes vs remainder
            # loop), regrouping the SAME real addends — so the bound is
            # reassociation noise (measured ~5e-9 relative on this
            # image, < 1 ulp at coefficient scale), not bitwise
            # equality across shapes.  Same-shape streams stay
            # bit-exact (tests/test_programs.py pins both halves).
            scale = float(max(np.abs(coef_off).max(), 1e-30))
            max_rel = float(np.abs(coef_off - coef_on).max() / scale)
            _record({
                "workload": f"recompile_tax_{len(sizes)}blk_x{dRT}",
                "blocks": len(sizes),
                "off": off,
                "on": on,
                "speedup": round(
                    off["wall_s"] / max(on["wall_s"], 1e-9), 3),
                "compiles_saved": off["compiles"] - on["compiles"],
                # the acceptance contract: strictly fewer compiles AND
                # the same model, or the bucketing default is wrong
                "fewer_compiles": on["compiles"] < off["compiles"],
                "bit_identical": bool(np.array_equal(coef_off, coef_on)),
                "max_rel_diff": max_rel,
                "results_match": bool(max_rel < 1e-6),
                "critical": _pair_critical(
                    {"off": off["critical"], "on": on["critical"]},
                    (off["cpu_over_wall"], on["cpu_over_wall"])),
            })
    except Exception:
        extra["recompile_tax_error"] = traceback.format_exc(limit=3)

    # --- packed OvR vs sequential: K one-vs-rest solves as ONE vmapped
    # program (the round-3 dispatch win on the GLM flagship) ---
    try:
        if _want("packed") and time.time() - _START_TS < _BUDGET_S * 0.93:
            from dask_ml_tpu.core import shard_rows as _sr
            from dask_ml_tpu.solvers import Logistic, lbfgs as _lbfgs
            from dask_ml_tpu.solvers import packed_solve as _packed

            from dask_ml_tpu.solvers import pack_strategy as _pack_pol

            nP, dP = (1_000_000, 28) if on_tpu else (100_000, 16)
            # K=4 AND K=16 on TPU: the pack win scales with K (the
            # packed gemm amortizes the X read K ways — measured 1.8x
            # at K=4, 4.2x at K=16 with the clean instrument), so the
            # record set pins both a small-K and a mid-K point.  CPU
            # keeps K=4 only (16 sequential CPU solves would dominate
            # the section budget for a question whose CPU answer does
            # not change with K).
            K_LIST = (4, 16) if on_tpu else (4,)
            Kmax = max(K_LIST)
            # LEARNABLE targets (random hyperplanes on X), NOT coin
            # flips: with unlearnable targets the line-search-failure
            # exit truncates lanes differently per arm per realization,
            # so the A/B compared UNCONTROLLED amounts of work — the
            # measured ratio swung 0.74x..3.4x across realizations on
            # the same chip in the same hour (r5 investigation,
            # BENCH_LOCAL.md).  With learnable targets every lane runs
            # its full max_iter in both arms (asserted via the recorded
            # executed-iteration counts) and the A/B compares equal
            # work.  Targets computed HOST-side before sharding — a
            # device fetch of X here would ride the tunnel.
            Xh = rng.normal(size=(nP, dP)).astype(np.float32)
            Wall = rng.normal(size=(Kmax, dP)).astype(np.float32)
            sXp = _sr(Xh)
            Yall = np.zeros((Kmax, sXp.data.shape[0]), np.float32)
            Yall[:, :nP] = ((Xh @ Wall.T) > 0).astype(np.float32).T
            del Xh
            it_p = 20
            _pack_prev = os.environ.get("DASK_ML_TPU_PACK")

            for KP in K_LIST:
              # device-resident once, OUTSIDE timing: numpy targets
              # would otherwise transfer per call inside the timed
              # region (and, pre-fix, device targets round-tripped in
              # _prep — both distorted earlier adjudications)
              Yp = jnp.asarray(Yall[:KP])
              # what the auto policy would pick here (only meaningful
              # when the user hasn't forced it — record the override
              # otherwise); K-aware, so resolved per K
              auto_choice = (
                  _pack_pol(KP) if _pack_prev in (None, "", "auto")
                  else f"forced:{_pack_prev}"
              )
              # the A/B must pin each arm explicitly — under auto the
              # "packed" call would fall back on the losing platform/K
              # BOTH arms pin line_search='backtrack': the packed arm
              # is vmap-forced to backtrack, so letting the sequential
              # arm resolve the TPU 'auto' (probe_grid) would confound
              # the pack-vs-dispatch question with the line-search one.

              def run_packed(Yp=Yp):
                  B, _nit = _packed("lbfgs", sXp, Yp, family=Logistic,
                                    lamduh=1.0, max_iter=it_p, tol=0.0,
                                    line_search="backtrack")
                  # ONE fetch whose value depends on EVERY lane
                  float(jnp.sum(B[:, 0]))

              def run_seq(Yp=Yp, KP=KP):
                  outs = [
                      _lbfgs(sXp, Yp[k], family=Logistic, lamduh=1.0,
                             max_iter=it_p, tol=0.0,
                             line_search="backtrack")
                      for k in range(KP)
                  ]
                  # ONE fetch depending on ALL K solves: fetching only
                  # outs[-1] does not prove the other K-1 completed
                  # inside the timed window
                  tot = outs[0][0]
                  for o in outs[1:]:
                      tot = tot + o[0]
                  float(tot)

              try:
                  # force the packed arm's path for BOTH the warmup
                  # capture and the timed reps — inside the try so an
                  # exception anywhere cannot leak the forced value
                  os.environ["DASK_ML_TPU_PACK"] = "packed"
                  # Iteration counts are DETERMINISTIC per (data,
                  # config), so they are captured once here OUTSIDE the
                  # timed closures — fetching them inside would add K+1
                  # device round-trips to the sequential arm vs 2 to
                  # the packed arm, biasing the ratio packed-ward
                  Bw, nitw = _packed("lbfgs", sXp, Yp, family=Logistic,
                                     lamduh=1.0, max_iter=it_p, tol=0.0,
                                     line_search="backtrack")
                  sw = [_lbfgs(sXp, Yp[k], family=Logistic, lamduh=1.0,
                               max_iter=it_p, tol=0.0,
                               line_search="backtrack",
                               return_n_iter=True) for k in range(KP)]
                  ab_iters = {
                      "packed": np.asarray(nitw).tolist(),
                      "sequential": [int(o[1]) for o in sw],
                  }
                  del Bw, sw
                  s_pk, s_sq, dec = _ab_stats(run_packed, run_seq)
              finally:
                  # restore, never leak the forced arm (or clobber a
                  # user-provided setting) past this A/B
                  if _pack_prev is None:
                      os.environ.pop("DASK_ML_TPU_PACK", None)
                  else:
                      os.environ["DASK_ML_TPU_PACK"] = _pack_prev
              measured_winner = {
                  "a": "packed", "b": "sequential"}.get(dec, "undecided")
              # fixed-work validity gate: if any lane in either arm
              # exited before max_iter, the arms did different work and
              # the ratio is not a pack-vs-dispatch measurement
              wm = bool(
                  all(i == it_p for i in ab_iters.get("packed", []))
                  and all(i == it_p
                          for i in ab_iters.get("sequential", []))
              )
              _record({
                  "workload": f"packed_ovr_fixedwork_{nP}x{dP}_K{KP}",
                  "packed_s": s_pk["median_s"],
                  "sequential_s": s_sq["median_s"],
                  "packed_speedup": round(
                      s_sq["median_s"] / max(s_pk["median_s"], 1e-9), 3),
                  "stats": {"packed": s_pk, "sequential": s_sq},
                  "executed_iters": ab_iters,
                  "work_matched": wm,
                  # the decision is the DISPERSION-AWARE winner:
                  # undecided when the arms' IQR intervals overlap — a
                  # default must never flip on a margin inside run-to-
                  # run noise; an unmatched-work run cannot decide
                  "decision": measured_winner if wm else "invalid_work",
                  # the auto policy's pick vs what this run measured —
                  # a mismatch on chip is the signal to flip the default
                  "auto_policy": auto_choice,
                  "auto_matches_measurement": (
                      None if (not wm or measured_winner == "undecided")
                      else bool(auto_choice == measured_winner)),
              })
            # device-resident single target for the sweep/line-search
            # A/Bs below (they only use lane 0 — uploading all of Yall
            # would move Kmax x 4 MB where 4 MB suffices)
            Yp = jnp.asarray(Yall[:1])

            # C-sweep (the r4 grid-search fast path): K solves of the
            # SAME (X, y) at different lamduh as one vmapped program
            # (solvers.lambda_sweep) vs K sequential solves — the chip
            # number behind GridSearchCV's packed path
            from dask_ml_tpu.solvers import lambda_sweep as _lsweep

            lams = np.logspace(-4, 1, 8).astype(np.float32)

            def run_sweep():
                B, _ = _lsweep("lbfgs", sXp, Yp[0], lams, family=Logistic,
                               max_iter=it_p, tol=0.0)
                float(jnp.sum(B[:, 0]))  # depends on EVERY lane

            def run_sweep_seq():
                # pinned backtrack for the same reason as the OvR A/B:
                # the vmapped sweep is backtrack by construction
                outs = [
                    _lbfgs(sXp, Yp[0], family=Logistic,
                           lamduh=float(lam), max_iter=it_p, tol=0.0,
                           line_search="backtrack")
                    for lam in lams
                ]
                tot = outs[0][0]
                for o in outs[1:]:
                    tot = tot + o[0]
                float(tot)  # depends on ALL candidate solves

            s_sw, s_sws, dec_sw = _ab_stats(run_sweep, run_sweep_seq)
            _record({
                "workload": f"grid_sweep_lbfgs_{nP}x{dP}_K8",
                "sweep_s": s_sw["median_s"],
                "sequential_s": s_sws["median_s"],
                "sweep_speedup": round(
                    s_sws["median_s"] / max(s_sw["median_s"], 1e-9), 3),
                "stats": {"packed": s_sw, "sequential": s_sws},
                "decision": {
                    "a": "packed", "b": "sequential"}.get(
                        dec_sw, "undecided"),
            })

            # line-search strategy go/no-go (lbfgs_core docstring): the
            # batched probe_grid is bandwidth-optimal ON PAPER for big-n
            # solves but measured slower on compute-bound CPU; this chip
            # ratio decides whether the sequential default flips
            def run_ls(ls):
                b = _lbfgs(sXp, Yp[0], family=Logistic,
                           lamduh=1.0, max_iter=it_p, tol=0.0,
                           line_search=ls)
                float(b[0])

            s_pg, s_bt, dec_ls = _ab_stats(
                lambda: run_ls("probe_grid"),
                lambda: run_ls("backtrack"))
            _record({
                "workload": f"lbfgs_line_search_{nP}x{dP}",
                "backtrack_s": s_bt["median_s"],
                "probe_grid_s": s_pg["median_s"],
                "probe_grid_speedup": round(
                    s_bt["median_s"] / max(s_pg["median_s"], 1e-9), 3),
                "stats": {"probe_grid": s_pg, "backtrack": s_bt},
                "decision": {
                    "a": "probe_grid", "b": "backtrack"}.get(
                        dec_ls, "undecided"),
            })
    except Exception:
        extra["packed_error"] = traceback.format_exc(limit=3)

    # --- native CSV ingest (C++ streaming parser) throughput ---
    try:
        if _want("csv") and time.time() - _START_TS < _BUDGET_S * 0.95:
            import tempfile

            from dask_ml_tpu.io import stream_csv_blocks

            # ~300MB of realistic float text (a formatted block repeated)
            # so parse throughput is sustained, not startup-dominated —
            # the r3 number (40 MB/s on a 12MB file) was mostly open+
            # index cost.  Throughput is FILE TEXT MB/s (what a parser
            # is judged on), not output-array bytes.
            dcsv = 32
            # own RandomState: the shared rng's state depends on which
            # earlier sections ran, and the workload NAME must be stable
            # across filtered/full runs or carry-forward mints duplicates
            block_arr = np.random.RandomState(42).rand(
                2000, dcsv).astype(np.float32)
            block_txt = "\n".join(
                ",".join(f"{v:.6g}" for v in row) for row in block_arr
            ) + "\n"
            target_bytes = int(300e6)
            reps = max(1, target_bytes // len(block_txt))
            rows_csv = 2000 * reps
            with tempfile.NamedTemporaryFile(
                suffix=".csv", delete=False
            ) as f:
                csv_path = f.name
            try:
                with open(csv_path, "w") as f:
                    for _ in range(reps):
                        f.write(block_txt)
                file_bytes = os.path.getsize(csv_path)
                best_dt, n_parsed = float("inf"), 0
                for _ in range(2):  # 2nd pass = warm page cache
                    t0 = time.perf_counter()
                    n_parsed = 0
                    for blk in stream_csv_blocks(csv_path, 65536):
                        n_parsed += blk.shape[0]
                    best_dt = min(best_dt, time.perf_counter() - t0)
            finally:
                try:
                    os.unlink(csv_path)
                except OSError:
                    pass
            _record({
                "workload": f"csv_ingest_300mb_x{dcsv}",
                "n_rows": rows_csv,
                "file_mb": round(file_bytes / 1e6, 1),
                "rows_per_s": round(n_parsed / max(best_dt, 1e-9), 1),
                "parse_mb_s": round(
                    file_bytes / max(best_dt, 1e-9) / 1e6, 1),
            })
    except Exception:
        extra["csv_error"] = traceback.format_exc(limit=3)

    section_s["streamed"] = round(time.time() - _t_sec, 1)
    _t_sec = time.time()

    # --- sharded dataset ingest (data/, design.md §18): the parallel-
    # reader A/B (1 vs 4 readers over the SAME key-shuffled columnar
    # dataset — identical stream order by construction, so the arms are
    # model-equality-checked at rtol 1e-5), CSV vs columnar parse cost,
    # and the windowed-path VmHWM ceiling.  Two A/B arms: "real" parse
    # (pread + zlib + decode — on a 1-core gate box the readers compete
    # for the same core, so this arm is honest but saturation-bound,
    # same situation as the search section's in-memory pair) and a
    # remote-store emulation (3 ms/block fetch latency inside each
    # reader — an object-store GET has RTT the page cache does not),
    # where reader parallelism is the whole win. ---
    try:
        if _want("ingest") and time.time() - _START_TS < _BUDGET_S * 0.95:
            import shutil
            import subprocess
            import tempfile

            from dask_ml_tpu import data as _dsdata
            from dask_ml_tpu.diagnostics import (
                pipeline_report, reset_pipeline_stats)
            from dask_ml_tpu.io import stream_csv_blocks
            from dask_ml_tpu.linear_model import SGDClassifier
            from dask_ml_tpu.obs import scope as _ing_scope
            from dask_ml_tpu.pipeline import stream_partial_fit

            nI, dI = (2_097_152, 32) if on_tpu else (262_144, 16)
            blkI = 16384  # an `auto` ladder rung: pad-free stream
            rngI = np.random.RandomState(23)
            XI = rngI.normal(size=(nI, dI)).astype(np.float32)
            wI = rngI.normal(size=dI)
            yI = (XI @ wI > 0).astype(np.int32)
            ds_dir = tempfile.mkdtemp(prefix="bench-ingest-")
            try:
                t0 = time.perf_counter()
                _dsdata.write_dataset(ds_dir, XI, yI, shards=4,
                                      block_rows=blkI)
                write_s = time.perf_counter() - t0
                ds_bytes = sum(
                    os.path.getsize(os.path.join(ds_dir, f))
                    for f in os.listdir(ds_dir))

                # CSV vs columnar parse cost: drain-only rows/s over the
                # same logical rows (CSV arm scaled down if huge — the
                # text file for 2M x 32 would be ~1.3 GB)
                n_csv = min(nI, 262_144)
                csv_path = os.path.join(ds_dir, "ab.csv")
                with open(csv_path, "w") as f:
                    for lo in range(0, n_csv, 16384):
                        blk = XI[lo:lo + 16384]
                        f.write("\n".join(
                            ",".join(f"{v:.6g}" for v in row)
                            for row in blk) + "\n")
                t0 = time.perf_counter()
                got_csv = sum(b.shape[0]
                              for b in stream_csv_blocks(csv_path, blkI))
                csv_s = time.perf_counter() - t0
                t0 = time.perf_counter()
                got_col = 0
                with _dsdata.ShardedDataset(
                        ds_dir, key=23, readers=1, shuffle=False,
                        label="bench_ingest_scan").iter_blocks(
                            epoch=0) as scan:
                    for xb, _yb in scan:
                        got_col += xb.shape[0]
                col_s = time.perf_counter() - t0
                _record({
                    "workload": f"ingest_parse_csv_vs_columnar_{dI}d",
                    "csv_rows": got_csv,
                    "csv_rows_per_s": round(got_csv / max(csv_s, 1e-9), 1),
                    "columnar_rows": got_col,
                    "columnar_rows_per_s": round(
                        got_col / max(col_s, 1e-9), 1),
                    "parse_speedup": round(
                        (got_col / max(col_s, 1e-9))
                        / max(got_csv / max(csv_s, 1e-9), 1e-9), 2),
                    "dataset_mb": round(ds_bytes / 1e6, 1),
                    "write_s": round(write_s, 2),
                })

                def _fit_arm(readers, latency_s, tag):
                    """One streamed-fit arm: rows/s + stall + util +
                    cpu_over_wall + graftpath verdict + coef for the
                    equality check."""
                    clf = SGDClassifier(random_state=0)
                    reset_pipeline_stats()
                    cur = _ing_scope.cursor()
                    ds = _dsdata.ShardedDataset(
                        ds_dir, key=23, readers=readers,
                        fetch_latency_s=latency_s,
                        label=f"bench_ingest_{tag}")
                    c0 = time.process_time()
                    t0 = time.perf_counter()
                    stream_partial_fit(
                        clf, ds, depth=2,
                        fit_kwargs={"classes": np.array([0, 1])},
                        label=f"bench_ingest_{tag}")
                    dt = time.perf_counter() - t0
                    cpu = time.process_time() - c0
                    rep = pipeline_report()
                    dev = _ing_scope.device_report(since=cur,
                                                   settle_s=5.0)
                    wall = float(rep.get("wall_s", 0.0)) or 1e-9
                    return {
                        "rows_per_s": round(nI / max(dt, 1e-9), 1),
                        "wall_s": round(dt, 3),
                        "stall_fraction": round(min(
                            float(rep.get("stall_s", 0.0)) / wall,
                            1.0), 4),
                        "device_util": float(dev["utilization"]),
                        # saturation evidence, machine-readable in
                        # EVERY A/B section (the search section's
                        # idiom): ~1.0 on both arms means the host
                        # core was the binding resource
                        "cpu_over_wall": round(
                            cpu / max(dt, 1e-9), 3),
                        "critical": _critical_arm(),
                    }, np.asarray(clf.coef_, np.float64).ravel()

                # 10 ms/block fetch emulation: conservative against a
                # same-region object-store GET (tens of ms first-byte)
                # and large enough to DOMINATE the 1-core box's
                # serialized zlib parse — at 3 ms the latency share was
                # too small to overlap into a stable ratio (measured
                # 1.13-1.51x run to run; parse ~10 ms/block is the
                # same order, so the A/B measured noise)
                with _spans_armed():
                    for tag, lat in (("real", 0.0),
                                     ("remote10ms", 0.010)):
                        # warm arm (compiles paid once, page cache hot)
                        _fit_arm(1, lat, f"{tag}_warm")
                        a1, c1 = _fit_arm(1, lat, f"{tag}_r1")
                        a4, c4 = _fit_arm(4, lat, f"{tag}_r4")
                        denom = np.maximum(np.abs(c1), 1e-12)
                        max_rel = float(np.max(np.abs(c4 - c1) / denom))
                        _record({
                            "workload": f"ingest_readers_ab_{tag}",
                            "rows": nI,
                            "block_rows": blkI,
                            "r1_rows_per_s": a1["rows_per_s"],
                            "r4_rows_per_s": a4["rows_per_s"],
                            "speedup": round(
                                a4["rows_per_s"]
                                / max(a1["rows_per_s"], 1e-9), 3),
                            "r1_stall_fraction": a1["stall_fraction"],
                            "r4_stall_fraction": a4["stall_fraction"],
                            "r1_device_util": a1["device_util"],
                            "r4_device_util": a4["device_util"],
                            "r1_cpu_over_wall": a1["cpu_over_wall"],
                            "r4_cpu_over_wall": a4["cpu_over_wall"],
                            "max_rel_diff": max_rel,
                            "results_match": bool(max_rel < 1e-5),
                            # each arm's bottleneck verdict + the
                            # tool's saturation label (design.md §19)
                            "critical": _pair_critical(
                                {"r1": a1["critical"],
                                 "r4": a4["critical"]},
                                (a1["cpu_over_wall"],
                                 a4["cpu_over_wall"])),
                        })

                # VmHWM ceiling for the windowed dataset path: a child
                # process streams the whole dataset (readers=4) and
                # reports its own peak — the 1B-row config's bounded-
                # host-RAM claim, measured at this geometry (peak must
                # stay O(window), not O(rows)).
                child = (
                    "import numpy as np\n"
                    "from dask_ml_tpu import data\n"
                    f"ds = data.ShardedDataset({ds_dir!r}, key=23, "
                    "readers=4, label='bench_vmhwm')\n"
                    "rows = sum(xb.shape[0] "
                    "for xb, yb in ds.iter_blocks(epoch=0))\n"
                    "peak = ''\n"
                    "for line in open('/proc/self/status'):\n"
                    "    if line.startswith('VmHWM'):\n"
                    "        peak = line.split()[1]\n"
                    "print(rows, peak)\n"
                )
                try:
                    out = subprocess.run(
                        [sys.executable, "-c", child],
                        env={**os.environ, "JAX_PLATFORMS": "cpu"},
                        capture_output=True, text=True, timeout=600,
                        check=True).stdout.split()
                    if len(out) >= 2 and out[1]:
                        _record({
                            "workload": "ingest_vmhwm_windowed",
                            "rows": int(out[0]),
                            "dataset_mb": round(ds_bytes / 1e6, 1),
                            "vmhwm_mb": round(int(out[1]) / 1024.0, 1),
                        })
                except (subprocess.SubprocessError, OSError,
                        ValueError):
                    extra["ingest_vmhwm_error"] = \
                        traceback.format_exc(limit=2)
            finally:
                shutil.rmtree(ds_dir, ignore_errors=True)
    except _SkipSection:
        pass
    except Exception:
        extra["ingest_error"] = traceback.format_exc(limit=3)

    section_s["ingest"] = round(time.time() - _t_sec, 1)
    _t_sec = time.time()

    # --- online serving latency (serve/, design.md §15): closed-loop
    # and open-loop (Poisson arrivals) p50/p99/throughput for 1-row and
    # 16-row requests against a fitted SGD model.  Closed loop times
    # each request on the caller (the client's number, queue wait and
    # gather window included); open loop reads the registry's
    # serve.request_s histogram, recorded at fulfillment on the serve
    # thread, plus batch occupancy (rows per dispatch) — the
    # micro-batcher's coalescing win under load. ---
    try:
        if _want("serve") and time.time() - _START_TS < _BUDGET_S * 0.97:
            from dask_ml_tpu import obs as _obs_serve
            from dask_ml_tpu.linear_model import SGDClassifier
            from dask_ml_tpu.serve import ModelServer

            dV = 32
            rngS = np.random.RandomState(7)
            XS = rngS.normal(size=(4096, dV)).astype(np.float32)
            yS = (XS @ rngS.normal(size=dV) > 0).astype(np.int32)
            clfS = SGDClassifier(random_state=0)
            clfS.partial_fit(XS, yS, classes=np.array([0, 1]))

            def _pq(lats_s):
                arr = np.sort(np.asarray(lats_s, np.float64))
                return (round(float(arr[len(arr) // 2]) * 1e3, 3),
                        round(float(
                            arr[min(int(len(arr) * 0.99),
                                    len(arr) - 1)]) * 1e3, 3))

            closed_rps = None
            with ModelServer(label="bench_serve_closed",
                             window_s=0.0) as srv:
                srv.load("m", clfS)
                for _ in range(20):  # warm: programs + request path
                    srv.predict("m", XS[:1])
                for rows in (1, 16):
                    N = 400 if rows == 1 else 200
                    lats = []
                    t0 = time.perf_counter()
                    for i in range(N):
                        lo = (i * rows) % 2048
                        t1 = time.perf_counter()
                        srv.predict("m", XS[lo:lo + rows])
                        lats.append(time.perf_counter() - t1)
                    dt = time.perf_counter() - t0
                    p50, p99 = _pq(lats)
                    if rows == 1:
                        closed_rps = N / max(dt, 1e-9)
                    _record({
                        "workload": f"serve_closed_{rows}row",
                        "requests": N,
                        "p50_ms": p50,
                        "p99_ms": p99,
                        "requests_per_s": round(N / max(dt, 1e-9), 1),
                        "rows_per_s": round(
                            N * rows / max(dt, 1e-9), 1),
                    })
            # open loop: Poisson arrivals at ~60% of the measured
            # closed-loop rate (NO floor — a floor would overrun a
            # slow device, fill the admission queue, and abort the
            # section via queue_full), DEFAULT gather window — latency
            # from the fulfillment-side histogram, occupancy from the
            # per-dispatch row books.  N scales with the rate so the
            # section costs a few seconds on any device.
            lam = (closed_rps or 100.0) * 0.6
            N = int(min(400, max(100, lam * 5)))
            gaps = np.random.RandomState(11).exponential(1.0 / lam,
                                                         size=N)
            reg = _obs_serve.registry()
            with ModelServer(label="bench_serve_open") as srv:
                srv.load("m", clfS)
                for _ in range(20):
                    srv.predict("m", XS[:1])
                reg.reset(prefix="serve.request_s")
                reg.reset(prefix="serve.batch_rows")
                reg.reset(prefix="serve.batch_requests")
                futs = []
                t0 = time.perf_counter()
                for i in range(N):
                    time.sleep(float(gaps[i]))
                    futs.append(srv.submit("m", XS[i % 2048:
                                                   i % 2048 + 1]))
                for f in futs:
                    f.result(30.0)
                dt = time.perf_counter() - t0
                hist = reg.histogram("serve.request_s", "m")
                occ = reg.histogram("serve.batch_rows")
                n_disp = occ.snapshot().get("count", 0)
                _record({
                    "workload": "serve_open_poisson_1row",
                    "requests": N,
                    "offered_rps": round(lam, 1),
                    "achieved_rps": round(N / max(dt, 1e-9), 1),
                    "p50_ms": round(hist.quantile(0.50) * 1e3, 3),
                    "p99_ms": round(hist.quantile(0.99) * 1e3, 3),
                    "dispatches": int(n_disp),
                    "rows_per_dispatch": round(
                        N / max(n_disp, 1), 2),
                })
    except _SkipSection:
        pass
    except Exception:
        extra["serve_error"] = traceback.format_exc(limit=3)

    section_s["serve"] = round(time.time() - _t_sec, 1)
    _t_sec = time.time()

    # --- fleet: graftfleet under deliberate overload (serve/fleet.py,
    # design.md §22).  First a closed-loop 1-row rate on ONE server
    # (the section's own measurement — sections must run standalone),
    # then Poisson open-loop arrivals at 4x that rate against an N=4
    # replica fleet: the offered load exceeds single-process capacity
    # BY CONSTRUCTION, so the record shows what the router turns the
    # overload into — coalescing + spread across replicas, counted
    # retries/rejections (never silent), and a per-replica graftpath
    # verdict from the metrics_tag-split latency histograms.  On the
    # 2-core gate box the drive loop and 4 replica loops share the
    # host, so cpu_over_wall ~1 labels the record saturation_pinned:
    # these numbers measure the ROUTER under pressure, not 4x chip
    # capacity (honesty label, same convention as the pair records).
    try:
        if _want("fleet") and time.time() - _START_TS < _BUDGET_S * 0.97:
            from dask_ml_tpu import obs as _obs_fleet
            from dask_ml_tpu.linear_model import SGDClassifier
            from dask_ml_tpu.obs.critical import serve_critical
            from dask_ml_tpu.resilience.elastic import FaultBudget
            from dask_ml_tpu.serve import ModelServer, ServeFleet
            from dask_ml_tpu.serve.batcher import RequestRejected

            dF = 32
            rngF = np.random.RandomState(7)
            XF = rngF.normal(size=(4096, dF)).astype(np.float32)
            yF = (XF @ rngF.normal(size=dF) > 0).astype(np.int32)
            clfF = SGDClassifier(random_state=0)
            clfF.partial_fit(XF, yF, classes=np.array([0, 1]))

            # single-process closed-loop rate (the 4x anchor)
            with ModelServer(label="bench_fleet_anchor",
                             window_s=0.0) as srv:
                srv.load("m", clfF)
                for _ in range(20):
                    srv.predict("m", XF[:1])
                NA = 150
                t0 = time.perf_counter()
                for i in range(NA):
                    srv.predict("m", XF[i % 2048:i % 2048 + 1])
                closed_rps = NA / max(time.perf_counter() - t0, 1e-9)

            reg = _obs_fleet.registry()
            n_rep = 4
            lam = closed_rps * 4.0
            NF = int(min(800, max(200, lam * 2)))
            gaps = np.random.RandomState(11).exponential(
                1.0 / lam, size=NF)
            fleet = ServeFleet(
                replicas=n_rep, label="bench_fleet", window_s=0.0,
                hedge_ms=0.0, retries=2,
                budget=FaultBudget(4 * NF, 600.0, name="bench_fleet"))
            try:
                fleet.load("m", clfF, hot=True)
                for _ in range(4 * n_rep):  # touch every replica warm
                    fleet.predict("m", XF[:1])
                reg.reset(prefix="serve.req_")
                reg.reset(prefix="serve.request_s")
                reg.reset(prefix="fleet.request_s")
                rej0 = sum(reg.family("fleet.rejected").values())
                ret0 = sum(reg.family("fleet.retry").values())
                futsF, rejectedF = [], 0
                c0 = time.process_time()
                t0 = time.perf_counter()
                for i in range(NF):
                    time.sleep(float(gaps[i]))
                    try:
                        futsF.append(fleet.submit(
                            "m", XF[i % 2048:i % 2048 + 1]))
                    except RequestRejected:
                        rejectedF += 1  # counted shed, not an error
                for f in futsF:
                    try:
                        f.result(30.0)
                    except RequestRejected:
                        rejectedF += 1
                dtF = time.perf_counter() - t0
                cpuF = time.process_time() - c0
                cw = cpuF / max(dtF, 1e-9)
                hist = reg.histogram("fleet.request_s", "m")
                per_rep = {}
                for i in range(n_rep):
                    v = serve_critical(tag=f"r{i}", publish=False)
                    if v is not None:
                        per_rep[f"r{i}"] = {
                            "requests": v["requests"],
                            "class": v["verdict"]["class"],
                            "confidence": v["verdict"]["confidence"],
                        }
                _record({
                    "workload": "fleet_open_poisson_1row_4x",
                    "replicas": n_rep,
                    "requests": NF,
                    "closed_rps_1proc": round(closed_rps, 1),
                    "offered_rps": round(lam, 1),
                    "achieved_rps": round(
                        (NF - rejectedF) / max(dtF, 1e-9), 1),
                    "p50_ms": round(hist.quantile(0.50) * 1e3, 3),
                    "p99_ms": round(hist.quantile(0.99) * 1e3, 3),
                    "rejected": rejectedF,
                    "fleet_rejected_counted": int(
                        sum(reg.family("fleet.rejected").values())
                        - rej0),
                    "fleet_retries": int(
                        sum(reg.family("fleet.retry").values()) - ret0),
                    "per_replica": per_rep,
                    "cpu_over_wall": round(cw, 3),
                    "saturation_pinned": bool(cw >= 0.9),
                })
            finally:
                fleet.close()
    except _SkipSection:
        pass
    except Exception:
        extra["fleet_error"] = traceback.format_exc(limit=3)

    section_s["fleet"] = round(time.time() - _t_sec, 1)
    _t_sec = time.time()

    # --- search: concurrent orchestrator vs sequential brackets (ISSUE
    # 13).  The SAME multi-bracket Hyperband search (same data, same
    # seeds, so same configs and — asserted — the same results at rtol
    # 1e-5) runs on the concurrent control plane (brackets multiplexed
    # as coroutines on the blessed dask-ml-tpu-search dispatch thread,
    # per-unit staged feeds, survivors re-packed into vmapped cohorts)
    # and with DASK_ML_TPU_SEARCH_CONCURRENCY=off +
    # sequential_brackets=True — the round-5 single-controller loop
    # whose 1.53x sequentialization bound this lane exists to close.
    # TWO A/B pairs: (a) in-memory blocks — on a CPU gate box whose
    # "device" programs execute inline on the same cores, both arms
    # saturate the machine (cpu/wall recorded as evidence) and the
    # ratio is pinned near 1.0 by physics, so this pair's job is the
    # chip trajectory; (b) relay-emulated staging — each block's stage
    # pays a fixed EMULATED latency (labelled in the record; the axon
    # tunnel's measured RTT is ~70 ms, 2 ms is conservative), the
    # deployment the lane actually targets, where overlap is real even
    # single-core.  configs/s, wall, and the graftscope device_util /
    # device_idle_s deltas land per arm. ---
    try:
        if not _want("search"):
            raise _SkipSection
        from dask_ml_tpu.linear_model import SGDClassifier as _SrchSGD
        from dask_ml_tpu.model_selection import HyperbandSearchCV \
            as _SrchHB
        from dask_ml_tpu.obs import scope as _srch_scope

        _RELAY_MS = 2.0

        class _RelaySGD(_SrchSGD):
            """Relay-emulated staging: every block's host->device stage
            carries a fixed latency on the (host-only) staging thread —
            sleeps release the GIL exactly like tunnel I/O."""

            def _pf_stage(self, X, y, **kw):
                time.sleep(_RELAY_MS / 1e3)
                return super()._pf_stage(X, y, **kw)

        nS, dS = (200_000, 32) if on_tpu else (40_000, 16)
        rngS2 = np.random.RandomState(13)
        XS2 = rngS2.normal(size=(nS, dS)).astype(np.float32)
        yS2 = (XS2 @ rngS2.normal(size=dS) > 0).astype(np.int32)
        # heterogeneous statics: units stay unpacked, so the orchestrator
        # multiplexes real independent units (the packed form collapses
        # each bracket to one cohort — a different, already-measured win)
        _srch_grid = {
            "loss": ["log_loss", "hinge", "squared_hinge",
                     "modified_huber"],
            "penalty": ["l2", "l1", "elasticnet"],
            "alpha": list(np.logspace(-5, -2, 4)),
        }

        def _srch_fit(est, sequential):
            saved = os.environ.get("DASK_ML_TPU_SEARCH_CONCURRENCY")
            if sequential:
                os.environ["DASK_ML_TPU_SEARCH_CONCURRENCY"] = "off"
            else:
                os.environ.pop("DASK_ML_TPU_SEARCH_CONCURRENCY", None)
            try:
                hb = _SrchHB(
                    est, _srch_grid,
                    max_iter=9, random_state=0, test_size=0.25,
                    sequential_brackets=sequential,
                )
                cur = _srch_scope.cursor()
                c0 = time.process_time()
                t0 = time.perf_counter()
                hb.fit(XS2, yS2, classes=np.array([0, 1]))
                wall = time.perf_counter() - t0
                cpu = time.process_time() - c0
                dev = _srch_scope.device_report(since=cur, settle_s=5.0)
                return hb, wall, cpu, dev, _critical_arm()
            finally:
                if saved is None:
                    os.environ.pop("DASK_ML_TPU_SEARCH_CONCURRENCY",
                                   None)
                else:
                    os.environ["DASK_ML_TPU_SEARCH_CONCURRENCY"] = saved

        def _srch_pair(prefix, est_factory, extra_cols=None):
            _srch_fit(est_factory(), False)  # warm: compiles out
            hb_c, wall_c, cpu_c, dev_c, cr_c = \
                _srch_fit(est_factory(), False)
            hb_s, wall_s, cpu_s, dev_s, cr_s = \
                _srch_fit(est_factory(), True)
            n_cfg = hb_c.metadata_["n_models"]
            np.testing.assert_allclose(
                np.asarray(hb_c.cv_results_["test_score"]),
                np.asarray(hb_s.cv_results_["test_score"]), rtol=1e-5)
            for name, wall, cpu, dev, cr in (
                    (f"{prefix}_concurrent", wall_c, cpu_c, dev_c,
                     cr_c),
                    (f"{prefix}_sequential", wall_s, cpu_s, dev_s,
                     cr_s)):
                _record({
                    "workload": name,
                    "configs": int(n_cfg),
                    "wall_s": round(wall, 4),
                    "configs_per_s": round(n_cfg / max(wall, 1e-9), 2),
                    "cpu_over_wall": round(cpu / max(wall, 1e-9), 3),
                    "device_util": dev["utilization"],
                    "device_idle_s": dev["idle_s"],
                    "device_busy_s": dev["busy_s"],
                    "critical": cr,
                    **(extra_cols or {}),
                })
            _record({
                "workload": f"{prefix}_vs_sequential",
                "configs": int(n_cfg),
                "speedup": round(wall_s / max(wall_c, 1e-9), 3),
                "util_delta": round(
                    dev_c["utilization"] - dev_s["utilization"], 4),
                "idle_delta_s": round(
                    dev_s["idle_s"] - dev_c["idle_s"], 4),
                "results_equal_rtol": 1e-5,
                # per-arm verdicts + the tool's saturation label: a
                # ~1.0x pair with both arms host-saturated is PINNED,
                # not a refuted overlap hypothesis (design.md §19)
                "critical": _pair_critical(
                    {"concurrent": cr_c, "sequential": cr_s},
                    (round(cpu_c / max(wall_c, 1e-9), 3),
                     round(cpu_s / max(wall_s, 1e-9), 3))),
                **(extra_cols or {}),
            })
            return wall_s / max(wall_c, 1e-9)

        with _spans_armed():
            _srch_pair("search", lambda: _SrchSGD(random_state=0))
            _srch_pair("search_relay",
                       lambda: _RelaySGD(random_state=0),
                       {"emulated_stage_latency_ms": _RELAY_MS})
    except _SkipSection:
        pass
    except Exception:
        extra["search_error"] = traceback.format_exc(limit=3)

    section_s["search"] = round(time.time() - _t_sec, 1)
    _t_sec = time.time()

    # --- graftpilot controller A/B (control/, design.md §21): three
    # arms per emulated regime — tuned (env defaults: the hand-tuned
    # values), frozen (detuned env, no pilot: the do-nothing baseline)
    # and autopilot (same detuned env + a live Autopilot polling the
    # real graftpath verdict).  Two regimes: remote-store ingest
    # (10 ms/block fetch inside the readers — the data_readers /
    # prefetch_depth chain) and relay search (2 ms/block staging on
    # the search plane — the search_inflight chain).  Each record
    # carries the verdict per arm, the pilot's knob trajectory and
    # freeze counters, and the saturation label: on a host-pinned box
    # the pilot must make ZERO moves (the freeze is the contract, not
    # a missed win). ---
    try:
        if not _want("controller"):
            raise _SkipSection
        import shutil
        import tempfile

        from dask_ml_tpu import data as _ctl_data
        from dask_ml_tpu.control import knobs as _ctl_knobs
        from dask_ml_tpu.control.pilot import Autopilot as _CtlPilot
        from dask_ml_tpu.linear_model import SGDClassifier as _CtlSGD
        from dask_ml_tpu.model_selection import HyperbandSearchCV \
            as _CtlHB
        from dask_ml_tpu.pipeline import stream_partial_fit as _ctl_spf

        _CTL_ENV = ("DASK_ML_TPU_DATA_READERS",
                    "DASK_ML_TPU_PREFETCH_DEPTH",
                    "DASK_ML_TPU_SEARCH_INFLIGHT")

        def _ctl_env(overrides):
            """Set/restore the detune env vars around one arm."""
            saved = {k: os.environ.get(k) for k in _CTL_ENV}
            os.environ.update(overrides)
            for k in _CTL_ENV:
                if k not in overrides:
                    os.environ.pop(k, None)
            return saved

        def _ctl_restore(saved):
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

        def _ctl_pilot_cols(pilot):
            rep = pilot.report()
            return {
                "moves": len(rep["moves"]),
                "knob_trajectory": [
                    {"knob": m["knob"], "direction": m["direction"],
                     "to": m["to"], "class": m["class"]}
                    for m in rep["moves"]],
                "freezes": rep["freezes"],
                "converged": rep["converged"],
            }

        # regime 1: remote-store ingest (the perf ratchet's workload
        # geometry, bench-sized) — fetch dominates a detuned pipeline,
        # readers then depth win it back
        nC, dC, blkC = 65_536, 16, 4096
        rngC = np.random.RandomState(29)
        XC = rngC.normal(size=(nC, dC)).astype(np.float32)
        yC = (XC @ rngC.normal(size=dC) > 0).astype(np.int32)
        blocks_per_epoch = nC // blkC
        ctl_dir = tempfile.mkdtemp(prefix="bench-controller-")
        try:
            _ctl_data.write_dataset(ctl_dir, XC, yC, shards=4,
                                    block_rows=blkC)

            def _ctl_fit(tag, epochs):
                """One streamed-fit arm under whatever env/overrides
                are in force; rate in blocks/s + cpu_over_wall +
                verdict.  Knobs resolve live (no ctor args = nothing
                pinned, the pilot's plane)."""
                clf = _CtlSGD(random_state=0)
                ds = _ctl_data.ShardedDataset(
                    ctl_dir, key=29, epochs=epochs,
                    fetch_latency_s=0.010,
                    label=f"bench_ctl_{tag}")
                c0 = time.process_time()
                t0 = time.perf_counter()
                _ctl_spf(clf, ds.iter_blocks(),
                         fit_kwargs={"classes": np.array([0, 1])},
                         label=f"bench_ctl_{tag}")
                dt = time.perf_counter() - t0
                cpu = time.process_time() - c0
                return {
                    "blocks_per_s": round(
                        blocks_per_epoch * epochs / max(dt, 1e-9), 2),
                    "wall_s": round(dt, 3),
                    "cpu_over_wall": round(cpu / max(dt, 1e-9), 3),
                    "critical": _critical_arm(),
                }

            detuneC = {"DASK_ML_TPU_DATA_READERS": "1",
                       "DASK_ML_TPU_PREFETCH_DEPTH": "1"}
            with _spans_armed():
                saved = _ctl_env({})
                pilot = None
                try:
                    _ctl_knobs.clear_overrides()
                    _ctl_fit("warm", 1)  # compiles + reader paths hot
                    tuned = _ctl_fit("tuned", 3)
                    _ctl_env(detuneC)
                    frozen = _ctl_fit("frozen", 3)
                    pilot = _CtlPilot(cadence_ms=25.0, cooldown=2,
                                      max_moves=5)
                    pilot.start()
                    _ctl_fit("converge", 10)
                    auto = _ctl_fit("auto", 3)
                    pilot.stop()
                    pcols = _ctl_pilot_cols(pilot)
                finally:
                    if pilot is not None and pilot.running():
                        pilot.stop()
                    _ctl_knobs.clear_overrides()
                    _ctl_restore(saved)
            cw = (tuned["cpu_over_wall"], frozen["cpu_over_wall"],
                  auto["cpu_over_wall"])
            pinned = bool(min(cw) >= 0.9)
            _record({
                "workload": "controller_ingest_remote10ms",
                "rows": nC,
                "block_rows": blkC,
                "tuned_blocks_per_s": tuned["blocks_per_s"],
                "frozen_blocks_per_s": frozen["blocks_per_s"],
                "auto_blocks_per_s": auto["blocks_per_s"],
                "auto_over_frozen": round(
                    auto["blocks_per_s"]
                    / max(frozen["blocks_per_s"], 1e-9), 3),
                "auto_over_tuned": round(
                    auto["blocks_per_s"]
                    / max(tuned["blocks_per_s"], 1e-9), 3),
                "tuned_cpu_over_wall": tuned["cpu_over_wall"],
                "frozen_cpu_over_wall": frozen["cpu_over_wall"],
                "auto_cpu_over_wall": auto["cpu_over_wall"],
                # on a saturation-pinned box every move would thrash:
                # zero moves IS the pass condition there
                "zero_moves_when_pinned": (not pinned)
                or pcols["moves"] == 0,
                "critical": _pair_critical(
                    {"tuned": tuned["critical"],
                     "frozen": frozen["critical"],
                     "auto": auto["critical"]}, cw),
                **pcols,
            })
        finally:
            shutil.rmtree(ctl_dir, ignore_errors=True)

        # regime 2: relay search (2 ms/block staging latency on the
        # host-only staging thread) — the search_inflight chain: a
        # detuned dispatcher (inflight 1) serializes units the relay
        # latency could have overlapped
        _CTL_RELAY_MS = 2.0

        class _CtlRelaySGD(_CtlSGD):
            def _pf_stage(self, X, y, **kw):
                time.sleep(_CTL_RELAY_MS / 1e3)
                return super()._pf_stage(X, y, **kw)

        nR, dR = 20_000, 16
        rngR = np.random.RandomState(31)
        XR = rngR.normal(size=(nR, dR)).astype(np.float32)
        yR = (XR @ rngR.normal(size=dR) > 0).astype(np.int32)
        ctl_grid = {
            "loss": ["log_loss", "hinge"],
            "penalty": ["l2", "l1"],
            "alpha": [1e-4, 1e-3],
        }

        def _ctl_search(tag, pilot_on):
            pilot = None
            c0 = time.process_time()
            t0 = time.perf_counter()
            try:
                if pilot_on:
                    pilot = _CtlPilot(cadence_ms=25.0, cooldown=2,
                                      max_moves=5)
                    pilot.start()
                hb = _CtlHB(_CtlRelaySGD(random_state=0), ctl_grid,
                            max_iter=9, random_state=0,
                            test_size=0.25)
                hb.fit(XR, yR, classes=np.array([0, 1]))
            finally:
                if pilot is not None:
                    pilot.stop()
            wall = time.perf_counter() - t0
            cpu = time.process_time() - c0
            n_cfg = hb.metadata_["n_models"]
            return {
                "configs": int(n_cfg),
                "wall_s": round(wall, 3),
                "configs_per_s": round(n_cfg / max(wall, 1e-9), 2),
                "cpu_over_wall": round(cpu / max(wall, 1e-9), 3),
                "critical": _critical_arm(),
                "pilot": _ctl_pilot_cols(pilot) if pilot else None,
            }

        detuneR = {"DASK_ML_TPU_SEARCH_INFLIGHT": "1"}
        with _spans_armed():
            saved = _ctl_env({})
            try:
                _ctl_knobs.clear_overrides()
                _ctl_search("warm", False)  # compiles out
                tunedR = _ctl_search("tuned", False)
                _ctl_env(detuneR)
                # detuned warm: inflight=1 schedules different unit
                # cohorts, whose compiles must not bill the frozen arm
                _ctl_search("frozen_warm", False)
                frozenR = _ctl_search("frozen", False)
                _ctl_knobs.clear_overrides()
                autoR = _ctl_search("auto", True)
            finally:
                _ctl_knobs.clear_overrides()
                _ctl_restore(saved)
        pR = autoR.pop("pilot")
        cwR = (tunedR["cpu_over_wall"], frozenR["cpu_over_wall"],
               autoR["cpu_over_wall"])
        pinnedR = bool(min(cwR) >= 0.9)
        _record({
            "workload": "controller_search_relay2ms",
            "configs": tunedR["configs"],
            "emulated_stage_latency_ms": _CTL_RELAY_MS,
            "tuned_configs_per_s": tunedR["configs_per_s"],
            "frozen_configs_per_s": frozenR["configs_per_s"],
            "auto_configs_per_s": autoR["configs_per_s"],
            "auto_over_frozen": round(
                autoR["configs_per_s"]
                / max(frozenR["configs_per_s"], 1e-9), 3),
            "auto_over_tuned": round(
                autoR["configs_per_s"]
                / max(tunedR["configs_per_s"], 1e-9), 3),
            "tuned_cpu_over_wall": tunedR["cpu_over_wall"],
            "frozen_cpu_over_wall": frozenR["cpu_over_wall"],
            "auto_cpu_over_wall": autoR["cpu_over_wall"],
            "zero_moves_when_pinned": (not pinnedR)
            or pR["moves"] == 0,
            "critical": _pair_critical(
                {"tuned": tunedR["critical"],
                 "frozen": frozenR["critical"],
                 "auto": autoR["critical"]}, cwR),
            **pR,
        })
    except _SkipSection:
        pass
    except Exception:
        extra["controller_error"] = traceback.format_exc(limit=3)

    section_s["controller"] = round(time.time() - _t_sec, 1)
    _t_sec = time.time()

    # --- roofline: per-program FLOP/byte attribution for the ratcheted
    # hot loops (ISSUE 12).  Runs the three committed streamed workloads
    # plus a cached-Lloyd fit under graftscope and records each cached
    # program's XLA-estimated flops/bytes joined with measured busy
    # time — the same table device_report()/tools/lint.sh --perf gate,
    # landed in the bench record so chip rounds trend roofline fraction
    # next to throughput. ---
    try:
        if not _want("roofline"):
            raise _SkipSection
        from dask_ml_tpu.cluster import KMeans
        from dask_ml_tpu.obs import perf as _perf
        from dask_ml_tpu.obs import scope as _rf_scope

        rf_cur = _rf_scope.cursor()
        rf_res = _perf.run_suite(
            ["sgd_stream_d0", "sgd_stream_d2", "mbk_stream_d2",
             "serve_latency"])
        nrf, drf = (500_000, 50) if on_tpu else (100_000, 50)
        Xrf = rng.normal(size=(nrf, drf)).astype(np.float32)
        KMeans(n_clusters=8, init="random", max_iter=10,
               random_state=0).fit(Xrf)
        rf_dev = _rf_scope.device_report(since=rf_cur, settle_s=5.0)
        table = {
            name: {k: p.get(k) for k in
                   ("dispatches", "busy_s", "flops", "bytes",
                    "achieved_flops_per_s", "achieved_bytes_per_s",
                    "intensity", "roofline_frac")}
            for name, p in sorted(rf_dev.get("programs", {}).items())
        }
        _record_extra("roofline", {
            "platform_peaks": rf_dev.get("roofline"),
            "programs": table,
            "workloads": {n: {k: m.get(k) for k in
                              ("p50_block_s", "utilization", "programs")}
                          for n, m in sorted(rf_res.items())},
        })
    except _SkipSection:
        pass
    except Exception:
        extra["roofline_error"] = traceback.format_exc(limit=3)

    section_s["roofline"] = round(time.time() - _t_sec, 1)
    try:
        # session-total observability counters for the compact line
        # (BENCH_r*.json): the per-workload deltas live on each entry's
        # "obs" block in the full payload.  NOTE: totals since process
        # start; an in-section reset_*() means they can undercount a
        # family relative to the summed per-workload deltas.
        extra["obs_totals"] = _obs_read()
    except Exception:
        pass
    watchdog.cancel()
    try:
        _merge_and_finalize()
    except Exception:
        extra["merge_error"] = traceback.format_exc(limit=2)
    _emit_final(result)
    try:
        _compact_partial()
    except Exception:
        pass


if __name__ == "__main__":
    main()

"""Benchmark harness — prints ONE JSON line, always.

Measures the two BASELINE.md north-star workloads, reporting KMeans
Lloyd throughput (rows*iters/sec) as the primary metric and ADMM
logistic fit time as context.  ``vs_baseline`` is 1.0-normalized because
the reference publishes no absolute numbers (BASELINE.json :: published
== {}).

Environment-proofing (VERDICT.md round-1 item #1): backend acquisition
is guarded — if the preset TPU plugin fails to initialize, fall back to
CPU (with a smaller workload) rather than crash; each workload fails
soft; the JSON line is emitted no matter what.

Both workloads run their ENTIRE iteration loop as one XLA program
(lax.while_loop fusion); on TPU the Lloyd round additionally uses the
fused Pallas assign+reduce kernel (ops.lloyd) when enabled.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
import traceback

# Hard cap on total bench runtime.  A watchdog THREAD (not SIGALRM: Python
# signal handlers only run between bytecodes, and the wedge we guard
# against is the main thread blocked inside a PJRT C++ wait that releases
# the GIL) prints the JSON accumulated so far and exits 0, so the driver
# never records a bare rc=124 with no JSON line.
_BUDGET_S = int(os.environ.get("DASK_ML_TPU_BENCH_BUDGET_S", "480"))
_START_TS = time.time()
_RESULT = {
    "metric": "kmeans_lloyd_rows_per_sec",
    "value": 0.0,
    "unit": "rows*iters/s (fp32)",
    "vs_baseline": 0.0,
    "extra": {},
}


def _emit_and_exit():
    _RESULT["extra"]["timed_out"] = True
    print(json.dumps(_RESULT), flush=True)
    os._exit(0)


def _tpu_backend_usable(probe_timeout_s: float = 75.0) -> bool:
    """Probe the preset (axon/TPU) backend in a SUBPROCESS with a hard
    timeout.  jax.devices() can hang forever (not just raise) when the
    TPU tunnel is down — round-1 MULTICHIP rc=124 — so an in-process
    try/except is not enough; only a killable child is safe."""
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        return False
    try:
        proc = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices(); print('OK')"],
            timeout=probe_timeout_s,
            capture_output=True,
            text=True,
        )
        return proc.returncode == 0 and "OK" in proc.stdout
    except (subprocess.TimeoutExpired, OSError):
        return False


def _acquire_backend():
    """Initialize a jax backend, falling back to CPU if the preset TPU
    plugin is unavailable or hung.  Returns (jax, platform)."""
    if not _tpu_backend_usable():
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            pass
        return jax, jax.devices()[0].platform
    import jax

    return jax, jax.devices()[0].platform


def main():
    watchdog = threading.Timer(_BUDGET_S, _emit_and_exit)
    watchdog.daemon = True
    watchdog.start()
    result = _RESULT
    extra = result["extra"]
    try:
        jax, platform = _acquire_backend()
    except Exception:
        extra["backend_error"] = traceback.format_exc(limit=3)
        watchdog.cancel()
        print(json.dumps(result))
        return

    import numpy as np
    import jax.numpy as jnp

    extra["platform"] = platform
    extra["n_devices"] = len(jax.devices())
    on_tpu = platform not in ("cpu",)
    rng = np.random.RandomState(0)

    # Roofline peaks for judging bw_frac / mfu.  Defaults are TPU v5e
    # single-chip numbers (819 GB/s HBM, ~49 TFLOP/s fp32 on the MXU);
    # override via env for other parts.  CPU numbers are indicative only.
    peak_gb_s = float(os.environ.get(
        "DASK_ML_TPU_PEAK_GB_S", "819" if on_tpu else "50"))
    peak_tflops = float(os.environ.get(
        "DASK_ML_TPU_PEAK_FP32_TFLOPS", "49" if on_tpu else "1"))
    extra["assumed_peaks"] = {"hbm_gb_s": peak_gb_s, "fp32_tflops": peak_tflops}
    workloads = extra["workloads"] = []

    def _time_lloyd(s, centers, n, d, k, iters, use_pallas, mh):
        from dask_ml_tpu.cluster.k_means import _lloyd_loop

        args = (s.data, s.mask, centers, jnp.float32(0.0), jnp.int32(iters))
        # the trailing float() pull is the only reliable sync on the axon
        # relay (block_until_ready returns early); the loop may stop short
        # of `iters` at an exact fixed point, so throughput uses the ACTUAL
        # round count
        float(_lloyd_loop(*args, mesh_holder=mh, use_pallas=use_pallas)[1])
        t0 = time.perf_counter()
        out = _lloyd_loop(*args, mesh_holder=mh, use_pallas=use_pallas)
        float(out[1])
        dt = time.perf_counter() - t0
        n_rounds = max(int(out[2]), 1)
        # per round: assign gemm 2ndk + onehot-reduce gemm 2ndk flops;
        # minimum HBM traffic = one X read (n*d*4B) per round
        flops = 4.0 * n * d * k * n_rounds
        gbytes = n * d * 4 * n_rounds / 1e9
        return {
            "workload": f"kmeans_lloyd_{n}x{d}_k{k}" + ("_pallas" if use_pallas else "_xla"),
            "wall_s": round(dt, 3),
            "rounds": n_rounds,
            "rows_per_s": round(n * n_rounds / dt, 1),
            "achieved_gb_s": round(gbytes / dt, 2),
            "bw_frac": round(gbytes / dt / peak_gb_s, 4),
            "achieved_tflops": round(flops / dt / 1e12, 3),
            "mfu": round(flops / dt / 1e12 / peak_tflops, 4),
        }

    # --- KMeans Lloyd throughput (north-star #2 shape, scaled to chip) ---
    try:
        from dask_ml_tpu.core import shard_rows, get_mesh
        from dask_ml_tpu.core.mesh import MeshHolder

        n, d, k = (2_000_000, 50, 8) if on_tpu else (200_000, 50, 8)
        X = rng.normal(size=(n, d)).astype(np.float32)
        s = shard_rows(X)
        centers = s.data[:k]
        iters = 40
        mh = MeshHolder(get_mesh())

        xla_stats = _time_lloyd(s, centers, n, d, k, iters, False, mh)
        workloads.append(xla_stats)
        best = xla_stats

        if on_tpu:
            # Pallas is the TPU default (blessed by the hardware parity
            # test; cluster.k_means._pallas_ok) — bench still re-verifies
            # on the RUNNING chip and records the result alongside the
            # Pallas-vs-XLA timing delta
            try:
                from dask_ml_tpu.ops import lloyd_assign_reduce

                ps, pc, pi = lloyd_assign_reduce(
                    s.data[:8192], s.mask[:8192], centers
                )
                # reference via plain XLA ops on the same slice
                import jax as _jax

                from dask_ml_tpu.metrics.pairwise import _sq_euclidean_hi

                d2 = _sq_euclidean_hi(s.data[:8192], centers)
                lbl = jnp.argmin(d2, 1)
                oh = _jax.nn.one_hot(lbl, k) * s.mask[:8192, None]
                # float64 HOST reference for the sums so the gate is not
                # comparing one device gemm's rounding against another's
                es = (
                    np.asarray(oh, np.float64).T
                    @ np.asarray(s.data[:8192], np.float64)
                )
                # assignments (counts) must match EXACTLY; sums only to a
                # scale-aware tolerance — near-zero entries of onehot.T @ x
                # are catastrophic cancellations where fp32 accumulation
                # ORDER legitimately differs from fp64
                ok = bool(
                    np.array_equal(np.asarray(pc), np.asarray(oh.sum(0)))
                    and np.max(np.abs(np.asarray(ps, np.float64) - es))
                    <= 1e-3 * max(np.max(np.abs(es)), 1.0)
                )
                extra["pallas_parity_ok"] = bool(ok)
                if ok:
                    pallas_stats = _time_lloyd(s, centers, n, d, k, iters, True, mh)
                    workloads.append(pallas_stats)
                    extra["pallas_vs_xla_speedup"] = round(
                        xla_stats["wall_s"] / pallas_stats["wall_s"], 3
                    )
                    if pallas_stats["rows_per_s"] > best["rows_per_s"]:
                        best = pallas_stats
            except Exception:
                extra["pallas_error"] = traceback.format_exc(limit=3)

        result["value"] = best["rows_per_s"]
        result["unit"] = f"rows*iters/s ({n}x{d}, k={k}, fp32)"
        result["vs_baseline"] = 1.0
    except Exception:
        extra["lloyd_error"] = traceback.format_exc(limit=3)

    # --- ADMM logistic fit (north-star #1, HIGGS shape scaled to chip) ---
    try:
        from dask_ml_tpu.core import shard_rows
        from dask_ml_tpu.linear_model import LogisticRegression

        # full HIGGS rows only if at least ~half the budget remains
        # (compile + 1.2GB ingest are front-loaded costs)
        half_left = (time.time() - _START_TS) < _BUDGET_S * 0.45
        n2, d2 = (
            (11_000_000 if half_left else 1_000_000, 28) if on_tpu
            else (100_000, 28)
        )
        w = rng.normal(size=d2).astype(np.float32)
        X2 = rng.normal(size=(n2, d2)).astype(np.float32)
        y2 = (1 / (1 + np.exp(-(X2 @ w))) > rng.uniform(size=n2)).astype(
            np.float32
        )
        sX2, sy2 = shard_rows(X2), shard_rows(y2)
        admm_iters, inner = 10, 30
        lr = LogisticRegression(
            solver="admm", C=1e4, max_iter=admm_iters,
            solver_kwargs={"inner_iter": inner},
        )
        lr.fit(sX2, sy2)  # compile
        t0 = time.perf_counter()
        lr.fit(sX2, sy2)
        dt2 = time.perf_counter() - t0
        acc = float(lr.score(sX2, y2))
        # per outer iter: inner L-BFGS evals of loss+grad ~ 2 matvecs
        # (4*n*d flops) each; X re-read per eval bounds HBM traffic
        flops2 = admm_iters * inner * 4.0 * n2 * d2
        gbytes2 = admm_iters * inner * n2 * d2 * 4 / 1e9
        workloads.append({
            "workload": f"admm_logreg_{n2}x{d2}_{admm_iters}outer",
            "wall_s": round(dt2, 3),
            "rows_per_s": round(n2 * admm_iters / dt2, 1),
            "train_accuracy": round(acc, 4),
            "achieved_gb_s": round(gbytes2 / dt2, 2),
            "bw_frac": round(gbytes2 / dt2 / peak_gb_s, 4),
            "achieved_tflops": round(flops2 / dt2 / 1e12, 3),
            "mfu": round(flops2 / dt2 / 1e12 / peak_tflops, 4),
        })
    except Exception:
        extra["admm_error"] = traceback.format_exc(limit=3)

    watchdog.cancel()
    print(json.dumps(result))


if __name__ == "__main__":
    main()
